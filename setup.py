"""Build hook: compile the native runtime into the wheel.

The reference's equivalent step is the Maven native profile pulling
prebuilt cuDF/JNI jars (ref aggregator/pom.xml:27-50); here the native
layer is one translation unit compiled with g++ at wheel-build time.  If
no compiler exists the wheel still builds — the engine falls back to its
pure-python codec paths and records the reason (native/__init__.py)."""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildPyWithNative(build_py):
    def run(self):
        super().run()
        src = os.path.join("spark_rapids_tpu", "native", "src",
                           "tpu_native.cpp")
        out_dir = os.path.join(self.build_lib, "spark_rapids_tpu",
                               "native", "build")
        out = os.path.join(out_dir, "libtpu_native.so")
        os.makedirs(out_dir, exist_ok=True)
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", out,
               src]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=300)
            if r.returncode != 0:
                self.announce(
                    f"native build skipped: {r.stderr[-500:]}", level=3)
        except (OSError, subprocess.TimeoutExpired) as ex:
            self.announce(f"native build skipped: {ex}", level=3)


setup(cmdclass={"build_py": BuildPyWithNative})
