"""Shared probe harness: persistent compile cache + fetch-forced timing."""
import time

import numpy as np

import spark_rapids_tpu  # noqa: F401
import jax


def enable_cache():
    import os
    cache_dir = os.path.expanduser("~/.cache/spark_rapids_tpu_probe_xla")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def timeit(name, fn, *args, reps=3):
    jf = jax.jit(fn)
    t0 = time.perf_counter()
    o = jf(*args)
    leaf = jax.tree_util.tree_leaves(o)[0]
    np.asarray(leaf.ravel()[-1:])
    c = time.perf_counter() - t0
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        o = jf(*args)
        leaf = jax.tree_util.tree_leaves(o)[0]
        np.asarray(leaf.ravel()[-1:])
        ts.append(time.perf_counter() - t0)
    print(f"{name:44s} {min(ts)*1e3:9.2f} ms  (first {c:6.1f}s)", flush=True)
    return jf
