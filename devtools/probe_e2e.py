#!/usr/bin/env python
"""End-to-end warm timing of bench queries through the real engine."""
import sys
import time

import numpy as np

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import make_tables, write_parquet_input, queries
import tempfile, shutil, os


def main():
    which = sys.argv[1:] or ["agg"]
    fact, dim = make_tables(1_000_000)
    root = tempfile.mkdtemp(prefix="probe_e2e_")
    try:
        pq_path = write_parquet_input(fact, root)
        from spark_rapids_tpu.api.session import TpuSession
        s = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", True).get_or_create())
        qs = dict(queries(s, fact, dim, pq_path, root))
        for name in which:
            q = qs[name]
            t0 = time.perf_counter()
            q()
            print(f"{name} first (compile): {time.perf_counter()-t0:.2f}s",
                  flush=True)
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                out = q()
                ts.append(time.perf_counter() - t0)
            print(f"{name} warm: {min(ts):.3f}s  (rows={out.num_rows})",
                  flush=True)
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
