#!/usr/bin/env python
"""CI entry point for the tpulint repo lint and the flow-sensitive
plan-lint gate.

Default mode runs the TPU-Rxxx invariant rules over spark_rapids_tpu/
and exits nonzero on any violation NOT in the checked-in baseline
(devtools/lint_baseline.txt), so the invariants ratchet: existing debt
is frozen, new debt fails the suite (tests/test_lint_clean.py invokes
this from tier-1).

--interp runs the plan lint in flow-sensitive mode (abstract
interpreter, analysis/interp.py) over the golden corpus and exits
nonzero when the analyzer regresses in either direction:

  * any ERROR diagnostic on tests/goldens/lint/good_plans.py
    (false reject), or
  * a missing expected code on tests/goldens/lint/bad_plans.py
    (false admit — expected_codes.json is the contract), or
  * any differential-oracle mismatch between predicted and executed
    schema/residency/partitioning on the good corpus.

    python devtools/run_lint.py                    # repo check
    python devtools/run_lint.py --update-baseline  # re-freeze debt
    python devtools/run_lint.py --interp           # plan typechecker gate
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "devtools", "lint_baseline.txt")
GOLDEN = os.path.join(REPO, "tests", "goldens", "lint")


def _builders(path):
    import runpy
    ns = runpy.run_path(path)
    return {k: ns[k] for k in ns if k.startswith("plan_")
            and callable(ns[k])}


def run_interp_gate() -> int:
    from spark_rapids_tpu.analysis.oracle import verify_plan
    from spark_rapids_tpu.analysis.plan_lint import lint_plan
    from spark_rapids_tpu.config import RapidsConf

    failures = 0

    good = _builders(os.path.join(GOLDEN, "good_plans.py"))
    for name in sorted(good):
        root, conf_map = good[name]()
        conf = RapidsConf(conf_map)
        errors = [d for d in lint_plan(root, conf, infer=True)
                  if d.is_error]
        for d in errors:
            failures += 1
            print(f"FALSE REJECT {name}: {d.render()}")
        mismatches = verify_plan(root, conf)
        for m in mismatches:
            failures += 1
            print(f"ORACLE DRIFT {name}: {m}")

    with open(os.path.join(GOLDEN, "expected_codes.json")) as f:
        expected = json.load(f)
    bad = _builders(os.path.join(GOLDEN, "bad_plans.py"))
    for name in sorted(expected):
        root, conf_map = bad[name]()
        got = {d.code for d in lint_plan(root, RapidsConf(conf_map),
                                         infer=True)}
        for code in set(expected[name]) - got:
            failures += 1
            print(f"FALSE ADMIT {name}: expected {code}, got "
                  f"{sorted(got)}")

    n = len(good) + len(expected)
    if failures:
        print(f"plan typechecker gate: {failures} failure(s) over {n} "
              f"golden plans")
        return 1
    print(f"plan typechecker gate clean ({len(good)} good plans "
          f"oracle-verified, {len(expected)} hazards flagged)")
    return 0


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--interp" in args:
        return run_interp_gate()
    from spark_rapids_tpu.tools.__main__ import main as tools_main
    cli = ["lint", "--repo", "--baseline", BASELINE]
    if "--update-baseline" in args:
        cli.append("--update-baseline")
    return tools_main(cli)


if __name__ == "__main__":
    sys.exit(main())
