#!/usr/bin/env python
"""CI entry point for the tpulint repo lint.

Runs the TPU-Rxxx invariant rules over spark_rapids_tpu/ and exits
nonzero on any violation NOT in the checked-in baseline
(devtools/lint_baseline.txt), so the invariants ratchet: existing debt
is frozen, new debt fails the suite (tests/test_lint_clean.py invokes
this from tier-1).

    python devtools/run_lint.py                    # check
    python devtools/run_lint.py --update-baseline  # re-freeze debt
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_baseline.txt")


def main(argv=None):
    from spark_rapids_tpu.tools.__main__ import main as tools_main
    args = ["lint", "--repo", "--baseline", BASELINE]
    if "--update-baseline" in (argv or sys.argv[1:]):
        args.append("--update-baseline")
    return tools_main(args)


if __name__ == "__main__":
    sys.exit(main())
