#!/usr/bin/env python
"""CI entry point for the tpulint repo lint and the flow-sensitive
plan-lint gate.

Default mode runs the TPU-Rxxx invariant rules over spark_rapids_tpu/
and exits nonzero on any violation NOT in the checked-in baseline
(devtools/lint_baseline.txt), so the invariants ratchet: existing debt
is frozen, new debt fails the suite (tests/test_lint_clean.py invokes
this from tier-1).

--interp runs the plan lint in flow-sensitive mode (abstract
interpreter, analysis/interp.py) over the golden corpus and exits
nonzero when the analyzer regresses in either direction:

  * any ERROR diagnostic on tests/goldens/lint/good_plans.py
    (false reject), or
  * a missing expected code on tests/goldens/lint/bad_plans.py
    (false admit — expected_codes.json is the contract), or
  * any differential-oracle mismatch between predicted and executed
    schema/residency/partitioning on the good corpus.

--memsan runs the tmsan gate: the lifetime/peak pass over the golden
corpus plus a full shadow-ledger replay — every good plan executes with
the runtime sanitizer installed and must (a) keep its measured peak
device bytes at or under the static TPU-L014 bound, (b) leave a clean
ledger (no leaks, no lifecycle violations); the memory bad-plan
fixtures (L013/L014/L015) must each trip their code.

--obs runs the flight-recorder gate: one golden query executes with
tracing + the self-emitted event log enabled and the gate fails on
unclosed spans, unflushed event logs, event-log lines the parser
rejects, or a round-trip mismatch (parsed operator aggregates !=
live last_query_metrics).

--regress runs the cross-run watchdog gate: the golden query corpus
replays TWICE in fresh subprocesses (fresh process = fresh JIT/plan
caches, so both replays see identical steady state), each run's
self-emitted event log distills into fingerprints (obs/history.py),
and the gate fails when the two replays show ANY deterministic drift —
plus anti-vacuity: an injected fallback and an injected fetch-crossing
bump must each be flagged by the differ.

--metrics runs the continuous-metrics gate: one golden query (plus one
in-process bridge round trip) must light up nonzero series from >= 6
distinct subsystems (spill, arena, shuffle, fetch, session queries,
bridge) in the Prometheus exposition, and the JSON health snapshot
must carry the expected schema.

--jit runs the compile-observatory gate: the golden corpus replays
twice in ONE process and the second pass must build ZERO programs
(shape-canonicalization honesty), the compile ledger / jit.build spans
/ tpu_jit_misses_total must agree about the build count, every build
must carry a classified cause with >= 95% of wall compile time
attributed, and injected bucket/dtype perturbations must classify as
shape_churn / dtype_churn (anti-vacuity).

--shuffle runs the distributed-shuffle gate: the checked-in forced-
shuffled-join bridge golden replays through a real session under the
memsan shadow ledger with the spill budget forced to 1 byte (every
registered map-output block must demote and come back correct), and
the gate fails on a wrong join result, a plan that fell back to
broadcast, a dirty ledger, leaked catalog blocks after stage release,
a silent slice-view write (zero saved bytes), or a transport leg whose
fetched-block/byte counters disagree with what the server actually
registered.

--csan runs the concurrency-sanitizer gate: the tpucsan repo pass
(TPU-R008 lock-order cycles, TPU-R009 unguarded multi-root shared
writes, TPU-R010 condvar misuse) must be clean modulo the baseline,
the ABBA/shared-write/condvar fixtures must each trip their rule
(anti-vacuity), the static lock-order artifact must be non-trivial
with every declared thread root matched, and the serve golden mix
replays under the runtime lock witness (obs/lockwitness.py) — the
gate fails on any acquisition edge the static graph cannot explain
(unmodeled edge) or any observed lock-order cycle.

--feedback runs the estimator-observatory gate: the golden corpus
replays cold (fresh estimator ledger, static cost model) then warm
(feedback-directed planning over the cold arm's ledger) in fresh
subprocesses; the warm replay's mean relative row-estimate error must
be STRICTLY below the cold one, and TWO warm replays over identical
ledger snapshots must show zero deterministic fingerprint drift
(feedback-directed planning must be reproducible, never thrash) —
plus anti-vacuity: an injected 100x row misestimate at a shuffle
boundary must trigger a recorded re-plan whose three sinks (replan
span, tpu_replan_total, ledger event) agree, with the join result
bit-exact against the CPU-engine ground truth.

--fleet runs the fleet-observatory gate: TWO serve_map child processes
serve the join sides over loopback while this process fetches with a
live tracer and a FleetAggregator over both peers' /metrics — the
golden cross-process join must be bit-exact, the merged trace must
contain each producer's serve spans nested under the consumer's fetch
spans (skew-corrected, zero lost spans, producer buffers fully
drained), the aggregator must expose rollup series for both peers with
an ok verdict, and an injected peer death (child killed mid-fleet)
must flip the verdict to degraded AND surface the orphan-span counter
with the dead peer's fetch span closed typed — anti-vacuity both ways:
the clean half must actually merge spans, the degraded half must
actually degrade.

--hbm runs the HBM-observatory gate: a golden replay where the tenant
memory timeline, the memsan shadow ledger and the spill catalog must
agree byte-for-byte on peak device occupancy, then a 4-session pool
stress where every lifecycle event must book under its pool tenant
(zero unattributed) with the tpu_hbm_tenant_bytes gauge family summing
to the timeline's live total — anti-vacuity both ways: an allocation
injected from a context-free thread must trip the unattributed
counter, and an injected operator failure must leave exactly one
parseable post-mortem bundle naming the failing operator and tenant.

--faults runs the tpufsan fault-injection campaign: the exception-flow
pass (analysis/raiseflow.py) must be finding-free (TPU-R011 broad
swallow, TPU-R012 leaking release obligation, TPU-R013 untyped seam
escape, TPU-R014 deadline-free socket), its raise-graph artifact must
enumerate >= 40 statically-reachable (seam, typed-error) pairs with
zero untyped leaks, and every pair is then injected for real — through
the session, the serving pool, the async fetcher and the block server
— asserting the exact typed error reaches the caller, the admission /
shuffle / spill books balance with all spans closed, and exactly one
parseable post-mortem bundle records each failure; background roots
(heartbeat loop, metrics endpoint) must survive an injected fault
while counting it, degrading health and black-boxing it — plus
anti-vacuity: planted orphans must trip the books check and an
untyped injection must fail the propagation verdict.

    python devtools/run_lint.py                    # repo check
    python devtools/run_lint.py --update-baseline  # re-freeze debt
    python devtools/run_lint.py --interp           # plan typechecker gate
    python devtools/run_lint.py --memsan           # lifetime + ledger gate
    python devtools/run_lint.py --obs              # flight-recorder gate
    python devtools/run_lint.py --regress          # cross-run watchdog gate
    python devtools/run_lint.py --metrics          # metrics/health gate
    python devtools/run_lint.py --jit              # compile-observatory gate
    python devtools/run_lint.py --shuffle          # distributed-shuffle gate
    python devtools/run_lint.py --csan             # concurrency-sanitizer gate
    python devtools/run_lint.py --feedback         # estimator-observatory gate
    python devtools/run_lint.py --fleet            # fleet-observatory gate
    python devtools/run_lint.py --hbm              # HBM-observatory gate
--dsan runs the tpudsan determinism gate: the replay-safety repo pass
(TPU-R015 volatile reads, TPU-R016 arrival-order float folds, TPU-L017
fingerprint hygiene) must be finding-free with zero frozen baseline
debt, the planted rule fixtures must each trip (anti-vacuity), and the
permuted-replay oracle replays every golden-corpus exchange's map
write under permuted batch arrival AND a changed input split — every
subtree claiming order_stable or better must reproduce its
content-addressed block digests (ShuffleBufferCatalog write-time
digests, cross-checked against recomputes), while two planted
nondeterminism injections (an arrival-order float sum, a
PYTHONHASHSEED-dependent set-iteration router) must produce
DIFFERENT digests, proving the oracle is not vacuous.

--hlo runs the tpuxsan program-efficiency gate: the golden corpus
replays with StableHLO + cost_analysis() persistence on, every
persisted program artifact must resolve (deduped, size-capped), the
analytic cost model (analysis/hlocost.py) must agree with XLA's own
bytes-accessed within the declared tolerance on >= 90% of compiled
programs (a drifting model fails the gate — anti-vacuity for the
costing itself), the runtime padding-waste books must reconcile three
ways (span padWasteBytes vs recomputation from live rows/capacity vs
the tpu_pad_waste_bytes_total counter), the TPU-L018/L019/L020/R017
fixtures must each trip with their clean twins passing, an injected
pathological bucket (a 1M-capacity launch carrying 10 live rows) must
produce both the L018 finding and the expected counter delta, and
`tools kernel-report` must rank the grouped-aggregate and hash-join
programs among the top fusion targets with nonzero projected savings.

    python devtools/run_lint.py --faults           # tpufsan fault campaign
    python devtools/run_lint.py --dsan             # tpudsan determinism gate
    python devtools/run_lint.py --hlo              # tpuxsan efficiency gate
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "devtools", "lint_baseline.txt")
GOLDEN = os.path.join(REPO, "tests", "goldens", "lint")


def _builders(path):
    import runpy
    ns = runpy.run_path(path)
    return {k: ns[k] for k in ns if k.startswith("plan_")
            and callable(ns[k])}


def run_interp_gate() -> int:
    from spark_rapids_tpu.analysis.oracle import verify_plan
    from spark_rapids_tpu.analysis.plan_lint import lint_plan
    from spark_rapids_tpu.config import RapidsConf

    failures = 0

    good = _builders(os.path.join(GOLDEN, "good_plans.py"))
    for name in sorted(good):
        root, conf_map = good[name]()
        conf = RapidsConf(conf_map)
        errors = [d for d in lint_plan(root, conf, infer=True)
                  if d.is_error]
        for d in errors:
            failures += 1
            print(f"FALSE REJECT {name}: {d.render()}")
        mismatches = verify_plan(root, conf)
        for m in mismatches:
            failures += 1
            print(f"ORACLE DRIFT {name}: {m}")

    with open(os.path.join(GOLDEN, "expected_codes.json")) as f:
        expected = json.load(f)
    bad = _builders(os.path.join(GOLDEN, "bad_plans.py"))
    for name in sorted(expected):
        root, conf_map = bad[name]()
        got = {d.code for d in lint_plan(root, RapidsConf(conf_map),
                                         infer=True)}
        for code in set(expected[name]) - got:
            failures += 1
            print(f"FALSE ADMIT {name}: expected {code}, got "
                  f"{sorted(got)}")

    n = len(good) + len(expected)
    if failures:
        print(f"plan typechecker gate: {failures} failure(s) over {n} "
              f"golden plans")
        return 1
    print(f"plan typechecker gate clean ({len(good)} good plans "
          f"oracle-verified, {len(expected)} hazards flagged)")
    return 0


def _release_plan(root):
    """Mirror TpuSession.release_plan_shuffles for bare exec trees: drop
    shuffle blocks and device exchange memos so the post-query ledger
    check sees what a real session would."""
    ids = []
    root.foreach(lambda e: ids.append(e._shuffle_id)
                 if getattr(e, "_shuffle_id", None) is not None else None)
    if ids:
        from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
        mgr = TpuShuffleManager.get()
        for sid in ids:
            mgr.unregister(sid)
    root.foreach(lambda e: e.release_shuffle()
                 if hasattr(e, "release_shuffle") else None)


def run_memsan_gate() -> int:
    from spark_rapids_tpu.analysis.lifetime import analyze_memory
    from spark_rapids_tpu.analysis.plan_lint import lint_plan
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec import base as eb
    from spark_rapids_tpu.memory import memsan
    from spark_rapids_tpu.memory.spill import SpillCatalog

    failures = 0
    good = _builders(os.path.join(GOLDEN, "good_plans.py"))
    for name in sorted(good):
        root, conf_map = good[name]()
        conf = RapidsConf(conf_map)
        bound = analyze_memory(root, conf).bound(root)
        with SpillCatalog._lock:
            SpillCatalog._instance = SpillCatalog()
        with memsan.installed() as ledger:
            ctx = eb.ExecContext(conf)
            ctx.task_context["no_speculation"] = True
            try:
                root.execute_collect(ctx)
                _release_plan(root)
            except memsan.LifecycleViolation as ex:
                failures += 1
                print(f"LEDGER VIOLATION {name}: {ex}")
                continue
            if bound is not None and ledger.peak_device_bytes > bound:
                failures += 1
                print(f"BOUND VIOLATION {name}: measured "
                      f"{ledger.peak_device_bytes} device bytes > "
                      f"static bound {int(bound)}")
            try:
                ledger.assert_clean()
            except memsan.LifecycleViolation as ex:
                failures += 1
                print(f"DIRTY LEDGER {name}: {ex}")

    # the memory hazard fixtures must each trip their diagnostic
    bad = _builders(os.path.join(GOLDEN, "bad_plans.py"))
    mem_fixtures = {
        "plan_L013_shared_boundary_use_after_close": "TPU-L013",
        "plan_L014_peak_over_hbm_budget": "TPU-L014",
        "plan_L015_boundary_never_closes": "TPU-L015",
    }
    for name, code in sorted(mem_fixtures.items()):
        root, conf_map = bad[name]()
        got = {d.code for d in lint_plan(root, RapidsConf(conf_map),
                                         infer=True)}
        if code not in got:
            failures += 1
            print(f"FALSE ADMIT {name}: expected {code}, got "
                  f"{sorted(got)}")

    if failures:
        print(f"memsan gate: {failures} failure(s)")
        return 1
    print(f"memsan gate clean ({len(good)} good plans ledger-replayed "
          f"within their static bounds, {len(mem_fixtures)} memory "
          f"hazards flagged)")
    return 0


def run_obs_gate() -> int:
    """Flight-recorder gate: replay one golden query with tracing and
    the self-emitted event log on; fail on unclosed spans, an unflushed
    or unparsable log, or live-vs-parsed aggregate drift."""
    import shutil
    import tempfile

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession, last_query_metrics
    from spark_rapids_tpu.tools.eventlog import parse_event_log
    from spark_rapids_tpu.tools.profiling import (accuracy_report,
                                                  operator_metrics)

    failures = 0
    tmp = tempfile.mkdtemp(prefix="obs_gate_")
    try:
        s = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", True)
             .config("spark.rapids.tpu.eventLog.dir", tmp)
             .config("spark.rapids.tpu.trace.enabled", True)
             .get_or_create())
        tb = pa.table({
            "k": pa.array((np.arange(500) % 11).astype(np.int64)),
            "v": pa.array(np.arange(500, dtype=np.int64))})
        out = (s.create_dataframe(tb, num_partitions=2)
               .filter(col("v") > 5).group_by(col("k"))
               .agg(F.sum(col("v")).alias("sv"),
                    F.count("*").alias("c"))
               .collect())
        assert out.num_rows == 11
        trace = s.last_query_trace()
        if trace is None or not trace.sealed:
            failures += 1
            print("OBS: query left no sealed trace")
        elif trace.open_span_count():
            failures += 1
            print(f"OBS: {trace.open_span_count()} unclosed span(s)")
        logs = [f for f in os.listdir(tmp) if f.startswith("events_")]
        if not logs:
            failures += 1
            print("OBS: no event log flushed")
            return 1
        path = os.path.join(tmp, logs[0])
        rejected = 0
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    json.loads(line)
                except json.JSONDecodeError:
                    rejected += 1
        if rejected:
            failures += 1
            print(f"OBS: {rejected} event-log line(s) the parser "
                  f"rejects")
        app = parse_event_log(path)
        if 0 not in app.sql_executions or \
                app.sql_executions[0].end_time is None:
            failures += 1
            print("OBS: SQL execution missing or never ended in the "
                  "parsed log")
        parsed = operator_metrics(app, 0, "DEBUG")
        live = [tuple(r) for r in last_query_metrics(s, "DEBUG")]
        if parsed != live:
            failures += 1
            print(f"OBS: round-trip drift — parsed {len(parsed)} "
                  f"operator metric(s), live {len(live)}")
            for a, b in zip(parsed, live):
                if a != b:
                    print(f"  parsed {a} != live {b}")
        if not accuracy_report(app):
            failures += 1
            print("OBS: no predicted-vs-actual rows in the emitted "
                  "plan")
        if not app.spans:
            failures += 1
            print("OBS: no flight-recorder span records in the log")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        print(f"obs gate: {failures} failure(s)")
        return 1
    print("obs gate clean (1 golden query traced, logged, re-parsed "
          "and matched against live metrics)")
    return 0


# the golden regression corpus: three deterministic queries covering
# shuffle (fuse off), join and global sort.  Runs in a FRESH subprocess
# per replay so process-level caches (JIT, speculative fetch plans,
# scan pins) start identical — the same steady state two real CI runs
# see — making the deterministic fingerprint fields exactly comparable.
_REGRESS_CORPUS = r"""
import sys
import numpy as np
import pyarrow as pa
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col

eventlog_dir = sys.argv[1]
rng = np.random.default_rng(1234)
fact = pa.table({
    "k": pa.array((rng.integers(0, 97, 4000)).astype(np.int64)),
    "v": pa.array(rng.integers(-1000, 1000, 4000).astype(np.int64)),
})
dim = pa.table({
    "k": pa.array(np.arange(97, dtype=np.int64)),
    "w": pa.array(np.arange(97, dtype=np.int64) * 3),
})
s = (TpuSession.builder()
     .config("spark.rapids.sql.enabled", True)
     .config("spark.rapids.tpu.singleChipFuse", "off")
     # pin the sort kernel structure: 'auto' decides from the persistent
     # compile cache's cold/warm state, and the two gate replays must
     # compile the SAME program set (distinct_programs is deterministic)
     .config("spark.rapids.tpu.sort.compileLean", "off")
     .config("spark.rapids.tpu.eventLog.dir", eventlog_dir)
     .get_or_create())
fdf = s.create_dataframe(fact, num_partitions=2)
ddf = s.create_dataframe(dim)
out1 = (fdf.filter(col("v") > -500).group_by(col("k"))
        .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("c"))
        .collect())
assert out1.num_rows == 97, out1.num_rows
out2 = (fdf.join(ddf, on="k", how="inner").group_by(col("k"))
        .agg(F.sum(col("w")).alias("sw")).collect())
assert out2.num_rows == 97, out2.num_rows
out3 = fdf.sort(col("k"), col("v")).collect()
assert out3.num_rows == 4000, out3.num_rows
print("CORPUS_OK")
"""


def _replay_corpus(eventlog_dir: str) -> str:
    """One fresh-process replay of the golden corpus; returns the
    event-log path."""
    import subprocess
    r = subprocess.run(
        [sys.executable, "-c", _REGRESS_CORPUS, eventlog_dir],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS=os.environ.get(
            "JAX_PLATFORMS", "cpu")))
    if r.returncode != 0 or "CORPUS_OK" not in r.stdout:
        raise RuntimeError(f"corpus replay failed rc={r.returncode}:\n"
                           f"{r.stdout}\n{r.stderr}")
    logs = [f for f in os.listdir(eventlog_dir)
            if f.startswith("events_")]
    if len(logs) != 1:
        raise RuntimeError(f"expected 1 event log, found {logs}")
    return os.path.join(eventlog_dir, logs[0])


def run_regress_gate() -> int:
    import copy
    import shutil
    import tempfile

    from spark_rapids_tpu.obs.history import (HistoryDir,
                                              deterministic_drift,
                                              diff_runs,
                                              distill_event_log)

    failures = 0
    root = tempfile.mkdtemp(prefix="regress_gate_")
    try:
        hist = HistoryDir(os.path.join(root, "history"))
        for i in (1, 2):
            d = os.path.join(root, f"run{i}")
            os.makedirs(d)
            hist.record(distill_event_log(_replay_corpus(d)),
                        label=f"gate replay {i}")
        runs = hist.runs()
        run1, run2 = hist.load(runs[-2]), hist.load(runs[-1])
        drift = deterministic_drift(diff_runs(run1, run2))
        for dr in drift:
            failures += 1
            print(f"REPLAY DRIFT: {dr.render()}")

        # anti-vacuity: the differ must FLAG injected regressions —
        # a watchdog that never barks is worse than none
        tampered = copy.deepcopy(run2)
        q0 = tampered["queries"][0]
        q0["fallback_ops"] = sorted(q0["fallback_ops"] +
                                    ["InjectedHostOnlyExec"])
        q1 = tampered["queries"][min(1, len(tampered["queries"]) - 1)]
        q1["fetch_crossings"] = q1.get("fetch_crossings", 0) + 5
        kinds = {d.kind for d in
                 deterministic_drift(diff_runs(run1, tampered))}
        for want in ("new_fallback", "crossing_growth"):
            if want not in kinds:
                failures += 1
                print(f"VACUOUS DIFFER: injected {want} not flagged "
                      f"(got {sorted(kinds)})")
        n = len(run2.get("queries", ()))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    if failures:
        print(f"regress gate: {failures} failure(s)")
        return 1
    print(f"regress gate clean ({n} golden queries replayed twice with "
          f"identical deterministic fingerprints; injected fallback + "
          f"crossing bump both flagged)")
    return 0


# subsystem -> Prometheus family prefixes that must show a nonzero
# series after the golden query + bridge round trip (ISSUE acceptance:
# >= 6 distinct subsystems)
_METRIC_SUBSYSTEMS = {
    "spill": ("tpu_spill_",),
    "arena": ("tpu_arena_",),
    "shuffle": ("tpu_shuffle_",),
    "fetch": ("tpu_fetch_",),
    "session": ("tpu_queries_",),
    "ici/bridge": ("tpu_bridge_", "tpu_ici_"),
}


def run_metrics_gate() -> int:
    import threading

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.bridge import BridgeClient, SidecarServer
    from spark_rapids_tpu.obs.health import (HealthMonitor,
                                             render_prometheus)
    from spark_rapids_tpu.obs.metrics import MetricsRegistry

    failures = 0
    reg = MetricsRegistry.reset_for_tests()
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.singleChipFuse", "off")
         .config("spark.rapids.memory.pinnedPool.size", "8m")
         .config("spark.rapids.memory.tpu.spillBudgetBytes", 1)
         .get_or_create())
    tb = pa.table({
        "k": pa.array((np.arange(2000) % 53).astype(np.int64)),
        "v": pa.array(np.arange(2000, dtype=np.int64))})
    out = (s.create_dataframe(tb, num_partitions=2)
           .filter(col("v") > 5).group_by(col("k"))
           .agg(F.sum(col("v")).alias("sv")).collect())
    assert out.num_rows == 53, out.num_rows

    # one bridge round trip against the in-process reference sidecar
    server = SidecarServer(port=0)
    t = threading.Thread(target=server.serve_forever,
                         kwargs={"announce": False}, daemon=True)
    t.start()
    try:
        client = BridgeClient(server.port)
        res = client.execute_stage(
            {"ops": [{"op": "filter",
                      "condition": {"op": "gt",
                                    "children": [
                                        {"col": "v"},
                                        {"lit": 100,
                                         "type": "bigint"}]}}]},
            pa.table({"k": pa.array([1, 2, 3], pa.int64()),
                      "v": pa.array([50, 150, 250], pa.int64())}))
        assert res.num_rows == 2, res.num_rows
        client.close()
    finally:
        server.shutdown()

    text = render_prometheus(reg)
    lit = set()
    for sub, prefixes in _METRIC_SUBSYSTEMS.items():
        nonzero = [
            line for line in text.splitlines()
            if any(line.startswith(p) for p in prefixes)
            and not line.startswith("#")
            and float(line.rsplit(None, 1)[-1]) > 0]
        if nonzero:
            lit.add(sub)
        else:
            failures += 1
            print(f"METRICS: subsystem {sub} exposed no nonzero "
                  f"series (prefixes {prefixes})")
    snap = HealthMonitor(reg).snapshot()
    for key in ("status", "timestamp_ms", "components", "queries"):
        if key not in snap:
            failures += 1
            print(f"HEALTH: snapshot missing key {key!r}")
    if snap.get("status") not in ("ok", "degraded", "down"):
        failures += 1
        print(f"HEALTH: bad status {snap.get('status')!r}")
    if failures:
        print(f"metrics gate: {failures} failure(s)")
        return 1
    print(f"metrics gate clean ({len(lit)} subsystems exposed nonzero "
          f"Prometheus series from one golden query + one bridge round "
          f"trip; health snapshot schema ok)")
    return 0


def run_jit_gate() -> int:
    """Compile-observatory gate: the golden corpus replays TWICE in one
    process — the second pass must produce ZERO program builds (shape-
    canonicalization honesty: identical queries must share programs),
    the ledger, the jit.build spans and the tpu_jit_misses_total metric
    must agree about the build count, every build must carry a
    classified cause, `tools compile-report` must attribute >= 95% of
    measured wall compile time, and (anti-vacuity) a key/shape
    perturbing injection must produce a classified churn miss."""
    import shutil
    import tempfile

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec import base as eb
    from spark_rapids_tpu.obs.compileprof import (CAUSES,
                                                  CompileObservatory)
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.tools.eventlog import parse_event_log

    failures = 0
    tmp = tempfile.mkdtemp(prefix="jit_gate_")
    reg = MetricsRegistry.reset_for_tests()
    obs = CompileObservatory.reset_for_tests()
    eb.clear_jit_cache()
    try:
        evt = os.path.join(tmp, "evt")
        os.makedirs(evt)
        hist = os.path.join(tmp, "hist")
        s = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", True)
             .config("spark.rapids.tpu.singleChipFuse", "off")
             .config("spark.rapids.tpu.sort.compileLean", "off")
             .config("spark.rapids.tpu.eventLog.dir", evt)
             .config("spark.rapids.tpu.compile.ledgerDir", hist)
             .get_or_create())
        rng = np.random.default_rng(1234)
        fact = pa.table({
            "k": pa.array((rng.integers(0, 97, 4000)).astype(np.int64)),
            "v": pa.array(rng.integers(-1000, 1000, 4000)
                          .astype(np.int64))})
        dim = pa.table({
            "k": pa.array(np.arange(97, dtype=np.int64)),
            "w": pa.array(np.arange(97, dtype=np.int64) * 3)})
        fdf = s.create_dataframe(fact, num_partitions=2)
        ddf = s.create_dataframe(dim)

        def corpus():
            o1 = (fdf.filter(col("v") > -500).group_by(col("k"))
                  .agg(F.sum(col("v")).alias("sv"),
                       F.count("*").alias("c")).collect())
            o2 = (fdf.join(ddf, on="k", how="inner").group_by(col("k"))
                  .agg(F.sum(col("w")).alias("sw")).collect())
            o3 = fdf.sort(col("k"), col("v")).collect()
            assert (o1.num_rows, o2.num_rows, o3.num_rows) == \
                (97, 97, 4000)

        corpus()
        snap1 = obs.snapshot()
        if snap1["builds"] == 0:
            failures += 1
            print("JIT: vacuous gate — the corpus compiled nothing")
        for cause in snap1["by_cause"]:
            if cause not in CAUSES:
                failures += 1
                print(f"JIT: unrecognized miss cause {cause!r}")
        corpus()
        snap2 = obs.snapshot()
        if snap2["builds"] != snap1["builds"]:
            failures += 1
            print(f"JIT: SECOND-PASS MISS — replaying the identical "
                  f"corpus built {snap2['builds'] - snap1['builds']} "
                  f"new program(s) (shape canonicalization is lying); "
                  f"causes now {snap2['by_cause']}")

        # three sinks, one truth: ledger / spans / metrics must agree
        ledger_builds = 0
        ledger_path = os.path.join(hist, "compile_ledger.jsonl")
        if os.path.exists(ledger_path):
            with open(ledger_path) as f:
                ledger_builds = sum(
                    1 for line in f if line.strip()
                    and json.loads(line).get("event") == "build")
        logs = [f for f in os.listdir(evt) if f.startswith("events_")]
        span_builds = 0
        if logs:
            app = parse_event_log(os.path.join(evt, logs[0]))
            span_builds = sum(1 for sp in app.spans
                              if sp.get("name") == "jit.build")
        fam = reg.counter("tpu_jit_misses_total",
                          labelnames=("exec", "cause"))
        metric_builds = sum(ch.value for _, ch in fam.series())
        if not (snap2["builds"] == ledger_builds == span_builds ==
                metric_builds):
            failures += 1
            print(f"JIT: build-count disagreement — observatory "
                  f"{snap2['builds']}, ledger {ledger_builds}, "
                  f"jit.build spans {span_builds}, "
                  f"tpu_jit_misses_total {metric_builds}")

        # the dedupe projection is a CONTRACT, not a report: with
        # bucket-canonical tracing landed, the corpus must realize no
        # more distinct programs than the observatory projects under
        # canonicalization — i.e. zero projected savings left on the
        # table.  (Checked before the churn-injection probes below,
        # which deliberately add shape/dtype churn.)
        from spark_rapids_tpu.tools.compile_report import (
            aggregate_ledger, load_ledger)
        agg_c = aggregate_ledger(load_ledger(ledger_path))
        if agg_c["distinct_programs"] > agg_c["canonical_families"]:
            failures += 1
            print(f"JIT: PROJECTION BROKEN — corpus realized "
                  f"{agg_c['distinct_programs']} distinct program(s) "
                  f"vs {agg_c['canonical_families']} canonical "
                  f"familie(s): {agg_c['projected_savings_s']:.2f}s of "
                  f"bucket-churn compile left on the table")

        # recompile-drift watchdog: the gate's own event log distilled
        # against the pre-change recording — distinct compiled programs
        # per corpus query must not GROW past the baseline (fewer is
        # progress; query_added drifts from the second pass are
        # expected and ignored)
        from spark_rapids_tpu.obs.history import (diff_runs,
                                                  distill_event_log)
        baseline_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "jit_corpus_baseline.json")
        if logs and os.path.exists(baseline_path):
            with open(baseline_path) as f:
                baseline = json.load(f)
            current = {"queries":
                       distill_event_log(os.path.join(evt, logs[0]))}
            recompiles = [d for d in diff_runs(baseline, current)
                          if d.kind == "recompile_drift"]
            for d in recompiles:
                failures += 1
                print(f"JIT: RECOMPILE DRIFT vs pre-change baseline — "
                      f"{d.render()}")
        else:
            failures += 1
            print(f"JIT: recompile-drift check could not run "
                  f"(event log present: {bool(logs)}, baseline "
                  f"present: {os.path.exists(baseline_path)})")

        # anti-vacuity: a capacity-bucket perturbation (same program
        # modulo buckets) must be classified, not silently re-counted
        # as novel work
        import jax.numpy as jnp
        probe = eb.process_jit(("JitGateProbe", "sig"),
                               lambda: (lambda x: x + 1))
        probe(jnp.zeros(1024, jnp.int32))
        churn0 = obs.snapshot()["by_cause"].get("shape_churn", 0)
        probe(jnp.zeros(8192, jnp.int32))         # bucket perturbation
        churn1 = obs.snapshot()["by_cause"].get("shape_churn", 0)
        if churn1 != churn0 + 1:
            failures += 1
            print(f"JIT: bucket-perturbed probe not classified as "
                  f"shape_churn (causes {obs.snapshot()['by_cause']})")
        dt0 = obs.snapshot()["by_cause"].get("dtype_churn", 0)
        probe(jnp.zeros(1024, jnp.float32))       # dtype perturbation
        dt1 = obs.snapshot()["by_cause"].get("dtype_churn", 0)
        if dt1 != dt0 + 1:
            failures += 1
            print(f"JIT: dtype-perturbed probe not classified as "
                  f"dtype_churn (causes {obs.snapshot()['by_cause']})")

        # the acceptance bar: the report must attribute the wall
        # compile time it measured, with every miss carrying a cause
        agg = aggregate_ledger(load_ledger(ledger_path))
        if agg["attribution_pct"] < 95.0:
            failures += 1
            print(f"JIT: compile-report attributes only "
                  f"{agg['attribution_pct']:.1f}% of wall compile "
                  f"time (< 95%)")
        if agg["causeless_builds"]:
            failures += 1
            print(f"JIT: {agg['causeless_builds']} build(s) carry no "
                  f"miss cause")
        n_builds = snap2["builds"]
        total_s = agg["total_s"]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        eb.clear_jit_cache()
    if failures:
        print(f"jit gate: {failures} failure(s)")
        return 1
    print(f"jit gate clean ({n_builds} corpus program(s) built once, "
          f"{total_s:.2f}s wall compile fully attributed; second pass "
          f"zero-miss; ledger/span/metric counts agree; dedupe "
          f"projection realized; no recompile drift vs the pre-change "
          f"baseline; bucket and dtype perturbations classified)")
    return 0


def run_shuffle_gate() -> int:
    """Distributed-shuffle gate: (a) the forced-shuffled-join bridge
    golden replays through a real session under the memsan shadow
    ledger with the spill budget pinned to 1 byte, so every registered
    map-output block demotes off-device and must come back correct;
    (b) a transport leg serves real catalog blocks over TCP and the
    async fetcher's block/byte counters must agree with what the
    server registered (and count zero errors)."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.bridge.spec import plan_spec_to_logical
    from spark_rapids_tpu.columnar.device import (batch_to_arrow,
                                                  batch_to_device)
    from spark_rapids_tpu.memory import memsan
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.obs import metrics as m
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
    from spark_rapids_tpu.shuffle.transport import (AsyncBlockFetcher,
                                                    ShuffleClient,
                                                    ShuffleServer)

    failures = 0
    MetricsRegistry.reset_for_tests()
    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()

    golden = os.path.join(REPO, "bridge-jvm", "src", "test",
                          "resources", "goldens",
                          "shuffled_join_forced.json")
    with open(golden) as f:
        spec = json.load(f)["spec"]
    spec["numPartitions"] = 4

    # skewed keys: every other row hits key 0, so each map batch's
    # per-partition slice buckets sum PAST the whole-batch bucket and
    # the slice-view write must bank nonzero saved bytes (anti-vacuity
    # for tpu_shuffle_write_saved_bytes_total)
    n = 4000
    ids = np.where(np.arange(n) % 2 == 0, 0,
                   np.arange(n) % 97).astype(np.int64)
    fact = pa.table({"id": pa.array(ids),
                     "x": pa.array(np.arange(n, dtype=np.int64))})
    dim = pa.table({"user_id": pa.array(np.arange(97, dtype=np.int64)),
                    "w": pa.array(np.arange(97, dtype=np.int64) * 10)})

    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.singleChipFuse", "off")
         .config("spark.rapids.memory.tpu.spillBudgetBytes", 1)
         .get_or_create())
    with memsan.installed() as ledger:
        got = s.execute(plan_spec_to_logical(spec, fact, (dim,)))
        names = []
        s.last_plan.foreach(lambda e: names.append(type(e).__name__))
        if "ShuffledHashJoinExec" not in names or \
                names.count("ShuffleExchangeExec") < 2:
            failures += 1
            print(f"SHUFFLE: golden plan lost its shuffled shape: "
                  f"{names}")
        if "BroadcastHashJoinExec" in names:
            failures += 1
            print("SHUFFLE: forced-shuffled golden fell back to "
                  "broadcast")
        want = np.sort(ids * 10)
        if not np.array_equal(np.sort(got.column("w").to_numpy()),
                              want) or got.num_rows != n:
            failures += 1
            print(f"SHUFFLE: wrong join result ({got.num_rows} rows)")
        peak = ledger.peak_device_bytes
        try:
            ledger.assert_clean()
        except memsan.LifecycleViolation as ex:
            failures += 1
            print(f"SHUFFLE: dirty ledger after stage release: {ex}")
    if TpuShuffleManager.get().catalog.num_blocks() != 0:
        failures += 1
        print(f"SHUFFLE: {TpuShuffleManager.get().catalog.num_blocks()}"
              f" catalog block(s) leaked past release_plan_shuffles")
    leaks = SpillCatalog.get().leak_report()
    if leaks:
        failures += 1
        print(f"SHUFFLE: {len(leaks)} spillable buffer(s) leaked")
    spilled = sum(ch.value for _, ch in
                  m.counter("tpu_spill_bytes_total",
                            labelnames=("tier",)).series())
    if spilled <= 0:
        failures += 1
        print("SHUFFLE: vacuous replay — a 1-byte spill budget spilled "
              "nothing")
    saved = m.counter("tpu_shuffle_write_saved_bytes_total").value()
    if saved <= 0:
        failures += 1
        print("SHUFFLE: slice-view map write banked zero saved bytes "
              "on a skewed corpus")
    wrote = m.counter("tpu_shuffle_write_blocks_total").value()
    read = m.counter("tpu_shuffle_read_batches_total").value()
    if wrote <= 0 or read <= 0:
        failures += 1
        print(f"SHUFFLE: write/read counters never moved "
              f"(wrote {wrote}, read {read})")

    # transport leg: real catalog blocks over TCP, counters must agree
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    n_maps, rows = 6, 128
    for mid in range(n_maps):
        rb = pa.record_batch({"a": pa.array(
            [mid * 1000 + i for i in range(rows)], type=pa.int64())})
        mgr.write_map_output(21, mid, {0: batch_to_device(rb, xp=np)})
    server = ShuffleServer(mgr).start()
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        first = [batch_to_arrow(b).column("a").to_pylist()[0]
                 for b in AsyncBlockFetcher(cli, 21, 0, window=3)]
        cli.close()
    finally:
        server.stop()
        TpuShuffleManager.reset()
    if first != [mid * 1000 for mid in range(n_maps)]:
        failures += 1
        print(f"SHUFFLE: transport leg fetched wrong blocks: {first}")
    fetched = m.counter("tpu_shuffle_fetch_blocks_total").value()
    if fetched != n_maps:
        failures += 1
        print(f"SHUFFLE: fetched-block counter disagrees "
              f"({fetched} != {n_maps} served)")
    if m.counter("tpu_shuffle_fetch_bytes_total").value() <= 0:
        failures += 1
        print("SHUFFLE: fetched-bytes counter never moved")
    errs = m.counter("tpu_shuffle_fetch_errors_total",
                     labelnames=("kind",))
    n_errs = sum(ch.value for _, ch in errs.series())
    if n_errs:
        failures += 1
        print(f"SHUFFLE: clean transport leg counted {n_errs} fetch "
              f"error(s)")

    # wire leg: injected remote failures must surface TYPED, with
    # tpu_shuffle_fetch_errors_total{kind} agreeing, and the locality
    # split must prove local blocks never cross the wire
    wire_failures = _shuffle_wire_leg()
    failures += wire_failures

    MetricsRegistry.reset_for_tests()
    if failures:
        print(f"shuffle gate: {failures} failure(s)")
        return 1
    print(f"shuffle gate clean (forced-shuffled golden joined "
          f"correctly under a 1-byte spill budget, peak {int(peak)} "
          f"device bytes, {int(spilled)} bytes spilled, {int(saved)} "
          f"slice-view bytes saved, ledger + catalog clean; transport "
          f"leg fetched {int(fetched)} blocks with zero errors; wire "
          f"leg: every injected remote failure surfaced typed, "
          f"replica retry completed exactly once, local blocks stayed "
          f"zero-copy, cross-process golden bit-exact)")
    return 0


def _shuffle_wire_leg() -> int:
    """Injected-failure wire scenarios.  Each rogue server speaks just
    enough protocol to inject ONE specific fault; the client must fail
    with the matching typed error AND count it under the matching
    ``tpu_shuffle_fetch_errors_total{kind}`` — a mismatch between what
    raised and what was counted is itself a failure."""
    import socket
    import struct
    import subprocess
    import threading
    import time

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.columnar.device import (batch_to_arrow,
                                                  batch_to_device)
    from spark_rapids_tpu.memory.meta import (CODEC_LZ4, MAGIC, VERSION,
                                              _HEADER, TableMeta)
    from spark_rapids_tpu.obs import metrics as m
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.errors import (
        TpuShuffleCorruptBlockError, TpuShuffleFetchFailedError,
        TpuShufflePeerDeadError, TpuShuffleStaleFrameError,
        TpuShuffleTruncatedFrameError)
    from spark_rapids_tpu.shuffle.heartbeat import HeartbeatManager
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
    from spark_rapids_tpu.shuffle.registry import (BlockEndpoint,
                                                   BlockLocationRegistry)
    from spark_rapids_tpu.shuffle.transport import (
        _FRAME, _recv_exact, MSG_BUFFER, MSG_ERROR, MSG_HELLO,
        MSG_METADATA_RESP, AsyncBlockFetcher, ShuffleClient,
        ShuffleServer, _server_requests_counter)

    failures = 0
    errs = m.counter("tpu_shuffle_fetch_errors_total",
                     "async fetch failures by kind",
                     labelnames=("kind",))

    def rogue(script):
        """One-connection server running ``script(conn)`` then closing:
        the injected-failure side of each scenario."""
        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(1)
        port = lsock.getsockname()[1]

        def run():
            conn, _ = lsock.accept()
            try:
                script(conn)
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
                lsock.close()

        threading.Thread(target=run, daemon=True).start()
        return port

    def read_req(conn):
        while True:
            head = _recv_exact(conn, _FRAME.size)
            mtype, rid, blen = _FRAME.unpack(head)
            if blen:
                _recv_exact(conn, blen)
            if mtype == MSG_HELLO:
                # pre-fleet peer: the correlated bad_message refusal
                # pins the client to v1 framing, so the scripted
                # request arrives next in the shape read above
                eb = b"bad_message:unknown message type"
                conn.sendall(_FRAME.pack(MSG_ERROR, rid, len(eb)) + eb)
                continue
            return mtype, rid

    def expect(name, port, exc_type, kind, window=2):
        """Drive one fetch against the rogue at ``port``; it must raise
        ``exc_type`` and bump errs{kind} by exactly one."""
        nonlocal failures
        before = errs.value(kind=kind)
        cli = ShuffleClient("127.0.0.1", port, timeout=10.0)
        try:
            list(AsyncBlockFetcher(cli, 31, 0, window=window,
                                   timeout=10.0))
        except exc_type:
            pass
        except Exception as ex:  # noqa: BLE001 — report the wrong type
            failures += 1
            print(f"SHUFFLE-WIRE: {name} raised "
                  f"{type(ex).__name__} ({ex}), expected "
                  f"{exc_type.__name__}")
            cli.close()
            return
        else:
            failures += 1
            print(f"SHUFFLE-WIRE: {name} did not raise")
            cli.close()
            return
        cli.close()
        got = errs.value(kind=kind) - before
        if got != 1:
            failures += 1
            print(f"SHUFFLE-WIRE: {name} counted {got} "
                  f"errors_total{{kind={kind}}}, expected 1")

    # (1) stale frame: a response correlating to a DIFFERENT request id
    def stale_script(conn):
        _, rid = read_req(conn)
        conn.sendall(_FRAME.pack(MSG_METADATA_RESP, rid + 977, 0))

    expect("stale frame", rogue(stale_script),
           TpuShuffleStaleFrameError, "stale")

    # (2) truncated frame: header promises 100 body bytes, sends 10
    def trunc_script(conn):
        _, rid = read_req(conn)
        conn.sendall(_FRAME.pack(MSG_METADATA_RESP, rid, 100)
                     + b"x" * 10)

    expect("truncated frame", rogue(trunc_script),
           TpuShuffleTruncatedFrameError, "truncated")

    # (3) corrupt compressed body: valid TPUB header claiming lz4, then
    # garbage where the codec frame should be
    def corrupt_script(conn):
        _, rid = read_req(conn)
        meta = (struct.pack("<i", 1)
                + struct.pack("<qqqq", 31, 0, 0, 0)
                + TableMeta.of_stats(10, 160, 0).pack())
        conn.sendall(_FRAME.pack(MSG_METADATA_RESP, rid, len(meta))
                     + meta)
        _, rid = read_req(conn)
        payload = _HEADER.pack(MAGIC, VERSION, CODEC_LZ4, 10, 20) \
            + b"\xff" * 20
        conn.sendall(_FRAME.pack(MSG_BUFFER, rid, 8)
                     + struct.pack("<q", len(payload)) + payload)

    expect("corrupt codec body", rogue(corrupt_script),
           TpuShuffleCorruptBlockError, "corrupt")

    # (4) mid-fetch server death: a REAL server stopped after the
    # consumer takes its first block — the rest of the stream must fail
    # typed, not hang
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    for mid in range(6):
        rb = pa.record_batch({"a": pa.array(
            [mid * 100 + i for i in range(64)], type=pa.int64())})
        mgr.write_map_output(41, mid, {0: batch_to_device(rb, xp=np)})
    server = ShuffleServer(mgr).start()
    before = errs.value(kind="fetch_failed")
    cli = ShuffleClient("127.0.0.1", server.port, timeout=10.0)
    died_typed = False
    try:
        for i, _b in enumerate(AsyncBlockFetcher(cli, 41, 0, window=1,
                                                 timeout=10.0)):
            if i == 0:
                server.stop()
    except TpuShuffleFetchFailedError:
        died_typed = True
    except Exception as ex:  # noqa: BLE001
        failures += 1
        print(f"SHUFFLE-WIRE: mid-fetch death raised "
              f"{type(ex).__name__}, expected a typed fetch failure")
    cli.close()
    if not died_typed and not failures:
        failures += 1
        print("SHUFFLE-WIRE: mid-fetch server death did not fail the "
              "stream")
    if died_typed and errs.value(kind="fetch_failed") - before != 1:
        failures += 1
        print("SHUFFLE-WIRE: mid-fetch death not counted under "
              "kind=fetch_failed")

    # (5) heartbeat-dead peer: every replica expired -> typed peer-dead
    # without ever dialing
    hb = HeartbeatManager(timeout_s=0.01)
    hb.register_executor("wire-dead", "127.0.0.1", 1)
    time.sleep(0.05)
    BlockLocationRegistry.reset()
    reg = BlockLocationRegistry.get()
    reg.set_local("gate-reduce", "127.0.0.1", 0)
    reg.attach_heartbeat(hb)
    group = [BlockEndpoint("wire-dead", "127.0.0.1", 1)]
    before = errs.value(kind="peer_dead")
    try:
        list(locality._fetch_group(group, 42, 0, reg, np, 2, 5.0, 1, m))
        failures += 1
        print("SHUFFLE-WIRE: all-dead replica group did not raise")
    except TpuShufflePeerDeadError:
        if errs.value(kind="peer_dead") - before != 1:
            failures += 1
            print("SHUFFLE-WIRE: dead peer group not counted under "
                  "kind=peer_dead")

    # (6) replica retry, exactly once: first replica's port refuses the
    # dial, the live replica must serve ALL blocks with ONE retry and
    # zero duplicates
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    for mid in range(6):
        rb = pa.record_batch({"a": pa.array(
            [mid * 100 + i for i in range(64)], type=pa.int64())})
        mgr.write_map_output(43, mid, {0: batch_to_device(rb, xp=np)})
    server = ShuffleServer(mgr).start()
    dead_sock = socket.socket()
    dead_sock.bind(("127.0.0.1", 0))
    dead_port = dead_sock.getsockname()[1]
    dead_sock.close()  # nothing listens here anymore
    hb2 = HeartbeatManager(timeout_s=60.0)
    hb2.register_executor("replica-a", "127.0.0.1", dead_port)
    hb2.register_executor("replica-b", "127.0.0.1", server.port)
    reg.attach_heartbeat(hb2)
    group = [BlockEndpoint("replica-a", "127.0.0.1", dead_port),
             BlockEndpoint("replica-b", "127.0.0.1", server.port)]
    retries = m.counter("tpu_shuffle_fetch_retries_total")
    r_before = retries.value()
    locality.reset_pool()
    try:
        got = [batch_to_arrow(b).column("a").to_pylist()[0]
               for b in locality._fetch_group(group, 43, 0, reg, np,
                                              2, 5.0, 2, m)]
        if got != [mid * 100 for mid in range(6)]:
            failures += 1
            print(f"SHUFFLE-WIRE: replica retry delivered {got} "
                  f"(duplicates or gaps)")
        if retries.value() - r_before != 1:
            failures += 1
            print(f"SHUFFLE-WIRE: replica retry counted "
                  f"{retries.value() - r_before} retries, expected 1")
    except Exception as ex:  # noqa: BLE001
        failures += 1
        print(f"SHUFFLE-WIRE: replica retry failed: "
              f"{type(ex).__name__}: {ex}")
    finally:
        server.stop()
        locality.reset_pool()

    # (7) local zero-copy proof: a shuffle whose owner group is THIS
    # process must serve from the catalog — local counter moves, the
    # block-server transfer counter must NOT
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    rb = pa.record_batch({"a": pa.array(range(64), type=pa.int64())})
    mgr.write_map_output(44, 0, {0: batch_to_device(rb, xp=np)})
    server = ShuffleServer(mgr).start()
    BlockLocationRegistry.reset()
    reg = BlockLocationRegistry.get()
    reg.set_local("gate-local", "127.0.0.1", server.port)
    reg.register(44, [BlockEndpoint("gate-local", "127.0.0.1",
                                    server.port)])
    local_c = m.counter("tpu_shuffle_local_blocks_total")
    srv_c = _server_requests_counter()
    l_before = local_c.value()
    t_before = srv_c.value(kind="transfer")
    n_local = sum(1 for _ in locality.read_reduce_blocks(44, 0))
    server.stop()
    if n_local != 1 or local_c.value() - l_before != 1:
        failures += 1
        print(f"SHUFFLE-WIRE: local group read {n_local} block(s), "
              f"local counter moved "
              f"{local_c.value() - l_before} — zero-copy path broken")
    if srv_c.value(kind="transfer") - t_before != 0:
        failures += 1
        print("SHUFFLE-WIRE: local blocks crossed the wire (server "
              "transfer counter moved)")

    # (8) forced-remote golden over loopback: a child OS process owns
    # the map outputs; the joined result here must be bit-exact vs the
    # in-process reference, with zero local reads and zero leaks
    from spark_rapids_tpu.shuffle.serve_map import (
        DIM_SID, FACT_SID, build_side_tables, partition_record_batch)
    TpuShuffleManager.reset()
    BlockLocationRegistry.reset()
    reg = BlockLocationRegistry.get()
    reg.set_local("gate-reduce", "127.0.0.1", 0)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SPARK_RAPIDS_TPU_DISABLE_COMPILE_CACHE="1")
    rows, parts, seed = 4000, 2, 3
    child = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.shuffle.serve_map",
         "--rows", str(rows), "--parts", str(parts),
         "--codec", "lz4", "--seed", str(seed)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env, cwd=REPO)
    try:
        line = child.stdout.readline()
        port = int(line.split()[1])
        ep = BlockEndpoint("gate-map", "127.0.0.1", port)
        reg.register(FACT_SID, [ep])
        reg.register(DIM_SID, [ep])
        l_before = local_c.value()
        out = []
        for pid in range(parts):
            sides = []
            for sid in (FACT_SID, DIM_SID):
                rbs = [batch_to_arrow(b) for b in
                       locality.read_reduce_blocks(sid, pid)]
                sides.append(pa.Table.from_batches(rbs)
                             if rbs else None)
            if sides[0] is not None and sides[1] is not None:
                out.append(sides[0].join(sides[1], "k"))
        got_tbl = pa.concat_tables(out).sort_by(
            [("k", "ascending"), ("v", "ascending")])
        fact, dim = build_side_tables(rows, seed)
        ref = []
        fparts = partition_record_batch(fact, "k", parts)
        dparts = partition_record_batch(dim, "k", parts)
        for pid in range(parts):
            f, d = fparts.get(pid), dparts.get(pid)
            if f is not None and d is not None:
                ref.append(pa.table(f).join(pa.table(d), "k"))
        ref_tbl = pa.concat_tables(ref).sort_by(
            [("k", "ascending"), ("v", "ascending")])
        if not got_tbl.equals(ref_tbl):
            failures += 1
            print(f"SHUFFLE-WIRE: cross-process golden NOT bit-exact "
                  f"({got_tbl.num_rows} vs {ref_tbl.num_rows} rows)")
        if local_c.value() - l_before != 0:
            failures += 1
            print("SHUFFLE-WIRE: cross-process run took the local "
                  "path for remote-owned blocks")
        child.stdin.write("done\n")
        child.stdin.flush()
        stats_line = child.stdout.readline()
        stats = json.loads(stats_line[len("STATS "):])
        if stats["leaked_blocks"] or stats["leaks"]:
            failures += 1
            print(f"SHUFFLE-WIRE: map-side process leaked "
                  f"{stats['leaked_blocks']} block(s), "
                  f"{stats['leaks']} spill ledger leak(s)")
        ratio = (stats["compressed_bytes"] / stats["raw_bytes"]
                 if stats["raw_bytes"] else 1.0)
        if ratio >= 0.9:
            failures += 1
            print(f"SHUFFLE-WIRE: lz4 shuffle ratio {ratio:.3f} >= "
                  f"0.9 — compression not visible on the wire")
        child.wait(timeout=30)
    finally:
        child.stdin.close()
        child.stdout.close()
        if child.poll() is None:
            child.kill()
            child.wait()
        locality.reset_pool()
        BlockLocationRegistry.reset()
        TpuShuffleManager.reset()
    return failures


def run_serve_gate() -> int:
    """Multi-tenant serving gate: a golden four-query mix replayed 16
    times across 4 concurrent pooled sessions under byte-weighted
    admission.  Every concurrent result must equal the serial ground
    truth bit-for-bit; the memsan dirty-ledger counter must stay zero;
    the admission books must balance (admitted = completed + failed,
    zero timeouts, max bytes in flight nonzero and within budget); and
    after the pool drains no shuffle block or spillable buffer may
    survive (orphan check)."""
    import concurrent.futures as cf

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.expr.window import WindowBuilder
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.obs import metrics as m
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager

    failures = 0
    MetricsRegistry.reset_for_tests()
    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()
    AdmissionController.reset_for_tests()

    n = 4000
    rng = np.random.default_rng(7)
    fact = pa.table({
        "k": pa.array(rng.integers(0, 97, n).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(97, dtype=np.int64)),
        "w": pa.array(np.arange(97, dtype=np.int64) * 10),
    })
    budget = 256 << 20
    pool = SessionPool(4, {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.tpu.memsan.enabled": "true",
        "spark.rapids.tpu.singleChipFuse": "off",
        "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes": str(budget),
        "spark.rapids.tpu.serve.admissionTimeoutMs": "60000",
    })

    def mk_mix(s):
        fdf = s.create_dataframe(fact)
        # multi-partition join keeps real shuffle blocks in play so the
        # post-drain orphan check is not vacuous
        fdf4 = s.create_dataframe(fact, num_partitions=4)
        ddf2 = s.create_dataframe(dim, num_partitions=2)
        w = WindowBuilder().partition_by(col("k")).order_by(col("v"))
        return {
            "agg": lambda: (fdf.group_by(col("k"))
                            .agg(F.sum(col("v")).alias("sv"),
                                 F.count("*").alias("c")).collect()),
            "join": lambda: (fdf4.join(ddf2, on="k", how="inner")
                             .group_by(col("k"))
                             .agg(F.sum(col("w")).alias("sw"))
                             .collect()),
            "window": lambda: (fdf.select(
                col("k"), col("v"),
                F.row_number().over(w).alias("rn")).collect()),
            "sort": lambda: fdf.sort(col("k"), col("v")).collect(),
        }

    mixes = {id(s): mk_mix(s) for s in pool._sessions}

    def canon(tb):
        cols = sorted(tb.column_names)
        return sorted(zip(*(tb.column(c).to_pylist() for c in cols)))

    expected = {}
    with pool.session() as s:        # serial ground truth
        for name, q in mixes[id(s)].items():
            expected[name] = canon(q())

    worklist = [name for name in sorted(expected) for _ in range(4)]

    def one(name):
        with pool.session() as s:
            return name, canon(mixes[id(s)][name]())

    with cf.ThreadPoolExecutor(max_workers=4) as ex:
        results = list(ex.map(one, worklist))
    pool.drain(timeout=60)
    pool.close()

    wrong = [name for name, got in results if got != expected[name]]
    if wrong:
        failures += 1
        print(f"SERVE: {len(wrong)} concurrent result(s) diverged from "
              f"the serial ground truth: {sorted(set(wrong))}")
    dirty = m.counter("tpu_memsan_dirty_ledgers_total").value()
    if dirty:
        failures += 1
        print(f"SERVE: {dirty} dirty memsan ledger(s) under concurrency")
    # admission counters are tenant-labeled; total() sums the fleet
    admitted = m.counter("tpu_admission_admitted_total",
                         labelnames=("tenant",)).total()
    completed = m.counter("tpu_queries_completed_total").value()
    failed = m.counter("tpu_queries_failed_total").value()
    timeouts = m.counter("tpu_admission_timeouts_total",
                         labelnames=("tenant",)).total()
    if admitted != completed + failed:
        failures += 1
        print(f"SERVE: admission books don't balance: {admitted} "
              f"admitted != {completed} completed + {failed} failed")
    if failed or timeouts:
        failures += 1
        print(f"SERVE: clean mix counted {failed} failure(s), "
              f"{timeouts} timeout(s)")
    ctrl = AdmissionController.get()
    peak_in_flight = ctrl.max_in_flight_seen if ctrl else -1
    if ctrl is None or peak_in_flight <= 0:
        failures += 1
        print("SERVE: vacuous gate — no byte-weighted ticket was ever "
              "in flight")
    elif peak_in_flight > budget:
        failures += 1
        print(f"SERVE: bytes in flight exceeded the budget "
              f"({peak_in_flight} > {budget})")
    blocks = TpuShuffleManager.get().catalog.num_blocks()
    if blocks:
        failures += 1
        print(f"SERVE: {blocks} orphaned shuffle block(s) after drain")
    leaks = SpillCatalog.get().leak_report()
    if leaks:
        failures += 1
        print(f"SERVE: {len(leaks)} spillable buffer(s) leaked")

    MetricsRegistry.reset_for_tests()
    AdmissionController.reset_for_tests()
    if failures:
        print(f"serve gate: {failures} failure(s)")
        return 1
    print(f"serve gate clean ({len(results)} concurrent queries across "
          f"4 sessions matched the serial ground truth; {admitted} "
          f"admitted = {completed} completed + {failed} failed, zero "
          f"timeouts; peak {int(peak_in_flight)} ticket bytes in "
          f"flight within the {budget} budget; ledgers, shuffle "
          f"catalog and spill catalog all clean after drain)")
    return 0


def run_hbm_gate() -> int:
    """HBM-observatory gate (obs/memprof.py): (1) golden replay where
    three independent sinks must agree — the tenant timeline's
    spill-backed peak, the memsan shadow ledger's measured peak and the
    spill catalog's registered device bytes, all equal and nonzero, and
    the tpu_hbm_tenant_bytes gauge family must sum to the timeline's
    live total; (2) a 4-session pool stress where pool tenants book
    their own occupancy and ZERO events go unattributed; (3)
    anti-vacuity — an allocation injected from a context-free thread
    MUST count as unattributed, and an injected operator failure MUST
    leave exactly one well-formed post-mortem bundle naming the failing
    operator and the owning tenant."""
    import concurrent.futures as cf
    import shutil
    import tempfile
    import threading

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.expr.window import WindowBuilder
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.obs import metrics as m
    from spark_rapids_tpu.obs import postmortem as pm
    from spark_rapids_tpu.obs.memprof import MemoryTimeline
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager

    failures = 0
    MetricsRegistry.reset_for_tests()
    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()
    AdmissionController.reset_for_tests()
    MemoryTimeline.reset_for_tests()

    n = 4000
    rng = np.random.default_rng(7)
    fact = pa.table({
        "k": pa.array(rng.integers(0, 97, n).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(97, dtype=np.int64)),
        "w": pa.array(np.arange(97, dtype=np.int64) * 10),
    })
    pmdir = tempfile.mkdtemp(prefix="tpu_hbm_gate_pm_")
    pool = SessionPool(4, {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.tpu.memsan.enabled": "true",
        "spark.rapids.tpu.trace.enabled": "true",
        "spark.rapids.tpu.singleChipFuse": "off",
        "spark.rapids.tpu.hbm.postmortem.dir": pmdir,
    })

    def mk_mix(s):
        fdf = s.create_dataframe(fact)
        fdf4 = s.create_dataframe(fact, num_partitions=4)
        ddf2 = s.create_dataframe(dim, num_partitions=2)
        w = WindowBuilder().partition_by(col("k")).order_by(col("v"))
        return {
            "agg": lambda: (fdf.group_by(col("k"))
                            .agg(F.sum(col("v")).alias("sv"),
                                 F.count("*").alias("c")).collect()),
            "join": lambda: (fdf4.join(ddf2, on="k", how="inner")
                             .group_by(col("k"))
                             .agg(F.sum(col("w")).alias("sw"))
                             .collect()),
            "window": lambda: (fdf.select(
                col("k"), col("v"),
                F.row_number().over(w).alias("rn")).collect()),
            "sort": lambda: fdf.sort(col("k"), col("v")).collect(),
        }

    mixes = {id(s): mk_mix(s) for s in pool._sessions}
    tl = MemoryTimeline.get()

    # (1) golden replay: one fresh query, three sinks must agree
    with pool.session() as s:
        out = mixes[id(s)]["agg"]()
        assert out.num_rows > 0
        memsan_peak = int(s.last_peak_device_bytes or 0)
    timeline_peak = int(tl.report().get("peak_spill_backed_bytes", 0))
    catalog_live = int(SpillCatalog.get().device_bytes_registered())
    if not (timeline_peak > 0
            and timeline_peak == memsan_peak == catalog_live):
        failures += 1
        print(f"HBM: three sinks disagree after the golden replay: "
              f"timeline {timeline_peak}, memsan {memsan_peak}, "
              f"spill catalog {catalog_live}")

    # (2) pool stress: every event attributed, gauges reconcile
    worklist = [name for name in sorted(mixes[id(pool._sessions[0])])
                for _ in range(4)]

    def one(name):
        with pool.session() as s:
            out = mixes[id(s)][name]()
            assert out.num_rows > 0

    with cf.ThreadPoolExecutor(max_workers=4) as ex:
        list(ex.map(one, worklist))
    pool.drain(timeout=60)
    rep = tl.report()
    booked = sorted(t for t in rep.get("tenants", {})
                    if t.startswith("pool-"))
    if len(booked) < 2:
        failures += 1
        print(f"HBM: pool stress booked occupancy for {booked} only — "
              f"per-tenant attribution is vacuous")
    if rep.get("unattributed_events", 0):
        failures += 1
        print(f"HBM: {rep['unattributed_events']} event(s) went "
              f"unattributed under the pool stress")
    gauge_total = int(m.gauge("tpu_hbm_tenant_bytes",
                              labelnames=("tenant", "class")).total())
    live_total = int(tl.live_bytes())
    if gauge_total != live_total:
        failures += 1
        print(f"HBM: tpu_hbm_tenant_bytes gauges sum to {gauge_total} "
              f"but the timeline holds {live_total} live bytes")

    # (3a) anti-vacuity: a context-free allocation MUST go unattributed
    before = int(tl.report().get("unattributed_events", 0))
    rogue_rb = pa.record_batch(
        {"x": pa.array(np.arange(256, dtype=np.int64))})
    holder = {}

    def rogue():
        holder["sb"] = SpillCatalog.get().register(
            batch_to_device(rogue_rb, xp=np))

    t = threading.Thread(target=rogue)
    t.start()
    t.join()
    after = int(tl.report().get("unattributed_events", 0))
    if after <= before:
        failures += 1
        print("HBM: injected context-free allocation did NOT count as "
              "unattributed — the attribution check is vacuous")
    if holder.get("sb") is not None:
        holder["sb"].close()

    # (3b) anti-vacuity: injected operator failure -> exactly one
    # well-formed post-mortem bundle
    from spark_rapids_tpu.exec import basic as exec_basic
    from spark_rapids_tpu.exec.base import _wrap_execute_partition
    real_execute = exec_basic.FilterExec.execute_partition

    def boom(self, pid, ctx):
        # generator, so the raise happens at first pull — inside the
        # operator span the flight recorder opens for FilterExec
        raise RuntimeError("hbm gate injected operator failure")
        yield

    exec_basic.FilterExec.execute_partition = \
        _wrap_execute_partition(boom)
    raised = False
    try:
        with pool.session() as s:
            try:
                (s.create_dataframe(fact)
                 .filter(col("v") > 0)
                 .group_by(col("k"))
                 .agg(F.sum(col("v")).alias("sv"))
                 .collect())
            except Exception:
                raised = True
    finally:
        exec_basic.FilterExec.execute_partition = real_execute
    if not raised:
        failures += 1
        print("HBM: injected operator failure did not raise")
    bundles = pm.list_bundles(pmdir)
    if len(bundles) != 1:
        failures += 1
        print(f"HBM: expected exactly one post-mortem bundle, found "
              f"{len(bundles)} in {pmdir}")
    else:
        try:
            doc = pm.load_bundle(bundles[0])
            op = (doc.get("failing_operator") or {}).get("operator", "")
            rendered = pm.render_postmortem(doc)
            bad = []
            if doc.get("kind") != "query_failure":
                bad.append(f"kind={doc.get('kind')!r}")
            if not str(doc.get("tenant", "")).startswith("pool-"):
                bad.append(f"tenant={doc.get('tenant')!r}")
            if "FilterExec" not in op:
                bad.append(f"failing_operator={op!r}")
            if "report" not in (doc.get("hbm") or {}):
                bad.append("missing hbm report")
            if "FilterExec" not in rendered:
                bad.append("render omits the failing operator")
            if bad:
                failures += 1
                print("HBM: post-mortem bundle malformed: "
                      + ", ".join(bad))
        except Exception as ex:
            failures += 1
            print(f"HBM: post-mortem bundle unparseable: {ex!r}")

    pool.close()
    shutil.rmtree(pmdir, ignore_errors=True)
    MetricsRegistry.reset_for_tests()
    AdmissionController.reset_for_tests()
    MemoryTimeline.reset_for_tests()
    if failures:
        print(f"hbm gate: {failures} failure(s)")
        return 1
    print(f"hbm gate clean (three sinks agreed at {timeline_peak} "
          f"bytes; {len(worklist)} pooled queries booked "
          f"{len(booked)} tenants with zero unattributed events and "
          f"gauges reconciling at {live_total} live bytes; injected "
          f"rogue allocation tripped the attribution check; injected "
          f"operator failure left exactly one parseable post-mortem "
          f"bundle naming FilterExec)")
    return 0


# anti-vacuity fixtures for the csan gate: each must trip exactly its
# rule.  Self-contained modules the analyzer resolves without the repo.
_CSAN_ABBA_SRC = '''
import threading

class Pair:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def forward(self):
        with self._la:
            self.inner_b()

    def backward(self):
        with self._lb:
            self.inner_a()

    def inner_a(self):
        with self._la:
            pass

    def inner_b(self):
        with self._lb:
            pass
'''

_CSAN_R009_SRC = '''
import threading

class Stats:
    _instance = None
    _ilock = threading.Lock()

    def __init__(self):
        self.tally = 0

    @classmethod
    def get(cls):
        with cls._ilock:
            if cls._instance is None:
                cls._instance = Stats()
            return cls._instance

    def bump(self):
        self.tally += 1

def root_a():
    Stats.get().bump()

def root_b():
    Stats.get().bump()
'''

_CSAN_R010_SRC = '''
import threading

_cv = threading.Condition()
_items = []

def bad_wait():
    with _cv:
        if not _items:
            _cv.wait()
        return _items.pop()
'''


def run_csan_gate() -> int:
    """tpucsan gate, four legs: (1) the repo pass is clean against the
    baseline; (2) the ABBA / shared-write / condvar fixtures each trip
    their rule (anti-vacuity); (3) the static lock-order artifact is
    non-trivial (the serving locks and their metrics edges exist); (4)
    the serve golden mix replays under the runtime lock witness and
    execution must observe zero acquisition edges the static graph
    cannot explain and zero lock-order cycles, with the contention
    metrics registered."""
    import concurrent.futures as cf

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.analysis import concurrency as cc
    from spark_rapids_tpu.analysis.repo_lint import load_baseline

    failures = 0

    # -- leg 1: repo pass clean modulo baseline -----------------------------
    diags = cc.repo_diagnostics()
    baseline = load_baseline(BASELINE)
    fresh = [d for d in diags if d.fingerprint() not in baseline]
    for d in fresh:
        failures += 1
        print(f"CSAN: new finding: {d.render()}")

    # -- leg 2: anti-vacuity fixtures ---------------------------------------
    fixtures = (("TPU-R008", {"spark_rapids_tpu/pairmod.py":
                              _CSAN_ABBA_SRC}, None),
                ("TPU-R009", {"spark_rapids_tpu/statsmod.py":
                              _CSAN_R009_SRC},
                 ["statsmod.root_a", "statsmod.root_b"]),
                ("TPU-R010", {"spark_rapids_tpu/cvmod.py":
                              _CSAN_R010_SRC}, None))
    for code, sources, roots in fixtures:
        got = {d.code for d in
               cc.analyze_sources(sources, roots=roots).diagnostics}
        if code not in got:
            failures += 1
            print(f"CSAN: {code} fixture did not trip (got "
                  f"{sorted(got) or 'nothing'}) — the rule is vacuous")

    # -- leg 3: the artifact the witness consumes is non-trivial ------------
    art = cc.lock_order_artifact()
    if len(art["locks"]) < 20 or len(art["edges"]) < 10:
        failures += 1
        print(f"CSAN: implausibly small lock graph "
              f"({len(art['locks'])} locks, {len(art['edges'])} edges) "
              f"— extraction regressed")
    if len(art["roots"]) < len(cc.THREAD_ROOTS):
        failures += 1
        print(f"CSAN: only {len(art['roots'])} of "
              f"{len(cc.THREAD_ROOTS)} declared thread roots matched "
              f"a function — the root table is stale")
    if art["cycles"]:
        failures += 1
        print(f"CSAN: static lock-order cycle(s): {art['cycles']}")

    # -- leg 4: serve corpus under the runtime lock witness -----------------
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.expr.window import WindowBuilder
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.obs import lockwitness
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager

    MetricsRegistry.reset_for_tests()
    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()
    AdmissionController.reset_for_tests()
    lockwitness.reset_for_tests()

    n = 4000
    rng = np.random.default_rng(7)
    fact = pa.table({
        "k": pa.array(rng.integers(0, 97, n).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(97, dtype=np.int64)),
        "w": pa.array(np.arange(97, dtype=np.int64) * 10),
    })
    try:
        witness = lockwitness.install(art)
        # the singletons whose instance locks the serve path takes must
        # exist before refresh() so they get wrapped
        TpuShuffleManager.get()
        SpillCatalog.get()
        pool = SessionPool(4, {
            "spark.rapids.sql.enabled": "true",
            "spark.rapids.tpu.csan.enabled": "true",
            "spark.rapids.tpu.singleChipFuse": "off",
            "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes":
                str(256 << 20),
            "spark.rapids.tpu.serve.admissionTimeoutMs": "60000",
        })
        witness.refresh()

        def mk_mix(s):
            fdf = s.create_dataframe(fact)
            fdf4 = s.create_dataframe(fact, num_partitions=4)
            ddf2 = s.create_dataframe(dim, num_partitions=2)
            w = WindowBuilder().partition_by(col("k")).order_by(col("v"))
            return {
                "agg": lambda: (fdf.group_by(col("k"))
                                .agg(F.sum(col("v")).alias("sv"),
                                     F.count("*").alias("c"))
                                .collect()),
                "join": lambda: (fdf4.join(ddf2, on="k", how="inner")
                                 .group_by(col("k"))
                                 .agg(F.sum(col("w")).alias("sw"))
                                 .collect()),
                "window": lambda: (fdf.select(
                    col("k"), col("v"),
                    F.row_number().over(w).alias("rn")).collect()),
                "sort": lambda: fdf.sort(col("k"), col("v")).collect(),
            }

        mixes = {id(s): mk_mix(s) for s in pool._sessions}
        worklist = [name for name in sorted(mixes[id(
            pool._sessions[0])]) for _ in range(4)]

        def one(name):
            with pool.session() as s:
                return mixes[id(s)][name]()

        with cf.ThreadPoolExecutor(max_workers=4) as ex:
            list(ex.map(one, worklist))
        pool.drain(timeout=60)
        pool.close()

        rep = witness.report()
        if rep["n_wrapped"] < 8:
            failures += 1
            print(f"CSAN: witness wrapped only {rep['n_wrapped']} "
                  f"lock(s) — registration regressed")
        if not rep["edges"]:
            failures += 1
            print("CSAN: vacuous witness run — no nested acquisition "
                  "was ever observed")
        for a, b in rep["unmodeled"]:
            failures += 1
            print(f"CSAN: UNMODELED runtime edge {a} -> {b}: the "
                  f"static graph cannot explain this nesting")
        for cyc in rep["cycles"]:
            failures += 1
            print(f"CSAN: runtime lock-order cycle observed: {cyc}")
        fams = {f.name for f in MetricsRegistry.get().families()}
        for fam in ("tpu_lock_contention_total", "tpu_lock_wait_seconds"):
            if fam not in fams:
                failures += 1
                print(f"CSAN: contention metric family {fam} missing")
    finally:
        lockwitness.reset_for_tests()
        MetricsRegistry.reset_for_tests()
        AdmissionController.reset_for_tests()
        TpuShuffleManager.reset()

    if failures:
        print(f"csan gate: {failures} failure(s)")
        return 1
    print(f"csan gate clean (repo pass clean modulo baseline; R008/"
          f"R009/R010 fixtures all trip; static graph: "
          f"{len(art['locks'])} locks, {len(art['edges'])} edges, "
          f"{len(art['roots'])} thread roots, no cycles; witness "
          f"replay: {rep['n_wrapped']} locks wrapped, "
          f"{len(rep['edges'])} observed edges all modeled, zero "
          f"runtime cycles)")
    return 0


# the feedback gate's corpus: the regress corpus queries, run traced
# against an estimator ledger dir.  "cold" records the static model's
# errors; "warm" loads the cold arm's ledger and blends its recorded
# actuals back into the estimates (spark.rapids.tpu.feedback.enabled).
# Fresh subprocess per arm: the ledger singleton, jit caches and plan
# caches all start identical, so cold vs warm isolates the feedback.
_FEEDBACK_CORPUS = r"""
import json
import sys
import numpy as np
import pyarrow as pa
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.obs.estimator import EstimatorLedger

hist_dir, eventlog_dir, arm = sys.argv[1], sys.argv[2], sys.argv[3]
rng = np.random.default_rng(1234)
fact = pa.table({
    "k": pa.array((rng.integers(0, 97, 4000)).astype(np.int64)),
    "v": pa.array(rng.integers(-1000, 1000, 4000).astype(np.int64)),
})
dim = pa.table({
    "k": pa.array(np.arange(97, dtype=np.int64)),
    "w": pa.array(np.arange(97, dtype=np.int64) * 3),
})
s = (TpuSession.builder()
     .config("spark.rapids.sql.enabled", True)
     .config("spark.rapids.tpu.singleChipFuse", "off")
     .config("spark.rapids.tpu.sort.compileLean", "off")
     .config("spark.rapids.tpu.trace.enabled", True)
     .config("spark.rapids.tpu.regress.historyDir", hist_dir)
     .config("spark.rapids.tpu.feedback.enabled", arm == "warm")
     .config("spark.rapids.tpu.eventLog.dir", eventlog_dir)
     .get_or_create())
fdf = s.create_dataframe(fact, num_partitions=2)
ddf = s.create_dataframe(dim)
out1 = (fdf.filter(col("v") > -500).group_by(col("k"))
        .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("c"))
        .collect())
assert out1.num_rows == 97, out1.num_rows
out2 = (fdf.join(ddf, on="k", how="inner").group_by(col("k"))
        .agg(F.sum(col("w")).alias("sw")).collect())
assert out2.num_rows == 97, out2.num_rows
out3 = fdf.sort(col("k"), col("v")).collect()
assert out3.num_rows == 4000, out3.num_rows
print("EST_JSON=" + json.dumps(EstimatorLedger.get().snapshot()))
"""


# anti-vacuity corpus: the static row model is sabotaged by 100x at
# shuffle boundaries, so the measured map output disagrees with the
# prediction by exactly the factor the re-planner keys on.  The gate
# demands a recorded strategy_switch whose three sinks agree AND a
# bit-exact join result against the CPU-engine ground truth.
_MISESTIMATE_CORPUS = r"""
import json
import os
import sys
from spark_rapids_tpu.plan import cost

_orig = cost._static_rows


def _skewed(node, child_rows):
    r = _orig(node, child_rows)
    if type(node).__name__ == "ShuffleExchangeExec":
        return r / 100.0  # injected 100x row misestimate
    return r


cost._static_rows = _skewed
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.obs import metrics as m
from spark_rapids_tpu.obs.estimator import EstimatorLedger

hist_dir = sys.argv[1]
s = TpuSession({
    "spark.rapids.sql.enabled": True,
    "spark.rapids.tpu.regress.historyDir": hist_dir,
    "spark.rapids.tpu.trace.enabled": True,
    "spark.rapids.tpu.feedback.enabled": True,
    "spark.rapids.tpu.singleChipFuse": "off",
    "spark.rapids.sql.autoBroadcastJoinThreshold": 0,
    "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes": 1 << 30,
})
n = 2000
left = s.create_dataframe(
    {"k": [i % 50 for i in range(n)], "v": list(range(n))},
    num_partitions=4)
right = s.create_dataframe(
    {"k": list(range(50)), "w": [i * 10 for i in range(50)]},
    num_partitions=4)
out = left.join(right, on="k").collect()

spans = [sp for sp in s.last_query_trace().spans
         if sp.name == "replan"]
fam = m.registry().counter("tpu_replan_total",
                           labelnames=("decision", "cause"))
metric_n = int(sum(ch.value for _, ch in fam.series()))
ledger_n = 0
with open(os.path.join(hist_dir, "estimator_ledger.jsonl")) as f:
    for line in f:
        if line.strip() and \
                json.loads(line).get("event") == "replan":
            ledger_n += 1
switches = [sp for sp in spans
            if sp.attrs.get("decision") == "strategy_switch"
            and sp.attrs.get("cause") == "row_misestimate"]

# exact results: the re-plan must never change the answer
cost._static_rows = _orig
s2 = TpuSession({"spark.rapids.sql.enabled": False})
truth = left.join(right, on="k").collect()


def canon(t):
    t = t.select(sorted(t.column_names))
    return t.combine_chunks().sort_by(
        [(c, "ascending") for c in t.column_names])


print("REPLAN_JSON=" + json.dumps({
    "rows": out.num_rows,
    "spans": len(spans), "metric": metric_n, "ledger": ledger_n,
    "strategy_switches": len(switches),
    "snapshot_replans": EstimatorLedger.get().snapshot()["replans"],
    "exact": bool(canon(out).equals(canon(truth)))}))
"""


def _feedback_subprocess(script, args, marker):
    """One fresh-process feedback-gate leg; returns the marker JSON or
    None (with the transcript printed) on failure."""
    import subprocess
    r = subprocess.run(
        [sys.executable, "-c", script, *args],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS=os.environ.get(
            "JAX_PLATFORMS", "cpu")))
    payload = None
    for line in r.stdout.splitlines():
        if line.startswith(marker + "="):
            payload = json.loads(line[len(marker) + 1:])
    if r.returncode != 0 or payload is None:
        print(f"FEEDBACK: subprocess failed rc={r.returncode}:\n"
              f"{r.stdout}\n{r.stderr}")
        return None
    return payload


def run_feedback_gate() -> int:
    """Estimator-observatory gate: cold-then-warm golden replay (warm
    must be strictly more accurate; two warm replays over identical
    ledger snapshots must show zero deterministic drift) plus the
    injected-misestimate re-plan anti-vacuity leg."""
    import shutil
    import tempfile

    from spark_rapids_tpu.obs.history import (deterministic_drift,
                                              diff_runs,
                                              distill_event_log)

    failures = 0
    root = tempfile.mkdtemp(prefix="feedback_gate_")
    try:
        cold_hist = os.path.join(root, "hist_cold")
        os.makedirs(cold_hist)
        est, fps = {}, {}
        # cold first (records the ledger), then two warm replays over
        # IDENTICAL copies of the cold ledger — each warm arm appends
        # its own observations, so sharing one dir would hand warm2 a
        # different (grown) ledger and make the drift diff meaningless
        arms = [("cold", cold_hist), ("warm", None), ("warm2", None)]
        for i, (arm, hist) in enumerate(arms):
            if hist is None:
                hist = os.path.join(root, f"hist_{arm}")
                shutil.copytree(cold_hist, hist)
                arms[i] = (arm, hist)
            evt = os.path.join(root, f"evt_{arm}")
            os.makedirs(evt)
            payload = _feedback_subprocess(
                _FEEDBACK_CORPUS,
                [hist, evt, "cold" if arm == "cold" else "warm"],
                "EST_JSON")
            if payload is None:
                return 1
            est[arm] = payload
            logs = [f for f in os.listdir(evt)
                    if f.startswith("events_")]
            fps[arm] = {"queries": distill_event_log(
                os.path.join(evt, logs[0]))} if logs else None

        if est["cold"]["observations"] == 0:
            failures += 1
            print("FEEDBACK: vacuous gate — the cold replay recorded "
                  "no observations")
        if not est["warm"]["feedback_enabled"]:
            failures += 1
            print("FEEDBACK: warm arm ran without feedback enabled")
        cold_err = est["cold"]["mean_rows_err"]
        warm_err = est["warm"]["mean_rows_err"]
        if not warm_err < cold_err:
            failures += 1
            print(f"FEEDBACK: warm ledger did not sharpen the model "
                  f"(warm mean rel row error {warm_err} !< cold "
                  f"{cold_err})")
        if fps["warm"] is None or fps["warm2"] is None:
            failures += 1
            print("FEEDBACK: corpus replay left no event log to diff")
        else:
            for dr in deterministic_drift(
                    diff_runs(fps["warm"], fps["warm2"])):
                failures += 1
                print(f"FEEDBACK DRIFT warm replay 1 -> 2: "
                      f"{dr.render()}")

        # anti-vacuity: the injected 100x misestimate MUST re-plan,
        # the three sinks must agree, and the answer must not change
        mhist = os.path.join(root, "mis_hist")
        os.makedirs(mhist)
        rep = _feedback_subprocess(
            _MISESTIMATE_CORPUS, [mhist], "REPLAN_JSON")
        if rep is None:
            return 1
        if rep["strategy_switches"] < 1:
            failures += 1
            print(f"FEEDBACK: injected 100x misestimate did not "
                  f"trigger a strategy_switch re-plan ({rep})")
        if rep["spans"] < 1 or not (
                rep["spans"] == rep["metric"] == rep["ledger"]
                == rep["snapshot_replans"]):
            failures += 1
            print(f"FEEDBACK: re-plan sinks disagree — spans "
                  f"{rep['spans']}, tpu_replan_total {rep['metric']}, "
                  f"ledger events {rep['ledger']}, snapshot "
                  f"{rep['snapshot_replans']}")
        if not rep["exact"]:
            failures += 1
            print("FEEDBACK: re-planned join diverged from the "
                  "CPU-engine ground truth")
    finally:
        shutil.rmtree(root, ignore_errors=True)
    if failures:
        print(f"feedback gate: {failures} failure(s)")
        return 1
    print(f"feedback gate clean (warm replay mean row error "
          f"{warm_err} < cold {cold_err} over "
          f"{est['cold']['observations']} observations, zero "
          f"deterministic drift across warm replays; injected 100x "
          f"misestimate re-planned {rep['spans']} decision(s) with "
          f"span/metric/ledger agreeing and exact results)")
    return 0


def run_fleet_gate() -> int:
    """Fleet-observatory gate: two real peer processes, one merged
    trace, one aggregator — then a peer dies and everything that must
    notice does.  See the module docstring for the full contract."""
    import subprocess

    import pyarrow as pa

    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.columnar.device import batch_to_arrow
    from spark_rapids_tpu.obs import tracer as tr
    from spark_rapids_tpu.obs.fleet import (ClockSync, FleetAggregator,
                                            RemoteSpanStore,
                                            install_aggregator)
    from spark_rapids_tpu.obs.health import HealthMonitor
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.heartbeat import HeartbeatManager
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
    from spark_rapids_tpu.shuffle.registry import (BlockEndpoint,
                                                   BlockLocationRegistry)
    from spark_rapids_tpu.shuffle.serve_map import (
        DIM_SID, FACT_SID, build_side_tables, partition_record_batch)

    failures = 0
    rows, parts, seed = 6000, 3, 23
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SPARK_RAPIDS_TPU_DISABLE_COMPILE_CACHE="1")

    def spawn(name):
        return subprocess.Popen(
            [sys.executable, "-m",
             "spark_rapids_tpu.shuffle.serve_map",
             "--rows", str(rows), "--parts", str(parts),
             "--codec", "lz4", "--seed", str(seed),
             "--executor-id", name],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env, cwd=REPO)

    def reset_all():
        tr.uninstall()
        install_aggregator(None)
        locality.reset_pool()
        BlockLocationRegistry.reset()
        TpuShuffleManager.reset()
        RemoteSpanStore.reset()
        ClockSync.reset()
        m.MetricsRegistry.reset_for_tests()

    reset_all()
    # one peer owns the fact side, the other the dim side: every fetch
    # of the golden join exercises BOTH peers' serve paths
    children = {"peer-a": spawn("peer-a"), "peer-b": spawn("peer-b")}
    stats_a = None
    try:
        ports = {}
        for name, child in children.items():
            fields = child.stdout.readline().split()
            if len(fields) < 4 or fields[0] != "PORT" \
                    or fields[2] != "OBS":
                print(f"FLEET: {name} announced no PORT/OBS line")
                return 1
            ports[name] = (int(fields[1]), int(fields[3]))
        reg = BlockLocationRegistry.get()
        reg.set_local("driver", "127.0.0.1", 0)
        hb = HeartbeatManager(timeout_s=30.0)
        for name, (port, obs_port) in ports.items():
            reg.register(FACT_SID if name == "peer-a" else DIM_SID,
                         [BlockEndpoint(name, "127.0.0.1", port)])
            hb.register_executor(name, "127.0.0.1", port,
                                 obs_port=obs_port)
        reg.attach_heartbeat(hb)
        agg = install_aggregator(FleetAggregator(hb, max_peers=4,
                                                 timeout_s=5.0))

        # -- clean half: golden cross-process join under a live tracer
        trace = tr.install(tr.QueryTrace())
        out = []
        for pid in range(parts):
            sides = []
            for sid in (FACT_SID, DIM_SID):
                rbs = [batch_to_arrow(b) for b in
                       locality.read_reduce_blocks(sid, pid)]
                sides.append(pa.Table.from_batches(rbs) if rbs else None)
            if sides[0] is not None and sides[1] is not None:
                out.append(sides[0].join(sides[1], "k"))
        got = pa.concat_tables(out).sort_by(
            [("k", "ascending"), ("v", "ascending")])
        trace.finalize()
        tr.uninstall()
        fact, dim = build_side_tables(rows, seed)
        fparts = partition_record_batch(fact, "k", parts)
        dparts = partition_record_batch(dim, "k", parts)
        ref = pa.concat_tables(
            [pa.table(fparts[p]).join(pa.table(dparts[p]), "k")
             for p in range(parts) if p in fparts and p in dparts]
        ).sort_by([("k", "ascending"), ("v", "ascending")])
        if not got.equals(ref):
            failures += 1
            print("FLEET: cross-process join diverged from the "
                  "in-process reference")

        spans = trace.span_dicts()
        by_parent = {}
        for s in spans:
            by_parent.setdefault(s.get("parentId"), []).append(s)
        fetch = [s for s in spans if s["name"] == "shuffle.fetch"]
        bad_fetch = 0
        for f in fetch:
            kids = by_parent.get(f["spanId"], [])
            roots = [k for k in kids
                     if k.get("proc") == f["attrs"].get("peer")]
            names = {k["name"] for k in roots}
            f0, f1 = f["startNs"], f["startNs"] + f["durNs"]
            ok = {"shuffle.serve.metadata",
                  "shuffle.serve.transfer"} <= names
            for r in roots:
                ok = ok and f0 <= r["startNs"] \
                    and r["startNs"] + r["durNs"] <= f1
            if not ok:
                bad_fetch += 1
        if bad_fetch:
            failures += 1
            print(f"FLEET: {bad_fetch}/{len(fetch)} fetch span(s) "
                  f"missing nested producer serve spans (or spans "
                  f"outside the parent interval)")
        procs = {s.get("proc") for s in spans if s.get("proc")}
        # anti-vacuity, clean direction: the merge must have HAPPENED,
        # for both peers, with zero losses
        if trace.remote_spans_merged == 0 or procs != set(children):
            failures += 1
            print(f"FLEET: vacuous merge — {trace.remote_spans_merged} "
                  f"remote span(s) from peers {sorted(procs)}")
        lost_clean = m.counter(
            "tpu_trace_remote_spans_lost_total").value()
        if trace.remote_spans_lost or lost_clean:
            failures += 1
            print(f"FLEET: clean run lost {trace.remote_spans_lost} "
                  f"remote span(s) (counter {lost_clean})")

        peers = agg.scrape()
        scraped = [p for p, e in peers.items() if e.get("scraped")]
        if sorted(scraped) != sorted(children):
            failures += 1
            print(f"FLEET: aggregator scraped {sorted(scraped)}, "
                  f"wanted both of {sorted(children)}")
        rollup = m.gauge("tpu_fleet_rollup",
                         labelnames=("peer", "name"))
        for name in children:
            served = rollup.value(
                peer=name, name="tpu_shuffle_server_requests_total")
            if not served:
                failures += 1
                print(f"FLEET: no rollup series shows {name} serving "
                      f"requests")
        verdict_clean = agg.verdict(scrape_first=False)["status"]
        if verdict_clean != "ok":
            failures += 1
            print(f"FLEET: clean fleet verdict is {verdict_clean}")

        # -- degraded half: kill peer-b mid-fleet, fetch into the hole
        children["peer-b"].kill()
        children["peer-b"].wait()
        trace2 = tr.install(tr.QueryTrace())
        try:
            list(locality.read_reduce_blocks(DIM_SID, 0))
            failures += 1
            print("FLEET: fetch against the killed peer succeeded")
        except Exception:
            pass
        trace2.finalize()
        tr.uninstall()
        lost_spans = [s for s in trace2.span_dicts()
                      if s["name"] == "shuffle.fetch"
                      and s["attrs"].get("spans_lost")]
        lost_total = m.counter(
            "tpu_trace_remote_spans_lost_total").value()
        # anti-vacuity, degraded direction: the orphan path must fire
        if not lost_spans or lost_total <= lost_clean:
            failures += 1
            print(f"FLEET: peer death surfaced no orphan spans "
                  f"({len(lost_spans)} annotated, counter "
                  f"{lost_total})")
        if any(s["status"] != "error" for s in lost_spans):
            failures += 1
            print("FLEET: a spans_lost fetch span is not closed typed")
        # the children never run a heartbeat loop; a dead process is
        # silence, which expiry models as a stale last-heartbeat stamp
        hb._peers["peer-b"].last_heartbeat -= hb.timeout_s + 1
        verdict = agg.verdict()
        if verdict["status"] != "degraded" or not any(
                "peer-b" in r for r in verdict["reasons"]):
            failures += 1
            print(f"FLEET: dead peer left verdict {verdict['status']} "
                  f"(reasons {verdict['reasons']})")
        snap = HealthMonitor().snapshot()
        if snap["status"] != "degraded" or \
                snap["components"].get("fleet", {}).get("status") \
                != "degraded":
            failures += 1
            print(f"FLEET: /healthz does not carry the degraded fleet "
                  f"verdict (status {snap['status']})")

        # peer-a shuts down clean: its span buffer must be fully
        # drained (every serve span came home in the merged trace)
        children["peer-a"].stdin.write("done\n")
        children["peer-a"].stdin.flush()
        stats_line = children["peer-a"].stdout.readline()
        stats_a = json.loads(stats_line[len("STATS "):]) \
            if stats_line.startswith("STATS ") else None
        if stats_a is None or stats_a.get("unpulled_spans") != 0:
            failures += 1
            print(f"FLEET: peer-a left serve spans unpulled "
                  f"({stats_a and stats_a.get('unpulled_spans')})")
        if stats_a is not None and stats_a.get("leaked_blocks"):
            failures += 1
            print(f"FLEET: peer-a leaked "
                  f"{stats_a['leaked_blocks']} block(s)")
    finally:
        for child in children.values():
            try:
                child.stdin.close()
                child.stdout.close()
            except OSError:
                pass
            if child.poll() is None:
                child.kill()
                child.wait()
        reset_all()
    if failures:
        print(f"fleet gate: {failures} failure(s)")
        return 1
    print(f"fleet gate clean (cross-process join bit-exact over "
          f"{parts} partitions x 2 peers; merged trace carries "
          f"{len(fetch)} fetch spans with producer serve spans nested "
          f"and zero lost; rollup + ok verdict for both peers; killed "
          f"peer degraded the fleet verdict and /healthz and counted "
          f"{int(lost_total)} orphaned span record(s); peer-a drained "
          f"clean)")
    return 0


def run_faults_gate() -> int:
    """tpufsan fault-injection campaign: the raise-graph artifact
    enumerates every statically-reachable (seam, typed-error) pair
    (>= 50) and the gate injects each one, asserting (a) the exact
    typed error propagates to the seam's caller, (b) the admission /
    shuffle / spill books balance afterward with all spans closed, and
    (c) exactly one parseable post-mortem bundle records the failure.
    Background thread roots (heartbeat loop, metrics endpoint) get
    their own legs: an injected fault must increment
    tpu_background_errors_total{root}, degrade health and black-box a
    background_failure bundle while the thread SURVIVES.  Anti-vacuity:
    the books check must flag planted orphans, and an untyped injected
    error must fail the propagation verdict."""
    import shutil
    import tempfile
    import time as _time
    import urllib.error
    import urllib.request

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.analysis import raiseflow
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.pool import (PoolClosedError, PoolTimeout,
                                           SessionPool)
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec import basic as exec_basic
    from spark_rapids_tpu.exec.base import _wrap_execute_partition
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.obs import bgerrors, health
    from spark_rapids_tpu.obs import metrics as m
    from spark_rapids_tpu.obs import postmortem as pm
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.shuffle import transport as tr
    from spark_rapids_tpu.shuffle.errors import TpuShuffleError
    from spark_rapids_tpu.shuffle.heartbeat import (HeartbeatEndpoint,
                                                    HeartbeatManager)
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager

    failures = 0
    injected = 0

    # -- leg 1: the static campaign plan itself -----------------------------
    for d in raiseflow.repo_diagnostics():
        failures += 1
        print(f"FAULTS: raiseflow finding (fix it, don't baseline it): "
              f"{d.render()}")
    art = raiseflow.raise_graph_artifact()
    plan = art["injections"]
    if len(plan) < 50:
        failures += 1
        print(f"FAULTS: injection plan shrank to {len(plan)} pairs "
              f"(< 50) — seam reachability regressed")
    leaks = sum(len(s["untyped"]) for s in art["seams"].values())
    if leaks:
        failures += 1
        print(f"FAULTS: {leaks} untyped operational leak(s) at public "
              f"seams in the artifact")
    by_seam = {}
    for inj in plan:
        by_seam.setdefault(inj["seam"], []).append(inj["error"])

    # -- fresh world --------------------------------------------------------
    MetricsRegistry.reset_for_tests()
    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()
    AdmissionController.reset_for_tests()
    bgerrors.reset()
    pmdir = tempfile.mkdtemp(prefix="tpu_faults_pm_")

    def books(session=None):
        probs = []
        blocks = TpuShuffleManager.get().catalog.num_blocks()
        if blocks:
            probs.append(f"{blocks} orphaned shuffle block(s)")
        sleaks = SpillCatalog.get().leak_report()
        if sleaks:
            probs.append(f"{len(sleaks)} spill leak(s)")
        ac = AdmissionController.get()
        if ac is not None:
            if ac.bytes_in_flight():
                probs.append(f"{ac.bytes_in_flight()} admission "
                             f"byte(s) still in flight")
            if ac.queue_depth():
                probs.append(f"admission queue depth "
                             f"{ac.queue_depth()}")
        if session is not None:
            trace = session.last_query_trace()
            if trace is not None and trace.open_span_count():
                probs.append(f"{trace.open_span_count()} unclosed "
                             f"span(s)")
        return probs

    def expect_bundle(before, name):
        new = [b for b in pm.list_bundles(pmdir) if b not in before]
        if len(new) != 1:
            return [f"expected exactly 1 new bundle, found {len(new)}"]
        try:
            doc = pm.load_bundle(new[0])
        except Exception as ex:
            return [f"bundle unparseable: {ex!r}"]
        probs = []
        if (doc.get("error") or {}).get("type") != name:
            probs.append(f"bundle names "
                         f"{(doc.get('error') or {}).get('type')!r}, "
                         f"injected {name}")
        if not doc.get("kind"):
            probs.append("bundle has no kind")
        return probs

    # -- leg 2: session seams (main-query, serving-client) ------------------
    tb = pa.table({
        "k": pa.array((np.arange(400) % 7).astype(np.int64)),
        "v": pa.array(np.arange(400, dtype=np.int64))})
    conf = {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.tpu.memsan.enabled": "true",
        "spark.rapids.tpu.trace.enabled": "true",
        "spark.rapids.tpu.hbm.postmortem.dir": pmdir,
        "spark.rapids.tpu.hbm.postmortem.maxBundles": "500",
    }
    sess = TpuSession(conf)
    pool = SessionPool(2, conf)
    real_execute = exec_basic.FilterExec.execute_partition

    def inject_session(seam, name, runner, raise_obj=None,
                       expect_name=None):
        """Arm FilterExec with the constructed error, run one golden
        query through the seam, verify type + books + bundle."""
        err = raise_obj if raise_obj is not None \
            else raiseflow.construct_error(name)
        expect_name = expect_name or name

        def boom(self, pid, ctx):
            raise err
            yield  # generator: the raise happens inside the op span

        exec_basic.FilterExec.execute_partition = \
            _wrap_execute_partition(boom)
        before = set(pm.list_bundles(pmdir))
        caught = None
        used_session = []
        try:
            try:
                runner(used_session)
            except BaseException as ex:
                caught = ex
        finally:
            exec_basic.FilterExec.execute_partition = real_execute
        probs = []
        if caught is None:
            probs.append("injected fault never surfaced")
        elif type(caught).__name__ != expect_name:
            probs.append(f"typed propagation broken: injected "
                         f"{expect_name}, caller saw "
                         f"{type(caught).__name__}: {caught}")
        probs += books(used_session[0] if used_session else None)
        probs += expect_bundle(before, expect_name)
        return probs

    def run_main(used):
        used.append(sess)
        sess.create_dataframe(tb, num_partitions=2) \
            .filter(col("v") > 5).collect()

    def run_pool(used):
        def q(s):
            used.append(s)
            return (s.create_dataframe(tb, num_partitions=2)
                    .filter(col("v") > 5).collect())
        pool.run(q, timeout=60)

    for seam, runner in (("main-query", run_main),
                         ("serving-client", run_pool)):
        for name in by_seam.get(seam, []):
            injected += 1
            for p in inject_session(seam, name, runner):
                failures += 1
                print(f"FAULTS [{seam}/{name}]: {p}")

    # -- leg 3: pool seams driven for real ----------------------------------
    def harness_bundle(seam, err):
        """Non-session seams have no session to black-box for them; the
        serving harness records the typed failure itself."""
        pm.dump_postmortem(pmdir, err, tenant=f"faults:{seam}",
                           max_bundles=500)

    def drive_pool_seam(seam, name, driver):
        before = set(pm.list_bundles(pmdir))
        caught = None
        try:
            driver()
        except BaseException as ex:
            caught = ex
        probs = []
        if caught is None:
            probs.append("real-path drive raised nothing")
        elif type(caught).__name__ != name:
            probs.append(f"expected {name}, got "
                         f"{type(caught).__name__}: {caught}")
        else:
            harness_bundle(seam, caught)
            probs += expect_bundle(before, name)
        probs += books()
        return probs

    def drive_borrow_closed():
        p2 = SessionPool(1, {"spark.rapids.sql.enabled": "true"})
        p2.close()
        with p2.session():
            pass

    def drive_borrow_timeout():
        p2 = SessionPool(1, {"spark.rapids.sql.enabled": "true"})
        try:
            with p2.session():
                with p2.session(timeout=0.05):
                    pass
        finally:
            p2.close()

    def drive_drain_timeout():
        p2 = SessionPool(1, {"spark.rapids.sql.enabled": "true"})
        try:
            ctx = p2.session()
            ctx.__enter__()  # held busy past the drain deadline
            try:
                p2.drain(timeout=0.05)
            finally:
                ctx.__exit__(None, None, None)
        finally:
            p2.close()

    for seam, name, driver in (
            ("pool-borrow", "PoolClosedError", drive_borrow_closed),
            ("pool-borrow", "PoolTimeout", drive_borrow_timeout),
            ("pool-drain", "PoolTimeout", drive_drain_timeout)):
        injected += 1
        for p in drive_pool_seam(seam, name, driver):
            failures += 1
            print(f"FAULTS [{seam}/{name}]: {p}")

    # -- leg 4: shuffle-fetcher seam ----------------------------------------
    class _Tx:
        def __init__(self, result=None, exc=None):
            self.result, self.exc = result, exc

        def wait(self, timeout=None):
            if self.exc is not None:
                raise self.exc
            return self.result

    class _StubClient:
        def __init__(self, err):
            self.err = err

        def fetch_metadata(self, sid, rid, ctx=None):
            return _Tx(result=[((sid, 0, rid, 0), None)])

        def fetch_block(self, sid, mid, rid, idx, xp=None, ctx=None):
            return _Tx(exc=self.err)

    for name in by_seam.get("shuffle-fetcher", []):
        injected += 1
        err = raiseflow.construct_error(name)
        before = set(pm.list_bundles(pmdir))
        fetcher = tr.AsyncBlockFetcher(_StubClient(err), 7, 0,
                                       timeout=5.0)
        caught = None
        try:
            list(fetcher.blocks())
        except BaseException as ex:
            caught = ex
        probs = []
        if caught is None:
            probs.append("fetcher swallowed the injected fault")
        elif type(caught).__name__ != name:
            probs.append(f"fetch classification mangled the type: "
                         f"injected {name}, got "
                         f"{type(caught).__name__}: {caught}")
        else:
            harness_bundle("shuffle-fetcher", caught)
            probs += expect_bundle(before, name)
        probs += books()
        for p in probs:
            failures += 1
            print(f"FAULTS [shuffle-fetcher/{name}]: {p}")
    errs_counted = sum(
        ch.value for _, ch in
        m.counter("tpu_shuffle_fetch_errors_total",
                  labelnames=("kind",)).series())
    # cancellation is control flow, not a fetch failure: the fetcher
    # passes TpuQueryCancelled/TpuQueryDeadlineExceeded through without
    # booking a fetch-error kind (they count in tpu_cancellations_total)
    fetch_faults = [n for n in by_seam.get("shuffle-fetcher", [])
                    if n not in ("TpuQueryCancelled",
                                 "TpuQueryDeadlineExceeded")]
    if errs_counted < len(fetch_faults):
        failures += 1
        print(f"FAULTS: fetch-error counter saw {errs_counted} of "
              f"{len(fetch_faults)} injections")

    # -- leg 5: block-server seam (typed relay over the wire) ---------------
    for name in by_seam.get("block-server", []):
        injected += 1
        err = raiseflow.construct_error(name)
        mgr = TpuShuffleManager.get()
        server = tr.ShuffleServer(mgr).start()
        before = set(pm.list_bundles(pmdir))
        real_get = mgr.catalog.get
        mgr.catalog.get = lambda *a, **k: (_ for _ in ()).throw(err)
        caught = None
        try:
            client = tr.ShuffleClient("127.0.0.1", server.port,
                                      timeout=5.0)
            try:
                client.fetch_block(1, 0, 0, 0).wait(5.0)
            except BaseException as ex:
                caught = ex
            probs = []
            if caught is None:
                probs.append("server swallowed the injected fault")
            elif not isinstance(caught, TpuShuffleError):
                probs.append(f"wire relay lost the typed taxonomy: "
                             f"got {type(caught).__name__}: {caught}")
            elif name not in str(caught):
                probs.append(f"relayed error does not name the "
                             f"server-side {name}: {caught}")
            else:
                harness_bundle("block-server", caught)
                probs += expect_bundle(before, type(caught).__name__)
            # liveness: the server must still answer after the fault
            mgr.catalog.get = real_get
            metas = client.fetch_metadata(99, 0).wait(5.0)
            if metas is None:
                probs.append("server dead after relaying the fault")
        finally:
            mgr.catalog.get = real_get
            server.stop()
        probs += books()
        for p in probs:
            failures += 1
            print(f"FAULTS [block-server/{name}]: {p}")

    # -- leg 6: background thread roots -------------------------------------
    bgerrors.reset()
    bgerrors.set_postmortem_dir(pmdir)

    def bg_counter(root):
        fam = m.counter("tpu_background_errors_total",
                        labelnames=("root",))
        return sum(ch.value for lbl, ch in fam.series()
                   if lbl.get("root") == root)

    # heartbeat loop: one poisoned beat, then the loop must keep beating
    before = set(pm.list_bundles(pmdir))
    hb_mgr = HeartbeatManager(timeout_s=30.0)
    calls = {"n": 0}
    real_beat = hb_mgr.executor_heartbeat

    def flaky_beat(eid):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("faults gate injected heartbeat failure")
        return real_beat(eid)

    hb_mgr.executor_heartbeat = flaky_beat
    ep = HeartbeatEndpoint(hb_mgr, "e1", "127.0.0.1", 1,
                           interval_s=0.02).start()
    deadline = _time.monotonic() + 5.0
    while calls["n"] < 3 and _time.monotonic() < deadline:
        _time.sleep(0.02)
    ep.stop()
    probs = []
    if calls["n"] < 3:
        probs.append(f"heartbeat loop died after the injected fault "
                     f"(beats: {calls['n']})")
    if bg_counter("heartbeat-loop") < 1:
        probs.append("tpu_background_errors_total{root=heartbeat-loop} "
                     "never incremented")
    rec = bgerrors.last_error("heartbeat-loop")
    if not rec or rec["type"] != "RuntimeError":
        probs.append(f"last-error record wrong: {rec}")
    new = [b for b in pm.list_bundles(pmdir) if b not in before]
    kinds = []
    for b in new:
        try:
            kinds.append(pm.load_bundle(b).get("kind"))
        except Exception:
            kinds.append("<unparseable>")
    if kinds != ["background_failure"]:
        probs.append(f"expected one background_failure bundle, "
                     f"got {kinds}")
    injected += 1
    for p in probs:
        failures += 1
        print(f"FAULTS [heartbeat-loop]: {p}")

    # metrics endpoint: a failing scrape must 500 + count + degrade,
    # and the endpoint must keep serving afterward
    before = set(pm.list_bundles(pmdir))
    srv = health.MetricsServer(0)
    real_render = health.render_prometheus

    def bad_render(*a, **k):
        raise RuntimeError("faults gate injected scrape failure")

    probs = []
    try:
        health.render_prometheus = bad_render
        code = None
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5)
        except urllib.error.HTTPError as ex:
            code = ex.code
        if code != 500:
            probs.append(f"poisoned scrape answered {code}, not 500")
        health.render_prometheus = real_render
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=5) as resp:
            if resp.status != 200:
                probs.append(f"endpoint dead after the fault: "
                             f"{resp.status}")
        if bg_counter("metrics-http") < 1:
            probs.append("tpu_background_errors_total"
                         "{root=metrics-http} never incremented")
        snap = srv.monitor.snapshot()
        comp = (snap.get("components") or {}).get("background")
        status = comp.get("status") if isinstance(comp, dict) else comp
        if status not in ("degraded", "DEGRADED"):
            probs.append(f"health did not degrade on a background "
                         f"fault: {status!r}")
        new = [b for b in pm.list_bundles(pmdir) if b not in before]
        if len(new) != 1:
            probs.append(f"expected one metrics-http bundle, "
                         f"found {len(new)}")
    finally:
        health.render_prometheus = real_render
        srv.close()
    injected += 1
    for p in probs:
        failures += 1
        print(f"FAULTS [metrics-http]: {p}")

    # -- leg 7: anti-vacuity ------------------------------------------------
    # (a) the books check must flag planted orphans
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockId
    rb = pa.record_batch({"x": pa.array(np.arange(64, dtype=np.int64))})
    planted_sb = SpillCatalog.get().register(batch_to_device(rb, xp=np))
    TpuShuffleManager.get().catalog.add(
        ShuffleBlockId(9999, 0, 0), batch_to_device(rb, xp=np))
    planted = books()
    TpuShuffleManager.get().catalog.remove_shuffle(9999)
    planted_sb.close()
    if len(planted) < 2:
        failures += 1
        print(f"FAULTS: books check is vacuous — planted an orphan "
              f"block AND a spill leak, it reported {planted}")
    if books():
        failures += 1
        print(f"FAULTS: books dirty after anti-vacuity cleanup: "
              f"{books()}")
    # (b) an untyped injected error must fail the propagation verdict
    untyped = inject_session(
        "main-query", "TpuShuffleTimeoutError", run_main,
        raise_obj=RuntimeError("untyped leak the verdict must catch"),
        expect_name="TpuShuffleTimeoutError")
    if not any("typed propagation broken" in p for p in untyped):
        failures += 1
        print("FAULTS: propagation verdict is vacuous — an untyped "
              "RuntimeError injection produced no typed-propagation "
              "complaint")

    pool.close()
    shutil.rmtree(pmdir, ignore_errors=True)
    bgerrors.reset()
    MetricsRegistry.reset_for_tests()
    AdmissionController.reset_for_tests()
    if failures:
        print(f"faults gate: {failures} failure(s) over {injected} "
              f"injection(s)")
        return 1
    print(f"faults gate clean ({injected} fault injections across "
          f"{len(by_seam)} seams + 2 background roots: 100% typed "
          f"propagation, books balanced, one parseable post-mortem "
          f"bundle per failure)")
    return 0


# --- tpudsan: determinism & replay-safety gate ------------------------------

# planted R015 hazards: a wall-clock read and a set-literal iteration on
# a result-affecting path in exec/ — both must trip or the rule is vacuous
_DSAN_R015_SRC = '''\
import time


def route_rows(batches, nparts):
    out = {}
    stamp = time.time()
    for key in {"alpha", "beta", "gamma"}:
        out[key] = stamp
    return out
'''

# planted R016 hazard: a float accumulator folded across an
# arrival-ordered source with no tolerance and no canonicalization
_DSAN_R016_SRC = '''\
def fold(batches):
    running_sum = 0.0
    for b in batches:
        running_sum += b.column_sum("v")
    return running_sum
'''

# the set-iteration injection, run for REAL under two PYTHONHASHSEEDs:
# partition routing follows set(KEYS) iteration order, so the printed
# block digests must differ between seeds (dynamic anti-vacuity) AND the
# same source must trip TPU-R015 statically (for key in set(...)).
_DSAN_HASHSEED_SRC = r"""
import json

import pyarrow as pa

from spark_rapids_tpu.shuffle.digest import block_digest

KEYS = ["key-%03d" % i for i in range(32)]
assign = {}
pos = 0
for key in set(KEYS):
    assign.setdefault(pos % 4, []).append(key)
    pos += 1
digests = {}
for pid in sorted(assign):
    ks = assign[pid]
    rb = pa.RecordBatch.from_pydict({
        "k": pa.array(ks, type=pa.string()),
        "v": pa.array([KEYS.index(k) for k in ks], type=pa.int64()),
    })
    digests[str(pid)] = block_digest(rb)
print(json.dumps(digests))
"""


def run_dsan_gate() -> int:
    """tpudsan gate, four legs: (1) the determinism repo pass
    (TPU-R015/R016 + the L017 fingerprint-hygiene registry check) is
    finding-free with nothing frozen in the baseline; (2) static
    anti-vacuity — the planted R015/R016 sources, an L017 volatile /
    overlapping fingerprint schema and a stable_merge=off float partial
    aggregate must each trip their rule; (3) the permuted-replay oracle
    — every golden-corpus exchange site replays its map write under
    permuted batch arrival and again under a changed input split, and
    every subtree that CLAIMS order_stable or better must reproduce its
    content digests (bit_exact claims: per-(map,reduce) block-digest
    multisets; order_stable claims: per-(map,reduce) row-multiset
    digests; changed split: per-reduce row folds, skipped for
    partition-scoped partials), with every recorded write-time digest
    cross-checked against a recompute; (4) dynamic anti-vacuity — the
    planted arrival-order float sum and the PYTHONHASHSEED-dependent
    set-iteration router must each produce DIFFERENT digests when
    replayed, proving the oracle can see real nondeterminism."""
    import subprocess
    from collections import Counter

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.analysis import determinism as dsan
    from spark_rapids_tpu.analysis.plan_lint import lint_plan
    from spark_rapids_tpu.analysis.repo_lint import load_baseline
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec import base as eb
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.basic import LocalScanExec
    from spark_rapids_tpu.expr.aggregates import (AggregateExpression,
                                                  PARTIAL, Sum)
    from spark_rapids_tpu.expr.core import AttributeReference
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.shuffle.digest import (block_digest,
                                                 fold_multiset,
                                                 row_multiset_digest)
    from spark_rapids_tpu.shuffle.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.shuffle.manager import (TpuShuffleManager,
                                                  materialize_block)
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioning

    failures = 0

    # -- leg 1: repo pass finding-free, nothing frozen ----------------------
    for d in dsan.repo_diagnostics():
        failures += 1
        print(f"DSAN: repo finding (baseline is burned empty): "
              f"{d.render()}")
    frozen = [fp for fp in load_baseline(BASELINE)
              if fp.split("\t", 1)[0] in ("TPU-R015", "TPU-R016",
                                          "TPU-L017")]
    if frozen:
        failures += 1
        print(f"DSAN: {len(frozen)} determinism fingerprint(s) frozen "
              f"in the baseline — these rules must stay at zero debt")

    # -- leg 2: static anti-vacuity -----------------------------------------
    got = {d.code for d in dsan.module_diagnostics(
        _DSAN_R015_SRC, "spark_rapids_tpu/exec/injected.py")}
    n_r015 = sum(d.code == "TPU-R015" for d in dsan.module_diagnostics(
        _DSAN_R015_SRC, "spark_rapids_tpu/exec/injected.py"))
    if n_r015 < 2:
        failures += 1
        print(f"DSAN: R015 fixture tripped {n_r015}/2 plants (wall "
              f"clock + set iteration) — the rule is vacuous "
              f"(got {sorted(got)})")
    got = {d.code for d in dsan.module_diagnostics(
        _DSAN_R016_SRC, "spark_rapids_tpu/exec/injected.py")}
    if "TPU-R016" not in got:
        failures += 1
        print(f"DSAN: R016 fixture did not trip (got "
              f"{sorted(got) or 'nothing'}) — the rule is vacuous")
    hyg = dsan.fingerprint_hygiene_diagnostics(
        deterministic=["plan_hash", "submit_time_ms"],
        timing=["submit_time_ms"])
    if sum(d.code == "TPU-L017" for d in hyg) < 1:
        failures += 1
        print("DSAN: L017 did not flag an overlapping volatile "
              "fingerprint field — the hygiene check is vacuous")
    hyg = dsan.fingerprint_hygiene_diagnostics(
        deterministic=["plan_hash", "wall_start"], timing=[])
    if sum(d.code == "TPU-L017" for d in hyg) < 1:
        failures += 1
        print("DSAN: L017 did not flag a time-derived deterministic "
              "fingerprint field — the hygiene check is vacuous")

    def _inject_plan(stable):
        """scan(batch_rows=1) -> PARTIAL float Sum -> hash exchange.
        With stable_merge off the partial's float buffers fold in batch
        arrival order — the canonical L016 hazard; the data is chosen so
        a reversed arrival changes the sum ((1e16 - 1e16) + 1 = 1 but
        (1 - 1e16) + 1e16 = 0 in float64)."""
        tbl = pa.table({
            "k": pa.array([0, 0, 0], type=pa.int64()),
            "v": pa.array([1e16, -1e16, 1.0], type=pa.float64()),
        })
        scan = LocalScanExec(tbl, num_partitions=1, batch_rows=1)
        scan.placement = eb.CPU
        partial = TpuHashAggregateExec(
            [AttributeReference("k")],
            [AggregateExpression(Sum(AttributeReference("v")))],
            PARTIAL, scan)
        partial.placement = eb.CPU
        partial.stable_merge = stable
        ex = ShuffleExchangeExec(
            HashPartitioning([AttributeReference("k")], 2), partial)
        ex.placement = eb.CPU
        return ex, scan

    bad_ex, _ = _inject_plan(stable=False)
    got = {d.code for d in lint_plan(bad_ex, RapidsConf({}))}
    if "TPU-L016" not in got:
        failures += 1
        print(f"DSAN: the stable_merge=off float partial did not trip "
              f"TPU-L016 (got {sorted(got)}) — the rule is vacuous")
    clean_ex, _ = _inject_plan(stable=True)
    got = {d.code for d in lint_plan(clean_ex, RapidsConf({}))}
    if "TPU-L016" in got:
        failures += 1
        print("DSAN: the canonical-merge twin tripped TPU-L016 — "
              "false positive on the clean shape")

    # -- leg 3: the permuted-replay oracle over golden exchange sites -------
    def _walk(node):
        yield node
        for c in node.children:
            yield from _walk(c)

    def _prep_scans(root, batch_rows, extra_parts=0):
        """Deterministic chunking for the oracle: fixed batch_rows so
        legs differ ONLY in what the leg varies; pin caches off so
        every leg rereads the table."""
        for n in _walk(root):
            if isinstance(n, LocalScanExec):
                n.batch_rows = batch_rows
                n.pin_cache = None
                n._num_partitions += extra_parts

    class _Permuted(eb.Exec):
        """Adversarial scheduler: replays the child's batches in
        reversed arrival order.  Exactly the perturbation an
        order_stable claim promises immunity to, so the wrapper itself
        declares nothing."""

        def __init__(self, inner):
            super().__init__([inner])
            self.placement = inner.placement

        @property
        def output_names(self):
            return self.children[0].output_names

        @property
        def output_types(self):
            return self.children[0].output_types

        def execute_partition(self, pid, ctx):
            return iter(list(
                self.children[0].execute_partition(pid, ctx))[::-1])

    def _permute_scans(root):
        for n in list(_walk(root)):
            if isinstance(n, _Permuted):
                continue
            for i, c in enumerate(n.children):
                if isinstance(c, LocalScanExec):
                    n.children[i] = _Permuted(c)

    def _run_exchange(ex, conf_map):
        """Drive ONE exchange's map write and harvest its content
        addressing: recorded write-time digests per (map, reduce), a
        row-multiset digest per (map, reduce), the per-reduce row fold,
        and any recorded-vs-recomputed digest mismatches."""
        conf = RapidsConf(dict(conf_map))
        ctx = eb.ExecContext(conf)
        ctx.task_context["no_speculation"] = True
        ex._ensure_written(ctx)
        sid = ex._shuffle_id
        mgr = TpuShuffleManager.get()
        blockdg = {}   # (mid, rid) -> Counter of recorded block digests
        for ((_, mid, rid), _idx), dg in \
                mgr.catalog.digests_for_shuffle(sid).items():
            blockdg.setdefault((mid, rid), Counter())[dg] += 1
        rowdg = {}     # (mid, rid) -> u64 row-multiset fold
        reduce_fold = {}  # rid -> u64 row fold across all maps
        bad_records = []
        for rid in range(ex.num_partitions):
            for blk in mgr.catalog.blocks_for_reduce(sid, rid):
                for i, sb in enumerate(mgr.catalog.get(blk)):
                    rb = materialize_block(sb, np)
                    recorded = mgr.catalog.digest(blk, i)
                    recomputed = block_digest(rb)
                    if recorded != recomputed:
                        bad_records.append((tuple(blk), i, recorded,
                                            recomputed))
                    rd = row_multiset_digest(rb)
                    key = (blk[1], rid)
                    rowdg[key] = (rowdg.get(key, 0) + rd) \
                        & 0xFFFFFFFFFFFFFFFF
                    reduce_fold[rid] = (reduce_fold.get(rid, 0) + rd) \
                        & 0xFFFFFFFFFFFFFFFF
        mgr.unregister(sid)
        return blockdg, rowdg, reduce_fold, bad_records

    from spark_rapids_tpu.analysis.determinism import (BIT_EXACT,
                                                       ORDER_STABLE,
                                                       RANK)

    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()

    good = _builders(os.path.join(GOLDEN, "good_plans.py"))
    oracle_sites = 0
    split_skips = 0
    for name in ("plan_partial_final_aggregate",
                 "plan_colocated_join_with_exchanges",
                 "plan_exchange_fully_read"):
        roots = {}
        for leg in ("A", "B", "C"):
            root, conf_map = good[name]()
            _prep_scans(root, batch_rows=5,
                        extra_parts=1 if leg == "C" else 0)
            if leg == "C":
                _prep_scans(root, batch_rows=7)
            if leg == "B":
                _permute_scans(root)
            roots[leg] = (root, conf_map)
        res = dsan.classify_plan(roots["A"][0],
                                 RapidsConf(dict(roots["A"][1])))
        exchanges = {leg: [n for n in _walk(roots[leg][0])
                           if isinstance(n, ShuffleExchangeExec)]
                     for leg in roots}
        for i, exa in enumerate(exchanges["A"]):
            oracle_sites += 1
            child = exa.children[0]
            claim = res.effective(child)
            scoped = res.is_partition_scoped(child)
            if RANK[claim] < RANK[ORDER_STABLE]:
                failures += 1
                print(f"DSAN: {name} exchange[{i}] subtree claims "
                      f"{claim} ({res.reason(child)}) — golden plans "
                      f"must replay order_stable or better")
                continue
            A = _run_exchange(exa, roots["A"][1])
            B = _run_exchange(exchanges["B"][i], roots["B"][1])
            C = _run_exchange(exchanges["C"][i], roots["C"][1])
            for leg, r in (("A", A), ("B", B), ("C", C)):
                for blk, idx, rec, comp in r[3]:
                    failures += 1
                    print(f"DSAN: {name} exchange[{i}] leg {leg}: "
                          f"recorded digest {rec:#018x} != recomputed "
                          f"{comp:#018x} for block {blk}[{idx}] — "
                          f"write-time recording drifted")
            if claim == BIT_EXACT and A[0] != B[0]:
                failures += 1
                print(f"DSAN: {name} exchange[{i}]: subtree claims "
                      f"bit_exact but permuted arrival changed the "
                      f"per-(map,reduce) block-digest multisets")
            if A[1] != B[1]:
                failures += 1
                print(f"DSAN: {name} exchange[{i}]: subtree claims "
                      f"{claim} but permuted arrival changed the "
                      f"per-(map,reduce) row-multiset digests — "
                      f"recomputed blocks would not match the lost "
                      f"ones")
            if scoped:
                split_skips += 1
                print(f"DSAN: note: {name} exchange[{i}] changed-split "
                      f"leg skipped — the subtree is partition-scoped "
                      f"(partial buffers regroup with the input "
                      f"split); arrival-permutation still enforced")
            elif A[2] != C[2]:
                failures += 1
                print(f"DSAN: {name} exchange[{i}]: a changed input "
                      f"split altered the per-reduce row multisets — "
                      f"hash routing must be content-determined")

    # -- leg 4a: dynamic anti-vacuity — arrival-order float sum -------------
    ex_fwd, _ = _inject_plan(stable=False)
    ex_rev, scan_rev = _inject_plan(stable=False)
    agg_rev = ex_rev.children[0]
    agg_rev.children[0] = _Permuted(scan_rev)
    F = _run_exchange(ex_fwd, {})
    R = _run_exchange(ex_rev, {})
    if F[1] == R[1]:
        failures += 1
        print("DSAN: the stable_merge=off float sum digested "
              "IDENTICALLY under reversed arrival — the dynamic "
              "oracle cannot see arrival-order nondeterminism "
              "(vacuous)")

    # -- leg 4b: dynamic anti-vacuity — PYTHONHASHSEED set routing ----------
    got = {d.code for d in dsan.module_diagnostics(
        _DSAN_HASHSEED_SRC, "spark_rapids_tpu/shuffle/injected.py",
        rules=("TPU-R015",))}
    if "TPU-R015" not in got:
        failures += 1
        print("DSAN: the set-iteration router source did not trip "
              "TPU-R015 statically")
    runs = []
    for seed in ("1", "2"):
        env = dict(os.environ, PYTHONHASHSEED=seed,
                   JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        p = subprocess.run([sys.executable, "-c", _DSAN_HASHSEED_SRC],
                           capture_output=True, text=True, env=env,
                           cwd=REPO, timeout=300)
        if p.returncode != 0:
            failures += 1
            print(f"DSAN: hashseed probe (seed {seed}) failed: "
                  f"{p.stderr.strip()[-400:]}")
            runs.append(None)
        else:
            runs.append(json.loads(p.stdout.strip().splitlines()[-1]))
    if None not in runs and runs[0] == runs[1]:
        failures += 1
        print("DSAN: set-iteration routing digested IDENTICALLY under "
              "two PYTHONHASHSEEDs — the digest oracle cannot see "
              "hash-order nondeterminism (vacuous)")

    if failures:
        print(f"dsan gate: {failures} failure(s)")
        return 1
    print(f"dsan gate clean (repo determinism pass finding-free with "
          f"zero frozen debt; R015/R016/L017/L016 fixtures all trip "
          f"with the canonical-merge twin clean; {oracle_sites} golden "
          f"exchange sites digest-identical under permuted arrival "
          f"and changed split ({split_skips} partition-scoped "
          f"split-leg skip(s)); both planted nondeterminism "
          f"injections visible to the dynamic oracle)")
    return 0


def run_hlo_gate() -> int:
    """tpuxsan gate: the golden corpus replays with StableHLO +
    cost_analysis() persistence on; every persisted program artifact
    must resolve (deduped), the analytic cost model must agree with
    XLA's bytes-accessed on >= 90% of compiled programs, the padding
    books must reconcile three ways (span padWasteBytes vs live-row
    recomputation vs the counter), the L018/L019/L020/R017 fixtures
    must trip with clean twins passing, an injected pathological
    bucket (1M capacity over 10 live rows) must produce both the L018
    finding and the counter delta, and `tools kernel-report` must rank
    the grouped-aggregate and hash-join programs with nonzero
    projected savings."""
    import io
    import shutil
    import tempfile

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu import types as t
    from spark_rapids_tpu.analysis import hloaudit, hlocost
    from spark_rapids_tpu.analysis.plan_lint import (downgrade_hazards,
                                                     lint_plan)
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.columnar.device import (DeviceBatch,
                                                  DeviceColumn)
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec import base as eb
    from spark_rapids_tpu.memory.spill import batch_device_bytes
    from spark_rapids_tpu.obs.compileprof import (HLO_SUBDIR, HLO_SUFFIX,
                                                  CompileObservatory)
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.obs.tracer import QueryTrace
    from spark_rapids_tpu.tools.compile_report import load_ledger
    from spark_rapids_tpu.tools.eventlog import parse_event_log
    from spark_rapids_tpu.tools.kernel_report import (
        aggregate_kernel_report, load_estimator_ledger,
        run_kernel_report)

    failures = 0
    tmp = tempfile.mkdtemp(prefix="hlo_gate_")
    reg = MetricsRegistry.reset_for_tests()
    CompileObservatory.reset_for_tests()
    eb.clear_jit_cache()
    try:
        evt = os.path.join(tmp, "evt")
        os.makedirs(evt)
        hist = os.path.join(tmp, "hist")
        s = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", True)
             .config("spark.rapids.tpu.singleChipFuse", "off")
             .config("spark.rapids.tpu.sort.compileLean", "off")
             .config("spark.rapids.tpu.eventLog.dir", evt)
             .config("spark.rapids.tpu.compile.ledgerDir", hist)
             .get_or_create())
        rng = np.random.default_rng(20818)
        fact = pa.table({
            "k": pa.array((rng.integers(0, 97, 4000)).astype(np.int64)),
            "v": pa.array(rng.integers(-1000, 1000, 4000)
                          .astype(np.int64))})
        dim = pa.table({
            "k": pa.array(np.arange(97, dtype=np.int64)),
            "w": pa.array(np.arange(97, dtype=np.int64) * 3)})
        fdf = s.create_dataframe(fact, num_partitions=2)
        ddf = s.create_dataframe(dim)

        o1 = (fdf.filter(col("v") > -500).group_by(col("k"))
              .agg(F.sum(col("v")).alias("sv"),
                   F.count("*").alias("c")).collect())
        o2 = (fdf.join(ddf, on="k", how="inner").group_by(col("k"))
              .agg(F.sum(col("w")).alias("sw")).collect())
        o3 = fdf.sort(col("k"), col("v")).collect()
        o4 = (fdf.filter(col("v") > 0)
              .select(col("k"), (col("v") + col("v")).alias("v2"))
              .collect())
        if (o1.num_rows, o2.num_rows, o3.num_rows) != (97, 97, 4000) \
                or o4.num_rows == 0:
            failures += 1
            print("HLO: corpus produced wrong row counts")

        # [persist] every build's hlo_hash must resolve to exactly one
        # deduped artifact on disk; a corpus that persists nothing is
        # vacuous
        ledger_path = os.path.join(hist, "compile_ledger.jsonl")
        records = load_ledger(ledger_path)
        builds = [r for r in records if r.get("event") == "build"]
        hashes = {r["hlo_hash"] for r in builds if r.get("hlo_hash")}
        unhashed = [r for r in builds if not r.get("hlo_hash")]
        if not builds or not hashes:
            failures += 1
            print(f"HLO: vacuous — {len(builds)} build(s), "
                  f"{len(hashes)} persisted program(s)")
        if unhashed:
            failures += 1
            print(f"HLO: {len(unhashed)} build(s) carry no hlo_hash "
                  f"({sorted({r.get('exec') for r in unhashed})})")
        hlo_dir = os.path.join(hist, HLO_SUBDIR)
        on_disk = set()
        if os.path.isdir(hlo_dir):
            on_disk = {f[:-len(HLO_SUFFIX)] for f in os.listdir(hlo_dir)
                       if f.endswith(HLO_SUFFIX)}
        if on_disk != hashes:
            failures += 1
            print(f"HLO: artifact store out of step with the ledger — "
                  f"{len(hashes)} hash(es) vs {len(on_disk)} file(s); "
                  f"missing {sorted(hashes - on_disk)[:4]}, orphaned "
                  f"{sorted(on_disk - hashes)[:4]}")

        # [cost model] the analytic model must track XLA's own books —
        # drift means the report's gap column is fiction
        cm = hlocost.validate_model(builds, tolerance=8.0)
        if cm["checked"] == 0:
            failures += 1
            print("HLO: cost-model check vacuous — no build carried "
                  "cost_analysis() bytes")
        elif cm["agreement_pct"] < 90.0:
            failures += 1
            print(f"HLO: cost model agrees on only "
                  f"{cm['agreement_pct']:.0f}% of {cm['checked']} "
                  f"program(s) (< 90%); worst {cm['worst']}")

        # [pad books] three-way reconciliation: each span's persisted
        # padWasteBytes must equal the live-row recomputation, and the
        # counter must equal the span sum (checked BEFORE the synthetic
        # injection below adds counter-only traffic)
        logs = [f for f in os.listdir(evt) if f.startswith("events_")]
        op_spans = []
        if logs:
            app = parse_event_log(os.path.join(evt, logs[0]))
            op_spans = [sp for sp in app.spans
                        if "padWasteBytes" in sp]
        if not op_spans:
            failures += 1
            print("HLO: pad reconciliation vacuous — no operator span "
                  "carries padWasteBytes")
        span_total = 0
        for sp in op_spans:
            cap = int(sp.get("capRows") or 0)
            byt = int(sp.get("bytes") or 0)
            want = 0
            if cap > 0 and byt > 0:
                live = min(max(int(sp.get("rows") or 0), 0), cap)
                want = int(byt * (cap - live) / cap)
            got = int(sp["padWasteBytes"])
            if got != want:
                failures += 1
                print(f"HLO: span {sp.get('name')} books {got} pad "
                      f"bytes; rows/capacity recompute to {want}")
            span_total += got
        pad_fam = reg.counter("tpu_pad_waste_bytes_total",
                              labelnames=("exec",))
        metric_total = int(sum(ch.value for _, ch in pad_fam.series()))
        if metric_total != span_total:
            failures += 1
            print(f"HLO: tpu_pad_waste_bytes_total {metric_total} != "
                  f"event-log span sum {span_total}")

        # [kernel report] the headline artifact must rank the Pallas
        # candidates with nonzero projected savings, and the CLI must
        # render it
        agg = aggregate_kernel_report(records,
                                      load_estimator_ledger(hist))
        sav = {t_["target"]: t_["projected_savings_s"]
               for t_ in agg["targets"]}
        for want_target in ("fused grouped aggregate (sort+segment-"
                            "reduce)", "fused hash build/probe"):
            if sav.get(want_target, 0.0) <= 0.0:
                failures += 1
                print(f"HLO: kernel report projects no savings for "
                      f"{want_target!r} (targets {sav})")
        buf = io.StringIO()
        rc = run_kernel_report(ledger_path, hist, out=buf)
        if rc != 0 or "kernel gap report" not in buf.getvalue():
            failures += 1
            print(f"HLO: kernel-report CLI failed (rc {rc})")

        # [fixtures] bad twins trip, clean twins pass
        bad = _builders(os.path.join(GOLDEN, "bad_plans.py"))
        root18, cmap18 = bad["plan_L018_pad_waste"]()
        d18 = lint_plan(root18, RapidsConf(cmap18), infer=True)
        if "TPU-L018" not in {d.code for d in d18}:
            failures += 1
            print("HLO: the pathological-bucket plan did not trip "
                  "TPU-L018")
        root18c, _ = bad["plan_L018_pad_waste"]()
        clean = {d.code for d in lint_plan(root18c, RapidsConf({}),
                                           infer=True)}
        if {"TPU-L018", "TPU-L020"} & clean:
            failures += 1
            print(f"HLO: clean twin (default buckets) tripped "
                  f"{sorted(clean)}")
        root20, cmap20 = bad["plan_L020_fusion_break"]()
        if "TPU-L020" not in {d.code for d in lint_plan(
                root20, RapidsConf(cmap20), infer=True)}:
            failures += 1
            print("HLO: the project->filter pipeline did not trip "
                  "TPU-L020")
        root20x, _ = bad["plan_L020_fusion_break"]()
        off = {d.code for d in lint_plan(
            root20x, RapidsConf({"spark.rapids.tpu.xsan.enabled":
                                 False}), infer=True)}
        if {"TPU-L018", "TPU-L020"} & off:
            failures += 1
            print(f"HLO: xsan.enabled=false still emitted "
                  f"{sorted(off)}")

        # L018 repair: with a genuinely smaller bucket on the menu the
        # pre-flight must arm the speculative re-bucket and keep the
        # filter on device; with none it must refuse
        ns = __import__("runpy").run_path(
            os.path.join(GOLDEN, "bad_plans.py"))
        from spark_rapids_tpu.exec.basic import FilterExec
        from spark_rapids_tpu.expr.core import (AttributeReference,
                                                Literal)
        from spark_rapids_tpu.expr.predicates import GreaterThan
        scan = ns["_scan"](ns["_ints"](n=1200))
        flt = FilterExec(GreaterThan(AttributeReference("v"),
                                     Literal(600, t.LONG)), scan)
        flt.placement = eb.TPU
        rconf = RapidsConf({"spark.rapids.tpu.batchCapacityBuckets":
                            "1024,1048576"})
        rd = lint_plan(flt, rconf, infer=True)
        downgrade_hazards(flt, rd, rconf)
        if flt.rebucket_cap != 1024 or flt.placement != eb.TPU:
            failures += 1
            print(f"HLO: L018 repair did not arm (rebucket_cap="
                  f"{flt.rebucket_cap}, placement={flt.placement})")
        if getattr(root18, "rebucket_cap", None) is not None:
            failures += 1
            print("HLO: L018 repair armed with no smaller bucket "
                  "available (a no-op shrink)")

        # L019: a planted host callback inside a persisted program
        # trips; the pure twin is clean
        hdir = os.path.join(tmp, "hlo_fixtures")
        os.makedirs(hdir)
        bad_hlo = ('func.func @main(%arg0: tensor<4xi64>) {\n'
                   '  %0 = "stablehlo.custom_call"(%arg0) '
                   '{call_target_name = "xla_python_cpu_callback"} : '
                   '(tensor<4xi64>) -> tensor<4xi64>\n  return\n}\n')
        ok_hlo = ('func.func @main(%arg0: tensor<4xi64>) {\n'
                  '  %0 = stablehlo.add %arg0, %arg0 : tensor<4xi64>\n'
                  '  return\n}\n')
        for h, text in (("deadbeef00000001", bad_hlo),
                        ("deadbeef00000002", ok_hlo)):
            with open(os.path.join(hdir, h + HLO_SUFFIX), "w") as f:
                f.write(text)
        recs = [{"event": "build", "exec": "ProbeExec",
                 "hlo_hash": "deadbeef00000001"},
                {"event": "build", "exec": "CleanExec",
                 "hlo_hash": "deadbeef00000002"}]
        l19 = hloaudit.audit_ledger(recs, hdir, 16 << 20)
        codes19 = [d.code for d in l19]
        if codes19 != ["TPU-L019"]:
            failures += 1
            print(f"HLO: planted host callback produced {codes19} "
                  f"(expected exactly one TPU-L019, clean twin silent)")

        # R017: a raw jnp call in exec/ trips; the xp-parameterized and
        # allow-annotated twins are clean
        r_bad = "import jax.numpy as jnp\n\ndef widen(c):\n" \
                "    return jnp.cumsum(c)\n"
        r_ok = "def widen(c, xp):\n    return xp.cumsum(c)\n"
        r_allow = ("import jax.numpy as jnp\n\ndef widen(c):\n"
                   "    return jnp.cumsum(c)  "
                   "# tpulint: allow[TPU-R017] gate fixture\n")
        if [d.code for d in hloaudit.module_diagnostics(
                r_bad, "exec/fake.py")] != ["TPU-R017"]:
            failures += 1
            print("HLO: raw jnp call in exec/ did not trip TPU-R017")
        for src, rel, why in ((r_ok, "exec/fake.py", "xp twin"),
                              (r_allow, "exec/fake.py", "allow twin"),
                              (r_bad, "obs/fake.py", "non-kernel path")):
            got = [d.code for d in hloaudit.module_diagnostics(src, rel)]
            if got:
                failures += 1
                print(f"HLO: R017 {why} flagged {got}")
        # burned-in baseline: the live tree owes zero R017 findings
        live = [d for d in hloaudit.repo_diagnostics(
            os.path.join(REPO, "spark_rapids_tpu"))
            if d.code == "TPU-R017"]
        if live:
            failures += 1
            print(f"HLO: {len(live)} unregistered raw jnp/lax site(s) "
                  f"in the live tree: {[d.loc for d in live[:4]]}")

        # [injection] a 1M-capacity launch carrying 10 live rows must
        # move the counter by exactly bytes*(cap-live)/cap
        cap = 1 << 20
        import jax.numpy as jnp
        pathological = DeviceBatch(
            [DeviceColumn(t.LONG, data=jnp.zeros(cap, jnp.int64))],
            10, ["v"])
        expect = int(batch_device_bytes(pathological)
                     * (cap - 10) / cap)
        before = int(sum(ch.value for _, ch in pad_fam.series()))
        qt = QueryTrace()

        class InjectedBucketExec:
            pass

        for _ in qt.trace_operator(InjectedBucketExec(), 0,
                                   iter([pathological])):
            pass
        qt.finalize()
        after = int(sum(ch.value for _, ch in pad_fam.series()))
        if after - before != expect or expect <= 0:
            failures += 1
            print(f"HLO: pathological bucket moved the counter by "
                  f"{after - before} (expected {expect})")

        n_prog = len(hashes)
        pct = cm["agreement_pct"] or 0.0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        eb.clear_jit_cache()
    if failures:
        print(f"hlo gate: {failures} failure(s)")
        return 1
    print(f"hlo gate clean ({n_prog} persisted program(s) resolve "
          f"deduped; cost model agrees on {pct:.0f}% of programs; pad "
          f"books reconcile span/recompute/counter; kernel report "
          f"ranks the grouped-aggregate and hash-join fusions with "
          f"nonzero savings; L018/L019/L020/R017 fixtures trip with "
          f"clean twins silent; repair arms only when a smaller "
          f"bucket exists; injected 1M-capacity launch booked the "
          f"exact padding delta)")
    return 0


def run_slo_gate() -> int:
    """Latency-observatory gate (obs/critpath.py + obs/slo.py), two
    phases through one 4-session pool:

    * **Golden mix** — the serve gate's four queries replayed
      concurrently with tracing on: every completed query's
      critical-path segments must sum to its wall time within the
      tolerance gate, the three sinks must agree (root-span annotation,
      tpu_latency_segment_seconds_total counters, latency ledger), and
      the burn-rate health rule must NOT trip (anti-vacuity one way).
    * **Injected whale** — tenant pool-0's FilterExec is armed with a
      sleep and its admission ticket inflated so victims (pool-1..3)
      queue behind it deterministically: the sustained-burn health rule
      must flip DEGRADED naming the victims, tail-report must attribute
      each victim's p99 >= 50% to queue_wait while its p50 mix stays
      compute-dominated, and the whale itself must stay
      compute-attributed (anti-vacuity the other way).  Plus the
      observatory's own overhead must stay under 5% of query wall —
      the same accounting `bench.py --serve` reports.
    """
    import concurrent.futures as cf
    import shutil
    import tempfile
    import time as _time

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec.base import _wrap_execute_partition
    from spark_rapids_tpu.exec.basic import FilterExec
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.obs import metrics as m
    from spark_rapids_tpu.obs.critpath import SEGMENT_FAMILY
    from spark_rapids_tpu.obs.health import DEGRADED, OK, HealthMonitor
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.obs.slo import LatencyObservatory
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager

    failures = 0
    MetricsRegistry.reset_for_tests()
    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()
    AdmissionController.reset_for_tests()
    LatencyObservatory.reset_for_tests()

    n = 4000
    rng = np.random.default_rng(7)
    fact = pa.table({
        "k": pa.array(rng.integers(0, 97, n).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(97, dtype=np.int64)),
        "w": pa.array(np.arange(97, dtype=np.int64) * 10),
    })
    budget = 256 << 20
    hist = tempfile.mkdtemp(prefix="slo_gate_hist_")
    pool = SessionPool(4, {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.tpu.memsan.enabled": "true",
        "spark.rapids.tpu.singleChipFuse": "off",
        "spark.rapids.tpu.trace.enabled": "true",
        "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes": str(budget),
        "spark.rapids.tpu.serve.admissionTimeoutMs": "60000",
        "spark.rapids.tpu.regress.historyDir": hist,
        # generous golden-phase target: the golden mix must never burn
        # on a loaded CI host (the whale phase reconfigures to 400ms)
        "spark.rapids.tpu.slo.targetMs": "600000",
        "spark.rapids.tpu.slo.objective": "0.9",
    })
    monitor = HealthMonitor()

    from spark_rapids_tpu.expr.window import WindowBuilder

    def mk_mix(s):
        fdf = s.create_dataframe(fact)
        fdf4 = s.create_dataframe(fact, num_partitions=4)
        ddf2 = s.create_dataframe(dim, num_partitions=2)
        w = WindowBuilder().partition_by(col("k")).order_by(col("v"))
        return {
            "agg": lambda: (fdf.group_by(col("k"))
                            .agg(F.sum(col("v")).alias("sv"),
                                 F.count("*").alias("c")).collect()),
            "join": lambda: (fdf4.join(ddf2, on="k", how="inner")
                             .group_by(col("k"))
                             .agg(F.sum(col("w")).alias("sw"))
                             .collect()),
            "window": lambda: (fdf.select(
                col("k"), col("v"),
                F.row_number().over(w).alias("rn")).collect()),
            "sort": lambda: fdf.sort(col("k"), col("v")).collect(),
            # whale-phase query: single partition so the armed filter
            # sleeps exactly once per run
            "filter_agg": lambda: (fdf.filter(col("v") > -10_000)
                                   .group_by(col("k"))
                                   .agg(F.sum(col("v")).alias("sv"))
                                   .collect()),
        }

    mixes = {id(s): mk_mix(s) for s in pool._sessions}
    worklist = [name for name in ("agg", "join", "window", "sort")
                for _ in range(4)]

    def one(name):
        with pool.session() as s:
            mixes[id(s)][name]()

    with cf.ThreadPoolExecutor(max_workers=4) as ex:
        list(ex.map(one, worklist))
    pool.drain(timeout=60)

    def load_ledger():
        import json
        path = os.path.join(hist, "latency_ledger.jsonl")
        if not os.path.exists(path):
            return []
        with open(path, encoding="utf-8") as f:
            return [json.loads(x) for x in f if x.strip()]

    # -- golden-phase checks -------------------------------------------------
    records = load_ledger()
    completed = m.counter("tpu_queries_completed_total").value()
    if not records or len(records) != completed:
        failures += 1
        print(f"SLO: ledger sink disagrees with the query counter "
              f"({len(records)} records != {completed} completed)")
    bad_recon = [r for r in records if not r.get("reconciled")]
    for r in records:
        covered = sum(r["segments"].values())
        if abs(covered - r["wall_s"]) > max(0.05 * r["wall_s"], 0.002):
            bad_recon.append(r)
    if bad_recon:
        failures += 1
        print(f"SLO: {len(bad_recon)} record(s) failed segment-vs-wall "
              f"reconciliation (first: {bad_recon[0]})")
    ledger_seg_s = sum(sum(r["segments"].values()) for r in records)
    fam = [f for f in MetricsRegistry.get().families()
           if f.name == SEGMENT_FAMILY]
    counter_seg_s = fam[0].total() if fam else 0.0
    if not fam or abs(counter_seg_s - ledger_seg_s) > \
            max(0.01 * ledger_seg_s, 1e-3):
        failures += 1
        print(f"SLO: counter sink disagrees with span math "
              f"({counter_seg_s:.4f}s counted vs {ledger_seg_s:.4f}s "
              f"in the ledger)")
    annotated = [sp for s in pool._sessions
                 if s.last_query_trace() is not None
                 for sp in s.last_query_trace().span_dicts()
                 if sp["kind"] == "query" and
                 sp["attrs"].get("critical_path")]
    if not annotated:
        failures += 1
        print("SLO: no root span carries the critical_path annotation")
    for _ in range(2):
        snap = monitor.snapshot()
    if snap["components"]["slo"]["status"] != OK:
        failures += 1
        print(f"SLO: burn rule tripped on the clean golden mix "
              f"(vacuity): {snap['components']['slo']}")

    # -- whale phase ---------------------------------------------------------
    def run_as(s, fn):
        TpuSession.bind_to_thread(s)
        try:
            return fn()
        finally:
            TpuSession.bind_to_thread(None)

    # warm the filter_agg jit before arming anything so the whale's
    # tail is sleep, not first-compile
    for s in pool._sessions:
        run_as(s, mixes[id(s)]["filter_agg"])

    # the whale phase writes its own ledger: the CLI report below must
    # describe the incident, not the golden phase's first-compile tails
    hist_whale = tempfile.mkdtemp(prefix="slo_gate_whale_")
    LatencyObservatory.reset_for_tests()
    LatencyObservatory.get().configure(
        target_ms=400, objective=0.9,
        ledger_path=os.path.join(hist_whale, "latency_ledger.jsonl"))

    whale_sleep, victim_sleep = 0.6, 0.05
    raw_ep = FilterExec.execute_partition.__wrapped__
    orig_ep = FilterExec.execute_partition
    orig_bound = TpuSession._static_peak_bound

    def sleepy_ep(self, pid, ctx):
        s = TpuSession.active()
        tenant = getattr(s, "_tenant", "") if s is not None else ""
        slp = whale_sleep if tenant == "pool-0" else victim_sleep
        for b in raw_ep(self, pid, ctx):
            if slp:
                _time.sleep(slp)  # inside the operator span: compute
                slp = 0.0
            yield b

    def fixed_bound(self, final_plan, conf, budget=None):
        # whale + any victim oversubscribes the 256M budget, two
        # victims co-run: victims queue IFF the whale is in flight
        return (200 << 20) if getattr(self, "_tenant", "") == "pool-0" \
            else (100 << 20)

    FilterExec.execute_partition = _wrap_execute_partition(sleepy_ep)
    TpuSession._static_peak_bound = fixed_bound
    try:
        whale, victims = pool._sessions[0], pool._sessions[1:]
        # uncontended victim baselines: GOOD and compute-dominated
        for _ in range(6):
            for s in victims:
                run_as(s, mixes[id(s)]["filter_agg"])
        for _ in range(2):
            snap = monitor.snapshot()
        if snap["components"]["slo"]["status"] != OK:
            failures += 1
            print(f"SLO: burn rule tripped on uncontended victims "
                  f"(vacuity): {snap['components']['slo']}")
        # whale rounds: pool-0 admits first and holds 200M through its
        # armed 0.6s filter; victims arrive 0.15s later and queue
        for _ in range(4):
            with cf.ThreadPoolExecutor(max_workers=4) as ex:
                futs = [ex.submit(run_as, whale,
                                  mixes[id(whale)]["filter_agg"])]
                _time.sleep(0.15)
                futs += [ex.submit(run_as, s,
                                   mixes[id(s)]["filter_agg"])
                         for s in victims]
                for f in futs:
                    f.result()
    finally:
        FilterExec.execute_partition = orig_ep
        TpuSession._static_peak_bound = orig_bound

    # -- whale-phase checks --------------------------------------------------
    rep = LatencyObservatory.get().slo_report()
    tail = LatencyObservatory.get().tail_report()
    victim_names = [f"pool-{i}" for i in (1, 2, 3)]
    for name in victim_names:
        row = rep["tenants"].get(name, {})
        if row.get("burn_rate", 0.0) <= 1.0:
            failures += 1
            print(f"SLO: victim {name} burn rate "
                  f"{row.get('burn_rate')} did not exceed 1 under the "
                  f"whale")
        agg = tail["tenants"].get(name, {})
        if agg.get("dominant_tail_segment") != "queue_wait" or \
                agg.get("p99_mix", {}).get("queue_wait", 0.0) < 0.5:
            failures += 1
            print(f"SLO: victim {name} p99 not attributed >= 50% to "
                  f"queue_wait: {agg.get('p99_mix')}")
        if agg.get("p50_mix", {}).get("queue_wait", 0.0) >= 0.5:
            failures += 1
            print(f"SLO: victim {name} p50 mix is queue-dominated — "
                  f"the baseline should be compute-bound: "
                  f"{agg.get('p50_mix')}")
    whale_dom = tail["tenants"].get("pool-0", {}).get(
        "dominant_tail_segment") or ""
    if not whale_dom.startswith("compute:"):
        failures += 1
        print(f"SLO: the whale's own tail should be compute-bound, "
              f"got {whale_dom!r}")
    for _ in range(2):
        snap = monitor.snapshot()
    slo_comp = snap["components"]["slo"]
    burning = slo_comp.get("signals", {}).get("burning_tenants", [])
    if slo_comp["status"] != DEGRADED or \
            not set(victim_names) <= set(burning):
        failures += 1
        print(f"SLO: sustained burn did not degrade /healthz naming "
              f"the victims: {slo_comp}")
    # admission.wait span: queue time must be a real span under the
    # root, carrying its ticket bytes and queue depth at enqueue
    waits = [sp for s in pool._sessions
             if s.last_query_trace() is not None
             for sp in s.last_query_trace().span_dicts()
             if sp["name"] == "admission.wait"]
    if not waits or not any("queue_depth_at_enqueue" in sp["attrs"]
                            for sp in waits):
        failures += 1
        print("SLO: no admission.wait span with queue depth recorded")
    overhead = LatencyObservatory.get().overhead()
    if overhead["pct"] >= 5.0:
        failures += 1
        print(f"SLO: critical-path extraction overhead "
              f"{overhead['pct']:.2f}% of query wall (>= 5%)")
    # tail-report CLI over the same ledger: the culprit line must name
    # queue_wait for a victim tenant
    import contextlib
    import io
    from spark_rapids_tpu.tools.tail_report import run_tail_report
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = run_tail_report(hist_whale)
    cli_out = buf.getvalue()
    if rc != 0 or not any(f"tenant {v}'s p99 is" in cli_out and
                          "queue_wait" in cli_out
                          for v in victim_names):
        failures += 1
        print(f"SLO: tools tail-report did not name queue_wait as a "
              f"victim's dominant tail segment:\n{cli_out}")

    pool.drain(timeout=60)
    pool.close()
    shutil.rmtree(hist, ignore_errors=True)
    shutil.rmtree(hist_whale, ignore_errors=True)
    MetricsRegistry.reset_for_tests()
    AdmissionController.reset_for_tests()
    LatencyObservatory.reset_for_tests()
    if failures:
        print(f"slo gate: {failures} failure(s)")
        return 1
    print(f"slo gate clean ({len(records)} golden queries reconciled "
          f"segments to wall with span/counter/ledger sinks agreeing; "
          f"injected whale flipped the burn-rate health rule naming "
          f"{burning}; victims' p99 >= 50% queue_wait with "
          f"compute-dominated p50; extraction overhead "
          f"{overhead['pct']:.2f}% < 5%)")
    return 0


def run_progress_gate() -> int:
    """Progress-observatory gate (obs/progress.py), one 4-session pool:

    * **Golden mix** — the serve mix replayed concurrently with tracing
      on: every finished query's live-view record must show ratio 1.0
      with partitions_done reconciling exactly to the trace's operator
      span count, a probed query must show monotone mid-flight ratios
      that actually move, the watchdog must stay quiet (anti-vacuity),
      and tracker hook overhead must stay < 5% of query wall with the
      on/off check proving the hooks are really the thing measured.
    * **Injected stall** — an armed FilterExec sleeps past
      ``watchdog.stallSeconds``: the scan must flag the query naming
      the deepest open operator, degrade /healthz, black-box exactly
      one stall record, then auto-cancel with cause=watchdog.
    * **Cancel legs** — cancels injected during compute (session
      API), queue-wait (pool API, ticket removed from the admission
      FIFO while the whale still holds budget), and remote-fetch
      (fetcher poll loop), plus a blown ``deadline_ms``: each must
      propagate the exact typed error, balance the books (no orphaned
      shuffle blocks, no stranded admission bytes, no open spans, no
      spill leaks) and produce exactly one classified bundle.
    """
    import concurrent.futures as cf
    import shutil
    import tempfile
    import threading
    import time as _time

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec.base import _wrap_execute_partition
    from spark_rapids_tpu.exec.basic import FilterExec
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.obs import bgerrors
    from spark_rapids_tpu.obs import metrics as m
    from spark_rapids_tpu.obs import postmortem as pm
    from spark_rapids_tpu.obs import progress as prog
    from spark_rapids_tpu.obs.health import DEGRADED, OK, HealthMonitor
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.obs.progress import (ProgressTracker,
                                               TpuQueryCancelled,
                                               TpuQueryDeadlineExceeded)
    from spark_rapids_tpu.obs.slo import LatencyObservatory
    from spark_rapids_tpu.shuffle import transport as tr
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
    from spark_rapids_tpu.tools.top import format_top

    failures = 0
    MetricsRegistry.reset_for_tests()
    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()
    AdmissionController.reset_for_tests()
    LatencyObservatory.reset_for_tests()
    ProgressTracker.reset_for_tests()
    bgerrors.reset()

    pmdir = tempfile.mkdtemp(prefix="progress_gate_pm_")

    n = 4000
    rng = np.random.default_rng(11)
    fact = pa.table({
        "k": pa.array(rng.integers(0, 97, n).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(97, dtype=np.int64)),
        "w": pa.array(np.arange(97, dtype=np.int64) * 10),
    })
    budget = 256 << 20
    pool = SessionPool(4, {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.tpu.memsan.enabled": "true",
        "spark.rapids.tpu.singleChipFuse": "off",
        "spark.rapids.tpu.trace.enabled": "true",
        "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes": str(budget),
        "spark.rapids.tpu.serve.admissionTimeoutMs": "60000",
        "spark.rapids.tpu.hbm.postmortem.dir": pmdir,
        "spark.rapids.tpu.hbm.postmortem.maxBundles": "500",
    })
    monitor = HealthMonitor()

    def mk_mix(s):
        fdf = s.create_dataframe(fact)
        fdf4 = s.create_dataframe(fact, num_partitions=4)
        ddf2 = s.create_dataframe(dim, num_partitions=2)
        return {
            "agg": lambda: (fdf4.group_by(col("k"))
                            .agg(F.sum(col("v")).alias("sv"),
                                 F.count("*").alias("c")).collect()),
            "join": lambda: (fdf4.join(ddf2, on="k", how="inner")
                             .group_by(col("k"))
                             .agg(F.sum(col("w")).alias("sw"))
                             .collect()),
            "sort": lambda: fdf.sort(col("k"), col("v")).collect(),
            # armed-leg query: every cancel/stall leg drives this shape
            # so the armed FilterExec sits mid-plan with 4 partitions
            "filter4": lambda: (fdf4.filter(col("v") > -10_000)
                                .group_by(col("k"))
                                .agg(F.sum(col("v")).alias("sv"))
                                .collect()),
            # exchange-free: with a warmed plan the group-by exchange's
            # map stage (and an armed FilterExec inside it) can run
            # during PLANNING, before admission — the queue-cancel
            # whale must park post-admission, so it parks here
            "filter_only": lambda: (fdf4.filter(col("v") > -10_000)
                                    .collect()),
        }

    mixes = {id(s): mk_mix(s) for s in pool._sessions}
    worklist = [name for name in ("agg", "join", "sort", "filter4")
                for _ in range(4)]

    def one(name):
        with pool.session() as s:
            mixes[id(s)][name]()

    def run_as(s, fn):
        TpuSession.bind_to_thread(s)
        try:
            return fn()
        finally:
            TpuSession.bind_to_thread(None)

    def books(session=None):
        probs = []
        blocks = TpuShuffleManager.get().catalog.num_blocks()
        if blocks:
            probs.append(f"{blocks} orphaned shuffle block(s)")
        sleaks = SpillCatalog.get().leak_report()
        if sleaks:
            probs.append(f"{len(sleaks)} spill leak(s)")
        ac = AdmissionController.get()
        if ac is not None:
            if ac.bytes_in_flight:
                probs.append(f"{ac.bytes_in_flight} admission "
                             f"byte(s) still in flight")
            if ac.queue_depth:
                probs.append(f"admission queue depth "
                             f"{ac.queue_depth}")
        if session is not None:
            trace = session.last_query_trace()
            if trace is not None and trace.open_span_count():
                probs.append(f"{trace.open_span_count()} unclosed "
                             f"span(s)")
        return probs

    def expect_bundle(before, err_name, kind, extra_kinds=()):
        docs = []
        for b in pm.list_bundles(pmdir):
            if b in before:
                continue
            try:
                docs.append(pm.load_bundle(b))
            except Exception as ex:
                return [f"bundle unparseable: {ex!r}"]
        main = [d for d in docs if d.get("kind") == kind]
        rest = sorted(d.get("kind") or "?" for d in docs
                      if d.get("kind") != kind)
        probs = []
        if len(main) != 1:
            return [f"expected exactly 1 {kind} bundle, found "
                    f"{len(main)} (all new kinds: "
                    f"{[d.get('kind') for d in docs]})"]
        if rest != sorted(extra_kinds):
            probs.append(f"unexpected extra bundle kind(s): {rest} "
                         f"(expected {sorted(extra_kinds)})")
        doc = main[0]
        if (doc.get("error") or {}).get("type") != err_name:
            probs.append(f"bundle names "
                         f"{(doc.get('error') or {}).get('type')!r}, "
                         f"expected {err_name}")
        if "cancellation" not in doc:
            probs.append("bundle lost the cancellation section")
        rendered = pm.render_postmortem(doc)
        if "cancel:" not in rendered or "observed at" not in rendered:
            probs.append("rendered post-mortem does not show the "
                         "cancel cause/checkpoint")
        return probs

    def cancel_count(cause):
        fam = m.counter("tpu_cancellations_total",
                        labelnames=("cause",))
        return sum(ch.value for lbl, ch in fam.series()
                   if lbl.get("cause") == cause)

    # -- golden mix ----------------------------------------------------------
    with cf.ThreadPoolExecutor(max_workers=4) as ex:
        list(ex.map(one, worklist))
    pool.drain(timeout=60)

    view = ProgressTracker.get().live_view()
    if view["inflight"]:
        failures += 1
        print(f"PROGRESS: {len(view['inflight'])} quer(ies) still "
              f"in flight after drain")
    if view["stalled"]:
        failures += 1
        print(f"PROGRESS: watchdog flagged the healthy golden mix "
              f"(vacuity): {view['stalled']}")
    recent = view["recent"]
    done = [r for r in recent if r["error"] is None]
    if len(done) < len(worklist):
        failures += 1
        print(f"PROGRESS: finished ring holds {len(done)} clean "
              f"records, ran {len(worklist)}")
    for r in done:
        if r["progress_ratio"] != 1.0 or r["rows"] <= 0 or \
                r["partitions_done"] <= 0:
            failures += 1
            print(f"PROGRESS: finished record not fully accounted: "
                  f"{r['tenant']}/{r['query']} "
                  f"ratio={r['progress_ratio']} rows={r['rows']} "
                  f"partitions={r['partitions_done']}")
    if not any(r.get("predicted_rows") for r in done):
        failures += 1
        print("PROGRESS: no finished record carries estimator-ledger "
              "row predictions")
    # live-view partition accounting must reconcile exactly to the
    # trace: one closed operator span per observed execute_partition
    for s in pool._sessions:
        trace = s.last_query_trace()
        mine = [r for r in recent if r["tenant"] == s._tenant]
        if trace is None or not mine:
            failures += 1
            print(f"PROGRESS: {s._tenant} left no trace/record to "
                  f"reconcile")
            continue
        spans = [sp for sp in trace.span_dicts()
                 if sp["kind"] == "operator"]
        if mine[-1]["partitions_done"] != len(spans):
            failures += 1
            print(f"PROGRESS: {s._tenant} live view counted "
                  f"{mine[-1]['partitions_done']} partition(s), the "
                  f"trace closed {len(spans)} operator span(s)")
    snap = monitor.snapshot()
    if snap["components"].get("progress", {}).get("status") != OK:
        failures += 1
        print(f"PROGRESS: /healthz progress component not OK on the "
              f"golden mix: {snap['components'].get('progress')}")
    top_out = format_top(view)
    if "in flight" not in top_out or "recent:" not in top_out:
        failures += 1
        print(f"PROGRESS: tools top render missing sections:\n"
              f"{top_out}")
    inflight_fam = [f for f in MetricsRegistry.get().families()
                    if f.name == "tpu_queries_inflight"]
    if not inflight_fam or inflight_fam[0].total() != 0:
        failures += 1
        print(f"PROGRESS: tpu_queries_inflight gauges did not return "
              f"to zero: "
              f"{inflight_fam[0].total() if inflight_fam else 'absent'}")
    if not any(f.name == "tpu_query_progress_ratio"
               for f in MetricsRegistry.get().families()):
        failures += 1
        print("PROGRESS: tpu_query_progress_ratio family never "
              "published")

    # -- probed monotone mid-flight ratios -----------------------------------
    raw_ep = FilterExec.execute_partition.__wrapped__
    orig_ep = FilterExec.execute_partition
    probe = []

    def probing_ep(self, pid, ctx):
        h = prog.current_handle()
        for b in raw_ep(self, pid, ctx):
            if h is not None:
                probe.append(h.progress_ratio())
            yield b

    FilterExec.execute_partition = _wrap_execute_partition(probing_ep)
    try:
        s0 = pool._sessions[0]
        run_as(s0, mixes[id(s0)]["filter4"])
    finally:
        FilterExec.execute_partition = orig_ep
    if len(probe) < 4:
        failures += 1
        print(f"PROGRESS: probe saw only {len(probe)} mid-flight "
              f"ratio sample(s)")
    if probe != sorted(probe):
        failures += 1
        print(f"PROGRESS: mid-flight ratios not monotone: {probe}")
    if probe and (min(probe) == max(probe) or max(probe) > 1.0):
        failures += 1
        print(f"PROGRESS: mid-flight ratios never moved (or "
              f"overshot 1.0): {probe}")

    # -- hook overhead < 5% of query wall ------------------------------------
    view = ProgressTracker.get().live_view(scan=False)
    wall_s = sum(r["elapsed_s"] for r in view["recent"])
    oh = ProgressTracker.get().overhead()
    pct = 100.0 * oh["hook_s"] / wall_s if wall_s else 100.0
    if oh["hook_s"] <= 0.0:
        failures += 1
        print("PROGRESS: hook overhead booked zero seconds over the "
              "golden mix (vacuity — the hooks are not measuring)")
    if pct >= 5.0:
        failures += 1
        print(f"PROGRESS: tracker hook overhead {pct:.2f}% of query "
              f"wall (>= 5%)")

    # on/off anti-vacuity: disabled tracking registers nothing, books
    # no overhead, and the query's result bytes do not change
    s0 = pool._sessions[0]
    ref = run_as(s0, mixes[id(s0)]["agg"])
    ring_before = len(ProgressTracker.get().live_view(
        scan=False)["recent"])
    oh_before = ProgressTracker.get().overhead()["hook_s"]
    ProgressTracker.get().configure(enabled=False)
    try:
        off = run_as(s0, mixes[id(s0)]["agg"])
    finally:
        ProgressTracker.get().configure(enabled=True)
    ring_after = len(ProgressTracker.get().live_view(
        scan=False)["recent"])
    oh_after = ProgressTracker.get().overhead()["hook_s"]
    if ring_after != ring_before or oh_after != oh_before:
        failures += 1
        print(f"PROGRESS: disabled tracker still observed the query "
              f"(ring {ring_before}->{ring_after}, hook_s "
              f"{oh_before}->{oh_after})")
    if not ref.equals(off):
        failures += 1
        print("PROGRESS: tracking on/off changed query results")

    # -- injected stall: watchdog flags, names, black-boxes, auto-cancels ----
    ProgressTracker.get().configure(stall_seconds=0.35,
                                    auto_cancel_seconds=0.9)
    started = threading.Event()

    def stuck_ep(self, pid, ctx):
        for b in raw_ep(self, pid, ctx):
            if not started.is_set():
                started.set()
                _time.sleep(1.4)  # one dead-silent stall, no touch()
            yield b

    FilterExec.execute_partition = _wrap_execute_partition(stuck_ep)
    before = set(pm.list_bundles(pmdir))
    caught = {}

    def victim_stall():
        s1 = pool._sessions[1]
        try:
            run_as(s1, mixes[id(s1)]["filter4"])
        except BaseException as ex:  # noqa: BLE001 — verified below
            caught["stall"] = ex

    th = threading.Thread(target=victim_stall)
    th.start()
    stall_rec = None
    auto_cancelled = False
    try:
        if not started.wait(30):
            failures += 1
            print("PROGRESS: armed stall never reached the operator")
        deadline = _time.monotonic() + 15
        while not auto_cancelled and _time.monotonic() < deadline:
            _time.sleep(0.05)
            for rec in ProgressTracker.get().watchdog_scan():
                if rec["tenant"] == "pool-1":
                    stall_rec = stall_rec or rec
                    auto_cancelled = auto_cancelled or \
                        rec.get("auto_cancelled", False)
        if stall_rec is not None and not auto_cancelled:
            # the stall was seen but never aged past auto-cancel
            pass
        snap = monitor.snapshot()
    finally:
        th.join(30)
        FilterExec.execute_partition = orig_ep
        ProgressTracker.get().configure(stall_seconds=30.0)
        ProgressTracker.get().auto_cancel_seconds = None
    op = (stall_rec or {}).get("deepest_open_operator")
    if stall_rec is None or not op or not str(op).endswith("Exec"):
        failures += 1
        print(f"PROGRESS: watchdog did not flag the stall naming the "
              f"deepest open operator: {stall_rec}")
    if snap["components"].get("progress", {}).get("status") != DEGRADED:
        failures += 1
        print(f"PROGRESS: /healthz did not degrade on the stalled "
              f"query: {snap['components'].get('progress')}")
    if m.counter("tpu_query_stalls_total").value() != 1:
        failures += 1
        print(f"PROGRESS: tpu_query_stalls_total counted "
              f"{m.counter('tpu_query_stalls_total').value()} "
              f"(expected exactly 1 — scans must dedup)")
    bb = bgerrors.last_error("watchdog")
    if not bb or "no progress" not in str(bb.get("message", "")):
        failures += 1
        print(f"PROGRESS: stall never reached the failure black box: "
              f"{bb}")
    err = caught.get("stall")
    if not isinstance(err, TpuQueryCancelled) or \
            getattr(err, "cause", None) != "watchdog":
        failures += 1
        print(f"PROGRESS: watchdog auto-cancel did not propagate "
              f"typed with cause=watchdog: {err!r}")
    for p in books(pool._sessions[1]):
        failures += 1
        print(f"PROGRESS [stall]: {p}")
    for p in expect_bundle(before, "TpuQueryCancelled", "cancelled",
                           extra_kinds=("background_failure",)):
        failures += 1
        print(f"PROGRESS [stall]: {p}")
    if cancel_count("watchdog") != 1:
        failures += 1
        print(f"PROGRESS: cancellations{{cause=watchdog}} = "
              f"{cancel_count('watchdog')}, expected 1")

    # -- cancel mid-compute (session API) ------------------------------------
    started2 = threading.Event()
    release2 = threading.Event()

    def slow_ep(self, pid, ctx):
        for b in raw_ep(self, pid, ctx):
            started2.set()
            release2.wait(10.0)  # held until the cancel has landed
            yield b

    FilterExec.execute_partition = _wrap_execute_partition(slow_ep)
    before = set(pm.list_bundles(pmdir))
    s2 = pool._sessions[2]

    def victim_compute():
        try:
            run_as(s2, mixes[id(s2)]["filter4"])
        except BaseException as ex:  # noqa: BLE001 — verified below
            caught["compute"] = ex

    th = threading.Thread(target=victim_compute)
    th.start()
    try:
        if not started2.wait(30):
            failures += 1
            print("PROGRESS: compute-cancel query never reached the "
                  "armed operator")
        if not s2.cancel("q0"):
            failures += 1
            print("PROGRESS: session.cancel found no in-flight query")
        release2.set()
    finally:
        th.join(30)
        FilterExec.execute_partition = orig_ep
    err = caught.get("compute")
    if not isinstance(err, TpuQueryCancelled) or \
            getattr(err, "cause", None) != "client" or \
            getattr(err, "checkpoint", None) not in ("compute",
                                                     "partition"):
        failures += 1
        print(f"PROGRESS: mid-compute cancel did not propagate typed "
              f"at a compute checkpoint: {err!r}")
    for p in books(s2):
        failures += 1
        print(f"PROGRESS [compute-cancel]: {p}")
    for p in expect_bundle(before, "TpuQueryCancelled", "cancelled"):
        failures += 1
        print(f"PROGRESS [compute-cancel]: {p}")

    # -- cancel while queued for admission (pool API) ------------------------
    orig_bound = TpuSession._static_peak_bound

    def fixed_bound(self, final_plan, conf, budget=None):
        # whale 200M + victim 100M oversubscribes 256M: the victim
        # queues IFF the whale is in flight
        return (200 << 20) if getattr(self, "_tenant", "") == "pool-0" \
            else (100 << 20)

    h_started = threading.Event()
    hold = threading.Event()

    def holding_ep(self, pid, ctx):
        s = TpuSession.active()
        if getattr(s, "_tenant", "") == "pool-0" and \
                not h_started.is_set():
            h_started.set()
            hold.wait(20.0)  # holds 200M of admitted budget
        for b in raw_ep(self, pid, ctx):
            yield b

    FilterExec.execute_partition = _wrap_execute_partition(holding_ep)
    TpuSession._static_peak_bound = fixed_bound
    before = set(pm.list_bundles(pmdir))
    whale, victim = pool._sessions[0], pool._sessions[3]
    whale_res = {}

    def run_whale():
        try:
            whale_res["table"] = run_as(
                whale, mixes[id(whale)]["filter_only"])
        except BaseException as ex:  # noqa: BLE001 — verified below
            whale_res["err"] = ex

    def victim_queue():
        try:
            run_as(victim, mixes[id(victim)]["filter4"])
        except BaseException as ex:  # noqa: BLE001 — verified below
            caught["queue"] = ex

    th_w = threading.Thread(target=run_whale)
    th_v = threading.Thread(target=victim_queue)
    th_w.start()
    try:
        if not h_started.wait(30):
            failures += 1
            print("PROGRESS: whale never started holding admission")
        th_v.start()
        ac = AdmissionController.get()
        deadline = _time.monotonic() + 15
        while ac.queue_depth < 1 and _time.monotonic() < deadline:
            _time.sleep(0.01)
        if ac.queue_depth < 1:
            failures += 1
            print("PROGRESS: victim never queued behind the whale")
        if not pool.cancel("pool-3", "q0"):
            failures += 1
            print("PROGRESS: pool.cancel found no in-flight query")
        # the cancelled ticket must leave the FIFO while the whale
        # still holds the budget — cancel-while-queued, not timeout
        deadline = _time.monotonic() + 5
        while ac.queue_depth and _time.monotonic() < deadline:
            _time.sleep(0.01)
        if ac.queue_depth:
            failures += 1
            print(f"PROGRESS: cancelled ticket still queued "
                  f"(depth {ac.queue_depth})")
    finally:
        hold.set()
        th_v.join(30)
        th_w.join(30)
        FilterExec.execute_partition = orig_ep
        TpuSession._static_peak_bound = orig_bound
    err = caught.get("queue")
    if not isinstance(err, TpuQueryCancelled) or \
            getattr(err, "checkpoint", None) != "queue-wait":
        failures += 1
        print(f"PROGRESS: queued cancel did not propagate typed at "
              f"the queue-wait checkpoint: {err!r}")
    if "table" not in whale_res:
        failures += 1
        print(f"PROGRESS: the whale did not survive the victim's "
              f"cancel: {whale_res.get('err')!r}")
    for p in books(victim):
        failures += 1
        print(f"PROGRESS [queue-cancel]: {p}")
    for p in expect_bundle(before, "TpuQueryCancelled", "cancelled"):
        failures += 1
        print(f"PROGRESS [queue-cancel]: {p}")

    # -- blown deadline_ms ---------------------------------------------------
    before = set(pm.list_bundles(pmdir))
    s1 = pool._sessions[1]

    def run_deadline():
        lp = (s1.create_dataframe(fact, num_partitions=4)
              .group_by(col("k")).agg(F.sum(col("v")).alias("sv"))
              ._lp)
        return s1.execute(lp, deadline_ms=1)

    err = None
    try:
        run_as(s1, run_deadline)
    except BaseException as ex:  # noqa: BLE001 — verified below
        err = ex
    if not isinstance(err, TpuQueryDeadlineExceeded):
        failures += 1
        print(f"PROGRESS: deadline_ms=1 did not raise "
              f"TpuQueryDeadlineExceeded: {err!r}")
    for p in books(s1):
        failures += 1
        print(f"PROGRESS [deadline]: {p}")
    for p in expect_bundle(before, "TpuQueryDeadlineExceeded",
                           "deadline_exceeded"):
        failures += 1
        print(f"PROGRESS [deadline]: {p}")
    if cancel_count("deadline") != 1:
        failures += 1
        print(f"PROGRESS: cancellations{{cause=deadline}} = "
              f"{cancel_count('deadline')}, expected 1")

    # -- cancel during remote fetch ------------------------------------------
    unblock = threading.Event()

    class _MetaTx:
        def __init__(self, metas):
            self.metas = metas

        def wait(self, timeout=None):
            return self.metas

    class _SlowTx:
        def wait(self, timeout=None):
            unblock.wait(min(timeout or 3.0, 3.0))
            return None

    class _SlowClient:
        def fetch_metadata(self, sid, rid, ctx=None):
            return _MetaTx([((sid, 0, rid, 0), None)])

        def fetch_block(self, sid, mid, rid, idx, xp=None, ctx=None):
            return _SlowTx()

    before = set(pm.list_bundles(pmdir))
    handle = ProgressTracker.get().begin_query("qfetch", tenant="gate")
    prog.bind_to_thread(handle)
    timer = threading.Timer(
        0.4, lambda: ProgressTracker.get().cancel("qfetch",
                                                  tenant="gate"))
    timer.start()
    err = None
    try:
        fetcher = tr.AsyncBlockFetcher(_SlowClient(), 9, 0,
                                       timeout=5.0)
        list(fetcher.blocks())
    except BaseException as ex:  # noqa: BLE001 — verified below
        err = ex
    finally:
        timer.cancel()
        unblock.set()
        ProgressTracker.get().end_query(handle, err)
        prog.bind_to_thread(None)
    if not isinstance(err, TpuQueryCancelled) or \
            getattr(err, "checkpoint", None) != "remote-fetch":
        failures += 1
        print(f"PROGRESS: mid-fetch cancel did not propagate typed at "
              f"the remote-fetch checkpoint: {err!r}")
    else:
        # no session owns the fetcher: the serving harness black-boxes
        pm.dump_postmortem(pmdir, err, tenant="gate", max_bundles=500)
        for p in expect_bundle(before, "TpuQueryCancelled",
                               "cancelled"):
            failures += 1
            print(f"PROGRESS [fetch-cancel]: {p}")
    for p in books():
        failures += 1
        print(f"PROGRESS [fetch-cancel]: {p}")
    if cancel_count("client") != 3:
        failures += 1
        print(f"PROGRESS: cancellations{{cause=client}} = "
              f"{cancel_count('client')}, expected 3 (compute, "
              f"queue-wait, remote-fetch)")

    # -- wind-down -----------------------------------------------------------
    inflight_fam = [f for f in MetricsRegistry.get().families()
                    if f.name == "tpu_queries_inflight"]
    if not inflight_fam or inflight_fam[0].total() != 0:
        failures += 1
        print(f"PROGRESS: inflight gauges dirty after the cancel "
              f"legs: "
              f"{inflight_fam[0].total() if inflight_fam else 'absent'}")
    pool.drain(timeout=60)
    pool.close()
    shutil.rmtree(pmdir, ignore_errors=True)
    bgerrors.reset()
    MetricsRegistry.reset_for_tests()
    AdmissionController.reset_for_tests()
    LatencyObservatory.reset_for_tests()
    ProgressTracker.reset_for_tests()
    if failures:
        print(f"progress gate: {failures} failure(s)")
        return 1
    print(f"progress gate clean ({len(done)} golden queries at ratio "
          f"1.0 reconciling partitions to operator spans; probed "
          f"ratios monotone {probe[0]:.2f}->{probe[-1]:.2f}; injected "
          f"stall flagged {op} then auto-cancelled; compute/"
          f"queue-wait/remote-fetch/deadline cancels all typed with "
          f"balanced books and one bundle each; hook overhead "
          f"{pct:.3f}% < 5%)")
    return 0


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--interp" in args:
        return run_interp_gate()
    if "--memsan" in args:
        return run_memsan_gate()
    if "--obs" in args:
        return run_obs_gate()
    if "--regress" in args:
        return run_regress_gate()
    if "--metrics" in args:
        return run_metrics_gate()
    if "--jit" in args:
        return run_jit_gate()
    if "--shuffle" in args:
        return run_shuffle_gate()
    if "--serve" in args:
        return run_serve_gate()
    if "--csan" in args:
        return run_csan_gate()
    if "--feedback" in args:
        return run_feedback_gate()
    if "--fleet" in args:
        return run_fleet_gate()
    if "--hbm" in args:
        return run_hbm_gate()
    if "--faults" in args:
        return run_faults_gate()
    if "--dsan" in args:
        return run_dsan_gate()
    if "--hlo" in args:
        return run_hlo_gate()
    if "--slo" in args:
        return run_slo_gate()
    if "--progress" in args:
        return run_progress_gate()
    from spark_rapids_tpu.tools.__main__ import main as tools_main
    cli = ["lint", "--repo", "--baseline", BASELINE]
    if "--update-baseline" in args:
        cli.append("--update-baseline")
    return tools_main(cli)


if __name__ == "__main__":
    sys.exit(main())
