#!/usr/bin/env python
"""Headline benchmark: mixed SQL operator suite, TPU engine vs CPU engine.

Workloads mirror the reference's best-suited shapes (docs/FAQ.md:107-116:
high-cardinality group-by / join / sort, windows):

  q1 aggregate: scan -> filter -> GROUP BY k SUM/AVG/COUNT   (100k groups)
  q2 join:      shuffled hash join on a 100k-key dimension, then agg
  q3 sort:      global sort by two keys
  q4 window:    row_number + running sum over partitions

Prints ONE JSON line: value = total rows processed per second through
the TPU engine across the suite; vs_baseline = CPU-engine time / TPU
time on the same host (the stand-in for Spark-CPU until a cluster
baseline exists).
"""

import json
import sys
import time

import numpy as np
import pyarrow as pa


def make_tables(n_rows: int):
    rng = np.random.default_rng(42)
    fact = pa.table({
        "k": pa.array(rng.integers(0, 100_000, n_rows).astype(np.int64)),
        "v": pa.array(rng.integers(-(10**6), 10**6, n_rows).astype(np.int64)),
        "f": pa.array(rng.random(n_rows)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(100_000, dtype=np.int64)),
        "w": pa.array(rng.random(100_000)),
    })
    return fact, dim


def queries(session, fact, dim):
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.expr.window import WindowBuilder

    fdf = session.create_dataframe(fact)
    ddf = session.create_dataframe(dim)

    def q1():
        return (fdf.filter(col("v") > -(10**6) // 2)
                .group_by(col("k"))
                .agg(F.sum(col("v")).alias("sv"),
                     F.avg(col("f")).alias("af"),
                     F.count("*").alias("c"))
                .collect())

    def q2():
        return (fdf.join(ddf, on="k", how="inner")
                .group_by(col("k"))
                .agg(F.sum(col("w")).alias("sw"))
                .collect())

    def q3():
        return fdf.sort(col("k"), col("v")).collect()

    def q4():
        w = WindowBuilder().partition_by(col("k")).order_by(col("v"))
        return (fdf.select(col("k"), col("v"),
                           F.row_number().over(w).alias("rn"),
                           F.sum(col("v")).over(w).alias("rs"))
                .collect())

    return [("agg", q1), ("join", q2), ("sort", q3), ("window", q4)]


def time_engine(enabled: bool, fact, dim, repeats: int = 2):
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    enabled).get_or_create()
    qs = queries(s, fact, dim)
    per_query = {}
    for name, q in qs:
        q()  # warmup (compile)
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = q()
            best = min(best, time.perf_counter() - t0)
        assert out.num_rows > 0
        per_query[name] = best
    return per_query


def main():
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    fact, dim = make_tables(n_rows)
    tpu = time_engine(True, fact, dim)
    cpu = time_engine(False, fact, dim)
    tpu_total = sum(tpu.values())
    cpu_total = sum(cpu.values())
    # rows processed: each of the 4 queries consumes the fact table once
    value = (4 * n_rows) / tpu_total
    print(json.dumps({
        "metric": "sql_suite_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(cpu_total / tpu_total, 3),
        "detail": {k: {"tpu_s": round(tpu[k], 3),
                       "cpu_s": round(cpu[k], 3)} for k in tpu},
    }))


if __name__ == "__main__":
    main()
