#!/usr/bin/env python
"""Headline benchmark: mixed SQL operator suite, TPU engine vs CPU engine.

Workloads mirror the reference's best-suited shapes (docs/FAQ.md:107-116:
high-cardinality group-by / join / sort, windows, parquet IO):

  q1 agg:     scan -> filter -> GROUP BY k SUM/AVG/COUNT   (100k groups)
  q2 join:    shuffled hash join on a 100k-key dimension, then agg
  q3 sort:    global sort by two keys
  q4 window:  row_number + running sum over partitions
  q5 parquet: multi-file parquet scan -> filter -> aggregate
  q6 shjoin:  multi-partition shuffle join (broadcast disabled), the
              multi-batch host-exchange path
  q7 write:   scan -> parquet write (columnar write path)

Plus one out-of-loop measurement: `big_join`, a join whose build side
deliberately exceeds the JVM bridge's retired 256 MB driver-collect cap
(`spark.tpu.bridge.maxBuildSideBytes`), executed through the
spill-backed shuffled path under the memsan ledger (--skip-big-join to
omit; it costs one full build-side shuffle).

Prints ONE JSON line: value = total rows processed per second through
the TPU engine across the suite; vs_baseline = CPU-engine time / TPU
time on the same host (the stand-in for Spark-CPU until a cluster
baseline exists).
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


def make_tables(n_rows: int):
    rng = np.random.default_rng(42)
    fact = pa.table({
        "k": pa.array(rng.integers(0, 100_000, n_rows).astype(np.int64)),
        "v": pa.array(rng.integers(-(10**6), 10**6, n_rows).astype(np.int64)),
        "f": pa.array(rng.random(n_rows)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(100_000, dtype=np.int64)),
        "w": pa.array(rng.random(100_000)),
    })
    return fact, dim


def write_parquet_input(fact: pa.Table, root: str, n_files: int = 4) -> str:
    """Multi-file parquet dataset for the scan benchmarks."""
    path = os.path.join(root, "fact_pq")
    os.makedirs(path, exist_ok=True)
    per = -(-fact.num_rows // n_files)
    for i in range(n_files):
        pq.write_table(fact.slice(i * per, per),
                       os.path.join(path, f"part-{i:02d}.parquet"))
    return path


def queries(session, fact, dim, pq_path, out_root):
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.expr.window import WindowBuilder

    fdf = session.create_dataframe(fact)
    ddf = session.create_dataframe(dim)
    # multi-partition variants exercise the shuffle paths
    fdf4 = session.create_dataframe(fact, num_partitions=4)
    ddf2 = session.create_dataframe(dim, num_partitions=2)

    def q1_agg():
        return (fdf.filter(col("v") > -(10**6) // 2)
                .group_by(col("k"))
                .agg(F.sum(col("v")).alias("sv"),
                     F.avg(col("f")).alias("af"),
                     F.count("*").alias("c"))
                .collect())

    def q2_join():
        return (fdf.join(ddf, on="k", how="inner")
                .group_by(col("k"))
                .agg(F.sum(col("w")).alias("sw"))
                .collect())

    def q3_sort():
        return fdf.sort(col("k"), col("v")).collect()

    def q4_window():
        w = WindowBuilder().partition_by(col("k")).order_by(col("v"))
        return (fdf.select(col("k"), col("v"),
                           F.row_number().over(w).alias("rn"),
                           F.sum(col("v")).over(w).alias("rs"))
                .collect())

    def q5_parquet():
        return (session.read.parquet(pq_path)
                .filter(col("f") < 0.5)
                .group_by(col("k"))
                .agg(F.sum(col("v")).alias("sv"),
                     F.count("*").alias("c"))
                .collect())

    def q6_shuffle_join():
        return (fdf4.join(ddf2, on="k", how="inner")
                .group_by(col("k"))
                .agg(F.sum(col("w")).alias("sw"))
                .collect())

    def q7_write():
        out = os.path.join(out_root, f"bench_out_{time.time_ns()}")
        fdf.filter(col("v") > 0).write.mode("overwrite").parquet(out)
        # row verification reads only footers; a full read-back would
        # charge scan cost to the write benchmark
        n = sum(pq.ParquetFile(os.path.join(out, f)).metadata.num_rows
                for f in os.listdir(out) if f.endswith(".parquet"))
        shutil.rmtree(out, ignore_errors=True)

        class R:  # uniform "has rows" result contract
            num_rows = n
        return R

    return [("agg", q1_agg), ("join", q2_join), ("sort", q3_sort),
            ("window", q4_window), ("parquet", q5_parquet),
            ("shuffle_join", q6_shuffle_join), ("write", q7_write)]


def time_engine(enabled: bool, fact, dim, pq_path, out_root,
                repeats: int = 3, trace: bool = False,
                eventlog_dir: str = None, metrics: bool = None,
                hbm: bool = None):
    from spark_rapids_tpu.api.session import TpuSession
    extra = {}
    if enabled and os.environ.get("BENCH_TRANSPORT"):
        extra["spark.rapids.shuffle.transport"] = \
            os.environ["BENCH_TRANSPORT"]
    if trace:
        extra["spark.rapids.tpu.trace.enabled"] = True
    if eventlog_dir:
        extra["spark.rapids.tpu.eventLog.dir"] = eventlog_dir
    if metrics is not None:
        extra["spark.rapids.tpu.metrics.enabled"] = metrics
    if hbm is not None:
        extra["spark.rapids.tpu.hbm.timeline.enabled"] = hbm
    b = TpuSession.builder().config("spark.rapids.sql.enabled", enabled)
    for k, v in extra.items():
        b = b.config(k, v)
    s = b.get_or_create()
    qs = queries(s, fact, dim, pq_path, out_root)
    per_query = {}
    compile_s = {}
    for name, q in qs:
        t0 = time.perf_counter()
        q()  # warmup; any uncached compiles happen here
        first = time.perf_counter() - t0
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = q()
            times.append(time.perf_counter() - t0)
        assert out.num_rows > 0
        # median: best-of flattered the number, mean punishes one-off
        # host hiccups; median is the honest middle
        warm = sorted(times)[len(times) // 2]
        per_query[name] = warm
        # cold-query overhead: first run minus warm = compile + trace
        # cost a NOVEL query shape pays (persistent-cache hits shrink it)
        compile_s[name] = max(first - warm, 0.0)
    return per_query, compile_s


# a v5e chip moves ~819 GB/s from HBM; the suite's per-query input is the
# fact table — bytes/s against that bound shows how far the engine sits
# from the hardware, not just from the host CPU baseline
_HBM_BYTES_PER_S = 819e9


_COLD_SCRIPT = r"""
import sys, time
import numpy as np
import pyarrow as pa
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col

n = int(sys.argv[1])
cache_dir = sys.argv[2]
rng = np.random.default_rng(7)
# a shape the suite never compiles: different column set and dtypes
tb = pa.table({
    "g":  pa.array(rng.integers(0, 4321, n).astype(np.int64)),
    "a":  pa.array(rng.integers(-500, 500, n).astype(np.int32)),
    "b":  pa.array(rng.random(n)),
})
s = (TpuSession.builder()
     .config("spark.rapids.sql.enabled", True)
     .config("spark.rapids.tpu.compilationCache.dir", cache_dir)
     .get_or_create())
df = s.create_dataframe(tb)
t0 = time.perf_counter()
out = (df.filter(col("a") > -250)
       .group_by(col("g"))
       .agg(F.sum(col("a")).alias("sa"), F.avg(col("b")).alias("ab"),
            F.count("*").alias("c"))
       .collect())
assert out.num_rows > 0
print("COLD_SECONDS=%.2f" % (time.perf_counter() - t0))
"""


def measure_cache_cold(n_rows: int) -> float:
    """Wall seconds for a NOVEL filter+group-by in a fresh process with
    an EMPTY persistent compile cache — the first-query cost a new
    deployment actually pays (warm `compile_s` numbers ride the
    populated cache).  The cold-cache probe auto-selects the
    compile-lean sort kernels (spark.rapids.tpu.sort.compileLean)."""
    import subprocess
    cache_dir = tempfile.mkdtemp(prefix="tpu_cold_cache_")
    try:
        r = subprocess.run(
            [sys.executable, "-c", _COLD_SCRIPT, str(n_rows), cache_dir],
            capture_output=True, text=True, timeout=300)
        for line in r.stdout.splitlines():
            if line.startswith("COLD_SECONDS="):
                return float(line.split("=")[1])
        return -1.0
    except Exception:
        return -1.0
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


_SUITE_NAMES = ("agg", "join", "sort", "window", "parquet",
                "shuffle_join", "write")


# the JVM bridge's retired driver-collect ceiling: shuffled/SMJ joins
# whose build side exceeded this were REJECTED outright before the
# spill-backed shuffle catalog existed.  big_join deliberately builds
# past it so the retired cap has a measured after.
_OLD_BUILD_CAP_BYTES = 256 * 1024 * 1024


def measure_big_join(cap_bytes: int = _OLD_BUILD_CAP_BYTES) -> dict:
    """One end-to-end join whose BUILD side exceeds the old 256 MB
    bridge cap (`spark.tpu.bridge.maxBuildSideBytes`), executed through
    the co-partitioned spill-backed shuffle path — the workload the
    bridge used to reject.  Runs ONCE (~the cost of shuffling the full
    build side through the catalog), outside the repeated suite loop.

    A wide FK->PK dimension keeps the byte size past the cap without a
    row-explosion: 33 int64 columns, unique keys, so the join output is
    one row per probe row.  The LEFT join pins the oversized dimension
    as the build side (an inner join would flip the smaller fact into
    build position and broadcast it).  singleChipFuse is off so the
    single-device host still plans the real ShuffledHashJoinExec over
    co-clustered catalog partitions instead of fusing the exchanges
    away.  The memsan shadow ledger rides the run: peak device bytes
    are measured, and a dirty ledger (leaked shuffle blocks, lifecycle
    violations) fails the measurement rather than reporting around it."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.memory import memsan

    ncols = 32
    row_bytes = 8 * (1 + ncols)
    build_rows = cap_bytes // row_bytes + 1      # first size past the cap
    cols = {"k": pa.array(np.arange(build_rows, dtype=np.int64))}
    base = np.arange(build_rows, dtype=np.int64)
    for i in range(ncols):
        cols[f"w{i}"] = pa.array(base + i)
    dim = pa.table(cols)
    assert dim.nbytes > cap_bytes
    rng = np.random.default_rng(42)
    probe_rows = 1_000_000
    fact = pa.table({
        "k": pa.array(rng.integers(0, build_rows,
                                   probe_rows).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000,
                                   probe_rows).astype(np.int64))})
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.singleChipFuse", "off")
         .get_or_create())
    fdf = s.create_dataframe(fact, num_partitions=4)
    ddf = s.create_dataframe(dim, num_partitions=4)
    t0 = time.perf_counter()
    with memsan.installed() as ledger:
        out = (fdf.join(ddf, on="k", how="left")
               .group_by(col("k"))
               .agg(F.sum(col("w0")).alias("sw"))
               .collect())
    wall = time.perf_counter() - t0
    expect_groups = len(np.unique(fact.column("k").to_numpy()))
    assert out.num_rows == expect_groups, \
        f"big_join lost rows: {out.num_rows} != {expect_groups}"
    kinds = []
    s.last_plan.foreach(lambda e: kinds.append(type(e).__name__))
    shuffled = "ShuffledHashJoinExec" in kinds and \
        "BroadcastHashJoinExec" not in kinds
    assert shuffled, f"big_join did not take the shuffled path: {kinds}"
    try:
        ledger.assert_clean()
        clean = True
    except Exception:
        clean = False
    rows_in = probe_rows + build_rows
    return {
        "build_side_bytes": dim.nbytes,
        "old_cap_bytes": cap_bytes,
        "probe_rows": probe_rows,
        "build_rows": build_rows,
        "wall_s": round(wall, 2),
        "rows_per_s": round(rows_in / wall, 1),
        "output_rows": out.num_rows,
        "peak_device_bytes": int(ledger.peak_device_bytes),
        "shuffled_plan": shuffled,
        "memsan_clean": clean,
    }


def run_one_suite(name: str, n_rows: int, cache_dir: str,
                  ledger_dir: str = "", accuracy_history: str = "",
                  feedback: bool = False) -> None:
    """Internal mode (--one-suite): run ONE suite query in THIS fresh
    process against the given persistent compile cache dir, and print
    the compile observatory's totals.  The --compile-report driver runs
    this twice per suite — a cold subprocess (empty cache) then a warm
    one (populated cache) — so cold/warm compile cost and the distinct-
    program count are measured per suite instead of today's single
    lumped first-run-minus-warm `compile_s` guess.

    With `accuracy_history` set (the --accuracy driver), the session
    also runs traced against that regression HistoryDir, so the
    estimator ledger records predicted-vs-actual for every operator —
    and `feedback=True` (the warm arm) blends the prior cold arm's
    recorded actuals back into the estimates first.  SUITE_JSON then
    carries this process's mean relative row/byte estimate error."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.obs.compileprof import CompileObservatory
    fact, dim = make_tables(n_rows)
    root = tempfile.mkdtemp(prefix="tpu_suite_")
    try:
        pq_path = write_parquet_input(fact, root)
        b = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", True)
             .config("spark.rapids.tpu.jit.persistentCacheDir",
                     cache_dir)
             # pin the sort kernel structure: 'auto' flips lean->
             # throughput between the cold and warm process (by
             # design), which would make cold/warm compile distinct
             # program SETS instead of the same set re-measured
             .config("spark.rapids.tpu.sort.compileLean", "off"))
        if ledger_dir:
            b = b.config("spark.rapids.tpu.compile.ledgerDir",
                         ledger_dir)
        if accuracy_history:
            b = (b.config("spark.rapids.tpu.regress.historyDir",
                          accuracy_history)
                 .config("spark.rapids.tpu.trace.enabled", True)
                 .config("spark.rapids.tpu.feedback.enabled",
                         feedback))
        s = b.get_or_create()
        qs = dict(queries(s, fact, dim, pq_path, root))
        t0 = time.perf_counter()
        out = qs[name]()
        wall = time.perf_counter() - t0
        assert out.num_rows > 0
        snap = CompileObservatory.get().snapshot()
        from spark_rapids_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.registry()
        disk_hits = reg.counter(
            "tpu_jit_persistent_cache_hits_total").value()
        disk_misses = reg.counter(
            "tpu_jit_persistent_cache_misses_total").value()
        payload = {
            "suite": name, "wall_s": round(wall, 3),
            "compile_s": snap["compile_seconds_total"],
            "trace_s": snap["trace_seconds_total"],
            "build_total_s": round(snap["compile_seconds_total"] +
                                   snap["trace_seconds_total"], 3),
            "distinct_programs": snap["distinct_programs"],
            "builds": snap["builds"],
            "prewarm_hits": snap["prewarm_hits"],
            "prewarm_s": snap["prewarm_seconds"],
            "disk_hits": disk_hits, "disk_misses": disk_misses}
        # tpuxsan padding-waste books (obs/tracer.py): counters only
        # fill when tracing ran, so a no-trace suite honestly reports 0
        pad_fam = reg.counter("tpu_pad_waste_bytes_total",
                              labelnames=("exec",))
        tot_fam = reg.counter("tpu_operator_bytes_total",
                              labelnames=("exec",))
        pad = sum(ch.value for _, ch in pad_fam.series())
        tot = sum(ch.value for _, ch in tot_fam.series())
        payload["pad_waste_bytes"] = int(pad)
        payload["pad_waste_ratio"] = round(pad / tot, 4) if tot else 0.0
        if accuracy_history:
            from spark_rapids_tpu.obs.estimator import EstimatorLedger
            est = EstimatorLedger.get().snapshot()
            payload.update({
                "est_observations": est["observations"],
                "mean_rows_err": est["mean_rows_err"],
                "mean_bytes_err": est["mean_bytes_err"],
                "calibration_score": est["calibration_score"]})
        print("SUITE_JSON=" + json.dumps(payload))
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _one_suite_subprocess(name: str, n_rows: int, cache_dir: str,
                          ledger_dir: str = "",
                          accuracy_history: str = "",
                          feedback: bool = False):
    """One fresh-process suite run; returns the parsed SUITE_JSON."""
    import subprocess
    env = dict(os.environ)
    env.pop("SPARK_RAPIDS_TPU_DISABLE_COMPILE_CACHE", None)
    cmd = [sys.executable, os.path.abspath(__file__), str(n_rows),
           f"--one-suite={name}", f"--cache-dir={cache_dir}"]
    if ledger_dir:
        cmd.append(f"--ledger-dir={ledger_dir}")
    if accuracy_history:
        cmd.append(f"--accuracy-history={accuracy_history}")
        if feedback:
            cmd.append("--with-feedback")
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=900, env=env)
    for line in r.stdout.splitlines():
        if line.startswith("SUITE_JSON="):
            return json.loads(line[len("SUITE_JSON="):])
    raise RuntimeError(f"suite {name} subprocess failed "
                       f"rc={r.returncode}:\n{r.stdout}\n{r.stderr}")


def measure_compile_report(n_rows: int) -> dict:
    """Per-suite cold/warm compile attribution: each suite runs in a
    cold subprocess (fresh persistent cache) then a warm one (same
    cache dir + compile ledger dir).  compile_cold_s is the full
    trace+lower+compile wall a new deployment pays; compile_warm_s is
    what the warm-start tier leaves at QUERY time — with the cold run's
    recipes prewarmed at session init, it should be ~0 (zero builds),
    with the re-trace cost reported separately as warm_prewarm_s."""
    report = {}
    for name in _SUITE_NAMES:
        cache_dir = tempfile.mkdtemp(prefix=f"tpu_ccache_{name}_")
        ledger_dir = tempfile.mkdtemp(prefix=f"tpu_ledger_{name}_")
        try:
            cold = _one_suite_subprocess(name, n_rows, cache_dir,
                                         ledger_dir)
            warm = _one_suite_subprocess(name, n_rows, cache_dir,
                                         ledger_dir)
            report[name] = {
                "compile_cold_s": round(cold["build_total_s"], 2),
                "compile_warm_s": round(warm["build_total_s"], 2),
                "distinct_programs": cold["distinct_programs"],
                "warm_builds": warm["builds"],
                "warm_prewarm_hits": warm["prewarm_hits"],
                "warm_prewarm_s": round(warm["prewarm_s"], 2),
                "warm_disk_hits": warm["disk_hits"],
            }
        finally:
            shutil.rmtree(cache_dir, ignore_errors=True)
            shutil.rmtree(ledger_dir, ignore_errors=True)
    return report


def measure_accuracy(n_rows: int) -> dict:
    """Per-suite estimator accuracy, cold model vs warm ledger: each
    suite runs in a cold subprocess (fresh regression HistoryDir — the
    static cost model alone) and then a warm one (same HistoryDir with
    ``spark.rapids.tpu.feedback.enabled``, so the session loads the
    cold arm's estimator ledger and blends its recorded actuals into
    the estimates).  The per-arm mean relative row/byte estimate error
    comes straight off each subprocess's EstimatorLedger snapshot —
    the cold->warm delta is the measured value of closing the
    predict->execute loop, per workload shape."""
    report = {}
    for name in _SUITE_NAMES:
        hist_dir = tempfile.mkdtemp(prefix=f"tpu_acc_hist_{name}_")
        cache_dir = tempfile.mkdtemp(prefix=f"tpu_acc_cache_{name}_")
        try:
            cold = _one_suite_subprocess(name, n_rows, cache_dir,
                                         accuracy_history=hist_dir)
            warm = _one_suite_subprocess(name, n_rows, cache_dir,
                                         accuracy_history=hist_dir,
                                         feedback=True)
            report[name] = {
                "rows_err_cold": cold["mean_rows_err"],
                "rows_err_warm": warm["mean_rows_err"],
                "bytes_err_cold": cold["mean_bytes_err"],
                "bytes_err_warm": warm["mean_bytes_err"],
                "est_observations": cold["est_observations"],
                "calibration_cold": cold["calibration_score"],
                "calibration_warm": warm["calibration_score"],
            }
        finally:
            shutil.rmtree(hist_dir, ignore_errors=True)
            shutil.rmtree(cache_dir, ignore_errors=True)
    return report


def time_pyspark(fact, dim, pq_path, out_root, repeats: int = 3):
    """The same 7 queries on local-mode Spark-CPU — the reference's true
    comparison target (FAQ.md's 3-7x bar).  Returns per-query medians,
    or None when pyspark is not importable (the hermetic engine
    environment ships none; CI environments with pyspark report it)."""
    try:
        from pyspark.sql import SparkSession, functions as SF
        from pyspark.sql.window import Window as SW
    except ImportError:
        return None
    spark = (SparkSession.builder.master("local[*]")
             .config("spark.sql.shuffle.partitions", "4")
             .config("spark.ui.enabled", "false")
             .appName("bench-baseline").getOrCreate())
    fdf = spark.createDataFrame(fact.to_pandas())
    ddf = spark.createDataFrame(dim.to_pandas())
    fdf.cache().count()
    ddf.cache().count()

    def q1():
        return (fdf.filter(SF.col("v") > -(10**6) // 2).groupBy("k")
                .agg(SF.sum("v"), SF.avg("f"), SF.count("*")).collect())

    def q2():
        return (fdf.join(ddf, on="k").groupBy("k")
                .agg(SF.sum("w")).collect())

    def q3():
        return fdf.orderBy("k", "v").collect()

    def q4():
        w = SW.partitionBy("k").orderBy("v")
        return fdf.select("k", "v", SF.row_number().over(w),
                          SF.sum("v").over(w)).collect()

    def q5():
        return (spark.read.parquet(pq_path).filter(SF.col("f") < 0.5)
                .groupBy("k").agg(SF.sum("v"), SF.count("*")).collect())

    def q6():
        return (fdf.repartition(4, "k").join(ddf.repartition(2, "k"),
                                             on="k")
                .groupBy("k").agg(SF.sum("w")).collect())

    def q7():
        out = os.path.join(out_root, f"spark_out_{time.time_ns()}")
        fdf.filter(SF.col("v") > 0).write.mode("overwrite").parquet(out)
        shutil.rmtree(out, ignore_errors=True)

    names = ["agg", "join", "sort", "window", "parquet", "shuffle_join",
             "write"]
    out = {}
    for name, q in zip(names, (q1, q2, q3, q4, q5, q6, q7)):
        q()
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            q()
            times.append(time.perf_counter() - t0)
        out[name] = sorted(times)[len(times) // 2]
    spark.stop()
    return out


def _device_reachable(timeout_s: float = 180.0):
    """One tiny round trip with a hard deadline: a dead accelerator
    tunnel must produce an honest error line, not a hung benchmark.
    Returns (ok, error_string)."""
    import threading
    ok = []
    err = []

    def probe():
        try:
            import jax
            import jax.numpy as jnp
            import numpy as _np
            _np.asarray(jnp.arange(4) + 1)
            ok.append(True)
        except Exception as ex:
            err.append(repr(ex))

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if ok:
        return True, None
    if err:
        return False, f"device probe failed: {err[0]}"
    return False, f"device probe timed out after {timeout_s:g}s"


def measure_trace_overhead(fact, dim, pq_path, out_root) -> float:
    """Flight-recorder overhead guard: the suite with tracing on vs off
    (same session config otherwise).  Returns overhead as a percentage
    of the untraced total — the observability acceptance bar is <5% on
    these golden queries (tracing is per-partition spans + deferred
    scalars, never a hot-path sync, so the budget holds with room)."""
    plain, _ = time_engine(True, fact, dim, pq_path, out_root)
    traced, _ = time_engine(True, fact, dim, pq_path, out_root,
                            trace=True)
    base = sum(plain.values())
    return 100.0 * (sum(traced.values()) - base) / base


def measure_metrics_overhead(fact, dim, pq_path, out_root) -> float:
    """Continuous-metrics overhead guard: the suite with the registry
    feeding vs fully disabled.  The acceptance bar is <2% — every hook
    is one dict lookup + one locked integer add, nothing touches the
    device, so the budget holds with a wide margin.

    The 2% bar is tighter than single-run host jitter on small inputs,
    so each arm runs twice and keeps its noise floor (the minimum):
    systematic overhead survives a minimum, scheduler hiccups do not."""
    def floor(metrics_on):
        totals = []
        for _ in range(2):
            t, _c = time_engine(True, fact, dim, pq_path, out_root,
                                metrics=metrics_on)
            totals.append(sum(t.values()))
        return min(totals)

    base = floor(False)
    return 100.0 * (floor(True) - base) / base


def measure_hbm_overhead(fact, dim, pq_path, out_root,
                         trace_out: str = None) -> float:
    """HBM-observatory overhead guard: the suite with the memory
    timeline feeding vs fully disabled.  The acceptance bar is <5% —
    every lifecycle hook is a dict update + bounded ring append under
    one lock, published to gauges outside it, so the budget holds.

    Like the metrics guard, each arm runs twice and keeps its noise
    floor (the minimum): systematic overhead survives a minimum,
    scheduler hiccups do not.

    When ``trace_out`` is set, one extra traced+timeline run exports
    its Chrome trace there so the HBM counter tracks ("ph": "C",
    ``HBM <tenant>``) land next to the operator spans for eyeballing
    in Perfetto."""
    def floor(hbm_on):
        totals = []
        for _ in range(2):
            t, _c = time_engine(True, fact, dim, pq_path, out_root,
                                hbm=hbm_on)
            totals.append(sum(t.values()))
        return min(totals)

    base = floor(False)
    pct = 100.0 * (floor(True) - base) / base
    if trace_out:
        _hbm_trace_export(fact, dim, pq_path, out_root, trace_out)
    return pct


def _hbm_trace_export(fact, dim, pq_path, out_root,
                      trace_out: str) -> None:
    """One traced run of the suite's agg query with the timeline on,
    Chrome trace (operator spans + HBM counter tracks) to a file."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.trace.enabled", True)
         .config("spark.rapids.tpu.hbm.timeline.enabled", True)
         .get_or_create())
    out = (s.create_dataframe(fact)
           .group_by(col("k"))
           .agg(F.sum(col("v")).alias("sv"))
           .collect())
    assert out.num_rows > 0
    tr = s.last_query_trace()
    if tr is None:
        return
    with open(trace_out, "w") as f:
        json.dump(tr.to_chrome(), f)
    print(f"bench --hbm-overhead: Chrome trace with HBM counter "
          f"tracks -> {trace_out}", file=sys.stderr)


# ---------------------------------------------------------------------------
# --serve: sustained-QPS serving benchmark (pool + byte-weighted admission)
# ---------------------------------------------------------------------------

#: synthetic sql_id for the serve fingerprint (real event-log sql_ids are
#: small per-app ordinals; this can never collide with one)
_SERVE_SQL_ID = 100_000


def measure_serve_deadlines(fact, dim, pq_path, concurrency: int = 8,
                            deadline_ms: int = 1,
                            queries_per_worker: int = 3) -> dict:
    """``--deadline-ms`` leg: the serving mix with every third request
    carrying a per-request deadline tight enough to always trip.  Those
    requests must fail as TYPED TpuQueryDeadlineExceeded — counted
    under ``tpu_cancellations_total{cause="deadline"}`` — while every
    surviving request returns a bit-exact result vs a no-deadline
    reference, with zero dirty memsan ledgers and balanced admission
    books: a deadline storm is a correctness no-op for its
    neighbours."""
    import concurrent.futures as cf

    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.obs import metrics as obs_metrics
    from spark_rapids_tpu.obs.progress import TpuQueryDeadlineExceeded

    conf = {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.tpu.memsan.enabled": "true",
        "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes": str(2 << 30),
        "spark.rapids.tpu.serve.admissionTimeoutMs": "120000",
    }
    reg = obs_metrics.registry()

    def deadline_cancels():
        fam = reg.counter("tpu_cancellations_total",
                          labelnames=("cause",))
        return sum(ch.value for lbl, ch in fam.series()
                   if lbl.get("cause") == "deadline")

    def dirty_ledgers():
        return reg.counter("tpu_memsan_dirty_ledgers_total").value()

    pool = SessionPool(concurrency, conf)
    plans = {id(s): serve_mix(s, fact, dim, pq_path, as_plans=True)
             for s in pool._sessions}
    mix_names = ("agg", "join", "window", "parquet")
    # warm the jit cache and pin the bit-exact reference answer per mix
    # entry (deterministic inputs: every session agrees)
    refs = {}
    for name in mix_names:
        with pool.session() as s:
            refs[name] = plans[id(s)][name]().collect()

    worklist = [(i, mix_names[i % len(mix_names)], i % 3 == 0)
                for i in range(concurrency * queries_per_worker)]
    tight_n = sum(1 for _, _, tight in worklist if tight)
    cancels0, dirty0 = deadline_cancels(), dirty_ledgers()
    outcomes = {}

    def one(item):
        i, name, tight = item
        with pool.session() as s:
            df = plans[id(s)][name]()
            if tight:
                try:
                    s.execute(df._lp, deadline_ms=deadline_ms)
                    outcomes[i] = ("no-trip", name)
                except TpuQueryDeadlineExceeded:
                    outcomes[i] = ("deadline", name)
                except Exception as ex:  # wrong TYPE is the failure
                    outcomes[i] = ("wrong-error",
                                   f"{name}: {type(ex).__name__}")
            else:
                out = df.collect()
                outcomes[i] = ("ok", name) if out.equals(refs[name]) \
                    else ("mismatch", name)

    with cf.ThreadPoolExecutor(max_workers=concurrency) as ex:
        list(ex.map(one, worklist))
    pool.drain(timeout=60)
    pool.close()

    typed = sum(1 for k, _ in outcomes.values() if k == "deadline")
    survivors_ok = sum(1 for k, _ in outcomes.values() if k == "ok")
    counted = deadline_cancels() - cancels0
    dirty = dirty_ledgers() - dirty0
    ctrl = AdmissionController.get()
    failures = []
    if typed != tight_n:
        bad = sorted(v for v in outcomes.values()
                     if v[0] in ("no-trip", "wrong-error"))
        failures.append(
            f"{typed}/{tight_n} tight-deadline requests raised typed "
            f"TpuQueryDeadlineExceeded (offenders: {bad[:4]})")
    if counted != typed:
        failures.append(
            f'tpu_cancellations_total{{cause="deadline"}} grew by '
            f"{counted}, expected {typed}")
    if survivors_ok != len(worklist) - tight_n:
        failures.append(
            f"{len(worklist) - tight_n - survivors_ok} surviving "
            f"request(s) were not bit-exact vs the no-deadline "
            f"reference")
    if dirty:
        failures.append(f"{dirty} dirty memsan ledger(s) after the "
                        f"deadline storm")
    if ctrl is not None and (ctrl.bytes_in_flight or ctrl.queue_depth):
        failures.append(
            f"admission books unbalanced after drain: "
            f"{ctrl.bytes_in_flight}B in flight, "
            f"queue depth {ctrl.queue_depth}")
    return {
        "deadline_ms": int(deadline_ms),
        "requests": len(worklist),
        "tight_requests": tight_n,
        "deadline_failures_typed": typed,
        "deadline_cancellations_counted": int(counted),
        "survivors_bit_exact": survivors_ok,
        "dirty_ledgers": int(dirty),
        "failures": failures,
    }


def serve_mix(session, fact, dim, pq_path, as_plans: bool = False):
    """The four-query serving mix (agg/join/window/parquet), bound to one
    pooled session.  Dataframes are pre-created so the measured cost is
    query execution, not host-side table registration.  ``as_plans``
    returns the un-collected dataframe builders instead of collect
    closures — the ``--deadline-ms`` leg needs the logical plan so it
    can execute with a per-request deadline."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.expr.window import WindowBuilder

    fdf = session.create_dataframe(fact)
    ddf = session.create_dataframe(dim)

    def agg():
        return (fdf.filter(col("v") > -(10**6) // 2)
                .group_by(col("k"))
                .agg(F.sum(col("v")).alias("sv"),
                     F.count("*").alias("c")))

    def join():
        return (fdf.join(ddf, on="k", how="inner")
                .group_by(col("k"))
                .agg(F.sum(col("w")).alias("sw")))

    def window():
        w = WindowBuilder().partition_by(col("k")).order_by(col("v"))
        return fdf.select(col("k"), col("v"),
                          F.row_number().over(w).alias("rn"))

    def parquet():
        return (session.read.parquet(pq_path)
                .filter(col("f") < 0.5)
                .group_by(col("k"))
                .agg(F.sum(col("v")).alias("sv")))

    builders = {"agg": agg, "join": join, "window": window,
                "parquet": parquet}
    if as_plans:
        return builders
    return {name: (lambda b=b: b().collect())
            for name, b in builders.items()}


def measure_serve(fact, dim, pq_path, concurrency: int = 8,
                  queries_per_worker: int = 3,
                  request_io_ms: float = 150.0) -> dict:
    """Sustained-QPS serving measurement: the SAME request list through
    a 1-session pool serially (one-at-a-time server), then a
    `concurrency`-session pool with `concurrency` client threads, under
    byte-weighted admission.  The concurrent arm must sustain strictly
    higher aggregate QPS than the serial arm (``qps_speedup > 1``) —
    the whole point of co-running — with zero dirty memsan ledgers and
    zero admission accounting drift (every admitted ticket ends as a
    completed or failed query).

    ``request_io_ms`` models the per-request client transfer latency of
    the offered load (request receive + response delivery), charged
    identically to every request in BOTH arms: a one-at-a-time server
    eats it sequentially, a multi-tenant server overlaps it with other
    tenants' compute.  On a multi-core host the compute itself overlaps
    too; on a single-core CI host this client I/O is the slack that
    makes the co-running dividend measurable at all."""
    import concurrent.futures as cf

    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.obs import metrics as obs_metrics

    conf = {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.tpu.memsan.enabled": "true",
        "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes": str(2 << 30),
        "spark.rapids.tpu.serve.admissionTimeoutMs": "120000",
        # latency observatory: tracing feeds critical-path extraction,
        # the SLO target classifies each request GOOD/BAD (generous:
        # the interesting output is the per-tenant segment mix, not a
        # burn alert on a loaded CI host)
        "spark.rapids.tpu.trace.enabled": "true",
        "spark.rapids.tpu.slo.targetMs": str(
            int(request_io_ms * 10) or 1000),
    }
    reg = obs_metrics.registry()

    def counters():
        # admission counters are tenant-labeled; total() sums the fleet
        out = {n: reg.counter(f"tpu_admission_{n}_total",
                              labelnames=("tenant",)).total()
               for n in ("admitted", "queued", "timeouts", "repaired")}
        out["completed"] = reg.counter(
            "tpu_queries_completed_total").value()
        out["failed"] = reg.counter("tpu_queries_failed_total").value()
        out["dirty_ledgers"] = reg.counter(
            "tpu_memsan_dirty_ledgers_total").value()
        qw = reg.histogram("tpu_admission_queue_wait_seconds").value()
        # value() is 0 (not a tuple) before the first observation
        cnt, total = qw if isinstance(qw, tuple) else (0, 0.0)
        out["queue_wait_count"], out["queue_wait_sum_s"] = cnt, total
        return out

    mix_names = ("agg", "join", "window", "parquet")
    worklist = [mix_names[i % len(mix_names)]
                for i in range(concurrency * queries_per_worker)]
    peak_seen = [0]
    peak_lock = __import__("threading").Lock()

    def run_list(pool, mixes, workers):
        latencies = {}

        def one(i_name):
            i, name = i_name
            io_s = request_io_ms / 1000.0
            with pool.session() as s:
                t0 = time.perf_counter()
                time.sleep(io_s / 2)      # request receive
                out = mixes[id(s)][name]()
                time.sleep(io_s / 2)      # response delivery
                lat = time.perf_counter() - t0
            assert out.num_rows > 0
            pk = s.last_peak_device_bytes or 0
            with peak_lock:
                peak_seen[0] = max(peak_seen[0], pk)
            latencies[i] = (name, lat)

        t0 = time.perf_counter()
        if workers == 1:
            for item in enumerate(worklist):
                one(item)
        else:
            with cf.ThreadPoolExecutor(max_workers=workers) as ex:
                list(ex.map(one, enumerate(worklist)))
        wall = time.perf_counter() - t0
        return wall, [latencies[i][1] for i in sorted(latencies)]

    c0 = counters()
    # serial arm: one session, one client
    pool1 = SessionPool(1, conf)
    mixes1 = {id(s): serve_mix(s, fact, dim, pq_path)
              for s in pool1._sessions}
    for name in mix_names:  # warm the shared jit cache once per shape
        with pool1.session() as s:
            mixes1[id(s)][name]()
    serial_wall, serial_lat = run_list(pool1, mixes1, 1)
    pool1.close()
    # concurrent arm: N sessions, N client threads, same worklist.
    # Reset the latency observatory between arms so the per-tenant
    # report describes the concurrent arm only (pool1's session is
    # also tenant pool-0); the new pool's sessions reconfigure it
    from spark_rapids_tpu.obs.slo import LatencyObservatory
    LatencyObservatory.reset_for_tests()
    poolN = SessionPool(concurrency, conf)
    mixesN = {id(s): serve_mix(s, fact, dim, pq_path)
              for s in poolN._sessions}
    conc_before = counters()
    conc_wall, conc_lat = run_list(poolN, mixesN, concurrency)
    poolN.drain(timeout=30)
    c1 = counters()
    ctrl = AdmissionController.get()
    # HBM observatory rollup: per-tenant peak device occupancy and how
    # much of that peak was demotable (spillable-now) — the co-running
    # headroom story per pool tenant (obs/memprof.py)
    from spark_rapids_tpu.obs.memprof import MemoryTimeline
    hbm_rep = MemoryTimeline.get().report()
    hbm_tenants = {}
    for tenant, row in sorted(hbm_rep.get("tenants", {}).items()):
        pk = int(row.get("peak_bytes", 0))
        dm = int(row.get("peak_demotable_bytes", 0))
        hbm_tenants[tenant] = {
            "peak_device_bytes": pk,
            "demotable_share": round(dm / pk, 4) if pk else 0.0,
        }

    def pct(lats, p):
        srt = sorted(lats)
        return srt[min(int(p * (len(srt) - 1) + 0.5), len(srt) - 1)]

    # latency observatory rollup for the concurrent arm: per-tenant
    # p50/p99 with the dominant tail segment — the attribution columns
    # the QoS work (ROADMAP item 4) diffs before/after
    slo_rep = LatencyObservatory.get().slo_report()
    slo_tenants = {}
    for tenant, row in sorted(slo_rep.get("tenants", {}).items()):
        slo_tenants[tenant] = {
            "p50_ms": row["p50_ms"],
            "p99_ms": row["p99_ms"],
            "burn_rate": row["burn_rate"],
            "dominant_segment": row["dominant_tail_segment"],
        }
    slo_overhead_pct = slo_rep.get("overhead", {}).get("pct", 0.0)
    if slo_tenants:
        print("bench --serve per-tenant latency attribution:",
              file=sys.stderr)
        print(f"  {'tenant':<10} {'p50_ms':>9} {'p99_ms':>9} "
              f"{'burn':>6}  dominant_segment", file=sys.stderr)
        for tenant, row in slo_tenants.items():
            print(f"  {tenant:<10} {row['p50_ms']:>9.1f} "
                  f"{row['p99_ms']:>9.1f} {row['burn_rate']:>6.2f}  "
                  f"{row['dominant_segment'] or '-'}", file=sys.stderr)

    total = len(worklist)
    delta = {k: c1[k] - c0[k] for k in c0}
    qw_cnt = c1["queue_wait_count"] - conc_before["queue_wait_count"]
    qw_sum = c1["queue_wait_sum_s"] - conc_before["queue_wait_sum_s"]
    serial_qps = total / serial_wall
    conc_qps = total / conc_wall
    return {
        "mix": list(mix_names),
        "queries": total,
        "concurrency": concurrency,
        "request_io_ms": request_io_ms,
        "serial_qps": round(serial_qps, 2),
        "concurrent_qps": round(conc_qps, 2),
        "qps_speedup": round(conc_qps / serial_qps, 3),
        "p50_ms": round(pct(conc_lat, 0.50) * 1000, 1),
        "p99_ms": round(pct(conc_lat, 0.99) * 1000, 1),
        "serial_p50_ms": round(pct(serial_lat, 0.50) * 1000, 1),
        "queue_wait_mean_ms": round(
            1000 * qw_sum / qw_cnt, 2) if qw_cnt else 0.0,
        "peak_device_bytes": int(peak_seen[0]),
        "max_bytes_in_flight": int(ctrl.max_in_flight_seen)
            if ctrl else 0,
        "budget_bytes": int(ctrl.budget_bytes) if ctrl else 0,
        "admission": {k: int(delta[k]) for k in
                      ("admitted", "queued", "timeouts", "repaired")},
        "completed": int(delta["completed"]),
        "failed": int(delta["failed"]),
        "dirty_ledgers": int(delta["dirty_ledgers"]),
        "accounting_drift": int(
            delta["admitted"] - delta["completed"] - delta["failed"]),
        "hbm": {
            "enabled": bool(hbm_rep.get("enabled")),
            "total_peak_bytes": int(hbm_rep.get("peak_bytes", 0)),
            "demotable_bytes": int(hbm_rep.get("demotable_bytes", 0)),
            "unattributed_events": int(
                hbm_rep.get("unattributed_events", 0)),
            "tenants": hbm_tenants,
        },
        "slo": {
            "target_ms": slo_rep.get("target_ms"),
            "objective": slo_rep.get("objective"),
            "overhead_pct": slo_overhead_pct,
            "tenants": slo_tenants,
        },
    }


def serve_fingerprint(serve: dict) -> dict:
    """The serve run as ONE history fingerprint: counter totals are the
    deterministic half (fixed mix + budget replays identically; queued
    is scheduling noise and excluded), percentiles the timing half."""
    from spark_rapids_tpu.obs.history import FINGERPRINT_VERSION
    return {
        "version": FINGERPRINT_VERSION,
        "sql_id": _SERVE_SQL_ID,
        "description": "serve_mix",
        "failed": False,
        "serve_counters": {
            "admitted": serve["admission"]["admitted"],
            "repaired": serve["admission"]["repaired"],
            "timeouts": serve["admission"]["timeouts"],
            "completed": serve["completed"],
            "failed": serve["failed"],
        },
        "serve_p50_ms": serve["p50_ms"],
        "serve_p99_ms": serve["p99_ms"],
        # advisory (never diffed — byte peaks are data-layout noise):
        # per-tenant HBM peaks + demotable share from the observatory
        "serve_hbm": serve.get("hbm", {}),
        # advisory timing-class per-tenant SLO fields: burn rate is
        # load-dependent; the dominant tail segment feeds the
        # tail_mix_shift differ (timing-gated, never deterministic)
        "slo_burn_rate": {
            t: row["burn_rate"]
            for t, row in serve.get("slo", {}).get("tenants",
                                                   {}).items()},
        "tail_dominant_segment": {
            t: row["dominant_segment"]
            for t, row in serve.get("slo", {}).get("tenants",
                                                   {}).items()},
    }


def record_serve_history(history_dir: str, serve: dict, check: bool,
                         wall_threshold=None) -> int:
    """--record/--check for the serving benchmark, through the same
    append-only HistoryDir + differ as the suite fingerprints."""
    from spark_rapids_tpu.obs.history import (HistoryDir,
                                              deterministic_drift,
                                              diff_runs)
    hist = HistoryDir(history_dir)
    path = hist.record([serve_fingerprint(serve)], label="bench serve")
    print(f"bench --serve: recorded serve fingerprint -> {path}",
          file=sys.stderr)
    if not check:
        return 0
    runs = hist.runs()
    if len(runs) < 2:
        print("bench --serve --check: first recorded run, nothing to "
              "diff", file=sys.stderr)
        return 0
    drifts = diff_runs(hist.load(runs[-2]), hist.load(runs[-1]),
                       wall_threshold_pct=wall_threshold)
    for d in drifts:
        print(f"bench --serve --check: {d.render()}", file=sys.stderr)
    if deterministic_drift(drifts):
        print("SERVE REGRESSION CHECK FAILED: deterministic "
              "fingerprint drift vs the previous recorded run",
              file=sys.stderr)
        return 1
    print("bench --serve --check: no deterministic drift vs previous "
          "run", file=sys.stderr)
    return 0


def record_history(history_dir: str, eventlog_dir: str,
                   check: bool, wall_threshold=None) -> int:
    """Distill this run's event log into the append-only fingerprint
    history (--record); with --check, diff against the previous run and
    return 1 on deterministic drift (obs/history.py)."""
    from spark_rapids_tpu.obs.history import (HistoryDir,
                                              deterministic_drift,
                                              diff_runs,
                                              distill_event_log)
    hist = HistoryDir(history_dir)
    fps = []
    for f in sorted(os.listdir(eventlog_dir)):
        if f.startswith("events_"):
            fps += distill_event_log(os.path.join(eventlog_dir, f))
    path = hist.record(fps, label="bench suite")
    print(f"bench: recorded {len(fps)} query fingerprint(s) -> {path}",
          file=sys.stderr)
    if not check:
        return 0
    runs = hist.runs()
    if len(runs) < 2:
        print("bench --check: first recorded run, nothing to diff",
              file=sys.stderr)
        return 0
    drifts = diff_runs(hist.load(runs[-2]), hist.load(runs[-1]),
                       wall_threshold_pct=wall_threshold)
    for d in drifts:
        print(f"bench --check: {d.render()}", file=sys.stderr)
    if deterministic_drift(drifts):
        print("BENCH REGRESSION CHECK FAILED: deterministic "
              "fingerprint drift vs the previous recorded run",
              file=sys.stderr)
        return 1
    print("bench --check: no deterministic drift vs previous run",
          file=sys.stderr)
    return 0


# ---------------------------------------------------------------------------
# --dist: multi-process shuffle benchmark (remote block fetch over loopback)
# ---------------------------------------------------------------------------

_DIST_CODECS = ("none", "lz4", "zstd")
#: fetch window per mode: serial drains one block at a time; pipelined
#: keeps the fetcher's producer thread decompressing ahead of the join
_DIST_MODES = (("pipelined", 4), ("serial", 1))


def _dist_reference(rows: int, parts: int, seed: int):
    """In-process reference: same tables, same murmur3 routing, same
    per-partition pyarrow join the distributed run performs — the
    bit-exactness oracle."""
    import pyarrow as pa
    from spark_rapids_tpu.shuffle.serve_map import (build_side_tables,
                                                    partition_record_batch)
    fact, dim = build_side_tables(rows, seed)
    fparts = partition_record_batch(fact, "k", parts)
    dparts = partition_record_batch(dim, "k", parts)
    out = []
    for pid in range(parts):
        f, d = fparts.get(pid), dparts.get(pid)
        if f is None or d is None:
            continue
        out.append(pa.table(f).join(pa.table(d), "k"))
    return pa.concat_tables(out).sort_by(
        [("k", "ascending"), ("v", "ascending")])


def _dist_fetch_join(parts: int, window: int):
    """Reduce side of the distributed join: stream both shuffles'
    blocks for every partition through the locality read path (all
    remote here — the child owns every block) and join per partition."""
    import pyarrow as pa
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.columnar.device import batch_to_arrow
    from spark_rapids_tpu.shuffle.locality import read_reduce_blocks
    from spark_rapids_tpu.shuffle.manager import materialize_block
    from spark_rapids_tpu.shuffle.serve_map import DIM_SID, FACT_SID
    conf = cfg.RapidsConf(
        {cfg.SHUFFLE_FETCH_MAX_IN_FLIGHT.key: str(window)})
    out = []
    for pid in range(parts):
        sides = []
        for sid in (FACT_SID, DIM_SID):
            rbs = [batch_to_arrow(materialize_block(b, np))
                   for b in read_reduce_blocks(sid, pid, conf=conf,
                                               xp=np)]
            sides.append(pa.Table.from_batches(rbs) if rbs else None)
        f, d = sides
        if f is None or d is None:
            continue
        out.append(f.join(d, "k"))
    return pa.concat_tables(out).sort_by(
        [("k", "ascending"), ("v", "ascending")])


def _dist_run(rows: int, parts: int, codec: str, window: int,
              seed: int, traced: bool = False,
              digest: bool = True) -> dict:
    """One (codec, window) distributed run: child process owns the map
    outputs and serves them; this process plays the reduce side.

    ``traced=True`` installs a live tracer around the fetch/join, so
    the run pays the full fleet-observatory path (fetch spans, the v2
    context on the wire, the post-fetch /spans pulls + merge) and the
    result carries ``_trace`` for the merged-trace report.

    ``digest=False`` turns content addressing off on BOTH sides (the
    child skips write-time block digests, this side skips fetch
    verification) — the baseline arm of the tpudsan overhead guard."""
    import subprocess
    from spark_rapids_tpu.obs import metrics as m
    from spark_rapids_tpu.obs import tracer as tr
    from spark_rapids_tpu.shuffle.digest import set_digest_enabled
    from spark_rapids_tpu.shuffle.locality import reset_pool
    from spark_rapids_tpu.shuffle.registry import (BlockEndpoint,
                                                   BlockLocationRegistry)
    from spark_rapids_tpu.shuffle.serve_map import DIM_SID, FACT_SID
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SPARK_RAPIDS_TPU_DISABLE_COMPILE_CACHE="1",
               SPARK_RAPIDS_TPU_DSAN_DIGEST="1" if digest else "0")
    child = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.shuffle.serve_map",
         "--rows", str(rows), "--parts", str(parts),
         "--codec", codec, "--seed", str(seed),
         "--executor-id", "bench-map-0"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    trace = None
    try:
        line = child.stdout.readline()
        if not line.startswith("PORT "):
            raise RuntimeError(f"bad serve_map handshake: {line!r}")
        port = int(line.split()[1])
        reg = BlockLocationRegistry.get()
        reg.set_local("bench-reduce", "127.0.0.1", 0)
        ep = BlockEndpoint("bench-map-0", "127.0.0.1", port)
        reg.register(FACT_SID, [ep])
        reg.register(DIM_SID, [ep])
        local_c = m.counter("tpu_shuffle_local_blocks_total")
        local_before = local_c.value()
        verified_c = m.counter("tpu_shuffle_digest_verified_total")
        mismatch_c = m.counter("tpu_shuffle_digest_mismatch_total")
        verified_before = verified_c.value()
        mismatch_before = mismatch_c.value()
        if traced:
            trace = tr.install(tr.QueryTrace())
        set_digest_enabled(digest)
        t0 = time.perf_counter()
        joined = _dist_fetch_join(parts, window)
        wall = time.perf_counter() - t0
        if trace is not None:
            trace.finalize()
            tr.uninstall()
        local_after = local_c.value()
        child.stdin.write("done\n")
        child.stdin.flush()
        stats_line = child.stdout.readline()
        if not stats_line.startswith("STATS "):
            raise RuntimeError(f"bad serve_map stats: {stats_line!r}")
        stats = json.loads(stats_line[len("STATS "):])
        rc = child.wait(timeout=30)
        if rc != 0:
            raise RuntimeError(f"serve_map exited {rc}")
    finally:
        set_digest_enabled(True)
        if trace is not None and tr.active_tracer() is trace:
            tr.uninstall()
        child.stdin.close()
        child.stdout.close()
        if child.poll() is None:
            child.kill()
            child.wait()
        reset_pool()
        BlockLocationRegistry.get().forget_shuffle(FACT_SID)
        BlockLocationRegistry.get().forget_shuffle(DIM_SID)
    raw = stats.get("raw_bytes") or 0
    comp = stats.get("compressed_bytes") or 0
    out = {
        "codec": codec,
        "window": window,
        "rows_joined": joined.num_rows,
        "wall_s": round(wall, 4),
        "fetch_mb_s": round(raw / max(wall, 1e-9) / 1e6, 2),
        "raw_bytes": raw,
        "compressed_bytes": comp,
        "compression_ratio": round(comp / raw, 4) if raw else None,
        "server_metadata_requests": stats.get(
            "server_metadata_requests"),
        "server_transfer_requests": stats.get(
            "server_transfer_requests"),
        "child_leaked_blocks": stats.get("leaked_blocks"),
        "child_leaks": stats.get("leaks"),
        "child_unpulled_spans": stats.get("unpulled_spans"),
        "parent_local_blocks": local_after - local_before,
        "digest": digest,
        "digest_verified_blocks": verified_c.value() - verified_before,
        "digest_mismatches": mismatch_c.value() - mismatch_before,
        "_table": joined,
    }
    if trace is not None:
        out["_trace"] = trace
    return out


def _dist_trace_report(trace, trace_out: str) -> tuple:
    """Verify the merged trace's fleet shape and write it as ONE
    Chrome/Perfetto JSON: every remote fetch span must carry the
    producer's serve spans (metadata + transfer roots, with serialize
    and compress step children under the transfers), skew-corrected
    into the consumer's clock, with zero lost spans.  Returns
    (report, failures)."""
    from spark_rapids_tpu.obs.export import fleet_summary
    failures = []
    spans = trace.span_dicts()
    by_parent = {}
    for s in spans:
        by_parent.setdefault(s.get("parentId"), []).append(s)
    fetch = [s for s in spans if s["name"] == "shuffle.fetch"]
    if not fetch:
        failures.append("traced dist run recorded no fetch spans")
    for f in fetch:
        roots = [k for k in by_parent.get(f["spanId"], [])
                 if k.get("proc")]
        names = {r["name"] for r in roots}
        if not {"shuffle.serve.metadata",
                "shuffle.serve.transfer"} <= names:
            failures.append(
                f"fetch span {f['spanId']} lacks producer serve "
                f"children (got {sorted(names)})")
            continue
        steps = {c["name"]
                 for r in roots if r["name"] == "shuffle.serve.transfer"
                 for c in by_parent.get(r["spanId"], [])}
        if not {"serve.serialize", "serve.compress"} <= steps:
            failures.append(
                f"fetch span {f['spanId']} transfer lacks serialize/"
                f"compress children (got {sorted(steps)})")
        f0, f1 = f["startNs"], f["startNs"] + f["durNs"]
        for r in roots:
            if not (f0 <= r["startNs"]
                    and r["startNs"] + r["durNs"] <= f1):
                failures.append(
                    f"remote span {r['name']} outside its fetch "
                    f"parent — clock skew not corrected")
    if trace.remote_spans_merged == 0:
        failures.append("traced dist run merged zero remote spans")
    if trace.remote_spans_lost:
        failures.append(f"clean dist run lost "
                        f"{trace.remote_spans_lost} remote span(s)")
    with open(trace_out, "w") as f:
        json.dump(trace.to_chrome(), f)
    report = {
        "trace_file": trace_out,
        "fetch_spans": len(fetch),
        "remote_spans_merged": trace.remote_spans_merged,
        "remote_spans_lost": trace.remote_spans_lost,
        "fleet": fleet_summary(spans),
    }
    return report, failures


def measure_dist_trace_overhead(rows: int, parts: int,
                                seed: int) -> float:
    """Distributed flight-recorder overhead: the lz4/pipelined dist
    run with the full fleet path on (fetch spans, wire contexts,
    /spans pulls + merge) vs untraced.  Same <5% bar as the local
    guard; each arm keeps its two-run noise floor."""
    def floor(traced):
        walls = []
        for _ in range(2):
            r = _dist_run(rows, parts, "lz4", 4, seed, traced=traced)
            r.pop("_table", None)
            r.pop("_trace", None)
            walls.append(r["wall_s"])
        return min(walls)

    base = floor(False)
    return 100.0 * (floor(True) - base) / base


def measure_dist_digest_overhead(rows: int, parts: int,
                                 seed: int) -> dict:
    """tpudsan content-addressing overhead: the lz4/pipelined dist run
    with write-time block digests + fetch-side verification on vs
    fully off (both processes).  The digest arm must actually verify
    blocks (anti-vacuity) with zero mismatches; each arm keeps its
    two-run noise floor.  Budget: < 2% of untraced fetch wall time."""
    failures = []

    def floor(digest):
        walls, verified, mismatches = [], 0, 0
        for _ in range(2):
            r = _dist_run(rows, parts, "lz4", 4, seed, digest=digest)
            r.pop("_table", None)
            walls.append(r["wall_s"])
            verified += r["digest_verified_blocks"]
            mismatches += r["digest_mismatches"]
        return min(walls), verified, mismatches

    base, base_verified, _ = floor(False)
    on, on_verified, on_mismatches = floor(True)
    if base_verified:
        failures.append(
            f"digest-off arm verified {base_verified} block(s) — the "
            f"off switch does not reach the fetch path")
    if not on_verified:
        failures.append(
            "digest-on arm verified ZERO blocks — the overhead "
            "measurement is vacuous (digests never reached the wire)")
    if on_mismatches:
        failures.append(
            f"digest-on arm recorded {on_mismatches} content "
            f"mismatch(es) on a clean loopback run")
    pct = 100.0 * (on - base) / base
    return {"pct": round(pct, 2), "verified_blocks": on_verified,
            "failures": failures}


def measure_dist(rows: int, parts: int, seed: int,
                 trace_out: str = "tpu_dist_trace.json") -> dict:
    """Full --dist sweep: none/lz4/zstd x pipelined/serial, each run
    bit-exact against the in-process reference, zero leaked blocks on
    both sides, lz4 visibly compressing (ratio < 0.9).  A final traced
    lz4/pipelined run (outside the timing sweep) must merge the
    producer's serve spans under every fetch span with zero lost
    spans, and its clock-aligned Chrome trace lands in trace_out."""
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
    reference = _dist_reference(rows, parts, seed)
    runs = []
    failures = []
    for codec in _DIST_CODECS:
        for mode, window in _DIST_MODES:
            r = _dist_run(rows, parts, codec, window, seed)
            r["mode"] = mode
            tbl = r.pop("_table")
            r["bit_exact"] = tbl.equals(reference)
            if not r["bit_exact"]:
                failures.append(
                    f"{codec}/{mode}: result not bit-exact vs "
                    f"in-process reference ({tbl.num_rows} vs "
                    f"{reference.num_rows} rows)")
            if r["child_leaked_blocks"]:
                failures.append(
                    f"{codec}/{mode}: child leaked "
                    f"{r['child_leaked_blocks']} catalog block(s)")
            if r["child_leaks"]:
                failures.append(
                    f"{codec}/{mode}: child spill ledger reported "
                    f"{r['child_leaks']} leak(s)")
            if r["parent_local_blocks"]:
                failures.append(
                    f"{codec}/{mode}: {r['parent_local_blocks']} "
                    f"block(s) took the local path — every block is "
                    f"remote in this topology")
            if codec != "none" and r["compression_ratio"] is not None \
                    and r["compression_ratio"] >= 0.9:
                failures.append(
                    f"{codec}/{mode}: compression ratio "
                    f"{r['compression_ratio']} >= 0.9 — codec not "
                    f"actually compressing the shuffle payload")
            runs.append(r)
            print("SUITE_JSON=" + json.dumps(
                {"suite": f"dist_{codec}_{mode}",
                 **{k: v for k, v in r.items()}}))
    traced = _dist_run(rows, parts, "lz4", 4, seed, traced=True)
    traced_tbl = traced.pop("_table")
    if not traced_tbl.equals(reference):
        failures.append("traced lz4/pipelined run not bit-exact vs "
                        "in-process reference")
    trace_report, trace_failures = _dist_trace_report(
        traced.pop("_trace"), trace_out)
    failures.extend(trace_failures)
    if traced.get("child_unpulled_spans"):
        failures.append(
            f"traced run left {traced['child_unpulled_spans']} span "
            f"record(s) unpulled in the child's RemoteSpanStore")
    print("SUITE_JSON=" + json.dumps(
        {"suite": "dist_trace_merged", **trace_report}))
    parent_leaks = len(SpillCatalog.get().leak_report())
    if parent_leaks:
        failures.append(f"reduce side spill ledger reported "
                        f"{parent_leaks} leak(s)")
    leftover = TpuShuffleManager.get().catalog.num_blocks()
    if leftover:
        failures.append(f"reduce side catalog still holds {leftover} "
                        f"block(s) after all runs drained")
    def _wall(codec, mode):
        for r in runs:
            if r["codec"] == codec and r["mode"] == mode:
                return r["wall_s"]
        return None
    summary = {
        "metric": "dist_shuffle_fetch",
        "rows": rows,
        "parts": parts,
        "runs": runs,
        "pipelined_vs_serial_lz4": round(
            _wall("lz4", "serial") / max(_wall("lz4", "pipelined"),
                                         1e-9), 3),
        "merged_trace": trace_report,
        "failures": failures,
    }
    return summary


def _arg_value(flag: str, default=None):
    for a in sys.argv[1:]:
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return default


def _cpu_fallback_reexec(probe_error: str) -> None:
    """The dead-bench guard (BENCH_r01..r05 shipped FIVE rounds of
    `rows/s = 0.0 (accelerator unreachable)` without anything
    noticing): when the device probe fails, re-exec the whole suite in
    a fresh process pinned to JAX_PLATFORMS=cpu — jax may already be
    wedged half-initialized in THIS process, so an in-process retry
    cannot work — and emit a REAL suite number tagged
    `"backend": "cpu_fallback"` with the probe error preserved.  The
    trajectory keeps an honest measurement instead of a zero."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               BENCH_CPU_FALLBACK_ERROR=probe_error)
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         *sys.argv[1:], "--cpu-fallback"],
        env=env, capture_output=True, text=True)
    sys.stderr.write(r.stderr)
    sys.stdout.write(r.stdout)
    sys.exit(r.returncode)


def main():
    pos = [a for a in sys.argv[1:] if not a.startswith("--")]
    n_rows = int(pos[0]) if pos else 1_000_000
    one_suite = _arg_value("--one-suite")
    if one_suite:
        # internal mode used by the --compile-report and --accuracy
        # drivers' cold/warm subprocesses
        run_one_suite(one_suite, n_rows, _arg_value("--cache-dir", ""),
                      _arg_value("--ledger-dir", ""),
                      _arg_value("--accuracy-history", ""),
                      "--with-feedback" in sys.argv[1:])
        return
    if "--dist" in sys.argv[1:]:
        # multi-process shuffle mode: map side in a child OS process,
        # reduce side here, blocks over loopback TCP.  Pure host-side
        # (numpy + pyarrow) — no accelerator probe needed.
        dist_rows = int(pos[0]) if pos else 20_000
        dist_parts = int(_arg_value("--parts", "4"))
        dist_seed = int(_arg_value("--seed", "7"))
        trace_out = _arg_value("--trace-out", "tpu_dist_trace.json")
        summary = measure_dist(dist_rows, dist_parts, dist_seed,
                               trace_out=trace_out)
        dg = measure_dist_digest_overhead(dist_rows, dist_parts,
                                          dist_seed)
        summary["dist_digest_overhead_pct"] = dg["pct"]
        summary["dist_digest_verified_blocks"] = dg["verified_blocks"]
        summary["failures"].extend(dg["failures"])
        if dg["pct"] > 2.0:
            summary["failures"].append(
                f"content-addressing overhead {dg['pct']:.2f}% > 2% "
                f"of digest-off fetch wall time")
        if "--trace-overhead" in sys.argv[1:]:
            pct = measure_dist_trace_overhead(dist_rows, dist_parts,
                                              dist_seed)
            summary["dist_trace_overhead_pct"] = round(pct, 2)
            if pct > 5.0:
                summary["failures"].append(
                    f"distributed tracing overhead {pct:.2f}% > 5% of "
                    f"untraced fetch wall time")
        print(json.dumps(summary))
        for msg in summary["failures"]:
            print(f"DIST GUARD FAILED: {msg}", file=sys.stderr)
        sys.exit(1 if summary["failures"] else 0)
    with_serve = "--serve" in sys.argv[1:]
    with_pyspark = "--baseline=pyspark" in sys.argv[1:]
    with_trace_guard = "--trace-overhead" in sys.argv[1:]
    with_metrics_guard = "--metrics-overhead" in sys.argv[1:]
    with_hbm_guard = "--hbm-overhead" in sys.argv[1:]
    hbm_trace_out = _arg_value("--trace-out")
    with_compile_report = "--compile-report" in sys.argv[1:]
    with_accuracy = "--accuracy" in sys.argv[1:]
    with_record = "--record" in sys.argv[1:]
    with_check = "--check" in sys.argv[1:]
    with_big_join = "--skip-big-join" not in sys.argv[1:]
    is_cpu_fallback = "--cpu-fallback" in sys.argv[1:]
    history_dir = _arg_value("--history", "tpu_bench_history")
    wall_threshold = _arg_value("--wall-threshold")
    wall_threshold = float(wall_threshold) if wall_threshold else None
    if not is_cpu_fallback:
        reachable, probe_error = _device_reachable()
        if not reachable:
            _cpu_fallback_reexec(probe_error)
    if with_serve:
        # serving mode: sustained-QPS mix under the session pool + byte
        # admission gate, instead of the single-tenant suite.  Smaller
        # default row count: the measurement is throughput under
        # concurrency, not per-query scan speed.
        serve_rows = int(pos[0]) if pos else 200_000
        concurrency = int(_arg_value("--concurrency", "8"))
        request_io_ms = float(_arg_value("--request-io-ms", "150"))
        deadline_ms = _arg_value("--deadline-ms")
        fact, dim = make_tables(serve_rows)
        root = tempfile.mkdtemp(prefix="spark_rapids_tpu_serve_")
        try:
            pq_path = write_parquet_input(fact, root)
            serve = measure_serve(fact, dim, pq_path,
                                  concurrency=concurrency,
                                  request_io_ms=request_io_ms)
            if deadline_ms is not None:
                serve["cancellations"] = measure_serve_deadlines(
                    fact, dim, pq_path, concurrency=concurrency,
                    deadline_ms=int(deadline_ms))
        finally:
            shutil.rmtree(root, ignore_errors=True)
        out = {
            "metric": "serve_sustained_qps",
            "value": serve["concurrent_qps"],
            "unit": "queries/s",
            "vs_baseline": serve["qps_speedup"],
            "serve": serve,
        }
        if is_cpu_fallback:
            out["backend"] = "cpu_fallback"
            out["probe_error"] = os.environ.get(
                "BENCH_CPU_FALLBACK_ERROR", "accelerator unreachable")
        print(json.dumps(out))
        regress_rc = 0
        if with_record or with_check:
            serve_hist = _arg_value("--history",
                                    "tpu_bench_serve_history")
            regress_rc = record_serve_history(
                serve_hist, serve, with_check, wall_threshold)
        failed = False
        if serve["qps_speedup"] <= 1.0:
            print(f"SERVE QPS GUARD FAILED: concurrent "
                  f"{serve['concurrent_qps']} qps <= serial "
                  f"{serve['serial_qps']} qps", file=sys.stderr)
            failed = True
        if serve.get("slo", {}).get("overhead_pct", 0.0) >= 5.0:
            print(f"SERVE OBSERVATORY OVERHEAD GUARD FAILED: "
                  f"critical-path extraction cost "
                  f"{serve['slo']['overhead_pct']:.2f}% of query wall "
                  f"(>= 5%)", file=sys.stderr)
            failed = True
        if serve["dirty_ledgers"]:
            print(f"SERVE MEMSAN GUARD FAILED: "
                  f"{serve['dirty_ledgers']} dirty ledger(s)",
                  file=sys.stderr)
            failed = True
        if serve["accounting_drift"]:
            print(f"SERVE ADMISSION GUARD FAILED: accounting drift "
                  f"{serve['accounting_drift']} (admitted != completed "
                  f"+ failed)", file=sys.stderr)
            failed = True
        for msg in serve.get("cancellations", {}).get("failures", []):
            print(f"SERVE DEADLINE GUARD FAILED: {msg}",
                  file=sys.stderr)
            failed = True
        sys.exit(1 if failed or regress_rc else 0)
    fact, dim = make_tables(n_rows)
    root = tempfile.mkdtemp(prefix="spark_rapids_tpu_bench_")
    eventlog_dir = None
    if with_record or with_check:
        eventlog_dir = os.path.join(root, "eventlog")
        os.makedirs(eventlog_dir, exist_ok=True)
    spark_cpu = None
    trace_overhead = None
    metrics_overhead = None
    hbm_overhead = None
    regress_rc = 0
    try:
        pq_path = write_parquet_input(fact, root)
        tpu, tpu_compile = time_engine(True, fact, dim, pq_path, root,
                                       eventlog_dir=eventlog_dir)
        cpu, _ = time_engine(False, fact, dim, pq_path, root)
        if with_pyspark:
            spark_cpu = time_pyspark(fact, dim, pq_path, root)
        if with_trace_guard:
            trace_overhead = measure_trace_overhead(fact, dim, pq_path,
                                                    root)
        if with_metrics_guard:
            metrics_overhead = measure_metrics_overhead(
                fact, dim, pq_path, root)
        if with_hbm_guard:
            hbm_overhead = measure_hbm_overhead(
                fact, dim, pq_path, root, trace_out=hbm_trace_out)
        if with_record or with_check:
            regress_rc = record_history(history_dir, eventlog_dir,
                                        with_check, wall_threshold)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    compile_report = None
    if with_compile_report:
        compile_report = measure_compile_report(n_rows)
    accuracy_report = None
    if with_accuracy:
        accuracy_report = measure_accuracy(n_rows)
    tpu_total = sum(tpu.values())
    cpu_total = sum(cpu.values())
    # rows processed: each query consumes the fact table once
    value = (len(tpu) * n_rows) / tpu_total
    in_bytes = fact.nbytes
    detail = {}
    for k in tpu:
        bps = in_bytes / tpu[k]
        detail[k] = {"tpu_s": round(tpu[k], 3),
                     "cpu_s": round(cpu[k], 3),
                     "compile_s": round(tpu_compile[k], 1),
                     "mb_per_s": round(bps / 1e6, 1),
                     "hbm_pct": round(100.0 * bps / _HBM_BYTES_PER_S, 4)}
        if compile_report is not None and k in compile_report:
            # the observatory's measured cold/warm split replaces the
            # lumped first-run-minus-warm guess
            del detail[k]["compile_s"]
            detail[k].update(compile_report[k])
        if accuracy_report is not None and k in accuracy_report:
            detail[k].update(accuracy_report[k])
    big_join = None
    if with_big_join:
        # once, not in the repeated suite loop: the measurement IS a
        # full 256 MB+ build side through the spill-backed catalog
        big_join = measure_big_join()
    cold_s = measure_cache_cold(n_rows)
    out = {
        "metric": "sql_suite_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(cpu_total / tpu_total, 3),
        "cache_cold_compile_s": round(cold_s, 2),
        "detail": detail,
    }
    if big_join is not None:
        out["big_join"] = big_join
    if with_pyspark:
        if spark_cpu is None:
            out["vs_spark_cpu"] = None   # pyspark not importable here
        else:
            out["vs_spark_cpu"] = round(
                sum(spark_cpu.values()) / tpu_total, 3)
            for k in detail:
                detail[k]["spark_cpu_s"] = round(spark_cpu[k], 3)
    if trace_overhead is not None:
        out["trace_overhead_pct"] = round(trace_overhead, 2)
    if metrics_overhead is not None:
        out["metrics_overhead_pct"] = round(metrics_overhead, 2)
    if hbm_overhead is not None:
        out["hbm_overhead_pct"] = round(hbm_overhead, 2)
    if is_cpu_fallback:
        # honest provenance: a real rows/s number, measured on the CPU
        # backend because the accelerator probe failed — never a 0.0
        out["backend"] = "cpu_fallback"
        out["probe_error"] = os.environ.get(
            "BENCH_CPU_FALLBACK_ERROR", "accelerator unreachable")
    print(json.dumps(out))
    if trace_overhead is not None and trace_overhead > 5.0:
        print(f"TRACE OVERHEAD GUARD FAILED: {trace_overhead:.2f}% > 5%",
              file=sys.stderr)
        sys.exit(1)
    if metrics_overhead is not None and metrics_overhead > 2.0:
        print(f"METRICS OVERHEAD GUARD FAILED: "
              f"{metrics_overhead:.2f}% > 2%", file=sys.stderr)
        sys.exit(1)
    if hbm_overhead is not None and hbm_overhead > 5.0:
        print(f"HBM OVERHEAD GUARD FAILED: {hbm_overhead:.2f}% > 5%",
              file=sys.stderr)
        sys.exit(1)
    if regress_rc:
        sys.exit(regress_rc)


if __name__ == "__main__":
    main()
