#!/usr/bin/env python
"""Benchmark: hash-aggregate pipeline throughput, TPU engine vs CPU engine.

Workload mirrors the reference's first-line benchmark shape
(integration_tests hash_aggregate / BASELINE.json config 1): scan ->
filter -> GROUP BY k SUM/AVG/COUNT over int/long/double columns.

Prints ONE JSON line: metric, value (rows/s through the TPU engine),
vs_baseline (speedup over the CPU fallback engine on the same host —
the stand-in for Spark-CPU until a cluster baseline exists).
"""

import json
import sys
import time

import numpy as np
import pyarrow as pa


def make_table(n_rows: int, n_groups: int) -> pa.Table:
    rng = np.random.default_rng(42)
    return pa.table({
        "k": pa.array(rng.integers(0, n_groups, n_rows).astype(np.int64)),
        "v": pa.array(rng.integers(-(10**6), 10**6, n_rows).astype(np.int64)),
        "f": pa.array(rng.random(n_rows)),
    })


def run_query(session, table):
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    df = session.create_dataframe(table)
    return (df.filter(col("v") > -(10**6) // 2)
              .group_by(col("k"))
              .agg(F.sum(col("v")).alias("sv"),
                   F.avg(col("f")).alias("af"),
                   F.count("*").alias("c"))
              .collect())


def time_engine(enabled: bool, table, repeats: int = 3) -> float:
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    enabled).get_or_create()
    run_query(s, table)  # warmup (compile)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = run_query(s, table)
        best = min(best, time.perf_counter() - t0)
    assert out.num_rows > 0
    return best


def main():
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    table = make_table(n_rows, n_groups=100_000)
    tpu_t = time_engine(True, table)
    cpu_t = time_engine(False, table)
    value = n_rows / tpu_t
    print(json.dumps({
        "metric": "hash_agg_pipeline_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(cpu_t / tpu_t, 3),
    }))


if __name__ == "__main__":
    main()
