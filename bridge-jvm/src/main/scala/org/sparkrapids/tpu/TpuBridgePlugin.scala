/*
 * TPU bridge — Spark side.
 *
 * The role Plugin.scala + GpuOverrides.scala play for the reference
 * plugin: inject a physical-plan rule that replaces the largest
 * supported subtree with an exec that runs it inside the TPU engine's
 * sidecar process, splicing Arrow results back as InternalRows.
 *
 * Built by CI against Spark 3.3-3.5 (see bridge-jvm/README.md and
 * .github/workflows/bridge-jvm.yml); the engine's hermetic environment
 * carries no Spark distribution, so this source is validated by the
 * fake-JVM protocol harness on that side (tests/test_bridge.py), the
 * golden-spec fixtures (tests/test_bridge_goldens.py ↔
 * src/test/scala/.../SpecBuilderSuite.scala), and the pyspark-marked
 * integration test where pyspark exists (tests/test_bridge_pyspark.py).
 *
 * Classes that need Spark's private[sql] Arrow machinery live in
 * org.apache.spark.sql.tpubridge (TpuBridgeExec.scala), the same move
 * the reference makes with its org.apache.spark.sql.rapids package.
 */
package org.sparkrapids.tpu

import java.io.{BufferedInputStream, BufferedOutputStream, DataInputStream, DataOutputStream}
import java.net.Socket
import java.nio.charset.StandardCharsets

import scala.collection.mutable.ArrayBuffer

import org.apache.spark.api.plugin.{DriverPlugin, ExecutorPlugin, SparkPlugin}
import org.apache.spark.sql.SparkSessionExtensions
import org.apache.spark.sql.catalyst.expressions._
import org.apache.spark.sql.catalyst.expressions.aggregate._
import org.apache.spark.sql.catalyst.plans._
import org.apache.spark.sql.catalyst.rules.Rule
import org.apache.spark.sql.execution._
import org.apache.spark.sql.execution.aggregate.HashAggregateExec
import org.apache.spark.sql.execution.exchange.{BroadcastExchangeExec, ShuffleExchangeExec}
import org.apache.spark.sql.execution.joins.{BroadcastHashJoinExec, ShuffledHashJoinExec, SortMergeJoinExec}
import org.apache.spark.sql.execution.window.WindowExec
import org.apache.spark.sql.tpubridge.TpuBridgeExec

/** Entry point for --conf spark.sql.extensions=... */
class TpuBridgeExtensions extends (SparkSessionExtensions => Unit) {
  override def apply(ext: SparkSessionExtensions): Unit = {
    ext.injectColumnarRule(_ => TpuBridgeColumnarRule)
  }
}

object TpuBridgeColumnarRule extends org.apache.spark.sql.execution.ColumnarRule {
  override def preColumnarTransitions: Rule[SparkPlan] = TpuBridgeRule
}

/**
 * Replace the largest supported plan prefix with a TpuBridgeExec.  The
 * match collects the chain of spec-capable operators (project/filter/
 * aggregate/sort/limit/window/join) whose expressions all translate;
 * the first untranslatable node becomes the bridge exec's child and
 * executes on the CPU as usual.
 *
 * Placement is restricted to positions where no ancestor can depend on
 * the replaced subtree's outputPartitioning/outputOrdering: the PLAN
 * ROOT, or DIRECTLY BELOW AN EXCHANGE.  This rule runs as a columnar
 * rule, i.e. AFTER EnsureRequirements has satisfied every operator's
 * required distribution and ordering — a TpuBridgeExec reports unknown
 * partitioning and no ordering, and SpecBuilder elides the exchanges
 * and sorts under a bridged SMJ/SHJ, so bridging mid-plan would feed
 * parents silently unpartitioned, unsorted input (no re-planning pass
 * runs afterwards to notice).  Below an exchange both properties are
 * re-established/destroyed anyway, so the replacement is invisible.
 */
object TpuBridgeRule extends Rule[SparkPlan] {
  override def apply(plan: SparkPlan): SparkPlan = {
    if (!plan.conf.getConfString("spark.tpu.bridge.enabled", "false").toBoolean) {
      return plan
    }
    rewrite(plan, atSafeBoundary = true)
  }

  private[tpu] def rewrite(p: SparkPlan, atSafeBoundary: Boolean): SparkPlan =
    p match {
      case p if atSafeBoundary && SpecBuilder.supportedChain(p) =>
        val (ops, child, extraInputs) = SpecBuilder.build(p)
        // keep rewriting below the bridged stage's input (an exchange
        // there re-enables the boundary for its own subtree)
        TpuBridgeExec(p.output, ops,
          rewrite(child, atSafeBoundary = false), extraInputs)
      case e: org.apache.spark.sql.execution.exchange.Exchange =>
        e.withNewChildren(e.children.map(rewrite(_, atSafeBoundary = true)))
      case other =>
        other.withNewChildren(
          other.children.map(rewrite(_, atSafeBoundary = false)))
    }
}

/** Catalyst -> JSON spec translation (mirrors bridge/spec.py). */
object SpecBuilder {
  private[tpu] def json(s: String): String = {
    val sb = new StringBuilder("\"")
    s.foreach {
      case '\\' => sb.append("\\\\")
      case '"'  => sb.append("\\\"")
      case c if c < 0x20 =>
        // bare control chars are invalid JSON; \n, \t etc. included
        sb.append(f"\\u${c.toInt}%04x")
      case c => sb.append(c)
    }
    sb.append('"').toString
  }

  private def nary(op: String, children: Seq[Expression]): Option[String] = {
    val cs = children.map(expr)
    if (cs.exists(_.isEmpty)) None
    else Some(s"""{"op": ${json(op)}, "children": [${cs.flatten.mkString(", ")}]}""")
  }

  // engine-supported cast targets (mirrors spec.py _parse_type coverage)
  private val castable = Set(
    "tinyint", "smallint", "int", "bigint", "float", "double", "string",
    "boolean", "date", "timestamp")

  def expr(e: Expression): Option[String] = e match {
    case a: AttributeReference => Some(s"""{"col": ${json(a.name)}}""")
    case Alias(c, _) => expr(c)
    case l: Literal if l.value == null =>
      Some(s"""{"lit": null, "type": ${json(l.dataType.catalogString)}}""")
    case l: Literal =>
      val v = l.dataType.catalogString match {
        case "string" => json(l.value.toString)
        case "boolean" => l.value.toString
        case "tinyint" | "smallint" | "int" | "bigint" | "float" |
            "double" => l.value.toString
        case _ => return None
      }
      Some(s"""{"lit": $v, "type": ${json(l.dataType.catalogString)}}""")
    case c: Cast if castable(c.dataType.catalogString) =>
      expr(c.child).map(cs =>
        s"""{"op": "cast", "type": ${json(c.dataType.catalogString)}, "children": [$cs]}""")
    case b: BinaryOperator =>
      val op = b match {
        case _: EqualTo            => "eq"
        case _: LessThan           => "lt"
        case _: LessThanOrEqual    => "le"
        case _: GreaterThan       => "gt"
        case _: GreaterThanOrEqual => "ge"
        case _: And                => "and"
        case _: Or                 => "or"
        case _: Add                => "add"
        case _: Subtract           => "sub"
        case _: Multiply           => "mul"
        case _: Divide             => "div"
        case _: Remainder          => "mod"
        case _                     => return None
      }
      nary(op, Seq(b.left, b.right))
    case In(v, list) if list.forall(_.isInstanceOf[Literal]) =>
      for {
        vs <- expr(v)
        items <- {
          val xs = list.map(expr)
          if (xs.exists(_.isEmpty)) None else Some(xs.flatten)
        }
      } yield s"""{"op": "in", "children": [$vs], "values": [${items.mkString(", ")}]}"""
    case Not(EqualTo(l, r)) => nary("ne", Seq(l, r))
    case Not(c)             => nary("not", Seq(c))
    case IsNull(c)          => nary("isnull", Seq(c))
    case IsNotNull(c)       => nary("isnotnull", Seq(c))
    case IsNaN(c)           => nary("isnan", Seq(c))
    case a: Abs             => nary("abs", Seq(a.child))
    case Coalesce(cs)       => nary("coalesce", cs)
    case If(p, t, f)        => nary("if", Seq(p, t, f))
    // --- string tier ------------------------------------------------------
    case Upper(c)           => nary("upper", Seq(c))
    case Lower(c)           => nary("lower", Seq(c))
    case Length(c)          => nary("length", Seq(c))
    case Substring(s, p, l) => nary("substr", Seq(s, p, l))
    case Contains(l, r)     => nary("contains", Seq(l, r))
    case StartsWith(l, r)   => nary("startswith", Seq(l, r))
    case EndsWith(l, r)     => nary("endswith", Seq(l, r))
    case Concat(cs)         => nary("concat", cs)
    case t: StringTrim if t.trimStr.isEmpty      => nary("trim", Seq(t.srcStr))
    case t: StringTrimLeft if t.trimStr.isEmpty  => nary("ltrim", Seq(t.srcStr))
    case t: StringTrimRight if t.trimStr.isEmpty => nary("rtrim", Seq(t.srcStr))
    // --- datetime tier ----------------------------------------------------
    case Year(c)       => nary("year", Seq(c))
    case Month(c)      => nary("month", Seq(c))
    case DayOfMonth(c) => nary("dayofmonth", Seq(c))
    case Hour(c, _)    => nary("hour", Seq(c))
    case Minute(c, _)  => nary("minute", Seq(c))
    case Second(c, _)  => nary("second", Seq(c))
    case DateDiff(l, r) => nary("datediff", Seq(l, r))
    case DateAdd(l, r)  => nary("date_add", Seq(l, r))
    case DateSub(l, r)  => nary("date_sub", Seq(l, r))
    case _ => None
  }

  /** Complete-mode aggregate translation (final values). */
  private def aggFn(a: AggregateFunction): Option[(String, Option[Expression])] =
    a match {
      case s: Sum     => Some(("sum", Some(s.child)))
      case a: Average => Some(("avg", Some(a.child)))
      case m: Min     => Some(("min", Some(m.child)))
      case m: Max     => Some(("max", Some(m.child)))
      case f: First if !f.ignoreNulls => Some(("first", Some(f.child)))
      case l: Last if !l.ignoreNulls  => Some(("last", Some(l.child)))
      case Count(Seq(Literal(1, _))) => Some(("count", None))
      case Count(Seq(c))             => Some(("count", Some(c)))
      case _                         => None
    }

  /**
   * Partial-mode aggregate translation: emit Spark's BUFFER schema (the
   * columns a Final HashAggregateExec above the exchange expects), e.g.
   * avg -> (sum: double, count: long).  One spec agg per buffer column,
   * named after the buffer attribute.
   */
  private def partialAggs(ae: AggregateExpression): Option[Seq[String]] = {
    if (ae.isDistinct || ae.filter.isDefined) return None
    val bufs = ae.aggregateFunction.aggBufferAttributes
    ae.aggregateFunction match {
      case s: Sum if !s.dataType.catalogString.startsWith("decimal") =>
        // buffer layout differs across versions (3.x non-ANSI: [sum]);
        // translate the single-buffer layout only
        if (bufs.length != 1) return None
        val cast = s"""{"op": "cast", "type": ${json(s.dataType.catalogString)}, "children": [%s]}"""
        expr(s.child).map(c => Seq(
          s"""{"fn": "sum", "expr": ${cast.format(c)}, "name": ${json(bufs(0).name)}}"""))
      case a: Average if !a.dataType.catalogString.startsWith("decimal") =>
        if (bufs.length != 2) return None
        expr(a.child).map { c =>
          val sumT = bufs(0).dataType.catalogString
          Seq(
            s"""{"fn": "sum", "expr": {"op": "cast", "type": ${json(sumT)}, "children": [$c]}, "name": ${json(bufs(0).name)}}""",
            s"""{"fn": "count", "expr": $c, "name": ${json(bufs(1).name)}}""")
        }
      case m: Min =>
        expr(m.child).map(c => Seq(
          s"""{"fn": "min", "expr": $c, "name": ${json(bufs(0).name)}}"""))
      case m: Max =>
        expr(m.child).map(c => Seq(
          s"""{"fn": "max", "expr": $c, "name": ${json(bufs(0).name)}}"""))
      case Count(Seq(Literal(1, _))) =>
        Some(Seq(s"""{"fn": "count", "expr": null, "name": ${json(bufs(0).name)}}"""))
      case Count(Seq(c)) =>
        expr(c).map(cs => Seq(
          s"""{"fn": "count", "expr": $cs, "name": ${json(bufs(0).name)}}"""))
      case _ => None
    }
  }

  private def joinHow(t: JoinType): Option[String] = t match {
    case Inner     => Some("inner")
    case LeftOuter => Some("left")
    // NO FullOuter: TpuBridgeExec runs the spec once per stream partition
    // against the whole collected build side, so each partition would
    // emit the build side's unmatched rows (and null-extend build rows
    // matched only in another partition) — duplicated/wrong results for
    // any full outer join with >1 stream partition.  The reference
    // handles full outer via a co-partitioned shuffle only.
    case LeftSemi  => Some("left_semi")
    case LeftAnti  => Some("left_anti")
    case _         => None
  }

  /**
   * Join keys -> spec fields.  Identically-named attribute pairs emit
   * `"on": [names]` (USING semantics).  Differing names emit an equi
   * `"condition"` — valid only when every key name resolves to exactly
   * one side, so the engine's name-based key extraction cannot misbind.
   */
  private def joinKeys(leftKeys: Seq[Expression], rightKeys: Seq[Expression],
                       left: SparkPlan, right: SparkPlan): Option[String] = {
    val pairs = leftKeys.zip(rightKeys).map {
      case (l: AttributeReference, r: AttributeReference) => Some((l, r))
      case _ => None
    }
    if (pairs.exists(_.isEmpty)) return None
    val ps = pairs.flatten
    if (ps.forall { case (l, r) => l.name == r.name }) {
      return Some(s""""on": [${ps.map(p => json(p._1.name)).mkString(", ")}]""")
    }
    val lNames = left.output.map(_.name).toSet
    val rNames = right.output.map(_.name).toSet
    val unambiguous = ps.forall { case (l, r) =>
      !rNames.contains(l.name) && !lNames.contains(r.name)
    }
    if (!unambiguous) return None
    val conds = ps.map { case (l, r) =>
      s"""{"op": "eq", "children": [{"col": ${json(l.name)}}, {"col": ${json(r.name)}}]}"""
    }
    val cond = conds.reduceLeft((a, b) =>
      s"""{"op": "and", "children": [$a, $b]}""")
    Some(s""""condition": $cond""")
  }

  /** Default-frame check: Spark's defaults match the engine's, so these
   *  emit no frame clause (ranking functions force ROWS
   *  UNBOUNDED..CURRENT; ordered aggregates default to RANGE
   *  UNBOUNDED..CURRENT; unordered to the whole partition). */
  private def defaultFrame(frame: Expression, hasOrder: Boolean): Boolean =
    frame match {
      case SpecifiedWindowFrame(RowFrame, UnboundedPreceding, CurrentRow) =>
        true
      case SpecifiedWindowFrame(RangeFrame, UnboundedPreceding, CurrentRow) =>
        hasOrder
      case SpecifiedWindowFrame(_, UnboundedPreceding, UnboundedFollowing) =>
        !hasOrder
      case UnspecifiedFrame => true
      case _ => false
    }

  /** Non-default frames with literal integer bounds emit an explicit
   *  frame clause ("" = engine default; None = untranslatable). */
  private def frameJson(frame: Expression, hasOrder: Boolean): Option[String] = {
    if (defaultFrame(frame, hasOrder)) return Some("")
    def bound(e: Expression): Option[String] = e match {
      case UnboundedPreceding => Some("\"unboundedPreceding\"")
      case UnboundedFollowing => Some("\"unboundedFollowing\"")
      case CurrentRow         => Some("\"currentRow\"")
      case Literal(n: Int, _)  => Some(n.toString)
      case Literal(n: Long, _) => Some(n.toString)
      case _ => None
    }
    frame match {
      case SpecifiedWindowFrame(ft, lo, hi) =>
        val t = ft match {
          case RowFrame   => "rows"
          case RangeFrame => "range"
        }
        for (l <- bound(lo); h <- bound(hi)) yield
          s""", "frame": {"type": ${json(t)}, "start": $l, "end": $h}"""
      case _ => None
    }
  }

  private def windowFn(e: Expression): Option[(String, Option[Expression], Option[Int])] =
    e match {
      case _: RowNumber => Some(("row_number", None, None))
      case _: Rank      => Some(("rank", None, None))
      case _: DenseRank => Some(("dense_rank", None, None))
      case _: PercentRank => Some(("percent_rank", None, None))
      case _: CumeDist    => Some(("cume_dist", None, None))
      case NTile(Literal(n: Int, _)) =>
        // the Python side reads "offset" for lead/lag and "n" for
        // ntile; reuse the offset slot, renamed at emit time
        Some(("ntile", None, Some(n)))
      case l: Lead => (l.offset, l.default) match {
        case (Literal(o: Int, _), Literal(null, _)) =>
          Some(("lead", Some(l.input), Some(o)))
        case _ => None
      }
      case l: Lag => (l.offset, l.default) match {
        case (Literal(o: Int, _), Literal(null, _)) =>
          Some(("lag", Some(l.input), Some(-o)))
        case _ => None
      }
      case ae: AggregateExpression =>
        aggFn(ae.aggregateFunction)
          .map { case (fn, c) => (fn, c, None) }
      case _ => None
    }

  /** Window translation: one spec window op per distinct
   *  (partitionBy, orderBy) group, in output order. */
  private def windowOps(w: WindowExec): Option[List[String]] = {
    case class Grp(part: Seq[Expression], order: Seq[SortOrder],
                   frame: String)
    val grouped = scala.collection.mutable.LinkedHashMap
      .empty[(Seq[String], Seq[String], String), (Grp, ArrayBuffer[String])]
    for (ne <- w.windowExpression) {
      val (name, we) = ne match {
        case Alias(we: WindowExpression, n) => (n, we)
        case _ => return None
      }
      val spec = we.windowSpec
      val fj = frameJson(spec.frameSpecification,
                         spec.orderSpec.nonEmpty).getOrElse(return None)
      val fn = windowFn(we.windowFunction).getOrElse(return None)
      val (fname, child, offset) = fn
      val childJs = child match {
        case Some(c) => expr(c).getOrElse(return None)
        case None    => "null"
      }
      val off = offset.map(o =>
        if (fname == "ntile") s""", "n": $o"""
        else s""", "offset": $o""").getOrElse("")
      val fjson =
        s"""{"fn": ${json(fname)}, "expr": $childJs, "name": ${json(name)}$off}"""
      val key = (spec.partitionSpec.map(_.sql), spec.orderSpec.map(_.sql),
                 fj)
      grouped.getOrElseUpdate(
        key, (Grp(spec.partitionSpec, spec.orderSpec, fj), ArrayBuffer()))
        ._2 += fjson
    }
    val ops = grouped.values.map { case (g, fns) =>
      val parts = g.part.map(expr)
      if (parts.exists(_.isEmpty)) return None
      val orders = g.order.map { so =>
        expr(so.child).map { e =>
          val asc = so.direction == Ascending
          val nf = so.nullOrdering == NullsFirst
          s"""{"expr": $e, "ascending": $asc, "nullsFirst": $nf}"""
        }
      }
      if (orders.exists(_.isEmpty)) return None
      s"""{"op": "window", "partitionBy": [${parts.flatten.mkString(", ")}], """ +
        s""""orderBy": [${orders.flatten.mkString(", ")}], """ +
        s""""funcs": [${fns.mkString(", ")}]${g.frame}}"""
    }
    Some(ops.toList)
  }

  /** Is this node (and its supported chain) fully translatable? */
  def supportedChain(p: SparkPlan): Boolean = build0(p).isDefined

  def build(p: SparkPlan): (String, SparkPlan, Seq[SparkPlan]) =
    build0(p).get

  /** Strip the exchange under a shuffled join input: the sidecar joins
   *  each stream partition against the WHOLE collected build side, so
   *  co-partitioning is unnecessary (and the exchange would re-shuffle
   *  rows the bridge ships anyway). */
  private def stripExchange(p: SparkPlan): SparkPlan = p match {
    case e: ShuffleExchangeExec => stripExchange(e.child)
    case e: BroadcastExchangeExec => stripExchange(e.child)
    // a sort-merge join's per-partition sort: the sidecar hash join
    // needs neither the co-partitioning nor the order
    case SortExec(_, false, child, _) => stripExchange(child)
    case other => other
  }

  /**
   * Broadcast-vs-shuffled CBO threshold (formerly the driver-collect
   * scale ceiling): a shuffled/sort-merge join whose build side's
   * optimizer estimate is under the cap may run as the engine's
   * broadcast-style hash join; past the cap — or when the estimate is
   * unknown — the join translates with `"strategy": "shuffled"`, which
   * pins the engine to the co-partitioned spill-backed shuffle path
   * (both sides hash-exchanged into the spillable shuffle catalog, one
   * co-clustered shard joined at a time).  Nothing falls back anymore:
   * the old behavior of rejecting the translation made
   * maxBuildSideBytes a hard input-scale ceiling.
   */
  private def buildSideFits(build: SparkPlan): Boolean = {
    val cap = try {
      org.apache.spark.sql.internal.SQLConf.get.getConfString(
        "spark.tpu.bridge.maxBuildSideBytes", "268435456").toLong
    } catch { case _: Exception => 268435456L }
    try {
      build.logicalLink.exists(_.stats.sizeInBytes <= cap)
    } catch { case _: Exception => false }
  }

  private def translateJoin(
      joinType: JoinType, leftKeys: Seq[Expression],
      rightKeys: Seq[Expression], condition: Option[Expression],
      left: SparkPlan, right: SparkPlan,
      extra: ArrayBuffer[SparkPlan],
      walk: SparkPlan => Option[(List[String], SparkPlan)],
      gateBuildSize: Boolean)
      : Option[(List[String], SparkPlan)] = {
    val how = joinHow(joinType).getOrElse(return None)
    // residual conditions only on inner joins (engine post-filters)
    if (condition.isDefined && how != "inner") return None
    val outNames = left.output.map(_.name) ++ (how match {
      case "left_semi" | "left_anti" => Nil
      case _ => right.output.map(_.name)
    })
    // Duplicated output names: names are the engine's only addressing.
    // The one recoverable case is an INNER equi join whose duplicates
    // are exactly the identically-named join keys (the common
    // `df.join(dim, on="k")` shape — Spark's USING join keeps BOTH key
    // attributes at the join node): emit the engine's coalescing "on"
    // form and restore the duplicated key columns with a projection.
    // Sound for inner joins only — both sides' key values are equal on
    // every surviving row; an outer join's null-extended side would be
    // resurrected from the wrong side's values.
    val dups = outNames.diff(outNames.distinct).toSet
    val keyPairs = leftKeys.zip(rightKeys).flatMap {
      case (l: AttributeReference, r: AttributeReference) => Some((l, r))
      case _ => None
    }
    val restoreDupKeys = dups.nonEmpty
    if (restoreDupKeys) {
      val allSameNamed = keyPairs.length == leftKeys.length &&
        keyPairs.forall { case (l, r) => l.name == r.name }
      if (how != "inner" || !allSameNamed || condition.isDefined ||
          !dups.subsetOf(keyPairs.map(_._1.name).toSet)) {
        return None
      }
    }
    val keys = joinKeys(leftKeys, rightKeys, left, right)
      .getOrElse(return None)
    val onStyle = keys.startsWith("\"on\"")
    if (onStyle && condition.isDefined) {
      // USING-style keys share names on both sides, so a residual
      // cannot reference them unambiguously — fall back
      return None
    }
    val keyField = condition match {
      case Some(c) =>
        // merge the equi condition with the residual
        val res = expr(c).getOrElse(return None)
        val eq = keys.stripPrefix("\"condition\": ")
        s""""condition": {"op": "and", "children": [$eq, $res]}"""
      case None => keys
    }
    val buildPlan = stripExchange(right)
    // oversized (or unknown-size) build sides no longer reject: they
    // pin the engine's shuffled path, where the build side streams
    // through the spill-backed shuffle catalog one shard at a time
    val forceShuffled = gateBuildSize && !buildSideFits(buildPlan)
    extra += buildPlan
    val idx = extra.size
    walk(stripExchange(left)).map { case (ops, leaf) =>
      val strategyField =
        if (forceShuffled) """, "strategy": "shuffled"""" else ""
      val joinOp =
        s"""{"op": "join", "right": $idx, "how": ${json(how)}$strategyField, $keyField}"""
      val opsOut = if (restoreDupKeys) {
        // the engine's "on" join outputs [keys, left rest, right rest];
        // restore Spark's schema (left.output ++ right.output, key
        // names duplicated) by projecting the coalesced key twice
        val exprs = outNames.map(n =>
          s"""{"expr": {"col": ${json(n)}}, "name": ${json(n)}}""")
        s"""{"op": "project", "exprs": [${exprs.mkString(", ")}]}""" ::
          joinOp :: ops
      } else joinOp :: ops
      (opsOut, leaf)
    }
  }

  private def build0(p: SparkPlan): Option[(String, SparkPlan, Seq[SparkPlan])] = {
    val extra = ArrayBuffer[SparkPlan]()

    def walk(node: SparkPlan): Option[(List[String], SparkPlan)] = node match {
      case ProjectExec(exprs, child) =>
        val parts = exprs.map { ne =>
          expr(ne).map(e => s"""{"expr": $e, "name": ${json(ne.name)}}""")
        }
        if (parts.exists(_.isEmpty)) None
        else walk(child).map { case (ops, leaf) =>
          (s"""{"op": "project", "exprs": [${parts.flatten.mkString(", ")}]}""" :: ops, leaf)
        }
      case FilterExec(cond, child) =>
        expr(cond).flatMap { c =>
          walk(child).map { case (ops, leaf) =>
            (s"""{"op": "filter", "condition": $c}""" :: ops, leaf)
          }
        }
      case agg: HashAggregateExec
          if agg.aggregateExpressions.forall(_.mode == Complete) =>
        val groups = agg.groupingExpressions.map(expr)
        val aggs = agg.aggregateExpressions.map { ae =>
          aggFn(ae.aggregateFunction).flatMap { case (fn, childE) =>
            val ce = childE.map(expr)
            if (ce.exists(_.isEmpty)) None
            else Some(s"""{"fn": ${json(fn)}, "expr": ${ce.flatten.getOrElse("null")}, "name": ${json(ae.resultAttribute.name)}}""")
          }
        }
        if (groups.exists(_.isEmpty) || aggs.exists(_.isEmpty)) None
        else walk(agg.child).map { case (ops, leaf) =>
          (s"""{"op": "aggregate", "groupBy": [${groups.flatten.mkString(", ")}], "aggs": [${aggs.flatten.mkString(", ")}]}""" :: ops, leaf)
        }
      case agg: HashAggregateExec if agg.aggregateExpressions.nonEmpty &&
          agg.aggregateExpressions.forall(_.mode == Partial) =>
        // partial pushdown: emit the buffer schema the Final agg above
        // the exchange expects (ref aggregate.scala partial mode)
        val groups = agg.groupingExpressions.map(expr)
        val aggs = agg.aggregateExpressions.map(partialAggs)
        if (groups.exists(_.isEmpty) || aggs.exists(_.isEmpty)) None
        else walk(agg.child).map { case (ops, leaf) =>
          (s"""{"op": "aggregate", "groupBy": [${groups.flatten.mkString(", ")}], "aggs": [${aggs.flatten.flatten.mkString(", ")}]}""" :: ops, leaf)
        }
      case SortExec(orders, true, child, _) =>
        val os = orders.map { so =>
          expr(so.child).map { e =>
            val asc = so.direction == Ascending
            val nf = so.nullOrdering == NullsFirst
            s"""{"expr": $e, "ascending": $asc, "nullsFirst": $nf}"""
          }
        }
        if (os.exists(_.isEmpty)) None
        else walk(child).map { case (ops, leaf) =>
          (s"""{"op": "sort", "orders": [${os.flatten.mkString(", ")}]}""" :: ops, leaf)
        }
      case w: WindowExec =>
        windowOps(w).flatMap { wops =>
          walk(w.child).map { case (ops, leaf) => (wops ::: ops, leaf) }
        }
      case j: BroadcastHashJoinExec
          if j.buildSide == org.apache.spark.sql.catalyst.optimizer.BuildRight =>
        // Spark's own broadcast threshold already bounded this build side
        translateJoin(j.joinType, j.leftKeys, j.rightKeys, j.condition,
          j.left, j.right, extra, walk, gateBuildSize = false)
      case j: ShuffledHashJoinExec
          if j.buildSide == org.apache.spark.sql.catalyst.optimizer.BuildRight =>
        translateJoin(j.joinType, j.leftKeys, j.rightKeys, j.condition,
          j.left, j.right, extra, walk, gateBuildSize = true)
      case j: SortMergeJoinExec =>
        // the engine replaces sort-merge with hash joins (like the
        // reference's replaceSortMergeJoin); input sort order is not
        // required by the sidecar stage
        translateJoin(j.joinType, j.leftKeys, j.rightKeys, j.condition,
          j.left, j.right, extra, walk, gateBuildSize = true)
      case leaf => Some((Nil, leaf))
    }

    walk(p).flatMap { case (opsTopFirst, leaf) =>
      if (opsTopFirst.isEmpty) None  // nothing to push down
      else {
        val schema = leaf.output.map(a =>
          s"""[${json(a.name)}, ${json(a.dataType.catalogString)}]""")
        val extraSchemas = extra.map(e =>
          s"""{"schema": [${e.output.map(a => s"""[${json(a.name)}, ${json(a.dataType.catalogString)}]""").mkString(", ")}]}""")
        // ops execute bottom-up
        val ops = opsTopFirst.reverse.mkString(", ")
        val spec =
          s"""{"input": {"schema": [${schema.mkString(", ")}]}, """ +
            s""""inputs": [${extraSchemas.mkString(", ")}], "ops": [$ops]}"""
        Some((spec, leaf, extra.toSeq))
      }
    }
  }
}

/** Framed localhost protocol client (bridge/sidecar.py docstring). */
object SidecarClient {
  private val MAGIC = "TPUB".getBytes(StandardCharsets.US_ASCII)

  def executeStage(port: Int, spec: String,
                   inputs: Seq[Array[Byte]]): Array[Byte] = {
    val sock = new Socket("127.0.0.1", port)
    try {
      val out = new DataOutputStream(
        new BufferedOutputStream(sock.getOutputStream))
      val in = new DataInputStream(
        new BufferedInputStream(sock.getInputStream))
      val specBytes = spec.getBytes(StandardCharsets.UTF_8)
      out.write(MAGIC)
      out.writeByte('M')
      out.writeInt(Integer.reverseBytes(specBytes.length))
      out.write(specBytes)
      out.writeInt(Integer.reverseBytes(inputs.size))
      inputs.foreach { ipc =>
        out.writeLong(java.lang.Long.reverseBytes(ipc.length.toLong))
        out.write(ipc)
      }
      out.flush()
      val tag = in.readByte().toChar
      if (tag == 'E') {
        val n = Integer.reverseBytes(in.readInt())
        val msg = new Array[Byte](n)
        in.readFully(msg)
        throw new RuntimeException(
          "TPU sidecar stage failed: " + new String(msg, StandardCharsets.UTF_8))
      }
      val n = java.lang.Long.reverseBytes(in.readLong()).toInt
      val body = new Array[Byte](n)
      in.readFully(body)
      body
    } finally {
      sock.close()
    }
  }
}

/** Executor lifecycle: launch one sidecar per executor, handshake port. */
class TpuBridgeSparkPlugin extends SparkPlugin {
  override def driverPlugin(): DriverPlugin = null
  override def executorPlugin(): ExecutorPlugin = new TpuBridgeExecutorPlugin
}

object TpuBridgeSidecar {
  @volatile var port: Int = -1
  @volatile private var proc: Process = _

  def ensureStarted(): Unit = synchronized {
    if (port > 0) return
    val pb = new ProcessBuilder(
      "python", "-m", "spark_rapids_tpu.bridge.sidecar")
    pb.redirectErrorStream(false)
    proc = pb.start()
    val reader = new java.io.BufferedReader(
      new java.io.InputStreamReader(proc.getInputStream))
    var line = reader.readLine()
    while (line != null && !line.startsWith("TPU_SIDECAR_PORT=")) {
      line = reader.readLine()
    }
    require(line != null, "sidecar never announced its port")
    port = line.stripPrefix("TPU_SIDECAR_PORT=").trim.toInt
  }

  def stop(): Unit = synchronized {
    if (proc != null) proc.destroy()
    port = -1
  }
}

class TpuBridgeExecutorPlugin extends ExecutorPlugin {
  override def init(ctx: org.apache.spark.api.plugin.PluginContext,
                    extraConf: java.util.Map[String, String]): Unit = {
    TpuBridgeSidecar.ensureStarted()
  }
  override def shutdown(): Unit = TpuBridgeSidecar.stop()
}
