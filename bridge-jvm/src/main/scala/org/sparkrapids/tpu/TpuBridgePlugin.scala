/*
 * TPU bridge — Spark side.
 *
 * The role Plugin.scala + GpuOverrides.scala play for the reference
 * plugin: inject a physical-plan rule that replaces the largest
 * supported subtree with an exec that runs it inside the TPU engine's
 * sidecar process, splicing Arrow results back as InternalRows.
 *
 * Built by CI against Spark 3.3-3.5 (see bridge-jvm/README.md); the
 * engine's hermetic environment carries no Spark distribution, so this
 * source is validated by the fake-JVM protocol harness on that side
 * (tests/test_bridge.py) and by the pyspark-marked integration test
 * where pyspark exists (tests/test_bridge_pyspark.py).
 */
package org.sparkrapids.tpu

import java.io.{BufferedInputStream, BufferedOutputStream, DataInputStream, DataOutputStream}
import java.net.Socket
import java.nio.charset.StandardCharsets

import scala.collection.mutable.ArrayBuffer

import org.apache.spark.api.plugin.{DriverPlugin, ExecutorPlugin, SparkPlugin}
import org.apache.spark.rdd.RDD
import org.apache.spark.sql.SparkSessionExtensions
import org.apache.spark.sql.catalyst.InternalRow
import org.apache.spark.sql.catalyst.expressions._
import org.apache.spark.sql.catalyst.expressions.aggregate._
import org.apache.spark.sql.catalyst.rules.Rule
import org.apache.spark.sql.execution._
import org.apache.spark.sql.execution.aggregate.HashAggregateExec
import org.apache.spark.sql.execution.arrow.ArrowConverters
import org.apache.spark.sql.execution.joins.BroadcastHashJoinExec
import org.apache.spark.sql.execution.window.WindowExec
import org.apache.spark.sql.types.StructType
import org.apache.spark.sql.util.ArrowUtils

/** Entry point for --conf spark.sql.extensions=... */
class TpuBridgeExtensions extends (SparkSessionExtensions => Unit) {
  override def apply(ext: SparkSessionExtensions): Unit = {
    ext.injectColumnarRule(_ => TpuBridgeColumnarRule)
  }
}

object TpuBridgeColumnarRule extends org.apache.spark.sql.execution.ColumnarRule {
  override def preColumnarTransitions: Rule[SparkPlan] = TpuBridgeRule
}

/**
 * Replace the largest supported plan prefix with a TpuBridgeExec.  The
 * match walks top-down: at each node, collect the chain of spec-capable
 * operators (project/filter/aggregate/sort/limit/window/broadcast join)
 * whose expressions all translate; the first untranslatable node becomes
 * the bridge exec's child and executes on the CPU as usual.
 */
object TpuBridgeRule extends Rule[SparkPlan] {
  override def apply(plan: SparkPlan): SparkPlan = {
    if (!plan.conf.getConfString("spark.tpu.bridge.enabled", "false").toBoolean) {
      return plan
    }
    plan.transformDown {
      case p if SpecBuilder.supportedChain(p) =>
        val (ops, child, extraInputs) = SpecBuilder.build(p)
        TpuBridgeExec(p.output, ops, child, extraInputs)
    }
  }
}

/** Catalyst -> JSON spec translation (mirrors bridge/spec.py). */
object SpecBuilder {
  private def json(s: String): String =
    "\"" + s.replace("\\", "\\\\").replace("\"", "\\\"") + "\""

  def expr(e: Expression): Option[String] = e match {
    case a: AttributeReference => Some(s"""{"col": ${json(a.name)}}""")
    case Alias(c, _) => expr(c)
    case l: Literal if l.value == null =>
      Some(s"""{"lit": null, "type": ${json(l.dataType.catalogString)}}""")
    case l: Literal =>
      val v = l.dataType.catalogString match {
        case "string" => json(l.value.toString)
        case _        => l.value.toString
      }
      Some(s"""{"lit": $v, "type": ${json(l.dataType.catalogString)}}""")
    case b: BinaryOperator =>
      val op = b match {
        case _: EqualTo            => "eq"
        case _: LessThan           => "lt"
        case _: LessThanOrEqual    => "le"
        case _: GreaterThan        => "gt"
        case _: GreaterThanOrEqual => "ge"
        case _: And                => "and"
        case _: Or                 => "or"
        case _: Add                => "add"
        case _: Subtract           => "sub"
        case _: Multiply           => "mul"
        case _: Divide             => "div"
        case _                     => return None
      }
      for (l <- expr(b.left); r <- expr(b.right))
        yield s"""{"op": ${json(op)}, "children": [$l, $r]}"""
    case Not(EqualTo(l, r)) =>
      for (ls <- expr(l); rs <- expr(r))
        yield s"""{"op": "ne", "children": [$ls, $rs]}"""
    case Not(c) => expr(c).map(cs => s"""{"op": "not", "children": [$cs]}""")
    case IsNull(c) =>
      expr(c).map(cs => s"""{"op": "isnull", "children": [$cs]}""")
    case IsNotNull(c) =>
      expr(c).map(cs => s"""{"op": "isnotnull", "children": [$cs]}""")
    case _ => None
  }

  private def aggFn(a: AggregateFunction): Option[(String, Option[Expression])] =
    a match {
      case Sum(c, _)           => Some(("sum", Some(c)))
      case Average(c, _)       => Some(("avg", Some(c)))
      case Min(c)              => Some(("min", Some(c)))
      case Max(c)              => Some(("max", Some(c)))
      case Count(Seq(Literal(1, _))) => Some(("count", None))
      case Count(Seq(c))       => Some(("count", Some(c)))
      case _                   => None
    }

  /** Is this node (and its supported chain) fully translatable? */
  def supportedChain(p: SparkPlan): Boolean = build0(p).isDefined

  def build(p: SparkPlan): (String, SparkPlan, Seq[SparkPlan]) =
    build0(p).get

  private def build0(p: SparkPlan): Option[(String, SparkPlan, Seq[SparkPlan])] = {
    val extra = ArrayBuffer[SparkPlan]()

    def walk(node: SparkPlan): Option[(List[String], SparkPlan)] = node match {
      case ProjectExec(exprs, child) =>
        val parts = exprs.map { ne =>
          expr(ne).map(e => s"""{"expr": $e, "name": ${json(ne.name)}}""")
        }
        if (parts.exists(_.isEmpty)) None
        else walk(child).map { case (ops, leaf) =>
          (s"""{"op": "project", "exprs": [${parts.flatten.mkString(", ")}]}""" :: ops, leaf)
        }
      case FilterExec(cond, child) =>
        expr(cond).flatMap { c =>
          walk(child).map { case (ops, leaf) =>
            (s"""{"op": "filter", "condition": $c}""" :: ops, leaf)
          }
        }
      case agg: HashAggregateExec if agg.aggregateExpressions.forall(
          // Complete only: a Partial node must emit Spark's buffer
          // schema (e.g. avg -> (sum, count)), not final values
          ae => ae.mode == Complete) =>
        val groups = agg.groupingExpressions.map(expr)
        val aggs = agg.aggregateExpressions.map { ae =>
          aggFn(ae.aggregateFunction).flatMap { case (fn, childE) =>
            val ce = childE.map(expr)
            if (ce.exists(_.isEmpty)) None
            else Some(s"""{"fn": ${json(fn)}, "expr": ${ce.flatten.getOrElse("null")}, "name": ${json(ae.resultAttribute.name)}}""")
          }
        }
        if (groups.exists(_.isEmpty) || aggs.exists(_.isEmpty)) None
        else walk(agg.child).map { case (ops, leaf) =>
          (s"""{"op": "aggregate", "groupBy": [${groups.flatten.mkString(", ")}], "aggs": [${aggs.flatten.mkString(", ")}]}""" :: ops, leaf)
        }
      case SortExec(orders, true, child, _) =>
        val os = orders.map { so =>
          expr(so.child).map { e =>
            val asc = so.direction == Ascending
            val nf = so.nullOrdering == NullsFirst
            s"""{"expr": $e, "ascending": $asc, "nullsFirst": $nf}"""
          }
        }
        if (os.exists(_.isEmpty)) None
        else walk(child).map { case (ops, leaf) =>
          (s"""{"op": "sort", "orders": [${os.flatten.mkString(", ")}]}""" :: ops, leaf)
        }
      case j: BroadcastHashJoinExec
          if j.condition.isEmpty &&
            j.buildSide == org.apache.spark.sql.catalyst.optimizer.BuildRight =>
        // engine join-type names differ from JoinType.sql
        val how = j.joinType match {
          case org.apache.spark.sql.catalyst.plans.Inner     => Some("inner")
          case org.apache.spark.sql.catalyst.plans.LeftOuter => Some("left")
          case org.apache.spark.sql.catalyst.plans.FullOuter => Some("full")
          case org.apache.spark.sql.catalyst.plans.LeftSemi  => Some("left_semi")
          case org.apache.spark.sql.catalyst.plans.LeftAnti  => Some("left_anti")
          case _                                             => None
        }
        val keys = j.leftKeys.zip(j.rightKeys).map {
          case (l: AttributeReference, r: AttributeReference)
              if l.name == r.name => Some(json(l.name))
          case _ => None
        }
        if (keys.exists(_.isEmpty) || how.isEmpty) None
        else {
          // collect the build side BELOW the broadcast exchange —
          // BroadcastExchangeExec throws on the execute() code path
          val buildPlan = j.right match {
            case b: org.apache.spark.sql.execution.exchange.BroadcastExchangeExec =>
              b.child
            case other => other
          }
          extra += buildPlan
          val idx = extra.size
          walk(j.left).map { case (ops, leaf) =>
            (s"""{"op": "join", "right": $idx, "how": "${how.get}", "on": [${keys.flatten.mkString(", ")}]}""" :: ops, leaf)
          }
        }
      case w: WindowExec => None // window translation: follow-up; spec carries it
      case leaf => Some((Nil, leaf))
    }

    walk(p).flatMap { case (opsTopFirst, leaf) =>
      if (opsTopFirst.isEmpty) None  // nothing to push down
      else {
        val schema = leaf.output.map(a =>
          s"""[${json(a.name)}, ${json(a.dataType.catalogString)}]""")
        val extraSchemas = extra.map(e =>
          s"""{"schema": [${e.output.map(a => s"""[${json(a.name)}, ${json(a.dataType.catalogString)}]""").mkString(", ")}]}""")
        // ops execute bottom-up
        val ops = opsTopFirst.reverse.mkString(", ")
        val spec =
          s"""{"input": {"schema": [${schema.mkString(", ")}]}, """ +
            s""""inputs": [${extraSchemas.mkString(", ")}], "ops": [$ops]}"""
        Some((spec, leaf, extra.toSeq))
      }
    }
  }
}

/**
 * Executes `child` normally, ships each partition (plus the collected
 * extra-input plans, broadcast to every task) through the sidecar
 * protocol, and returns the sidecar's Arrow result rows.
 */
case class TpuBridgeExec(
    output: Seq[Attribute],
    spec: String,
    child: SparkPlan,
    extraInputs: Seq[SparkPlan]) extends UnaryExecNode {

  override protected def doExecute(): RDD[InternalRow] = {
    val childSchema = child.schema
    val outSchema = StructType.fromAttributes(output)
    val timeZone = conf.sessionLocalTimeZone
    val port = conf.getConfString("spark.tpu.bridge.port",
      TpuBridgeSidecar.port.toString).toInt
    val specStr = spec
    // extra inputs (join builds) are small broadcast-side plans:
    // collect them once on the driver as Arrow payloads
    val extras: Seq[Array[Byte]] = extraInputs.map { p =>
      ArrowWire.planToIpc(p, timeZone)
    }
    val extrasBc = sparkContext.broadcast(extras)
    child.execute().mapPartitionsInternal { rows =>
      val ipc = ArrowWire.rowsToIpc(rows, childSchema, timeZone)
      val result = SidecarClient.executeStage(
        port, specStr, ipc +: extrasBc.value)
      ArrowWire.ipcToRows(result, outSchema, timeZone)
    }
  }

  override protected def withNewChildInternal(newChild: SparkPlan): SparkPlan =
    copy(child = newChild)
}

/** Arrow IPC helpers over Spark's ArrowConverters. */
object ArrowWire {
  def rowsToIpc(rows: Iterator[InternalRow], schema: StructType,
                timeZone: String): Array[Byte] = {
    val batches = ArrowConverters.toBatchIterator(
      rows, schema, Int.MaxValue, timeZone, org.apache.spark.TaskContext.get())
    // toBatchIterator yields record-batch payloads; frame them as one
    // IPC stream with the schema header
    ArrowConverters.toArrowStream(schema, batches, timeZone)
  }

  def planToIpc(p: SparkPlan, timeZone: String): Array[Byte] = {
    val rows = p.executeCollect().iterator
    rowsToIpc(rows, p.schema, timeZone)
  }

  def ipcToRows(ipc: Array[Byte], schema: StructType,
                timeZone: String): Iterator[InternalRow] = {
    ArrowConverters.fromArrowStream(ipc, schema, timeZone)
  }
}

/** Framed localhost protocol client (bridge/sidecar.py docstring). */
object SidecarClient {
  private val MAGIC = "TPUB".getBytes(StandardCharsets.US_ASCII)

  def executeStage(port: Int, spec: String,
                   inputs: Seq[Array[Byte]]): Array[Byte] = {
    val sock = new Socket("127.0.0.1", port)
    try {
      val out = new DataOutputStream(
        new BufferedOutputStream(sock.getOutputStream))
      val in = new DataInputStream(
        new BufferedInputStream(sock.getInputStream))
      val specBytes = spec.getBytes(StandardCharsets.UTF_8)
      out.write(MAGIC)
      out.writeByte('M')
      out.writeInt(Integer.reverseBytes(specBytes.length))
      out.write(specBytes)
      out.writeInt(Integer.reverseBytes(inputs.size))
      inputs.foreach { ipc =>
        out.writeLong(java.lang.Long.reverseBytes(ipc.length.toLong))
        out.write(ipc)
      }
      out.flush()
      val tag = in.readByte().toChar
      if (tag == 'E') {
        val n = Integer.reverseBytes(in.readInt())
        val msg = new Array[Byte](n)
        in.readFully(msg)
        throw new RuntimeException(
          "TPU sidecar stage failed: " + new String(msg, StandardCharsets.UTF_8))
      }
      val n = java.lang.Long.reverseBytes(in.readLong()).toInt
      val body = new Array[Byte](n)
      in.readFully(body)
      body
    } finally {
      sock.close()
    }
  }
}

/** Executor lifecycle: launch one sidecar per executor, handshake port. */
class TpuBridgeSparkPlugin extends SparkPlugin {
  override def driverPlugin(): DriverPlugin = null
  override def executorPlugin(): ExecutorPlugin = new TpuBridgeExecutorPlugin
}

object TpuBridgeSidecar {
  @volatile var port: Int = -1
  @volatile private var proc: Process = _

  def ensureStarted(): Unit = synchronized {
    if (port > 0) return
    val pb = new ProcessBuilder(
      "python", "-m", "spark_rapids_tpu.bridge.sidecar")
    pb.redirectErrorStream(false)
    proc = pb.start()
    val reader = new java.io.BufferedReader(
      new java.io.InputStreamReader(proc.getInputStream))
    var line = reader.readLine()
    while (line != null && !line.startsWith("TPU_SIDECAR_PORT=")) {
      line = reader.readLine()
    }
    require(line != null, "sidecar never announced its port")
    port = line.stripPrefix("TPU_SIDECAR_PORT=").trim.toInt
  }

  def stop(): Unit = synchronized {
    if (proc != null) proc.destroy()
    port = -1
  }
}

class TpuBridgeExecutorPlugin extends ExecutorPlugin {
  override def init(ctx: org.apache.spark.api.plugin.PluginContext,
                    extraConf: java.util.Map[String, String]): Unit = {
    TpuBridgeSidecar.ensureStarted()
  }
  override def shutdown(): Unit = TpuBridgeSidecar.stop()
}
