/*
 * TPU bridge exec + Arrow wire (Spark side).
 *
 * Lives inside the org.apache.spark.sql namespace to reach Spark's
 * private[sql] ArrowWriter — the same move the reference plugin makes
 * with its org.apache.spark.sql.rapids package (ref
 * sql-plugin/src/main/scala/org/apache/spark/sql/rapids/).
 *
 * The Arrow schema construction is our own (a fixed mapping for the
 * bridge's supported type subset) instead of ArrowUtils.toArrowSchema:
 * that private helper changed arity in every minor release (3.3 -> 3.5),
 * while arrow-java's own Schema/Field API and Spark's
 * ArrowWriter.create(root) are stable across all of them.
 */
package org.apache.spark.sql.tpubridge

import java.io.{ByteArrayInputStream, ByteArrayOutputStream}
import java.nio.channels.Channels

import scala.collection.JavaConverters._
import scala.collection.mutable.ArrayBuffer

import org.apache.arrow.memory.RootAllocator
import org.apache.arrow.vector.VectorSchemaRoot
import org.apache.arrow.vector.ipc.{ArrowStreamReader, ArrowStreamWriter}
import org.apache.arrow.vector.types.{DateUnit, FloatingPointPrecision, TimeUnit}
import org.apache.arrow.vector.types.pojo.{ArrowType, Field, FieldType, Schema}

import org.apache.spark.rdd.RDD
import org.apache.spark.sql.catalyst.InternalRow
import org.apache.spark.sql.catalyst.expressions.{Attribute, UnsafeProjection}
import org.apache.spark.sql.execution.{SparkPlan, UnaryExecNode}
import org.apache.spark.sql.execution.arrow.ArrowWriter
import org.apache.spark.sql.types._
import org.apache.spark.sql.vectorized.{ArrowColumnVector, ColumnVector, ColumnarBatch}

/**
 * Executes `child` normally, ships each partition (plus the collected
 * extra-input plans, broadcast to every task) through the sidecar
 * protocol, and returns the sidecar's Arrow result rows.
 */
case class TpuBridgeExec(
    output: Seq[Attribute],
    spec: String,
    child: SparkPlan,
    extraInputs: Seq[SparkPlan]) extends UnaryExecNode {

  override protected def doExecute(): RDD[InternalRow] = {
    val childSchema = child.schema
    val outSchema = StructType(output.map(a =>
      StructField(a.name, a.dataType, a.nullable)))
    val port = conf.getConfString("spark.tpu.bridge.port",
      org.sparkrapids.tpu.TpuBridgeSidecar.port.toString).toInt
    val specStr = spec
    // extra inputs (join builds) are small build-side plans: collect
    // them once on the driver as Arrow payloads
    val extras: Seq[Array[Byte]] = extraInputs.map(ArrowWire.planToIpc)
    val extrasBc = sparkContext.broadcast(extras)
    child.execute().mapPartitions { rows =>
      val ipc = ArrowWire.rowsToIpc(rows, childSchema)
      val result = org.sparkrapids.tpu.SidecarClient.executeStage(
        port, specStr, ipc +: extrasBc.value)
      ArrowWire.ipcToRows(result, outSchema)
    }
  }

  override protected def withNewChildInternal(newChild: SparkPlan): SparkPlan =
    copy(child = newChild)
}

/** Arrow IPC helpers: InternalRow <-> one-stream IPC payloads. */
object ArrowWire {
  private val BATCH_ROWS = 1 << 16

  private def toArrowType(dt: DataType): ArrowType = dt match {
    case BooleanType => ArrowType.Bool.INSTANCE
    case ByteType => new ArrowType.Int(8, true)
    case ShortType => new ArrowType.Int(16, true)
    case IntegerType => new ArrowType.Int(32, true)
    case LongType => new ArrowType.Int(64, true)
    case FloatType =>
      new ArrowType.FloatingPoint(FloatingPointPrecision.SINGLE)
    case DoubleType =>
      new ArrowType.FloatingPoint(FloatingPointPrecision.DOUBLE)
    case StringType => ArrowType.Utf8.INSTANCE
    case BinaryType => ArrowType.Binary.INSTANCE
    case DateType => new ArrowType.Date(DateUnit.DAY)
    case TimestampType => new ArrowType.Timestamp(TimeUnit.MICROSECOND, "UTC")
    case d: DecimalType => ArrowType.Decimal.createDecimal(
      d.precision, d.scale, null)
    case other => throw new UnsupportedOperationException(
      s"bridge wire does not carry ${other.catalogString}")
  }

  def toArrowSchema(schema: StructType): Schema =
    new Schema(schema.map { f =>
      new Field(f.name,
        new FieldType(f.nullable, toArrowType(f.dataType), null),
        java.util.Collections.emptyList[Field]())
    }.asJava)

  def rowsToIpc(rows: Iterator[InternalRow],
                schema: StructType): Array[Byte] = {
    val allocator = new RootAllocator(Long.MaxValue)
    val root = VectorSchemaRoot.create(toArrowSchema(schema), allocator)
    try {
      val writer = ArrowWriter.create(root)
      val bos = new ByteArrayOutputStream()
      val sw = new ArrowStreamWriter(root, null, Channels.newChannel(bos))
      sw.start()
      var pending = 0
      while (rows.hasNext) {
        writer.write(rows.next())
        pending += 1
        if (pending == BATCH_ROWS) {
          writer.finish(); sw.writeBatch(); writer.reset(); pending = 0
        }
      }
      // final (possibly empty) batch carries the schema for empty
      // partitions
      writer.finish(); sw.writeBatch()
      sw.end()
      bos.toByteArray
    } finally {
      root.close()
      allocator.close()
    }
  }

  def planToIpc(p: SparkPlan): Array[Byte] =
    rowsToIpc(p.executeCollect().iterator, p.schema)

  def ipcToRows(ipc: Array[Byte],
                schema: StructType): Iterator[InternalRow] = {
    val allocator = new RootAllocator(Long.MaxValue)
    val reader = new ArrowStreamReader(
      new ByteArrayInputStream(ipc), allocator)
    val proj = UnsafeProjection.create(schema)
    val out = ArrayBuffer[InternalRow]()
    try {
      while (reader.loadNextBatch()) {
        val root = reader.getVectorSchemaRoot
        if (root.getRowCount > 0) {
          val cols: Array[ColumnVector] = root.getFieldVectors.asScala
            .map(v => new ArrowColumnVector(v): ColumnVector).toArray
          val batch = new ColumnarBatch(cols, root.getRowCount)
          val it = batch.rowIterator()
          while (it.hasNext) {
            out += proj(it.next()).copy()
          }
        }
      }
    } finally {
      reader.close()
      allocator.close()
    }
    out.iterator
  }
}
