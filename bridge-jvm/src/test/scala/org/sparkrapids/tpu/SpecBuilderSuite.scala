/*
 * Golden-file tests for SpecBuilder: build real Spark physical plans
 * with a local session, translate them, and compare against the JSON
 * fixtures in src/test/resources/goldens/ — the SAME fixtures the
 * Python side executes end-to-end (tests/test_bridge_goldens.py), so a
 * green run on both sides proves the wire contract from Catalyst down
 * to the engine's results.
 */
package org.sparkrapids.tpu

import org.apache.spark.sql.{DataFrame, SparkSession}
import org.apache.spark.sql.functions._
import org.json4s._
import org.json4s.jackson.JsonMethods
import org.scalatest.funsuite.AnyFunSuite

class SpecBuilderSuite extends AnyFunSuite {

  private lazy val spark: SparkSession = SparkSession.builder()
    .master("local[1]")
    .appName("SpecBuilderSuite")
    .config("spark.sql.codegen.wholeStage", "false")
    .config("spark.sql.adaptive.enabled", "false")
    .config("spark.sql.shuffle.partitions", "2")
    .config("spark.ui.enabled", "false")
    .getOrCreate()

  /** First supported stage found top-down — what TpuBridgeRule replaces. */
  private def specOf(df: DataFrame): String = {
    val plan = df.queryExecution.executedPlan
    val found = plan.collectFirst {
      case p if SpecBuilder.supportedChain(p) => SpecBuilder.build(p)._1
    }
    assert(found.isDefined, s"no supported stage in:\n$plan")
    found.get
  }

  /** Order-insensitive on object fields, order-sensitive on arrays. */
  private def canon(v: JValue): JValue = v match {
    case JObject(fields) =>
      JObject(fields.map { case (k, x) => (k, canon(x)) }.sortBy(_._1))
    case JArray(items) => JArray(items.map(canon))
    case other => other
  }

  private def golden(name: String): JValue = {
    val in = getClass.getResourceAsStream(s"/goldens/$name.json")
    assert(in != null, s"missing golden $name")
    val txt = scala.io.Source.fromInputStream(in, "UTF-8").mkString
    canon(JsonMethods.parse(txt) \ "spec")
  }

  private def check(name: String, df: DataFrame): Unit = {
    val got = canon(JsonMethods.parse(specOf(df)))
    val want = golden(name)
    assert(got == want,
      s"spec drift for $name:\n got: ${JsonMethods.compact(got)}\nwant: ${JsonMethods.compact(want)}")
  }

  import spark.implicits._

  test("filter + project") {
    val df = Seq((1L, 2L), (3L, -4L)).toDF("k", "v")
      .filter($"v" > 0).select($"k", ($"v" * 2).as("v2"))
    check("filter_project", df)
  }

  test("partial aggregate emits the buffer schema") {
    val df = Seq((1L, 2L), (1L, 3L), (2L, 4L)).toDF("k", "v")
      .groupBy($"k").agg(sum($"v").as("sv"), avg($"v").as("av"))
    check("partial_aggregate", df)
  }

  test("window: row_number + running sum") {
    import org.apache.spark.sql.expressions.Window
    val w = Window.partitionBy($"k").orderBy($"v")
    val df = Seq((1L, 2L), (1L, 3L), (2L, 4L)).toDF("k", "v")
      .select($"k", $"v",
        row_number().over(w).as("rn"), sum($"v").over(w).as("rs"))
    check("window_rownum_runsum", df)
  }

  test("shuffled join with differing key names") {
    val prev = spark.conf.get("spark.sql.autoBroadcastJoinThreshold")
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", "-1")
    try {
      val fact = Seq((1L, 10L), (2L, 20L)).toDF("id", "x")
      val dim = Seq((1L, 100L), (2L, 200L)).toDF("user_id", "w")
      val df = fact.join(dim, $"id" === $"user_id", "inner")
        .select($"x", $"w")
      check("shuffled_join_diff_keys", df)
    } finally {
      spark.conf.set("spark.sql.autoBroadcastJoinThreshold", prev)
    }
  }

  test("same-name inner equi join restores duplicated key columns") {
    val prev = spark.conf.get("spark.sql.autoBroadcastJoinThreshold")
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", "-1")
    try {
      val fact = Seq((1L, 10L), (2L, 20L)).toDF("k", "x")
      val dim = Seq((1L, 100L), (2L, 200L)).toDF("k", "w")
      val df = fact.join(dim, fact("k") === dim("k"), "inner")
      check("shuffled_join_same_keys", df)
    } finally {
      spark.conf.set("spark.sql.autoBroadcastJoinThreshold", prev)
    }
  }

  test("same-name OUTER equi join stays untranslatable") {
    // restoring the duplicated key from the coalesced "on" column is
    // only exact when both sides' values agree on every row — an outer
    // join's null-extended side would be resurrected from the wrong
    // side's values, so the fallback must hold
    val prev = spark.conf.get("spark.sql.autoBroadcastJoinThreshold")
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", "-1")
    try {
      val fact = Seq((1L, 10L), (2L, 20L)).toDF("k", "x")
      val dim = Seq((1L, 100L), (3L, 300L)).toDF("k", "w")
      val df = fact.join(dim, fact("k") === dim("k"), "left")
      val plan = df.queryExecution.executedPlan
      val join = plan.collectFirst {
        case j: org.apache.spark.sql.execution.joins.SortMergeJoinExec => j
        case j: org.apache.spark.sql.execution.joins.ShuffledHashJoinExec => j
      }
      assert(join.isDefined, s"no shuffled join in:\n$plan")
      assert(!SpecBuilder.supportedChain(join.get))
    } finally {
      spark.conf.set("spark.sql.autoBroadcastJoinThreshold", prev)
    }
  }

  test("shuffled join build side above the size cap pins the shuffled " +
       "strategy") {
    // maxBuildSideBytes used to be a hard translation ceiling (the
    // build side was executeCollect()-ed whole to the driver); it is
    // now only the broadcast-vs-shuffled CBO threshold: an over-cap
    // (or unknown-size) build side still translates, with the join op
    // pinned to the engine's spill-backed shuffled path
    val prevBc = spark.conf.get("spark.sql.autoBroadcastJoinThreshold")
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", "-1")
    try {
      val fact = Seq((1L, 10L), (2L, 20L)).toDF("id", "x")
      val dim = Seq((1L, 100L), (2L, 200L)).toDF("user_id", "w")
      val df = fact.join(dim, $"id" === $"user_id", "inner")
      val join = df.queryExecution.executedPlan.collectFirst {
        case j: org.apache.spark.sql.execution.joins.SortMergeJoinExec => j
        case j: org.apache.spark.sql.execution.joins.ShuffledHashJoinExec => j
      }.get
      spark.conf.set("spark.tpu.bridge.maxBuildSideBytes", "1")
      try {
        assert(SpecBuilder.supportedChain(join)) // no longer a ceiling
        assert(SpecBuilder.build(join)._1
          .contains(""""strategy": "shuffled""""))
      } finally {
        spark.conf.unset("spark.tpu.bridge.maxBuildSideBytes")
      }
      assert(SpecBuilder.supportedChain(join))
      // under the default cap the CBO may still pick broadcast-style
      assert(!SpecBuilder.build(join)._1.contains(""""strategy""""))
    } finally {
      spark.conf.set("spark.sql.autoBroadcastJoinThreshold", prevBc)
    }
  }

  test("forced shuffled join matches its golden") {
    val prevBc = spark.conf.get("spark.sql.autoBroadcastJoinThreshold")
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", "-1")
    spark.conf.set("spark.tpu.bridge.maxBuildSideBytes", "1")
    try {
      val fact = Seq((1L, 10L), (2L, 20L)).toDF("id", "x")
      val dim = Seq((1L, 100L), (2L, 200L)).toDF("user_id", "w")
      val df = fact.join(dim, $"id" === $"user_id", "inner")
        .select($"x", $"w")
      check("shuffled_join_forced", df)
    } finally {
      spark.conf.unset("spark.tpu.bridge.maxBuildSideBytes")
      spark.conf.set("spark.sql.autoBroadcastJoinThreshold", prevBc)
    }
  }

  test("bridge applies only at the root or directly below an exchange") {
    import org.apache.spark.sql.tpubridge.TpuBridgeExec
    spark.conf.set("spark.tpu.bridge.enabled", "true")
    try {
      // whole plan supported -> replaced at the root
      val root = Seq((1L, 2L), (3L, -4L)).toDF("k", "v")
        .filter($"v" > 0).select($"k", ($"v" * 2).as("v2"))
      assert(TpuBridgeRule(root.queryExecution.executedPlan)
        .isInstanceOf[TpuBridgeExec])
      // an untranslatable parent with NO exchange in between: the
      // supported chain below it must NOT bridge — TpuBridgeExec
      // reports unknown partitioning/ordering and EnsureRequirements
      // has already run, so a mid-plan replacement feeds ancestors
      // unpartitioned, unsorted input
      val mid = Seq((1L, 2L), (3L, -4L)).toDF("k", "v")
        .filter($"v" > 0)
        .select($"k", monotonically_increasing_id().as("id"))
      val midPlan = TpuBridgeRule(mid.queryExecution.executedPlan)
      assert(midPlan.collectFirst { case b: TpuBridgeExec => b }.isEmpty,
        s"bridged mid-plan:\n$midPlan")
      // ...but directly below an exchange the replacement is invisible
      // (partitioning is re-established, ordering destroyed anyway)
      val below = Seq((1L, 2L), (3L, -4L)).toDF("k", "v")
        .filter($"v" > 0).repartition($"k")
      val belowPlan = TpuBridgeRule(below.queryExecution.executedPlan)
      assert(belowPlan.collectFirst { case b: TpuBridgeExec => b }.isDefined,
        s"no bridge below the exchange:\n$belowPlan")
    } finally {
      spark.conf.set("spark.tpu.bridge.enabled", "false")
    }
  }

  test("string / datetime / cast tier") {
    val df = Seq(("ax", java.sql.Date.valueOf("2024-03-01"), 7L))
      .toDF("s", "d", "v")
      .filter($"s".contains("x"))
      .select(upper($"s").as("u"), year($"d").as("y"),
        $"v".cast("int").as("vi"))
    check("string_datetime_cast", df)
  }

  test("control characters escape as \\u sequences") {
    assert(SpecBuilder.json("a\nb\tc") == "\"a\\u000ab\\u0009c\"")
    assert(SpecBuilder.json("q\"\\") == "\"q\\\"\\\\\"")
  }
}
