name := "tpu-bridge"

version := "0.1"

scalaVersion := "2.12.18"

crossScalaVersions := Seq("2.12.18", "2.13.12")

val sparkVersion = sys.props.getOrElse("spark.version", "3.5.1")

libraryDependencies ++= Seq(
  "org.apache.spark" %% "spark-sql" % sparkVersion % "provided",
  "org.apache.spark" %% "spark-core" % sparkVersion % "provided",
  "org.scalatest" %% "scalatest-funsuite" % "3.2.17" % Test
)

Test / fork := true
Test / parallelExecution := false
