"""Fleet observatory tests: the v2 wire-context extension (mixed-
version decode in BOTH directions, typed unknown-version refusal), the
clock-offset handshake, the producer-side RemoteSpanStore + /spans pull,
orphan-span hygiene (a peer dying mid-fetch must never leave an
unclosed span), the driver-side FleetAggregator rollup/verdict, and the
cross-process end-to-end merged trace against ``serve_map``."""

import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.obs.metrics as m
from spark_rapids_tpu.obs import tracer as tr
from spark_rapids_tpu.obs.fleet import (ClockSync, FleetAggregator,
                                        RemoteSpanStore, TraceContext,
                                        install_aggregator,
                                        parse_prometheus_totals,
                                        pull_remote_spans)
from spark_rapids_tpu.shuffle.manager import TpuShuffleManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _serve_blocks(n_maps=4, rows=64, shuffle_id=11, reduce_id=2,
                  executor_id="", obs_port=0, private_mgr=False):
    """``private_mgr=True`` gives the server its own catalog (not the
    process singleton), so an in-process reader sees the blocks as
    REMOTE-only — the single-process stand-in for a real peer."""
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.shuffle.transport import ShuffleServer
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager() if private_mgr else TpuShuffleManager.get()
    for mid in range(n_maps):
        rb = pa.record_batch({"a": pa.array(
            [mid * 1000 + i for i in range(rows)], type=pa.int64())})
        mgr.write_map_output(shuffle_id, mid,
                             {reduce_id: batch_to_device(rb, xp=np)})
    return mgr, ShuffleServer(mgr, executor_id=executor_id,
                              obs_port=obs_port).start()


def _rogue_server(script):
    """One-connection server driving ``script(conn)`` — the injected
    wire-fault side of a scenario."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]

    def run():
        conn, _ = lsock.accept()
        try:
            script(conn)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            lsock.close()

    threading.Thread(target=run, daemon=True).start()
    return port


def _fresh_registry(local_id="test-local", port=0):
    from spark_rapids_tpu.shuffle.registry import BlockLocationRegistry
    BlockLocationRegistry.reset()
    reg = BlockLocationRegistry.get()
    reg.set_local(local_id, "127.0.0.1", port)
    return reg


def _reset_all():
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.registry import BlockLocationRegistry
    tr.uninstall()
    install_aggregator(None)
    locality.reset_pool()
    BlockLocationRegistry.reset()
    TpuShuffleManager.reset()
    RemoteSpanStore.reset()
    ClockSync.reset()
    m.MetricsRegistry.reset_for_tests()


# -- context + clock primitives ---------------------------------------------


def test_trace_context_roundtrip_and_tenant_bound():
    from spark_rapids_tpu.obs.fleet import new_trace_id
    tid = new_trace_id()
    ctx = TraceContext(tid, (1 << 61) + 7, tenant="team-a")
    back = TraceContext.unpack(ctx.pack())
    assert back.trace_id == tid
    assert back.span_id == (1 << 61) + 7
    assert back.tenant == "team-a"
    # the context must stay header-sized: a hostile tenant string is
    # truncated at pack time, never an oversized blob on the wire
    huge = TraceContext(tid, 1, tenant="x" * 500)
    assert len(huge.pack()) <= 25 + 64
    assert TraceContext.unpack(huge.pack()).tenant == "x" * 64


def test_clock_sync_estimate_and_min_rtt_retention():
    # t0/t3 client clock, t1/t2 server clock: server runs 100ns ahead,
    # 10ns each way on the wire, 5ns server turnaround
    offset, rtt = ClockSync.estimate(0, 110, 115, 25)
    assert offset == 100
    assert rtt == 20
    ClockSync.reset()
    cs = ClockSync.get()
    cs.observe("p", 0, 110, 115, 25)
    # a later, noisier sample (bigger rtt) must NOT replace the tighter
    # estimate: offset error is bounded by rtt/2
    cs.observe("p", 0, 500, 505, 1000)
    assert cs.offset_ns("p") == 100
    assert cs.rtt_ns("p") == 20
    cs.observe("p", 0, 105, 106, 12)  # tighter: replaces
    assert cs.rtt_ns("p") == 11
    ClockSync.reset()


def test_remote_span_store_bounds_and_drain():
    _reset_all()
    try:
        store = RemoteSpanStore.get()
        store.configure(2, 3)
        for i in range(5):
            store.add("t1", {"spanId": i, "t0Ns": i, "t1Ns": i + 1})
        assert len(store.peek_all()["t1"]) == 3  # per-trace cap
        assert store.dropped == 2
        assert m.counter(
            "tpu_trace_remote_spans_dropped_total").value() == 2
        store.add("t2", {"spanId": 10, "t0Ns": 0, "t1Ns": 1})
        store.add("t3", {"spanId": 11, "t0Ns": 0, "t1Ns": 1})
        # trace cap: oldest bucket ("t1") evicted, loss is visible
        assert "t1" not in store.peek_all()
        assert store.evicted_traces == 1
        assert [s["spanId"] for s in store.drain("t2")] == [10]
        assert store.drain("t2") == []  # pull semantics: gone
        assert store.span_count() == 1
    finally:
        _reset_all()


def test_parse_prometheus_totals_folds_histograms():
    text = "\n".join([
        "# HELP tpu_x_total help",
        "# TYPE tpu_x_total counter",
        'tpu_x_total{k="a"} 2',
        'tpu_x_total{k="b"} 3',
        'tpu_h_seconds_bucket{le="0.1"} 7',
        "tpu_h_seconds_sum 1.5",
        "tpu_h_seconds_count 9",
        "tpu_g 4",
    ])
    totals = parse_prometheus_totals(text)
    assert totals["tpu_x_total"] == 5.0
    assert totals["tpu_h_seconds"] == 9.0  # _count stands for the family
    assert "tpu_h_seconds_bucket" not in totals
    assert "tpu_h_seconds_sum" not in totals
    assert totals["tpu_g"] == 4.0


# -- wire version negotiation ------------------------------------------------


def test_hello_negotiates_v2_clock_and_identity():
    from spark_rapids_tpu.shuffle.transport import ShuffleClient
    _reset_all()
    mgr, server = _serve_blocks(executor_id="peer-A", obs_port=9123)
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        assert cli.peer_version is None
        metas = cli.fetch_metadata(11, 2).wait(10.0)
        assert len(metas) == 4
        assert cli.peer_version == 2
        assert cli.last_peer_version == 2
        assert cli.peer_executor_id == "peer-A"
        assert cli.peer_obs_port == 9123
        # same process, same perf_counter_ns: offset is tiny, rtt real
        assert cli.clock_offset_ns is not None
        assert cli.clock_rtt_ns > 0
        assert abs(cli.clock_offset_ns) < 1_000_000_000
        assert ClockSync.get().offset_ns("peer-A") is not None
        cli.close()
    finally:
        server.stop()
        _reset_all()


def test_new_client_pins_old_peer_to_v1():
    """Direction 1 of mixed-version decode: a pre-v2 server answers
    MSG_HELLO with a correlated bad_message error.  The client must pin
    the peer to v1 and never emit a v2 frame at it — every byte the old
    server sees must parse with the v1 struct."""
    from spark_rapids_tpu.shuffle.transport import (
        _FRAME, _recv_exact, MSG_ERROR, MSG_HELLO, MSG_METADATA_REQ,
        MSG_METADATA_RESP, ShuffleClient)
    seen_types = []

    def old_server(conn):
        for _ in range(2):
            head = _recv_exact(conn, _FRAME.size)
            mtype, rid, blen = _FRAME.unpack(head)
            seen_types.append(mtype)
            if blen:
                _recv_exact(conn, blen)
            if mtype == MSG_HELLO:
                body = f"bad_message:unknown type {mtype}".encode()
                conn.sendall(_FRAME.pack(MSG_ERROR, rid, len(body))
                             + body)
            else:
                conn.sendall(_FRAME.pack(MSG_METADATA_RESP, rid, 4)
                             + struct.pack("<i", 0))

    cli = ShuffleClient("127.0.0.1", _rogue_server(old_server),
                        timeout=10.0)
    ctx = TraceContext("ab" * 16, 42, "t")
    # a context in hand and STILL a v1 frame: the peer can't decode v2
    assert cli.fetch_metadata(11, 2, ctx=ctx).wait(10.0) == []
    assert cli.peer_version == 1
    assert cli.last_peer_version == 1
    assert seen_types == [MSG_HELLO, MSG_METADATA_REQ]
    cli.close()


def test_old_client_v1_frames_against_new_server():
    """Direction 2: an old client speaks raw v1 frames with no hello at
    a new server — responses must come back pure v1 with correct
    correlation (the upgrade never strands the old fleet half)."""
    from spark_rapids_tpu.shuffle.transport import (
        _FRAME, MSG_METADATA_REQ, MSG_METADATA_RESP)
    _reset_all()
    mgr, server = _serve_blocks(n_maps=2)
    try:
        s = socket.create_connection(("127.0.0.1", server.port),
                                     timeout=10.0)
        body = struct.pack("<qq", 11, 2)
        s.sendall(_FRAME.pack(MSG_METADATA_REQ, 77, len(body)) + body)
        head = s.recv(_FRAME.size, socket.MSG_WAITALL)
        mtype, rid, blen = _FRAME.unpack(head)
        assert mtype == MSG_METADATA_RESP
        assert rid == 77
        resp = s.recv(blen, socket.MSG_WAITALL)
        (n,) = struct.unpack_from("<i", resp, 0)
        assert n == 2
        s.close()
    finally:
        server.stop()
        _reset_all()


def test_unknown_version_request_refused_typed():
    """A v2 frame from the FUTURE (version 3): the frozen prefix lets
    the server correlate it, so the refusal is a typed bad_version
    error on the right request id — not framing corruption."""
    from spark_rapids_tpu.shuffle.errors import TpuShuffleVersionError
    from spark_rapids_tpu.shuffle.transport import (
        _FRAME, _FRAME2, _raise_peer_error, MSG_ERROR, MSG_METADATA_REQ,
        WIRE_V2_MAGIC)
    _reset_all()
    mgr, server = _serve_blocks(n_maps=1)
    try:
        s = socket.create_connection(("127.0.0.1", server.port),
                                     timeout=10.0)
        body = struct.pack("<qq", 11, 2)
        s.sendall(_FRAME2.pack(WIRE_V2_MAGIC, 3, MSG_METADATA_REQ, 99,
                               len(body), 0) + body)
        head = s.recv(_FRAME.size, socket.MSG_WAITALL)
        mtype, rid, blen = _FRAME.unpack(head)
        err = s.recv(blen, socket.MSG_WAITALL)
        assert mtype == MSG_ERROR
        assert rid == 99
        assert err == b"bad_version:3"
        s.close()
        with pytest.raises(TpuShuffleVersionError) as ei:
            _raise_peer_error(err)
        assert ei.value.got == 3
    finally:
        server.stop()
        _reset_all()


def test_unknown_version_response_refused_typed():
    """The client side of the same invariant: a peer answering with a
    v2 frame of an unknown version fails typed, never a misparse."""
    from spark_rapids_tpu.shuffle.errors import TpuShuffleVersionError
    from spark_rapids_tpu.shuffle.transport import (
        _FRAME, _FRAME2, _HELLO_RESP, _recv_exact, MSG_HELLO_RESP,
        MSG_METADATA_RESP, WIRE_V2_MAGIC, ShuffleClient)

    def future_server(conn):
        head = _recv_exact(conn, _FRAME.size)
        _, rid, blen = _FRAME.unpack(head)
        _recv_exact(conn, blen)
        body = _HELLO_RESP.pack(2, 0, 1, 2, 0, 0)
        conn.sendall(_FRAME.pack(MSG_HELLO_RESP, rid, len(body)) + body)
        head2 = _recv_exact(conn, _FRAME.size)  # the v1 metadata req
        _, rid2, blen2 = _FRAME.unpack(head2)
        _recv_exact(conn, blen2)
        conn.sendall(_FRAME2.pack(WIRE_V2_MAGIC, 3, MSG_METADATA_RESP,
                                  rid2, 0, 0))

    cli = ShuffleClient("127.0.0.1", _rogue_server(future_server),
                        timeout=10.0)
    with pytest.raises(TpuShuffleVersionError):
        cli.fetch_metadata(11, 2).wait(10.0)
    cli.close()


# -- producer serve spans + /spans pull -------------------------------------


def test_serve_spans_recorded_parented_and_drained_over_http():
    from spark_rapids_tpu.obs.health import MetricsServer
    from spark_rapids_tpu.shuffle.transport import ShuffleClient
    _reset_all()
    obs = MetricsServer(0)
    mgr, server = _serve_blocks(executor_id="peer-A",
                                obs_port=obs.port)
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        ctx = TraceContext("cd" * 16, 31, tenant="team-b")
        metas = cli.fetch_metadata(11, 2, ctx=ctx).wait(10.0)
        (sid, mid, rid, idx), _ = metas[0]
        cli.fetch_block(sid, mid, rid, idx, ctx=ctx).wait(10.0)
        spans = pull_remote_spans("127.0.0.1", obs.port, ctx.trace_id)
        roots = {s["name"]: s for s in spans if s.get("remoteParent")}
        assert set(roots) == {"shuffle.serve.metadata",
                              "shuffle.serve.transfer"}
        for root in roots.values():
            assert root["parentId"] == 31  # the consumer's fetch span
            assert root["proc"] == "peer-A"
            assert root["attrs"]["tenant"] == "team-b"
            assert root["t1Ns"] >= root["t0Ns"]
        steps = {s["name"] for s in spans if not s.get("remoteParent")}
        assert {"serve.decode", "serve.catalog_read", "serve.send",
                "serve.serialize", "serve.compress"} <= steps
        # every step child is parented under one of the two roots and
        # timed inside this process's clock domain
        root_ids = {r["spanId"] for r in roots.values()}
        for s in spans:
            if not s.get("remoteParent"):
                assert s["parentId"] in root_ids
        # drain semantics: the pull above emptied the bucket
        assert pull_remote_spans("127.0.0.1", obs.port,
                                 ctx.trace_id) == []
        # the serve-side breakdown histogram moved for every step
        hist = m.histogram("tpu_shuffle_serve_seconds",
                           labelnames=("step",))
        for step in ("decode", "catalog_read", "serialize", "compress",
                     "send"):
            assert hist.labels(step=step).count > 0, step
        cli.close()
    finally:
        server.stop()
        obs.close()
        _reset_all()


def test_requests_without_context_record_no_spans():
    """Anti-vacuity for the store: plain v1-ish traffic (no context)
    must not deposit spans — only the histogram moves."""
    from spark_rapids_tpu.shuffle.transport import ShuffleClient
    _reset_all()
    mgr, server = _serve_blocks(executor_id="peer-A")
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        metas = cli.fetch_metadata(11, 2).wait(10.0)
        (sid, mid, rid, idx), _ = metas[0]
        cli.fetch_block(sid, mid, rid, idx).wait(10.0)
        assert RemoteSpanStore.get().span_count() == 0
        assert m.histogram("tpu_shuffle_serve_seconds",
                           labelnames=("step",)) \
            .labels(step="send").count > 0
        cli.close()
    finally:
        server.stop()
        _reset_all()


# -- consumer-side merge + orphan hygiene -----------------------------------


def _fleet_read_setup(executor_id="peer-A"):
    """In-process producer (server + obs endpoint) registered as the
    remote owner of shuffle 11, with a live tracer installed."""
    from spark_rapids_tpu.obs.health import MetricsServer
    from spark_rapids_tpu.shuffle.registry import BlockEndpoint
    obs = MetricsServer(0)
    mgr, server = _serve_blocks(executor_id=executor_id,
                                obs_port=obs.port, private_mgr=True)
    reg = _fresh_registry("reduce-side")
    reg.register(11, [BlockEndpoint(executor_id, "127.0.0.1",
                                    server.port)])
    trace = tr.install(tr.QueryTrace())
    return obs, server, trace


def test_fetch_group_merges_serve_spans_under_fetch_span():
    from spark_rapids_tpu.shuffle import locality
    obs, server, trace = _fleet_read_setup()
    try:
        blocks = list(locality.read_reduce_blocks(11, 2))
        assert len(blocks) == 4
        trace.finalize()
        spans = trace.span_dicts()
        fetch = [s for s in spans if s["name"] == "shuffle.fetch"]
        assert len(fetch) == 1
        assert fetch[0]["status"] == "ok"
        assert fetch[0]["attrs"]["peer"] == "peer-A"
        assert fetch[0]["attrs"]["blocks"] == 4
        by_parent = {}
        for s in spans:
            by_parent.setdefault(s.get("parentId"), []).append(s)
        serve_roots = [s for s in by_parent.get(fetch[0]["spanId"], [])
                       if s.get("proc") == "peer-A"]
        names = {s["name"] for s in serve_roots}
        assert "shuffle.serve.metadata" in names
        assert "shuffle.serve.transfer" in names
        f0 = fetch[0]["startNs"]
        f1 = f0 + fetch[0]["durNs"]
        for root in serve_roots:
            # skew-corrected and clamped: inside the parent interval
            assert f0 <= root["startNs"]
            assert root["startNs"] + root["durNs"] <= f1
            for step in by_parent.get(root["spanId"], []):
                assert root["startNs"] <= step["startNs"]
                assert (step["startNs"] + step["durNs"]
                        <= root["startNs"] + root["durNs"])
        # 1 metadata root (+3 steps) and 4 transfer roots (+5 steps
        # each): everything the producer recorded came home
        assert trace.remote_spans_merged == \
            sum(1 for s in spans if s.get("proc"))
        assert trace.remote_spans_merged == 28
        assert trace.remote_spans_lost == 0
        assert m.counter(
            "tpu_trace_remote_spans_merged_total").value() > 0
        assert m.counter(
            "tpu_trace_remote_spans_lost_total").value() == 0
        # pull drained the producer's bucket: nothing left to leak
        assert RemoteSpanStore.get().span_count() == 0
    finally:
        server.stop()
        obs.close()
        _reset_all()


def test_spans_pull_failure_closes_fetch_span_with_spans_lost():
    """Orphan hygiene: the read path delivered the data but /spans did
    not answer — the fetch span must stay CLOSED, annotated spans_lost,
    and the loss counted.  Observability loss never fails the read."""
    from spark_rapids_tpu.obs import fleet
    from spark_rapids_tpu.shuffle import locality
    obs, server, trace = _fleet_read_setup()
    real_pull = fleet.pull_remote_spans

    def broken_pull(*a, **k):
        raise OSError("obs endpoint gone")

    fleet.pull_remote_spans = broken_pull
    try:
        blocks = list(locality.read_reduce_blocks(11, 2))
        assert len(blocks) == 4  # the data still arrived
        trace.finalize()
        spans = trace.span_dicts()
        fetch = [s for s in spans if s["name"] == "shuffle.fetch"]
        assert len(fetch) == 1
        assert fetch[0]["status"] == "ok"  # closed before the pull
        assert fetch[0]["attrs"]["spans_lost"] is True
        assert trace.remote_spans_lost == 1
        assert trace.remote_spans_merged == 0
        assert m.counter(
            "tpu_trace_remote_spans_lost_total").value() == 1
        assert trace.open_span_count() == 0
    finally:
        fleet.pull_remote_spans = real_pull
        server.stop()
        obs.close()
        _reset_all()


def test_dead_peer_closes_fetch_spans_typed_without_false_loss():
    """A peer that never answered (connect refused) closes every fetch
    attempt's span typed — and because no context ever crossed the
    wire, NO spans_lost is counted (nothing remote exists to lose)."""
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.registry import BlockEndpoint
    _reset_all()
    reg = _fresh_registry("reduce-side")
    reg.register(11, [BlockEndpoint("gone", "127.0.0.1", 1)])
    trace = tr.install(tr.QueryTrace())
    try:
        with pytest.raises(Exception):
            list(locality.read_reduce_blocks(11, 2))
        # hygiene: every fetch span closed; only the query root is open
        assert trace.open_span_count() == 1
        trace.finalize()
        fetch = [s for s in trace.span_dicts()
                 if s["name"] == "shuffle.fetch"]
        assert fetch  # one per attempt
        for f in fetch:
            assert f["status"] == "error"
            assert "spans_lost" not in f["attrs"]
        assert trace.remote_spans_lost == 0
        assert m.counter(
            "tpu_trace_remote_spans_lost_total").value() == 0
    finally:
        _reset_all()


# -- driver-side aggregation -------------------------------------------------


def test_fleet_aggregator_rollup_and_dead_peer_verdict():
    from spark_rapids_tpu.obs.health import MetricsServer
    from spark_rapids_tpu.shuffle.heartbeat import HeartbeatManager
    _reset_all()
    m.counter("tpu_queries_completed_total").inc(3)
    obs = MetricsServer(0)  # both peers expose THIS process's registry
    hb = HeartbeatManager(timeout_s=30.0)
    hb.register_executor("exec-1", "127.0.0.1", 7001, obs_port=obs.port)
    hb.register_executor("exec-2", "127.0.0.1", 7002, obs_port=obs.port)
    agg = FleetAggregator(hb, max_peers=4, timeout_s=5.0)
    try:
        peers = agg.scrape()
        assert set(peers) == {"exec-1", "exec-2"}
        for e in peers.values():
            assert e["scraped"] is True
            assert e["health"] == "ok"
        up = m.gauge("tpu_fleet_peer_up", labelnames=("peer",))
        assert up.value(peer="exec-1") == 1
        assert up.value(peer="exec-2") == 1
        rollup = m.gauge("tpu_fleet_rollup",
                         labelnames=("peer", "name"))
        for pid in ("exec-1", "exec-2"):
            assert rollup.value(
                peer=pid, name="tpu_queries_completed_total") == 3
        assert m.gauge("tpu_fleet_peers_live").value() == 2
        assert agg.verdict()["status"] == "ok"
        # exec-2 stops heartbeating: the fleet degrades and says why
        hb._peers["exec-2"].last_heartbeat -= 10_000
        verdict = agg.verdict()
        assert verdict["status"] == "degraded"
        assert any("exec-2" in r and "dead" in r
                   for r in verdict["reasons"])
        assert up.value(peer="exec-2") == 0
        assert up.value(peer="exec-1") == 1
        assert m.gauge("tpu_fleet_peers_live").value() == 1
        # the dead peer is remembered until explicitly forgotten
        agg.forget_peer("exec-2")
        assert agg.verdict()["status"] == "ok"
    finally:
        obs.close()
        _reset_all()


def test_scrape_cap_bounds_peer_cardinality():
    from spark_rapids_tpu.shuffle.heartbeat import HeartbeatManager
    _reset_all()
    hb = HeartbeatManager(timeout_s=30.0)
    for i in range(5):
        hb.register_executor(f"e{i}", "127.0.0.1", 7000 + i, obs_port=0)
    agg = FleetAggregator(hb, max_peers=2, timeout_s=1.0)
    try:
        peers = agg.scrape()
        assert len(peers) == 2
        assert m.counter("tpu_fleet_peers_skipped_total").value() == 3
    finally:
        _reset_all()


def test_healthz_carries_fleet_verdict():
    from spark_rapids_tpu.obs.health import HealthMonitor
    from spark_rapids_tpu.shuffle.heartbeat import HeartbeatManager
    _reset_all()
    hb = HeartbeatManager(timeout_s=30.0)
    hb.register_executor("exec-1", "127.0.0.1", 7001, obs_port=0)
    agg = install_aggregator(FleetAggregator(hb, timeout_s=1.0))
    try:
        agg.scrape()
        snap = HealthMonitor().snapshot()
        assert snap["components"]["fleet"]["status"] == "ok"
        hb._peers["exec-1"].last_heartbeat -= 10_000
        agg.scrape()
        snap = HealthMonitor().snapshot()
        assert snap["status"] == "degraded"
        fleet_comp = snap["components"]["fleet"]
        assert fleet_comp["status"] == "degraded"
        assert any("exec-1" in r for r in fleet_comp["signals"]["reasons"])
    finally:
        _reset_all()


# -- cross-process end-to-end ------------------------------------------------


def test_cross_process_merged_trace_e2e():
    """The fleet observatory's acceptance shape in one test: a child
    process serves both join sides; this process fetches with a live
    tracer.  The merged trace must show the child's serve spans (its
    clock domain, skew-corrected) nested under our fetch spans, with
    zero lost spans and the child's span buffer fully drained."""
    from spark_rapids_tpu.obs.export import fleet_summary
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.registry import BlockEndpoint
    from spark_rapids_tpu.shuffle.serve_map import DIM_SID, FACT_SID
    rows, parts = 4000, 2
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SPARK_RAPIDS_TPU_DISABLE_COMPILE_CACHE="1")
    child = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.shuffle.serve_map",
         "--rows", str(rows), "--parts", str(parts),
         "--codec", "lz4", "--seed", "13",
         "--executor-id", "map-side"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env, cwd=REPO)
    _reset_all()
    reg = _fresh_registry("reduce-side")
    trace = tr.install(tr.QueryTrace())
    try:
        line = child.stdout.readline()
        fields = line.split()
        assert fields[0] == "PORT" and fields[2] == "OBS", line
        port, obs_port = int(fields[1]), int(fields[3])
        assert obs_port > 0
        ep = BlockEndpoint("map-side", "127.0.0.1", port)
        reg.register(FACT_SID, [ep])
        reg.register(DIM_SID, [ep])
        n_blocks = 0
        for shuffle_sid in (FACT_SID, DIM_SID):
            for pid in range(parts):
                n_blocks += len(list(
                    locality.read_reduce_blocks(shuffle_sid, pid)))
        assert n_blocks > 0
        trace.finalize()
        spans = trace.span_dicts()
        by_parent = {}
        for s in spans:
            by_parent.setdefault(s.get("parentId"), []).append(s)
        fetch = [s for s in spans if s["name"] == "shuffle.fetch"]
        assert len(fetch) == parts * 2  # one per (shuffle, partition)
        for f in fetch:
            assert f["status"] == "ok"
            kids = by_parent.get(f["spanId"], [])
            serve_roots = [k for k in kids if k.get("proc")]
            names = {k["name"] for k in serve_roots}
            assert "shuffle.serve.metadata" in names, f
            assert "shuffle.serve.transfer" in names, f
            f0, f1 = f["startNs"], f["startNs"] + f["durNs"]
            for root in serve_roots:
                assert root["proc"] == "map-side"
                # the child's perf_counter_ns epoch is unrelated to
                # ours: only the offset correction can land these
                # inside the parent — monotone within each parent
                assert f0 <= root["startNs"]
                assert root["startNs"] + root["durNs"] <= f1
                for step in by_parent.get(root["spanId"], []):
                    assert root["startNs"] <= step["startNs"]
                    assert (step["startNs"] + step["durNs"]
                            <= root["startNs"] + root["durNs"])
        assert trace.remote_spans_merged > 0
        assert trace.remote_spans_lost == 0
        assert m.counter(
            "tpu_trace_remote_spans_lost_total").value() == 0
        # the tools-facing rollups agree with the raw spans
        summary = fleet_summary(spans)
        peer = summary["peers"]["map-side"]
        assert peer["fetches"] == parts * 2
        assert peer["serveNs"] > 0
        assert peer["spansLost"] == 0
        chrome = trace.to_chrome()
        lanes = {e["args"]["name"] for e in chrome["traceEvents"]
                 if e.get("ph") == "M"}
        assert "map-side" in lanes  # its own Perfetto process lane
        child.stdin.write("done\n")
        child.stdin.flush()
        stats = json.loads(child.stdout.readline()[len("STATS "):])
        assert stats["unpulled_spans"] == 0  # every span came home
        assert stats["serve_seconds_by_step"]["serialize"] > 0
        assert stats["serve_seconds_by_step"]["send"] > 0
        assert child.wait(timeout=30) == 0
    finally:
        child.stdin.close()
        child.stdout.close()
        if child.poll() is None:
            child.kill()
            child.wait()
        _reset_all()
