"""Scalar subqueries (GpuScalarSubquery analog), the Hive override hook
(GpuHiveOverrides analog), and zero-copy ML export (ColumnarRdd analog)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _session(enabled=True):
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", enabled).get_or_create())


def test_scalar_subquery_in_filter_and_project():
    s = _session()
    tb = pa.table({"k": pa.array([1, 2, 3, 4], type=pa.int64()),
                   "v": pa.array([10, 20, 30, 40], type=pa.int64())})
    df = s.create_dataframe(tb)
    avg_df = df.agg(F.avg(col("v")).alias("a"))
    out = df.filter(col("v") > F.scalar_subquery(avg_df)).collect()
    assert sorted(out.column("v").to_pylist()) == [30, 40]
    out2 = df.select(
        col("k"),
        (col("v") - F.scalar_subquery(avg_df)).alias("d")).collect()
    assert out2.column("d").to_pylist() == [-15.0, -5.0, 5.0, 15.0]


def test_scalar_subquery_must_be_single_row():
    s = _session()
    tb = pa.table({"v": pa.array([1, 2], type=pa.int64())})
    df = s.create_dataframe(tb)
    with pytest.raises(ValueError, match="one row"):
        df.filter(col("v") > F.scalar_subquery(df)).collect()


def test_hive_override_hook_registers_rules():
    from spark_rapids_tpu.api.column import Column
    from spark_rapids_tpu.hive import HiveHash, enable_hive_support
    from spark_rapids_tpu.plan.overrides import EXPR_RULES

    s = _session()
    tb = pa.table({"a": pa.array([1, 2, None], type=pa.int32()),
                   "b": pa.array([True, False, True])})
    df = s.create_dataframe(tb)
    # before opting in the expression has no rule -> CPU fallback works
    q = df.select(Column(HiveHash(col("a").expr, col("b").expr))
                  .alias("h"))
    out_cpu = q.collect()
    enable_hive_support()
    out_tpu = q.collect()
    assert HiveHash in EXPR_RULES
    assert out_cpu.column("h").to_pylist() == \
        out_tpu.column("h").to_pylist()
    # hive hash semantics: 31*h + int(col) per column, nulls contribute 0
    assert out_cpu.column("h").to_pylist() == [31 * 1 + 1, 31 * 2 + 0,
                                               31 * 0 + 1]


def test_ml_columnar_arrays_zero_copy():
    import jax

    from spark_rapids_tpu import ml
    s = _session()
    rng = np.random.default_rng(6)
    n = 1000
    tb = pa.table({"k": pa.array(rng.integers(0, 5, n).astype(np.int64)),
                   "x": pa.array(rng.random(n))})
    df = (s.create_dataframe(tb)
          .group_by(col("k")).agg(F.avg(col("x")).alias("mx")))
    parts = ml.columnar_arrays(df)
    assert len(parts) == 1
    d = parts[0]
    # arrays are device-resident jax arrays, not numpy (zero copy out of
    # the SQL pipeline, ColumnarRdd analog)
    assert isinstance(d["mx"][0], jax.Array)
    # and consumable by jax compute directly
    n_groups = int(np.asarray(d["__num_rows__"]))
    live = np.asarray(d["mx"][1])[:n_groups]
    vals = np.asarray(d["mx"][0])[:n_groups]
    want = {k: float(np.mean(np.array(tb.column("x"))[
        np.array(tb.column("k")) == k])) for k in range(5)}
    got = {int(k): float(v) for k, v, ok in zip(
        np.asarray(d["k"][0])[:n_groups], vals, live) if ok}
    for k in want:
        assert abs(got[k] - want[k]) < 1e-12


def test_scalar_subquery_inside_aggregate_and_window():
    """Subqueries nested in aggregate arguments and window expressions
    resolve too (code-review round-3 findings: stale
    AggregateExpression.func and the window_exprs walker gap)."""
    s = _session()
    tb = pa.table({"k": pa.array([1, 1, 2], type=pa.int64()),
                   "v": pa.array([10, 20, 40], type=pa.int64())})
    df = s.create_dataframe(tb)
    one = df.agg(F.min(col("v")).alias("m"))  # = 10
    out = df.agg(F.sum(col("v") - F.scalar_subquery(one)).alias("d")) \
        .collect()
    assert out.column("d").to_pylist() == [(10 - 10) + (20 - 10) +
                                           (40 - 10)]
    from spark_rapids_tpu.expr.window import WindowBuilder
    w = WindowBuilder().partition_by(col("k")).order_by(col("v"))
    out2 = df.select(
        col("k"),
        F.sum(col("v") - F.scalar_subquery(one)).over(w).alias("rs")) \
        .collect()
    assert sorted(out2.column("rs").to_pylist()) == [0, 10, 30]


def test_struct_key_null_distinct_from_null_fields_cpu():
    """A null struct key and a struct of null fields group separately on
    the CPU oracle (code-review round-3 finding: lost top-level
    validity)."""
    import datetime
    s = _session(False)
    base = datetime.datetime(2024, 3, 1, tzinfo=datetime.timezone.utc)
    tb = pa.table({
        "ts": pa.array([base, None, base], type=pa.timestamp("us",
                                                             tz="UTC")),
        "v": pa.array([1, 2, 4], type=pa.int64())})
    out = (s.create_dataframe(tb)
           .group_by(F.window(col("ts"), "10 minutes").alias("w"))
           .agg(F.sum(col("v")).alias("s")).collect())
    got = {(w is None): sv for w, sv in
           zip(out.column("w").to_pylist(), out.column("s").to_pylist())}
    assert got[True] == 2     # the null-ts row groups under the null key
    assert got[False] == 5


def test_explain_does_not_execute_subqueries_or_mutate_plan():
    """explain() substitutes placeholders without running the subquery,
    and a later collect() still resolves the REAL value (code-review
    round-3 findings: explain side effects + in-place plan mutation)."""
    s = _session()
    tb = pa.table({"v": pa.array([1, 2, 3], type=pa.int64())})
    df = s.create_dataframe(tb)
    calls = []
    orig = type(s).execute

    sub = df.agg(F.max(col("v")).alias("m"))
    q = df.filter(col("v") >= F.scalar_subquery(sub))

    import spark_rapids_tpu.api.session as sess_mod
    real_execute = sess_mod.TpuSession.execute

    def counting(self, lp):
        calls.append(lp)
        return real_execute(self, lp)

    sess_mod.TpuSession.execute = counting
    try:
        s.explain(q._lp)
        assert calls == []          # explain ran NO subquery
        out = q.collect()
    finally:
        sess_mod.TpuSession.execute = real_execute
    assert out.column("v").to_pylist() == [3]   # real value resolved
    # and the plan object still carries the subquery for future runs
    from spark_rapids_tpu.expr.subquery import has_scalar_subquery
    assert has_scalar_subquery(q._lp)


def test_subquery_in_window_partition_keys():
    s = _session()
    tb = pa.table({"k": pa.array([1, 1, 2], type=pa.int64()),
                   "v": pa.array([5, 7, 9], type=pa.int64())})
    df = s.create_dataframe(tb)
    one = df.agg(F.min(col("v")).alias("m"))   # 5
    from spark_rapids_tpu.expr.window import WindowBuilder
    w = (WindowBuilder()
         .partition_by((col("k") * 0 + F.scalar_subquery(one)))
         .order_by(col("v")))
    out = df.select(col("v"), F.row_number().over(w).alias("rn")) \
        .collect()
    # one partition (constant key) -> row numbers 1..3
    assert sorted(out.column("rn").to_pylist()) == [1, 2, 3]


def test_hive_text_round_trip(tmp_path):
    """Hive text tables (LazySimpleSerDe layout: \\x01 delimiters, \\N
    nulls) read into the engine and write back byte-compatibly
    (ref GpuHiveTableScanExec / GpuHiveFileFormat)."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu import types as t
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.hive import (enable_hive_support,
                                       read_hive_text, write_hive_text)

    enable_hive_support()
    src = tmp_path / "hive_table.txt"
    rows = [("a", 1, 1.5), (None, 2, None), ("cé", None, -0.25)]
    with open(src, "w", encoding="utf-8") as f:
        for s_, i_, d_ in rows:
            f.write("\x01".join([
                s_ if s_ is not None else r"\N",
                str(i_) if i_ is not None else r"\N",
                repr(d_) if d_ is not None else r"\N"]) + "\n")

    names = ["s", "i", "d"]
    dtypes = [t.STRING, t.LONG, t.DOUBLE]
    tbl = read_hive_text(str(src), names, dtypes)
    assert tbl.column("s").to_pylist() == ["a", None, "cé"]
    assert tbl.column("i").to_pylist() == [1, 2, None]
    assert tbl.column("d").to_pylist() == [1.5, None, -0.25]

    # engine query over the hive table via the session helper
    sess = (TpuSession.builder()
            .config("spark.rapids.sql.enabled", True).get_or_create())
    df = sess.read_hive_text(str(src), names, dtypes)
    out = df.select(col("i"), (col("d") * 2).alias("d2")).collect()
    assert out.column("d2").to_pylist() == [3.0, None, -0.5]

    # write back and re-read: identical values
    dst = tmp_path / "out.txt"
    write_hive_text(tbl, str(dst))
    back = read_hive_text(str(dst), names, dtypes)
    assert back.equals(tbl), (back.to_pydict(), tbl.to_pydict())

    # partitioned-table layout: files under subdirectories read
    # recursively; marker files skip; empty dirs read empty
    pdir = tmp_path / "ptable"
    (pdir / "part=1").mkdir(parents=True)
    (pdir / "_SUCCESS").write_text("")
    # leftovers from an interrupted write must NOT be ingested
    (pdir / "_temporary" / "0").mkdir(parents=True)
    import shutil
    shutil.copy(src, pdir / "_temporary" / "0" / "part-00000")
    shutil.copy(src, pdir / "part=1" / "000000_0")  # extension-less
    part = read_hive_text(str(pdir), names, dtypes)
    assert part.equals(tbl)
    empty = tmp_path / "etable"
    empty.mkdir()
    (empty / "_SUCCESS").write_text("")
    et = read_hive_text(str(empty), names, dtypes)
    assert et.num_rows == 0 and et.schema.names == names


def test_ml_export_preserves_partitions():
    """ml.device_batches must NOT inherit the collect boundary's
    gather/coalesce: partition structure and device residency are the
    export's contract (ref ColumnarRdd.scala)."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu import ml
    from spark_rapids_tpu.api.session import TpuSession

    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True).get_or_create())
    tb = pa.table({"v": pa.array(np.arange(4000, dtype=np.int64))})
    df = s.create_dataframe(tb, num_partitions=4)
    parts = ml.device_batches(df)
    assert len(parts) == 4, f"expected 4 partitions, got {len(parts)}"
    total = sum(int(b.num_rows) for bs in parts for b in bs)
    assert total == 4000
