"""Decimal tests (ref decimalExpressions.scala + DECIMAL_TYPE_ENABLED
RapidsConf.scala:565 — the reference is decimal64-backed; this build adds
exact 128-bit aggregation buffers on top)."""

import decimal

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api.session import TpuSession

D = decimal.Decimal


def _session(enabled=True):
    return TpuSession.builder().config("spark.rapids.sql.enabled",
                                       enabled).get_or_create()


def _dec_table(n=400, precision=12, scale=2, seed=0, null_every=7):
    rng = np.random.default_rng(seed)
    lim = 10 ** (precision - scale) - 1
    vals = [None if i % null_every == 0 else
            D(int(rng.integers(-lim, lim))).scaleb(-scale) +
            D(int(rng.integers(0, 10 ** scale))).scaleb(-scale)
            for i in range(n)]
    return pa.table({
        "k": pa.array((rng.integers(0, 20, n)).astype(np.int64)),
        "d": pa.array(vals, type=pa.decimal128(precision, scale)),
    })


def _placements(s):
    out = []
    s.last_plan.foreach(lambda e: out.append((type(e).__name__, e.placement)))
    return out


def test_decimal_project_filter_roundtrip():
    s = _session()
    tb = _dec_table()
    df = s.create_dataframe(tb)
    out = df.select(col("k"), (col("d") + col("d")).alias("dd"),
                    (col("d") * lit(2)).alias("d2")) \
        .filter(col("k") >= 0).collect()
    want = [None if v is None else v * 2 for v in
            tb.column("d").to_pylist()]
    assert out.column("dd").to_pylist() == want
    assert any(p == "tpu" for _, p in _placements(s))


def test_decimal_sum_exact_beyond_64_bits():
    s = _session()
    n = 3000
    tb = pa.table({"k": pa.array([1] * n),
                   "d": pa.array([D("9999999999999999.99")] * n,
                                 type=pa.decimal128(18, 2))})
    out = s.create_dataframe(tb).group_by(col("k")).agg(
        F.sum(col("d")).alias("sd")).collect()
    assert out.column("sd").to_pylist() == [D("9999999999999999.99") * n]
    assert ("TpuHashAggregateExec", "tpu") in _placements(s)


def test_decimal_group_agg_differential():
    s = _session()
    tb = _dec_table(600)
    out = (s.create_dataframe(tb).group_by(col("k"))
           .agg(F.sum(col("d")).alias("sd"),
                F.min(col("d")).alias("mn"),
                F.max(col("d")).alias("mx"),
                F.count(col("d")).alias("c"))
           .collect().sort_by("k"))
    want = pa.TableGroupBy(tb, ["k"], use_threads=False).aggregate(
        [("d", "sum"), ("d", "min"), ("d", "max"), ("d", "count")]
    ).sort_by("k")
    assert out.column("k").to_pylist() == want.column("k").to_pylist()
    assert out.column("sd").to_pylist() == want.column("d_sum").to_pylist()
    assert out.column("mn").to_pylist() == want.column("d_min").to_pylist()
    assert out.column("mx").to_pylist() == want.column("d_max").to_pylist()
    assert out.column("c").to_pylist() == want.column("d_count").to_pylist()


def test_decimal_sort():
    s = _session()
    tb = _dec_table(300, null_every=11)
    out = s.create_dataframe(tb).sort(col("d")).collect()
    vals = [v for v in out.column("d").to_pylist() if v is not None]
    assert vals == sorted(vals)


def test_decimal_group_keys_and_shuffle():
    s = _session()
    vals = [D("1.50"), D("-2.25"), D("1.50"), None, D("-2.25"), D("1.50")]
    tb = pa.table({"d": pa.array(vals * 50, type=pa.decimal128(10, 2)),
                   "v": pa.array(list(range(300)), type=pa.int64())})
    out = (s.create_dataframe(tb, num_partitions=4)
           .group_by(col("d")).agg(F.count("*").alias("c"))
           .collect())
    got = dict(zip(out.column("d").to_pylist(), out.column("c").to_pylist()))
    assert got == {D("1.50"): 150, D("-2.25"): 100, None: 50}


def test_decimal128_expressions_fall_back_to_cpu():
    s = _session()
    tb = pa.table({"d": pa.array([D("123456789012345678901.23")],
                                 type=pa.decimal128(30, 2))})
    df = s.create_dataframe(tb)
    out = df.select((col("d") + col("d")).alias("dd")).collect()
    assert out.column("dd").to_pylist() == [D("246913578024691357802.46")]
    # the projection must NOT have claimed the TPU
    assert not any(n == "ProjectExec" and p == "tpu"
                   for n, p in _placements(s))


def test_decimal128_min_max_on_tpu():
    s = _session()
    big = [D("123456789012345678901.23"), D("-99999999999999999999.99"),
           None, D("5.00")]
    tb = pa.table({"k": pa.array([1, 1, 1, 1]),
                   "d": pa.array(big, type=pa.decimal128(30, 2))})
    out = s.create_dataframe(tb).group_by(col("k")).agg(
        F.min(col("d")).alias("mn"), F.max(col("d")).alias("mx")).collect()
    assert out.column("mn").to_pylist() == [D("-99999999999999999999.99")]
    assert out.column("mx").to_pylist() == [D("123456789012345678901.23")]
    assert ("TpuHashAggregateExec", "tpu") in _placements(s)


def test_decimal_cast_to_double_and_string():
    s = _session()
    tb = pa.table({"d": pa.array([D("12.34"), None, D("-0.05")],
                                 type=pa.decimal128(10, 2))})
    df = s.create_dataframe(tb)
    out = df.select(col("d").cast("double").alias("f"),
                    col("d").cast("string").alias("s")).collect()
    assert out.column("f").to_pylist() == [12.34, None, -0.05]
    assert out.column("s").to_pylist() == ["12.34", None, "-0.05"]


def test_decimal_cast_scale_up_to_128_exact():
    """Regression: scale-up into a >18-digit target used to wrap in int64
    on both engines."""
    s = _session()
    tb = pa.table({"d": pa.array([D("999999999999999999"), None],
                                 type=pa.decimal128(18, 0))})
    df = s.create_dataframe(tb)
    out = df.select(col("d").cast(pa.decimal128(38, 5)).alias("x"),
                    col("d").cast(pa.decimal128(38, 20)).alias("y")).collect()
    assert out.column("x").to_pylist() == [D("999999999999999999.00000"),
                                           None]
    assert out.column("y").to_pylist() == [D("999999999999999999"), None]


def test_decimal128_literal_exact_on_cpu_fallback():
    s = _session()
    df = s.create_dataframe(pa.table({"x": pa.array([1])}))
    big = D("12345678901234567890123.45")
    out = df.select(lit(big).alias("L")).collect()
    assert out.column("L").to_pylist() == [big]


def test_decimal_mul_into_128_exact():
    s = _session()
    tb = pa.table({"a": pa.array([D("123456789012.34")],
                                 type=pa.decimal128(14, 2)),
                   "b": pa.array([D("987654321098.76")],
                                 type=pa.decimal128(14, 2))})
    out = s.create_dataframe(tb).select(
        (col("a") * col("b")).alias("p")).collect()
    assert out.column("p").to_pylist() == [
        D("123456789012.34") * D("987654321098.76")]


def test_decimal_cast_scale_down_half_up():
    """Regression: the scale-down branch of decimal->decimal cast was
    unreachable (mis-indented up-scale return) — cast(decimal(10,4) ->
    decimal(10,2)) raised UnboundLocalError on both engines."""
    from spark_rapids_tpu import types as t
    s = _session()
    vals = [D("1.2345"), D("-1.2345"), D("0.0050"), D("-0.0050"),
            D("99.9949"), D("99.9951"), None, D("0.0000")]
    tb = pa.table({"d": pa.array(vals, type=pa.decimal128(10, 4))})
    out = s.create_dataframe(tb).select(
        col("d").cast(t.DecimalType(10, 2)).alias("c")).collect()
    half_up = decimal.Decimal("0.01")
    want = [None if v is None else
            v.quantize(half_up, rounding=decimal.ROUND_HALF_UP)
            for v in vals]
    assert out.column("c").to_pylist() == want


def test_decimal_cast_scale_down_differential():
    from spark_rapids_tpu import types as t
    tb = _dec_table(300, precision=12, scale=4, seed=5)
    outs = {}
    for enabled in (True, False):
        s = _session(enabled)
        outs[enabled] = s.create_dataframe(tb).select(
            col("k"),
            col("d").cast(t.DecimalType(12, 1)).alias("c")).collect()
    assert outs[True].column("c").to_pylist() == \
        outs[False].column("c").to_pylist()
