"""Spark-facing bridge tests: a fake-JVM process plays the executor's
role (ref Plugin.scala:44-51 ColumnarRule replacing subtrees), shipping
a scan->filter->aggregate stage as a JSON plan spec + Arrow IPC stream
to a REAL sidecar subprocess, and checks the results against an
independent oracle — the smallest honest end-to-end proof that a Spark
query's aggregate executes inside this engine."""

import os
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pytest

from spark_rapids_tpu.bridge import BridgeClient, SidecarServer
from spark_rapids_tpu.bridge.client import BridgeError


@pytest.fixture(scope="module")
def sidecar():
    """A real sidecar OS process, discovered via its stdout handshake."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PALLAS_AXON_POOL_IPS"] = ""
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.bridge.sidecar"],
        stdout=subprocess.PIPE, env=env, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    port = None
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("TPU_SIDECAR_PORT="):
            port = int(line.strip().split("=")[1])
            break
    assert port, "sidecar never announced its port"
    yield port
    c = BridgeClient(port)
    c.shutdown_sidecar()
    c.close()
    proc.wait(timeout=10)


def _fact(n=20000):
    rng = np.random.default_rng(8)
    return pa.table({
        "k": pa.array(rng.integers(0, 100, n).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
    })


def test_scan_filter_aggregate_stage(sidecar):
    tb = _fact()
    spec = {
        "ops": [
            {"op": "filter",
             "condition": {"op": "gt",
                           "children": [{"col": "v"},
                                        {"lit": 0, "type": "bigint"}]}},
            {"op": "aggregate",
             "groupBy": [{"col": "k"}],
             "aggs": [{"fn": "sum", "expr": {"col": "v"}, "name": "sv"},
                      {"fn": "count", "expr": {"col": "v"}, "name": "c"}]},
            {"op": "sort",
             "orders": [{"expr": {"col": "k"}, "ascending": True}]},
        ],
    }
    client = BridgeClient(sidecar)
    assert client.ping()
    got = client.execute_stage(spec, tb)
    client.close()

    flt = tb.filter(pc.greater(tb.column("v"), 0))
    want = pa.TableGroupBy(flt, ["k"], use_threads=False).aggregate(
        [("v", "sum"), ("v", "count")]).sort_by("k")
    assert got.column("k").to_pylist() == want.column("k").to_pylist()
    assert got.column("sv").to_pylist() == want.column("v_sum").to_pylist()
    assert got.column("c").to_pylist() == want.column("v_count").to_pylist()


def test_project_and_limit_stage(sidecar):
    tb = _fact(500)
    spec = {
        "ops": [
            {"op": "project",
             "exprs": [{"expr": {"col": "k"}, "name": "k"},
                       {"expr": {"op": "mul",
                                 "children": [{"col": "v"},
                                              {"lit": 2,
                                               "type": "bigint"}]},
                        "name": "v2"}]},
            {"op": "sort",
             "orders": [{"expr": {"col": "v2"}, "ascending": False}]},
            {"op": "limit", "n": 5},
        ],
    }
    client = BridgeClient(sidecar)
    got = client.execute_stage(spec, tb)
    client.close()
    want = sorted((2 * v for v in tb.column("v").to_pylist()),
                  reverse=True)[:5]
    assert got.column("v2").to_pylist() == want


def test_bad_stage_reports_error_and_sidecar_survives(sidecar):
    tb = _fact(100)
    client = BridgeClient(sidecar)
    with pytest.raises(BridgeError, match="unsupported bridge"):
        client.execute_stage(
            {"ops": [{"op": "frobnicate"}]}, tb)
    # same connection still serves good stages
    got = client.execute_stage(
        {"ops": [{"op": "aggregate", "groupBy": [],
                  "aggs": [{"fn": "count", "expr": {"col": "k"},
                            "name": "c"}]}]}, tb)
    client.close()
    assert got.column("c").to_pylist() == [100]


def test_spec_roundtrip_in_process():
    """plan_spec_to_logical is usable without the socket layer (the unit
    seam a JVM-side test suite would target)."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.bridge import plan_spec_to_logical
    tb = _fact(1000)
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    lp = plan_spec_to_logical(
        {"ops": [{"op": "aggregate", "groupBy": [{"col": "k"}],
                  "aggs": [{"fn": "max", "expr": {"col": "v"},
                            "name": "m"}]}]}, tb)
    out = s.execute(lp).sort_by("k")
    want = pa.TableGroupBy(tb, ["k"], use_threads=False).aggregate(
        [("v", "max")]).sort_by("k")
    assert out.column("m").to_pylist() == want.column("v_max").to_pylist()


def test_join_stage_two_streams(sidecar):
    """Multi-input stage: the fake JVM ships TWO Arrow streams and a
    join op referencing the second (ref GpuOverrides.scala:3164 — the
    exec registry replaces joins too)."""
    client = BridgeClient(sidecar)
    try:
        rng = np.random.default_rng(12)
        fact = pa.table({
            "k": pa.array(rng.integers(0, 50, 2000).astype(np.int64)),
            "v": pa.array(rng.integers(-99, 99, 2000).astype(np.int64)),
        })
        dim = pa.table({
            "k": pa.array(np.arange(40, dtype=np.int64)),
            "w": pa.array(np.arange(40, dtype=np.int64) * 3),
        })
        spec = {
            "input": {"schema": [["k", "bigint"], ["v", "bigint"]]},
            "inputs": [{"schema": [["k", "bigint"], ["w", "bigint"]]}],
            "ops": [
                {"op": "join", "right": 1, "how": "inner", "on": ["k"]},
                {"op": "aggregate", "groupBy": [{"col": "k"}],
                 "aggs": [{"fn": "sum", "expr": {"col": "w"},
                           "name": "sw"},
                          {"fn": "count", "expr": None, "name": "c"}]},
                {"op": "sort",
                 "orders": [{"expr": {"col": "k"}, "ascending": True}]},
            ],
        }
        out = client.execute_stage(spec, fact, [dim])
        joined = fact.join(dim, keys="k", join_type="inner")
        want = pa.TableGroupBy(joined, ["k"], use_threads=False).aggregate(
            [("w", "sum"), ("k", "count")]).sort_by("k")
        assert out.column("k").to_pylist() == want.column("k").to_pylist()
        assert out.column("sw").to_pylist() == \
            want.column("w_sum").to_pylist()
        assert out.column("c").to_pylist() == \
            want.column("k_count").to_pylist()
    finally:
        client.close()


def test_window_stage(sidecar):
    """Window frames over the bridge: row_number + running sum."""
    client = BridgeClient(sidecar)
    try:
        tb = pa.table({
            "g": pa.array([1, 1, 1, 2, 2], type=pa.int64()),
            "o": pa.array([3, 1, 2, 2, 1], type=pa.int64()),
            "v": pa.array([10, 20, 30, 40, 50], type=pa.int64()),
        })
        spec = {
            "input": {"schema": [["g", "bigint"], ["o", "bigint"],
                                 ["v", "bigint"]]},
            "ops": [
                {"op": "window",
                 "partitionBy": [{"col": "g"}],
                 "orderBy": [{"expr": {"col": "o"}, "ascending": True}],
                 "funcs": [{"fn": "row_number", "name": "rn"},
                           {"fn": "sum", "expr": {"col": "v"},
                            "name": "rs"}]},
                {"op": "sort",
                 "orders": [{"expr": {"col": "g"}, "ascending": True},
                            {"expr": {"col": "o"}, "ascending": True}]},
            ],
        }
        out = client.execute_stage(spec, tb)
        # oracle by hand: per (g) ordered by o
        assert out.column("g").to_pylist() == [1, 1, 1, 2, 2]
        rows = list(zip(out.column("g").to_pylist(),
                        out.column("o").to_pylist(),
                        out.column("rn").to_pylist(),
                        out.column("rs").to_pylist()))
        assert rows == [(1, 1, 1, 20), (1, 2, 2, 50), (1, 3, 3, 60),
                        (2, 1, 1, 50), (2, 2, 2, 90)]
    finally:
        client.close()
