"""Differential oracle for the flow-sensitive plan typechecker: the
abstract interpreter's predictions (schema, residency, partitioning,
ordering) are checked against REAL numpy-backend execution on every
subtree of the golden corpus — the analyzer is itself statically checked
against the engine, the discipline capabilities.verify_gates()
established for dtype gates.

  * good_plans.py: zero false rejects (no error diagnostics) AND every
    prediction matches execution;
  * bad_plans.py: zero false admits (each fixture's expected codes fire
    in flow-sensitive mode);
  * plus drift-detection sanity: a deliberately wrong prediction IS
    caught, so a green oracle is evidence, not vacuity.
"""

import importlib.util
import json
import os

import pyarrow as pa
import pytest

from spark_rapids_tpu import types as t
from spark_rapids_tpu.analysis import absdomain
from spark_rapids_tpu.analysis.interp import format_states, infer_plan
from spark_rapids_tpu.analysis.oracle import _compare, _observe, verify_plan
from spark_rapids_tpu.analysis.plan_lint import lint_plan
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec import base as eb

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "goldens", "lint")


def _load(fname):
    spec = importlib.util.spec_from_file_location(
        fname.replace(".py", ""), os.path.join(GOLDEN_DIR, fname))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return {k: getattr(mod, k) for k in dir(mod) if k.startswith("plan_")}


GOOD = sorted(_load("good_plans.py"))
with open(os.path.join(GOLDEN_DIR, "expected_codes.json")) as f:
    BAD_EXPECTED = json.load(f)


# ---------------------------------------------------------------------------
# predictions match execution on every subtree (zero drift)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", GOOD)
def test_oracle_predictions_match_execution(name):
    root, conf_map = _load("good_plans.py")[name]()
    conf = RapidsConf(conf_map)
    mismatches = verify_plan(root, conf)
    assert not mismatches, "\n".join(
        [format_states(root, infer_plan(root, conf))] + mismatches)


@pytest.mark.parametrize("name", GOOD)
def test_good_corpus_has_zero_false_rejects(name):
    root, conf_map = _load("good_plans.py")[name]()
    diags = lint_plan(root, RapidsConf(conf_map), infer=True)
    errors = [d for d in diags if d.is_error]
    assert not errors, [d.render() for d in errors]


@pytest.mark.parametrize("name", sorted(BAD_EXPECTED))
def test_bad_corpus_has_zero_false_admits(name):
    """Flow-sensitive mode must still flag every golden hazard."""
    root, conf_map = _load("bad_plans.py")[name]()
    got = {d.code for d in lint_plan(root, RapidsConf(conf_map),
                                     infer=True)}
    assert set(BAD_EXPECTED[name]) <= got, (name, got)


# ---------------------------------------------------------------------------
# the oracle is not vacuous: wrong predictions ARE caught
# ---------------------------------------------------------------------------

def _observe_root(root, conf):
    ctx = eb.ExecContext(conf)
    ctx.task_context["no_speculation"] = True
    return _observe(root, ctx)


def test_oracle_catches_wrong_schema_prediction():
    root, conf_map = _load("good_plans.py")["plan_project_filter_device"]()
    conf = RapidsConf(conf_map)
    st = infer_plan(root, conf).state(root)
    obs = _observe_root(root, conf)
    assert not _compare(st, obs)
    wrong = st.replace(dtypes=[t.DOUBLE] * len(st.dtypes))
    assert any("dtypes" in m for m in _compare(wrong, obs))
    renamed = st.replace(names=["x" for _ in st.names])
    assert any("columns" in m for m in _compare(renamed, obs))


def test_oracle_catches_wrong_residency_prediction():
    root, conf_map = _load("good_plans.py")["plan_host_pipeline"]()
    conf = RapidsConf(conf_map)
    st = infer_plan(root, conf).state(root)
    obs = _observe_root(root, conf)
    assert st.residency == absdomain.HOST and not _compare(st, obs)
    wrong = st.replace(residency=absdomain.DEVICE)
    assert any("residency" in m for m in _compare(wrong, obs))


def test_oracle_catches_wrong_clustering_prediction():
    """Claiming hash clustering on a column the exchange does NOT route
    by must be refuted by the observed partition contents."""
    from spark_rapids_tpu.exec.basic import LocalScanExec
    from spark_rapids_tpu.expr.core import AttributeReference
    from spark_rapids_tpu.shuffle.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioning
    tb = pa.table({
        "k": pa.array([i % 5 for i in range(40)], type=pa.int64()),
        # v repeats 0/1: its values straddle every k-routed partition
        "v": pa.array([i % 2 for i in range(40)], type=pa.int64()),
    })
    scan = LocalScanExec(tb, num_partitions=2)
    scan.placement = eb.TPU
    ex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("k")], 4), scan)
    ex.placement = eb.TPU
    conf = RapidsConf({})
    st = infer_plan(ex, conf).state(ex)
    obs = _observe_root(ex, conf)
    assert not _compare(st, obs)  # the true claim (clustered on k) holds
    wrong = st.replace(dist=absdomain.HashDist(["v"], 4))
    assert any("clustering" in m for m in _compare(wrong, obs))


def test_oracle_catches_wrong_ordering_prediction():
    root, conf_map = _load("good_plans.py")["plan_global_sort"]()
    conf = RapidsConf(conf_map)
    st = infer_plan(root, conf).state(root)
    obs = _observe_root(root, conf)
    assert st.ordering and not _compare(st, obs)
    flipped = st.replace(ordering=((st.ordering[0][0],
                                    not st.ordering[0][1]),))
    assert any("ordering" in m for m in _compare(flipped, obs))


# ---------------------------------------------------------------------------
# interface-requirement declarations (verify_gates()-style drift checks)
# ---------------------------------------------------------------------------

def test_contract_declarations_exist_where_runtime_assumes_colocation():
    """The operators whose kernels ASSUME a partitioning contract must
    declare it via Exec.input_contracts — the declaration is what the
    interpreter enforces and the oracle keeps honest."""
    good = _load("good_plans.py")
    join, _ = good["plan_colocated_join_with_exchanges"]()
    assert isinstance(join.input_contracts(),
                      absdomain.CoClusteredContract)
    final, _ = good["plan_partial_final_aggregate"]()
    assert isinstance(final.input_contracts(),
                      absdomain.ClusteredContract)
    # non-colocated joins and PARTIAL aggregates assume nothing
    bj, _ = good["plan_broadcast_join"]()
    assert bj.input_contracts() is None
    assert final.children[0].children[0].input_contracts() is None


def test_declared_contracts_accept_what_execution_coLocates():
    """Satisfied declarations on the good corpus, violated ones on the
    bad corpus — the two directions of the admission drift check."""
    good = _load("good_plans.py")
    for name in ("plan_colocated_join_with_exchanges",
                 "plan_partial_final_aggregate"):
        root, conf_map = good[name]()
        res = infer_plan(root, RapidsConf(conf_map))
        assert not [d for d in res.diags
                    if d.code in ("TPU-L006", "TPU-L011")], name
    bad = _load("bad_plans.py")
    root, conf_map = bad["plan_L011_contract_broken_by_rewrite"]()
    res = infer_plan(root, RapidsConf(conf_map))
    assert [d for d in res.diags if d.code == "TPU-L011"]


def test_downgrade_repairs_flow_contract_violation():
    """TPU-L011 is downgradeable: the host flip clears the co-location
    assumption and re-lints clean."""
    from spark_rapids_tpu.analysis.plan_lint import downgrade_hazards
    bad = _load("bad_plans.py")
    root, conf_map = bad["plan_L011_contract_broken_by_rewrite"]()
    conf = RapidsConf(conf_map)
    fixed = downgrade_hazards(root, lint_plan(root, conf))
    assert fixed.placement == eb.CPU and not fixed.colocated
    assert not [d for d in lint_plan(fixed, conf) if d.is_error]


# ---------------------------------------------------------------------------
# property-style: inferred schema == executed schema through the session
# ---------------------------------------------------------------------------

def _session():
    from spark_rapids_tpu.api.session import TpuSession
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", True)
            .config("spark.rapids.sql.explain", "NONE")
            .get_or_create())


def test_inferred_schema_equals_executed_schema_via_session():
    """For real converted plans (the overrides engine's own output!),
    the interpreter's root schema equals the schema of the collected
    arrow table, column for column."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    s = _session()
    tb = pa.table({
        "k": pa.array([i % 3 for i in range(30)], type=pa.int64()),
        "v": pa.array(range(30), type=pa.int64()),
        "x": pa.array([float(i) / 2 for i in range(30)],
                      type=pa.float64()),
    })
    queries = [
        lambda df: df.filter(df["v"] > 4).select(col("k"), col("x")),
        lambda df: df.group_by(col("k")).agg(
            F.sum(col("v")).alias("sv")),
        lambda df: df.select((col("v") + col("k")).alias("s")),
    ]
    for q in queries:
        df = s.create_dataframe(tb, num_partitions=2)
        out = q(df).collect()
        plan = s.last_plan
        st = infer_plan(plan, s.conf).state(plan)
        assert st is not None
        assert list(st.names) == out.schema.names
        from spark_rapids_tpu.columnar.interop import to_arrow_schema
        predicted = to_arrow_schema(st.names, st.dtypes)
        assert [f.type for f in predicted] == \
            [f.type for f in out.schema], (predicted, out.schema)
        # and every subtree of the converted plan matches execution
        assert verify_plan(plan, s.conf) == []
