"""Continuous-metrics registry, Prometheus/health exposition, and the
instrumented-subsystem feeds (obs/metrics.py + obs/health.py).

Covers the ISSUE-5 test checklist: histogram bucket math, the
cardinality-cap overflow path, a concurrent-increment race, a Prometheus
exposition golden, the health JSON schema/status derivation, and the
TPU-R007 module-tally lint rule."""

import json
import threading
import urllib.request

import pytest

from spark_rapids_tpu.obs import metrics as M
from spark_rapids_tpu.obs.health import (DEGRADED, DOWN, OK,
                                         HealthMonitor, MetricsServer,
                                         render_prometheus)


@pytest.fixture()
def reg():
    r = M.MetricsRegistry.reset_for_tests()
    yield r
    M.MetricsRegistry.reset_for_tests()


# ---------------------------------------------------------------------------
# counters / gauges
# ---------------------------------------------------------------------------

def test_counter_basics(reg):
    c = reg.counter("t_total", "doc")
    c.inc()
    c.inc(4)
    assert c.value() == 5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_counter_labels_and_value(reg):
    c = reg.counter("t_by_kind_total", "doc", ("kind",))
    c.labels(kind="a").inc(2)
    c.labels(kind="b").inc(3)
    assert c.value(kind="a") == 2
    assert c.value(kind="b") == 3
    assert c.value(kind="missing") == 0
    with pytest.raises(ValueError):
        c.labels(wrong="x")
    with pytest.raises(ValueError):
        c.inc()  # labeled family has no unlabeled series


def test_gauge_set_inc_dec(reg):
    g = reg.gauge("t_gauge", "doc")
    g.set(10)
    g.gauge_inc(5)
    g.dec(3)
    assert g.value() == 12


def test_family_reregistration_must_match(reg):
    reg.counter("t_same", "doc")
    reg.counter("t_same", "doc")  # idempotent
    with pytest.raises(ValueError):
        reg.gauge("t_same", "doc")
    with pytest.raises(ValueError):
        reg.counter("t_same", "doc", ("extra",))


def test_disabled_registry_is_inert(reg):
    c = reg.counter("t_off_total", "doc")
    c.inc(7)
    reg.enabled = False
    c.inc(100)
    reg.counter("t_off2_total", "doc").inc()
    reg.enabled = True
    assert c.value() == 7
    assert reg.counter("t_off2_total", "doc").value() == 0


# ---------------------------------------------------------------------------
# histogram bucket math
# ---------------------------------------------------------------------------

def test_histogram_bucket_math(reg):
    h = reg.histogram("t_lat_seconds", "doc", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 1.0, 5.0, 100.0):
        h.observe(v)
    (_, ch), = h.series()
    # boundaries are INCLUSIVE upper bounds (le semantics)
    assert ch.bucket_counts == [2, 2, 1, 1]
    cum = ch.cumulative()
    assert cum == [(0.1, 2), (1.0, 4), (10.0, 5), (float("inf"), 6)]
    assert ch.count == 6
    assert ch.sum == pytest.approx(106.65)


def test_histogram_fixed_buckets_sorted(reg):
    h = reg.histogram("t_h2", "doc", buckets=(5, 1, 3))
    h.observe(2)
    (_, ch), = h.series()
    assert ch.bounds == (1, 3, 5)
    assert ch.bucket_counts == [0, 1, 0, 0]


# ---------------------------------------------------------------------------
# cardinality cap
# ---------------------------------------------------------------------------

def test_cardinality_cap_evicts_into_overflow(reg):
    c = reg.counter("t_capped_total", "doc", ("q",))
    fam = c
    for i in range(M.DEFAULT_MAX_SERIES):
        fam.labels(q=f"q{i}").inc()
    assert fam.overflowed == 0
    # past the cap: new label sets collapse into one _overflow series
    fam.labels(q="straw1").inc()
    fam.labels(q="straw2").inc(2)
    assert fam.overflowed == 2
    assert fam.value(q="straw1") == 0  # never materialized
    assert fam.value(q=M.OVERFLOW_LABEL) == 3
    # existing series keep working past the cap
    fam.labels(q="q0").inc()
    assert fam.value(q="q0") == 2
    assert reg.overflow_total() == 2
    # the hard cap holds: at most max_series real series + 1 overflow
    assert len(fam.series()) <= M.DEFAULT_MAX_SERIES + 1


# ---------------------------------------------------------------------------
# concurrency
# ---------------------------------------------------------------------------

def test_concurrent_increments_are_exact(reg):
    c = reg.counter("t_race_total", "doc", ("lane",))
    n_threads, per = 8, 5000
    start = threading.Barrier(n_threads)

    def worker(i):
        ch = c.labels(lane=str(i % 2))
        start.wait()
        for _ in range(per):
            ch.inc()

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    total = c.value(lane="0") + c.value(lane="1")
    assert total == n_threads * per


# ---------------------------------------------------------------------------
# Prometheus exposition golden
# ---------------------------------------------------------------------------

def test_prometheus_exposition_golden(reg):
    c = reg.counter("tpu_test_ops_total", "ops by kind", ("kind",))
    c.labels(kind="a").inc(3)
    g = reg.gauge("tpu_test_depth", "queue depth")
    g.set(2)
    h = reg.histogram("tpu_test_lat_seconds", "latency",
                      buckets=(0.5, 2.0))
    h.observe(0.25)
    h.observe(1.0)
    golden = (
        "# HELP tpu_test_depth queue depth\n"
        "# TYPE tpu_test_depth gauge\n"
        "tpu_test_depth 2\n"
        "# HELP tpu_test_lat_seconds latency\n"
        "# TYPE tpu_test_lat_seconds histogram\n"
        'tpu_test_lat_seconds_bucket{le="0.5"} 1\n'
        'tpu_test_lat_seconds_bucket{le="2"} 2\n'
        'tpu_test_lat_seconds_bucket{le="+Inf"} 2\n'
        "tpu_test_lat_seconds_sum 1.25\n"
        "tpu_test_lat_seconds_count 2\n"
        "# HELP tpu_test_ops_total ops by kind\n"
        "# TYPE tpu_test_ops_total counter\n"
        'tpu_test_ops_total{kind="a"} 3\n'
        "# HELP tpu_metrics_series_overflow_total label sets evicted "
        "into _overflow series by the cardinality cap\n"
        "# TYPE tpu_metrics_series_overflow_total counter\n"
        "tpu_metrics_series_overflow_total 0\n")
    assert render_prometheus(reg) == golden


def test_prometheus_label_escaping(reg):
    c = reg.counter("tpu_esc_total", "d", ("p",))
    c.labels(p='we"ird\nvalue\\x').inc()
    text = render_prometheus(reg)
    assert r'tpu_esc_total{p="we\"ird\nvalue\\x"} 1' in text


# ---------------------------------------------------------------------------
# health snapshot schema + status derivation
# ---------------------------------------------------------------------------

def _assert_schema(snap):
    for key in ("status", "timestamp_ms", "components", "queries",
                "series_overflow"):
        assert key in snap, key
    assert snap["status"] in (OK, DEGRADED, DOWN)
    for comp in ("device", "arena", "memory", "shuffle", "queries"):
        assert comp in snap["components"], comp
        assert snap["components"][comp]["status"] in (OK, DEGRADED,
                                                      DOWN)
    for key in ("active", "completed", "failed", "retried"):
        assert key in snap["queries"], key


def test_health_snapshot_schema_and_deltas(reg):
    mon = HealthMonitor(reg)
    snap = _assert_schema_ret(mon.snapshot())
    assert snap["status"] == OK
    # an arena exhaustion since the last snapshot degrades
    reg.counter("tpu_arena_exhaustions_total", "d").inc()
    snap = mon.snapshot()
    assert snap["status"] == DEGRADED
    assert snap["components"]["arena"]["status"] == DEGRADED
    # the counter stopped moving -> next snapshot recovers
    snap = mon.snapshot()
    assert snap["status"] == OK
    # a dirty memsan ledger is DOWN, not degraded
    reg.counter("tpu_memsan_dirty_ledgers_total", "d").inc()
    assert mon.snapshot()["status"] == DOWN
    # dead device probe gauge pins DOWN regardless of deltas
    reg.gauge("tpu_device_probe_ok", "d").set(0)
    snap = mon.snapshot()
    assert snap["status"] == DOWN
    assert snap["components"]["device"]["status"] == DOWN
    reg.gauge("tpu_device_probe_ok", "d").set(1)
    assert mon.snapshot()["status"] == OK
    assert json.loads(json.dumps(snap))  # JSON-serializable throughout


def _assert_schema_ret(snap):
    _assert_schema(snap)
    return snap


def test_http_endpoint_serves_metrics_and_health(reg):
    reg.counter("tpu_endpoint_total", "d").inc(9)
    srv = MetricsServer(0, reg=reg)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "tpu_endpoint_total 9" in text
        snap = json.loads(urllib.request.urlopen(
            base + "/healthz").read())
        _assert_schema(snap)
        with pytest.raises(Exception):
            urllib.request.urlopen(base + "/nope")
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# TPU-R007: module-level tallies must route through the registry
# ---------------------------------------------------------------------------

def _r007(source):
    from spark_rapids_tpu.analysis.repo_lint import \
        module_tally_diagnostics
    return module_tally_diagnostics(source,
                                    "spark_rapids_tpu/exec/fake.py")


def test_r007_flags_module_tallies():
    diags = _r007(
        "import collections\n"
        "_N_CALLS = 0\n"
        "_HIT_COUNTS = {}\n"
        "_STATS = collections.Counter()\n"
        "_WHATEVER = collections.defaultdict(int)\n")
    assert len(diags) == 4
    assert all(d.code == "TPU-R007" for d in diags)


def test_r007_flags_module_aug_assign():
    diags = _r007("_TOTAL_ROWS = 0\n_TOTAL_ROWS += 5\n")
    assert len(diags) == 2


def test_r007_ignores_tables_caches_and_locals():
    diags = _r007(
        "_PA_JOIN = {'inner': 'inner'}\n"       # lookup table
        "_JIT_CACHE = {}\n"                      # cache, not a tally
        "_LEVEL_ORDER = {'A': 0}\n"
        "MAX_SPANS = 65536\n"                    # limit, not a count...
        "def f():\n"
        "    n_count = 0\n"                      # function-local is fine
        "    n_count += 1\n"
        "    return n_count\n")
    # MAX_SPANS matches no tally word; 'n_count' is not module level
    assert diags == []


def test_r007_allow_annotation_sanctions_in_place(tmp_path):
    """The shared `# tpulint: allow[TPU-R007]` mechanism covers R007
    like every other repo rule."""
    from spark_rapids_tpu.analysis.repo_lint import _allowed_lines
    src = ("# tpulint: allow[TPU-R007] legacy sink, migrating in PR 6\n"
           "_N_CALLS = 0\n")
    diags = _r007(src)
    assert len(diags) == 1
    allowed = _allowed_lines(src)
    lineno = int(diags[0].loc.rsplit(":", 1)[-1])
    assert lineno in allowed["TPU-R007"]


# ---------------------------------------------------------------------------
# device-probe deadline (the MULTICHIP rc=124 guard)
# ---------------------------------------------------------------------------

def test_discover_devices_timeout_counts_and_raises(reg, monkeypatch):
    import spark_rapids_tpu.parallel.mesh as mesh

    def hang():
        import time
        time.sleep(60)

    monkeypatch.setattr(mesh.jax, "devices", hang)
    with pytest.raises(mesh.DeviceDiscoveryTimeout):
        mesh.discover_devices(timeout_s=0.2)
    c = reg.counter("tpu_device_probe_failures_total", "d")
    assert c.value() == 1
    assert reg.gauge("tpu_device_probe_ok", "d").value() == 0
    # and device_count degrades to the single-chip default
    assert mesh.device_count(timeout_s=0.2, default=1) == 1


def test_discover_devices_success_sets_probe_ok(reg):
    import spark_rapids_tpu.parallel.mesh as mesh
    devs = mesh.discover_devices(timeout_s=30.0)
    assert devs
    assert reg.gauge("tpu_device_probe_ok", "d").value() == 1
