"""Window function tests (model: integration_tests/window_function_test.py).

The window kernels are shared between engines, so correctness here is
checked against independent pandas oracles, not just CPU-vs-TPU.
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.expr.window import Window
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect, with_tpu_session)
from spark_rapids_tpu.testing.data_gen import (
    IntegerGen, LongGen, gen_df, gen_table)


def _data(spark, length=256, seed=0):
    return gen_df(spark, [("k", IntegerGen(lo=0, hi=10, null_prob=0.1)),
                          ("o", IntegerGen(lo=0, hi=1000)),
                          ("v", IntegerGen(lo=-1000, hi=1000))],
                  length=length, seed=seed)


def test_row_number_vs_pandas():
    w = Window.partition_by(col("k")).order_by(col("o"), col("v"))

    def q(spark):
        return _data(spark).select("k", "o", "v",
                                   F.row_number().over(w).alias("rn"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    pdf = out[["k", "o", "v"]].copy()
    # stable sort so ties break by original order, same as the engine
    exp = (pdf.sort_values(["o", "v"], kind="stable", na_position="first")
           .groupby("k", dropna=False).cumcount() + 1)
    assert (out["rn"] == exp.reindex(out.index)).all()


def test_rank_dense_rank():
    w = Window.partition_by(col("k")).order_by(col("o"))

    def q(spark):
        return _data(spark).select(
            "k", "o", F.rank().over(w).alias("r"),
            F.dense_rank().over(w).alias("dr"),
            F.row_number().over(w).alias("rn"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    g = out.sort_values(["k", "o"]).reset_index(drop=True)
    exp_r = (g.groupby("k", dropna=False)["o"]
             .rank(method="min", na_option="top").astype(int))
    exp_dr = (g.groupby("k", dropna=False)["o"]
              .rank(method="dense", na_option="top").astype(int))
    assert (g["r"].values == exp_r.values).all()
    assert (g["dr"].values == exp_dr.values).all()


def test_running_sum_and_count():
    w = (Window.partition_by(col("k")).order_by(col("o"), col("v"))
         .rows_between(Window.unboundedPreceding, Window.currentRow))

    def q(spark):
        return _data(spark).select(
            "k", "o", "v",
            F.sum(col("v")).over(w).alias("rs"),
            F.count(col("v")).over(w).alias("rc"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    srt = out.sort_values(["o", "v"], kind="stable", na_position="first")
    g = srt.groupby("k", dropna=False)["v"]
    exp_sum = g.transform(lambda s: s.fillna(0).cumsum()).reindex(out.index)
    exp_cnt = g.transform(lambda s: s.notna().cumsum()).reindex(out.index)
    # Spark: sum skips nulls; null only while no non-null values seen yet
    ok = ((exp_cnt > 0) & (out["rs"] == exp_sum)) | \
        ((exp_cnt == 0) & out["rs"].isna())
    assert ok.all()
    assert (out["rc"] == exp_cnt).all()


def test_whole_partition_agg():
    w = Window.partition_by(col("k"))

    def q(spark):
        return _data(spark).select(
            "k", "v",
            F.sum(col("v")).over(w).alias("ts"),
            F.max(col("v")).over(w).alias("tm"),
            F.avg(col("v")).over(w).alias("ta"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    g = out.groupby("k", dropna=False)
    assert np.allclose(out["ts"], g["v"].transform("sum"))
    assert (out["tm"] == g["v"].transform("max")).all()
    assert np.allclose(out["ta"], g["v"].transform("mean"))


def test_lead_lag():
    w = Window.partition_by(col("k")).order_by(col("o"), col("v"))

    def q(spark):
        return _data(spark).select(
            "k", "o", "v",
            F.lead(col("v")).over(w).alias("ld"),
            F.lag(col("v")).over(w).alias("lg"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    srt = out.sort_values(["o", "v"], kind="stable", na_position="first")
    exp_ld = srt.groupby("k", dropna=False)["v"].shift(-1)
    exp_lg = srt.groupby("k", dropna=False)["v"].shift(1)
    assert np.array_equal(out["ld"].fillna(-999999).values,
                          exp_ld.reindex(out.index).fillna(-999999).values)
    assert np.array_equal(out["lg"].fillna(-999999).values,
                          exp_lg.reindex(out.index).fillna(-999999).values)


def test_sliding_sum():
    w = (Window.partition_by(col("k")).order_by(col("o"), col("v"))
         .rows_between(-2, 2))

    def q(spark):
        return _data(spark, length=128).select(
            "k", "o", "v", F.sum(col("v")).over(w).alias("ss"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    srt = out.sort_values(["o", "v"], kind="stable", na_position="first")
    exp = (srt.groupby("k", dropna=False)["v"]
           .rolling(window=5, min_periods=1, center=True).sum()
           .reset_index(level=0, drop=True))
    assert np.allclose(out["ss"].values.astype(float),
                       exp.reindex(out.index).values)


def test_window_differential():
    w = Window.partition_by(col("k")).order_by(col("o"), col("v"))

    def q(spark):
        return _data(spark, length=512, seed=3).select(
            "k", "o", "v",
            F.row_number().over(w).alias("rn"),
            F.sum(col("v")).over(
                Window.partition_by(col("k"))).alias("ts"))
    assert_tpu_and_cpu_are_equal_collect(q)
