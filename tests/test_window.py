"""Window function tests (model: integration_tests/window_function_test.py).

The window kernels are shared between engines, so correctness here is
checked against independent pandas oracles, not just CPU-vs-TPU.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.expr.window import Window
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect, with_tpu_session)
from spark_rapids_tpu.testing.data_gen import (
    IntegerGen, LongGen, gen_df, gen_table)


def _data(spark, length=256, seed=0):
    return gen_df(spark, [("k", IntegerGen(lo=0, hi=10, null_prob=0.1)),
                          ("o", IntegerGen(lo=0, hi=1000)),
                          ("v", IntegerGen(lo=-1000, hi=1000))],
                  length=length, seed=seed)


def test_row_number_vs_pandas():
    w = Window.partition_by(col("k")).order_by(col("o"), col("v"))

    def q(spark):
        return _data(spark).select("k", "o", "v",
                                   F.row_number().over(w).alias("rn"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    pdf = out[["k", "o", "v"]].copy()
    # stable sort so ties break by original order, same as the engine
    exp = (pdf.sort_values(["o", "v"], kind="stable", na_position="first")
           .groupby("k", dropna=False).cumcount() + 1)
    assert (out["rn"] == exp.reindex(out.index)).all()


def test_rank_dense_rank():
    w = Window.partition_by(col("k")).order_by(col("o"))

    def q(spark):
        return _data(spark).select(
            "k", "o", F.rank().over(w).alias("r"),
            F.dense_rank().over(w).alias("dr"),
            F.row_number().over(w).alias("rn"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    g = out.sort_values(["k", "o"]).reset_index(drop=True)
    exp_r = (g.groupby("k", dropna=False)["o"]
             .rank(method="min", na_option="top").astype(int))
    exp_dr = (g.groupby("k", dropna=False)["o"]
              .rank(method="dense", na_option="top").astype(int))
    assert (g["r"].values == exp_r.values).all()
    assert (g["dr"].values == exp_dr.values).all()


def test_running_sum_and_count():
    w = (Window.partition_by(col("k")).order_by(col("o"), col("v"))
         .rows_between(Window.unboundedPreceding, Window.currentRow))

    def q(spark):
        return _data(spark).select(
            "k", "o", "v",
            F.sum(col("v")).over(w).alias("rs"),
            F.count(col("v")).over(w).alias("rc"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    srt = out.sort_values(["o", "v"], kind="stable", na_position="first")
    g = srt.groupby("k", dropna=False)["v"]
    exp_sum = g.transform(lambda s: s.fillna(0).cumsum()).reindex(out.index)
    exp_cnt = g.transform(lambda s: s.notna().cumsum()).reindex(out.index)
    # Spark: sum skips nulls; null only while no non-null values seen yet
    ok = ((exp_cnt > 0) & (out["rs"] == exp_sum)) | \
        ((exp_cnt == 0) & out["rs"].isna())
    assert ok.all()
    assert (out["rc"] == exp_cnt).all()


def test_whole_partition_agg():
    w = Window.partition_by(col("k"))

    def q(spark):
        return _data(spark).select(
            "k", "v",
            F.sum(col("v")).over(w).alias("ts"),
            F.max(col("v")).over(w).alias("tm"),
            F.avg(col("v")).over(w).alias("ta"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    g = out.groupby("k", dropna=False)
    assert np.allclose(out["ts"], g["v"].transform("sum"))
    assert (out["tm"] == g["v"].transform("max")).all()
    assert np.allclose(out["ta"], g["v"].transform("mean"))


def test_lead_lag():
    w = Window.partition_by(col("k")).order_by(col("o"), col("v"))

    def q(spark):
        return _data(spark).select(
            "k", "o", "v",
            F.lead(col("v")).over(w).alias("ld"),
            F.lag(col("v")).over(w).alias("lg"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    srt = out.sort_values(["o", "v"], kind="stable", na_position="first")
    exp_ld = srt.groupby("k", dropna=False)["v"].shift(-1)
    exp_lg = srt.groupby("k", dropna=False)["v"].shift(1)
    assert np.array_equal(out["ld"].fillna(-999999).values,
                          exp_ld.reindex(out.index).fillna(-999999).values)
    assert np.array_equal(out["lg"].fillna(-999999).values,
                          exp_lg.reindex(out.index).fillna(-999999).values)


def test_sliding_sum():
    w = (Window.partition_by(col("k")).order_by(col("o"), col("v"))
         .rows_between(-2, 2))

    def q(spark):
        return _data(spark, length=128).select(
            "k", "o", "v", F.sum(col("v")).over(w).alias("ss"))
    out = with_tpu_session(lambda s: q(s).collect()).to_pandas()
    srt = out.sort_values(["o", "v"], kind="stable", na_position="first")
    exp = (srt.groupby("k", dropna=False)["v"]
           .rolling(window=5, min_periods=1, center=True).sum()
           .reset_index(level=0, drop=True))
    assert np.allclose(out["ss"].values.astype(float),
                       exp.reindex(out.index).values)


def test_window_differential():
    w = Window.partition_by(col("k")).order_by(col("o"), col("v"))

    def q(spark):
        return _data(spark, length=512, seed=3).select(
            "k", "o", "v",
            F.row_number().over(w).alias("rn"),
            F.sum(col("v")).over(
                Window.partition_by(col("k"))).alias("ts"))
    assert_tpu_and_cpu_are_equal_collect(q)


# ---------------------------------------------------------------------------
# widened frames: bounded rows min/max/first/last + bounded range frames
# (brute-force python oracle for independence from the engine kernels)
# ---------------------------------------------------------------------------

def _brute_frame(rows, kind, lo_b, hi_b, key_of, val_of, ord_of):
    """Per-row frame aggregate oracle over (partition, order)-sorted rows."""
    import math
    out = []
    by_part = {}
    srt = sorted(range(len(rows)),
                 key=lambda i: (key_of(i), (ord_of(i) is None, ord_of(i) or 0)))
    for i in srt:
        by_part.setdefault(key_of(i), []).append(i)
    frames = {}
    for part, idxs in by_part.items():
        for j, i in enumerate(idxs):
            if kind == "rows":
                lo = 0 if lo_b is None else max(0, j + lo_b)
                hi = len(idxs) - 1 if hi_b is None else min(len(idxs) - 1,
                                                            j + hi_b)
                frames[i] = [idxs[k] for k in range(lo, hi + 1)] \
                    if hi >= lo else []
            else:  # range
                v = ord_of(i)
                if v is None:
                    frames[i] = [k for k in idxs if ord_of(k) is None]
                    continue
                lo_t = -math.inf if lo_b is None else v + lo_b
                hi_t = math.inf if hi_b is None else v + hi_b
                frames[i] = [k for k in idxs if ord_of(k) is not None and
                             lo_t <= ord_of(k) <= hi_t]
    return frames


def _window_df(n=200, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 6, n).astype(np.int64)),
        "o": pa.array(rng.integers(0, 50, n).astype(np.int64)),
        "v": pa.array([None if i % 11 == 0 else int(x) for i, x in
                       enumerate(rng.integers(-100, 100, n))],
                      type=pa.int64()),
        "rid": pa.array(np.arange(n, dtype=np.int64)),
    })


def test_bounded_rows_min_max(tpu_session):
    tb = _window_df()
    s = tpu_session
    from spark_rapids_tpu.expr.window import WindowBuilder
    w = (WindowBuilder().partition_by(col("k"))
         .order_by(col("o"), col("rid")).rows_between(-2, 2))
    out = (s.create_dataframe(tb)
           .select(col("rid"), F.min(col("v")).over(w).alias("mn"),
                   F.max(col("v")).over(w).alias("mx"))
           .collect().sort_by("rid"))
    rows = list(range(tb.num_rows))
    k = tb.column("k").to_pylist()
    o = tb.column("o").to_pylist()
    v = tb.column("v").to_pylist()
    rid = tb.column("rid").to_pylist()
    frames = _brute_frame(rows, "rows", -2, 2,
                          key_of=lambda i: k[i],
                          val_of=lambda i: v[i],
                          ord_of=lambda i: (o[i], rid[i]))
    got_mn = out.column("mn").to_pylist()
    got_mx = out.column("mx").to_pylist()
    for i in rows:
        vals = [v[j] for j in frames[i] if v[j] is not None]
        assert got_mn[i] == (min(vals) if vals else None), i
        assert got_mx[i] == (max(vals) if vals else None), i


def test_bounded_range_sum_count(tpu_session):
    tb = _window_df()
    s = tpu_session
    from spark_rapids_tpu.expr.window import WindowBuilder
    w = (WindowBuilder().partition_by(col("k"))
         .order_by(col("o")).range_between(-5, 5))
    out = (s.create_dataframe(tb)
           .select(col("rid"), F.sum(col("v")).over(w).alias("sv"),
                   F.count(col("v")).over(w).alias("cv"))
           .collect().sort_by("rid"))
    rows = list(range(tb.num_rows))
    k = tb.column("k").to_pylist()
    o = tb.column("o").to_pylist()
    v = tb.column("v").to_pylist()
    frames = _brute_frame(rows, "range", -5, 5,
                          key_of=lambda i: k[i],
                          val_of=lambda i: v[i],
                          ord_of=lambda i: o[i])
    got_sv = out.column("sv").to_pylist()
    got_cv = out.column("cv").to_pylist()
    for i in rows:
        vals = [v[j] for j in frames[i] if v[j] is not None]
        assert got_cv[i] == len(vals), i
        assert got_sv[i] == (sum(vals) if vals else None), i


def test_bounded_range_min_max(tpu_session):
    tb = _window_df(seed=13)
    s = tpu_session
    from spark_rapids_tpu.expr.window import WindowBuilder
    w = (WindowBuilder().partition_by(col("k"))
         .order_by(col("o")).range_between(-3, 0))
    out = (s.create_dataframe(tb)
           .select(col("rid"), F.min(col("v")).over(w).alias("mn"),
                   F.max(col("v")).over(w).alias("mx"))
           .collect().sort_by("rid"))
    rows = list(range(tb.num_rows))
    k = tb.column("k").to_pylist()
    o = tb.column("o").to_pylist()
    v = tb.column("v").to_pylist()
    frames = _brute_frame(rows, "range", -3, 0,
                          key_of=lambda i: k[i],
                          val_of=lambda i: v[i],
                          ord_of=lambda i: o[i])
    got_mn = out.column("mn").to_pylist()
    got_mx = out.column("mx").to_pylist()
    for i in rows:
        vals = [v[j] for j in frames[i] if v[j] is not None]
        assert got_mn[i] == (min(vals) if vals else None), i
        assert got_mx[i] == (max(vals) if vals else None), i


@pytest.mark.parametrize("seed,lo_b,hi_b", [
    (1, -5, 5), (2, -3, 0), (3, 0, 4), (4, -7, -2), (5, 2, 6),
])
def test_bounded_range_fuzz(tpu_session, seed, lo_b, hi_b):
    """Fuzzed bounded RANGE frames incl. null order keys (peer-run frame
    for null rows, per Spark semantics) against a brute-force oracle.
    Regression guard for the padded-row search-window bug (dead rows must
    park at +extreme so _vec_bound's ascending precondition holds)."""
    rng = np.random.default_rng(seed)
    n = 150
    tb = pa.table({
        "k": pa.array(rng.integers(0, 5, n).astype(np.int64)),
        "o": pa.array([None if i % 13 == 0 else int(x) for i, x in
                       enumerate(rng.integers(-30, 30, n))],
                      type=pa.int64()),
        "v": pa.array([None if i % 9 == 0 else int(x) for i, x in
                       enumerate(rng.integers(-50, 50, n))],
                      type=pa.int64()),
        "rid": pa.array(np.arange(n, dtype=np.int64)),
    })
    s = tpu_session
    from spark_rapids_tpu.expr.window import WindowBuilder
    w = (WindowBuilder().partition_by(col("k"))
         .order_by(col("o")).range_between(lo_b, hi_b))
    out = (s.create_dataframe(tb)
           .select(col("rid"), F.sum(col("v")).over(w).alias("sv"),
                   F.count(col("v")).over(w).alias("cv"),
                   F.min(col("v")).over(w).alias("mn"))
           .collect().sort_by("rid"))
    rows = list(range(n))
    k = tb.column("k").to_pylist()
    o = tb.column("o").to_pylist()
    v = tb.column("v").to_pylist()
    frames = _brute_frame(rows, "range", lo_b, hi_b,
                          key_of=lambda i: k[i],
                          val_of=lambda i: v[i],
                          ord_of=lambda i: o[i])
    got_sv = out.column("sv").to_pylist()
    got_cv = out.column("cv").to_pylist()
    got_mn = out.column("mn").to_pylist()
    for i in rows:
        vals = [v[j] for j in frames[i] if v[j] is not None]
        assert got_cv[i] == len(vals), (i, "count")
        assert got_sv[i] == (sum(vals) if vals else None), (i, "sum")
        assert got_mn[i] == (min(vals) if vals else None), (i, "min")


def test_percent_rank_and_cume_dist():
    """percent_rank / cume_dist vs a pandas oracle, with ties (peer
    runs) and a single-row partition (percent_rank -> 0.0)."""
    import pandas as pd

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.expr.window import WindowBuilder

    # the max-sorted partition has MULTIPLE rows: batch padding rows
    # sort after it, so an unmasked partition count inflates exactly
    # here (code-review round-3 finding)
    tb = pa.table({
        "k": pa.array([1, 1, 1, 1, 2, 2, 3, 3], type=pa.int64()),
        "v": pa.array([10, 20, 20, 30, 5, 5, 7, 9], type=pa.int64()),
    })
    w = WindowBuilder().partition_by(col("k")).order_by(col("v"))

    for enabled in (True, False):
        s = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", enabled).get_or_create())
        out = (s.create_dataframe(tb)
               .select(col("k"), col("v"),
                       F.percent_rank().over(w).alias("pr"),
                       F.cume_dist().over(w).alias("cd"))
               .collect().sort_by([("k", "ascending"),
                                   ("v", "ascending")]))
        pdf = tb.to_pandas()
        g = pdf.groupby("k")["v"]
        want_pr = pdf.assign(
            pr=g.rank(method="min").sub(1) /
            g.transform("count").sub(1).clip(lower=1) *
            (g.transform("count") > 1)) \
            .sort_values(["k", "v"])["pr"].tolist()
        want_cd = pdf.assign(cd=g.rank(method="max") /
                             g.transform("count")) \
            .sort_values(["k", "v"])["cd"].tolist()
        np.testing.assert_allclose(out.column("pr").to_pylist(), want_pr,
                                   rtol=1e-12, err_msg=str(enabled))
        np.testing.assert_allclose(out.column("cd").to_pylist(), want_cd,
                                   rtol=1e-12, err_msg=str(enabled))

        # no partition_by: ONE global frame over all live rows
        wg = WindowBuilder().order_by(col("v"))
        og = (s.create_dataframe(tb)
              .select(col("v"), F.cume_dist().over(wg).alias("cd"))
              .collect().sort_by("v"))
        n = tb.num_rows
        ranks = pd.Series(tb.column("v").to_pylist()).rank(method="max")
        want_g = (ranks / n).sort_values().tolist()
        np.testing.assert_allclose(sorted(og.column("cd").to_pylist()),
                                   want_g, rtol=1e-12,
                                   err_msg=str(enabled))


def test_window_scale_multi_spec_differential():
    """The round-4 window rewrite (shared per-spec carry-sort layouts,
    int32 positions, pad-shift running reductions, one carry-sort back)
    at 50k rows: several functions across two specs, nulls, descending
    order, bounded ROWS frames — differential vs the CPU engine."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.expr.window import WindowBuilder

    rng = np.random.default_rng(77)
    n = 50_000
    hot = rng.random(n) < 0.3
    k = np.where(hot, 3, rng.integers(0, 200, n)).astype(np.int64)
    v = rng.integers(-(10**9), 10**9, n).astype(np.int64)
    vmask = rng.random(n) < 0.08
    f = rng.random(n) * 1e6
    tbl = pa.table({"k": pa.array(k),
                    "v": pa.array(v, mask=vmask),
                    "f": pa.array(f)})

    def q(enabled):
        s = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", enabled).get_or_create())
        df = s.create_dataframe(tbl)
        w1 = WindowBuilder().partition_by(col("k")).order_by(col("v"))
        w2 = (WindowBuilder().partition_by(col("k"))
              .order_by(col("f").desc()))
        w3 = (WindowBuilder().partition_by(col("k")).order_by(col("v"))
              .rows_between(-2, 2))
        return (df.select(
            col("k"), col("v"), col("f"),
            F.row_number().over(w1).alias("rn"),
            F.sum(col("v")).over(w1).alias("rs"),
            F.rank().over(w2).alias("rk"),
            F.avg(col("f")).over(w2).alias("ra"),
            F.min(col("v")).over(w3).alias("m3"),
            F.count(col("v")).over(w3).alias("c3"),
            F.lag(col("v"), 1).over(w1).alias("lg"))
            .collect()
            .sort_by([("k", "ascending"), ("v", "ascending"),
                      ("f", "ascending")]))

    tpu, cpu = q(True), q(False)
    assert tpu.num_rows == cpu.num_rows == n
    for name in tpu.column_names:
        a, b = tpu.column(name).to_pylist(), cpu.column(name).to_pylist()
        for i, (x, y) in enumerate(zip(a, b)):
            if isinstance(x, float) and isinstance(y, float):
                assert x == y or abs(x - y) <= 1e-9 * max(1.0, abs(x),
                                                          abs(y)), \
                    (name, i, x, y)
            else:
                assert x == y, (name, i, x, y)
