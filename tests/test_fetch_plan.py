"""Fetch transfer-plan fuzz: random schemas, dtypes, null patterns, and
value ranges round-trip device -> packed wire -> host EXACTLY.

This is the subsystem with the most room for silent corruption
(validity-lane skipping, bool bit-packing, live-range integer
narrowing with device/host offset agreement), so it gets a property
test across many shapes rather than a few examples."""

import numpy as np
import pyarrow as pa
import pytest

import jax.numpy as jnp

from spark_rapids_tpu.columnar.device import batch_to_device
from spark_rapids_tpu.columnar.fetch import fetch_batch
from spark_rapids_tpu.columnar.device import batch_to_arrow, DeviceBatch


def _rand_column(rng, n, kind):
    if kind == "i64_small":
        vals = rng.integers(0, 200, n).astype(np.int64)
    elif kind == "i64_offset":
        # big offset, small span -> narrows to uint8/16 via live-min
        vals = rng.integers(10**15, 10**15 + 300, n).astype(np.int64)
    elif kind == "i64_wide":
        vals = rng.integers(-(2**62), 2**62, n).astype(np.int64)
    elif kind == "i32":
        vals = rng.integers(-50000, 50000, n).astype(np.int32)
    elif kind == "i16":
        vals = rng.integers(-30000, 30000, n).astype(np.int16)
    elif kind == "f64":
        vals = rng.random(n) * rng.choice([1.0, 1e18])
    elif kind == "f32":
        vals = (rng.random(n) * 100).astype(np.float32)
    elif kind == "bool":
        vals = rng.random(n) < 0.5
    elif kind == "str":
        vals = np.array(["s" * int(k) + str(k) for k in
                         rng.integers(0, 23, n)], dtype=object)
    elif kind == "ts":
        vals = rng.integers(1_500_000_000_000_000,
                            1_700_000_000_000_000, n).astype("M8[us]")
    else:
        raise AssertionError(kind)
    null_frac = float(rng.choice([0.0, 0.0, 0.1, 0.9]))
    mask = rng.random(n) < null_frac
    arr = pa.array(vals.tolist() if kind == "str" else vals,
                   mask=mask if null_frac else None)
    return arr


KINDS = ["i64_small", "i64_offset", "i64_wide", "i32", "i16", "f64",
         "f32", "bool", "str", "ts"]


@pytest.mark.parametrize("seed", range(8))
def test_fetch_round_trip_fuzz(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 3000))
    ncols = int(rng.integers(1, 6))
    kinds = [str(rng.choice(KINDS)) for _ in range(ncols)]
    cols = {f"c{i}_{k}": _rand_column(rng, n, k)
            for i, k in enumerate(kinds)}
    tbl = pa.table(cols)
    rb = tbl.combine_chunks().to_batches()[0]
    dev = batch_to_device(rb, xp=jnp)
    fetched = fetch_batch(dev)
    back = batch_to_arrow(fetched)
    want = batch_to_arrow(batch_to_device(rb, xp=np))
    assert back.num_rows == rb.num_rows
    for name in tbl.column_names:
        got = back.column(name).to_pylist()
        exp = want.column(name).to_pylist()
        assert got == exp, (name, kinds, n, got[:5], exp[:5])


def test_fetch_nested_round_trip():
    rng = np.random.default_rng(99)
    n = 500
    tbl = pa.table({
        "arr": pa.array([None if i % 7 == 0 else
                         list(range(i % 5)) for i in range(n)],
                        type=pa.list_(pa.int64())),
        "m": pa.array([None if i % 11 == 0 else
                       [(f"k{j}", i * j) for j in range(i % 3)]
                       for i in range(n)],
                      type=pa.map_(pa.string(), pa.int64())),
        "st": pa.array([{"a": int(i), "b": None if i % 3 else float(i)}
                        for i in range(n)],
                       type=pa.struct([("a", pa.int64()),
                                       ("b", pa.float64())])),
        "v": pa.array(rng.integers(0, 9, n).astype(np.int64)),
    })
    rb = tbl.combine_chunks().to_batches()[0]
    dev = batch_to_device(rb, xp=jnp)
    back = batch_to_arrow(fetch_batch(dev))
    want = batch_to_arrow(batch_to_device(rb, xp=np))
    for name in tbl.column_names:
        assert back.column(name).to_pylist() == \
            want.column(name).to_pylist(), name


def test_fetch_speculation_validates_and_falls_back():
    """Speculation arms only after the plan repeats: the THIRD fetch of
    a stable shape rides the single-sync path, and a same-shape batch
    whose value range changes the narrowing plan must discard the
    speculative buffers (a stale narrower width would silently wrap)."""
    from spark_rapids_tpu.columnar import fetch as fetch_mod

    fetch_mod._LAST_PLAN.clear()
    rng = np.random.default_rng(7)
    a = pa.table({"k": pa.array(rng.integers(0, 100, 2000)
                                .astype(np.int64)),
                  "s": pa.array([f"v{i%9}" for i in range(2000)])})
    rb = a.combine_chunks().to_batches()[0]
    dev = batch_to_device(rb, xp=jnp)
    one = batch_to_arrow(fetch_batch(dev))
    two = batch_to_arrow(fetch_batch(dev))   # arms the plan (count 1)
    (pkey, (plan0, cnt)), = fetch_mod._LAST_PLAN.items()
    assert cnt == 1
    three = batch_to_arrow(fetch_batch(dev))  # speculative single-sync
    assert one.equals(two) and one.equals(three)
    assert fetch_mod._LAST_PLAN[pkey][1] == 2

    # SAME padded shapes (same schema key), different value range ->
    # the narrowing plan widens; speculation must mispredict safely
    wide = pa.table({
        "k": pa.array(rng.integers(-(2**60), 2**60, 2000)
                      .astype(np.int64)),
        "s": pa.array([f"v{i%9}" for i in range(2000)])})
    rb2 = wide.combine_chunks().to_batches()[0]
    dev2 = batch_to_device(rb2, xp=jnp)
    assert fetch_mod._schema_key(dev2) == pkey[0]
    got = batch_to_arrow(fetch_batch(dev2))   # speculates, must discard
    want = batch_to_arrow(batch_to_device(rb2, xp=np))
    assert got.column("k").to_pylist() == want.column("k").to_pylist()
    assert got.column("s").to_pylist() == want.column("s").to_pylist()
    assert fetch_mod._LAST_PLAN[pkey][1] == 0  # repeat count reset


def test_fetch_extra_scalars_ride_along():
    """Deferred speculation guards ride the sizes transfer: values come
    back exactly, the batch is unchanged, and the speculative one-sync
    plan still validates on repeats."""
    rng = np.random.default_rng(44)
    tb = pa.table({
        "a": pa.array(rng.integers(0, 1000, 500).astype(np.int64)),
        "b": pa.array(rng.random(500)),
    })
    rb = tb.combine_chunks().to_batches()[0]
    for _ in range(3):   # repeats arm + use the speculative plan
        b = batch_to_device(rb)
        out, extras = fetch_batch(
            b, extra_scalars=[jnp.bool_(True), jnp.bool_(False),
                              jnp.int64(12345)])
        assert list(extras) == [1, 0, 12345]
        back = pa.Table.from_batches(
            [batch_to_arrow(DeviceBatch(out.columns, out.num_rows,
                                        tb.schema.names))])
        assert back.equals(tb)
    # host-side batches answer extras without device work
    host_out, host_extras = fetch_batch(out, extra_scalars=[jnp.bool_(True)])
    assert list(host_extras) == [1]
