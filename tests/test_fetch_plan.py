"""Fetch transfer-plan fuzz: random schemas, dtypes, null patterns, and
value ranges round-trip device -> packed wire -> host EXACTLY.

This is the subsystem with the most room for silent corruption
(validity-lane skipping, bool bit-packing, live-range integer
narrowing with device/host offset agreement), so it gets a property
test across many shapes rather than a few examples."""

import numpy as np
import pyarrow as pa
import pytest

import jax.numpy as jnp

from spark_rapids_tpu.columnar.device import batch_to_device
from spark_rapids_tpu.columnar.fetch import fetch_batch
from spark_rapids_tpu.columnar.device import batch_to_arrow, DeviceBatch


def _rand_column(rng, n, kind):
    if kind == "i64_small":
        vals = rng.integers(0, 200, n).astype(np.int64)
    elif kind == "i64_offset":
        # big offset, small span -> narrows to uint8/16 via live-min
        vals = rng.integers(10**15, 10**15 + 300, n).astype(np.int64)
    elif kind == "i64_wide":
        vals = rng.integers(-(2**62), 2**62, n).astype(np.int64)
    elif kind == "i32":
        vals = rng.integers(-50000, 50000, n).astype(np.int32)
    elif kind == "i16":
        vals = rng.integers(-30000, 30000, n).astype(np.int16)
    elif kind == "f64":
        vals = rng.random(n) * rng.choice([1.0, 1e18])
    elif kind == "f32":
        vals = (rng.random(n) * 100).astype(np.float32)
    elif kind == "bool":
        vals = rng.random(n) < 0.5
    elif kind == "str":
        vals = np.array(["s" * int(k) + str(k) for k in
                         rng.integers(0, 23, n)], dtype=object)
    elif kind == "ts":
        vals = rng.integers(1_500_000_000_000_000,
                            1_700_000_000_000_000, n).astype("M8[us]")
    else:
        raise AssertionError(kind)
    null_frac = float(rng.choice([0.0, 0.0, 0.1, 0.9]))
    mask = rng.random(n) < null_frac
    arr = pa.array(vals.tolist() if kind == "str" else vals,
                   mask=mask if null_frac else None)
    return arr


KINDS = ["i64_small", "i64_offset", "i64_wide", "i32", "i16", "f64",
         "f32", "bool", "str", "ts"]


@pytest.mark.parametrize("seed", range(8))
def test_fetch_round_trip_fuzz(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 3000))
    ncols = int(rng.integers(1, 6))
    kinds = [str(rng.choice(KINDS)) for _ in range(ncols)]
    cols = {f"c{i}_{k}": _rand_column(rng, n, k)
            for i, k in enumerate(kinds)}
    tbl = pa.table(cols)
    rb = tbl.combine_chunks().to_batches()[0]
    dev = batch_to_device(rb, xp=jnp)
    fetched = fetch_batch(dev)
    back = batch_to_arrow(fetched)
    want = batch_to_arrow(batch_to_device(rb, xp=np))
    assert back.num_rows == rb.num_rows
    for name in tbl.column_names:
        got = back.column(name).to_pylist()
        exp = want.column(name).to_pylist()
        assert got == exp, (name, kinds, n, got[:5], exp[:5])


def test_fetch_nested_round_trip():
    rng = np.random.default_rng(99)
    n = 500
    tbl = pa.table({
        "arr": pa.array([None if i % 7 == 0 else
                         list(range(i % 5)) for i in range(n)],
                        type=pa.list_(pa.int64())),
        "m": pa.array([None if i % 11 == 0 else
                       [(f"k{j}", i * j) for j in range(i % 3)]
                       for i in range(n)],
                      type=pa.map_(pa.string(), pa.int64())),
        "st": pa.array([{"a": int(i), "b": None if i % 3 else float(i)}
                        for i in range(n)],
                       type=pa.struct([("a", pa.int64()),
                                       ("b", pa.float64())])),
        "v": pa.array(rng.integers(0, 9, n).astype(np.int64)),
    })
    rb = tbl.combine_chunks().to_batches()[0]
    dev = batch_to_device(rb, xp=jnp)
    back = batch_to_arrow(fetch_batch(dev))
    want = batch_to_arrow(batch_to_device(rb, xp=np))
    for name in tbl.column_names:
        assert back.column(name).to_pylist() == \
            want.column(name).to_pylist(), name


def test_group_reduce_scale_and_skew_differential():
    """Carry-sort group-by at 100k rows with skew, nulls, strings,
    decimals, and every reduction family — differential vs the CPU
    engine (the scale/skew case the small generator tests miss)."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession

    rng = np.random.default_rng(1234)
    n = 100_000
    hot = rng.random(n) < 0.35
    k = np.where(hot, 7, rng.integers(0, 500, n)).astype(np.int64)
    kmask = rng.random(n) < 0.02
    v = rng.integers(-(10**12), 10**12, n).astype(np.int64)
    vmask = rng.random(n) < 0.1
    f = rng.random(n) * rng.choice([1.0, 1e12], n)
    s_ = np.array([f"name_{int(x):03d}" for x in rng.integers(0, 97, n)],
                  dtype=object)
    tbl = pa.table({
        "k": pa.array(k, mask=kmask),
        "v": pa.array(v, mask=vmask),
        "f": pa.array(f),
        "s": pa.array(s_.tolist()),
        "d": pa.array((v % 10**10).tolist(),
                      type=pa.decimal128(12, 2)).cast(pa.decimal128(12, 2)),
    })

    def q(enabled):
        sess = (TpuSession.builder()
                .config("spark.rapids.sql.enabled", enabled)
                .get_or_create())
        df = sess.create_dataframe(tbl)
        return (df.group_by(col("k"))
                .agg(F.sum(col("v")).alias("sv"),
                     F.avg(col("f")).alias("af"),
                     F.min(col("v")).alias("mv"),
                     F.max(col("f")).alias("xf"),
                     F.min(col("s")).alias("ms"),
                     F.sum(col("d")).alias("sd"),
                     F.count(col("v")).alias("cv"),
                     F.count("*").alias("c"))
                .collect().sort_by("k"))

    tpu, cpu = q(True), q(False)
    assert tpu.num_rows == cpu.num_rows
    for name in tpu.column_names:
        a, b = tpu.column(name).to_pylist(), cpu.column(name).to_pylist()
        for x, y in zip(a, b):
            if isinstance(x, float) and isinstance(y, float):
                assert x == y or abs(x - y) <= 1e-9 * max(1.0, abs(x),
                                                          abs(y)), name
            else:
                assert x == y, (name, x, y)
