"""End-to-end shim dialect tests: the SAME query through the public API
produces DIFFERENT results per spark.rapids.tpu.sparkVersion, proving
the providers are actually selected and consulted (ref ShimLoader +
per-version SparkBaseShims deltas; round-2 verdict weak #5)."""

import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as t
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _session(version: str, enabled=True):
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", enabled)
            .config("spark.rapids.tpu.sparkVersion", version)
            .get_or_create())


def _stddev_single_rows(version: str, enabled: bool):
    s = _session(version, enabled)
    tb = pa.table({"k": pa.array([1, 2], type=pa.int64()),
                   "v": pa.array([10.0, 20.0])})
    out = (s.create_dataframe(tb).group_by(col("k"))
           .agg(F.stddev(col("v")).alias("sd")).collect().sort_by("k"))
    return out.column("sd").to_pylist()


def test_legacy_statistical_aggregate_dialect():
    """3.0: stddev of a single-row group is NaN; 3.1+: null — on BOTH
    engines (the CPU oracle consults the shim too)."""
    for enabled in (True, False):
        legacy = _stddev_single_rows("3.0.1", enabled)
        modern = _stddev_single_rows("3.2.0", enabled)
        assert all(v is not None and math.isnan(v) for v in legacy), \
            (enabled, legacy)
        assert modern == [None, None], (enabled, modern)


def _cast_unpadded_date(version: str):
    s = _session(version)
    tb = pa.table({"s": pa.array(["2024-3-5", "2024-03-05", "oops"])})
    out = (s.create_dataframe(tb)
           .select(col("s").cast(t.DATE).alias("d")).collect())
    return out.column("d").to_pylist()


def test_lenient_string_to_date_dialect():
    """3.0 parses unpadded yyyy-M-d; 3.1+ requires full ISO padding."""
    import datetime
    legacy = _cast_unpadded_date("3.0.1")
    modern = _cast_unpadded_date("3.2.0")
    d = datetime.date(2024, 3, 5)
    assert legacy == [d, d, None], legacy
    assert modern == [None, d, None], modern


def test_aqe_read_name_dialect():
    """The AQE shuffle-read exec advertises the version's class name
    (CustomShuffleReader in 3.0/3.1 vs AQEShuffleRead in 3.2)."""
    def name_for(version):
        s = _session(version)
        rng = np.random.default_rng(0)
        tb = pa.table({"k": pa.array(rng.integers(0, 4, 400)
                                     .astype(np.int64)),
                       "v": pa.array(rng.random(400))})
        (s.create_dataframe(tb, num_partitions=4)
         .group_by(col("k")).agg(F.sum(col("v")).alias("s")).collect())
        descs = []
        s.last_plan.foreach(lambda e: descs.append(e.describe()))
        return [d for d in descs if "ShuffleRead" in d]

    n32 = name_for("3.2.0")
    n31 = name_for("3.1.1")
    assert n32 and all(d.startswith("AQEShuffleRead") for d in n32), n32
    assert n31 and all(d.startswith("CustomShuffleReader")
                       for d in n31), n31


def test_cached_batch_serializer_dialect():
    """df.cache() materializes through the parquet cached-batch
    serializer on 3.1.1+ but is a no-op recompute on 3.0
    (ref tests-spark310+ gating)."""
    tb = pa.table({"v": pa.array([1, 2, 3], type=pa.int64())})
    s_old = _session("3.0.1")
    df_old = s_old.create_dataframe(tb)
    df_old.cache()
    assert not df_old.is_cached
    s_new = _session("3.2.0")
    df_new = s_new.create_dataframe(tb)
    df_new.cache()
    assert df_new.is_cached
    df_new.unpersist()


def test_unknown_version_fails_loudly():
    with pytest.raises(ValueError, match="no shim provider"):
        _session("9.9.9").create_dataframe(
            pa.table({"v": pa.array([1])})).collect()
