"""UDF layer tests: bytecode compiler, opaque Python/pandas UDFs through
ArrowEvalPythonExec, native columnar UDFs.

Mirrors the reference's udf-compiler OpcodeSuite (bytecode translation
cases) and integration_tests udf_test.py (pandas UDF round trips).
"""

import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as t
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.expr.core import AttributeReference as A
from spark_rapids_tpu.udf.compiler import (UdfCompileError, compile_udf,
                                           try_compile_udf)

from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect as assert_tpu_and_cpu_are_equal)


# ---------------------------------------------------------------------------
# bytecode compiler unit tests (ref OpcodeSuite)
# ---------------------------------------------------------------------------

def _args(*dtypes):
    return [A(f"c{i}", dt) for i, dt in enumerate(dtypes)]


def _run_compiled(fn, dtypes, rows):
    """Compile fn, evaluate the expression on a batch, compare to Python."""
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.expr.core import (ColumnValue, EvalContext,
                                            bind_expression)
    args = _args(*dtypes)
    expr = compile_udf(fn, args)
    names = [a.name for a in args]
    table = pa.table({n: pa.array(col_vals)
                      for n, col_vals in zip(names, zip(*rows))})
    from spark_rapids_tpu.columnar.interop import from_arrow_type
    dts = [from_arrow_type(f.type) for f in table.schema]
    bound = bind_expression(expr, names, dts)
    rb = table.combine_chunks().to_batches()[0]
    batch = batch_to_device(rb, xp=np)
    ctx = EvalContext(np, batch)
    v = bound.eval(ctx)
    assert isinstance(v, ColumnValue)
    from spark_rapids_tpu.columnar.device import column_to_arrow
    got = column_to_arrow(v.col, len(rows)).to_pylist()
    want = [fn(*r) for r in rows]
    for g, w in zip(got, want):
        if isinstance(w, float):
            assert g == pytest.approx(w, rel=1e-12)
        else:
            assert g == w
    return expr


def test_compile_arithmetic():
    _run_compiled(lambda x: x + 1, [t.LONG], [(1,), (-5,), (100,)])
    _run_compiled(lambda x, y: (x - y) * 2, [t.LONG, t.LONG],
                  [(3, 1), (10, 20)])
    _run_compiled(lambda x: x / 4, [t.LONG], [(8,), (10,)])
    _run_compiled(lambda x: x % 3, [t.LONG], [(7,), (9,)])
    _run_compiled(lambda x: x ** 2, [t.LONG], [(3,), (5,)])


def test_compile_conditional():
    _run_compiled(lambda x: x if x > 0 else -x, [t.LONG],
                  [(5,), (-7,), (0,)])

    def grade(v):
        if v >= 90:
            return "A"
        if v >= 80:
            return "B"
        return "C"
    _run_compiled(grade, [t.LONG], [(95,), (85,), (40,)])


def test_compile_boolean_ops():
    _run_compiled(lambda x: x > 3 and x < 10, [t.LONG],
                  [(5,), (2,), (15,)])
    _run_compiled(lambda x: x < 0 or x > 100, [t.LONG],
                  [(-1,), (50,), (200,)])
    _run_compiled(lambda x: not (x == 3), [t.LONG], [(3,), (4,)])


def test_compile_math_calls():
    _run_compiled(lambda x: math.sqrt(x) + math.log(x), [t.DOUBLE],
                  [(1.0,), (4.0,), (10.0,)])
    _run_compiled(lambda x: abs(x) + max(x, 3), [t.LONG],
                  [(-5,), (7,)])
    _run_compiled(lambda x: math.floor(x) + math.ceil(x), [t.DOUBLE],
                  [(1.5,), (-2.5,)])


def test_compile_string_methods():
    _run_compiled(lambda s: s.upper(), [t.STRING], [("abc",), ("X",)])
    _run_compiled(lambda s: s.strip() + "!", [t.STRING],
                  [("  hi  ",), ("a",)])
    _run_compiled(lambda s: s.startswith("ab"), [t.STRING],
                  [("abc",), ("xyz",)])
    _run_compiled(lambda s: len(s), [t.STRING], [("abc",), ("",)])
    _run_compiled(lambda s: s.replace("a", "b"), [t.STRING],
                  [("banana",), ("ccc",)])


def test_compile_closure_constant():
    k = 10

    def f(x):
        return x + k
    _run_compiled(f, [t.LONG], [(1,), (2,)])


def test_compile_rejects_loops():
    def f(x):
        s = 0
        for i in range(3):
            s = s + x
        return s
    with pytest.raises(UdfCompileError):
        compile_udf(f, _args(t.LONG))
    assert try_compile_udf(f, _args(t.LONG)) is None


def test_compile_rejects_unknown_calls():
    import os

    def f(x):
        return os.getpid() + x
    with pytest.raises(UdfCompileError):
        compile_udf(f, _args(t.LONG))


# ---------------------------------------------------------------------------
# end-to-end through the engine (ref integration_tests/udf_test.py)
# ---------------------------------------------------------------------------

def _table():
    rng = np.random.default_rng(7)
    n = 500
    return pa.table({
        "a": pa.array(rng.integers(-100, 100, n), type=pa.int64()),
        "b": pa.array(rng.random(n)),
        "s": pa.array([f"w{i % 17} x{i % 5}" for i in range(n)]),
    })


def test_scalar_udf_fallback_collect():
    plus_one = F.udf(lambda x: x + 1, returnType=t.LONG)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(_table())
                   .select(plus_one(col("a")).alias("r")))


def test_scalar_udf_in_filter():
    is_pos = F.udf(lambda x: x > 0, returnType=t.BOOLEAN)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(_table())
                   .filter(is_pos(col("a")))
                   .select(col("a")))


def test_pandas_udf():
    doubled = F.pandas_udf(lambda x: x * 2.0, returnType=t.DOUBLE)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(_table())
                   .select(doubled(col("b")).alias("r")))


def test_udf_compiler_fuses_on_tpu():
    """With the compiler on, a compilable UDF must become IR (TPU plan),
    not an ArrowEvalPythonExec (ref assert_gpu_fallback_collect inverse)."""
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder() \
        .config("spark.rapids.sql.udfCompiler.enabled", True).get_or_create()
    f = F.udf(lambda x: x * 3 + 1, returnType=t.LONG)
    df = s.create_dataframe(_table()).select(f(col("a")).alias("r"))
    plan_str = df.explain()
    assert "ArrowEvalPython" not in plan_str
    got = df.collect()
    want = [int(x) * 3 + 1 for x in _table()["a"].to_pylist()]
    assert got["r"].to_pylist() == want


def test_udf_compiled_matches_uncompiled():
    fn = lambda x: x * 2 if x > 0 else -x  # noqa: E731
    from spark_rapids_tpu.api.session import TpuSession
    out = []
    for enabled in (True, False):
        s = TpuSession.builder() \
            .config("spark.rapids.sql.udfCompiler.enabled", enabled) \
            .get_or_create()
        f = F.udf(fn, returnType=t.LONG)
        df = s.create_dataframe(_table()).select(f(col("a")).alias("r"))
        out.append(df.collect())
    assert out[0].equals(out[1])


# ---------------------------------------------------------------------------
# native columnar UDFs (ref udf-examples)
# ---------------------------------------------------------------------------

def test_native_udf_word_count():
    from spark_rapids_tpu.udf.examples import StringWordCount
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(_table())
                   .select(F.native_udf(StringWordCount(), col("s"))
                           .alias("wc")))


def test_native_udf_word_count_values():
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.udf.examples import StringWordCount
    s = TpuSession.builder().get_or_create()
    tbl = pa.table({"s": pa.array(["one two three", "", "  padded  ",
                                   None, "single"])})
    df = s.create_dataframe(tbl).select(
        F.native_udf(StringWordCount(), col("s")).alias("wc"))
    assert df.collect()["wc"].to_pylist() == [3, 0, 1, None, 1]


def test_native_udf_cosine_similarity():
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.udf.examples import CosineSimilarity
    s = TpuSession.builder().get_or_create()
    tbl = pa.table({"x": pa.array([1.0, 0.5, -2.0]),
                    "y": pa.array([2.0, 0.5, 4.0])})
    df = s.create_dataframe(tbl).select(
        F.native_udf(CosineSimilarity(), col("x"), col("y")).alias("sim"))
    got = df.collect()["sim"].to_pylist()
    # 1-wide vectors: sim is sign(x*y)
    assert got == pytest.approx([1.0, 1.0, -1.0])


# ---------------------------------------------------------------------------
# review regressions
# ---------------------------------------------------------------------------

def test_udf_return_type_stable_across_compiler_flag():
    """Declared returnType must hold whether or not the compiler fires."""
    from spark_rapids_tpu.api.session import TpuSession
    schemas = []
    for enabled in (True, False):
        s = TpuSession.builder() \
            .config("spark.rapids.sql.udfCompiler.enabled", enabled) \
            .get_or_create()
        f = F.udf(lambda x: x + 1, returnType=t.INT)
        out = s.create_dataframe(_table()).select(
            f(col("a")).alias("r")).collect()
        schemas.append(out.schema.field("r").type)
    assert schemas[0] == schemas[1] == pa.int32()


def test_udf_string_literal_arg():
    join = F.udf(lambda a, sep: sep + a, returnType=t.STRING)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(_table())
                   .select(join(col("s"), F.lit("-")).alias("r")))


def test_udf_decorator_with_positional_return_type():
    @F.udf(t.LONG)
    def plus2(x):
        return x + 2
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(_table())
                   .select(plus2(col("a")).alias("r")))


def test_identical_lambdas_share_jit_cache_entry():
    """A re-created but bytecode-identical UDF must HIT the process jit
    cache — a fresh trace costs minutes on a remote-compile TPU
    (round-2 verdict weak #7)."""
    import pyarrow as pa

    from spark_rapids_tpu import types as t
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec.base import jit_cache_size

    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    tb = pa.table({"v": pa.array([1, 2, 3], type=pa.int64())})
    df = s.create_dataframe(tb)

    def make_query():
        # a FRESH lambda object each call, same bytecode
        u = F.udf(lambda x: x * 2 + 1, t.LONG)
        return df.select(u(col("v")).alias("y"))

    out1 = make_query().collect()
    n_after_first = jit_cache_size()
    out2 = make_query().collect()
    assert jit_cache_size() == n_after_first   # no re-trace
    assert out1.column("y").to_pylist() == out2.column("y").to_pylist() \
        == [3, 5, 7]

    # different bytecode still misses (correctness over reuse)
    u3 = F.udf(lambda x: x * 3, t.LONG)
    out3 = df.select(u3(col("v")).alias("y")).collect()
    assert out3.column("y").to_pylist() == [3, 6, 9]

    # different CLOSURE VALUES miss too
    def make_closure(k):
        u = F.udf(lambda x: x + k, t.LONG)
        return df.select(u(col("v")).alias("y")).collect()

    assert make_closure(10).column("y").to_pylist() == [11, 12, 13]
    assert make_closure(20).column("y").to_pylist() == [21, 22, 23]


_GLOBAL_K = 10


def test_udf_global_value_change_misses_cache():
    """A UDF reading a module global must NOT hit a kernel traced under
    a different global value (code-review round-3 finding: wrong hits
    are never acceptable)."""
    import pyarrow as pa

    from spark_rapids_tpu import types as t
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession

    global _GLOBAL_K
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    tb = pa.table({"v": pa.array([1, 2], type=pa.int64())})
    df = s.create_dataframe(tb)

    def make():
        u = F.udf(lambda x: x + _GLOBAL_K, t.LONG)
        return df.select(u(col("v")).alias("y")).collect()

    _GLOBAL_K = 10
    assert make().column("y").to_pylist() == [11, 12]
    _GLOBAL_K = 20
    assert make().column("y").to_pylist() == [21, 22]
    _GLOBAL_K = 10
