"""Host-assisted sort collect (spark.rapids.sql.collect.hostAssisted).

A global sort of host-resident data is a permutation: the engine fetches
only the device-computed row index (range-narrowed) and `take`s the host
copy.  Results must be bit-identical to the direct device fetch."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession

N = 70_000  # above the 64Ki host-assist threshold


@pytest.fixture(scope="module")
def fact():
    rng = np.random.default_rng(9)
    return pa.table({
        # narrow key range -> many duplicates -> stability is observable
        "k": pa.array(rng.integers(0, 50, N).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, N).astype(np.int64)),
        "f": pa.array(rng.random(N)),
    })


def _session(assisted: bool):
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", True)
            .config("spark.rapids.sql.collect.hostAssisted", assisted)
            .get_or_create())


def test_sorted_collect_matches_direct(fact):
    for parts in (1, 4):
        got = (_session(True).create_dataframe(fact, num_partitions=parts)
               .sort(col("k"), col("v")).collect())
        want = (_session(False).create_dataframe(fact,
                                                 num_partitions=parts)
                .sort(col("k"), col("v")).collect())
        assert got.equals(want), f"mismatch at num_partitions={parts}"


def test_sorted_collect_with_filter_and_pruning(fact):
    def q(s):
        return (s.create_dataframe(fact, num_partitions=2)
                .filter(col("v") > 0).select(col("k"), col("v"))
                .sort(col("k"), col("v").desc()).collect())
    assert q(_session(True)).equals(q(_session(False)))


def test_descending_and_stability(fact):
    # equal keys keep input order (stable sort) on both paths
    def q(s):
        return (s.create_dataframe(fact)
                .sort(col("k").desc()).collect())
    assert q(_session(True)).equals(q(_session(False)))


def test_small_results_use_direct_path():
    from spark_rapids_tpu.plan.host_assist import try_host_assisted_collect
    small = pa.table({"k": pa.array(np.arange(100, dtype=np.int64))})
    s = _session(True)
    df = s.create_dataframe(small).sort(col("k"))
    assert try_host_assisted_collect(s, df._lp) is None
