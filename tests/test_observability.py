"""Docs generation, metrics levels, trace annotations, api_validation
(ref SupportedOpsDocs, GpuMetric levels, NvtxWithMetrics,
api_validation/)."""

import os

import pyarrow as pa

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession, last_query_metrics
from spark_rapids_tpu.docsgen import generate_supported_ops, write_docs
from spark_rapids_tpu.tools.api_validation import validate


def test_api_validation_clean():
    assert validate() == []


def test_generate_configs_docs_contains_keys():
    text = cfg.generate_docs()
    assert "spark.rapids.sql.enabled" in text
    assert "spark.rapids.shuffle.compression.codec" in text
    assert "spark.sql.adaptive.enabled" in text


def test_generate_supported_ops_matrix():
    text = generate_supported_ops()
    assert "| TpuHashAggregateExec |" in text or \
        "| CpuHashAggregateExec |" in text
    assert "## Expressions" in text
    # regex exprs are registered with an explicit host-fallback reason
    # (round 3): they appear in the matrix instead of being silently
    # absent
    assert "RLike" in text
    # decimal128 min/max supported, average not over decimals
    assert "| Min | S | S" in text


def test_write_docs(tmp_path):
    paths = write_docs(str(tmp_path))
    assert all(os.path.exists(p) for p in paths)


def test_metrics_levels_and_report():
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    df = s.create_dataframe(pa.table({"x": pa.array(range(100))}))
    df.group_by(col("x")).agg(F.count("*").alias("c")).collect()
    essential = last_query_metrics(s, "ESSENTIAL")
    moderate = last_query_metrics(s, "MODERATE")
    assert essential and moderate
    assert len(moderate) > len(essential)
    assert all(m == "numOutputRows" for _, m, _ in essential)
    rows_out = [v for op, m, v in essential
                if op == "DeviceToHostExec" and m == "numOutputRows"]
    assert rows_out and rows_out[0] == 100


def test_trace_annotations_run():
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.sql.profile.traceAnnotations", True)
         .get_or_create())
    try:
        df = s.create_dataframe(pa.table({"x": pa.array(range(10))}))
        out = df.filter(col("x") > 3).collect()
        assert out.num_rows == 6
    finally:
        from spark_rapids_tpu.exec.base import set_trace_annotations
        set_trace_annotations(False)


# ---------------------------------------------------------------------------
# flight recorder (obs/): span tree, exporters, CLI subcommands
# ---------------------------------------------------------------------------

def _traced_session(**extra):
    b = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.trace.enabled", True))
    for k, v in extra.items():
        b = b.config(k, v)
    return b.get_or_create()


def test_last_query_trace_span_tree():
    s = _traced_session()
    df = s.create_dataframe(pa.table({"x": pa.array(range(64))}))
    out = df.filter(col("x") > 9).collect()
    assert out.num_rows == 54
    tr = s.last_query_trace()
    assert tr is not None and tr.sealed and tr.open_span_count() == 0
    names = [sp.name for sp in tr.spans]
    # session phases + per-operator execute spans
    assert "phase:plan" in names and "phase:execute" in names
    ops = [sp for sp in tr.spans if sp.kind == "operator"]
    assert any(sp.attrs.get("op") == "DeviceToHostExec" for sp in ops)
    # the root-operator span resolved its output rows (deferred scalars
    # drained at finalize, never on the hot path)
    root_ops = [sp for sp in ops
                if sp.attrs.get("op") == "DeviceToHostExec"]
    assert sum(sp.rows for sp in root_ops) == 54
    # operator spans nest under the execute phase
    by_id = {sp.span_id: sp for sp in tr.spans}
    for sp in ops:
        anc = sp
        while anc.parent_id is not None:
            anc = by_id[anc.parent_id]
        assert anc.kind == "query"


def test_chrome_export_schema_and_text_timeline():
    s = _traced_session()
    df = s.create_dataframe(pa.table({"x": pa.array(range(32))}))
    df.filter(col("x") > 0).collect()
    tr = s.last_query_trace()
    ch = tr.to_chrome()
    assert set(ch) == {"traceEvents", "displayTimeUnit"}
    evs = ch["traceEvents"]
    assert evs and all({"name", "ph", "pid", "tid"} <= set(e)
                       for e in evs)
    complete = [e for e in evs if e["ph"] == "X"]
    assert complete and all("ts" in e and "dur" in e and e["dur"] > 0
                            for e in complete)
    assert any(e["name"] == "DeviceToHostExec.execute"
               for e in complete)
    txt = tr.to_text()
    assert "phase:execute" in txt and "DeviceToHostExec" in txt


def test_tools_cli_trace_and_accuracy(tmp_path, capsys):
    import json

    from spark_rapids_tpu.tools.__main__ import main as tools_main
    s = _traced_session(**{"spark.rapids.tpu.eventLog.dir":
                           str(tmp_path / "logs")})
    df = s.create_dataframe(pa.table(
        {"k": pa.array([i % 3 for i in range(90)]),
         "v": pa.array(range(90))}))
    df.group_by(col("k")).agg(F.sum(col("v")).alias("sv")).collect()
    log_dir = tmp_path / "logs"
    log = str(next(log_dir.glob("events_*")))

    # profiling --accuracy prints the predicted-vs-actual table
    rc = tools_main(["profiling", log, "-o", str(tmp_path / "out"),
                     "--accuracy"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Predicted vs Actual" in out and "actRows" in out

    # trace --export chrome writes Perfetto-loadable JSON
    chrome_path = tmp_path / "q.trace.json"
    rc = tools_main(["trace", log, "--export", "chrome", "-o",
                     str(chrome_path)])
    assert rc == 0
    ch = json.loads(chrome_path.read_text())
    assert ch["traceEvents"] and any(
        e.get("ph") == "X" for e in ch["traceEvents"])

    # trace --export text prints the timeline
    rc = tools_main(["trace", log, "--export", "text"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "phase:execute" in out

    # a foreign log (no span records) is a clean error, not a crash
    foreign = tmp_path / "foreign_log"
    foreign.write_text('{"Event": "SparkListenerLogStart", '
                       '"Spark Version": "3.1.1"}\n')
    assert tools_main(["trace", str(foreign)]) == 2


def test_generated_docs_cover_observability():
    text = cfg.generate_docs()
    assert "spark.rapids.tpu.eventLog.dir" in text
    assert "spark.rapids.tpu.trace.enabled" in text
    from spark_rapids_tpu.docsgen import generate_lint_rules
    assert "TPU-R006" in generate_lint_rules()
