"""Docs generation, metrics levels, trace annotations, api_validation
(ref SupportedOpsDocs, GpuMetric levels, NvtxWithMetrics,
api_validation/)."""

import os

import pyarrow as pa

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession, last_query_metrics
from spark_rapids_tpu.docsgen import generate_supported_ops, write_docs
from spark_rapids_tpu.tools.api_validation import validate


def test_api_validation_clean():
    assert validate() == []


def test_generate_configs_docs_contains_keys():
    text = cfg.generate_docs()
    assert "spark.rapids.sql.enabled" in text
    assert "spark.rapids.shuffle.compression.codec" in text
    assert "spark.sql.adaptive.enabled" in text


def test_generate_supported_ops_matrix():
    text = generate_supported_ops()
    assert "| TpuHashAggregateExec |" in text or \
        "| CpuHashAggregateExec |" in text
    assert "## Expressions" in text
    # regex exprs are registered with an explicit host-fallback reason
    # (round 3): they appear in the matrix instead of being silently
    # absent
    assert "RLike" in text
    # decimal128 min/max supported, average not over decimals
    assert "| Min | S | S" in text


def test_write_docs(tmp_path):
    paths = write_docs(str(tmp_path))
    assert all(os.path.exists(p) for p in paths)


def test_metrics_levels_and_report():
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    df = s.create_dataframe(pa.table({"x": pa.array(range(100))}))
    df.group_by(col("x")).agg(F.count("*").alias("c")).collect()
    essential = last_query_metrics(s, "ESSENTIAL")
    moderate = last_query_metrics(s, "MODERATE")
    assert essential and moderate
    assert len(moderate) > len(essential)
    assert all(m == "numOutputRows" for _, m, _ in essential)
    rows_out = [v for op, m, v in essential
                if op == "DeviceToHostExec" and m == "numOutputRows"]
    assert rows_out and rows_out[0] == 100


def test_trace_annotations_run():
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.sql.profile.traceAnnotations", True)
         .get_or_create())
    try:
        df = s.create_dataframe(pa.table({"x": pa.array(range(10))}))
        out = df.filter(col("x") > 3).collect()
        assert out.num_rows == 6
    finally:
        from spark_rapids_tpu.exec.base import set_trace_annotations
        set_trace_annotations(False)
