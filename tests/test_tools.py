"""Qualification & profiling tool tests over synthetic Spark event logs
(model: the reference's QualificationSuite/ApplicationInfoSuite with
golden CSV expectations)."""

import csv
import gzip
import json
import os

import pytest

from spark_rapids_tpu.tools.eventlog import parse_event_log
from spark_rapids_tpu.tools.profiling import (app_information, compare_apps,
                                              generate_dot,
                                              generate_timeline,
                                              health_check, profile,
                                              stage_aggregates)
from spark_rapids_tpu.tools.qualification import qualify


def _mk_log(path, app_id="app-001", app_name="TestApp", with_udf=False,
            fail_stage=False, fmt="parquet", gz=False):
    plan = {
        "nodeName": "WholeStageCodegen",
        "simpleString": "WholeStageCodegen",
        "children": [
            {"nodeName": "HashAggregate",
             "simpleString": "HashAggregate(keys=[k], functions=[sum(v)])",
             "children": [
                 {"nodeName": "Project",
                  "simpleString": ("Project [myudf(v) AS u]" if with_udf
                                   else "Project [v]"),
                  "children": [
                      {"nodeName": f"Scan {fmt}",
                       "simpleString": f"FileScan {fmt} [k,v]",
                       "children": [], "metrics": []}],
                  "metrics": []}],
             "metrics": []}],
        "metrics": [],
    }
    events = [
        {"Event": "SparkListenerLogStart", "Spark Version": "3.1.1"},
        {"Event": "SparkListenerApplicationStart", "App Name": app_name,
         "App ID": app_id, "Timestamp": 1000},
        {"Event": "SparkListenerExecutorAdded", "Executor ID": "1",
         "Timestamp": 1100,
         "Executor Info": {"Host": "h1", "Total Cores": 8}},
        {"Event":
         "org.apache.spark.sql.execution.ui."
         "SparkListenerSQLExecutionStart",
         "executionId": 0, "description": "select sum(v) group by k",
         "time": 1500, "sparkPlanInfo": plan},
        {"Event": "SparkListenerJobStart", "Job ID": 0,
         "Submission Time": 1600,
         "Stage Infos": [{"Stage ID": 0, "Stage Attempt ID": 0,
                          "Stage Name": "stage0", "Number of Tasks": 2}],
         "Properties": {"spark.sql.execution.id": "0"}},
        {"Event": "SparkListenerStageSubmitted",
         "Stage Info": {"Stage ID": 0, "Stage Attempt ID": 0,
                        "Stage Name": "stage0", "Number of Tasks": 2,
                        "Submission Time": 1700}},
    ]
    for tid in (0, 1):
        events.append({
            "Event": "SparkListenerTaskEnd", "Stage ID": 0,
            "Task Info": {"Task ID": tid, "Attempt": 0, "Launch Time": 1800,
                          "Finish Time": 2800, "Failed": False,
                          "Executor ID": "1"},
            "Task Metrics": {"Executor Run Time": 900,
                             "Executor CPU Time": 600_000_000,
                             "JVM GC Time": 10,
                             "Input Metrics": {"Bytes Read": 1 << 20},
                             "Memory Bytes Spilled": 0,
                             "Disk Bytes Spilled": 0}})
    stage_done = {"Event": "SparkListenerStageCompleted",
                  "Stage Info": {"Stage ID": 0, "Stage Attempt ID": 0,
                                 "Stage Name": "stage0",
                                 "Number of Tasks": 2,
                                 "Submission Time": 1700,
                                 "Completion Time": 2900}}
    if fail_stage:
        stage_done["Stage Info"]["Failure Reason"] = "boom"
    events += [
        stage_done,
        {"Event": "SparkListenerJobEnd", "Job ID": 0,
         "Completion Time": 3000,
         "Job Result": {"Result": "JobSucceeded"}},
        {"Event":
         "org.apache.spark.sql.execution.ui.SparkListenerSQLExecutionEnd",
         "executionId": 0, "time": 3100},
        {"Event": "SparkListenerApplicationEnd", "Timestamp": 4000},
    ]
    opener = gzip.open if gz else open
    with opener(path, "wt") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return path


def test_parse_event_log(tmp_path):
    log = _mk_log(str(tmp_path / "app1"))
    app = parse_event_log(log)
    assert app.app_id == "app-001"
    assert app.app_duration == 3000
    assert len(app.tasks) == 2
    assert app.sql_executions[0].duration == 1600
    assert app.sql_task_duration(0) == 1800
    assert app.executors["1"]["cores"] == 8


def test_parse_gzip_log(tmp_path):
    log = _mk_log(str(tmp_path / "app1.gz"), gz=True)
    app = parse_event_log(log)
    assert app.app_id == "app-001"


def test_qualification_scores_and_csv(tmp_path):
    good = _mk_log(str(tmp_path / "good"), app_id="app-good")
    udf = _mk_log(str(tmp_path / "udf"), app_id="app-udf", with_udf=True)
    json_scan = _mk_log(str(tmp_path / "jsonscan"), app_id="app-json",
                        fmt="json")
    outdir = str(tmp_path / "out")
    results = qualify([good, udf, json_scan], outdir)
    by_id = {r.app.app_id: r for r in results}
    assert "UDF" in by_id["app-udf"].problems
    assert by_id["app-good"].problems == set()
    assert by_id["app-json"].unsupported_read_formats == {"JSON"}
    # UDF and bad-read apps score below the clean app
    assert by_id["app-good"].score > by_id["app-udf"].score
    assert by_id["app-good"].score > by_id["app-json"].score
    csv_path = os.path.join(outdir,
                            "spark_rapids_tpu_qualification_output.csv")
    with open(csv_path) as f:
        rows = list(csv.reader(f))
    assert rows[0][0] == "App Name"
    assert len(rows) == 4
    # sorted by score: first data row is the clean app
    assert rows[1][1] == "app-good"


def test_profiling_report_and_health(tmp_path):
    ok = _mk_log(str(tmp_path / "ok"), app_id="app-ok")
    bad = _mk_log(str(tmp_path / "bad"), app_id="app-bad", fail_stage=True)
    outdir = str(tmp_path / "prof")
    reports = profile([ok, bad], outdir, compare=True)
    assert len(reports) == 2
    rep_ok = [r for r in reports
              if r["application"]["appId"] == "app-ok"][0]
    rep_bad = [r for r in reports
               if r["application"]["appId"] == "app-bad"][0]
    assert rep_ok["health"]["failedStages"] == []
    assert rep_bad["health"]["failedStages"][0]["reason"] == "boom"
    assert rep_ok["stages"][0]["numTasks"] == 2
    assert rep_ok["sql"][0]["taskDuration"] == 1800
    assert os.path.exists(os.path.join(outdir, "app-ok_profile.txt"))
    assert os.path.exists(os.path.join(outdir, "app-ok_timeline.svg"))
    assert os.path.exists(os.path.join(outdir, "app-ok_sql0.dot"))
    assert os.path.exists(os.path.join(outdir, "compare.txt"))


def test_generate_dot_structure(tmp_path):
    log = _mk_log(str(tmp_path / "app"))
    app = parse_event_log(log)
    out = str(tmp_path / "plan.dot")
    generate_dot(app, 0, out)
    text = open(out).read()
    assert "digraph plan" in text
    assert "HashAggregate" in text and "->" in text


def test_cli_qualification(tmp_path, capsys):
    from spark_rapids_tpu.tools.__main__ import main
    log = _mk_log(str(tmp_path / "app"))
    rc = main(["qualification", log, "-o", str(tmp_path / "o")])
    assert rc == 0
    assert "Qualified 1 application" in capsys.readouterr().out


def test_compare_apps(tmp_path):
    a = parse_event_log(_mk_log(str(tmp_path / "a"), app_id="a1"))
    b = parse_event_log(_mk_log(str(tmp_path / "b"), app_id="b1"))
    rows = compare_apps([a, b])
    assert [r["appId"] for r in rows] == ["a1", "b1"]
    assert all(r["taskDuration"] == 1800 for r in rows)


def _mk_rich_log(path, app_id, plan, app_name="RichApp"):
    """Synthetic log with a caller-supplied SQL plan tree."""
    events = [
        {"Event": "SparkListenerLogStart", "Spark Version": "3.3.0"},
        {"Event": "SparkListenerApplicationStart", "App Name": app_name,
         "App ID": app_id, "Timestamp": 1000},
        {"Event":
         "org.apache.spark.sql.execution.ui."
         "SparkListenerSQLExecutionStart",
         "executionId": 0, "description": "q", "time": 1500,
         "sparkPlanInfo": plan},
        {"Event": "SparkListenerJobStart", "Job ID": 0,
         "Submission Time": 1600,
         "Stage Infos": [{"Stage ID": 0, "Stage Attempt ID": 0,
                          "Stage Name": "s0", "Number of Tasks": 1}],
         "Properties": {"spark.sql.execution.id": "0"}},
        {"Event": "SparkListenerTaskEnd", "Stage ID": 0,
         "Task Info": {"Task ID": 0, "Attempt": 0, "Launch Time": 1800,
                       "Finish Time": 2800, "Failed": False,
                       "Executor ID": "1"},
         "Task Metrics": {"Executor Run Time": 1000,
                          "Executor CPU Time": 900_000_000}},
        {"Event":
         "org.apache.spark.sql.execution.ui.SparkListenerSQLExecutionEnd",
         "executionId": 0, "time": 3100},
        {"Event": "SparkListenerApplicationEnd", "Timestamp": 4000},
    ]
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return path


def _node(name, simple, *children):
    return {"nodeName": name, "simpleString": simple,
            "children": list(children), "metrics": []}


def test_qualification_registry_scoring_golden(tmp_path):
    """Scores come from the LIVE registries (tools/supported_ops.py):
    heavyweight accelerable operators outrank pass-through plans, and an
    unregistered expression inside a supported exec downgrades exactly
    that node (ref PluginTypeChecker + operatorsScore weighting)."""
    scan = _node("Scan parquet", "FileScan parquet [k,v]")
    heavy = _mk_rich_log(
        str(tmp_path / "heavy"), "app-heavy",
        _node("SortMergeJoin", "SortMergeJoin [k], [k2], Inner",
              _node("HashAggregate",
                    "HashAggregate(keys=[k], functions=[sum(v), avg(v)])",
                    scan),
              _node("Sort", "Sort [k2 ASC NULLS FIRST]", scan)))
    passthrough = _mk_rich_log(
        str(tmp_path / "passthrough"), "app-passthrough",
        _node("LocalLimit", "LocalLimit 10",
              _node("Coalesce", "Coalesce 1", scan)))
    bad_expr = _mk_rich_log(
        str(tmp_path / "badexpr"), "app-badexpr",
        _node("SortMergeJoin", "SortMergeJoin [k], [k2], Inner",
              _node("HashAggregate",
                    "HashAggregate(keys=[k], "
                    "functions=[some_exotic_udaf(v)])",
                    scan),
              _node("Sort", "Sort [k2 ASC NULLS FIRST]", scan)))
    outdir = str(tmp_path / "out")
    results = qualify([heavy, passthrough, bad_expr], outdir)
    by_id = {r.app.app_id: r for r in results}
    # identical task time everywhere: ranking is pure op discrimination
    assert by_id["app-heavy"].score > by_id["app-badexpr"].score
    assert by_id["app-heavy"].score > by_id["app-passthrough"].score
    assert "some_exotic_udaf" in by_id["app-badexpr"].unsupported_exprs
    assert by_id["app-heavy"].unsupported_exprs == set()

    golden = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "goldens", "qualification_scores.csv")
    got_rows = [[r.app.app_id, f"{r.score:.2f}"] for r in results]
    if not os.path.exists(golden):  # first run materializes the golden
        with open(golden, "w", newline="") as f:
            csv.writer(f).writerows(got_rows)
    with open(golden) as f:
        want_rows = [row for row in csv.reader(f) if row]
    assert got_rows == want_rows, (got_rows, want_rows)
