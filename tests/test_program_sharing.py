"""Cross-query program sharing under bucket-canonical tracing.

The jit key space is meant to collapse to (exec kind, dtype layout,
capacity bucket): two structurally distinct queries that differ only in
literal constants and land in the same capacity buckets must run the
second query on the FIRST query's programs — zero new compilations.
ParamLiteral (expr/params.py) hoists eligible literals out of the
traced closures into traced arguments, and the semantic jit key
excludes their values, so this is exactly what the seam should deliver.

The anti-vacuity twin proves the test has teeth: changing a column's
DTYPE (not a literal) must fork the key space and compile new
programs — if it didn't, the sharing assertion above would be
vacuously green for the wrong reason (e.g. a disabled observatory).
"""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.exec.base as eb
import spark_rapids_tpu.obs.metrics as obs_metrics
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.obs.compileprof import CompileObservatory


@pytest.fixture
def obs():
    obs_metrics.MetricsRegistry.reset_for_tests()
    o = CompileObservatory.reset_for_tests()
    eb.clear_jit_cache()
    yield o
    eb.clear_jit_cache()
    CompileObservatory.reset_for_tests()
    obs_metrics.MetricsRegistry.reset_for_tests()


def _session() -> TpuSession:
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", True)
            .config("spark.rapids.tpu.singleChipFuse", "off")
            .config("spark.rapids.tpu.sort.compileLean", "off")
            .get_or_create())


def _table(n=2000):
    # v = 0..n-1: the filter survivor counts for `v > 5` (1994) and
    # `v > 9` (1990) land in the SAME capacity bucket (2048), so even
    # the survivor-repack transfer programs are shared — a different
    # bucket would be an honest, wanted recompile, not sharing failure
    return pa.table({
        "k": pa.array((np.arange(n) % 7).astype(np.int64)),
        "v": pa.array(np.arange(n, dtype=np.int64)),
    })


def _query(df, threshold: int, addend: int):
    return (df.filter(col("v") > threshold)
            .select(col("k"), (col("v") + addend).alias("x"))
            .collect())


def test_literal_twins_share_all_programs(obs):
    s = _session()
    df = s.create_dataframe(_table())

    out1 = _query(df, 5, 7)
    snap1 = obs.snapshot()
    assert snap1["builds"] > 0  # the cold query really compiled

    out2 = _query(df, 9, 11)
    snap2 = obs.snapshot()

    assert snap2["builds"] == snap1["builds"], (
        f"literal-only twin compiled "
        f"{snap2['builds'] - snap1['builds']} new program(s): "
        f"{snap2['by_cause']}")
    assert snap2["hits"] > snap1["hits"]

    # sharing must not bend correctness: both results are exact
    v = np.arange(2000, dtype=np.int64)
    np.testing.assert_array_equal(
        np.sort(out1.column("x").to_numpy()), np.sort(v[v > 5] + 7))
    np.testing.assert_array_equal(
        np.sort(out2.column("x").to_numpy()), np.sort(v[v > 9] + 11))


def test_dtype_change_must_compile(obs):
    s = _session()
    df = s.create_dataframe(_table())
    _query(df, 5, 7)
    snap1 = obs.snapshot()

    # same query shape over float64 — a dtype-layout change is a
    # genuinely different program family and MUST compile
    n = 2000
    ftbl = pa.table({
        "k": pa.array((np.arange(n) % 7).astype(np.int64)),
        "v": pa.array(np.arange(n, dtype=np.float64)),
    })
    fdf = s.create_dataframe(ftbl)
    out = (fdf.filter(col("v") > 5.0)
           .select(col("k"), (col("v") + 7.0).alias("x"))
           .collect())
    snap2 = obs.snapshot()

    assert snap2["builds"] > snap1["builds"], (
        "dtype change compiled nothing — the sharing test is vacuous")
    v = np.arange(n, dtype=np.float64)
    np.testing.assert_allclose(
        np.sort(out.column("x").to_numpy()), np.sort(v[v > 5.0] + 7.0))


def test_shared_program_ratio_gauge(obs):
    """tpu_jit_shared_program_ratio drops as calls reuse programs."""
    s = _session()
    df = s.create_dataframe(_table())
    _query(df, 5, 7)
    _query(df, 9, 11)
    ratio = obs_metrics.registry().gauge(
        "tpu_jit_shared_program_ratio").value()
    assert 0.0 < ratio < 1.0
