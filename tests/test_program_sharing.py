"""Cross-query program sharing under bucket-canonical tracing.

The jit key space is meant to collapse to (exec kind, dtype layout,
capacity bucket): two structurally distinct queries that differ only in
literal constants and land in the same capacity buckets must run the
second query on the FIRST query's programs — zero new compilations.
ParamLiteral (expr/params.py) hoists eligible literals out of the
traced closures into traced arguments, and the semantic jit key
excludes their values, so this is exactly what the seam should deliver.

The anti-vacuity twin proves the test has teeth: changing a column's
DTYPE (not a literal) must fork the key space and compile new
programs — if it didn't, the sharing assertion above would be
vacuously green for the wrong reason (e.g. a disabled observatory).
"""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.exec.base as eb
import spark_rapids_tpu.obs.metrics as obs_metrics
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.obs.compileprof import CompileObservatory


@pytest.fixture
def obs():
    obs_metrics.MetricsRegistry.reset_for_tests()
    o = CompileObservatory.reset_for_tests()
    eb.clear_jit_cache()
    yield o
    eb.clear_jit_cache()
    CompileObservatory.reset_for_tests()
    obs_metrics.MetricsRegistry.reset_for_tests()


def _session() -> TpuSession:
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", True)
            .config("spark.rapids.tpu.singleChipFuse", "off")
            .config("spark.rapids.tpu.sort.compileLean", "off")
            .get_or_create())


def _table(n=2000):
    # v = 0..n-1: the filter survivor counts for `v > 5` (1994) and
    # `v > 9` (1990) land in the SAME capacity bucket (2048), so even
    # the survivor-repack transfer programs are shared — a different
    # bucket would be an honest, wanted recompile, not sharing failure
    return pa.table({
        "k": pa.array((np.arange(n) % 7).astype(np.int64)),
        "v": pa.array(np.arange(n, dtype=np.int64)),
    })


def _query(df, threshold: int, addend: int):
    return (df.filter(col("v") > threshold)
            .select(col("k"), (col("v") + addend).alias("x"))
            .collect())


def test_literal_twins_share_all_programs(obs):
    s = _session()
    df = s.create_dataframe(_table())

    out1 = _query(df, 5, 7)
    snap1 = obs.snapshot()
    assert snap1["builds"] > 0  # the cold query really compiled

    out2 = _query(df, 9, 11)
    snap2 = obs.snapshot()

    assert snap2["builds"] == snap1["builds"], (
        f"literal-only twin compiled "
        f"{snap2['builds'] - snap1['builds']} new program(s): "
        f"{snap2['by_cause']}")
    assert snap2["hits"] > snap1["hits"]

    # sharing must not bend correctness: both results are exact
    v = np.arange(2000, dtype=np.int64)
    np.testing.assert_array_equal(
        np.sort(out1.column("x").to_numpy()), np.sort(v[v > 5] + 7))
    np.testing.assert_array_equal(
        np.sort(out2.column("x").to_numpy()), np.sort(v[v > 9] + 11))


def test_dtype_change_must_compile(obs):
    s = _session()
    df = s.create_dataframe(_table())
    _query(df, 5, 7)
    snap1 = obs.snapshot()

    # same query shape over float64 — a dtype-layout change is a
    # genuinely different program family and MUST compile
    n = 2000
    ftbl = pa.table({
        "k": pa.array((np.arange(n) % 7).astype(np.int64)),
        "v": pa.array(np.arange(n, dtype=np.float64)),
    })
    fdf = s.create_dataframe(ftbl)
    out = (fdf.filter(col("v") > 5.0)
           .select(col("k"), (col("v") + 7.0).alias("x"))
           .collect())
    snap2 = obs.snapshot()

    assert snap2["builds"] > snap1["builds"], (
        "dtype change compiled nothing — the sharing test is vacuous")
    v = np.arange(n, dtype=np.float64)
    np.testing.assert_allclose(
        np.sort(out.column("x").to_numpy()), np.sort(v[v > 5.0] + 7.0))


def test_in_list_twins_share_programs(obs):
    """IN-list items hoist like comparison literals: twins that differ
    only in the listed values (same list LENGTH) share every program."""
    s = _session()
    df = s.create_dataframe(_table())
    out1 = df.filter(col("v").isin(3, 700, 1500)).collect()
    snap1 = obs.snapshot()
    assert snap1["builds"] > 0
    out2 = df.filter(col("v").isin(8, 901, 1999)).collect()
    snap2 = obs.snapshot()
    assert snap2["builds"] == snap1["builds"], snap2["by_cause"]
    assert sorted(out1.column("v").to_pylist()) == [3, 700, 1500]
    assert sorted(out2.column("v").to_pylist()) == [8, 901, 1999]


def test_case_arm_twins_share_programs(obs):
    """Numeric CASE value arms hoist: twins differing only in the arm
    constants (and the compared literal) share every program."""
    from spark_rapids_tpu.api.functions import when
    s = _session()
    df = s.create_dataframe(_table())

    def q(cut, a, b):
        return df.select(
            when(col("v") > cut, a).otherwise(b).alias("c")).collect()

    out1 = q(1000, 7, 3)
    snap1 = obs.snapshot()
    assert snap1["builds"] > 0
    out2 = q(500, 90, 40)
    snap2 = obs.snapshot()
    assert snap2["builds"] == snap1["builds"], snap2["by_cause"]
    v = np.arange(2000, dtype=np.int64)
    np.testing.assert_array_equal(out1.column("c").to_numpy(),
                                  np.where(v > 1000, 7, 3))
    np.testing.assert_array_equal(out2.column("c").to_numpy(),
                                  np.where(v > 500, 90, 40))


def _stable():
    n = 512
    vals = ["red", "blu", "grn", "yel"]
    return pa.table({
        "s": pa.array([vals[i % 4] for i in range(n)]),
        "v": pa.array(np.arange(n, dtype=np.int64)),
    })


def test_string_literal_twins_share_programs(obs):
    """Same-BYTE-LENGTH string literal twins share programs: the chars
    ride in as a traced uint8 array, equality hashes on device."""
    s = _session()
    df = s.create_dataframe(_stable())
    out1 = df.filter(col("s") == "red").collect()
    snap1 = obs.snapshot()
    assert snap1["builds"] > 0
    out2 = df.filter(col("s") == "grn").collect()
    snap2 = obs.snapshot()
    assert snap2["builds"] == snap1["builds"], snap2["by_cause"]
    assert set(out1.column("s").to_pylist()) == {"red"}
    assert set(out2.column("s").to_pylist()) == {"grn"}
    assert out1.num_rows == out2.num_rows == 128


def test_string_length_change_must_compile(obs):
    """Anti-vacuity: a DIFFERENT byte length is a different traced
    shape and must fork the key space (honest recompile)."""
    s = _session()
    df = s.create_dataframe(_stable())
    df.filter(col("s") == "red").collect()
    snap1 = obs.snapshot()
    out = df.filter(col("s") == "reddish").collect()
    snap2 = obs.snapshot()
    assert snap2["builds"] > snap1["builds"]
    assert out.num_rows == 0


def test_shared_program_ratio_gauge(obs):
    """tpu_jit_shared_program_ratio drops as calls reuse programs."""
    s = _session()
    df = s.create_dataframe(_table())
    _query(df, 5, 7)
    _query(df, 9, 11)
    ratio = obs_metrics.registry().gauge(
        "tpu_jit_shared_program_ratio").value()
    assert 0.0 < ratio < 1.0
