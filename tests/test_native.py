"""Native layer tests: C++ LZ4 codec, zstd binding, host arena, and
compressed shuffle/spill round trips."""

import ctypes
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.native import get_lib, build_error
from spark_rapids_tpu.native import codec as ncodec
from spark_rapids_tpu.native.arena import HostArena


def test_native_lib_builds():
    lib = get_lib()
    assert lib is not None, f"native build failed: {build_error()}"


@pytest.mark.parametrize("payload", [
    b"",
    b"a",
    b"hello world " * 1000,
    bytes(range(256)) * 64,
    np.random.default_rng(0).integers(0, 255, 100_000,
                                      dtype=np.uint8).tobytes(),
    b"\x00" * 65536,
])
def test_lz4_roundtrip(payload):
    comp = ncodec.lz4_compress(payload)
    assert ncodec.lz4_decompress(comp) == payload


def test_lz4_compresses_repetitive_data():
    data = b"abcdefgh" * 10_000
    comp = ncodec.lz4_compress(data)
    assert len(comp) < len(data) // 10


def test_lz4_interops_with_system_liblz4():
    """Our block output must decode with the canonical liblz4."""
    import ctypes.util
    name = ctypes.util.find_library("lz4") or "liblz4.so.1"
    try:
        syslz4 = ctypes.CDLL(name)
    except OSError:
        pytest.skip("no system liblz4")
    syslz4.LZ4_decompress_safe.restype = ctypes.c_int
    syslz4.LZ4_decompress_safe.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                           ctypes.c_int, ctypes.c_int]
    data = (b"the quick brown fox jumps over the lazy dog. " * 500 +
            os.urandom(1000))
    framed = ncodec.lz4_compress(data)
    n, backend = ncodec._FRAME.unpack_from(framed, 0)
    if backend != ncodec._B_NATIVE_LZ4:
        pytest.skip("native codec unavailable")
    block = framed[ncodec._FRAME.size:]
    out = ctypes.create_string_buffer(n)
    m = syslz4.LZ4_decompress_safe(block, out, len(block), n)
    assert m == n and out.raw[:n] == data


def test_zstd_roundtrip():
    data = b"columnar data! " * 5000
    comp = ncodec.zstd_compress(data)
    assert ncodec.zstd_decompress(comp) == data
    assert len(comp) < len(data)


def test_lz4_rejects_truncated_input():
    comp = ncodec.lz4_compress(b"some compressible data " * 100)
    with pytest.raises(Exception):
        ncodec.lz4_decompress(comp[:-5])


def test_arena_alloc_reset():
    a = HostArena(1 << 20)
    v1 = a.alloc(1000)
    v2 = a.alloc(3000, align=256)
    assert v1 is not None and v2 is not None
    v1[:4] = b"abcd"
    v2[:4] = b"efgh"
    assert bytes(v1[:4]) == b"abcd" and bytes(v2[:4]) == b"efgh"
    assert a.used >= 4000
    assert a.n_allocs == 2
    big = a.alloc(2 << 20)
    assert big is None  # exhausted, no exception
    a.reset()
    assert a.used == 0
    v3 = a.alloc(64)
    assert v3 is not None
    a.close()


def test_compressed_batch_roundtrip():
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.memory import meta

    rb = pa.record_batch({
        "k": pa.array(np.arange(500, dtype=np.int64)),
        "s": pa.array([f"val_{i % 7}" for i in range(500)]),
    })
    batch = batch_to_device(rb, xp=np)
    for codec in (meta.CODEC_NONE, meta.CODEC_LZ4, meta.CODEC_ZSTD):
        data = meta.serialize_batch(batch, codec=codec)
        back = meta.deserialize_batch(data, xp=np)
        rb2 = pa.record_batch(
            {"k": pa.array(np.asarray(back.columns[0].data[:500])),
             "s": pa.array([s for s in _strings(back.columns[1], 500)])})
        assert rb2.column("k").to_pylist() == rb.column("k").to_pylist()
        assert rb2.column("s").to_pylist() == rb.column("s").to_pylist()


def _strings(col, n):
    from spark_rapids_tpu.columnar.device import column_to_arrow
    return column_to_arrow(col, n).to_pylist()


def test_spill_uses_default_codec():
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.memory import meta
    from spark_rapids_tpu.memory.spill import SpillCatalog

    meta.set_default_codec("lz4")
    try:
        rb = pa.record_batch(
            {"v": pa.array(np.zeros(10_000, dtype=np.int64))})
        cat = SpillCatalog()
        sb = cat.register(batch_to_device(rb, xp=np))
        sb.spill_to_host()
        # highly repetitive data: compression must have shrunk it
        assert sb.host_size() < 10_000 * 8 // 10
        back = sb.get_batch(np)
        assert int(back.num_rows) == 10_000
        assert not np.asarray(back.columns[0].data[:10_000]).any()
    finally:
        meta.set_default_codec("none")
