"""String expression tests — differential + independent pyarrow oracles
(model: integration_tests/string_test.py)."""

import pyarrow as pa
import pyarrow.compute as pc
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect, with_tpu_session)
from spark_rapids_tpu.testing.data_gen import StringGen, IntegerGen, gen_df

_SAMPLE = ["hello world", "", None, "  padded  ", "UPPER lower",
           "a", "abcabcabc", "xyz", "foo bar baz", "  ", "ab_cd%ef"]


def _df(spark):
    return spark.create_dataframe(pa.table({
        "s": pa.array(_SAMPLE, type=pa.string()),
        "n": pa.array(list(range(len(_SAMPLE))), type=pa.int32())}))


def test_upper_lower_length_vs_arrow():
    def q(spark):
        return _df(spark).select(
            F.upper(col("s")).alias("u"),
            F.lower(col("s")).alias("l"),
            F.length(col("s")).alias("n"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    arr = pa.array(_SAMPLE, type=pa.string())
    assert tpu.column("u").to_pylist() == pc.utf8_upper(arr).to_pylist()
    assert tpu.column("l").to_pylist() == pc.utf8_lower(arr).to_pylist()
    assert tpu.column("n").to_pylist() == pc.utf8_length(arr).to_pylist()


def test_substring():
    def q(spark):
        return _df(spark).select(
            F.substring(col("s"), 1, 3).alias("a"),
            F.substring(col("s"), 3, 100).alias("b"),
            F.substring(col("s"), -3, 2).alias("c"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    exp = [None if s is None else s[0:3] for s in _SAMPLE]
    assert tpu.column("a").to_pylist() == exp
    def sub_sql(s, pos, n):
        start = len(s) + pos if pos < 0 else (pos - 1 if pos > 0 else 0)
        end = start + n
        return s[max(start, 0):max(min(end, len(s)), 0)]
    exp_c = [None if s is None else sub_sql(s, -3, 2) for s in _SAMPLE]
    assert tpu.column("c").to_pylist() == exp_c


def test_concat_trim():
    def q(spark):
        return _df(spark).select(
            F.concat(col("s"), lit("!"), col("s")).alias("cc"),
            Fx_trim(col("s")).alias("tr"))

    def Fx_trim(c):
        from spark_rapids_tpu.expr.strings import Trim
        from spark_rapids_tpu.api.column import Column
        return Column(Trim(c.expr))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    exp = [None if s is None else s + "!" + s for s in _SAMPLE]
    assert tpu.column("cc").to_pylist() == exp
    assert tpu.column("tr").to_pylist() == \
        [None if s is None else s.strip(" ") for s in _SAMPLE]


def test_contains_startswith_endswith():
    def q(spark):
        return _df(spark).select(
            col("s").contains("ab").alias("c"),
            col("s").startswith("he").alias("st"),
            col("s").endswith("z").alias("en"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("c").to_pylist() == \
        [None if s is None else "ab" in s for s in _SAMPLE]
    assert tpu.column("st").to_pylist() == \
        [None if s is None else s.startswith("he") for s in _SAMPLE]
    assert tpu.column("en").to_pylist() == \
        [None if s is None else s.endswith("z") for s in _SAMPLE]


def test_replace():
    def q(spark):
        from spark_rapids_tpu.expr.strings import StringReplace
        from spark_rapids_tpu.api.column import Column
        from spark_rapids_tpu.expr.core import Literal
        return _df(spark).select(Column(StringReplace(
            col("s").expr, Literal("ab"), Literal("XYZ"))).alias("r"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("r").to_pylist() == \
        [None if s is None else s.replace("ab", "XYZ") for s in _SAMPLE]


def test_like():
    import fnmatch

    def q(spark):
        from spark_rapids_tpu.expr.strings import Like
        from spark_rapids_tpu.api.column import Column
        from spark_rapids_tpu.expr.core import Literal
        return _df(spark).select(
            Column(Like(col("s").expr, Literal("h%"))).alias("p"),
            Column(Like(col("s").expr, Literal("%z"))).alias("sfx"),
            Column(Like(col("s").expr, Literal("%bar%"))).alias("mid"),
            Column(Like(col("s").expr, Literal("a_c%"))).alias("w"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("p").to_pylist() == \
        [None if s is None else s.startswith("h") for s in _SAMPLE]
    assert tpu.column("sfx").to_pylist() == \
        [None if s is None else s.endswith("z") for s in _SAMPLE]
    assert tpu.column("mid").to_pylist() == \
        [None if s is None else "bar" in s for s in _SAMPLE]


def test_pad_repeat_reverse_initcap():
    def q(spark):
        from spark_rapids_tpu.expr.strings import (InitCap, Reverse,
                                                   StringLPad, StringRepeat,
                                                   StringRPad)
        from spark_rapids_tpu.api.column import Column
        from spark_rapids_tpu.expr.core import Literal
        return _df(spark).select(
            Column(StringLPad(col("s").expr, Literal(8),
                              Literal("*"))).alias("lp"),
            Column(StringRPad(col("s").expr, Literal(8),
                              Literal("*"))).alias("rp"),
            Column(StringRepeat(col("s").expr, Literal(2))).alias("rep"),
            Column(Reverse(col("s").expr)).alias("rev"),
            Column(InitCap(col("s").expr)).alias("ic"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("lp").to_pylist() == \
        [None if s is None else s.rjust(8, "*")[:8] if len(s) <= 8
         else s[:8] for s in _SAMPLE]
    assert tpu.column("rep").to_pylist() == \
        [None if s is None else s * 2 for s in _SAMPLE]
    assert tpu.column("rev").to_pylist() == \
        [None if s is None else s[::-1] for s in _SAMPLE]


def test_string_fuzz_differential():
    def q(spark):
        df = gen_df(spark, [("s", StringGen(max_len=12)),
                            ("p", IntegerGen(lo=-5, hi=8))], length=512)
        return df.select(
            F.upper(col("s")).alias("u"),
            F.length(col("s")).alias("n"),
            F.substring(col("s"), 2, 4).alias("sub"),
            F.concat(col("s"), lit("-"), col("s")).alias("cc"),
            col("s").contains("a").alias("ca"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_locate():
    def q(spark):
        from spark_rapids_tpu.expr.strings import StringLocate
        from spark_rapids_tpu.api.column import Column
        from spark_rapids_tpu.expr.core import Literal
        return _df(spark).select(
            Column(StringLocate(Literal("b"), col("s").expr)).alias("l1"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("l1").to_pylist() == \
        [None if s is None else (s.find("b") + 1) for s in _SAMPLE]


def test_regexp_replace_group_refs_and_escaped_dollar():
    """Java replacement semantics: $N is a group ref, \\$ a literal
    dollar, $0 the whole match (Python spells that \\g<0>, not \\0)."""
    s = TpuSession.builder().get_or_create()
    tb = pa.table({"s": pa.array(["ab12cd", "xy7", "noop", None])})
    df = s.create_dataframe(tb)
    out = df.select(
        F.regexp_replace(col("s"), r"(\d+)", r"[$1]").alias("grp"),
        F.regexp_replace(col("s"), r"(\d+)", r"\$1").alias("lit"),
        F.regexp_replace(col("s"), r"\d+", r"<$0>").alias("whole"),
    ).collect()
    assert out.column("grp").to_pylist() == \
        ["ab[12]cd", "xy[7]", "noop", None]
    assert out.column("lit").to_pylist() == ["ab$1cd", "xy$1", "noop", None]
    assert out.column("whole").to_pylist() == \
        ["ab<12>cd", "xy<7>", "noop", None]


def test_regexp_replace_backslash_escapes():
    """Java appendReplacement: backslash makes the next char literal
    (\\d is a literal d, \\\\$1 is a literal backslash then a group
    ref) — needs a left-to-right scan, not a single regex pass."""
    s = TpuSession.builder().get_or_create()
    tb = pa.table({"s": pa.array(["a12b"])})
    df = s.create_dataframe(tb)
    out = df.select(
        F.regexp_replace(col("s"), r"(\d+)", "\\d").alias("litd"),
        F.regexp_replace(col("s"), r"(\d+)", "\\\\$1").alias("bsref"),
    ).collect()
    assert out.column("litd").to_pylist() == ["adb"]
    assert out.column("bsref").to_pylist() == ["a\\12b"]


def test_regexp_replace_group_ref_edge_cases():
    """Java takes $-digits only while they form a valid group number
    ('$12' with one group = group 1 + literal '2'); ${name} references
    a named group."""
    s = TpuSession.builder().get_or_create()
    tb = pa.table({"s": pa.array(["a1b"])})
    out = s.create_dataframe(tb).select(
        F.regexp_replace(col("s"), r"(\d)", "$12").alias("over"),
        F.regexp_replace(col("s"), r"(?P<d>\d)", "${d}!").alias("named"),
    ).collect()
    assert out.column("over").to_pylist() == ["a12b"]
    assert out.column("named").to_pylist() == ["a1!b"]


def test_pattern_string_gen_differential():
    """Fuzzed regex-pattern strings (the reference's sre_yield-style
    generation, ref data_gen.py:153) through string kernels: TPU vs CPU
    engines agree, including UTF-8 multibyte special cases."""
    import re

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing.data_gen import StringGen, LongGen, gen_df
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession

    cols = [("s", StringGen(pattern=r"(ab|cd){1,3}[0-9]{0,4}_?end")),
            ("v", LongGen())]

    def q(spark):
        df = gen_df(spark, cols, length=400, seed=7)
        return (df.select(col("s"), F.upper(col("s")).alias("u"),
                          F.length(col("s")).alias("n"),
                          F.substring(col("s"), 2, 3).alias("sub"))
                .collect())

    tpu = TpuSession.builder().config("spark.rapids.sql.enabled",
                                      True).get_or_create()
    cpu = TpuSession.builder().config("spark.rapids.sql.enabled",
                                      False).get_or_create()
    a, b = q(tpu), q(cpu)
    for name in ("s", "u", "n", "sub"):
        assert a.column(name).to_pylist() == b.column(name).to_pylist(), \
            name
    # the generator actually produced pattern-conforming values
    pat = re.compile(r"(ab|cd){1,3}[0-9]{0,4}_?end")
    vals = [v for v in a.column("s").to_pylist() if v]
    conforming = [v for v in vals if pat.fullmatch(v)]
    # specials (empty/UTF-8) dilute, but the bulk must match
    assert len(conforming) >= len(vals) * 0.8


def test_nested_gen_weighted_depth_roundtrip():
    """Weighted-depth nested generators build valid arrow tables and
    survive an engine scan round trip."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.testing.data_gen import gen_table, nested_gen

    for seed in range(3):
        g = nested_gen(seed, max_depth=3)
        tb = gen_table([("x", g)], length=64, seed=seed)
        s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                        True).get_or_create()
        out = s.create_dataframe(tb).collect()
        assert out.num_rows == 64
        # string compare: NaN != NaN under == but reprs match
        assert str(out.column("x").to_pylist()) == \
            str(tb.column("x").to_pylist())
