"""Warm-start tier round trip (obs/prewarm.py).

Session A builds programs with a compile ledger configured, which
persists one recipe per program (key + stubbed traced callable +
abstract arg signatures).  A "new session" (observatory + jit table
reset — process death in miniature) replays the ledger's recipes and
must then run the same query with ZERO builds: every call is served by
a prewarmed executable, counted in prewarm_hits and the
tpu_jit_prewarm_* metric families.
"""

import os

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.columnar.fetch as fetch
import spark_rapids_tpu.exec.base as eb
import spark_rapids_tpu.obs.metrics as obs_metrics
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.obs.compileprof import CompileObservatory
from spark_rapids_tpu.obs.prewarm import (prewarm_from_ledger,
                                          rank_ledger_programs,
                                          recipes_dir)


@pytest.fixture
def fresh():
    obs_metrics.MetricsRegistry.reset_for_tests()
    CompileObservatory.reset_for_tests()
    eb.clear_jit_cache()
    # the speculative-fetch plan memo is schema-keyed: an earlier test
    # fetching the same schema at another capacity would arm a doomed
    # speculation here, adding a one-shot program run 2 never dispatches
    fetch._LAST_PLAN.clear()
    yield
    eb.clear_jit_cache()
    CompileObservatory.reset_for_tests()
    obs_metrics.MetricsRegistry.reset_for_tests()


def _run_query(session):
    n = 1500
    tbl = pa.table({
        "k": pa.array((np.arange(n) % 5).astype(np.int64)),
        "v": pa.array(np.arange(n, dtype=np.int64)),
    })
    df = session.create_dataframe(tbl)
    out = (df.filter(col("v") > 10)
           .select(col("k"), (col("v") * 3).alias("x"))
           .collect())
    v = np.arange(n, dtype=np.int64)
    np.testing.assert_array_equal(
        np.sort(out.column("x").to_numpy()), np.sort(v[v > 10] * 3))


def test_prewarm_round_trip(fresh, tmp_path):
    ledger_dir = str(tmp_path / "hist")
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.singleChipFuse", "off")
         .config("spark.rapids.tpu.sort.compileLean", "off")
         .config("spark.rapids.tpu.compile.ledgerDir", ledger_dir)
         .get_or_create())
    ledger_path = CompileObservatory.get().ledger_path
    assert ledger_path

    _run_query(s)
    built = CompileObservatory.get().snapshot()["builds"]
    assert built > 0
    rdir = recipes_dir(ledger_path)
    assert os.path.isdir(rdir) and len(os.listdir(rdir)) == built
    assert len(rank_ledger_programs(ledger_path)) == built

    # "next session": fresh observatory + empty jit table, replay
    obs_metrics.MetricsRegistry.reset_for_tests()
    obs2 = CompileObservatory.reset_for_tests()
    eb.clear_jit_cache()
    fetch._LAST_PLAN.clear()
    obs2.configure(enabled=True, ledger_path=ledger_path)
    stats = prewarm_from_ledger(ledger_path, top_k=32)
    assert stats["recipes"] == built
    assert stats["programs"] >= built
    assert stats["errors"] == 0

    _run_query(s)
    snap = obs2.snapshot()
    assert snap["builds"] == 0, (
        f"prewarmed session still compiled: {snap['by_cause']}")
    assert snap["prewarm_hits"] == built, (
        f"unclaimed staged keys: {list(obs2._prewarm_staged)}")
    assert obs_metrics.registry().counter(
        "tpu_jit_prewarm_seconds_total").value() > 0


def test_prewarm_missing_ledger_is_noop(fresh, tmp_path):
    stats = prewarm_from_ledger(str(tmp_path / "nope.jsonl"), top_k=8)
    assert stats == {"recipes": 0, "programs": 0, "skipped": 0,
                     "errors": 0, "seconds": 0.0}
