"""tpufsan unit tests: exception-flow rules TPU-R011..R014 against
bad/clean twin fixtures (anti-vacuity in both directions), raise-set
propagation over the real repo's call chains, the fault-injection plan
the --faults gate executes, and the background-error routing seam.

The end-to-end campaign lives in ``devtools/run_lint.py --faults``
(wired into tier-1 by tests/test_lint_clean.py); these units pin the
analysis semantics the campaign relies on."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.analysis import raiseflow


def _codes(res):
    return sorted({d.code for d in res.diagnostics})


# ---------------------------------------------------------------------------
# TPU-R011: broad except swallowing a typed engine error
# ---------------------------------------------------------------------------

_R011_COMMON = '''
class EngineError(Exception):
    pass

def work():
    raise EngineError("x")
'''


def test_r011_broad_swallow_fires():
    src = _R011_COMMON + '''
def seam():
    try:
        work()
    except Exception:
        pass
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/m11.py": src}, seams=())
    assert _codes(res) == ["TPU-R011"]
    (d,) = res.diagnostics
    assert "EngineError" in d.message


def test_r011_reraise_twin_is_clean():
    src = _R011_COMMON + '''
def seam():
    try:
        work()
    except Exception:
        raise
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/m11.py": src}, seams=())
    assert _codes(res) == []


def test_r011_narrow_handler_is_clean():
    # catching the typed error BY TYPE is a deliberate decision, not a
    # swallow — only bare/broad handlers are in scope
    src = _R011_COMMON + '''
def seam():
    try:
        work()
    except EngineError:
        pass
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/m11.py": src}, seams=())
    assert _codes(res) == []


# ---------------------------------------------------------------------------
# TPU-R012: raising successor can skip a declared release obligation
# ---------------------------------------------------------------------------

# the fixture lives at the real admission relpath so its admit() fid
# matches the declared obligation suffix
_R012_COMMON = '''
class AdmissionController:
    def admit(self, n):
        return object()
    def release(self):
        pass

def might_raise():
    raise ValueError("x")
'''


def test_r012_leaking_acquire_fires():
    src = _R012_COMMON + '''
def risky():
    ctrl = AdmissionController()
    ctrl.admit(8)
    might_raise()
    ctrl.release()
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/memory/admission.py": src}, seams=())
    assert _codes(res) == ["TPU-R012"]
    (d,) = res.diagnostics
    assert "admission ticket" in d.message


def test_r012_finally_twin_is_clean():
    src = _R012_COMMON + '''
def careful():
    ctrl = AdmissionController()
    ctrl.admit(8)
    try:
        might_raise()
    finally:
        ctrl.release()
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/memory/admission.py": src}, seams=())
    assert _codes(res) == []


def test_r012_ownership_transfer_is_clean():
    # handing the ticket to another frame (stored on self / passed as
    # an argument) transfers the obligation out of this function
    src = _R012_COMMON + '''
def handoff(sink):
    ctrl = AdmissionController()
    t = ctrl.admit(8)
    might_raise()
    sink.finish(t)
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/memory/admission.py": src}, seams=())
    assert _codes(res) == []


# ---------------------------------------------------------------------------
# TPU-R013: untyped operational exception escaping a public seam
# ---------------------------------------------------------------------------

_R013_SEAM = (("svc", "svc.py", "serve", ("svc.py",)),)


def test_r013_untyped_leak_fires():
    src = '''
def helper():
    raise RuntimeError("boom")

def serve():
    helper()
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/svc.py": src}, seams=_R013_SEAM)
    assert _codes(res) == ["TPU-R013"]
    (d,) = res.diagnostics
    assert "RuntimeError" in d.message


def test_r013_typed_twin_is_clean():
    src = '''
class SvcError(Exception):
    pass

def helper():
    raise SvcError("boom")

def serve():
    helper()
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/svc.py": src}, seams=_R013_SEAM)
    assert _codes(res) == []


# ---------------------------------------------------------------------------
# TPU-R014: socket on a thread-root path without a deadline
# ---------------------------------------------------------------------------

def test_r014_socket_without_deadline_fires():
    src = '''
import socket

def _run():
    s = socket.create_connection(("peer", 9))
    return s.recv(4)
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/sockmod.py": src},
        roots=["sockmod._run"], seams=())
    assert _codes(res) == ["TPU-R014"]


def test_r014_timeout_twin_is_clean():
    src = '''
import socket

def _run():
    s = socket.create_connection(("peer", 9), timeout=5.0)
    return s.recv(4)
'''
    res = raiseflow.analyze_sources(
        {"spark_rapids_tpu/sockmod.py": src},
        roots=["sockmod._run"], seams=())
    assert _codes(res) == []


# ---------------------------------------------------------------------------
# raise-set propagation over the real repo
# ---------------------------------------------------------------------------

def test_repo_is_fsan_clean():
    assert raiseflow.repo_diagnostics() == []


def test_main_query_seam_raise_set():
    res = raiseflow.analyze_repo()
    typed = res.raises[res.seams["main-query"]]
    # errors raised many frames below TpuSession.execute must have
    # propagated up through the interprocedural fixpoint
    for name in ("AdmissionTimeout", "EvalError", "LifecycleViolation"):
        assert name in typed, f"{name} did not propagate to main-query"


def test_pool_seams_include_pool_lifecycle_errors():
    art = raiseflow.raise_graph_artifact()
    assert "PoolClosedError" in art["seams"]["pool-borrow"]["typed"]
    assert "PoolTimeout" in art["seams"]["pool-drain"]["typed"]
    # serving-client delegates to main-query AND adds the pool's own
    # lifecycle errors on top
    serving = set(art["seams"]["serving-client"]["typed"])
    main = set(art["seams"]["main-query"]["typed"])
    assert main <= serving
    assert {"PoolClosedError", "PoolTimeout"} <= serving - main


def test_fetcher_seam_carries_wire_errors():
    art = raiseflow.raise_graph_artifact()
    fetcher = set(art["seams"]["shuffle-fetcher"]["typed"])
    assert {"TpuShuffleBlockMissingError", "TpuShufflePeerDeadError",
            "TpuShuffleTruncatedFrameError"} <= fetcher


def test_injection_plan_floor_and_no_leaks():
    art = raiseflow.raise_graph_artifact()
    assert len(art["injections"]) >= 40
    leaks = {label: s["untyped"]
             for label, s in art["seams"].items() if s["untyped"]}
    assert not leaks, f"untyped operational leaks at seams: {leaks}"


def test_every_planned_error_is_constructible():
    art = raiseflow.raise_graph_artifact()
    for inj in art["injections"]:
        err = raiseflow.construct_error(inj["error"])
        assert isinstance(err, Exception)
        assert type(err).__name__ == inj["error"]


# ---------------------------------------------------------------------------
# background-error routing (heartbeat / metrics-http thread roots)
# ---------------------------------------------------------------------------

def test_note_background_error_counts_records_and_bundles(tmp_path):
    from spark_rapids_tpu.obs import bgerrors
    from spark_rapids_tpu.obs import metrics as m
    from spark_rapids_tpu.obs import postmortem as pm
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    MetricsRegistry.reset_for_tests()
    bgerrors.reset()
    try:
        bgerrors.set_postmortem_dir(str(tmp_path))
        bgerrors.note_background_error(
            "heartbeat-loop", RuntimeError("beat failed"))
        bgerrors.note_background_error(
            "heartbeat-loop", RuntimeError("beat failed again"))
        rec = bgerrors.last_error("heartbeat-loop")
        assert rec["type"] == "RuntimeError"
        assert rec["count"] == 2
        fam = m.counter("tpu_background_errors_total",
                        labelnames=("root",))
        assert fam.value(root="heartbeat-loop") == 2
        bundles = pm.list_bundles(str(tmp_path))
        assert len(bundles) == 2
        doc = pm.load_bundle(bundles[0])
        assert doc["kind"] == "background_failure"
        assert doc["error"]["type"] == "RuntimeError"
    finally:
        bgerrors.reset()
        MetricsRegistry.reset_for_tests()


def test_background_errors_degrade_health():
    from spark_rapids_tpu.obs import bgerrors
    from spark_rapids_tpu.obs.health import HealthMonitor
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    MetricsRegistry.reset_for_tests()
    bgerrors.reset()
    try:
        mon = HealthMonitor()
        mon.snapshot()  # baseline for the delta rules
        bgerrors.note_background_error(
            "metrics-http", RuntimeError("render blew up"))
        snap = mon.snapshot()
        assert snap["components"]["background"]["status"] == "degraded"
    finally:
        bgerrors.reset()
        MetricsRegistry.reset_for_tests()


# ---------------------------------------------------------------------------
# fault-injection mechanics: the properties the --faults gate asserts
# ---------------------------------------------------------------------------

@pytest.fixture
def _fresh_world(tmp_path):
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
    MetricsRegistry.reset_for_tests()
    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()
    AdmissionController.reset_for_tests()
    yield tmp_path
    TpuShuffleManager.reset()
    AdmissionController.reset_for_tests()
    MetricsRegistry.reset_for_tests()


def _golden_table():
    return pa.table({
        "k": pa.array((np.arange(60) % 7).astype(np.int64)),
        "v": pa.array(np.arange(60, dtype=np.int64))})


def test_injected_typed_fault_propagates_and_books_balance(_fresh_world):
    """One real injection end to end: arm FilterExec with a typed
    engine error, run a golden query, and assert exactly what the gate
    asserts per (seam, error) pair — typed propagation, balanced books
    and one parseable post-mortem bundle."""
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec import basic as exec_basic
    from spark_rapids_tpu.exec.base import _wrap_execute_partition
    from spark_rapids_tpu.obs import postmortem as pm
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager

    pmdir = str(_fresh_world)
    sess = TpuSession({
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.tpu.trace.enabled": "true",
        "spark.rapids.tpu.hbm.postmortem.dir": pmdir,
    })
    err = raiseflow.construct_error("TpuShufflePeerDeadError")

    def boom(self, pid, ctx):
        raise err
        yield

    real = exec_basic.FilterExec.execute_partition
    exec_basic.FilterExec.execute_partition = \
        _wrap_execute_partition(boom)
    try:
        with pytest.raises(Exception) as ei:
            (sess.create_dataframe(_golden_table(), num_partitions=2)
             .filter(col("v") > 5).collect())
    finally:
        exec_basic.FilterExec.execute_partition = real
    assert type(ei.value).__name__ == "TpuShufflePeerDeadError"
    # books balance: no orphaned blocks, no open spans
    assert TpuShuffleManager.get().catalog.num_blocks() == 0
    trace = sess.last_query_trace()
    assert trace is not None and trace.open_span_count() == 0
    # exactly one parseable bundle naming the injected error
    bundles = pm.list_bundles(pmdir)
    assert len(bundles) == 1
    doc = pm.load_bundle(bundles[0])
    assert doc["error"]["type"] == "TpuShufflePeerDeadError"


def test_leaking_fault_is_detected(_fresh_world):
    """Anti-vacuity for the campaign's books check: a fault that fires
    AFTER the exchange wrote its map outputs, combined with a broken
    release path, must leave orphaned shuffle blocks — exactly the
    signal that fails the --faults gate.  With the release path intact
    the same fault leaves the catalog clean."""
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec import basic as exec_basic
    from spark_rapids_tpu.exec.base import _wrap_execute_partition
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager

    sess = TpuSession({"spark.rapids.sql.enabled": "true"})
    err = raiseflow.construct_error("TpuShuffleFetchFailedError")

    def boom(self, pid, ctx):
        # drain the child first so the exchange below has materialized
        # its map outputs before the fault unwinds the query
        for _ in self.children[0].execute_partition(pid, ctx):
            pass
        raise err
        yield

    def run_query():
        return (sess.create_dataframe(_golden_table(), num_partitions=3)
                .repartition(5, col("k"))
                .filter(col("v") > 5).collect())

    real = exec_basic.FilterExec.execute_partition
    exec_basic.FilterExec.execute_partition = \
        _wrap_execute_partition(boom)
    real_release = TpuSession.release_plan_shuffles
    try:
        # broken release path: the fault strands every map-output block
        TpuSession.release_plan_shuffles = lambda self, plan: None
        with pytest.raises(Exception):
            run_query()
        leaked = TpuShuffleManager.get().catalog.num_blocks()
        assert leaked > 0, \
            "books check is vacuous: broken release leaked nothing"
        # intact release path: the same fault leaves balanced books
        TpuSession.release_plan_shuffles = real_release
        TpuShuffleManager.reset()
        with pytest.raises(Exception):
            run_query()
        assert TpuShuffleManager.get().catalog.num_blocks() == 0
    finally:
        TpuSession.release_plan_shuffles = real_release
        exec_basic.FilterExec.execute_partition = real


def test_untyped_injection_breaks_typed_propagation_check(_fresh_world):
    """The campaign's propagation check is not vacuous: injecting a
    RAW RuntimeError surfaces as RuntimeError at the seam, which is
    precisely the mismatch the gate reports as broken propagation."""
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec import basic as exec_basic
    from spark_rapids_tpu.exec.base import _wrap_execute_partition

    sess = TpuSession({"spark.rapids.sql.enabled": "true"})

    def boom(self, pid, ctx):
        raise RuntimeError("untyped operational failure")
        yield

    real = exec_basic.FilterExec.execute_partition
    exec_basic.FilterExec.execute_partition = \
        _wrap_execute_partition(boom)
    try:
        with pytest.raises(Exception) as ei:
            (sess.create_dataframe(_golden_table(), num_partitions=2)
             .filter(col("v") > 5).collect())
    finally:
        exec_basic.FilterExec.execute_partition = real
    assert type(ei.value).__name__ != "TpuShuffleTimeoutError"
    assert type(ei.value).__name__ == "RuntimeError"
