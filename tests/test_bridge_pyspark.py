"""Real-Spark bridge integration: runs ONLY where pyspark is importable
(CI; the hermetic engine environment ships no Spark — there the protocol
is proven by the fake-JVM harness in test_bridge.py).

The loop: a pyspark DataFrame's collected partitions ship through the
sidecar protocol exactly as the Scala TpuBridgeExec would (bridge-jvm/
README.md), and the sidecar-computed stage must match Spark's own
result.  This drives the same spec JSON the Scala SpecBuilder emits.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

pyspark = pytest.importorskip("pyspark")

from spark_rapids_tpu.bridge import BridgeClient  # noqa: E402


@pytest.fixture(scope="module")
def spark():
    from pyspark.sql import SparkSession
    s = (SparkSession.builder.master("local[2]")
         .appName("tpu-bridge-it").getOrCreate())
    yield s
    s.stop()


@pytest.fixture(scope="module")
def sidecar():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PALLAS_AXON_POOL_IPS"] = ""
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.bridge.sidecar"],
        stdout=subprocess.PIPE, env=env, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    port = None
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("TPU_SIDECAR_PORT="):
            port = int(line.strip().split("=")[1])
            break
    assert port, "sidecar never announced its port"
    yield port
    proc.kill()


def test_spark_aggregate_through_sidecar(spark, sidecar):
    sdf = spark.range(0, 10_000).selectExpr(
        "id % 37 as k", "id as v", "cast(id as double) / 7 as f")
    # what TpuBridgeRule would emit for
    #   scan -> filter(v > 100) -> groupBy(k).agg(sum(v), count(*))
    spec = {
        "input": {"schema": [["k", "bigint"], ["v", "bigint"],
                             ["f", "double"]]},
        "ops": [
            {"op": "filter", "condition": {
                "op": "gt", "children": [{"col": "v"},
                                         {"lit": 100, "type": "bigint"}]}},
            {"op": "aggregate", "groupBy": [{"col": "k"}],
             "aggs": [{"fn": "sum", "expr": {"col": "v"}, "name": "sv"},
                      {"fn": "count", "expr": None, "name": "c"}]},
            {"op": "sort", "orders": [{"expr": {"col": "k"},
                                       "ascending": True}]},
        ],
    }
    table = pa.Table.from_pandas(sdf.toPandas())
    client = BridgeClient(sidecar)
    try:
        got = client.execute_stage(spec, table)
    finally:
        client.close()
    want = (sdf.filter("v > 100").groupBy("k")
            .agg({"v": "sum", "*": "count"})
            .withColumnRenamed("sum(v)", "sv")
            .withColumnRenamed("count(1)", "c")
            .orderBy("k").toPandas())
    assert got.column("k").to_pylist() == want["k"].tolist()
    assert got.column("sv").to_pylist() == want["sv"].tolist()
    assert got.column("c").to_pylist() == want["c"].tolist()
