"""tpudsan tests: the TPU-R015/R016 repo rules and their clean twins,
TPU-L016 on the plan (stable_merge off/on) with the stabilizing
repair, TPU-L017 fingerprint hygiene via the injectable schema, the
permuted-replay oracle round trip over a real exchange write, the
replica-retry read failing typed with provenance when a block's
content digest is corrupted, and the digest metadata surviving the v2
wire frame in both directions."""

import socket
from collections import Counter

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.analysis import determinism as dsan
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec import base as eb
from spark_rapids_tpu.shuffle.manager import (TpuShuffleManager,
                                              materialize_block)

_REL = "spark_rapids_tpu/exec/injected.py"


def _codes(diags):
    return sorted({d.code for d in diags})


# -- TPU-R015: volatile sources on result paths -----------------------------

_R015_BAD = '''\
import time


def route_rows(batches, nparts):
    out = {}
    stamp = time.time()
    for key in {"alpha", "beta", "gamma"}:
        out[key] = stamp
    return out
'''

# twin differs only in the sanctioned forms: a seeded RNG and a
# deterministic iteration order
_R015_CLEAN = '''\
import random


def route_rows(batches, nparts):
    out = {}
    rng = random.Random(1234)
    for key in sorted(["alpha", "beta", "gamma"]):
        out[key] = rng.random()
    return out
'''


def test_r015_flags_wall_clock_and_set_iteration():
    diags = dsan.module_diagnostics(_R015_BAD, _REL)
    assert _codes(diags) == ["TPU-R015"]
    msgs = " | ".join(d.message for d in diags)
    assert "wall-clock" in msgs and "set literal" in msgs
    assert len(diags) >= 2


def test_r015_clean_twin_is_silent():
    assert dsan.module_diagnostics(_R015_CLEAN, _REL) == []


# -- TPU-R016: arrival-order float folds ------------------------------------

_R016_BAD = '''\
def fold(batches):
    running_sum = 0.0
    for b in batches:
        running_sum += b.column_sum("v")
    return running_sum
'''

# twin canonicalizes the fold order before accumulating — the repair
# the rule message prescribes
_R016_CLEAN = '''\
def fold(batches):
    running_sum = 0.0
    for b in sorted(batches, key=lambda b: b.block_key):
        running_sum += b.column_sum("v")
    return running_sum
'''


def test_r016_flags_arrival_order_float_fold():
    diags = dsan.module_diagnostics(_R016_BAD, _REL)
    assert _codes(diags) == ["TPU-R016"]
    assert "arrival order" in diags[0].message


def test_r016_canonicalized_twin_is_silent():
    assert dsan.module_diagnostics(_R016_CLEAN, _REL) == []


# -- TPU-L016: weak subtree feeding an exchange -----------------------------


def _float_partial_plan(stable: bool):
    """scan(batch_rows=1) -> PARTIAL float Sum -> hash exchange; the
    values make arrival order observable in float64 ((1e16 - 1e16) + 1
    vs (1 - 1e16) + 1e16)."""
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.basic import LocalScanExec
    from spark_rapids_tpu.expr.aggregates import (AggregateExpression,
                                                  PARTIAL, Sum)
    from spark_rapids_tpu.expr.core import AttributeReference
    from spark_rapids_tpu.shuffle.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioning
    tbl = pa.table({
        "k": pa.array([0, 0, 0], type=pa.int64()),
        "v": pa.array([1e16, -1e16, 1.0], type=pa.float64()),
    })
    scan = LocalScanExec(tbl, num_partitions=1, batch_rows=1)
    scan.placement = eb.CPU
    partial = TpuHashAggregateExec(
        [AttributeReference("k")],
        [AggregateExpression(Sum(AttributeReference("v")))],
        PARTIAL, scan)
    partial.placement = eb.CPU
    partial.stable_merge = stable
    ex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("k")], 2), partial)
    ex.placement = eb.CPU
    return ex


def test_l016_flags_unstable_float_partial_under_exchange():
    from spark_rapids_tpu.analysis.plan_lint import lint_plan
    diags = lint_plan(_float_partial_plan(stable=False), RapidsConf({}))
    l016 = [d for d in diags if d.code == "TPU-L016"]
    assert l016, f"expected TPU-L016, got {_codes(diags)}"
    assert "order_stable" in l016[0].message


def test_l016_clean_with_canonical_merge():
    from spark_rapids_tpu.analysis.plan_lint import lint_plan
    diags = lint_plan(_float_partial_plan(stable=True), RapidsConf({}))
    assert "TPU-L016" not in _codes(diags)


def test_l016_stabilize_repair_upgrades_the_subtree():
    """The repair forces the canonical keyed merge on the flagged
    boundary's canonicalizable operators; the re-classified subtree
    must reach order_stable and a re-lint must come back clean."""
    from spark_rapids_tpu.analysis.plan_lint import lint_plan
    ex = _float_partial_plan(stable=False)
    conf = RapidsConf({})
    l016 = [d for d in lint_plan(ex, conf) if d.code == "TPU-L016"]
    node = getattr(l016[0], "node", None)
    assert node is not None
    assert dsan.try_stabilize_repair(ex, node, conf)
    assert ex.children[0].stable_merge is True
    res = dsan.classify_plan(ex, conf)
    assert dsan.RANK[res.effective(ex.children[0])] >= \
        dsan.RANK[dsan.ORDER_STABLE]
    assert "TPU-L016" not in _codes(lint_plan(ex, conf))


# -- TPU-L017: fingerprint hygiene ------------------------------------------


def test_l017_overlapping_and_volatile_schemas_flagged():
    overlap = dsan.fingerprint_hygiene_diagnostics(
        deterministic=["plan_hash", "submit_time_ms"],
        timing=["submit_time_ms"])
    assert _codes(overlap) == ["TPU-L017"]
    volatile = dsan.fingerprint_hygiene_diagnostics(
        deterministic=["plan_hash", "wall_start"], timing=[])
    assert _codes(volatile) == ["TPU-L017"]


def test_l017_clean_schema_and_live_registry_silent():
    assert dsan.fingerprint_hygiene_diagnostics(
        deterministic=["plan_hash"], timing=["submit_time_ms"]) == []
    # the live obs/history schema must itself be hygienic
    assert dsan.fingerprint_hygiene_diagnostics() == []


# -- permuted-replay oracle round trip --------------------------------------


class _Permuted(eb.Exec):
    """Adversarial scheduler: replays the child's batches in reversed
    arrival order."""

    def __init__(self, inner):
        super().__init__([inner])
        self.placement = inner.placement

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def execute_partition(self, pid, ctx):
        return iter(list(
            self.children[0].execute_partition(pid, ctx))[::-1])


def _scan_exchange(permute: bool):
    from spark_rapids_tpu.exec.basic import LocalScanExec
    from spark_rapids_tpu.expr.core import AttributeReference
    from spark_rapids_tpu.shuffle.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioning
    n = 64
    tbl = pa.table({
        "k": pa.array([i % 8 for i in range(n)], type=pa.int64()),
        "v": pa.array([i * 11 for i in range(n)], type=pa.int64()),
    })
    scan = LocalScanExec(tbl, num_partitions=2, batch_rows=5)
    scan.placement = eb.CPU
    scan.pin_cache = None
    child = _Permuted(scan) if permute else scan
    ex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("k")], 4), child)
    ex.placement = eb.CPU
    return ex


def _write_and_harvest(ex):
    """Drive the exchange's map write; return the per-(map, reduce)
    Counter of recorded block digests plus any recorded-vs-recomputed
    disagreements, then unregister the shuffle."""
    from spark_rapids_tpu.shuffle.digest import block_digest
    ctx = eb.ExecContext(RapidsConf({}))
    ctx.task_context["no_speculation"] = True
    ex._ensure_written(ctx)
    sid = ex._shuffle_id
    mgr = TpuShuffleManager.get()
    blockdg = {}
    for ((_, mid, rid), _idx), dg in \
            mgr.catalog.digests_for_shuffle(sid).items():
        blockdg.setdefault((mid, rid), Counter())[dg] += 1
    bad = []
    for rid in range(ex.num_partitions):
        for blk in mgr.catalog.blocks_for_reduce(sid, rid):
            for i, sb in enumerate(mgr.catalog.get(blk)):
                recorded = mgr.catalog.digest(blk, i)
                recomputed = block_digest(materialize_block(sb, np))
                if recorded != recomputed:
                    bad.append((tuple(blk), i, recorded, recomputed))
    mgr.unregister(sid)
    return blockdg, bad


def test_permuted_replay_reproduces_block_digests():
    """The oracle round trip: an exchange over a bit-exact scan must
    write digest-identical block multisets under permuted batch
    arrival, and every write-time digest must agree with a recompute
    from the stored buffers (the content-addressing invariant)."""
    fwd = _scan_exchange(permute=False)
    res = dsan.classify_plan(fwd, RapidsConf({}))
    assert dsan.RANK[res.effective(fwd.children[0])] >= \
        dsan.RANK[dsan.ORDER_STABLE]
    TpuShuffleManager.reset()
    try:
        a, bad_a = _write_and_harvest(fwd)
        b, bad_b = _write_and_harvest(_scan_exchange(permute=True))
        assert bad_a == [] and bad_b == []
        assert a and a == b
    finally:
        TpuShuffleManager.reset()


def test_oracle_sees_planted_arrival_order_nondeterminism():
    """Anti-vacuity: the stable_merge=off float partial must produce
    DIFFERENT digests under reversed arrival — if it did not, the
    oracle could never catch a real order_dependent subtree."""
    TpuShuffleManager.reset()
    try:
        fwd = _float_partial_plan(stable=False)
        rev = _float_partial_plan(stable=False)
        rev.children[0].children[0] = _Permuted(
            rev.children[0].children[0])
        a, _ = _write_and_harvest(fwd)
        b, _ = _write_and_harvest(rev)
        assert a != b
    finally:
        TpuShuffleManager.reset()


# -- corrupted block fails typed with provenance ----------------------------


def _serve_blocks(n_maps=4, rows=64, shuffle_id=11, reduce_id=2):
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.shuffle.transport import ShuffleServer
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    for mid in range(n_maps):
        rb = pa.record_batch({"a": pa.array(
            [mid * 1000 + i for i in range(rows)], type=pa.int64())})
        mgr.write_map_output(shuffle_id, mid,
                             {reduce_id: batch_to_device(rb, xp=np)})
    return mgr, ShuffleServer(mgr).start()


def test_corrupted_block_digest_fails_replica_retry_typed():
    """A fetched block whose content does not match the write-time
    digest must fail the replica-retry read as the typed digest error
    carrying fetch provenance (which replica, how many attempts), move
    the mismatch counter, and leave expected != got on the error."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.errors import TpuShuffleDigestError
    from spark_rapids_tpu.shuffle.registry import (BlockEndpoint,
                                                   BlockLocationRegistry)
    m.MetricsRegistry.reset_for_tests()
    mgr, server = _serve_blocks(n_maps=2)
    # corrupt one registered digest: the advertised metadata now
    # promises content the payload cannot hash to
    key = sorted(mgr.catalog._digests)[0]
    mgr.catalog._digests[key] ^= 0x1
    BlockLocationRegistry.reset()
    reg = BlockLocationRegistry.get()
    reg.set_local("test-local", "127.0.0.1", 0)
    group = [BlockEndpoint("replica-a", "127.0.0.1", server.port)]
    locality.reset_pool()
    try:
        with pytest.raises(TpuShuffleDigestError) as ei:
            list(locality._fetch_group(group, 11, 2, reg, np,
                                       2, 5.0, 2, m))
        assert ei.value.expected != ei.value.got
        prov = getattr(ei.value, "fetch_provenance", "")
        assert "replica-a" in prov and "attempt" in prov
        assert m.counter("tpu_shuffle_digest_mismatch_total").value() \
            >= 1
    finally:
        server.stop()
        locality.reset_pool()
        TpuShuffleManager.reset()
        BlockLocationRegistry.reset()
        m.MetricsRegistry.reset_for_tests()


# -- replay-class drift across runs -----------------------------------------


def test_replay_class_drift_is_deterministic():
    from spark_rapids_tpu.obs.history import diff_fingerprints
    base = {"sql_id": 0, "description": "q0",
            "replay_class": "order_stable"}
    weakened = dict(base, replay_class="order_dependent")
    drifts = diff_fingerprints(base, weakened)
    kinds = {d.kind for d in drifts}
    assert "replay_class_drift" in kinds
    d = next(d for d in drifts if d.kind == "replay_class_drift")
    assert d.deterministic
    assert "order_stable" in d.detail and "order_dependent" in d.detail


def test_replay_class_drift_needs_both_runs_to_carry_it():
    """A history spanning the tpudsan upgrade (old runs have no
    replay_class) must never false-trip."""
    from spark_rapids_tpu.obs.history import diff_fingerprints
    old = {"sql_id": 0, "description": "q0"}
    new = {"sql_id": 0, "description": "q0",
           "replay_class": "order_stable"}
    assert not any(d.kind == "replay_class_drift"
                   for d in diff_fingerprints(old, new))
    assert not any(d.kind == "replay_class_drift"
                   for d in diff_fingerprints(new, old))


def test_fingerprint_harvests_replay_class_from_overrides_span():
    from spark_rapids_tpu.obs.history import (DETERMINISTIC_FIELDS,
                                              query_fingerprint)

    class _Plan:
        node_name = "ScanExec"
        children = ()
        actual = {}

        def walk(self):
            return [self]

    class _Sql:
        sql_id = 0
        description = "q0"
        failed = False
        plan = _Plan()
        duration = 1
        peak_device_bytes = 0

    assert "replay_class" in DETERMINISTIC_FIELDS
    fp = query_fingerprint(_Sql(), [
        {"name": "phase:overrides",
         "attrs": {"lint_rules": [], "replay_class": "bit_exact"}}])
    assert fp["replay_class"] == "bit_exact"
    # logs predating the sanitizer leave the field None
    assert query_fingerprint(_Sql(), [])["replay_class"] is None


# -- failure black box records the replay class -----------------------------


def test_postmortem_bundle_carries_replay_class():
    """The failure black box must record the failed plan's replay
    class — whether a recompute is even guaranteed to reproduce the
    failing state — and the renderer must surface it."""
    from spark_rapids_tpu.obs.postmortem import (build_bundle,
                                                 render_postmortem)

    class _Session:
        conf = RapidsConf({})
        _conf_map = {}

    bundle = build_bundle(RuntimeError("boom"), session=_Session(),
                          plan=_float_partial_plan(stable=False))
    rep = bundle["replay"]
    assert rep["class"] == "order_dependent"
    assert rep["reason"]
    assert rep["weak_subtrees"]
    text = render_postmortem(bundle)
    assert "replay class:   order_dependent" in text
    # a stabilized twin classifies order_stable in the same bundle path
    clean = build_bundle(RuntimeError("boom"), session=_Session(),
                         plan=_float_partial_plan(stable=True))
    assert clean["replay"]["class"] == "order_stable"
    assert clean["replay"]["weak_subtrees"] == []


# -- digest metadata on the v2 wire frame -----------------------------------


def test_table_meta_digest_packs_and_unpacks():
    from spark_rapids_tpu.memory.meta import TableMeta
    big = (1 << 63) + 12345
    tm = TableMeta(10, 4096, 7, big)
    assert TableMeta._S.size == len(tm.pack())
    back = TableMeta.unpack(tm.pack())
    assert (back.num_rows, back.num_bytes, back.schema_fingerprint,
            back.content_digest) == (10, 4096, 7, big)


def test_digest_survives_wire_frame_both_directions():
    """Server -> client: fetch_metadata must carry every block's
    write-time digest verbatim.  Client -> payload: the transferred
    block must verify against that digest (verified counter moves,
    mismatch counter does not)."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.shuffle.transport import (AsyncBlockFetcher,
                                                    ShuffleClient)
    m.MetricsRegistry.reset_for_tests()
    mgr, server = _serve_blocks(n_maps=3, rows=50)
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        metas = cli.fetch_metadata(11, 2).wait(10.0)
        assert len(metas) == 3
        for (sid, mid, rid, idx), meta in metas:
            assert meta.content_digest != 0
            assert meta.content_digest == \
                mgr.catalog.digest((sid, mid, rid), idx)
        # the verifying read path re-digests every transferred payload
        got = list(AsyncBlockFetcher(cli, 11, 2, window=2,
                                     timeout=10.0))
        assert len(got) == 3
        cli.close()
        assert m.counter(
            "tpu_shuffle_digest_verified_total").value() == 3
        assert m.counter(
            "tpu_shuffle_digest_mismatch_total").value() == 0
    finally:
        server.stop()
        TpuShuffleManager.reset()
        m.MetricsRegistry.reset_for_tests()
