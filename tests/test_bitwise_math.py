"""Bitwise + extended math expressions: TPU-vs-CPU differential and
Java/Spark shift semantics (ref bitwise.scala GpuBitwise*/GpuShift*)."""

import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _both(q):
    outs = []
    for enabled in (True, False):
        s = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", enabled).get_or_create())
        outs.append((s, q(s)))
    return outs


def test_bitwise_and_or_xor_not_differential():
    rng = np.random.default_rng(11)
    tb = pa.table({
        "a": pa.array(rng.integers(-2**31, 2**31, 500).astype(np.int64)),
        "b": pa.array(rng.integers(-2**31, 2**31, 500).astype(np.int64)),
    })

    def q(s):
        df = s.create_dataframe(tb)
        return df.select(
            F.bitwise_and(col("a"), col("b")).alias("and_"),
            F.bitwise_or(col("a"), col("b")).alias("or_"),
            F.bitwise_xor(col("a"), col("b")).alias("xor_"),
            F.bitwise_not(col("a")).alias("not_")).collect()

    (s1, t1), (s2, t2) = _both(q)
    for name in ("and_", "or_", "xor_", "not_"):
        assert t1.column(name).to_pylist() == t2.column(name).to_pylist()
    # placement check: the project ran on TPU
    ops = []
    s1.last_plan.foreach(lambda e: ops.append((type(e).__name__,
                                               e.placement)))
    assert ("ProjectExec", "tpu") in ops, ops
    # oracle spot check
    a = tb.column("a").to_pylist()
    b = tb.column("b").to_pylist()
    assert t1.column("and_").to_pylist()[:5] == \
        [x & y for x, y in zip(a[:5], b[:5])]


def test_shifts_follow_java_masking():
    tb = pa.table({
        "v": pa.array([1, -8, 2**40, -1], type=pa.int64()),
        "s": pa.array([1, 2, 65, 63], type=pa.int32()),
    })

    def q(s):
        df = s.create_dataframe(tb)
        return df.select(
            F.shiftleft(col("v"), col("s")).alias("shl"),
            F.shiftright(col("v"), col("s")).alias("shr"),
            F.shiftrightunsigned(col("v"), col("s")).alias("shru"),
        ).collect()

    (_, t1), (_, t2) = _both(q)
    for n in ("shl", "shr", "shru"):
        assert t1.column(n).to_pylist() == t2.column(n).to_pylist(), n
    # Java masks long shifts by 63: shift of 65 acts as shift of 1
    assert t1.column("shl").to_pylist()[2] == (2**40) << 1
    # arithmetic vs logical right shift of a negative number
    assert t1.column("shr").to_pylist()[3] == -1       # sign-extends
    assert t1.column("shru").to_pylist()[3] == 1       # zero-fills


def test_extended_math_differential():
    rng = np.random.default_rng(12)
    tb = pa.table({"x": pa.array(rng.uniform(1.1, 5.0, 200))})

    def q(s):
        df = s.create_dataframe(tb)
        return df.select(F.cot(col("x")).alias("cot"),
                         F.asinh(col("x")).alias("asinh"),
                         F.acosh(col("x")).alias("acosh"),
                         F.log_base(F.lit(2.0), col("x")).alias("lg2"),
                         ).collect()

    (_, t1), (_, t2) = _both(q)
    for n in ("cot", "asinh", "acosh", "lg2"):
        np.testing.assert_allclose(np.array(t1.column(n)),
                                   np.array(t2.column(n)), rtol=1e-12)
    xs = tb.column("x").to_pylist()
    np.testing.assert_allclose(t1.column("lg2").to_pylist()[:3],
                               [math.log2(v) for v in xs[:3]], rtol=1e-12)


def test_ascii_and_host_fallback_families_documented():
    tb = pa.table({"s": pa.array(["Abc", "", "zoo", None])})

    def q(s):
        df = s.create_dataframe(tb)
        return df.select(F.ascii(col("s")).alias("a")).collect()

    (_, t1), (_, t2) = _both(q)
    assert t1.column("a").to_pylist() == [65, 0, 122, None]
    assert t1.column("a").to_pylist() == t2.column("a").to_pylist()

    # regex/json/md5 rules exist with a documented host-fallback reason
    from spark_rapids_tpu.expr.regex import RLike, StringSplit
    from spark_rapids_tpu.expr.json_expr import GetJsonObject
    from spark_rapids_tpu.expr.hashfns import Md5
    from spark_rapids_tpu.plan.overrides import EXPR_RULES
    for c in (RLike, StringSplit, GetJsonObject, Md5):
        assert c in EXPR_RULES, c
        assert EXPR_RULES[c].tag_fn is not None


def test_ascii_decodes_multibyte_first_char():
    tb = pa.table({"s": pa.array(["A", "é", "中", "😀", ""])})

    def q(s):
        df = s.create_dataframe(tb)
        return df.select(F.ascii(col("s")).alias("a")).collect()

    (_, t1), (_, t2) = _both(q)
    want = [ord("A"), ord("é"), ord("中"), ord("😀"), 0]
    assert t1.column("a").to_pylist() == want
    assert t2.column("a").to_pylist() == want


def test_udf_kwonly_defaults_and_inner_lambda_keying():
    """kw-only default changes must MISS; re-created UDFs containing an
    inner lambda must still HIT (code-review round-3 findings)."""
    from spark_rapids_tpu import types as t
    from spark_rapids_tpu.api.functions import udf
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec.base import jit_cache_size

    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    tb = pa.table({"v": pa.array([1, 2], type=pa.int64())})
    df = s.create_dataframe(tb)

    def make(m):
        def f(x, *, mult=m):
            return x * mult
        return udf(f, t.LONG)

    assert df.select(make(2)(col("v")).alias("y")).collect() \
        .column("y").to_pylist() == [2, 4]
    assert df.select(make(3)(col("v")).alias("y")).collect() \
        .column("y").to_pylist() == [3, 6]

    def make_inner():
        return udf(lambda x: (lambda y: y + 1)(x) * 2, t.LONG)

    df.select(make_inner()(col("v")).alias("y")).collect()
    n = jit_cache_size()
    out = df.select(make_inner()(col("v")).alias("y")).collect()
    assert jit_cache_size() == n        # inner-lambda UDF reused
    assert out.column("y").to_pylist() == [4, 6]
