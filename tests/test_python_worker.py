"""Out-of-process Python UDF worker tests (ref python/rapids/worker.py,
daemon.py, PythonWorkerSemaphore.scala, GpuArrowEvalPythonExec worker
exchange): correctness through the worker, crash containment, unpicklable
fallback, and the pool/semaphore discipline."""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.udf.worker import (PythonWorkerCrash,
                                         PythonWorkerError,
                                         PythonWorkerPool,
                                         task_map_in_pandas)


def _session(**extra):
    b = TpuSession.builder().config("spark.rapids.sql.enabled", True)
    for k, v in extra.items():
        b = b.config(k, v)
    return b.get_or_create()


def _table(n=200):
    rng = np.random.default_rng(3)
    return pa.table({"k": pa.array(rng.integers(0, 5, n).astype(np.int64)),
                     "v": pa.array(rng.integers(0, 50, n).astype(np.int64))})


def _double(it):
    for pdf in it:
        pdf = pdf.copy()
        pdf["v"] = pdf["v"] * 2
        yield pdf


def test_map_in_pandas_runs_in_worker_process():
    s = _session()
    pool = PythonWorkerPool.get(2)
    before = pool.spawned
    tb = _table()
    out = (s.create_dataframe(tb, num_partitions=2)
           .mapInPandas(_double, "k long, v long").collect())
    assert sorted(out.column("v").to_pylist()) == \
        sorted((2 * v for v in tb.column("v").to_pylist()))
    pool = PythonWorkerPool.get(2)
    # at least one real subprocess served the request
    assert pool.spawned >= max(before, 1)
    served = sum(w.requests_served for w in pool._idle)
    assert served >= 1


def _crash(it):
    for i, pdf in enumerate(it):
        os._exit(17)  # simulate an OOM-killed / segfaulted worker
        yield pdf


def test_worker_crash_is_contained_and_pool_recovers():
    s = _session()
    tb = _table()
    df = s.create_dataframe(tb, num_partitions=1)
    with pytest.raises(PythonWorkerCrash):
        df.mapInPandas(_crash, "k long, v long").collect()
    # the engine survives: the same session runs the next query through a
    # fresh worker
    out = df.mapInPandas(_double, "k long, v long").collect()
    assert out.num_rows == tb.num_rows
    # and non-UDF queries are untouched
    agg = df.group_by(col("k")).agg(F.count("*").alias("c")).collect()
    assert sum(agg.column("c").to_pylist()) == tb.num_rows


def _raise_value_error(it):
    for pdf in it:
        raise ValueError("bad udf логика")
        yield pdf


def test_udf_exception_carries_traceback_not_crash():
    s = _session()
    df = s.create_dataframe(_table(), num_partitions=1)
    with pytest.raises(PythonWorkerError, match="bad udf"):
        df.mapInPandas(_raise_value_error, "k long, v long").collect()
    # worker survives a UDF exception (no respawn needed)
    out = df.mapInPandas(_double, "k long, v long").collect()
    assert out.num_rows > 0


def test_unpicklable_udf_falls_back_in_process():
    import threading
    lock = threading.Lock()  # unpicklable closure cell

    def with_lock(it):
        for pdf in it:
            with lock:
                yield pdf

    s = _session()
    tb = _table()
    out = (s.create_dataframe(tb, num_partitions=1)
           .mapInPandas(with_lock, "k long, v long").collect())
    assert out.num_rows == tb.num_rows


def test_worker_disabled_conf_stays_in_process():
    s = _session(**{"spark.rapids.sql.python.worker.enabled": False})
    pool = PythonWorkerPool._instance
    before = pool.spawned if pool else 0
    tb = _table()
    out = (s.create_dataframe(tb, num_partitions=1)
           .mapInPandas(_double, "k long, v long").collect())
    assert out.num_rows == tb.num_rows
    after = PythonWorkerPool._instance.spawned \
        if PythonWorkerPool._instance else 0
    assert after == before


def test_pool_bounds_and_reuses_workers():
    # private pool (the process-global one accumulates counts from other
    # tests, including the deliberate crash)
    pool = PythonWorkerPool(2)
    import pyarrow as _pa
    schema = _pa.schema([("x", _pa.int64())])
    tb = _pa.table({"x": _pa.array([1, 2, 3], type=_pa.int64())})

    def ident(it):
        yield from it

    for _ in range(5):
        tables, _ = pool.run(task_map_in_pandas, (ident, schema), [tb])
        assert tables[0].column("x").to_pylist() == [1, 2, 3]
    # five sequential requests reuse one worker, never exceeding the cap
    assert len(pool._idle) <= 2
    assert pool.spawned <= 2
    pool.shutdown()


def test_grouped_and_agg_and_cogroup_through_worker():
    s = _session()
    tb = _table(120)
    df = s.create_dataframe(tb, num_partitions=2)

    def center(pdf):
        pdf = pdf.copy()
        pdf["v"] = pdf["v"] - pdf["v"].mean()
        return pdf

    got = df.group_by(col("k")).applyInPandas(center, "k long, v double") \
        .collect()
    assert got.num_rows == tb.num_rows

    from spark_rapids_tpu import types as t
    sum_udf = F.pandas_udf(lambda v: float(v.sum()), t.DOUBLE,
                           functionType="grouped_agg")
    sums = df.group_by(col("k")).agg(
        sum_udf(col("v")).alias("s")).collect()
    want = {}
    for k, v in zip(tb.column("k").to_pylist(), tb.column("v").to_pylist()):
        want[k] = want.get(k, 0) + v
    got_map = dict(zip(sums.column("k").to_pylist(),
                       sums.column("s").to_pylist()))
    assert got_map == {k: float(v) for k, v in want.items()}


def test_row_udf_through_worker_matches_in_process():
    tb = _table(90)

    def plus_one(x):
        return x + 1

    from spark_rapids_tpu import types as t
    from spark_rapids_tpu.api.functions import udf

    s1 = _session()
    f1 = udf(plus_one, t.LONG)
    out_w = (s1.create_dataframe(tb).select(
        col("k"), f1(col("v")).alias("v1")).collect())
    s2 = _session(**{"spark.rapids.sql.python.worker.enabled": False})
    out_i = (s2.create_dataframe(tb).select(
        col("k"), f1(col("v")).alias("v1")).collect())
    assert out_w.column("v1").to_pylist() == out_i.column("v1").to_pylist()


def _printing(it):
    for pdf in it:
        print("debug output that must not corrupt the protocol")
        yield pdf


def test_udf_print_does_not_corrupt_protocol():
    """The framing rides the worker's stdout; user print() is rebound to
    stderr so debugging output cannot poison the stream."""
    s = _session()
    tb = _table()
    out = (s.create_dataframe(tb, num_partitions=1)
           .mapInPandas(_printing, "k long, v long").collect())
    assert out.num_rows == tb.num_rows


def _stateful_sum(it):
    # carries state across batches: only valid if fn is called ONCE per
    # partition with a true iterator (the mapInPandas contract)
    total = 0
    for pdf in it:
        total += int(pdf["v"].sum())
        yield pdf.iloc[:0]
    import pandas as pd
    yield pd.DataFrame({"k": [0], "v": [total]})


def test_map_in_pandas_streams_once_per_partition():
    s = _session()
    tb = _table(100)
    out = (s.create_dataframe(tb, num_partitions=1, )
           .mapInPandas(_stateful_sum, "k long, v long").collect())
    assert out.column("v").to_pylist() == [sum(tb.column("v").to_pylist())]


def _inc(it):
    for pdf in it:
        pdf = pdf.copy()
        pdf["v"] = pdf["v"] + 1
        yield pdf


def test_stacked_map_in_pandas_does_not_deadlock():
    """Three chained streaming UDF stages with a 2-permit pool: nested
    borrows (a feeder driving upstream execs) bypass the semaphore, so a
    single stacked query can never deadlock against itself."""
    s = _session()
    tb = _table(60)
    df = s.create_dataframe(tb, num_partitions=1)
    out = (df.mapInPandas(_inc, "k long, v long")
           .mapInPandas(_inc, "k long, v long")
           .mapInPandas(_inc, "k long, v long").collect())
    assert sorted(out.column("v").to_pylist()) == \
        sorted(v + 3 for v in tb.column("v").to_pylist())


def _boom_iter():
    raise RuntimeError("upstream source exploded")


def test_upstream_iterator_error_propagates_not_hangs():
    """An error in the INPUT iterator of a streaming request surfaces as
    an exception (with the stream cleanly terminated) instead of hanging
    both processes."""
    from spark_rapids_tpu.udf.worker import (PythonWorkerPool,
                                             task_stream_map_in_pandas)
    import pyarrow as _pa
    pool = PythonWorkerPool(1)
    schema = _pa.schema([("x", _pa.int64())])

    def bad_iter():
        yield _pa.table({"x": _pa.array([1], type=_pa.int64())})
        raise RuntimeError("upstream source exploded")

    def ident(it):
        yield from it

    with pytest.raises(RuntimeError, match="upstream source exploded"):
        list(pool.run_stream(task_stream_map_in_pandas,
                             (ident, schema), bad_iter()))
    # pool permit was released; next request succeeds
    tables, _ = pool.run(task_map_in_pandas, (ident, schema),
                         [_pa.table({"x": _pa.array([2], type=_pa.int64())})])
    assert tables[0].column("x").to_pylist() == [2]
    pool.shutdown()
