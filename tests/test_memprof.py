"""HBM observatory tests (obs/memprof.py): timeline algebra against
the spill catalog, ring-buffer bounds under churn, per-tenant
attribution exactness under thread stress, the failure black box
(obs/postmortem.py + `tools postmortem`), and the disabled no-op path.

Everything runs in the shared tier-1 process, so every test restores
the process-global MemoryTimeline singleton it reconfigures."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.device import batch_to_device
from spark_rapids_tpu.memory.spill import SpillCatalog
from spark_rapids_tpu.obs import memprof
from spark_rapids_tpu.obs.memprof import (SHUFFLE_BLOCK, WORKING_SET,
                                          MemoryTimeline,
                                          active_timeline)


@pytest.fixture
def fresh_timeline():
    MemoryTimeline.reset_for_tests()
    tl = MemoryTimeline.configure(enabled=True)
    yield tl
    MemoryTimeline.reset_for_tests()


def _batch(n=500, seed=0):
    rng = np.random.default_rng(seed)
    rb = pa.record_batch({
        "a": pa.array(rng.integers(0, 100, n).astype(np.int64)),
        "b": pa.array(rng.random(n))})
    return batch_to_device(rb, xp=np)


# -- timeline algebra ---------------------------------------------------------

def test_timeline_reconciles_with_spill_catalog(tmp_path,
                                                fresh_timeline):
    """At every lifecycle step the timeline's spill-backed live bytes
    must equal the catalog's registered device bytes, and the sample
    deltas must sum to the final per-(tenant, class) live values —
    the three-sinks invariant the --hbm gate replays end to end."""
    tl = fresh_timeline
    cat = SpillCatalog(device_budget=1 << 30, host_budget=1 << 30,
                       spill_dir=str(tmp_path))
    memprof.push_context("tenant-a", "q1")
    try:
        sbs = [cat.register(_batch(seed=i)) for i in range(3)]
        assert cat.device_bytes_registered() > 0
        assert tl.spill_backed_bytes() == cat.device_bytes_registered()
        sbs[0].spill_to_host()
        assert tl.spill_backed_bytes() == cat.device_bytes_registered()
        back = sbs[0].get_batch(np)     # unspill: bytes return
        assert back is not None
        assert tl.spill_backed_bytes() == cat.device_bytes_registered()
        for sb in sbs:
            sb.close()
        assert cat.device_bytes_registered() == 0
        assert tl.spill_backed_bytes() == 0
    finally:
        memprof.pop_context()
    sums = {}
    for s in tl.window(10_000):
        key = (s["tenant"], s["class"])
        sums[key] = sums.get(key, 0) + s["delta"]
    for (tenant, cls), total in sums.items():
        assert total == tl.live_bytes(bclass=cls, tenant=tenant)


def test_arena_algebra_and_reset(fresh_timeline):
    """Arena fills book as used-after deltas (alignment padding
    reconciles exactly); reset returns every tenant's staging bytes."""
    tl = fresh_timeline
    memprof.push_context("tenant-b", "q2")
    try:
        tl.on_arena_alloc("ar1", 1024, 1 << 20)
        tl.on_arena_alloc("ar1", 3072, 1 << 20)
        assert tl.arena_bytes() == 3072
        rep = tl.report()
        assert rep["tenants"]["tenant-b"]["arena_staging_bytes"] == 3072
        # staging bytes are not device residency
        assert rep["tenants"]["tenant-b"]["resident_bytes"] == 0
        tl.on_arena_reset("ar1")
        assert tl.arena_bytes() == 0
    finally:
        memprof.pop_context()


def test_report_occupancy_split(fresh_timeline):
    """pinned vs demotable vs closed-pending split and the per-tenant
    demotable peak used by bench --serve."""
    tl = fresh_timeline
    memprof.push_context("t", "q")
    try:
        tl.on_alloc("h1", 1000, SHUFFLE_BLOCK)
        tl.on_alloc("h2", 2000, WORKING_SET)
        tl.on_pin("h3", 4000)
        tl.on_broadcast("h4", 8000)
        row = tl.report()["tenants"]["t"]
        assert row["demotable_bytes"] == 3000
        assert row["pinned_bytes"] == 4000
        assert row["closed_pending_bytes"] == 8000
        assert row["resident_bytes"] == 15000
        assert row["peak_demotable_bytes"] == 3000
        tl.on_close("h1")
        tl.on_close("h2")
        row = tl.report()["tenants"]["t"]
        assert row["demotable_bytes"] == 0
        assert row["peak_demotable_bytes"] == 3000   # peak survives
    finally:
        memprof.pop_context()


def test_admission_tickets_tracked(fresh_timeline):
    tl = fresh_timeline
    tl.note_ticket("t", 5000)
    tl.note_ticket("t", 2500)      # reprice up
    assert tl.report()["tenants"]["t"]["admitted_bytes"] == 7500
    tl.note_ticket("t", -7500)     # release zeroes out
    assert "t" not in tl.report()["tenants"]


# -- ring-buffer bounds -------------------------------------------------------

def test_ring_buffer_bounded_under_churn():
    MemoryTimeline.reset_for_tests()
    try:
        tl = MemoryTimeline.configure(enabled=True, max_samples=64)
        memprof.push_context("churn", "q")
        try:
            for i in range(500):
                tl.on_alloc(f"h{i}", 128, WORKING_SET)
                tl.on_close(f"h{i}")
        finally:
            memprof.pop_context()
        assert tl.sample_count() <= 64
        assert tl.samples_dropped > 0
        assert tl.live_bytes() == 0      # churn closed everything
        # the window holds the MOST RECENT samples
        assert tl.window(64)[-1]["delta"] == -128
    finally:
        MemoryTimeline.reset_for_tests()


def test_max_samples_clamped_to_floor():
    MemoryTimeline.reset_for_tests()
    try:
        tl = MemoryTimeline.configure(enabled=True, max_samples=1)
        assert tl.max_samples == 64
    finally:
        MemoryTimeline.reset_for_tests()


# -- per-tenant attribution under thread stress -------------------------------

def test_per_tenant_attribution_exact_under_threads(fresh_timeline):
    """8 threads booking under 4 tenants concurrently: every tenant's
    final occupancy must equal its own allocations exactly — no
    cross-tenant bleed, no unattributed events."""
    tl = fresh_timeline
    n_threads, per = 8, 50

    def worker(i):
        tenant = f"t{i % 4}"
        memprof.push_context(tenant, f"q{i}")
        try:
            for j in range(per):
                hid = f"h-{i}-{j}"
                tl.on_alloc(hid, 1000, SHUFFLE_BLOCK)
                if j % 2:
                    tl.on_close(hid)
        finally:
            memprof.pop_context()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rep = tl.report()
    # 2 threads per tenant, each leaving 25 of 50 allocations live
    for tenant in ("t0", "t1", "t2", "t3"):
        assert rep["tenants"][tenant]["demotable_bytes"] == 2 * 25 * 1000
    assert rep["unattributed_events"] == 0
    assert rep["total_bytes"] == 4 * 2 * 25 * 1000


def test_context_free_thread_counts_as_unattributed(fresh_timeline):
    tl = fresh_timeline
    done = []

    def rogue():
        tl.on_alloc("rogue-h", 512, WORKING_SET)
        done.append(True)

    t = threading.Thread(target=rogue)
    t.start()
    t.join()
    assert done
    rep = tl.report()
    assert rep["unattributed_events"] == 1
    assert rep["tenants"][memprof.UNATTRIBUTED_TENANT][
        "demotable_bytes"] == 512


def test_context_stack_nests(fresh_timeline):
    memprof.push_context("outer", "q1")
    memprof.push_context("inner", "q2")
    assert memprof.current_context() == ("inner", "q2")
    memprof.pop_context()
    assert memprof.current_context() == ("outer", "q1")
    memprof.pop_context()
    assert memprof.current_context() is None


# -- failure black box --------------------------------------------------------

def test_postmortem_bundle_on_injected_failure(tmp_path, capsys):
    """An injected operator failure must leave exactly one bundle that
    parses, names FilterExec as the culprit with the owning tenant and
    HBM occupancy, and renders through `tools postmortem`."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec import basic as exec_basic
    from spark_rapids_tpu.exec.base import _wrap_execute_partition
    from spark_rapids_tpu.obs import postmortem as pm
    from spark_rapids_tpu.tools.__main__ import main as tools_main

    MemoryTimeline.reset_for_tests()
    try:
        s = TpuSession({
            "spark.rapids.sql.enabled": "true",
            "spark.rapids.tpu.trace.enabled": "true",
            "spark.rapids.tpu.singleChipFuse": "off",
            "spark.rapids.tpu.hbm.postmortem.dir": str(tmp_path),
        })
        s._tenant = "tenant-pm"
        tb = pa.table({
            "k": pa.array(np.arange(400, dtype=np.int64) % 7),
            "v": pa.array(np.arange(400, dtype=np.int64)),
        })
        real = exec_basic.FilterExec.execute_partition

        def boom(self, pid, ctx):
            # generator: raises at first pull, inside FilterExec's span
            raise RuntimeError("injected failure for postmortem test")
            yield

        exec_basic.FilterExec.execute_partition = \
            _wrap_execute_partition(boom)
        try:
            from spark_rapids_tpu.api import functions as F
            from spark_rapids_tpu.api.column import col
            with pytest.raises(RuntimeError, match="injected failure"):
                (s.create_dataframe(tb)
                 .filter(col("v") >= 0)
                 .group_by(col("k"))
                 .agg(F.sum(col("v")).alias("sv"))
                 .collect())
        finally:
            exec_basic.FilterExec.execute_partition = real

        bundles = pm.list_bundles(str(tmp_path))
        assert len(bundles) == 1
        doc = pm.load_bundle(bundles[0])
        assert doc["version"] == pm.BUNDLE_VERSION
        assert doc["kind"] == "query_failure"
        assert doc["tenant"] == "tenant-pm"
        assert "injected failure" in doc["error"]["message"]
        assert "FilterExec" in doc["failing_operator"]["operator"]
        assert "report" in doc["hbm"]
        # renders through the CLI, naming the culprit and the tenant
        rc = tools_main(["postmortem", str(tmp_path)])
        assert not rc
        out = capsys.readouterr().out
        assert "FilterExec" in out
        assert "tenant-pm" in out
    finally:
        MemoryTimeline.reset_for_tests()


def test_postmortem_retention_cap(tmp_path):
    from spark_rapids_tpu.obs import postmortem as pm
    paths = [pm.dump_postmortem(str(tmp_path), RuntimeError(f"e{i}"),
                                max_bundles=2)
             for i in range(5)]
    assert all(p is not None for p in paths)
    kept = pm.list_bundles(str(tmp_path))
    assert len(kept) == 2
    # the newest bundles survive the cap
    assert sorted(kept) == sorted(paths[-2:])


def test_postmortem_classifies_admission_timeout(tmp_path):
    from spark_rapids_tpu.memory.admission import AdmissionTimeout
    from spark_rapids_tpu.obs import postmortem as pm
    path = pm.dump_postmortem(str(tmp_path),
                              AdmissionTimeout("budget exhausted"))
    doc = pm.load_bundle(path)
    assert doc["kind"] == "admission_timeout"


# -- disabled no-op path ------------------------------------------------------

def test_disabled_path_is_noop(tmp_path):
    MemoryTimeline.reset_for_tests()
    try:
        tl = MemoryTimeline.configure(enabled=False)
        assert active_timeline() is None
        cat = SpillCatalog(device_budget=1 << 30, host_budget=1 << 30,
                           spill_dir=str(tmp_path))
        memprof.push_context("t", "q")
        try:
            sb = cat.register(_batch())
            # the hook sites saw a disabled timeline: nothing recorded
            assert tl.sample_count() == 0
            assert tl.live_bytes() == 0
            sb.close()
        finally:
            memprof.pop_context()
        rep = tl.report()
        assert rep["enabled"] is False
        assert rep["total_bytes"] == 0
        assert rep["tenants"] == {}
    finally:
        MemoryTimeline.reset_for_tests()
