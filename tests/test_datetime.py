"""Date/time expression tests vs python datetime oracles
(model: integration_tests/date_time_test.py)."""

import datetime

import pyarrow as pa

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import Column, col, lit
from spark_rapids_tpu.expr import datetime_expr as D
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect)
from spark_rapids_tpu.testing.data_gen import DateGen, TimestampGen, gen_df

_DATES = [datetime.date(2024, 2, 29), datetime.date(1970, 1, 1),
          datetime.date(1969, 12, 31), datetime.date(2000, 12, 31),
          None, datetime.date(1582, 10, 15), datetime.date(2038, 1, 19)]


def _df(spark):
    return spark.create_dataframe(pa.table({
        "d": pa.array(_DATES, type=pa.date32()),
        "n": pa.array(list(range(len(_DATES))), type=pa.int32())}))


def test_extract_fields():
    def q(spark):
        return _df(spark).select(
            F.year(col("d")).alias("y"),
            F.month(col("d")).alias("m"),
            F.dayofmonth(col("d")).alias("dm"),
            Column(D.DayOfWeek(col("d").expr)).alias("dw"),
            Column(D.DayOfYear(col("d").expr)).alias("dy"),
            Column(D.Quarter(col("d").expr)).alias("q"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("y").to_pylist() == \
        [None if d is None else d.year for d in _DATES]
    assert tpu.column("m").to_pylist() == \
        [None if d is None else d.month for d in _DATES]
    assert tpu.column("dm").to_pylist() == \
        [None if d is None else d.day for d in _DATES]
    # Spark: Sunday=1..Saturday=7; python weekday(): Monday=0
    assert tpu.column("dw").to_pylist() == \
        [None if d is None else ((d.weekday() + 1) % 7) + 1 for d in _DATES]
    assert tpu.column("dy").to_pylist() == \
        [None if d is None else d.timetuple().tm_yday for d in _DATES]


def test_date_arithmetic():
    def q(spark):
        return _df(spark).select(
            Column(D.DateAdd(col("d").expr, lit(10).expr)).alias("pa"),
            Column(D.DateSub(col("d").expr, lit(10).expr)).alias("mi"),
            Column(D.AddMonths(col("d").expr, lit(1).expr)).alias("am"),
            Column(D.LastDay(col("d").expr)).alias("ld"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("pa").to_pylist() == \
        [None if d is None else d + datetime.timedelta(days=10)
         for d in _DATES]
    # add_months clamps to month end (e.g. Jan 31 + 1 = Feb 29)
    assert tpu.column("am").to_pylist()[0] == datetime.date(2024, 3, 29)
    assert tpu.column("ld").to_pylist()[0] == datetime.date(2024, 2, 29)


def test_timestamp_fields():
    ts = [datetime.datetime(2024, 6, 15, 13, 45, 59, 123456,
                            tzinfo=datetime.timezone.utc),
          datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc),
          None]

    def q(spark):
        df = spark.create_dataframe(pa.table(
            {"t": pa.array(ts, type=pa.timestamp("us", tz="UTC"))}))
        return df.select(
            Column(D.Hour(col("t").expr)).alias("h"),
            Column(D.Minute(col("t").expr)).alias("mi"),
            Column(D.Second(col("t").expr)).alias("s"),
            F.year(col("t")).alias("y"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("h").to_pylist() == [13, 0, None]
    assert tpu.column("mi").to_pylist() == [45, 0, None]
    assert tpu.column("s").to_pylist() == [59, 0, None]
    assert tpu.column("y").to_pylist() == [2024, 1970, None]


def test_datetime_fuzz_differential():
    def q(spark):
        df = gen_df(spark, [("d", DateGen()), ("t", TimestampGen())],
                    length=512)
        return df.select(
            F.year(col("d")).alias("yd"), F.month(col("d")).alias("md"),
            F.dayofmonth(col("d")).alias("dd"),
            F.year(col("t")).alias("yt"),
            Column(D.DateDiff(col("d").expr, lit(
                datetime.date(2000, 1, 1)).expr)).alias("dd2"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_murmur3_consistency():
    """hash() must agree between engines (partitioning correctness)."""
    from spark_rapids_tpu.testing.data_gen import (IntegerGen, LongGen,
                                                   StringGen, DoubleGen)

    def q(spark):
        df = gen_df(spark, [("i", IntegerGen()), ("l", LongGen()),
                            ("s", StringGen(max_len=12)),
                            ("f", DoubleGen())], length=512)
        return df.select(F.hash(col("i")).alias("hi"),
                         F.hash(col("l")).alias("hl"),
                         F.hash(col("s")).alias("hs"),
                         F.hash(col("f")).alias("hf"),
                         F.hash(col("i"), col("s"), col("l")).alias("hm"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_murmur3_known_values():
    """Spark-published murmur3 results: hash of int 0 with seed 42 etc.
    (values computed from the Murmur3_x86_32 spec)."""
    def q(spark):
        df = spark.create_dataframe(pa.table(
            {"i": pa.array([0, 1, 42], type=pa.int32())}))
        return df.select(F.hash(col("i")).alias("h"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    # reference Murmur3_x86_32(le32(v), seed=42) values
    import struct

    def mmh3_32(data: bytes, seed: int) -> int:
        c1, c2 = 0xCC9E2D51, 0x1B873593
        h = seed & 0xFFFFFFFF
        for i in range(0, len(data) - len(data) % 4, 4):
            k = struct.unpack_from("<I", data, i)[0]
            k = (k * c1) & 0xFFFFFFFF
            k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
            k = (k * c2) & 0xFFFFFFFF
            h ^= k
            h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
            h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
        h ^= len(data)
        h ^= h >> 16
        h = (h * 0x85EBCA6B) & 0xFFFFFFFF
        h ^= h >> 13
        h = (h * 0xC2B2AE35) & 0xFFFFFFFF
        h ^= h >> 16
        return h - (1 << 32) if h >= (1 << 31) else h
    exp = [mmh3_32(struct.pack("<i", v), 42) for v in [0, 1, 42]]
    assert tpu.column("h").to_pylist() == exp
