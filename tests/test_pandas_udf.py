"""Pandas UDF operator family tests (ref udf_test.py + the
GpuMapInPandas/FlatMapGroupsInPandas/AggregateInPandas/
FlatMapCoGroupsInPandas execs)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as t
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _session():
    return TpuSession.builder().config("spark.rapids.sql.enabled",
                                       True).get_or_create()


def _table(n=300):
    rng = np.random.default_rng(0)
    return pa.table({"k": pa.array(rng.integers(0, 8, n).astype(np.int64)),
                     "v": pa.array(rng.integers(0, 100, n).astype(np.int64)),
                     "f": pa.array(rng.random(n))})


def test_map_in_pandas():
    s = _session()
    tb = _table()

    def double_v(it):
        for pdf in it:
            pdf = pdf.copy()
            pdf["v"] = pdf["v"] * 2
            yield pdf[["k", "v"]]

    out = (s.create_dataframe(tb, num_partitions=3)
           .mapInPandas(double_v, "k long, v long").collect())
    assert out.num_rows == 300
    assert sorted(out.column("v").to_pylist()) == \
        sorted((tb.column("v").to_numpy() * 2).tolist())


def test_apply_in_pandas_grouped_map():
    s = _session()
    tb = _table()

    def center(pdf: pd.DataFrame) -> pd.DataFrame:
        pdf = pdf.copy()
        pdf["v"] = pdf["v"] - pdf["v"].mean()
        return pdf[["k", "v"]]

    out = (s.create_dataframe(tb, num_partitions=4)
           .group_by(col("k")).applyInPandas(center, "k long, v double")
           .collect())
    assert out.num_rows == 300
    # per-group means of the centered values are ~0
    got = pa.TableGroupBy(out, ["k"], use_threads=False).aggregate(
        [("v", "mean")])
    assert all(abs(m) < 1e-9 for m in got.column("v_mean").to_pylist())


def test_grouped_agg_pandas_udf():
    s = _session()
    tb = _table()
    mean_udf = F.pandas_udf(lambda v: float(v.mean()), t.DOUBLE,
                            functionType="grouped_agg")
    out = (s.create_dataframe(tb, num_partitions=3)
           .group_by(col("k"))
           .agg(mean_udf(col("f")).alias("mf"))
           .collect().sort_by("k"))
    want = pa.TableGroupBy(tb, ["k"], use_threads=False).aggregate(
        [("f", "mean")]).sort_by("k")
    assert out.column("k").to_pylist() == want.column("k").to_pylist()
    np.testing.assert_allclose(np.array(out.column("mf")),
                               np.array(want.column("f_mean")), rtol=1e-12)


def test_grouped_agg_global():
    s = _session()
    tb = _table()
    sum_udf = F.pandas_udf(lambda v: int(v.sum()), t.LONG,
                           functionType="grouped_agg")
    out = s.create_dataframe(tb).group_by().agg(
        sum_udf(col("v")).alias("sv")).collect()
    assert out.column("sv").to_pylist() == [int(tb.column("v").to_numpy()
                                               .sum())]


def test_cogroup_apply_in_pandas():
    s = _session()
    left = pa.table({"k": pa.array([1, 1, 2, 3], type=pa.int64()),
                     "v": pa.array([10, 11, 20, 30], type=pa.int64())})
    right = pa.table({"k": pa.array([1, 2, 2, 4], type=pa.int64()),
                      "w": pa.array([100, 200, 201, 400], type=pa.int64())})

    def summarize(lpdf: pd.DataFrame, rpdf: pd.DataFrame) -> pd.DataFrame:
        k = lpdf["k"].iloc[0] if len(lpdf) else rpdf["k"].iloc[0]
        return pd.DataFrame({"k": [k],
                             "lsum": [int(lpdf["v"].sum()) if len(lpdf)
                                      else 0],
                             "rsum": [int(rpdf["w"].sum()) if len(rpdf)
                                      else 0]})

    ldf = s.create_dataframe(left, num_partitions=2)
    rdf = s.create_dataframe(right, num_partitions=3)
    out = (ldf.group_by(col("k")).cogroup(rdf.group_by(col("k")))
           .applyInPandas(summarize, "k long, lsum long, rsum long")
           .collect().sort_by("k"))
    assert out.column("k").to_pylist() == [1, 2, 3, 4]
    assert out.column("lsum").to_pylist() == [21, 20, 30, 0]
    assert out.column("rsum").to_pylist() == [100, 401, 0, 400]


def test_mixing_pandas_agg_with_builtin_raises():
    s = _session()
    mean_udf = F.pandas_udf(lambda v: float(v.mean()), t.DOUBLE,
                            functionType="grouped_agg")
    df = s.create_dataframe(_table())
    with pytest.raises(TypeError):
        df.group_by(col("k")).agg(mean_udf(col("f")),
                                  F.count("*").alias("c"))
