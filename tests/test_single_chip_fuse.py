"""Single-chip exchange collapse (spark.rapids.tpu.singleChipFuse).

On one chip an N-partition exchange buys no parallelism — it costs N
serial per-partition programs.  With fuse forced 'on', partial->exchange->
final aggregates, co-partitioned shuffled joins, range-partitioned global
sorts and hash-partitioned windows must all absorb their exchanges into
ONE fused stage, with results identical to the CPU engine (the analog of
the reference owning the shuffle underneath these stages,
ref RapidsShuffleInternalManagerBase.scala:205).
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.window import WindowBuilder


def _tables(n=20_000, nkeys=500):
    rng = np.random.default_rng(11)
    fact = pa.table({
        "k": pa.array(rng.integers(0, nkeys, n).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
        "f": pa.array(rng.random(n)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(nkeys, dtype=np.int64)),
        "w": pa.array(rng.integers(0, 10**6, nkeys).astype(np.int64)),
    })
    return fact, dim


def _session(fuse: str, enabled=True) -> TpuSession:
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", enabled)
            .config("spark.rapids.tpu.singleChipFuse", fuse)
            .get_or_create())


def _no_exchange(session, df):
    plan = session.prepare_plan(df._lp)
    names = []
    plan.foreach(lambda e: names.append(type(e).__name__))
    return "ShuffleExchangeExec" not in names, names


@pytest.fixture(scope="module")
def data():
    return _tables()


def test_fused_shuffled_join_plan_and_result(data):
    fact, dim = data
    s = _session("on")
    fdf = s.create_dataframe(fact, num_partitions=4)
    ddf = s.create_dataframe(dim, num_partitions=2)
    # integer weights make the grouped sums exactly comparable
    q = (fdf.join(ddf, on="k", how="inner")
         .group_by(col("k")).agg(F.sum(col("w")).alias("sw")))
    ok, names = _no_exchange(s, q)
    assert ok, f"exchange survived the fuse: {names}"
    got = q.collect().sort_by("k")

    c = _session("off", enabled=False)
    cf = c.create_dataframe(fact, num_partitions=4)
    cd = c.create_dataframe(dim, num_partitions=2)
    want = (cf.join(cd, on="k", how="inner")
            .group_by(col("k")).agg(F.sum(col("w")).alias("sw"))
            .collect().sort_by("k"))
    assert got.equals(want)


def test_fused_aggregate_plan_and_result(data):
    fact, _ = data
    s = _session("on")
    fdf = s.create_dataframe(fact, num_partitions=4)
    q = (fdf.filter(col("v") > 0).group_by(col("k"))
         .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("c")))
    ok, names = _no_exchange(s, q)
    assert ok, f"exchange survived the fuse: {names}"
    got = q.collect().sort_by("k")

    c = _session("off", enabled=False)
    cf = c.create_dataframe(fact, num_partitions=4)
    want = (cf.filter(col("v") > 0).group_by(col("k"))
            .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("c"))
            .collect().sort_by("k"))
    assert got.equals(want)


def test_fused_global_sort(data):
    fact, _ = data
    s = _session("on")
    fdf = s.create_dataframe(fact, num_partitions=4)
    q = fdf.sort(col("k"), col("v"))
    ok, names = _no_exchange(s, q)
    assert ok, f"exchange survived the fuse: {names}"
    got = q.collect()

    c = _session("off", enabled=False)
    want = (c.create_dataframe(fact, num_partitions=4)
            .sort(col("k"), col("v")).collect())
    assert got.equals(want)


def test_fused_window(data):
    fact, _ = data
    s = _session("on")
    fdf = s.create_dataframe(fact, num_partitions=4)
    w = WindowBuilder().partition_by(col("k")).order_by(col("v"))
    q = fdf.select(col("k"), col("v"),
                   F.row_number().over(w).alias("rn"),
                   F.sum(col("v")).over(w).alias("rs"))
    ok, names = _no_exchange(s, q)
    assert ok, f"exchange survived the fuse: {names}"
    order = [("k", "ascending"), ("v", "ascending"), ("rn", "ascending")]
    got = q.collect().sort_by(order)

    c = _session("off", enabled=False)
    cf = c.create_dataframe(fact, num_partitions=4)
    want = cf.select(col("k"), col("v"),
                     F.row_number().over(w).alias("rn"),
                     F.sum(col("v")).over(w).alias("rs")
                     ).collect().sort_by(order)
    assert got.equals(want)


def test_bare_repartition_not_fused(data):
    """A user-visible repartition keeps its exchange (partition count and
    key->partition mapping are observable, e.g. through partitioned
    writes and spark_partition_id)."""
    fact, _ = data
    s = _session("on")
    fdf = s.create_dataframe(fact, num_partitions=2)
    q = fdf.repartition(4, col("k"))
    ok, names = _no_exchange(s, q)
    assert not ok, f"repartition exchange must survive: {names}"


def test_auto_mode_multichip_keeps_exchanges(data):
    """conftest forces an 8-device CPU mesh, so 'auto' must keep the
    multi-partition exchange plan (fusion is a 1-device rewrite)."""
    import jax
    assert len(jax.devices()) > 1
    fact, _ = data
    s = _session("auto")
    fdf = s.create_dataframe(fact, num_partitions=4)
    q = fdf.filter(col("v") > 0).group_by(col("k")).agg(
        F.sum(col("v")).alias("sv"))
    ok, names = _no_exchange(s, q)
    assert not ok, f"auto fused on a multi-device mesh: {names}"
