"""Join differential tests (model: integration_tests/join_test.py)."""

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect)
from spark_rapids_tpu.testing.data_gen import (
    IntegerGen, LongGen, StringGen, gen_df)

ALL_JOINS = ["inner", "left", "right", "full", "left_semi", "left_anti"]


def _sides(spark, key_gen, length=256):
    a = gen_df(spark, [("k", key_gen), ("va", LongGen())],
               length=length, seed=10)
    b = gen_df(spark, [("k2", key_gen), ("vb", LongGen())],
               length=length // 2, seed=20)
    return a, b


@pytest.mark.parametrize("how", ALL_JOINS)
def test_equi_join_int_keys(how):
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=50))
        return a.join(b, on=(col("k") == col("k2")), how=how)
    assert_tpu_and_cpu_are_equal_collect(q)


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_equi_join_string_keys(how):
    def q(spark):
        a = gen_df(spark, [("k", StringGen(max_len=4)), ("va", LongGen())],
                   length=256, seed=1)
        b = gen_df(spark, [("k2", StringGen(max_len=4)), ("vb", LongGen())],
                   length=128, seed=2)
        return a.join(b, on=(col("k") == col("k2")), how=how)
    assert_tpu_and_cpu_are_equal_collect(q)


@pytest.mark.parametrize("how", ALL_JOINS)
def test_join_null_keys(how):
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=5, null_prob=0.4), 64)
        return a.join(b, on=(col("k") == col("k2")), how=how)
    assert_tpu_and_cpu_are_equal_collect(q)


def test_using_join():
    def q(spark):
        a = gen_df(spark, [("k", IntegerGen(lo=0, hi=20)),
                           ("va", LongGen())], length=128, seed=3)
        b = gen_df(spark, [("k", IntegerGen(lo=0, hi=20)),
                           ("vb", LongGen())], length=64, seed=4)
        return a.join(b, on="k", how="inner")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_multi_key_join():
    def q(spark):
        a = gen_df(spark, [("k1", IntegerGen(lo=0, hi=8)),
                           ("k2", IntegerGen(lo=0, hi=8)),
                           ("va", LongGen())], length=256, seed=5)
        b = gen_df(spark, [("j1", IntegerGen(lo=0, hi=8)),
                           ("j2", IntegerGen(lo=0, hi=8)),
                           ("vb", LongGen())], length=128, seed=6)
        return a.join(b, on=(col("k1") == col("j1")) &
                      (col("k2") == col("j2")), how="inner")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_conditional_inner_join():
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=20), 128)
        return a.join(b, on=(col("k") == col("k2")) &
                      (col("va") > col("vb")), how="inner")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_cross_join():
    def q(spark):
        a = gen_df(spark, [("x", IntegerGen())], length=30, seed=7)
        b = gen_df(spark, [("y", IntegerGen())], length=20, seed=8)
        return a.join(b, how="cross")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_join_then_aggregate():
    """Join feeding aggregation (the TPC-DS bread-and-butter shape)."""
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=30), 512)
        return (a.join(b, on=(col("k") == col("k2")), how="inner")
                 .group_by(col("k"))
                 .agg(F.sum(col("va")).alias("sa"),
                      F.count("*").alias("c")))
    assert_tpu_and_cpu_are_equal_collect(q)


# ---------------------------------------------------------------------------
# Broadcast joins (ref GpuBroadcastHashJoinExec / GpuBroadcastNestedLoopJoin)
# ---------------------------------------------------------------------------

def _plan_exec_names(df_fn, conf=None):
    from spark_rapids_tpu.testing.asserts import _TPU_CONF, _mk
    c = dict(conf or {})
    c.update(_TPU_CONF)
    session = _mk(c)
    df_fn(session).collect()
    names = []
    session.last_plan.foreach(lambda e: names.append(type(e).__name__))
    return names


@pytest.mark.parametrize("how", ["inner", "left", "right", "left_semi",
                                 "left_anti"])
def test_broadcast_hash_join(how):
    """Small build side over a partitioned probe side must broadcast."""
    def q(spark):
        a = gen_df(spark, [("k", IntegerGen(lo=0, hi=40)),
                           ("va", LongGen())],
                   length=512, seed=30, num_partitions=4)
        b = gen_df(spark, [("k2", IntegerGen(lo=0, hi=40)),
                           ("vb", LongGen())], length=64, seed=31)
        return a.join(b, on=(col("k") == col("k2")), how=how)
    assert_tpu_and_cpu_are_equal_collect(q)
    names = _plan_exec_names(q)
    assert "BroadcastHashJoinExec" in names, names
    assert "BroadcastExchangeExec" in names, names
    assert "ShuffleExchangeExec" not in names, names


def test_broadcast_disabled_by_threshold():
    """threshold=-1 must fall back to shuffled hash join."""
    conf = {"spark.rapids.sql.autoBroadcastJoinThreshold": "-1"}
    def q(spark):
        a = gen_df(spark, [("k", IntegerGen(lo=0, hi=40)),
                           ("va", LongGen())],
                   length=512, seed=32, num_partitions=4)
        b = gen_df(spark, [("k2", IntegerGen(lo=0, hi=40)),
                           ("vb", LongGen())], length=64, seed=33)
        return a.join(b, on=(col("k") == col("k2")), how="inner")
    assert_tpu_and_cpu_are_equal_collect(q, conf=conf)
    names = _plan_exec_names(q, conf)
    assert "BroadcastHashJoinExec" not in names, names
    assert "ShuffleExchangeExec" in names, names


def test_broadcast_nested_loop_join():
    def q(spark):
        a = gen_df(spark, [("x", IntegerGen(lo=0, hi=100))],
                   length=64, seed=34, num_partitions=3)
        b = gen_df(spark, [("y", IntegerGen(lo=0, hi=100))],
                   length=16, seed=35)
        return a.join(b, on=(col("x") > col("y")), how="inner")
    assert_tpu_and_cpu_are_equal_collect(q)
    names = _plan_exec_names(q)
    assert "BroadcastNestedLoopJoinExec" in names, names


def test_inner_join_build_side_flip():
    """Inner join with the smaller side on the left should flip it to the
    build side and still produce left-first column order."""
    def q(spark):
        small = gen_df(spark, [("k", IntegerGen(lo=0, hi=10)),
                               ("vs", LongGen())], length=32, seed=36)
        big = gen_df(spark, [("k2", IntegerGen(lo=0, hi=10)),
                             ("vb", LongGen())],
                     length=512, seed=37, num_partitions=2)
        return small.join(big, on=(col("k") == col("k2")), how="inner")
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q)
    assert cpu.schema.names == ["k", "vs", "k2", "vb"]


def test_full_join_never_broadcast():
    def q(spark):
        a = gen_df(spark, [("k", IntegerGen(lo=0, hi=20)),
                           ("va", LongGen())],
                   length=256, seed=38, num_partitions=3)
        b = gen_df(spark, [("k2", IntegerGen(lo=0, hi=20)),
                           ("vb", LongGen())], length=32, seed=39)
        return a.join(b, on=(col("k") == col("k2")), how="full")
    assert_tpu_and_cpu_are_equal_collect(q)
    names = _plan_exec_names(q)
    assert "BroadcastHashJoinExec" not in names, names


def test_conditional_left_join():
    """LEFT join with a residual condition: pairs failing the condition
    drop, probe rows with no passing pair emit once with the build side
    nulled (expand+repair kernel; ref GpuOverrides.scala:3352-3355)."""
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=20), 128)
        return a.join(b, on=(col("k") == col("k2")) &
                      (col("va") > col("vb")), how="left")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_conditional_right_join_flips_to_left():
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=12), 96)
        return a.join(b, on=(col("k") == col("k2")) &
                      (col("va") < col("vb")), how="right")
    assert_tpu_and_cpu_are_equal_collect(q)
