"""Join differential tests (model: integration_tests/join_test.py)."""

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect)
from spark_rapids_tpu.testing.data_gen import (
    IntegerGen, LongGen, StringGen, gen_df)

ALL_JOINS = ["inner", "left", "right", "full", "left_semi", "left_anti"]


def _sides(spark, key_gen, length=256):
    a = gen_df(spark, [("k", key_gen), ("va", LongGen())],
               length=length, seed=10)
    b = gen_df(spark, [("k2", key_gen), ("vb", LongGen())],
               length=length // 2, seed=20)
    return a, b


@pytest.mark.parametrize("how", ALL_JOINS)
def test_equi_join_int_keys(how):
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=50))
        return a.join(b, on=(col("k") == col("k2")), how=how)
    assert_tpu_and_cpu_are_equal_collect(q)


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_equi_join_string_keys(how):
    def q(spark):
        a = gen_df(spark, [("k", StringGen(max_len=4)), ("va", LongGen())],
                   length=256, seed=1)
        b = gen_df(spark, [("k2", StringGen(max_len=4)), ("vb", LongGen())],
                   length=128, seed=2)
        return a.join(b, on=(col("k") == col("k2")), how=how)
    assert_tpu_and_cpu_are_equal_collect(q)


@pytest.mark.parametrize("how", ALL_JOINS)
def test_join_null_keys(how):
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=5, null_prob=0.4), 64)
        return a.join(b, on=(col("k") == col("k2")), how=how)
    assert_tpu_and_cpu_are_equal_collect(q)


def test_using_join():
    def q(spark):
        a = gen_df(spark, [("k", IntegerGen(lo=0, hi=20)),
                           ("va", LongGen())], length=128, seed=3)
        b = gen_df(spark, [("k", IntegerGen(lo=0, hi=20)),
                           ("vb", LongGen())], length=64, seed=4)
        return a.join(b, on="k", how="inner")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_multi_key_join():
    def q(spark):
        a = gen_df(spark, [("k1", IntegerGen(lo=0, hi=8)),
                           ("k2", IntegerGen(lo=0, hi=8)),
                           ("va", LongGen())], length=256, seed=5)
        b = gen_df(spark, [("j1", IntegerGen(lo=0, hi=8)),
                           ("j2", IntegerGen(lo=0, hi=8)),
                           ("vb", LongGen())], length=128, seed=6)
        return a.join(b, on=(col("k1") == col("j1")) &
                      (col("k2") == col("j2")), how="inner")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_conditional_inner_join():
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=20), 128)
        return a.join(b, on=(col("k") == col("k2")) &
                      (col("va") > col("vb")), how="inner")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_cross_join():
    def q(spark):
        a = gen_df(spark, [("x", IntegerGen())], length=30, seed=7)
        b = gen_df(spark, [("y", IntegerGen())], length=20, seed=8)
        return a.join(b, how="cross")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_join_then_aggregate():
    """Join feeding aggregation (the TPC-DS bread-and-butter shape)."""
    def q(spark):
        a, b = _sides(spark, IntegerGen(lo=0, hi=30), 512)
        return (a.join(b, on=(col("k") == col("k2")), how="inner")
                 .group_by(col("k"))
                 .agg(F.sum(col("va")).alias("sa"),
                      F.count("*").alias("c")))
    assert_tpu_and_cpu_are_equal_collect(q)
