"""Speculative join sizing (spark.rapids.tpu.join.speculativeSizing).

The join's count+expand fuse into one program at a guessed output
capacity; a deferred guard rides the result fetch and a miss re-executes
with exact sizing — results must be identical either way, and the
engine must never surface truncated output."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _session(spec: bool):
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", True)
            .config("spark.rapids.tpu.join.speculativeSizing", spec)
            .get_or_create())


def _sorted(t: pa.Table) -> pa.Table:
    return t.sort_by([(n, "ascending") for n in t.schema.names])


def test_speculation_hit_fk_pk_join():
    """Unique build keys: output rows == probe rows, the guess holds."""
    rng = np.random.default_rng(31)
    n = 5000
    probe = pa.table({
        "k": pa.array(rng.integers(0, 100, n).astype(np.int64)),
        "v": pa.array(rng.integers(-50, 50, n).astype(np.int64))})
    build = pa.table({
        "k": pa.array(np.arange(100, dtype=np.int64)),
        "w": pa.array(np.arange(100, dtype=np.int64) * 7)})
    outs = []
    for spec in (True, False):
        s = _session(spec)
        outs.append(_sorted(
            s.create_dataframe(probe)
            .join(s.create_dataframe(build), on="k").collect()))
    assert outs[0].equals(outs[1])
    assert outs[0].num_rows == n


def test_speculation_miss_reexecutes_exactly():
    """64x expansion blows past the probe-capacity guess; the deferred
    guard must trip and the re-execution must produce the exact rows."""
    n, dup = 5000, 64
    probe = pa.table({
        "k": pa.array((np.arange(n, dtype=np.int64) % 50)),
        "v": pa.array(np.arange(n, dtype=np.int64))})
    build = pa.table({
        "k": pa.array(np.repeat(np.arange(50, dtype=np.int64), dup)),
        "w": pa.array(np.arange(50 * dup, dtype=np.int64))})
    s = _session(True)
    got = (s.create_dataframe(probe)
           .join(s.create_dataframe(build), on="k").collect())
    c = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    False).get_or_create()
    want = (c.create_dataframe(probe)
            .join(c.create_dataframe(build), on="k").collect())
    assert got.num_rows == n * dup == want.num_rows
    assert _sorted(got).equals(_sorted(want))


def test_speculative_left_join_null_extension():
    rng = np.random.default_rng(33)
    probe = pa.table({
        "k": pa.array(np.arange(200, dtype=np.int64)),
        "v": pa.array(rng.integers(0, 9, 200).astype(np.int64))})
    build = pa.table({
        "k": pa.array(np.arange(0, 100, dtype=np.int64)),
        "w": pa.array(np.arange(100, dtype=np.int64))})
    outs = []
    for spec in (True, False):
        s = _session(spec)
        outs.append(_sorted(
            s.create_dataframe(probe)
            .join(s.create_dataframe(build), on="k", how="left")
            .collect()))
    assert outs[0].equals(outs[1])
    assert outs[0].num_rows == 200


def test_string_payloads_bypass_speculation():
    """Span schemas need char-cap guesses the spec program doesn't carry
    — they must take the exact-sizing path and still be correct."""
    probe = pa.table({
        "k": pa.array(np.arange(300, dtype=np.int64) % 40),
        "s": pa.array([f"row-{i}" for i in range(300)])})
    build = pa.table({
        "k": pa.array(np.arange(40, dtype=np.int64)),
        "t": pa.array([f"dim-{i}" for i in range(40)])})
    s = _session(True)
    got = _sorted(s.create_dataframe(probe)
                  .join(s.create_dataframe(build), on="k").collect())
    c = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    False).get_or_create()
    want = _sorted(c.create_dataframe(probe)
                   .join(c.create_dataframe(build), on="k").collect())
    assert got.equals(want)


def test_compile_lean_sort_matches_carry():
    """ops/carry.py lean mode: iterated 2-operand passes + gathers must
    permute identically to the payload carry-sort (including stability
    and span payloads)."""
    rng = np.random.default_rng(34)
    n = 4000
    tb = pa.table({
        "k": pa.array(rng.integers(0, 50, n).astype(np.int64)),
        "v": pa.array(rng.integers(-9, 9, n).astype(np.int64)),
        "s": pa.array([f"x{int(i) % 13}" for i in rng.integers(0, 99, n)]),
    })
    outs = []
    for lean in ("on", "off"):
        s = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", True)
             .config("spark.rapids.tpu.sort.compileLean", lean)
             .config("spark.rapids.sql.collect.hostAssisted", False)
             .get_or_create())
        outs.append(s.create_dataframe(tb, num_partitions=2)
                    .sort(col("k"), col("v").desc(), col("s")).collect())
    assert outs[0].equals(outs[1])


def test_speculation_miss_does_not_poison_df_cache():
    """A cache() materialization streamed during a mispredicted run must
    be discarded before re-execution — a truncated blob surviving into
    CachedScanExec would silently corrupt every later query."""
    n, dup = 4000, 64
    probe = pa.table({
        "k": pa.array((np.arange(n, dtype=np.int64) % 50)),
        "v": pa.array(np.arange(n, dtype=np.int64))})
    build = pa.table({
        "k": pa.array(np.repeat(np.arange(50, dtype=np.int64), dup)),
        "w": pa.array(np.arange(50 * dup, dtype=np.int64))})
    s = _session(True)
    df = (s.create_dataframe(probe)
          .join(s.create_dataframe(build), on="k").cache())
    first = df.collect()           # miss -> re-execute -> cache rebuilt
    assert first.num_rows == n * dup
    second = df.collect()          # served from the cache
    assert second.num_rows == n * dup
    assert _sorted(first).equals(_sorted(second))
