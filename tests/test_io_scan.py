

def test_filescan_device_pin_reuses_and_invalidates(tmp_path):
    """Repeated parquet queries reuse pinned device batches; touching the
    file (mtime/size change) invalidates the pin key."""
    import pyarrow.parquet as pq
    import numpy as np
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.io import scan as scan_mod

    import pyarrow as pa
    p = tmp_path / "pin.parquet"
    tb = pa.table({"k": pa.array(np.arange(100, dtype=np.int64) % 7),
                   "v": pa.array(np.arange(100, dtype=np.int64))})
    pq.write_table(tb, p)
    s = (TpuSession.builder().config("spark.rapids.sql.enabled", True)
         .get_or_create())

    def q():
        return (s.read.parquet(str(p)).group_by(col("k"))
                .agg(F.sum(col("v")).alias("sv")).collect().sort_by("k"))

    scan_mod._FILESCAN_PIN.clear()
    out1 = q()
    assert len(scan_mod._FILESCAN_PIN) >= 1
    calls = {"n": 0}
    orig = scan_mod.FileScanExec._read_file

    def spy(self, path):
        calls["n"] += 1
        return orig(self, path)

    scan_mod.FileScanExec._read_file = spy
    try:
        out2 = q()
        assert calls["n"] == 0, "pinned scan must not re-read the file"
        assert out1.equals(out2)
        # rewrite the file -> new key -> re-read
        tb2 = pa.table({"k": pa.array(np.arange(50, dtype=np.int64) % 7),
                        "v": pa.array(np.arange(50, dtype=np.int64))})
        pq.write_table(tb2, p)
        out3 = q()
        assert calls["n"] >= 1, "changed file must invalidate the pin"
        assert sum(out3.column("sv").to_pylist()) == sum(range(50))
    finally:
        scan_mod.FileScanExec._read_file = orig
