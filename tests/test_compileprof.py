"""Compile observatory (obs/compileprof.py): split build timing,
miss-cause classification, the tpu_jit_* metric family, the
cross-session ledger and `tools compile-report` aggregation.

The taxonomy tests drive the observatory directly through process_jit
with synthetic keys so each cause is provoked in isolation; the
end-to-end path (corpus replay, span/ledger/metric agreement) is the
tier-1 --jit gate in devtools/run_lint.py."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.exec import base as eb
from spark_rapids_tpu.obs import metrics as obs_metrics
from spark_rapids_tpu.obs.compileprof import (CAUSE_DTYPE, CAUSE_NEW,
                                              CAUSE_REFAULT,
                                              CAUSE_SHAPE,
                                              CompileObservatory,
                                              _mask_buckets)


@pytest.fixture()
def obs():
    """Fresh observatory + registry + jit table per test (the indexes
    are process-global by design)."""
    obs_metrics.MetricsRegistry.reset_for_tests()
    o = CompileObservatory.reset_for_tests()
    eb.clear_jit_cache()
    yield o
    eb.clear_jit_cache()
    CompileObservatory.reset_for_tests()
    obs_metrics.MetricsRegistry.reset_for_tests()


def _probe(key_tail, shape=1024, dtype=jnp.int32):
    fn = eb.process_jit(key_tail, lambda: (lambda x: x + 1))
    out = fn(jnp.zeros(shape, dtype))
    assert out.shape[0] == shape
    return fn


# ---------------------------------------------------------------------------
# cause taxonomy
# ---------------------------------------------------------------------------

def test_first_build_is_new_program(obs):
    _probe(("ProbeExec", "a"))
    snap = obs.snapshot()
    assert snap["builds"] == 1
    assert snap["by_cause"] == {CAUSE_NEW: 1}
    # split timing was measured and is sane
    assert snap["compile_seconds_total"] > 0
    assert snap["trace_seconds_total"] > 0


def test_bucket_shape_change_is_shape_churn(obs):
    f = _probe(("ProbeExec", "a"), shape=1024)
    f(jnp.zeros(8192, jnp.int32))       # another capacity bucket
    assert obs.snapshot()["by_cause"] == {CAUSE_NEW: 1, CAUSE_SHAPE: 1}


def test_bucket_int_in_key_is_shape_churn(obs):
    # two keys differing ONLY in an embedded capacity-bucket int (the
    # fetch_pack/join-expand pattern) canonicalize together
    _probe(("ProbeExec", "cap", 1024), shape=1024)
    _probe(("ProbeExec", "cap", 8192), shape=8192)
    assert obs.snapshot()["by_cause"] == {CAUSE_NEW: 1, CAUSE_SHAPE: 1}


def test_dtype_change_is_dtype_churn(obs):
    f = _probe(("ProbeExec", "a"), shape=1024)
    f(jnp.zeros(1024, jnp.float32))     # same capacity, new dtypes
    assert obs.snapshot()["by_cause"] == {CAUSE_NEW: 1, CAUSE_DTYPE: 1}


def test_genuinely_new_key_is_new_program(obs):
    _probe(("ProbeExec", "a"), shape=1024)
    _probe(("OtherExec", "b"), shape=2048)   # non-bucket shape too
    assert obs.snapshot()["by_cause"] == {CAUSE_NEW: 2}


def test_eviction_then_rebuild_is_refault(obs, monkeypatch):
    monkeypatch.setattr(eb, "_JIT_CACHE_MAX", 1)
    _probe(("ProbeExec", "a"))
    _probe(("OtherExec", "b"))           # evicts ProbeExec
    snap = obs.snapshot()
    assert snap["evictions"] == 1
    _probe(("ProbeExec", "a"))           # rebuild of the evicted entry
    snap = obs.snapshot()
    assert snap["by_cause"].get(CAUSE_REFAULT) == 1
    assert snap["refaults"] == 1


def test_clear_jit_cache_refaults_without_evictions(obs):
    _probe(("ProbeExec", "a"))
    eb.clear_jit_cache()
    _probe(("ProbeExec", "a"))
    snap = obs.snapshot()
    # honest refault classification, but a deliberate clear is not LRU
    # pressure: no eviction counted, no thrash warning armed
    assert snap["by_cause"].get(CAUSE_REFAULT) == 1
    assert snap["evictions"] == 0
    assert snap["refaults"] == 0


def test_second_call_same_shape_builds_nothing(obs):
    f = _probe(("ProbeExec", "a"))
    b1 = obs.snapshot()["builds"]
    for _ in range(3):
        f(jnp.ones(1024, jnp.int32))
    assert obs.snapshot()["builds"] == b1
    # ...and process_jit table hits are counted
    _probe(("ProbeExec", "a"))
    assert obs.snapshot()["hits"] >= 1


def test_profiled_result_matches_plain_jit(obs):
    f = eb.process_jit(("ProbeExec", "sum"),
                       lambda: (lambda x, y: (x * y).sum()))
    a = jnp.arange(100, dtype=jnp.float32)
    out = f(a, a)
    assert float(out) == float((np.arange(100.0) ** 2).sum())


# ---------------------------------------------------------------------------
# metrics family
# ---------------------------------------------------------------------------

def test_jit_metric_family_lights_up(obs, monkeypatch):
    monkeypatch.setattr(eb, "_JIT_CACHE_MAX", 1)
    _probe(("ProbeExec", "a"))
    _probe(("ProbeExec", "a"))           # hit
    _probe(("OtherExec", "b"))           # evicts
    reg = obs_metrics.registry()
    assert reg.counter("tpu_jit_hits_total",
                       labelnames=("exec",)).value(exec="ProbeExec") >= 1
    assert reg.counter(
        "tpu_jit_misses_total", labelnames=("exec", "cause")).value(
        exec="ProbeExec", cause=CAUSE_NEW) == 1
    assert reg.counter("tpu_jit_evictions_total",
                       labelnames=("exec",)).value(exec="ProbeExec") == 1
    count, secs = 0, 0.0
    fam = reg.counter("tpu_jit_compile_seconds_total",
                      labelnames=("exec", "cause"))
    for _, ch in fam.series():
        count += 1
        secs += ch.value
    assert count >= 2 and secs > 0
    assert reg.gauge("tpu_jit_cache_size").value() == 1


def test_thrash_warning_fires_above_ratio(obs, monkeypatch, caplog):
    import logging
    monkeypatch.setattr(eb, "_JIT_CACHE_MAX", 1)
    obs.configure(thrash_warn_ratio=0.4)
    with caplog.at_level(logging.WARNING,
                         logger="spark_rapids_tpu.obs.compileprof"):
        for _ in range(3):             # ping-pong: every build refaults
            _probe(("ProbeExec", "a"))
            _probe(("OtherExec", "b"))
    assert any("thrash" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# ledger + cross-session index + compile-report
# ---------------------------------------------------------------------------

def test_ledger_appends_and_report_aggregates(obs, tmp_path):
    ledger = str(tmp_path / "compile_ledger.jsonl")
    obs.configure(ledger_path=ledger)
    f = _probe(("ProbeExec", "cap", 1024), shape=1024)
    f(jnp.zeros(8192, jnp.int32))
    _probe(("OtherExec", "x"), shape=2048)
    lines = [json.loads(l) for l in open(ledger) if l.strip()]
    builds = [l for l in lines if l["event"] == "build"]
    assert len(builds) == 3
    for b in builds:
        assert b["cause"] and b["exec"] and b["key"] and b["shape"]
        assert b["total_s"] >= 0 and b["hlo_bytes"] > 0
    from spark_rapids_tpu.tools.compile_report import (aggregate_ledger,
                                                       load_ledger)
    agg = aggregate_ledger(load_ledger(str(tmp_path)))
    assert agg["builds"] == 3
    assert agg["distinct_programs"] == 3
    assert agg["attribution_pct"] >= 95.0
    assert agg["causeless_builds"] == 0
    # dedupe projection: the two ProbeExec bucket variants collapse
    assert agg["canonical_families"] == 2
    assert agg["projected_savings_s"] > 0
    assert agg["churn_offenders"][0]["exec"] == "ProbeExec"


def test_prior_session_ledger_classifies_refault(obs, tmp_path):
    ledger = str(tmp_path / "compile_ledger.jsonl")
    obs.configure(ledger_path=ledger)
    _probe(("ProbeExec", "a"))
    # "next session": fresh observatory + jit table, same ledger
    eb.clear_jit_cache()
    o2 = CompileObservatory.reset_for_tests()
    o2.configure(ledger_path=ledger)
    _probe(("ProbeExec", "a"))
    assert o2.snapshot()["by_cause"] == {CAUSE_REFAULT: 1}


def test_compile_report_cli(obs, tmp_path, capsys):
    obs.configure(ledger_path=str(tmp_path / "compile_ledger.jsonl"))
    _probe(("ProbeExec", "cap", 1024), shape=1024)
    _probe(("ProbeExec", "cap", 8192), shape=8192)
    from spark_rapids_tpu.tools.__main__ import main as tools_main
    assert tools_main(["compile-report", "--ledger",
                       str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "compile observatory report" in out
    assert "shape_churn" in out
    assert "2 program(s) collapse to 1" in out
    # an empty/missing ledger is a usage error, not a crash
    assert tools_main(["compile-report", "--ledger",
                       str(tmp_path / "nope")]) == 2


# ---------------------------------------------------------------------------
# canonicalization + proxy safety
# ---------------------------------------------------------------------------

def test_mask_buckets_masks_only_bucket_ints():
    buckets = frozenset((1024, 8192))
    key = ("Exec", 1024, 37, (8192, "s"), True)
    assert _mask_buckets(key, buckets) == \
        ("Exec", "<cap>", 37, ("<cap>", "s"), True)


def test_unsignable_args_fall_back_to_plain_jit(obs):
    # calling a profiled fn under an enclosing trace hands it Tracer
    # leaves: the proxy must dispatch through plain jit, not AOT
    import jax
    f = eb.process_jit(("ProbeExec", "inner"),
                       lambda: (lambda x: x * 2))

    @jax.jit
    def outer(x):
        return f(x) + 1

    out = outer(jnp.arange(4))
    assert list(np.asarray(out)) == [1, 3, 5, 7]


def test_disabled_observatory_returns_plain_jit(obs):
    obs.configure(enabled=False)
    f = eb.process_jit(("ProbeExec", "off"), lambda: (lambda x: x + 1))
    assert int(f(jnp.int32(41))) == 42
    assert obs.snapshot()["builds"] == 0
