"""Sort differential tests (model: integration_tests/sort_test.py)."""

import pytest

from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect)
from spark_rapids_tpu.testing.data_gen import (
    DoubleGen, IntegerGen, LongGen, StringGen, gen_df)


def test_sort_int_asc():
    def q(spark):
        df = gen_df(spark, [("a", IntegerGen()), ("b", LongGen())],
                    length=512)
        return df.order_by(col("a"), col("b"))
    assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)


def test_sort_desc_and_nulls():
    def q(spark):
        df = gen_df(spark, [("a", IntegerGen(null_prob=0.3)),
                            ("b", LongGen())], length=512)
        return df.order_by(col("a").desc(), col("b").asc())
    assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)


def test_sort_doubles_with_nan():
    def q(spark):
        df = gen_df(spark, [("d", DoubleGen()), ("x", IntegerGen())],
                    length=512)
        return df.order_by(col("d"), col("x"))
    assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)


def test_sort_strings():
    def q(spark):
        df = gen_df(spark, [("s", StringGen(max_len=10)),
                            ("x", IntegerGen())], length=512)
        return df.order_by(col("s"), col("x"))
    assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)


def test_sort_multi_partition_global():
    def q(spark):
        df = gen_df(spark, [("a", IntegerGen()), ("b", LongGen())],
                    length=1024, num_partitions=4)
        return df.order_by(col("a"), col("b"))
    assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
