"""Hash aggregate differential tests (model: integration_tests/
hash_aggregate_test.py — the reference's first-line aggregate coverage)."""

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect)
from spark_rapids_tpu.testing.data_gen import (
    ByteGen, DoubleGen, FloatGen, IntegerGen, LongGen, ShortGen, StringGen,
    gen_df)

_int_key_gens = [ByteGen(), ShortGen(), IntegerGen(), LongGen()]


@pytest.mark.parametrize("key_gen", _int_key_gens,
                         ids=lambda g: type(g).__name__)
def test_group_by_sum_int_keys(key_gen):
    def q(spark):
        df = gen_df(spark, [("k", key_gen), ("v", LongGen())], length=512)
        return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_group_by_sum_avg_count():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen()), ("v", LongGen()),
                            ("f", DoubleGen(no_nans=True))], length=1024)
        return df.group_by(col("k")).agg(
            F.sum(col("v")).alias("sv"),
            F.avg(col("f")).alias("af"),
            F.count(col("v")).alias("cv"),
            F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-9)


def test_group_by_min_max():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen()), ("v", LongGen()),
                            ("f", DoubleGen(no_nans=True))], length=1024)
        return df.group_by(col("k")).agg(
            F.min(col("v")).alias("mn"), F.max(col("v")).alias("mx"),
            F.min(col("f")).alias("fmn"), F.max(col("f")).alias("fmx"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-9)


def test_group_by_string_keys():
    def q(spark):
        df = gen_df(spark, [("k", StringGen(max_len=8)), ("v", LongGen())],
                    length=1024)
        return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"),
                                         F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_global_aggregate():
    def q(spark):
        df = gen_df(spark, [("v", LongGen()), ("f", DoubleGen(no_nans=True))],
                    length=777)
        return df.agg(F.sum(col("v")).alias("s"),
                      F.count("*").alias("c"),
                      F.avg(col("f")).alias("a"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-9)


def test_global_aggregate_empty_input():
    def q(spark):
        df = gen_df(spark, [("v", LongGen())], length=64)
        return df.filter(lit(False)).agg(F.count("*").alias("c"),
                                         F.sum(col("v")).alias("s"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_group_by_with_nulls_in_keys():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen(null_prob=0.5)),
                            ("v", LongGen())], length=512)
        return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"),
                                         F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_group_by_multiple_keys():
    def q(spark):
        df = gen_df(spark, [("k1", IntegerGen()), ("k2", StringGen(max_len=4)),
                            ("k3", ByteGen()), ("v", LongGen())], length=2048)
        return df.group_by(col("k1"), col("k2"), col("k3")).agg(
            F.sum(col("v")).alias("s"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_first_last():
    # first/last are order-dependent: use a sorted single-partition input
    def q(spark):
        df = spark.create_dataframe({
            "k": [1, 1, 1, 2, 2, 3],
            "v": [10, None, 30, 40, 50, None]})
        return df.group_by(col("k")).agg(
            F.first(col("v")).alias("f"),
            F.last(col("v")).alias("l"),
            F.first(col("v"), ignorenulls=True).alias("fn"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_stddev_variance():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen(lo=0, hi=20)),
                            ("v", DoubleGen(no_nans=True))], length=1024)
        df = df.filter(col("v").is_not_null() &
                       (F.abs(col("v")) < lit(1e6)))
        return df.group_by(col("k")).agg(
            F.stddev(col("v")).alias("sd"),
            F.var_pop(col("v")).alias("vp"),
            F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-6)


def test_avg_overflow_like_reference_config():
    """BASELINE.json config 1: single-partition GROUP BY SUM/AVG int/long."""
    def q(spark):
        df = gen_df(spark, [("k", LongGen()), ("i", IntegerGen()),
                            ("l", LongGen())], length=4096)
        return df.group_by(col("k")).agg(
            F.sum(col("i")).alias("si"), F.avg(col("i")).alias("ai"),
            F.sum(col("l")).alias("sl"), F.avg(col("l")).alias("al"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-9)


def test_group_by_min_max_strings():
    """Ordered reduce over variable-width values (regression: min/max on
    strings used to return the first value per group)."""
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen(nullable=False)),
                            ("s", StringGen())], length=512)
        return df.group_by(col("k")).agg(
            F.min(col("s")).alias("mn"), F.max(col("s")).alias("mx"),
            F.count(col("s")).alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_global_min_max_strings():
    def q(spark):
        df = gen_df(spark, [("s", StringGen())], length=256)
        return df.agg(F.min(col("s")).alias("mn"),
                      F.max(col("s")).alias("mx"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_collect_list_and_set():
    """collect_list/collect_set (ref AggregateFunctions.scala
    GpuCollectList/GpuCollectSet): list keeps duplicates in row order
    within the engine's key-sorted layout, set dedupes; nulls dropped."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    rng = np.random.default_rng(5)
    n = 3000
    tb = pa.table({
        "k": pa.array(rng.integers(0, 40, n).astype(np.int64)),
        "v": pa.array([None if i % 7 == 0 else int(x) for i, x in
                       enumerate(rng.integers(0, 15, n))],
                      type=pa.int64()),
    })
    out = (s.create_dataframe(tb, num_partitions=3)
           .group_by(col("k"))
           .agg(F.collect_list(col("v")).alias("cl"),
                F.collect_set(col("v")).alias("cs"))
           .collect().sort_by("k"))
    placements = []
    s.last_plan.foreach(lambda e: placements.append(
        (type(e).__name__, e.placement)))
    assert any(n_ == "TpuHashAggregateExec" and p == "tpu"
               for n_, p in placements), placements
    # oracle
    want = {}
    for k, v in zip(tb.column("k").to_pylist(), tb.column("v").to_pylist()):
        want.setdefault(k, []).append(v)
    got_k = out.column("k").to_pylist()
    got_cl = out.column("cl").to_pylist()
    got_cs = out.column("cs").to_pylist()
    assert got_k == sorted(want)
    for k, cl, cs in zip(got_k, got_cl, got_cs):
        ref = [v for v in want[k] if v is not None]
        assert sorted(cl) == sorted(ref), (k, "list contents")
        assert sorted(cs) == sorted(set(ref)), (k, "set contents")


def test_collect_differential_cpu_vs_tpu():
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api.session import TpuSession
    rng = np.random.default_rng(9)
    n = 1200
    tb = pa.table({
        "k": pa.array(rng.integers(0, 25, n).astype(np.int64)),
        "v": pa.array([None if i % 5 == 0 else int(x) for i, x in
                       enumerate(rng.integers(-8, 8, n))],
                      type=pa.int64()),
    })
    res = {}
    for enabled in (True, False):
        s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                        enabled).get_or_create()
        out = (s.create_dataframe(tb, num_partitions=2)
               .group_by(col("k"))
               .agg(F.collect_list(col("v")).alias("cl"),
                    F.collect_set(col("v")).alias("cs"))
               .collect().sort_by("k"))
        res[enabled] = (out.column("k").to_pylist(),
                        [sorted(x) for x in out.column("cl").to_pylist()],
                        [sorted(x) for x in out.column("cs").to_pylist()])
    assert res[True] == res[False]


def test_collect_list_strings():
    import pyarrow as pa
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    tb = pa.table({
        "k": pa.array([1, 1, 2, 2, 1, 2]),
        "s": pa.array(["a", "bb", "x", None, "a", "x"]),
    })
    out = (s.create_dataframe(tb).group_by(col("k"))
           .agg(F.collect_list(col("s")).alias("cl"),
                F.collect_set(col("s")).alias("cs"))
           .collect().sort_by("k"))
    cl = [sorted(x) for x in out.column("cl").to_pylist()]
    cs = [sorted(x) for x in out.column("cs").to_pylist()]
    assert cl == [["a", "a", "bb"], ["x", "x"]]
    assert cs == [["a", "bb"], ["x"]]


def test_pivot():
    """groupBy().pivot(col, values).agg(...) — each pivot value becomes a
    masked aggregate fused into one kernel pass (ref GpuPivotFirst in
    AggregateFunctions.scala)."""
    import numpy as np
    import pyarrow as pa
    import pandas as pd
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    rng = np.random.default_rng(3)
    n = 2000
    cats = ["red", "green", "blue"]
    tb = pa.table({
        "k": pa.array(rng.integers(0, 30, n).astype(np.int64)),
        "p": pa.array([cats[i] for i in rng.integers(0, 3, n)]),
        "v": pa.array(rng.integers(-100, 100, n).astype(np.int64)),
    })
    out = (s.create_dataframe(tb, num_partitions=2)
           .group_by(col("k")).pivot(col("p"), cats)
           .agg(F.sum(col("v")).alias("sv"))
           .collect().sort_by("k"))
    pdf = tb.to_pandas()
    want = pdf.pivot_table(index="k", columns="p", values="v",
                           aggfunc="sum")
    got_k = out.column("k").to_pylist()
    assert got_k == sorted(set(pdf.k))
    for c in cats:
        got = out.column(c).to_pylist()
        exp = [None if pd.isna(x) else int(x)
               for x in want[c].reindex(got_k)]
        assert got == exp, c


def test_pivot_inferred_values_multiple_aggs():
    import pyarrow as pa
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    tb = pa.table({
        "k": pa.array([1, 1, 2, 2, 2]),
        "p": pa.array(["a", "b", "a", "a", "b"]),
        "v": pa.array([10, 20, 30, 40, 50]),
    })
    out = (s.create_dataframe(tb).group_by(col("k"))
           .pivot(col("p"))
           .agg(F.sum(col("v")).alias("sv"),
                F.count(col("v")).alias("cv"))
           .collect().sort_by("k"))
    assert out.column("a_sv").to_pylist() == [10, 70]
    assert out.column("b_sv").to_pylist() == [20, 50]
    assert out.column("a_cv").to_pylist() == [1, 2]
    assert out.column("b_cv").to_pylist() == [1, 1]


def test_group_reduce_scale_and_skew_differential():
    import numpy as np
    import pyarrow as pa

    """Carry-sort group-by at 100k rows with skew, nulls, strings,
    decimals, and every reduction family — differential vs the CPU
    engine (the scale/skew case the small generator tests miss)."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession

    rng = np.random.default_rng(1234)
    n = 100_000
    hot = rng.random(n) < 0.35
    k = np.where(hot, 7, rng.integers(0, 500, n)).astype(np.int64)
    kmask = rng.random(n) < 0.02
    v = rng.integers(-(10**12), 10**12, n).astype(np.int64)
    vmask = rng.random(n) < 0.1
    f = rng.random(n) * rng.choice([1.0, 1e12], n)
    s_ = np.array([f"name_{int(x):03d}" for x in rng.integers(0, 97, n)],
                  dtype=object)
    tbl = pa.table({
        "k": pa.array(k, mask=kmask),
        "v": pa.array(v, mask=vmask),
        "f": pa.array(f),
        "s": pa.array(s_.tolist()),
        "d": pa.array((v % 10**10).tolist(),
                      type=pa.decimal128(12, 2)).cast(pa.decimal128(12, 2)),
    })

    def q(enabled):
        sess = (TpuSession.builder()
                .config("spark.rapids.sql.enabled", enabled)
                .get_or_create())
        df = sess.create_dataframe(tbl)
        return (df.group_by(col("k"))
                .agg(F.sum(col("v")).alias("sv"),
                     F.avg(col("f")).alias("af"),
                     F.min(col("v")).alias("mv"),
                     F.max(col("f")).alias("xf"),
                     F.min(col("s")).alias("ms"),
                     F.sum(col("d")).alias("sd"),
                     F.count(col("v")).alias("cv"),
                     F.count("*").alias("c"))
                .collect().sort_by("k"))

    tpu, cpu = q(True), q(False)
    assert tpu.num_rows == cpu.num_rows
    for name in tpu.column_names:
        a, b = tpu.column(name).to_pylist(), cpu.column(name).to_pylist()
        for x, y in zip(a, b):
            if isinstance(x, float) and isinstance(y, float):
                assert x == y or abs(x - y) <= 1e-9 * max(1.0, abs(x),
                                                          abs(y)), name
            else:
                assert x == y, (name, x, y)
