"""Hash aggregate differential tests (model: integration_tests/
hash_aggregate_test.py — the reference's first-line aggregate coverage)."""

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect)
from spark_rapids_tpu.testing.data_gen import (
    ByteGen, DoubleGen, FloatGen, IntegerGen, LongGen, ShortGen, StringGen,
    gen_df)

_int_key_gens = [ByteGen(), ShortGen(), IntegerGen(), LongGen()]


@pytest.mark.parametrize("key_gen", _int_key_gens,
                         ids=lambda g: type(g).__name__)
def test_group_by_sum_int_keys(key_gen):
    def q(spark):
        df = gen_df(spark, [("k", key_gen), ("v", LongGen())], length=512)
        return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_group_by_sum_avg_count():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen()), ("v", LongGen()),
                            ("f", DoubleGen(no_nans=True))], length=1024)
        return df.group_by(col("k")).agg(
            F.sum(col("v")).alias("sv"),
            F.avg(col("f")).alias("af"),
            F.count(col("v")).alias("cv"),
            F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-9)


def test_group_by_min_max():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen()), ("v", LongGen()),
                            ("f", DoubleGen(no_nans=True))], length=1024)
        return df.group_by(col("k")).agg(
            F.min(col("v")).alias("mn"), F.max(col("v")).alias("mx"),
            F.min(col("f")).alias("fmn"), F.max(col("f")).alias("fmx"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-9)


def test_group_by_string_keys():
    def q(spark):
        df = gen_df(spark, [("k", StringGen(max_len=8)), ("v", LongGen())],
                    length=1024)
        return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"),
                                         F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_global_aggregate():
    def q(spark):
        df = gen_df(spark, [("v", LongGen()), ("f", DoubleGen(no_nans=True))],
                    length=777)
        return df.agg(F.sum(col("v")).alias("s"),
                      F.count("*").alias("c"),
                      F.avg(col("f")).alias("a"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-9)


def test_global_aggregate_empty_input():
    def q(spark):
        df = gen_df(spark, [("v", LongGen())], length=64)
        return df.filter(lit(False)).agg(F.count("*").alias("c"),
                                         F.sum(col("v")).alias("s"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_group_by_with_nulls_in_keys():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen(null_prob=0.5)),
                            ("v", LongGen())], length=512)
        return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"),
                                         F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_group_by_multiple_keys():
    def q(spark):
        df = gen_df(spark, [("k1", IntegerGen()), ("k2", StringGen(max_len=4)),
                            ("k3", ByteGen()), ("v", LongGen())], length=2048)
        return df.group_by(col("k1"), col("k2"), col("k3")).agg(
            F.sum(col("v")).alias("s"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_first_last():
    # first/last are order-dependent: use a sorted single-partition input
    def q(spark):
        df = spark.create_dataframe({
            "k": [1, 1, 1, 2, 2, 3],
            "v": [10, None, 30, 40, 50, None]})
        return df.group_by(col("k")).agg(
            F.first(col("v")).alias("f"),
            F.last(col("v")).alias("l"),
            F.first(col("v"), ignorenulls=True).alias("fn"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_stddev_variance():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen(lo=0, hi=20)),
                            ("v", DoubleGen(no_nans=True))], length=1024)
        df = df.filter(col("v").is_not_null() &
                       (F.abs(col("v")) < lit(1e6)))
        return df.group_by(col("k")).agg(
            F.stddev(col("v")).alias("sd"),
            F.var_pop(col("v")).alias("vp"),
            F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-6)


def test_avg_overflow_like_reference_config():
    """BASELINE.json config 1: single-partition GROUP BY SUM/AVG int/long."""
    def q(spark):
        df = gen_df(spark, [("k", LongGen()), ("i", IntegerGen()),
                            ("l", LongGen())], length=4096)
        return df.group_by(col("k")).agg(
            F.sum(col("i")).alias("si"), F.avg(col("i")).alias("ai"),
            F.sum(col("l")).alias("sl"), F.avg(col("l")).alias("al"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-9)


def test_group_by_min_max_strings():
    """Ordered reduce over variable-width values (regression: min/max on
    strings used to return the first value per group)."""
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen(nullable=False)),
                            ("s", StringGen())], length=512)
        return df.group_by(col("k")).agg(
            F.min(col("s")).alias("mn"), F.max(col("s")).alias("mx"),
            F.count(col("s")).alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_global_min_max_strings():
    def q(spark):
        df = gen_df(spark, [("s", StringGen())], length=256)
        return df.agg(F.min(col("s")).alias("mn"),
                      F.max(col("s")).alias("mx"))
    assert_tpu_and_cpu_are_equal_collect(q)
