"""Remote-shuffle tests: wire-protocol correlation (64-bit request ids,
stale-frame rejection), the O(blocks) metadata fast path, the
locality-aware read split (local zero-copy vs remote fetch), bounded
replica retry, and the cross-process end-to-end golden against
``serve_map``."""

import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.shuffle.manager import TpuShuffleManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _serve_blocks(n_maps=4, rows=64, shuffle_id=11, reduce_id=2):
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.shuffle.transport import ShuffleServer
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    for mid in range(n_maps):
        rb = pa.record_batch({"a": pa.array(
            [mid * 1000 + i for i in range(rows)], type=pa.int64())})
        mgr.write_map_output(shuffle_id, mid,
                             {reduce_id: batch_to_device(rb, xp=np)})
    return mgr, ShuffleServer(mgr).start()


def _rogue_server(script):
    """One-connection server driving ``script(conn)`` — the injected
    wire-fault side of a scenario."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]

    def run():
        conn, _ = lsock.accept()
        try:
            script(conn)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            lsock.close()

    threading.Thread(target=run, daemon=True).start()
    return port


# -- wire protocol ----------------------------------------------------------


def test_request_ids_survive_past_32_bits():
    """Regression: the frame header carried req ids in a 32-bit field
    while the client draws from range(1, 1<<62) — ids past 4B aliased
    and correlated responses to the wrong request.  The field is u64
    now; a request id above 2^32 must round-trip verbatim."""
    from spark_rapids_tpu.columnar.device import batch_to_arrow
    from spark_rapids_tpu.shuffle.transport import ShuffleClient
    mgr, server = _serve_blocks(n_maps=1)
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        big = (1 << 40) + 17
        cli._req_ids = iter(range(big, 1 << 62))
        metas = cli.fetch_metadata(11, 2).wait(10.0)
        assert len(metas) == 1
        (sid, mid, rid, idx), meta = metas[0]
        assert meta.num_rows == 64
        b = cli.fetch_block(sid, mid, rid, idx).wait(10.0)
        assert batch_to_arrow(b).column("a").to_pylist()[0] == 0
        cli.close()
    finally:
        server.stop()
        TpuShuffleManager.reset()


def test_frame_header_is_64_bit():
    from spark_rapids_tpu.shuffle.transport import _FRAME
    mtype, rid, blen = _FRAME.unpack(_FRAME.pack(2, (1 << 40) + 17, 5))
    assert rid == (1 << 40) + 17


def test_stale_frame_rejected_typed():
    """A response whose request id does not match the in-flight request
    is a stale frame from a timed-out predecessor — accepting it would
    hand back the wrong partition's bytes.  Must fail typed, and the
    fetcher must count kind=stale."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.shuffle.errors import TpuShuffleStaleFrameError
    from spark_rapids_tpu.shuffle.transport import (_FRAME, _recv_exact,
                                                    MSG_METADATA_RESP,
                                                    AsyncBlockFetcher,
                                                    ShuffleClient)

    def liar(conn):
        head = _recv_exact(conn, _FRAME.size)
        _, rid, blen = _FRAME.unpack(head)
        if blen:
            _recv_exact(conn, blen)
        conn.sendall(_FRAME.pack(MSG_METADATA_RESP, rid + 1234, 0))

    m.MetricsRegistry.reset_for_tests()
    try:
        cli = ShuffleClient("127.0.0.1", _rogue_server(liar),
                            timeout=10.0)
        with pytest.raises(TpuShuffleStaleFrameError) as ei:
            list(AsyncBlockFetcher(cli, 11, 2, window=2, timeout=10.0))
        assert ei.value.got == ei.value.expected + 1234
        cli.close()
        errs = m.counter("tpu_shuffle_fetch_errors_total",
                         labelnames=("kind",))
        assert errs.value(kind="stale") == 1
    finally:
        m.MetricsRegistry.reset_for_tests()


def test_block_missing_surfaces_typed_from_peer():
    """A transfer request for a block the peer's catalog does not hold
    must come back as the typed missing-block error, not a generic
    failure string."""
    from spark_rapids_tpu.shuffle.errors import TpuShuffleBlockMissingError
    from spark_rapids_tpu.shuffle.transport import ShuffleClient
    mgr, server = _serve_blocks(n_maps=1)
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        with pytest.raises(TpuShuffleBlockMissingError):
            cli.fetch_block(11, 0, 2, 99).wait(10.0)
        cli.close()
    finally:
        server.stop()
        TpuShuffleManager.reset()


# -- metadata fast path -----------------------------------------------------


def test_metadata_answers_without_serializing_payloads(monkeypatch):
    """The block server's metadata path must derive row counts from
    catalog stats — O(blocks) — never by materializing and serializing
    partitions.  Poisoning the serializer proves no payload is touched,
    and the per-kind server counters must split metadata from
    transfer."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.shuffle import transport
    from spark_rapids_tpu.shuffle.transport import (ShuffleClient,
                                                    _server_requests_counter)

    def boom(*a, **k):
        raise AssertionError("metadata request serialized a payload")

    m.MetricsRegistry.reset_for_tests()
    mgr, server = _serve_blocks(n_maps=3, rows=50)
    monkeypatch.setattr(transport, "serialize_batch_with_sizes", boom)
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        metas = cli.fetch_metadata(11, 2).wait(10.0)
        assert [meta.num_rows for _, meta in metas] == [50, 50, 50]
        assert all(meta.num_bytes > 0 for _, meta in metas)
        # all blocks share one schema -> one fingerprint, and it matches
        # what the catalog recorded at registration
        fps = {meta.schema_fingerprint for _, meta in metas}
        assert fps == {mgr.catalog.schema_fp(11)} and fps != {0}
        cli.close()
        req_c = _server_requests_counter()
        assert req_c.value(kind="metadata") == 1
        assert req_c.value(kind="transfer") == 0
    finally:
        server.stop()
        TpuShuffleManager.reset()
        m.MetricsRegistry.reset_for_tests()


# -- locality split ---------------------------------------------------------


def _fresh_registry(local_id="test-local", port=0):
    from spark_rapids_tpu.shuffle.registry import BlockLocationRegistry
    BlockLocationRegistry.reset()
    reg = BlockLocationRegistry.get()
    reg.set_local(local_id, "127.0.0.1", port)
    return reg


def test_local_blocks_never_cross_the_wire():
    """A shuffle whose owner group is this process reads straight from
    the catalog: the local-blocks counter moves, the server's transfer
    counter must not."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.registry import BlockEndpoint
    from spark_rapids_tpu.shuffle.transport import _server_requests_counter
    m.MetricsRegistry.reset_for_tests()
    mgr, server = _serve_blocks(n_maps=3)
    reg = _fresh_registry(port=server.port)
    reg.register(11, [BlockEndpoint("test-local", "127.0.0.1",
                                    server.port)])
    try:
        blocks = list(locality.read_reduce_blocks(11, 2))
        assert len(blocks) == 3
        assert m.counter("tpu_shuffle_local_blocks_total").value() == 3
        assert _server_requests_counter().value(kind="transfer") == 0
        assert m.counter("tpu_shuffle_fetch_blocks_total").value() == 0
    finally:
        server.stop()
        TpuShuffleManager.reset()
        from spark_rapids_tpu.shuffle.registry import BlockLocationRegistry
        BlockLocationRegistry.reset()
        m.MetricsRegistry.reset_for_tests()


def test_locality_disabled_skips_remote_groups():
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.registry import (BlockEndpoint,
                                                   BlockLocationRegistry)
    TpuShuffleManager.reset()
    reg = _fresh_registry()
    reg.register(77, [BlockEndpoint("far-away", "127.0.0.1", 1)])
    conf = cfg.RapidsConf(
        {cfg.SHUFFLE_LOCALITY_ENABLED.key: "false"})
    try:
        assert list(locality.read_reduce_blocks(77, 0, conf=conf)) == []
    finally:
        BlockLocationRegistry.reset()
        TpuShuffleManager.reset()


def test_replica_retry_completes_exactly_once():
    """First replica refuses the dial; the fetch must fail over to the
    live replica, deliver every block exactly once, and count exactly
    one retry."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.columnar.device import batch_to_arrow
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.registry import (BlockEndpoint,
                                                   BlockLocationRegistry)
    m.MetricsRegistry.reset_for_tests()
    mgr, server = _serve_blocks(n_maps=4)
    reg = _fresh_registry()
    dead_sock = socket.socket()
    dead_sock.bind(("127.0.0.1", 0))
    dead_port = dead_sock.getsockname()[1]
    dead_sock.close()
    group = [BlockEndpoint("replica-dead", "127.0.0.1", dead_port),
             BlockEndpoint("replica-live", "127.0.0.1", server.port)]
    locality.reset_pool()
    try:
        got = [batch_to_arrow(b).column("a").to_pylist()[0]
               for b in locality._fetch_group(group, 11, 2, reg, np,
                                              2, 5.0, 2, m)]
        assert got == [0, 1000, 2000, 3000]
        assert m.counter("tpu_shuffle_fetch_retries_total").value() == 1
    finally:
        server.stop()
        locality.reset_pool()
        TpuShuffleManager.reset()
        BlockLocationRegistry.reset()
        m.MetricsRegistry.reset_for_tests()


def test_exhausted_group_fails_with_provenance():
    """When every replica fails, the error must carry fetch provenance
    (group, attempts, blocks delivered) — never hang, never raise
    untyped."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.errors import TpuShuffleError
    from spark_rapids_tpu.shuffle.registry import (BlockEndpoint,
                                                   BlockLocationRegistry)
    m.MetricsRegistry.reset_for_tests()
    TpuShuffleManager.reset()
    reg = _fresh_registry()
    dead_sock = socket.socket()
    dead_sock.bind(("127.0.0.1", 0))
    dead_port = dead_sock.getsockname()[1]
    dead_sock.close()
    group = [BlockEndpoint("gone", "127.0.0.1", dead_port)]
    locality.reset_pool()
    try:
        with pytest.raises(TpuShuffleError) as ei:
            list(locality._fetch_group(group, 11, 2, reg, np,
                                       2, 2.0, 1, m))
        prov = getattr(ei.value, "fetch_provenance", "")
        assert "gone" in prov and "attempt" in prov
    finally:
        locality.reset_pool()
        BlockLocationRegistry.reset()
        m.MetricsRegistry.reset_for_tests()


# -- cross-process end to end ----------------------------------------------


def test_cross_process_fetch_join_bit_exact():
    """Full remote path: a child OS process owns both sides' map
    outputs (lz4-compressed) and serves them over loopback; this
    process fetches through the locality reader and joins.  The result
    must be bit-exact vs the in-process reference, with zero local-path
    reads, zero leaked blocks on the serving side, and the compression
    ratio visible in the child's shuffle byte counters."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.columnar.device import batch_to_arrow
    from spark_rapids_tpu.shuffle import locality
    from spark_rapids_tpu.shuffle.registry import (BlockEndpoint,
                                                   BlockLocationRegistry)
    from spark_rapids_tpu.shuffle.serve_map import (
        DIM_SID, FACT_SID, build_side_tables, partition_record_batch)
    rows, parts, seed = 6000, 3, 11
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SPARK_RAPIDS_TPU_DISABLE_COMPILE_CACHE="1")
    child = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.shuffle.serve_map",
         "--rows", str(rows), "--parts", str(parts),
         "--codec", "lz4", "--seed", str(seed)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env, cwd=REPO)
    m.MetricsRegistry.reset_for_tests()
    TpuShuffleManager.reset()
    reg = _fresh_registry("reduce-side")
    try:
        line = child.stdout.readline()
        assert line.startswith("PORT "), line
        port = int(line.split()[1])
        ep = BlockEndpoint("map-side", "127.0.0.1", port)
        reg.register(FACT_SID, [ep])
        reg.register(DIM_SID, [ep])
        out = []
        for pid in range(parts):
            sides = []
            for sid in (FACT_SID, DIM_SID):
                rbs = [batch_to_arrow(b) for b in
                       locality.read_reduce_blocks(sid, pid)]
                sides.append(pa.Table.from_batches(rbs) if rbs else None)
            if sides[0] is not None and sides[1] is not None:
                out.append(sides[0].join(sides[1], "k"))
        got = pa.concat_tables(out).sort_by(
            [("k", "ascending"), ("v", "ascending")])
        fact, dim = build_side_tables(rows, seed)
        fparts = partition_record_batch(fact, "k", parts)
        dparts = partition_record_batch(dim, "k", parts)
        ref = [pa.table(fparts[p]).join(pa.table(dparts[p]), "k")
               for p in range(parts) if p in fparts and p in dparts]
        ref_t = pa.concat_tables(ref).sort_by(
            [("k", "ascending"), ("v", "ascending")])
        assert got.equals(ref_t)
        assert got.num_rows == rows
        # every block was remote: zero local reads, zero fetch errors
        assert m.counter("tpu_shuffle_local_blocks_total").value() == 0
        errs = m.counter("tpu_shuffle_fetch_errors_total",
                         labelnames=("kind",))
        assert sum(ch.value for _, ch in errs.series()) == 0
        child.stdin.write("done\n")
        child.stdin.flush()
        stats = json.loads(
            child.stdout.readline()[len("STATS "):])
        assert stats["leaked_blocks"] == 0
        assert stats["leaks"] == 0
        assert stats["raw_bytes"] > 0
        assert stats["compressed_bytes"] / stats["raw_bytes"] < 0.9
        assert stats["server_transfer_requests"] > 0
        assert child.wait(timeout=30) == 0
    finally:
        child.stdin.close()
        child.stdout.close()
        if child.poll() is None:
            child.kill()
            child.wait()
        locality.reset_pool()
        BlockLocationRegistry.reset()
        TpuShuffleManager.reset()
        m.MetricsRegistry.reset_for_tests()


# -- compression accounting -------------------------------------------------


def test_manager_tracks_per_shuffle_compression_ratio():
    from spark_rapids_tpu.columnar.device import batch_to_device
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    rb = pa.record_batch({"a": pa.array(np.arange(256, dtype=np.int64))})
    mgr.write_map_output(55, 0, {0: batch_to_device(rb, xp=np)})
    try:
        assert mgr.compression_stats(55) is None  # nothing served yet
        mgr.note_payload_sizes(55, 1000, 400)
        mgr.note_payload_sizes(55, 1000, 600)
        st = mgr.compression_stats(55)
        assert st == {"raw_bytes": 2000, "compressed_bytes": 1000,
                      "ratio": 0.5}
        mgr.unregister(55)
        assert mgr.compression_stats(55) is None  # dropped with shuffle
    finally:
        TpuShuffleManager.reset()


def test_spill_tiers_record_raw_vs_serialized_bytes(tmp_path):
    """spill_to_disk must account compressed-vs-raw per tier so the
    codec's effect on the spill path is observable, not inferred."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.memory import meta
    from spark_rapids_tpu.memory.spill import SpillCatalog
    m.MetricsRegistry.reset_for_tests()
    meta.set_default_codec("lz4")
    try:
        cat = SpillCatalog(device_budget=1 << 30, host_budget=1 << 30,
                           spill_dir=str(tmp_path))
        rb = pa.record_batch(
            {"a": pa.array(np.arange(4096, dtype=np.int64))})
        sb = cat.register(batch_to_device(rb, xp=np))
        sb.spill_to_host()
        sb.spill_to_disk()
        raw_c = m.counter("tpu_spill_raw_bytes_total",
                          labelnames=("tier",))
        ser_c = m.counter("tpu_spill_serialized_bytes_total",
                          labelnames=("tier",))
        for tier in ("host", "disk"):
            assert raw_c.value(tier=tier) > 0
            assert ser_c.value(tier=tier) > 0
            # lz4 on sequential int64 lanes: serialized < raw
            assert ser_c.value(tier=tier) < raw_c.value(tier=tier)
        sb.close()
    finally:
        meta.set_default_codec("none")
        m.MetricsRegistry.reset_for_tests()
