"""Expand (rollup/cube), Generate (explode), Sample, TopN differential
tests (model: integration_tests generate_expr_test.py /
hash_aggregate_test.py rollup cases / limit tests)."""

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect)
from spark_rapids_tpu.testing.data_gen import (
    ArrayGen, IntegerGen, LongGen, StringGen, gen_df)


def _arr_df(spark, elem_gen, length=128, parts=1):
    return gen_df(spark, [("i", IntegerGen()),
                          ("arr", ArrayGen(elem_gen, max_len=5))],
                  length=length, seed=40, num_partitions=parts)


@pytest.mark.parametrize("outer", [False, True])
def test_explode_ints(outer):
    def q(spark):
        df = _arr_df(spark, IntegerGen(null_prob=0.1))
        f = F.explode_outer if outer else F.explode
        return df.select(col("i"), f(col("arr")).alias("e"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_explode_runs_on_tpu():
    """Generate over array<int> must be TPU-placed, not a silent fallback."""
    from spark_rapids_tpu.testing.asserts import _TPU_CONF, _mk
    session = _mk(dict(_TPU_CONF))
    df = _arr_df(session, IntegerGen())
    df.select(col("i"), F.explode(col("arr")).alias("e")).collect()
    placements = []
    session.last_plan.foreach(
        lambda e: placements.append(e.placement)
        if type(e).__name__ == "GenerateExec" else None)
    assert placements == ["tpu"], placements


@pytest.mark.parametrize("outer", [False, True])
def test_posexplode(outer):
    def q(spark):
        df = _arr_df(spark, LongGen())
        f = F.posexplode_outer if outer else F.posexplode
        return df.select(col("i"), f(col("arr")))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_explode_strings():
    def q(spark):
        df = _arr_df(spark, StringGen(max_len=6))
        return df.select(col("i"), F.explode(col("arr")).alias("s"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_explode_then_aggregate():
    def q(spark):
        df = _arr_df(spark, IntegerGen(lo=0, hi=10), length=256)
        return (df.select(F.explode(col("arr")).alias("e"))
                  .group_by(col("e")).agg(F.count("*").alias("c")))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_size_and_contains():
    def q(spark):
        df = _arr_df(spark, IntegerGen(lo=0, hi=5, null_prob=0.2))
        return df.select(col("i"), F.size(col("arr")).alias("n"),
                         F.array_contains(col("arr"), 3).alias("has3"))
    assert_tpu_and_cpu_are_equal_collect(q)


@pytest.mark.parametrize("asc", [True, False])
def test_sort_array(asc):
    def q(spark):
        df = _arr_df(spark, IntegerGen(null_prob=0.15))
        return df.select(col("i"), F.sort_array(col("arr"), asc).alias("s"))
    assert_tpu_and_cpu_are_equal_collect(q)


# ---------------------------------------------------------------------------
# rollup / cube via Expand
# ---------------------------------------------------------------------------

def _kv_df(spark, parts=1):
    return gen_df(spark, [("a", IntegerGen(lo=0, hi=4, null_prob=0.1)),
                          ("b", IntegerGen(lo=0, hi=3)),
                          ("v", LongGen())],
                  length=256, seed=41, num_partitions=parts)


def test_rollup():
    def q(spark):
        return (_kv_df(spark).rollup("a", "b")
                .agg(F.sum(col("v")).alias("sv"),
                     F.count("*").alias("c")))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_cube():
    def q(spark):
        return (_kv_df(spark).cube("a", "b")
                .agg(F.sum(col("v")).alias("sv")))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_rollup_distributed():
    def q(spark):
        return (_kv_df(spark, parts=3).rollup("a", "b")
                .agg(F.count("*").alias("c"),
                     F.min(col("v")).alias("mv")))
    assert_tpu_and_cpu_are_equal_collect(q)


# ---------------------------------------------------------------------------
# sample / TopN
# ---------------------------------------------------------------------------

def test_sample_deterministic():
    def q(spark):
        df = gen_df(spark, [("x", LongGen())], length=1024, seed=42)
        return df.sample(0.3, seed=7)
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q)
    assert 0 < cpu.num_rows < 1024


def test_sample_fraction_bounds():
    def q(spark):
        df = gen_df(spark, [("x", LongGen())], length=512, seed=43,
                    num_partitions=2)
        return df.sample(1.0, seed=1)
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q)
    assert cpu.num_rows == 512


def test_topn():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen()), ("v", LongGen())],
                    length=512, seed=44, num_partitions=4)
        return df.order_by(col("v"), ascending=False).limit(10)
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert cpu.num_rows == 10


def test_topn_no_exchange():
    """TopN must not plan a range-partition exchange."""
    from spark_rapids_tpu.testing.asserts import _TPU_CONF, _mk
    session = _mk(dict(_TPU_CONF))
    df = gen_df(session, [("v", LongGen())], length=256, seed=45,
                num_partitions=4)
    df.order_by(col("v")).limit(5).collect()
    names = []
    session.last_plan.foreach(lambda e: names.append(type(e).__name__))
    assert "ShuffleExchangeExec" not in names, names


def test_rollup_aggregate_over_grouping_key():
    """Aggregating a grouping key must see original values in subtotal rows
    (Spark keeps separate agg-input and grouping-output copies in Expand)."""
    def q(spark):
        import pyarrow as pa
        df = spark.create_dataframe(pa.table(
            {"k": pa.array([1, 2, 2]), "v": pa.array([10, 20, 30])}))
        return df.rollup("k").agg(F.sum(col("k")).alias("sk"),
                                  F.count("*").alias("c"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q)
    rows = sorted(cpu.to_pylist(), key=str)
    total = [r for r in rows if r["k"] is None]
    assert total[0]["sk"] == 5, rows  # 1+2+2, not null


def test_grouping_id():
    def q(spark):
        import pyarrow as pa
        df = spark.create_dataframe(pa.table(
            {"a": pa.array([1, 1]), "b": pa.array([2, 3]),
             "v": pa.array([5, 6])}))
        return df.rollup("a", "b").agg(F.sum(col("v")).alias("sv"),
                                       F.grouping_id().alias("gid"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q)
    gids = sorted({r["gid"] for r in cpu.to_pylist()})
    assert gids == [0, 1, 3], gids
