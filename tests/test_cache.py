"""Cached-batch serializer tests (model: the reference's
tests-spark310+ cache-serializer suites + cache_test.py)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.io.cached_batch import CacheManager


@pytest.fixture(autouse=True)
def _clear_cache():
    yield
    CacheManager.clear()


def _session(**extra):
    b = TpuSession.builder().config("spark.rapids.sql.enabled", True)
    for k, v in extra.items():
        b = b.config(k, v)
    return b.get_or_create()


def _table(n=500):
    rng = np.random.default_rng(0)
    return pa.table({"k": pa.array(rng.integers(0, 10, n).astype(np.int64)),
                     "v": pa.array(rng.random(n))})


def _plan_names(s):
    out = []
    s.last_plan.foreach(lambda e: out.append(type(e).__name__))
    return out


def test_cache_materializes_then_serves_cached_scan():
    s = _session()
    df = s.create_dataframe(_table(), num_partitions=3).cache()
    assert df.is_cached
    first = df.collect()
    assert "CacheWriteExec" in _plan_names(s)
    second = df.collect()
    assert "CachedScanExec" in _plan_names(s)
    assert "LocalScanExec" not in _plan_names(s)  # source not re-read
    assert second.sort_by("v").equals(first.sort_by("v"))


def test_cached_subtree_reused_by_downstream_query():
    s = _session()
    df = s.create_dataframe(_table()).cache()
    df.collect()  # materialize
    out = df.group_by(col("k")).agg(F.count("*").alias("c")).collect()
    assert sum(out.column("c").to_pylist()) == 500
    assert "CachedScanExec" in _plan_names(s)


def test_unpersist_recomputes_from_source():
    s = _session()
    df = s.create_dataframe(_table()).cache()
    df.collect()
    df.unpersist()
    assert not df.is_cached
    df.collect()
    assert "CachedScanExec" not in _plan_names(s)
    assert "LocalScanExec" in _plan_names(s)


def test_limit_does_not_poison_cache():
    s = _session()
    df = s.create_dataframe(_table(), num_partitions=4).cache()
    # a limited action may not run every partition to completion
    df.limit(5).collect()
    full = df.collect()
    assert full.num_rows == 500


def test_cache_gated_by_shim_dialect():
    s = _session(**{"spark.rapids.tpu.sparkVersion": "3.0.1"})
    df = s.create_dataframe(_table()).cache()
    assert not df.is_cached  # 3.0.x dialect: no parquet cache serializer
    assert df.collect().num_rows == 500


def test_cache_preserves_nulls_and_strings():
    s = _session()
    tb = pa.table({"s": pa.array(["a", None, "ccc", "dd", None]),
                   "v": pa.array([1, 2, None, 4, 5], type=pa.int64())})
    df = s.create_dataframe(tb).cache()
    df.collect()
    out = df.collect()
    assert out.column("s").to_pylist() == ["a", None, "ccc", "dd", None]
    assert out.column("v").to_pylist() == [1, 2, None, 4, 5]
