"""Tier-1 gate for the repo lint: the package must stay clean modulo
the checked-in baseline (devtools/lint_baseline.txt), so any NEW
invariant violation fails the suite — the ratchet devtools/run_lint.py
applies in CI."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "devtools", "lint_baseline.txt")


def test_repo_lint_clean_modulo_baseline():
    from spark_rapids_tpu.analysis.diagnostics import format_diagnostics
    from spark_rapids_tpu.analysis.repo_lint import (lint_repo,
                                                     load_baseline,
                                                     new_violations)
    fresh = new_violations(lint_repo(REPO), load_baseline(BASELINE))
    assert not fresh, (
        "new repo-lint violations (run devtools/run_lint.py "
        "--update-baseline only if intentional):\n"
        + format_diagnostics(fresh))


def test_baseline_entries_are_not_stale():
    """A baseline line whose violation disappeared is debt already paid:
    fail so it gets deleted and the ratchet tightens."""
    from spark_rapids_tpu.analysis.repo_lint import (lint_repo,
                                                     load_baseline)
    current = {d.fingerprint() for d in lint_repo(REPO)}
    stale = load_baseline(BASELINE) - current
    assert not stale, f"stale baseline entries, remove them: {stale}"


def test_run_lint_cli_exits_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_run_lint_interp_gate_exits_zero():
    """Tier-1 gate for the flow-sensitive plan typechecker: zero false
    rejects + differential-oracle agreement on the good corpus, zero
    false admits on the bad corpus.  Any interpreter regression fails
    fast here."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--interp"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "gate clean" in proc.stdout, proc.stdout


def test_run_lint_memsan_gate_exits_zero():
    """Tier-1 gate for tmsan: every golden good plan replays under the
    shadow ledger with measured peak device bytes <= the static
    TPU-L014 bound and a clean ledger afterwards; the memory hazard
    fixtures (L013/L014/L015) each produce their diagnostic."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--memsan"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "memsan gate clean" in proc.stdout, proc.stdout


def test_run_lint_obs_gate_exits_zero():
    """Tier-1 gate for the flight recorder: one golden query replays
    with tracing + the self-emitted event log on, and the gate fails on
    unclosed spans, an unflushed/unparsable log, or live-vs-parsed
    aggregate drift."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--obs"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "obs gate clean" in proc.stdout, proc.stdout


def test_run_lint_regress_gate_exits_zero():
    """Tier-1 gate for the cross-run watchdog: the golden corpus
    replays twice in fresh subprocesses and the two runs' DETERMINISTIC
    fingerprints must be identical; the differ must flag an injected
    fallback and an injected crossing bump (anti-vacuity)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--regress"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "regress gate clean" in proc.stdout, proc.stdout


def test_run_lint_metrics_gate_exits_zero():
    """Tier-1 gate for the continuous-metrics layer: one golden query
    plus one bridge round trip must expose nonzero Prometheus series
    from >= 6 distinct subsystems (spill, arena, shuffle, fetch,
    session, bridge) and a schema-valid health snapshot."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--metrics"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "metrics gate clean" in proc.stdout, proc.stdout


def test_run_lint_jit_gate_exits_zero():
    """Tier-1 gate for the compile observatory: the golden corpus
    replays twice in one process with ZERO second-pass program builds
    (shape-canonicalization honesty), the compile ledger / jit.build
    spans / tpu_jit_misses_total agree on the build count, >= 95% of
    wall compile time is attributed with every build carrying a cause,
    and injected bucket/dtype perturbations classify as
    shape_churn/dtype_churn."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--jit"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "jit gate clean" in proc.stdout, proc.stdout


def test_run_lint_shuffle_gate_exits_zero():
    """Tier-1 gate for the distributed shuffle: the forced-shuffled-join
    bridge golden replays under the memsan shadow ledger with a 1-byte
    spill budget (every map-output block demotes and must come back
    correct), the catalog and ledger must be clean after stage release,
    the slice-view write must bank nonzero saved bytes, and a TCP
    transport leg's fetch counters must agree with the served blocks."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--shuffle"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "shuffle gate clean" in proc.stdout, proc.stdout


def test_run_lint_serve_gate_exits_zero():
    """Tier-1 gate for multi-tenant serving: the golden four-query mix
    replays 16 times across 4 concurrent pooled sessions under
    byte-weighted admission — every result must equal the serial ground
    truth, the admission books must balance (admitted = completed +
    failed, zero timeouts, peak ticket bytes within budget), and no
    dirty ledger, shuffle block, or spillable buffer may survive the
    drain."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--serve"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "serve gate clean" in proc.stdout, proc.stdout


def test_run_lint_slo_gate_exits_zero():
    """Tier-1 gate for the latency observatory: every golden-mix
    query's critical-path segments must sum to wall within tolerance
    with the span/counter/ledger sinks agreeing; an injected whale
    (sleep-armed FilterExec + inflated admission ticket on pool-0)
    must flip the sustained-burn health rule naming the victim tenants
    and appear as tail-report's queue_wait culprit while victim p50
    stays compute-dominated (anti-vacuity both ways); and the
    extraction overhead must stay under 5% of query wall."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--slo"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "slo gate clean" in proc.stdout, proc.stdout


def test_run_lint_csan_gate_exits_zero():
    """Tier-1 gate for tpucsan: the concurrency repo pass (TPU-R008/
    R009/R010) must be clean modulo the baseline, the ABBA/shared-write/
    condvar fixtures must each trip (anti-vacuity), and the serve golden
    mix must replay under the runtime lock witness with zero unmodeled
    acquisition edges and zero observed lock-order cycles."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--csan"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "csan gate clean" in proc.stdout, proc.stdout


def test_run_lint_feedback_gate_exits_zero():
    """Tier-1 gate for the estimator observatory: the golden corpus
    replays cold (recording) then warm (feedback-blended) and the warm
    replay's mean relative row error must be STRICTLY below cold; two
    warm replays over identical ledger snapshots must show zero
    deterministic drift; an injected 100x row misestimate must provably
    re-plan at the exchange boundary with the replan span, the
    tpu_replan_total metric, and the estimator ledger all agreeing —
    and bit-exact results throughout."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--feedback"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "feedback gate clean" in proc.stdout, proc.stdout


def test_run_lint_fleet_gate_exits_zero():
    """Tier-1 gate for the fleet observatory: a golden join fetched
    from TWO serve_map child processes must produce one merged trace
    with every producer's serve spans nested under the consumer's
    fetch spans and zero lost spans; the aggregator must expose rollup
    series and an ok verdict for both peers; killing a peer mid-fleet
    must degrade the verdict AND surface the orphan-span counter —
    anti-vacuity in both directions."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--fleet"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "fleet gate clean" in proc.stdout, proc.stdout


def test_run_lint_hbm_gate_exits_zero():
    """Tier-1 gate for the HBM observatory: the tenant memory timeline,
    the memsan shadow ledger and the spill catalog must agree
    byte-for-byte on a golden replay's peak device occupancy; a
    4-session pool stress must book every lifecycle event under its
    pool tenant with the tpu_hbm_tenant_bytes gauges summing to the
    timeline's live total; an injected context-free allocation must
    trip the unattributed counter and an injected operator failure must
    leave exactly one parseable post-mortem bundle naming the failing
    operator (anti-vacuity both ways)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--hbm"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "hbm gate clean" in proc.stdout, proc.stdout


def test_run_lint_progress_gate_exits_zero():
    """Tier-1 gate for the progress observatory: the golden serve mix
    must finish at ratio 1.0 with live-view partition counts
    reconciling exactly to closed operator spans, a probed query must
    show monotone mid-flight ratios that actually move, an injected
    stall must trip the watchdog naming the deepest open operator
    (degraded /healthz, black-boxed, then auto-cancelled with
    cause=watchdog), cancels injected during compute / queue-wait /
    remote-fetch plus a blown deadline_ms must each propagate their
    exact typed error with balanced books and exactly one classified
    bundle, and tracker hook overhead must stay under 5% of query
    wall with the on/off anti-vacuity check proving the hooks are the
    thing measured."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--progress"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "progress gate clean" in proc.stdout, proc.stdout


def test_run_lint_faults_gate_exits_zero():
    """Tier-1 gate for tpufsan: the exception-flow repo pass (TPU-R011/
    R012/R013/R014) must be clean, the raise-graph must plan >= 50
    statically-reachable (seam, typed-error) injection pairs with zero
    untyped operational leaks, and the fault-injection campaign must
    then execute every pair for real — each injected error propagating
    with its exact type, the books balancing afterwards (no orphaned
    shuffle blocks, spill leaks, stranded admission bytes or open
    spans) and exactly one parseable post-mortem bundle per failure;
    the background thread roots (heartbeat, metrics HTTP) must survive
    their faults and surface them via tpu_background_errors_total plus
    a degraded health verdict (anti-vacuity: planted orphans must trip
    the books check, an untyped injection must trip the propagation
    check)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--faults"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "faults gate clean" in proc.stdout, proc.stdout


def test_run_lint_dsan_gate_exits_zero():
    """Tier-1 gate for tpudsan: the determinism repo pass (TPU-R015/
    R016 + the L017 fingerprint-hygiene check) must be finding-free
    with zero frozen baseline debt, the planted rule fixtures must
    each trip, every golden-corpus exchange site must reproduce its
    content-addressed block digests under permuted batch arrival and a
    changed input split (write-time digests cross-checked against
    recomputes), and the two planted nondeterminism injections (an
    arrival-order float sum, a PYTHONHASHSEED-dependent set-iteration
    router) must each produce DIFFERENT digests — the oracle provably
    sees real nondeterminism."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--dsan"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dsan gate clean" in proc.stdout, proc.stdout


def test_run_lint_hlo_gate_exits_zero():
    """Tier-1 gate for tpuxsan: the golden corpus replays with
    StableHLO + cost_analysis() persistence on — every build's
    hlo_hash must resolve to exactly one deduped artifact, the
    analytic cost model must agree with XLA's bytes-accessed on
    >= 90% of compiled programs, the padding books must reconcile
    three ways (span padWasteBytes vs live-row recomputation vs the
    tpu_pad_waste_bytes_total counter), the L018/L019/L020/R017
    fixtures must trip with their clean twins silent, the L018
    repair must arm only when a genuinely smaller bucket exists, an
    injected pathological bucket (1M capacity over 10 live rows)
    must book the exact padding delta, and `tools kernel-report`
    must rank the grouped-aggregate and hash-join fusions with
    nonzero projected savings."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "devtools", "run_lint.py"),
         "--hlo"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "hlo gate clean" in proc.stdout, proc.stdout


def test_baseline_is_empty_and_stays_empty():
    """PR-3 burned the last baselined TPU-R001 debt down to zero: the
    ratchet now enforces a spotless repo (deliberate exceptions are
    `tpulint: allow[...]` annotations in place, not baseline lines)."""
    from spark_rapids_tpu.analysis.repo_lint import load_baseline
    assert load_baseline(BASELINE) == set()


def test_lint_cli_plan_mode_flags_goldens():
    proc = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--plan", os.path.join(REPO, "tests", "goldens", "lint",
                                "bad_plans.py")],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    # golden bad plans contain errors by design -> rc 1, all codes shown
    assert proc.returncode == 1, proc.stdout + proc.stderr
    for code in ("TPU-L001", "TPU-L004"):
        assert code in proc.stdout, proc.stdout
