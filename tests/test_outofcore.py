"""Out-of-core sort + aggregate merge (ref GpuSortExec.scala:231,
aggregate.scala:309-314): partitions several times larger than the spill
device budget must still produce exact results, with the SpillCatalog
recording nonzero spilled bytes."""

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pytest

from spark_rapids_tpu.exec.base import TPU, ExecContext
from spark_rapids_tpu.exec.basic import LocalScanExec
from spark_rapids_tpu.exec.sort import SortExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.expr.aggregates import (COMPLETE, AggregateExpression,
                                              Count, Min, Sum)
from spark_rapids_tpu.expr.core import AttributeReference as A
from spark_rapids_tpu.memory.spill import SpillCatalog


@pytest.fixture
def tiny_budget_catalog():
    """Install a catalog whose device budget forces out-of-core paths."""
    old = SpillCatalog._instance
    cat = SpillCatalog(device_budget=1 << 20, host_budget=4 << 20)
    SpillCatalog._instance = cat
    yield cat
    SpillCatalog._instance = old


def _fact(n=60_000, keys=None, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, keys if keys else n, n)
                      .astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
        "f": pa.array(rng.random(n)),
    })


def _batch_bytes_estimate(rows):
    return rows * (8 + 8 + 8 + 3)  # 3 int64-ish cols + validity


@pytest.mark.parametrize("placement", ["tpu", "cpu"])
def test_out_of_core_sort(tiny_budget_catalog, placement):
    tb = _fact(60_000)
    # ~4096-row batches of 3x8B cols ≈ 100KB each; 15 batches ≈ 1.5MB
    # against a 1MB budget -> external merge required
    scan = LocalScanExec(tb, num_partitions=1, batch_rows=4096)
    sort = SortExec([(A("k"), True, True), (A("v"), True, True)], scan)
    if placement == "tpu":
        scan.placement = TPU
        sort.placement = TPU
    out = sort.execute_collect(ExecContext())
    want = tb.sort_by([("k", "ascending"), ("v", "ascending")])
    assert out.column("k").to_pylist() == want.column("k").to_pylist()
    assert out.column("v").to_pylist() == want.column("v").to_pylist()
    assert np.allclose(out.column("f").to_numpy(),
                       want.column("f").to_numpy())
    assert tiny_budget_catalog.spilled_to_host_bytes > 0, \
        "out-of-core sort must have spilled"


@pytest.mark.parametrize("placement", ["tpu", "cpu"])
def test_out_of_core_aggregate_merge(tiny_budget_catalog, placement):
    # high-cardinality keys: partial outputs stay large, forcing the
    # bounded iterative merge
    tb = _fact(60_000, keys=50_000, seed=3)
    scan = LocalScanExec(tb, num_partitions=1, batch_rows=4096)
    aggs = [AggregateExpression(Sum(A("v")), "sv"),
            AggregateExpression(Count(None), "c"),
            AggregateExpression(Min(A("v")), "mn")]
    agg = TpuHashAggregateExec([A("k")], aggs, COMPLETE, scan)
    if placement == "tpu":
        scan.placement = TPU
        agg.placement = TPU
    out = agg.execute_collect(ExecContext()).sort_by("k")
    grouped = tb.group_by("k").aggregate(
        [("v", "sum"), ("v", "count"), ("v", "min")]).sort_by("k")
    assert out.column("k").to_pylist() == grouped.column("k").to_pylist()
    assert out.column("sv").to_pylist() == \
        grouped.column("v_sum").to_pylist()
    assert out.column("c").to_pylist() == \
        grouped.column("v_count").to_pylist()
    assert out.column("mn").to_pylist() == \
        grouped.column("v_min").to_pylist()


def test_aggregate_sort_fallback(tiny_budget_catalog):
    """Budget below two compacted partials -> the iterative merge cannot
    pair anything and must take the sort-based re-aggregation path."""
    cat = SpillCatalog(device_budget=220_000, host_budget=4 << 20)
    SpillCatalog._instance = cat
    tb = _fact(40_000, keys=39_000, seed=7)
    scan = LocalScanExec(tb, num_partitions=1, batch_rows=8192)
    aggs = [AggregateExpression(Sum(A("v")), "sv"),
            AggregateExpression(Count(None), "c")]
    agg = TpuHashAggregateExec([A("k")], aggs, COMPLETE, scan)
    scan.placement = TPU
    agg.placement = TPU
    out = agg.execute_collect(ExecContext()).sort_by("k")
    grouped = tb.group_by("k").aggregate(
        [("v", "sum"), ("v", "count")]).sort_by("k")
    assert out.column("k").to_pylist() == grouped.column("k").to_pylist()
    assert out.column("sv").to_pylist() == \
        grouped.column("v_sum").to_pylist()
    assert out.column("c").to_pylist() == \
        grouped.column("v_count").to_pylist()


def test_out_of_core_sort_with_strings(tiny_budget_catalog):
    rng = np.random.default_rng(11)
    n = 30_000
    tb = pa.table({
        "s": pa.array([f"key-{x:06d}" for x in
                       rng.integers(0, 100_000, n)]),
        "v": pa.array(rng.integers(-50, 50, n).astype(np.int64)),
    })
    scan = LocalScanExec(tb, num_partitions=1, batch_rows=4096)
    sort = SortExec([(A("s"), True, True)], scan)
    scan.placement = TPU
    sort.placement = TPU
    out = sort.execute_collect(ExecContext())
    want = tb.sort_by([("s", "ascending")])
    assert out.column("s").to_pylist() == want.column("s").to_pylist()
