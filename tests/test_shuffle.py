"""Shuffle tests: partitioning kernels, exchange-based multi-partition
queries, the TCP transport client/server, and heartbeats
(model: tests/.../shuffle suites — in-process, no real cluster)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.shuffle.heartbeat import (HeartbeatEndpoint,
                                                HeartbeatManager)
from spark_rapids_tpu.shuffle.manager import (ShuffleBlockId,
                                              TpuShuffleManager)
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect)
from spark_rapids_tpu.testing.data_gen import (IntegerGen, LongGen,
                                               StringGen, gen_df)


def test_hash_partition_ids_consistent_engines():
    """Murmur3 partition routing must agree across engines."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.expr.core import EvalContext, AttributeReference
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioning
    rb = pa.record_batch({"k": pa.array([1, 2, 3, None, 5, 6, 7, 8],
                                        type=pa.int64())})
    part = HashPartitioning([AttributeReference("k")], 4).bind(
        ["k"], [__import__("spark_rapids_tpu.types",
                           fromlist=["LONG"]).LONG])
    out = {}
    for xp in (np, jnp):
        b = batch_to_device(rb, xp=xp)
        ctx = EvalContext(xp, b)
        pids = part.partition_ids(xp, ctx, b)
        out[xp.__name__] = np.asarray(pids)[:8].tolist()
    assert out["numpy"] == out["jax.numpy"]
    assert all(0 <= p < 4 for p in out["numpy"])


@pytest.mark.parametrize("n_parts", [2, 4])
def test_multi_partition_aggregate(n_parts):
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen(lo=0, hi=40)),
                            ("v", LongGen())], length=2048,
                    num_partitions=n_parts)
        return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"),
                                         F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_multi_partition_string_group():
    def q(spark):
        df = gen_df(spark, [("k", StringGen(max_len=5)),
                            ("v", LongGen())], length=1024,
                    num_partitions=3)
        return df.group_by(col("k")).agg(F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_multi_partition_join():
    def q(spark):
        a = gen_df(spark, [("k", IntegerGen(lo=0, hi=30)),
                           ("va", LongGen())], length=512, seed=1,
                   num_partitions=3)
        b = gen_df(spark, [("k2", IntegerGen(lo=0, hi=30)),
                           ("vb", LongGen())], length=256, seed=2,
                   num_partitions=2)
        return a.join(b, on=(col("k") == col("k2")), how="inner")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_expanding_join_through_exchange_is_exact():
    """Regression: a speculative hash join whose output EXCEEDS the
    probe batch capacity, feeding a shuffle exchange that materializes
    under the AQE reader's private ExecContext.  The failed sizing
    guard used to die with that private context, so the catalog kept
    the TRUNCATED map blocks and the query silently lost rows (each
    partition contributed exactly its capacity-bucket of join output).
    The reader must now verify the guards itself and rewrite the map
    stage without speculation."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.obs import metrics as m
    rng = np.random.default_rng(11)
    n, k, dups = 32_768, 4_096, 4     # 8192-row partitions, 4x expansion
    fact = pa.table({
        "k": pa.array(rng.integers(0, k, n).astype(np.int64)),
        "v": pa.array(rng.integers(-100, 100, n).astype(np.int64))})
    dim = pa.table({
        "k": pa.array(np.repeat(np.arange(k, dtype=np.int64), dups)),
        "w": pa.array(np.arange(k * dups, dtype=np.int64))})
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.singleChipFuse", "off")
         .get_or_create())
    fdf = s.create_dataframe(fact, num_partitions=4)
    ddf = s.create_dataframe(dim, num_partitions=4)
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
    blocks_before = TpuShuffleManager.get().catalog.num_blocks()
    out = (fdf.join(ddf, on="k", how="left").group_by(col("k"))
           .agg(F.sum(col("w")).alias("sw"), F.count("*").alias("c"))
           .collect())
    # a replicated build reader with a stale pre-clone partner used to
    # shuffle the probe side a second time during planning and leak
    # every block it wrote (no plan node owned that shuffle id)
    assert TpuShuffleManager.get().catalog.num_blocks() == blocks_before
    kf = fact.column("k").to_numpy()
    sum_w = np.zeros(k, np.int64)
    np.add.at(sum_w, dim.column("k").to_numpy(),
              dim.column("w").to_numpy())
    fcnt = np.bincount(kf, minlength=k)
    present = np.flatnonzero(fcnt)
    assert out.num_rows == len(present)
    # every probe row matches `dups` build rows: exact totals, no
    # capacity-truncated partial input
    assert sum(out.column("c").to_pylist()) == n * dups
    order = np.argsort(out.column("k").to_numpy())
    assert np.array_equal(np.sort(out.column("k").to_numpy()), present)
    assert np.array_equal(
        np.asarray(out.column("sw").to_numpy())[order],
        (fcnt * sum_w)[present])


def test_shuffled_join_releases_all_planning_shuffles():
    """Regression: transition insertion clones every node, and its
    num_partitions probe EXECUTES the plan (the AQE reader over the agg
    exchange materializes its map stage to size its specs).  The
    replicated build reader's ``replicate_for`` still pointed at the
    PRE-clone probe partner at that moment, so the stale partner
    shuffled the probe side a second time — a shuffle no node in the
    final plan owned, leaking every block it wrote.  Partners must be
    relinked before anything can trigger materialization."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
    rng = np.random.default_rng(7)
    dim_rows, probe_rows = 4_096, 16_384
    dim = pa.table({"k": pa.array(np.arange(dim_rows, dtype=np.int64)),
                    "w": pa.array(np.arange(dim_rows, dtype=np.int64))})
    fact = pa.table({"k": pa.array(
        rng.integers(0, dim_rows, probe_rows).astype(np.int64))})
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.singleChipFuse", "off")
         .config("spark.rapids.sql.autoBroadcastJoinThreshold", 1024)
         .get_or_create())
    fdf = s.create_dataframe(fact, num_partitions=4)
    ddf = s.create_dataframe(dim, num_partitions=4)
    before = TpuShuffleManager.get().catalog.num_blocks()
    out = (fdf.join(ddf, on="k", how="left").group_by(col("k"))
           .agg(F.sum(col("w")).alias("sw")).collect())
    kinds = []
    s.last_plan.foreach(lambda e: kinds.append(type(e).__name__))
    assert "ShuffledHashJoinExec" in kinds
    assert out.num_rows == len(np.unique(fact.column("k").to_numpy()))
    assert TpuShuffleManager.get().catalog.num_blocks() == before


def test_multi_partition_global_sort():
    def q(spark):
        df = gen_df(spark, [("a", IntegerGen()), ("b", LongGen())],
                    length=1024, num_partitions=4)
        return df.order_by(col("a"), col("b"))
    assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)


def test_repartition_roundtrip():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen()), ("v", LongGen())],
                    length=512, num_partitions=2)
        return df.repartition(5, col("k")).group_by(col("k")).agg(
            F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_shuffle_serialization_roundtrip():
    from spark_rapids_tpu.columnar.device import (batch_to_arrow,
                                                  batch_to_device)
    from spark_rapids_tpu.memory.meta import (deserialize_batch,
                                              serialize_batch)
    rb = pa.record_batch({
        "a": pa.array([1, None, 3], type=pa.int64()),
        "s": pa.array(["x", "yy", None])})
    b = batch_to_device(rb, xp=np)
    data = serialize_batch(b)
    back = deserialize_batch(data, xp=np)
    assert batch_to_arrow(back).to_pylist() == rb.to_pylist()


def test_transport_fetch():
    """Client/server over real sockets, serving catalog blocks."""
    from spark_rapids_tpu.columnar.device import (batch_to_arrow,
                                                  batch_to_device)
    from spark_rapids_tpu.shuffle.transport import (ShuffleClient,
                                                    ShuffleServer)
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    rb = pa.record_batch({"a": pa.array(list(range(100)), type=pa.int64())})
    b = batch_to_device(rb, xp=np)
    mgr.write_map_output(7, 0, {3: b})
    server = ShuffleServer(mgr).start()
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        metas = cli.fetch_metadata(7, 3).wait(10)
        assert len(metas) == 1
        (sid, mid, rid, idx), meta = metas[0]
        assert (sid, mid, rid) == (7, 0, 3)
        assert meta.num_rows == 100
        got = cli.fetch_block(sid, mid, rid, idx).wait(10)
        assert batch_to_arrow(got).to_pylist() == rb.to_pylist()
        # error path: missing block -> fetch-failed
        from spark_rapids_tpu.shuffle.errors import (
            TpuShuffleFetchFailedError)
        with pytest.raises(TpuShuffleFetchFailedError):
            cli.fetch_block(7, 0, 3, 99).wait(10)
        cli.close()
    finally:
        server.stop()
        TpuShuffleManager.reset()


def _serve_blocks(n_maps=4, rows=64, shuffle_id=11, reduce_id=2):
    """A manager with n_maps map outputs for one reduce partition, plus
    a running server. Caller owns cleanup."""
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.shuffle.transport import ShuffleServer
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    for mid in range(n_maps):
        rb = pa.record_batch({"a": pa.array(
            [mid * 1000 + i for i in range(rows)], type=pa.int64())})
        mgr.write_map_output(shuffle_id, mid,
                             {reduce_id: batch_to_device(rb, xp=np)})
    return mgr, ShuffleServer(mgr).start()


def test_async_fetcher_happy_path():
    """Pipelined fetch yields every block in order and counts blocks +
    bytes in the tpu_shuffle_fetch_* metrics."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.columnar.device import batch_to_arrow
    from spark_rapids_tpu.shuffle.transport import (AsyncBlockFetcher,
                                                    ShuffleClient)
    m.MetricsRegistry.reset_for_tests()
    mgr, server = _serve_blocks(n_maps=5)
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        fetched = [batch_to_arrow(b).column("a").to_pylist()[0]
                   for b in AsyncBlockFetcher(cli, 11, 2, window=2)]
        assert fetched == [0, 1000, 2000, 3000, 4000]
        assert m.counter("tpu_shuffle_fetch_blocks_total").value() == 5
        assert m.counter("tpu_shuffle_fetch_bytes_total").value() > 0
        cli.close()
    finally:
        server.stop()
        TpuShuffleManager.reset()
        m.MetricsRegistry.reset_for_tests()


def test_async_fetcher_server_killed_mid_fetch():
    """Killing the ShuffleServer while the iterator drains must surface
    a typed shuffle error (not hang, not a bare socket error) and count
    it in tpu_shuffle_fetch_errors_total."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.shuffle.errors import TpuShuffleFetchFailedError
    from spark_rapids_tpu.shuffle.transport import (AsyncBlockFetcher,
                                                    ShuffleClient)
    m.MetricsRegistry.reset_for_tests()
    mgr, server = _serve_blocks(n_maps=8)
    cli = ShuffleClient("127.0.0.1", server.port)
    try:
        it = iter(AsyncBlockFetcher(cli, 11, 2, window=1, timeout=5.0))
        next(it)  # first block arrives fine
        server.stop()
        server = None
        with pytest.raises(TpuShuffleFetchFailedError):
            for _ in it:
                pass
        errs = m.counter("tpu_shuffle_fetch_errors_total",
                         labelnames=("kind",))
        assert sum(errs.value(kind=k) for k in
                   ("fetch_failed", "timeout", "truncated")) >= 1
        cli.close()
    finally:
        if server is not None:
            server.stop()
        TpuShuffleManager.reset()
        m.MetricsRegistry.reset_for_tests()


def test_async_fetcher_heartbeat_dead_peer():
    """A peer that heartbeat expiry declares dead fails the fetch with
    TpuShufflePeerDeadError BEFORE paying a socket timeout."""
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.shuffle.errors import TpuShufflePeerDeadError
    from spark_rapids_tpu.shuffle.transport import (AsyncBlockFetcher,
                                                    ShuffleClient)
    import time
    m.MetricsRegistry.reset_for_tests()
    mgr, server = _serve_blocks(n_maps=2)
    try:
        hb = HeartbeatManager(timeout_s=0.2)
        hb.register_executor("peer-1", "127.0.0.1", server.port)
        time.sleep(0.4)  # peer-1 stops heartbeating -> expires
        cli = ShuffleClient("127.0.0.1", server.port)
        f = AsyncBlockFetcher(cli, 11, 2, heartbeat=hb, peer_id="peer-1")
        with pytest.raises(TpuShufflePeerDeadError) as ei:
            list(f)
        assert ei.value.peer_id == "peer-1"
        assert m.counter("tpu_shuffle_fetch_errors_total",
                         labelnames=("kind",)).value(kind="peer_dead") == 1
        cli.close()
    finally:
        server.stop()
        TpuShuffleManager.reset()
        m.MetricsRegistry.reset_for_tests()


def test_truncated_frame_typed_error():
    """A peer that dies mid-frame produces TpuShuffleTruncatedFrameError
    with the expected/got byte counts."""
    import socket
    import struct as _struct
    import threading
    from spark_rapids_tpu.shuffle.errors import (
        TpuShuffleTruncatedFrameError)
    from spark_rapids_tpu.shuffle.transport import (_FRAME,
                                                    MSG_METADATA_RESP,
                                                    ShuffleClient)

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]

    def liar():
        conn, _ = lsock.accept()
        conn.recv(4096)  # the metadata request
        # declare a 100-byte body, deliver 10, vanish
        conn.sendall(_FRAME.pack(MSG_METADATA_RESP, 1, 100) + b"x" * 10)
        conn.close()

    t = threading.Thread(target=liar, daemon=True)
    t.start()
    try:
        cli = ShuffleClient("127.0.0.1", port, timeout=5.0)
        with pytest.raises(TpuShuffleTruncatedFrameError) as ei:
            cli.fetch_metadata(1, 0).wait(5)
        assert ei.value.expected == 100 and ei.value.got == 10
        cli.close()
    finally:
        lsock.close()
        t.join(timeout=2)


def test_sliced_map_output_zero_leaks():
    """Slice-view write path: one spill registration per map batch, per-
    reduce views serve correct rows, and unregister releases everything
    (no leaked blocks, clean SpillCatalog)."""
    from spark_rapids_tpu.columnar.device import (batch_to_arrow,
                                                  batch_to_device)
    from spark_rapids_tpu.memory.spill import SpillCatalog
    from spark_rapids_tpu.shuffle.manager import materialize_block
    with SpillCatalog._lock:
        SpillCatalog._instance = SpillCatalog()
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    # rows sorted by target partition: [0..9]->r0, [10..24]->r1, [25..39]->r2
    rb = pa.record_batch({"a": pa.array(list(range(40)), type=pa.int64())})
    mgr.write_map_output_sorted(
        3, 0, batch_to_device(rb, xp=np),
        layout=[(0, 0, 10), (1, 10, 15), (2, 25, 15)])
    assert mgr.catalog.num_blocks() == 3
    assert mgr.catalog.device_bytes() > 0
    got = [materialize_block(b, np) for b in mgr.read_partition(3, 1)]
    assert len(got) == 1
    assert batch_to_arrow(got[0]).column("a").to_pylist() == \
        list(range(10, 25))
    mgr.unregister(3)
    assert mgr.catalog.num_blocks() == 0
    leaks = SpillCatalog.get().leak_report()
    assert not leaks, leaks
    TpuShuffleManager.reset()


def test_heartbeats():
    mgr = HeartbeatManager(timeout_s=0.5)
    seen = {}
    e1 = HeartbeatEndpoint(mgr, "exec-1", "h1", 1111, interval_s=0.1,
                           on_peers=lambda ps: seen.__setitem__(
                               "e1", [p.executor_id for p in ps]))
    peers2 = mgr.register_executor("exec-2", "h2", 2222)
    assert [p.executor_id for p in peers2] == ["exec-1"]
    e1.start()
    import time
    time.sleep(0.3)
    assert seen.get("e1") == ["exec-2"]
    e1.stop()
    # exec-1 stops heartbeating; after timeout it expires
    time.sleep(0.7)
    mgr.executor_heartbeat("exec-2")
    assert [p.executor_id for p in mgr.live_peers()] == ["exec-2"]
    assert mgr.expire_dead() == ["exec-1"]
