"""Shuffle tests: partitioning kernels, exchange-based multi-partition
queries, the TCP transport client/server, and heartbeats
(model: tests/.../shuffle suites — in-process, no real cluster)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.shuffle.heartbeat import (HeartbeatEndpoint,
                                                HeartbeatManager)
from spark_rapids_tpu.shuffle.manager import (ShuffleBlockId,
                                              TpuShuffleManager)
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect)
from spark_rapids_tpu.testing.data_gen import (IntegerGen, LongGen,
                                               StringGen, gen_df)


def test_hash_partition_ids_consistent_engines():
    """Murmur3 partition routing must agree across engines."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.device import batch_to_device
    from spark_rapids_tpu.expr.core import EvalContext, AttributeReference
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioning
    rb = pa.record_batch({"k": pa.array([1, 2, 3, None, 5, 6, 7, 8],
                                        type=pa.int64())})
    part = HashPartitioning([AttributeReference("k")], 4).bind(
        ["k"], [__import__("spark_rapids_tpu.types",
                           fromlist=["LONG"]).LONG])
    out = {}
    for xp in (np, jnp):
        b = batch_to_device(rb, xp=xp)
        ctx = EvalContext(xp, b)
        pids = part.partition_ids(xp, ctx, b)
        out[xp.__name__] = np.asarray(pids)[:8].tolist()
    assert out["numpy"] == out["jax.numpy"]
    assert all(0 <= p < 4 for p in out["numpy"])


@pytest.mark.parametrize("n_parts", [2, 4])
def test_multi_partition_aggregate(n_parts):
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen(lo=0, hi=40)),
                            ("v", LongGen())], length=2048,
                    num_partitions=n_parts)
        return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"),
                                         F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_multi_partition_string_group():
    def q(spark):
        df = gen_df(spark, [("k", StringGen(max_len=5)),
                            ("v", LongGen())], length=1024,
                    num_partitions=3)
        return df.group_by(col("k")).agg(F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_multi_partition_join():
    def q(spark):
        a = gen_df(spark, [("k", IntegerGen(lo=0, hi=30)),
                           ("va", LongGen())], length=512, seed=1,
                   num_partitions=3)
        b = gen_df(spark, [("k2", IntegerGen(lo=0, hi=30)),
                           ("vb", LongGen())], length=256, seed=2,
                   num_partitions=2)
        return a.join(b, on=(col("k") == col("k2")), how="inner")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_multi_partition_global_sort():
    def q(spark):
        df = gen_df(spark, [("a", IntegerGen()), ("b", LongGen())],
                    length=1024, num_partitions=4)
        return df.order_by(col("a"), col("b"))
    assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)


def test_repartition_roundtrip():
    def q(spark):
        df = gen_df(spark, [("k", IntegerGen()), ("v", LongGen())],
                    length=512, num_partitions=2)
        return df.repartition(5, col("k")).group_by(col("k")).agg(
            F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_shuffle_serialization_roundtrip():
    from spark_rapids_tpu.columnar.device import (batch_to_arrow,
                                                  batch_to_device)
    from spark_rapids_tpu.memory.meta import (deserialize_batch,
                                              serialize_batch)
    rb = pa.record_batch({
        "a": pa.array([1, None, 3], type=pa.int64()),
        "s": pa.array(["x", "yy", None])})
    b = batch_to_device(rb, xp=np)
    data = serialize_batch(b)
    back = deserialize_batch(data, xp=np)
    assert batch_to_arrow(back).to_pylist() == rb.to_pylist()


def test_transport_fetch():
    """Client/server over real sockets, serving catalog blocks."""
    from spark_rapids_tpu.columnar.device import (batch_to_arrow,
                                                  batch_to_device)
    from spark_rapids_tpu.shuffle.transport import (ShuffleClient,
                                                    ShuffleServer)
    TpuShuffleManager.reset()
    mgr = TpuShuffleManager.get()
    rb = pa.record_batch({"a": pa.array(list(range(100)), type=pa.int64())})
    b = batch_to_device(rb, xp=np)
    mgr.write_map_output(7, 0, {3: b})
    server = ShuffleServer(mgr).start()
    try:
        cli = ShuffleClient("127.0.0.1", server.port)
        metas = cli.fetch_metadata(7, 3).wait(10)
        assert len(metas) == 1
        (sid, mid, rid, idx), meta = metas[0]
        assert (sid, mid, rid) == (7, 0, 3)
        assert meta.num_rows == 100
        got = cli.fetch_block(sid, mid, rid, idx).wait(10)
        assert batch_to_arrow(got).to_pylist() == rb.to_pylist()
        # error path: missing block -> fetch-failed
        from spark_rapids_tpu.shuffle.errors import (
            TpuShuffleFetchFailedError)
        with pytest.raises(TpuShuffleFetchFailedError):
            cli.fetch_block(7, 0, 3, 99).wait(10)
        cli.close()
    finally:
        server.stop()
        TpuShuffleManager.reset()


def test_heartbeats():
    mgr = HeartbeatManager(timeout_s=0.5)
    seen = {}
    e1 = HeartbeatEndpoint(mgr, "exec-1", "h1", 1111, interval_s=0.1,
                           on_peers=lambda ps: seen.__setitem__(
                               "e1", [p.executor_id for p in ps]))
    peers2 = mgr.register_executor("exec-2", "h2", 2222)
    assert [p.executor_id for p in peers2] == ["exec-1"]
    e1.start()
    import time
    time.sleep(0.3)
    assert seen.get("e1") == ["exec-2"]
    e1.stop()
    # exec-1 stops heartbeating; after timeout it expires
    time.sleep(0.7)
    mgr.executor_heartbeat("exec-2")
    assert [p.executor_id for p in mgr.live_peers()] == ["exec-2"]
    assert mgr.expire_dead() == ["exec-1"]
