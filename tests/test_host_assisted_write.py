"""Host-assisted writes (spark.rapids.sql.write.hostAssisted).

When a write's plan only filters rows / prunes columns of a host-resident
source, the engine fetches ONLY the bit-packed keep-mask from the device
and applies it to the host copy — the full filtered payload never crosses
the interconnect (write-side transfer elision; the role GDS plays for the
reference's write path)."""

import glob
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


@pytest.fixture(scope="module")
def fact():
    rng = np.random.default_rng(5)
    n = 20_000
    return pa.table({
        "k": pa.array(rng.integers(0, 100, n).astype(np.int64)),
        "v": pa.array(rng.integers(-100, 100, n).astype(np.int64)),
        "f": pa.array(rng.random(n)),
    })


def _read_back(out):
    files = sorted(glob.glob(os.path.join(out, "*.parquet")))
    return pa.concat_tables([pq.read_table(f) for f in files])


def _session(assisted: bool):
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", True)
            .config("spark.rapids.sql.write.hostAssisted", assisted)
            .get_or_create())


def test_filtered_write_matches_unassisted(fact, tmp_path):
    outs = []
    for assisted in (True, False):
        s = _session(assisted)
        df = (s.create_dataframe(fact).filter(col("v") > 0)
              .filter(col("f") < 0.9).select(col("k"), col("v")))
        out = str(tmp_path / f"out_{assisted}")
        df.write.mode("overwrite").parquet(out)
        outs.append(_read_back(out))
    assert outs[0].equals(outs[1])
    assert outs[0].num_rows > 0


def test_projection_only_write(fact, tmp_path):
    s = _session(True)
    out = str(tmp_path / "proj")
    s.create_dataframe(fact).select(col("f"), col("k")) \
        .write.mode("overwrite").parquet(out)
    got = _read_back(out)
    assert got.equals(fact.select(["f", "k"]))


def test_file_source_filtered_write(fact, tmp_path):
    src = str(tmp_path / "src")
    os.makedirs(src)
    pq.write_table(fact, os.path.join(src, "part-0.parquet"))
    outs = []
    for assisted in (True, False):
        s = _session(assisted)
        df = s.read.parquet(src).filter(col("f") < 0.5)
        out = str(tmp_path / f"fout_{assisted}")
        df.write.mode("overwrite").parquet(out)
        outs.append(_read_back(out))
    assert outs[0].equals(outs[1])


def test_compute_plans_fall_back(fact, tmp_path):
    """A plan that computes new values must NOT take the mask shortcut —
    the writer falls back to a full collect with identical results."""
    from spark_rapids_tpu.io.writer import _host_assisted_table
    s = _session(True)
    df = s.create_dataframe(fact).select(
        (col("v") + col("k")).alias("s"))
    assert _host_assisted_table(df) is None
    out = str(tmp_path / "computed")
    df.write.mode("overwrite").parquet(out)
    got = _read_back(out)
    want = pa.table({"s": pa.array(
        fact.column("v").to_numpy() + fact.column("k").to_numpy())})
    assert got.equals(want)


def test_partitioned_write_host_assisted(fact, tmp_path):
    s = _session(True)
    df = s.create_dataframe(fact).filter(col("k") < 3)
    out = str(tmp_path / "parts")
    df.write.mode("overwrite").partition_by("k").parquet(out)
    dirs = sorted(os.listdir(out))
    assert dirs == ["k=0", "k=1", "k=2"]
