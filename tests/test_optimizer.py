"""Cost-based optimizer tests (ref CostBasedOptimizerSuite)."""

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _session(**extra):
    b = TpuSession.builder().config("spark.rapids.sql.enabled", True)
    for k, v in extra.items():
        b = b.config(k.replace("_", "."), v)
    return b.get_or_create()


def _table(n=1000):
    rng = np.random.default_rng(0)
    return pa.table({
        "k": pa.array(rng.integers(0, 10, n).astype(np.int64)),
        "v": pa.array(rng.random(n)),
    })


def _placements(session):
    out = []
    session.last_plan.foreach(
        lambda e: out.append((type(e).__name__, e.placement)))
    return out


def test_cbo_disabled_by_default_keeps_tpu_plan():
    s = _session()
    df = s.create_dataframe(_table())
    got = df.filter(col("v") > 0.5).group_by(col("k")).agg(
        F.count("*").alias("c")).collect()
    assert got.num_rows == 10
    assert any(p == "tpu" for _, p in _placements(s))


def test_cbo_forces_cpu_when_tpu_cost_inflated():
    s = _session(**{
        "spark.rapids.sql.optimizer.enabled": True,
        # make every TPU op absurdly expensive: the DP must keep the
        # whole plan on CPU
        "spark.rapids.sql.optimizer.tpu.exec.LocalScanExec": 1e9,
        "spark.rapids.sql.optimizer.tpu.exec.FilterExec": 1e9,
        "spark.rapids.sql.optimizer.tpu.exec.ProjectExec": 1e9,
        "spark.rapids.sql.optimizer.tpu.exec.CpuHashAggregateExec": 1e9,
    })
    df = s.create_dataframe(_table())
    got = df.filter(col("v") > 0.5).group_by(col("k")).agg(
        F.count("*").alias("c")).collect()
    assert got.num_rows == 10
    assert all(p == "cpu" for _, p in _placements(s))


def test_cbo_enabled_default_costs_keeps_tpu():
    s = _session(**{"spark.rapids.sql.optimizer.enabled": True})
    df = s.create_dataframe(_table())
    got = df.filter(col("v") > 0.5).group_by(col("k")).agg(
        F.count("*").alias("c")).collect()
    assert got.num_rows == 10
    # with default costs (TPU 4x cheaper/row) acceleration stays on
    assert any(p == "tpu" for _, p in _placements(s))


def test_cbo_results_identical_either_way():
    base = None
    for enabled in (False, True):
        s = _session(**{"spark.rapids.sql.optimizer.enabled": enabled})
        df = s.create_dataframe(_table(500))
        got = (df.filter(col("v") > 0.25)
               .group_by(col("k"))
               .agg(F.sum(col("v")).alias("sv"))
               .collect().sort_by("k"))
        if base is None:
            base = got
        else:
            assert got.column("k").to_pylist() == base.column("k").to_pylist()
            np.testing.assert_allclose(np.array(got.column("sv")),
                                       np.array(base.column("sv")))
