"""Registry-tail expressions (ref GpuOverrides.scala:727-3048 delta):
NaNvl, InSet, AtLeastNNonNulls, decimal plumbing (UnscaledValue /
MakeDecimal / CheckOverflow), map family (map_keys/values/entries,
element access, map() construction, transform_keys/values), array
min/max, unix_timestamp — differential against the CPU engine or a
hand oracle."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api.column import Column, col
from spark_rapids_tpu.api.session import TpuSession


def _session(enabled=True):
    return TpuSession.builder().config("spark.rapids.sql.enabled",
                                       enabled).get_or_create()


def _c(expr):
    return Column(expr)


def _both(tbl, build):
    outs = []
    for enabled in (True, False):
        s = _session(enabled)
        df = s.create_dataframe(tbl)
        outs.append(build(df).collect())
    return outs


def test_nanvl_inset_atleastn():
    from spark_rapids_tpu.expr.misc_tail import (AtLeastNNonNulls, InSet,
                                                 NaNvl)
    tbl = pa.table({
        "a": pa.array([1.0, float("nan"), None, 4.0]),
        "b": pa.array([10.0, 20.0, 30.0, None]),
        "k": pa.array([1, 2, 3, 4], type=pa.int64()),
    })
    tpu, cpu = _both(tbl, lambda df: df.select(
        _c(NaNvl(col("a").expr, col("b").expr)).alias("nv"),
        _c(InSet(col("k").expr, (2, 4, None))).alias("ins"),
        _c(AtLeastNNonNulls(2, [col("a").expr, col("b").expr])).alias(
            "aln")))
    assert tpu.equals(cpu)
    assert tpu.column("nv").to_pylist() == [1.0, 20.0, None, 4.0]
    # IN with a null in the list: null unless matched
    assert tpu.column("ins").to_pylist() == [None, True, None, True]
    # NaN does not count as non-null for dropna semantics
    assert tpu.column("aln").to_pylist() == [True, False, False, False]


def test_decimal_plumbing():
    from spark_rapids_tpu.expr.misc_tail import (CheckOverflow,
                                                 MakeDecimal,
                                                 UnscaledValue)
    tbl = pa.table({
        "d": pa.array([None, 1, 12345, -99999], type=pa.decimal128(9, 2)),
        "u": pa.array([5, 123, 10**7, -(10**7)], type=pa.int64()),
    })
    tpu, cpu = _both(tbl, lambda df: df.select(
        _c(UnscaledValue(col("d").expr)).alias("uv"),
        _c(MakeDecimal(col("u").expr, 5, 2)).alias("md"),
        _c(CheckOverflow(col("d").expr, 4, 2)).alias("co")))
    assert tpu.equals(cpu)
    # pyarrow reads the ints as decimal VALUES: 1.00, 123.45, -999.99
    assert tpu.column("uv").to_pylist() == [None, 100, 1234500, -9999900]
    md = tpu.column("md").to_pylist()
    assert [str(x) if x is not None else None for x in md] == \
        ["0.05", "1.23", None, None]       # 10^7 overflows precision 5
    co = tpu.column("co").to_pylist()
    assert [str(x) if x is not None else None for x in co] == \
        [None, "1.00", None, None]         # |unscaled| >= 10^4 nulls out


def test_map_family():
    from spark_rapids_tpu.expr.collection import (ArrayMax, ArrayMin,
                                                  GetMapValue, MapEntries,
                                                  MapKeys, MapValues)
    tbl = pa.table({
        "m": pa.array([[("a", 1), ("b", 2)], None, [("b", 7)], []],
                      type=pa.map_(pa.string(), pa.int64())),
        "arr": pa.array([[3, 1, 2], None, [9], []],
                        type=pa.list_(pa.int64())),
    })
    tpu, cpu = _both(tbl, lambda df: df.select(
        _c(MapKeys(col("m").expr)).alias("mk"),
        _c(MapValues(col("m").expr)).alias("mv"),
        _c(MapEntries(col("m").expr)).alias("me"),
        _c(ArrayMax(col("arr").expr)).alias("amax"),
        _c(ArrayMin(col("arr").expr)).alias("amin")))
    assert tpu.equals(cpu), (tpu.to_pydict(), cpu.to_pydict())
    assert tpu.column("mk").to_pylist() == [["a", "b"], None, ["b"], []]
    assert tpu.column("mv").to_pylist() == [[1, 2], None, [7], []]
    assert tpu.column("amax").to_pylist() == [3, None, 9, None]
    assert tpu.column("amin").to_pylist() == [1, None, 9, None]

    from spark_rapids_tpu.expr.core import Literal
    tpu2, cpu2 = _both(tbl, lambda df: df.select(
        _c(GetMapValue(col("m").expr, Literal("b"))).alias("gb")))
    assert tpu2.equals(cpu2)
    assert tpu2.column("gb").to_pylist() == [2, None, 7, None]


def test_create_map_and_transform():
    from spark_rapids_tpu.expr.collection import CreateMap, MapValues
    from spark_rapids_tpu.expr.higher_order import (LambdaFunction,
                                                    NamedLambdaVariable,
                                                    TransformValues)
    from spark_rapids_tpu.expr.arithmetic import Multiply
    from spark_rapids_tpu.expr.core import Literal
    tbl = pa.table({
        "k1": pa.array([1, 2, 3], type=pa.int64()),
        "v1": pa.array([10, None, 30], type=pa.int64()),
        "m": pa.array([[("a", 1), ("b", 2)], [("c", 3)], []],
                      type=pa.map_(pa.string(), pa.int64())),
    })

    def build(df):
        cm = CreateMap([col("k1").expr, col("v1").expr])
        kvar = NamedLambdaVariable("k")
        vvar = NamedLambdaVariable("v")
        tv = TransformValues(
            col("m").expr,
            LambdaFunction(Multiply(vvar, Literal(2)), [kvar, vvar]))
        return df.select(_c(cm).alias("cm"),
                         _c(MapValues(tv)).alias("tv2"))

    tpu, cpu = _both(tbl, build)
    assert tpu.equals(cpu), (tpu.to_pydict(), cpu.to_pydict())
    assert tpu.column("cm").to_pylist() == \
        [[(1, 10)], [(2, None)], [(3, 30)]]
    assert tpu.column("tv2").to_pylist() == [[2, 4], [6], []]


def test_unix_timestamp_alias():
    from spark_rapids_tpu.expr.datetime_expr import UnixTimestamp
    tbl = pa.table({"ts": pa.array(
        np.array([0, 86_400_000_000, 1_600_000_000_123_456],
                 dtype="int64").view("M8[us]"))})
    tpu, cpu = _both(tbl, lambda df: df.select(
        _c(UnixTimestamp(col("ts").expr)).alias("u")))
    assert tpu.equals(cpu)
    assert tpu.column("u").to_pylist() == [0, 86_400, 1_600_000_000]


def test_substring_index_device_and_host():
    from spark_rapids_tpu.expr.strings import SubstringIndex
    vals = ["a.b.c.d", "no-delim", "", ".lead", "trail.", "..",
            None, "x.y"]
    tbl = pa.table({"s": pa.array(vals)})

    def build(df, cnt, delim="."):
        return df.select(
            _c(SubstringIndex(col("s").expr, delim, cnt)).alias("o"))

    for cnt in (2, 1, 0, -1, -2, 5, -9):
        tpu, cpu = _both(tbl, lambda df, c=cnt: build(df, c))
        want = []
        for sv in vals:
            if sv is None:
                want.append(None)
            elif cnt == 0:
                want.append("")
            elif cnt > 0:
                want.append(".".join(sv.split(".")[:cnt]))
            else:
                want.append(".".join(sv.split(".")[cnt:]))
        assert tpu.column("o").to_pylist() == want, (cnt, tpu.to_pydict())
        assert cpu.column("o").to_pylist() == want, (cnt, "cpu")

    # multi-byte delimiter: tagged to the host engine, still correct
    tbl2 = pa.table({"s": pa.array(["a::b::c", "q"])})
    tpu2, cpu2 = _both(tbl2, lambda df: build(df, 1, delim="::"))
    assert tpu2.column("o").to_pylist() == ["a", "q"]
    assert tpu2.equals(cpu2)
