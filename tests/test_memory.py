"""Memory framework tests: spill tiers, catalog budgets, semaphore
(model: RapidsDeviceMemoryStoreSuite / RapidsHostMemoryStoreSuite /
RapidsDiskStoreSuite / GpuSemaphoreSuite)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.device import batch_to_arrow, batch_to_device
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.memory.spill import (SpillCatalog, SpillPriority,
                                           SpillableBatch, StorageTier,
                                           with_retry_spill)


def _batch(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    rb = pa.record_batch({
        "a": pa.array(rng.integers(0, 100, n)),
        "s": pa.array([f"row{i}" for i in range(n)])})
    return rb, batch_to_device(rb, xp=np)


def test_spill_tiers_roundtrip(tmp_path):
    cat = SpillCatalog(device_budget=1 << 30, host_budget=1 << 30,
                       spill_dir=str(tmp_path))
    rb, b = _batch()
    sb = cat.register(b)
    assert sb.tier == StorageTier.DEVICE
    sb.spill_to_host()
    assert sb.tier == StorageTier.HOST
    back = sb.get_batch(np)
    assert batch_to_arrow(back).to_pylist() == rb.to_pylist()
    sb.spill_to_disk()
    assert sb.tier == StorageTier.DISK
    back = sb.get_batch(np)
    assert batch_to_arrow(back).to_pylist() == rb.to_pylist()
    sb.close()


def test_device_budget_triggers_spill(tmp_path):
    rb, b = _batch()
    one = sum(leaf.nbytes for leaf in
              __import__("jax").tree_util.tree_leaves(b))
    cat = SpillCatalog(device_budget=int(one * 2.5),
                       host_budget=1 << 30, spill_dir=str(tmp_path))
    sbs = [cat.register(_batch(seed=i)[1], SpillPriority.INPUT)
           for i in range(4)]
    # budget fits ~2.5 batches: at least one must have left the device
    tiers = [s.tier for s in sbs]
    assert any(t != StorageTier.DEVICE for t in tiers)
    assert cat.device_bytes_registered() <= int(one * 2.5)
    for s in sbs:
        s.close()


def test_host_budget_overflows_to_disk(tmp_path):
    rb, b = _batch()
    cat = SpillCatalog(device_budget=0, host_budget=1,
                       spill_dir=str(tmp_path))
    sb = cat.register(b)
    # device budget 0 -> immediate spill; host budget 1 byte -> disk
    assert sb.tier == StorageTier.DISK
    assert batch_to_arrow(sb.get_batch(np)).to_pylist() == rb.to_pylist()
    sb.close()


def test_retry_spill_on_oom(tmp_path):
    cat = SpillCatalog(device_budget=1 << 30, host_budget=1 << 30,
                       spill_dir=str(tmp_path))
    rb, b = _batch()
    sb = cat.register(b)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory on HBM")
        return 42

    assert with_retry_spill(flaky, cat) == 42
    assert sb.tier != StorageTier.DEVICE  # the retry spilled it
    sb.close()


def test_semaphore_limits_concurrency():
    sem = TpuSemaphore(2)
    order = []
    barrier = threading.Barrier(2)

    def task(tid):
        sem.acquire_if_necessary(tid)
        order.append(("in", tid))
        barrier.wait(timeout=5)
        sem.release_if_necessary(tid)

    t1 = threading.Thread(target=task, args=(1,))
    t2 = threading.Thread(target=task, args=(2,))
    t1.start()
    t2.start()
    t1.join(5)
    t2.join(5)
    assert len([o for o in order if o[0] == "in"]) == 2
    # third acquire with none released would block: use timeout path
    sem2 = TpuSemaphore(1)
    assert sem2.acquire_if_necessary(10)
    assert sem2.acquire_if_necessary(10)  # re-entrant
    assert not sem2.acquire_if_necessary(11, timeout=0.1)
    sem2.release_if_necessary(10)
    sem2.release_if_necessary(10)
    assert sem2.acquire_if_necessary(11, timeout=1.0)


def test_query_runs_with_tiny_device_budget(tmp_path):
    """End-to-end aggregation under heavy spill pressure: every partial
    demotes to disk and comes back for the merge."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.testing.asserts import with_tpu_session
    from spark_rapids_tpu.testing.data_gen import IntegerGen, LongGen, gen_df

    conf = {"spark.rapids.memory.tpu.spillBudgetBytes": 1,
            "spark.rapids.memory.host.spillStorageSize": 1,
            "spark.rapids.memory.spill.dirs": str(tmp_path)}
    old = SpillCatalog._instance
    try:
        def q(spark):
            df = gen_df(spark, [("k", IntegerGen(lo=0, hi=10)),
                                ("v", LongGen())], length=512,
                        num_partitions=3)
            return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"))
        out = with_tpu_session(lambda s: q(s).collect(), conf)
        assert out.num_rows > 0
        assert SpillCatalog._instance.spilled_to_disk_bytes > 0
    finally:
        SpillCatalog._instance = old


def test_device_capacity_resolution():
    """HBM capacity: explicit conf wins; PJRT stats next; device-kind
    table next; CPU backend falls back to host RAM; unknown accelerators
    fail loudly instead of assuming 16 GiB (round-2 verdict weak #4)."""
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.memory.device import DeviceManager
    from spark_rapids_tpu.plugin import PluginInitError

    class FakeDev:
        def __init__(self, kind, platform, stats=None):
            self.device_kind = kind
            self.platform = platform
            self._stats = stats

        def memory_stats(self):
            if self._stats is None:
                raise RuntimeError("no stats")
            return self._stats

    dm = DeviceManager.__new__(DeviceManager)

    # explicit override wins over everything
    dm.device = FakeDev("TPU v5 lite", "axon", {"bytes_limit": 123})
    conf = cfg.RapidsConf({"spark.rapids.memory.tpu.limitBytes": 42})
    assert dm._device_capacity(conf) == 42

    # PJRT stats
    conf = cfg.RapidsConf({})
    assert dm._device_capacity(conf) == 123

    # device-kind table when stats unavailable
    dm.device = FakeDev("TPU v5 lite", "axon")
    assert dm._device_capacity(conf) == 16 * (1 << 30)
    dm.device = FakeDev("TPU v4", "tpu")
    assert dm._device_capacity(conf) == 32 * (1 << 30)

    # CPU backend: host RAM (nonzero, sane)
    dm.device = FakeDev("cpu", "cpu")
    cap = dm._device_capacity(conf)
    assert cap > (1 << 28)

    # unknown accelerator with no stats: loud failure
    dm.device = FakeDev("FrobnitzPU", "frob")
    try:
        dm._device_capacity(conf)
        assert False, "expected PluginInitError"
    except PluginInitError as e:
        assert "limitBytes" in str(e)


def test_pinned_scan_cache_counts_and_evicts():
    """Pinned scan batches are accounted against the device budget and
    evicted (dropped, not serialized) under pressure, so spill accounting
    stays truthful with the pin cache on (code-review round-3 finding)."""
    from spark_rapids_tpu.memory.spill import SpillCatalog

    cat = SpillCatalog(device_budget=1 << 20)
    owner = {}
    import numpy as _np
    from spark_rapids_tpu.columnar.device import DeviceBatch, DeviceColumn
    from spark_rapids_tpu import types as t

    col = DeviceColumn(t.LONG, data=_np.zeros(1024, _np.int64),
                       validity=_np.ones(1024, bool))
    b = DeviceBatch([col], 1024, ["x"])
    owner[("k", 0)] = [b]
    cat.register_pinned(owner, ("k", 0), [b])
    assert cat.pinned_bytes() > 0
    assert cat.device_bytes_registered() >= cat.pinned_bytes()

    # force pressure: ask for more than the budget
    freed = cat.synchronous_spill(1)
    assert freed > 0
    assert ("k", 0) not in owner          # entry dropped from the cache
    assert cat.pinned_bytes() == 0
    assert cat.pinned_evicted_bytes > 0


def test_leak_tracker_clean_query_and_detects_leak():
    """Arm.scala-style leak discipline: debug mode records creation
    stacks and a clean query leaks nothing; an unclosed buffer is
    reported with its origin."""
    import numpy as _np
    import pyarrow as _pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.columnar.device import DeviceBatch, DeviceColumn
    from spark_rapids_tpu import types as _t

    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.memory.tpu.debug", True).get_or_create())
    tb = _pa.table({"k": _pa.array([1, 2, 1], type=_pa.int64()),
                    "v": _pa.array([1.0, 2.0, 3.0])})
    out = (s.create_dataframe(tb).group_by(col("k"))
           .agg(F.sum(col("v")).alias("sv"))
           .collect())          # must not raise: all buffers closed
    assert out.num_rows == 2

    cat = SpillCatalog.get()
    cat.debug = True
    col0 = DeviceColumn(_t.LONG, data=_np.zeros(8, _np.int64),
                        validity=_np.ones(8, bool))
    sb = cat.register(DeviceBatch([col0], 8, ["x"]))
    report = [l for l in cat.leak_report() if l[0] == sb.id]
    assert report and "register" in report[0][3]
    with sb:            # withResource-style close
        pass
    assert sb.closed
    assert not [l for l in cat.leak_report() if l[0] == sb.id]
    cat.debug = False
