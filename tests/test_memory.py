"""Memory framework tests: spill tiers, catalog budgets, semaphore
(model: RapidsDeviceMemoryStoreSuite / RapidsHostMemoryStoreSuite /
RapidsDiskStoreSuite / GpuSemaphoreSuite)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.device import batch_to_arrow, batch_to_device
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.memory.spill import (SpillCatalog, SpillPriority,
                                           SpillableBatch, StorageTier,
                                           with_retry_spill)


def _batch(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    rb = pa.record_batch({
        "a": pa.array(rng.integers(0, 100, n)),
        "s": pa.array([f"row{i}" for i in range(n)])})
    return rb, batch_to_device(rb, xp=np)


def test_spill_tiers_roundtrip(tmp_path):
    cat = SpillCatalog(device_budget=1 << 30, host_budget=1 << 30,
                       spill_dir=str(tmp_path))
    rb, b = _batch()
    sb = cat.register(b)
    assert sb.tier == StorageTier.DEVICE
    sb.spill_to_host()
    assert sb.tier == StorageTier.HOST
    back = sb.get_batch(np)
    assert batch_to_arrow(back).to_pylist() == rb.to_pylist()
    sb.spill_to_disk()
    assert sb.tier == StorageTier.DISK
    back = sb.get_batch(np)
    assert batch_to_arrow(back).to_pylist() == rb.to_pylist()
    sb.close()


def test_device_budget_triggers_spill(tmp_path):
    rb, b = _batch()
    one = sum(leaf.nbytes for leaf in
              __import__("jax").tree_util.tree_leaves(b))
    cat = SpillCatalog(device_budget=int(one * 2.5),
                       host_budget=1 << 30, spill_dir=str(tmp_path))
    sbs = [cat.register(_batch(seed=i)[1], SpillPriority.INPUT)
           for i in range(4)]
    # budget fits ~2.5 batches: at least one must have left the device
    tiers = [s.tier for s in sbs]
    assert any(t != StorageTier.DEVICE for t in tiers)
    assert cat.device_bytes_registered() <= int(one * 2.5)
    for s in sbs:
        s.close()


def test_host_budget_overflows_to_disk(tmp_path):
    rb, b = _batch()
    cat = SpillCatalog(device_budget=0, host_budget=1,
                       spill_dir=str(tmp_path))
    sb = cat.register(b)
    # device budget 0 -> immediate spill; host budget 1 byte -> disk
    assert sb.tier == StorageTier.DISK
    assert batch_to_arrow(sb.get_batch(np)).to_pylist() == rb.to_pylist()
    sb.close()


def test_retry_spill_on_oom(tmp_path):
    cat = SpillCatalog(device_budget=1 << 30, host_budget=1 << 30,
                       spill_dir=str(tmp_path))
    rb, b = _batch()
    sb = cat.register(b)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory on HBM")
        return 42

    assert with_retry_spill(flaky, cat) == 42
    assert sb.tier != StorageTier.DEVICE  # the retry spilled it
    sb.close()


def test_semaphore_limits_concurrency():
    sem = TpuSemaphore(2)
    order = []
    barrier = threading.Barrier(2)

    def task(tid):
        sem.acquire_if_necessary(tid)
        order.append(("in", tid))
        barrier.wait(timeout=5)
        sem.release_if_necessary(tid)

    t1 = threading.Thread(target=task, args=(1,))
    t2 = threading.Thread(target=task, args=(2,))
    t1.start()
    t2.start()
    t1.join(5)
    t2.join(5)
    assert len([o for o in order if o[0] == "in"]) == 2
    # third acquire with none released would block: use timeout path
    sem2 = TpuSemaphore(1)
    assert sem2.acquire_if_necessary(10)
    assert sem2.acquire_if_necessary(10)  # re-entrant
    assert not sem2.acquire_if_necessary(11, timeout=0.1)
    sem2.release_if_necessary(10)
    sem2.release_if_necessary(10)
    assert sem2.acquire_if_necessary(11, timeout=1.0)


def test_query_runs_with_tiny_device_budget(tmp_path):
    """End-to-end aggregation under heavy spill pressure: every partial
    demotes to disk and comes back for the merge."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.testing.asserts import with_tpu_session
    from spark_rapids_tpu.testing.data_gen import IntegerGen, LongGen, gen_df

    conf = {"spark.rapids.memory.tpu.spillBudgetBytes": 1,
            "spark.rapids.memory.host.spillStorageSize": 1,
            "spark.rapids.memory.spill.dirs": str(tmp_path)}
    old = SpillCatalog._instance
    try:
        def q(spark):
            df = gen_df(spark, [("k", IntegerGen(lo=0, hi=10)),
                                ("v", LongGen())], length=512,
                        num_partitions=3)
            return df.group_by(col("k")).agg(F.sum(col("v")).alias("s"))
        out = with_tpu_session(lambda s: q(s).collect(), conf)
        assert out.num_rows > 0
        assert SpillCatalog._instance.spilled_to_disk_bytes > 0
    finally:
        SpillCatalog._instance = old
