"""Plugin bootstrap + shim layer tests (ref Plugin.scala lifecycle,
ShimLoader/SparkShims selection)."""

import pyarrow as pa
import pytest

from spark_rapids_tpu.plugin import (ExecutionPlanCaptureCallback,
                                     PluginInitError, TpuDriverPlugin,
                                     TpuExecutorPlugin, fixup_configs)
from spark_rapids_tpu.shims import (ShimLoader, Spark301Shims, Spark311Shims,
                                    Spark320Shims)


def test_fixup_configs_forces_extension():
    out = fixup_configs({})
    assert "SQLExecPlugin" in out["spark.sql.extensions"]
    # idempotent
    again = fixup_configs(out)
    assert again["spark.sql.extensions"].count("SQLExecPlugin") == 1


def test_driver_executor_lifecycle_and_heartbeats():
    drv = TpuDriverPlugin({})
    drv.init()
    ex1 = TpuExecutorPlugin({}, driver=drv, executor_id="1")
    ex1.init()
    ex2 = TpuExecutorPlugin({}, driver=drv, executor_id="2")
    ex2.init()
    # second executor's heartbeat learns about the first (ref
    # RapidsShuffleHeartbeatManager.executorHeartbeat)
    resp = drv.receive({"kind": "heartbeat", "executor_id": "2"})
    assert resp["ok"]
    peer_ids = {p["executor_id"] for p in resp["peers"]}
    assert "1" in peer_ids
    ex1.shutdown()
    ex2.shutdown()
    drv.shutdown()


def test_version_handshake_passes_on_current_runtime():
    assert TpuExecutorPlugin.check_runtime_versions() == []


def test_unknown_rpc_message():
    drv = TpuDriverPlugin({})
    drv.init()
    assert not drv.receive({"kind": "bogus"})["ok"]


def test_shim_selection_by_version():
    assert isinstance(ShimLoader.get_shim("3.0.1"), Spark301Shims)
    assert isinstance(ShimLoader.get_shim("3.1.2"), Spark311Shims)
    assert isinstance(ShimLoader.get_shim("3.2.0"), Spark320Shims)
    with pytest.raises(ValueError):
        ShimLoader.get_shim("2.4.8")


def test_shim_behavior_deltas():
    s30 = ShimLoader.get_shim("3.0.1")
    s32 = ShimLoader.get_shim("3.2.0")
    assert s30.legacy_statistical_aggregate() and \
        not s32.legacy_statistical_aggregate()
    assert s30.parquet_rebase_mode_default() == "LEGACY"
    assert s32.parquet_rebase_mode_default() == "CORRECTED"
    assert s30.aqe_shuffle_read_name() == "CustomShuffleReader"
    assert s32.aqe_shuffle_read_name() == "AQEShuffleRead"
    assert not s30.cached_batch_serializer_supported()


def test_session_uses_plugins_and_capture_callback(tpu_session):
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    assert s.executor_plugin is not None
    assert s.driver_plugin is not None
    assert s.shim.version.startswith("3.2")
    ExecutionPlanCaptureCallback.start_capture()
    df = s.create_dataframe(pa.table({"x": pa.array([1, 2, 3])}))
    df.collect()
    plans = ExecutionPlanCaptureCallback.get_resulting_plans()
    assert plans
    assert ExecutionPlanCaptureCallback.assert_contains(
        plans[-1], "LocalScanExec")


def test_generated_docs_are_fresh():
    """The committed docs must match the live registries (the reference
    regenerates docs/configs.md + supported_ops.md from code the same
    way; ref TypeChecks.scala:1633)."""
    import os
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.docsgen import generate_supported_ops
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "docs", "configs.md")) as f:
        assert f.read() == cfg.generate_docs(), \
            "docs/configs.md is stale — run python -m spark_rapids_tpu.docsgen"
    with open(os.path.join(root, "docs", "supported_ops.md")) as f:
        assert f.read() == generate_supported_ops(), \
            "docs/supported_ops.md is stale — run python -m " \
            "spark_rapids_tpu.docsgen"
