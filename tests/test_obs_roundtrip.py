"""Flight-recorder round trip: a query executed with
spark.rapids.tpu.eventLog.dir set emits a log that tools/eventlog.py
parses and whose profiling aggregates equal the live metrics_report
values exactly; failure paths flush with error status; metrics_report
drains every pending device scalar through ONE fetch crossing."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession, last_query_metrics
from spark_rapids_tpu.tools.eventlog import parse_event_log
from spark_rapids_tpu.tools.profiling import (accuracy_report,
                                              operator_metrics)


def _session(tmp_path, **extra):
    b = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.eventLog.dir", str(tmp_path)))
    for k, v in extra.items():
        b = b.config(k, v)
    return b.get_or_create()


def _table(n=400):
    return pa.table({
        "k": pa.array((np.arange(n) % 9).astype(np.int64)),
        "v": pa.array(np.arange(n, dtype=np.int64)),
    })


def _only_log(tmp_path):
    logs = [f for f in os.listdir(tmp_path) if f.startswith("events_")]
    assert len(logs) == 1, logs
    return os.path.join(tmp_path, logs[0])


def test_eventlog_roundtrip_matches_live_metrics(tmp_path):
    s = _session(tmp_path)
    out = (s.create_dataframe(_table(), num_partitions=2)
           .filter(col("v") >= 0).group_by(col("k"))
           .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("c"))
           .collect())
    assert out.num_rows == 9
    path = _only_log(tmp_path)
    # every emitted line is valid JSON (nothing the parser rejects)
    with open(path) as f:
        for line in f:
            json.loads(line)
    app = parse_event_log(path)
    sx = app.sql_executions[0]
    assert not sx.failed and sx.end_time is not None
    # THE round-trip contract: parsed operator aggregates == live report
    for level in ("ESSENTIAL", "MODERATE", "DEBUG"):
        parsed = operator_metrics(app, 0, level)
        live = [tuple(r) for r in last_query_metrics(s, level)]
        assert parsed == live and parsed
    # the header makes it a well-formed application for the tools
    assert app.app_id.startswith("tpu-")
    assert app.spark_props  # EnvironmentUpdate carried the session conf
    # span records replay from the log
    assert any(sp.get("kind") == "operator" for sp in app.spans)
    assert any(sp["name"].startswith("phase:") for sp in app.spans)


def test_accuracy_report_predicted_vs_actual(tmp_path):
    s = _session(tmp_path,
                 **{"spark.rapids.tpu.memsan.enabled": True})
    (s.create_dataframe(_table(), num_partitions=2)
     .group_by(col("k")).agg(F.sum(col("v")).alias("sv")).collect())
    app = parse_event_log(_only_log(tmp_path))
    rows = accuracy_report(app)
    assert rows, "self-emitted plan must carry tpuPrediction/tpuActual"
    r = rows[0]
    assert {"node", "predictedRows", "actualRows", "rowsErr",
            "predictedBytes", "actualBytes", "bytesErr"} <= set(r)
    # ranked worst-first by row error
    errs = [x["rowsErr"] for x in rows]
    assert errs == sorted(errs, reverse=True)
    # memsan on: the query-level peak pair rides SQLExecutionEnd
    sx = app.sql_executions[0]
    assert sx.peak_device_bytes is not None
    assert sx.static_peak_bound is not None
    assert sx.peak_device_bytes <= sx.static_peak_bound


def test_failure_flushes_with_error_status(tmp_path, monkeypatch):
    from spark_rapids_tpu.exec import basic as xb
    s = _session(tmp_path)
    df = s.create_dataframe(_table(64)).filter(col("v") > 3)

    def boom(self, pid, ctx):
        raise RuntimeError("injected-operator-failure")
        yield  # pragma: no cover

    monkeypatch.setattr(xb.FilterExec, "execute_partition", boom)
    with pytest.raises(RuntimeError, match="injected-operator-failure"):
        df.collect()
    tr = s.last_query_trace()
    assert tr is not None and tr.sealed
    assert tr.open_span_count() == 0, "spans must close on failure"
    assert "injected-operator-failure" in (tr.error or "")
    err_spans = [sp for sp in tr.spans if sp.status == "error"]
    assert err_spans and any(sp.error and "injected" in sp.error
                             for sp in err_spans)
    app = parse_event_log(_only_log(tmp_path))
    assert app.sql_executions[0].failed  # JobFailed in the log
    # the session stays usable and the NEXT query appends sql_id 1
    monkeypatch.undo()
    s.create_dataframe(_table(64)).filter(col("v") > 3).collect()
    app = parse_event_log(_only_log(tmp_path))
    assert sorted(app.sql_executions) == [0, 1]
    assert not app.sql_executions[1].failed


def test_trace_covers_speculation_retry(tmp_path):
    # a traced query that speculates must leave a clean, sealed trace
    # whether or not the guess held (no dangling spans from attempt 1)
    s = _session(tmp_path)
    left = s.create_dataframe(_table(128))
    right = s.create_dataframe(pa.table({
        "k": pa.array(np.arange(9, dtype=np.int64)),
        "w": pa.array(np.arange(9, dtype=np.float64))}))
    out = left.join(right, on="k", how="inner").collect()
    assert out.num_rows == 128
    tr = s.last_query_trace()
    assert tr.sealed and tr.open_span_count() == 0


def test_metrics_report_single_fetch_crossing(monkeypatch):
    from spark_rapids_tpu.columnar import fetch as fetch_mod
    from spark_rapids_tpu.exec.base import Exec, metrics_report

    class _Leaf(Exec):
        def __init__(self):
            super().__init__([])

        @property
        def output_names(self):
            return []

        @property
        def output_types(self):
            return []

    root, child = _Leaf(), _Leaf()
    root.children = [child]
    # six metrics carrying pending DEVICE scalars across two operators
    for node in (root, child):
        for m in node.metrics.values():
            m.add(jnp.asarray(5))
            m.add(jnp.asarray(2))
    calls = []
    orig = fetch_mod.fetch_ints

    def counting(vals):
        calls.append(len(list(vals)))
        return orig(vals)

    monkeypatch.setattr(fetch_mod, "fetch_ints", counting)
    rows = metrics_report(root, "DEBUG")
    assert len(calls) == 1, \
        f"expected ONE fetch crossing, saw {len(calls)}"
    assert calls[0] == 12  # every pending scalar rode the one transfer
    assert all(v == 7 for _, _, v in rows)
    # drained: a second report costs zero crossings
    metrics_report(root, "DEBUG")
    assert len(calls) == 1


def test_tracing_off_records_nothing():
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True).get_or_create())
    s.create_dataframe(_table(32)).filter(col("v") > 1).collect()
    assert s.last_query_trace() is None
