"""Execute the SpecBuilder golden fixtures end-to-end.

The JSON files in bridge-jvm/src/test/resources/goldens/ are the exact
specs the Scala SpecBuilder emits for real Spark plans (asserted by
bridge-jvm's SpecBuilderSuite in CI).  Here the SAME fixtures execute
through the engine's spec interpreter against generated inputs, with
pyarrow/numpy oracles — together the two suites prove the wire contract
from Catalyst translation down to engine results."""

import glob
import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.bridge.spec import plan_spec_to_logical

GOLDEN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "bridge-jvm", "src", "test", "resources", "goldens")


def _load(name):
    with open(os.path.join(GOLDEN_DIR, name + ".json")) as f:
        return json.load(f)["spec"]


def _run(spec, table, extras=()):
    s = TpuSession.builder().config("spark.rapids.sql.enabled",
                                    True).get_or_create()
    lp = plan_spec_to_logical(spec, table, extras)
    return s.execute(lp)


def test_goldens_exist_and_parse():
    files = sorted(glob.glob(os.path.join(GOLDEN_DIR, "*.json")))
    assert len(files) >= 5
    for f in files:
        with open(f) as fh:
            spec = json.load(fh)["spec"]
        assert "ops" in spec and "input" in spec


def test_filter_project_golden():
    spec = _load("filter_project")
    rng = np.random.default_rng(1)
    tb = pa.table({"k": pa.array(rng.integers(0, 9, 500).astype(np.int64)),
                   "v": pa.array(rng.integers(-5, 5, 500).astype(np.int64))})
    got = _run(spec, tb)
    mask = pc.greater(tb.column("v"), 0)
    want_k = tb.column("k").filter(mask)
    want_v2 = pc.multiply(tb.column("v").filter(mask), 2)
    assert got.column("k").to_pylist() == want_k.to_pylist()
    assert got.column("v2").to_pylist() == want_v2.to_pylist()


def test_partial_aggregate_golden():
    spec = _load("partial_aggregate")
    rng = np.random.default_rng(2)
    tb = pa.table({"k": pa.array(rng.integers(0, 7, 300).astype(np.int64)),
                   "v": pa.array(rng.integers(-9, 9, 300).astype(np.int64))})
    got = _run(spec, tb).sort_by("k")
    # buffer schema: k, sum (bigint), sum (double), count
    assert got.schema.names == ["k", "sum", "sum", "count"]
    gb = pa.TableGroupBy(tb, ["k"], use_threads=False).aggregate(
        [("v", "sum"), ("v", "count")]).sort_by("k")
    assert got.column("k").to_pylist() == gb.column("k").to_pylist()
    assert got.column(1).to_pylist() == gb.column("v_sum").to_pylist()
    assert got.column(2).to_pylist() == [
        float(x) for x in gb.column("v_sum").to_pylist()]
    assert got.column(3).to_pylist() == gb.column("v_count").to_pylist()


def test_window_golden():
    spec = _load("window_rownum_runsum")
    rng = np.random.default_rng(3)
    tb = pa.table({"k": pa.array(rng.integers(0, 5, 200).astype(np.int64)),
                   "v": pa.array(rng.permutation(200).astype(np.int64))})
    got = _run(spec, tb).sort_by([("k", "ascending"), ("v", "ascending")])
    df = tb.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    df["rn"] = df.groupby("k").cumcount() + 1
    df["rs"] = df.groupby("k")["v"].cumsum()
    assert got.column("rn").to_pylist() == df["rn"].tolist()
    assert got.column("rs").to_pylist() == df["rs"].tolist()


def test_shuffled_join_diff_keys_golden():
    spec = _load("shuffled_join_diff_keys")
    fact = pa.table({
        "id": pa.array(np.arange(100, dtype=np.int64) % 20),
        "x": pa.array(np.arange(100, dtype=np.int64))})
    dim = pa.table({
        "user_id": pa.array(np.arange(20, dtype=np.int64)),
        "w": pa.array((np.arange(20, dtype=np.int64) * 10))})
    got = _run(spec, fact, (dim,)).sort_by(
        [("x", "ascending"), ("w", "ascending")])
    want_w = [int(i % 20) * 10 for i in range(100)]
    assert got.schema.names == ["x", "w"]
    assert got.column("x").to_pylist() == list(range(100))
    assert got.column("w").to_pylist() == want_w


def test_shuffled_join_same_keys_golden():
    """The common same-name equi join (`df.join(dim, on="k")`): the
    bridge emits the engine's coalescing "on" join plus a projection
    that restores Spark's duplicated key columns — exact for inner
    joins because both sides' key values agree on every surviving
    row."""
    spec = _load("shuffled_join_same_keys")
    fact = pa.table({
        "k": pa.array(np.arange(100, dtype=np.int64) % 20),
        "x": pa.array(np.arange(100, dtype=np.int64))})
    dim = pa.table({
        "k": pa.array(np.arange(15, dtype=np.int64)),
        "w": pa.array((np.arange(15, dtype=np.int64) * 10))})
    got = _run(spec, fact, (dim,)).sort_by(
        [("x", "ascending")])
    # Spark's join-node schema: left.output ++ right.output, key twice
    assert got.schema.names == ["k", "x", "k", "w"]
    keep = [i for i in range(100) if i % 20 < 15]
    assert got.column("x").to_pylist() == keep
    assert got.column(0).to_pylist() == [i % 20 for i in keep]
    # the restored right-side key equals the left key on every row
    assert got.column(2).to_pylist() == got.column(0).to_pylist()
    assert got.column("w").to_pylist() == [(i % 20) * 10 for i in keep]


def test_shuffled_join_forced_golden():
    """The bridge's over-cap strategy pin: a join op carrying
    `"strategy": "shuffled"` (emitted when the build side exceeds
    spark.tpu.bridge.maxBuildSideBytes) must route through the
    co-partitioned spill-backed shuffled path — exchanges on both
    sides + ShuffledHashJoinExec, never a broadcast/collected build —
    and still produce exact join results."""
    spec = _load("shuffled_join_forced")
    spec["numPartitions"] = 4
    fact = pa.table({
        "id": pa.array(np.arange(100, dtype=np.int64) % 20),
        "x": pa.array(np.arange(100, dtype=np.int64))})
    dim = pa.table({
        "user_id": pa.array(np.arange(20, dtype=np.int64)),
        "w": pa.array((np.arange(20, dtype=np.int64) * 10))})
    s = TpuSession.builder() \
        .config("spark.rapids.sql.enabled", True) \
        .config("spark.rapids.tpu.singleChipFuse", "off") \
        .get_or_create()
    lp = plan_spec_to_logical(spec, fact, (dim,))
    got = s.execute(lp).sort_by([("x", "ascending"), ("w", "ascending")])
    names = []
    s.last_plan.foreach(lambda e: names.append(type(e).__name__))
    assert "ShuffledHashJoinExec" in names, names
    assert names.count("ShuffleExchangeExec") >= 2, names
    assert "BroadcastHashJoinExec" not in names, names
    assert got.schema.names == ["x", "w"]
    assert got.column("x").to_pylist() == list(range(100))
    assert got.column("w").to_pylist() == [int(i % 20) * 10
                                           for i in range(100)]


def test_string_datetime_cast_golden():
    import datetime
    spec = _load("string_datetime_cast")
    tb = pa.table({
        "s": pa.array(["ax", "bb", "xc", None, "dx"]),
        "d": pa.array([datetime.date(2021, 1, 2),
                       datetime.date(2022, 3, 4),
                       datetime.date(2023, 5, 6),
                       datetime.date(2024, 7, 8),
                       datetime.date(2025, 9, 10)]),
        "v": pa.array(np.array([1, 2, 3, 4, 5], dtype=np.int64))})
    got = _run(spec, tb)
    assert got.column("u").to_pylist() == ["AX", "XC", "DX"]
    assert got.column("y").to_pylist() == [2021, 2023, 2025]
    assert got.column("vi").to_pylist() == [1, 3, 5]
    assert got.schema.field("vi").type == pa.int32()


def test_in_predicate_spec():
    """IN over a literal list round-trips through the spec language."""
    spec = {
        "input": {"schema": [["k", "bigint"]]},
        "inputs": [],
        "ops": [{"op": "filter", "condition": {
            "op": "in", "children": [{"col": "k"}],
            "values": [{"lit": 2, "type": "bigint"},
                       {"lit": 5, "type": "bigint"}]}}],
    }
    tb = pa.table({"k": pa.array(np.arange(10, dtype=np.int64))})
    got = _run(spec, tb)
    assert sorted(got.column("k").to_pylist()) == [2, 5]


def test_window_ranking_tier_spec():
    """percent_rank / cume_dist / ntile ride the window spec op."""
    spec = {
        "input": {"schema": [["k", "bigint"], ["v", "bigint"]]},
        "inputs": [],
        "ops": [{"op": "window",
                 "partitionBy": [{"col": "k"}],
                 "orderBy": [{"expr": {"col": "v"}, "ascending": True,
                              "nullsFirst": True}],
                 "funcs": [
                     {"fn": "percent_rank", "expr": None, "name": "pr"},
                     {"fn": "cume_dist", "expr": None, "name": "cd"},
                     {"fn": "ntile", "expr": None, "n": 4, "name": "nt"}]}],
    }
    rng = np.random.default_rng(12)
    tb = pa.table({"k": pa.array(rng.integers(0, 4, 80).astype(np.int64)),
                   "v": pa.array(rng.permutation(80).astype(np.int64))})
    got = _run(spec, tb).sort_by([("k", "ascending"), ("v", "ascending")])
    df = tb.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    want_pr = df.groupby("k")["v"].rank(method="min").sub(1) / \
        (df.groupby("k")["v"].transform("count") - 1)
    assert np.allclose(got.column("pr").to_numpy(), want_pr.to_numpy())
    want_cd = df.groupby("k")["v"].rank(method="max") / \
        df.groupby("k")["v"].transform("count")
    assert np.allclose(got.column("cd").to_numpy(), want_cd.to_numpy())
    nt = got.column("nt").to_numpy()
    assert nt.min() == 1 and nt.max() == 4


def test_window_explicit_rows_frame_spec():
    """An explicit ROWS frame clause rides the window op (moving sum
    over the 3-row trailing window)."""
    spec = {
        "input": {"schema": [["k", "bigint"], ["v", "bigint"]]},
        "inputs": [],
        "ops": [{"op": "window",
                 "partitionBy": [{"col": "k"}],
                 "orderBy": [{"expr": {"col": "v"}, "ascending": True,
                              "nullsFirst": True}],
                 "funcs": [{"fn": "sum", "expr": {"col": "v"},
                            "name": "ms"}],
                 "frame": {"type": "rows", "start": -2,
                           "end": "currentRow"}}],
    }
    rng = np.random.default_rng(13)
    tb = pa.table({"k": pa.array(rng.integers(0, 3, 60).astype(np.int64)),
                   "v": pa.array(rng.permutation(60).astype(np.int64))})
    got = _run(spec, tb).sort_by([("k", "ascending"), ("v", "ascending")])
    df = tb.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    want = df.groupby("k")["v"].rolling(3, min_periods=1).sum() \
        .reset_index(drop=True)
    assert got.column("ms").to_pylist() == [int(x) for x in want]
