"""Parquet/ORC/CSV read+write tests (model: integration_tests/
parquet_test.py, parquet_write_test.py, orc_test.py, csv_test.py)."""

import os

import pyarrow as pa
import pyarrow.parquet as papq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect, with_cpu_session,
    with_tpu_session)
from spark_rapids_tpu.testing.data_gen import (DoubleGen, IntegerGen,
                                               LongGen, StringGen,
                                               gen_table)


@pytest.fixture
def sample_table():
    return gen_table([("k", IntegerGen(lo=0, hi=50)), ("v", LongGen()),
                      ("s", StringGen(max_len=10)),
                      ("f", DoubleGen(no_nans=True))], length=1000, seed=7)


def _write_parquet_files(tmp_path, table, n_files=3):
    paths = []
    bounds = [round(i * table.num_rows / n_files)
              for i in range(n_files + 1)]
    for i in range(n_files):
        p = str(tmp_path / f"f{i}.parquet")
        papq.write_table(table.slice(bounds[i], bounds[i + 1] - bounds[i]),
                         p)
        paths.append(p)
    return paths


@pytest.mark.parametrize("reader_type",
                         ["PERFILE", "COALESCING", "MULTITHREADED"])
def test_parquet_read_strategies(tmp_path, sample_table, reader_type):
    paths = _write_parquet_files(tmp_path, sample_table)
    conf = {"spark.rapids.sql.format.parquet.reader.type": reader_type}

    def q(spark):
        return spark.read.parquet(*paths).group_by(col("k")).agg(
            F.sum(col("v")).alias("sv"), F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q, conf)


def test_parquet_pushdown_and_pruning(tmp_path, sample_table):
    paths = _write_parquet_files(tmp_path, sample_table)

    def q(spark):
        df = spark.read.parquet(*paths)
        return df.filter(col("k") > 25).select("k", "v")
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q)
    assert cpu.schema.names == ["k", "v"]
    exp = sample_table.to_pandas()
    exp = exp[exp.k > 25]
    assert cpu.num_rows == len(exp)


def test_parquet_roundtrip_write(tmp_path, sample_table):
    src = str(tmp_path / "src.parquet")
    papq.write_table(sample_table, src)
    out_dir = str(tmp_path / "out")

    def write(spark):
        df = spark.read.parquet(src)
        df.write.mode("overwrite").parquet(out_dir)
        return spark.read.parquet(out_dir)
    back = with_tpu_session(lambda s: write(s).collect())
    assert back.num_rows == sample_table.num_rows
    assert sorted(back.column("v").to_pylist(),
                  key=lambda x: (x is None, x)) == \
        sorted(sample_table.column("v").to_pylist(),
               key=lambda x: (x is None, x))


def test_partitioned_write(tmp_path, sample_table):
    src = str(tmp_path / "src.parquet")
    small = sample_table.slice(0, 100)
    papq.write_table(small, src)
    out_dir = str(tmp_path / "pout")

    def write(spark):
        df = spark.read.parquet(src)
        df.write.mode("overwrite").partition_by("k").parquet(out_dir)
    with_tpu_session(write)
    parts = [d for d in os.listdir(out_dir) if d.startswith("k=")]
    assert len(parts) >= 2


def test_orc_roundtrip(tmp_path, sample_table):
    import pyarrow.orc as paorc
    src = str(tmp_path / "a.orc")
    # ORC writer doesn't take large_string: cast
    cast = sample_table.cast(pa.schema([
        pa.field("k", pa.int32()), pa.field("v", pa.int64()),
        pa.field("s", pa.string()), pa.field("f", pa.float64())]))
    paorc.write_table(cast, src)

    def q(spark):
        return spark.read.orc(src).group_by(col("k")).agg(
            F.count("*").alias("c"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_csv_read(tmp_path):
    p = str(tmp_path / "data.csv")
    with open(p, "w") as f:
        f.write("a,b,c\n1,2.5,hello\n2,3.5,world\n3,,x\n")

    def q(spark):
        return spark.read.csv(p).select(
            (col("a") * 2).alias("a2"), col("b"), col("c"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("a2").to_pylist() == [2, 4, 6]
    assert tpu.column("b").to_pylist() == [2.5, 3.5, None]


def test_partitioned_write_read_roundtrip(tmp_path):
    """Reader must find files under k=<v>/ subdirectories (recursive)."""
    import pyarrow as pa
    from spark_rapids_tpu.testing.asserts import with_tpu_session
    out = str(tmp_path / "part_out")

    def w(spark):
        df = spark.create_dataframe(pa.table(
            {"k": pa.array([1, 1, 2]), "v": pa.array([10, 20, 30])}))
        df.write.partition_by("k").parquet(out)
        return spark.read.parquet(out).collect()
    tbl = with_tpu_session(w)
    assert tbl.num_rows == 3


def test_filter_pushdown_does_not_leak_across_queries(tmp_path):
    """Planning a filtered query must not mutate the shared relation."""
    import pyarrow as pa
    from spark_rapids_tpu.testing.asserts import with_tpu_session
    p = str(tmp_path / "t.parquet")

    def w(spark):
        spark.create_dataframe(pa.table(
            {"k": pa.array(range(100)), "v": pa.array(range(100))})) \
            .write.parquet(p)
        base = spark.read.parquet(p)
        filtered = base.filter(col("k") > 90).collect()
        full = base.select("k", "v").collect()
        return filtered.num_rows, full.num_rows
    nf, nfull = with_tpu_session(w)
    assert nf == 9
    assert nfull == 100
