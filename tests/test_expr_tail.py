"""Differential tests for the round-3 expression tail: PivotFirst,
approx_percentile, tumbling time windows, NormalizeNaNAndZero
(ref GpuPivotFirst / ApproximatePercentile / TimeWindow.scala /
NormalizeFloatingNumbers.scala)."""

import datetime

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as t
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _session(enabled=True):
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", enabled).get_or_create())


def _tpu_ops(s):
    names = []
    s.last_plan.foreach(lambda e: names.append((type(e).__name__,
                                                e.placement)))
    return names


def test_pivot_first_matches_manual_pivot():
    s = _session()
    tb = pa.table({
        "k": pa.array([1, 1, 1, 2, 2, 3], type=pa.int64()),
        "p": pa.array(["a", "b", "a", "a", "c", None]),
        "v": pa.array([10, 20, 30, 40, 50, 60], type=pa.int64()),
    })
    df = s.create_dataframe(tb)
    out = (df.group_by(col("k"))
           .agg(F.pivot_first(col("p"), col("v"), "a").alias("pa"),
                F.pivot_first(col("p"), col("v"), "c").alias("pc"))
           .collect().sort_by("k"))
    assert out.column("pa").to_pylist() == [10, 40, None]
    assert out.column("pc").to_pylist() == [None, 50, None]
    # the aggregate ran on the TPU engine
    assert any(n == "TpuHashAggregateExec" and p == "tpu"
               for n, p in _tpu_ops(s))


def test_pivot_api_uses_pivot_first_and_matches_oracle():
    s = _session()
    rng = np.random.default_rng(4)
    n = 400
    tb = pa.table({
        "k": pa.array(rng.integers(0, 10, n).astype(np.int64)),
        "p": pa.array([["x", "y", "z"][i] for i in
                       rng.integers(0, 3, n)]),
        "v": pa.array(rng.integers(0, 100, n).astype(np.int64)),
    })
    df = s.create_dataframe(tb)
    got = (df.group_by(col("k")).pivot(col("p"), ["x", "y", "z"])
           .agg(F.sum(col("v")).alias("s")).collect().sort_by("k"))
    import collections
    want = collections.defaultdict(lambda: {"x": None, "y": None,
                                            "z": None})
    for k, p, v in zip(tb.column("k").to_pylist(),
                       tb.column("p").to_pylist(),
                       tb.column("v").to_pylist()):
        cur = want[k][p]
        want[k][p] = v if cur is None else cur + v
    for i, k in enumerate(got.column("k").to_pylist()):
        for p in ("x", "y", "z"):
            assert got.column(p).to_pylist()[i] == want[k][p], (k, p)


def test_approx_percentile_differential_and_sane():
    rng = np.random.default_rng(5)
    n = 3000
    tb = pa.table({
        "k": pa.array(rng.integers(0, 7, n).astype(np.int64)),
        "v": pa.array(rng.normal(0, 100, n)),
    })
    for p in (0.0, 0.25, 0.5, 0.9, 1.0):
        s1 = _session(True)
        got = (s1.create_dataframe(tb).group_by(col("k"))
               .agg(F.approx_percentile(col("v"), p).alias("q"))
               .collect().sort_by("k"))
        s2 = _session(False)
        want = (s2.create_dataframe(tb).group_by(col("k"))
                .agg(F.approx_percentile(col("v"), p).alias("q"))
                .collect().sort_by("k"))
        np.testing.assert_allclose(np.array(got.column("q")),
                                   np.array(want.column("q")),
                                   rtol=1e-12)
        # sanity vs numpy's inverted-CDF quantile per group
        ks = np.array(tb.column("k"))
        vs = np.array(tb.column("v"))
        for i, k in enumerate(got.column("k").to_pylist()):
            grp = np.sort(vs[ks == k])
            idx = max(int(np.ceil(p * len(grp))) - 1, 0)
            assert abs(got.column("q").to_pylist()[i] - grp[idx]) < 1e-9


def test_approx_percentile_int_type_preserved():
    s = _session()
    tb = pa.table({"v": pa.array([5, 1, 9, 3, 7], type=pa.int64())})
    out = s.create_dataframe(tb).agg(
        F.approx_percentile(col("v"), 0.5).alias("m")).collect()
    assert out.schema.field("m").type == pa.int64()
    assert out.column("m").to_pylist() == [5]


def test_tumbling_time_window_groups():
    s = _session()
    base = datetime.datetime(2024, 3, 1, 10, 0, 0,
                             tzinfo=datetime.timezone.utc)
    ts = [base + datetime.timedelta(minutes=m) for m in
          (0, 3, 7, 12, 14, 21)]
    tb = pa.table({
        "ts": pa.array(ts, type=pa.timestamp("us", tz="UTC")),
        "v": pa.array([1, 2, 3, 4, 5, 6], type=pa.int64()),
    })
    df = s.create_dataframe(tb)
    out = (df.group_by(F.window(col("ts"), "10 minutes").alias("w"))
           .agg(F.sum(col("v")).alias("s")).collect())
    rows = sorted((w["start"], s_) for w, s_ in
                  zip(out.column("w").to_pylist(),
                      out.column("s").to_pylist()))
    # minutes 0-9 -> 1+2+3; 10-19 -> 4+5; 20-29 -> 6
    assert [r[1] for r in rows] == [6, 9, 6]
    starts = [r[0].replace(tzinfo=datetime.timezone.utc) for r in rows]
    assert starts[0] == base
    assert starts[1] == base + datetime.timedelta(minutes=10)


def test_sliding_window_lowers_through_expand():
    """slide != window lowers through an Expand in the plan (Spark's
    TimeWindowing rule); a bare un-lowered sliding expression still
    raises rather than silently evaluating as tumbling."""
    s = _session()
    base = datetime.datetime(2024, 3, 1, tzinfo=datetime.timezone.utc)
    tb = pa.table({"ts": pa.array([base], type=pa.timestamp("us",
                                                            tz="UTC")),
                   "v": pa.array([1], type=pa.int64())})
    df = s.create_dataframe(tb)
    df.select(F.window(col("ts"), "10 minutes", "5 minutes")
              .alias("w")).collect()
    names = [n for n, _ in _tpu_ops(s)]
    assert "ExpandExec" in names, names

    # un-lowered bare expression (e.g. smuggled into a filter) raises
    from spark_rapids_tpu.api.column import Column
    from spark_rapids_tpu.expr.datetime_expr import TimeWindow
    from spark_rapids_tpu.expr.complextype import GetStructField
    bare = TimeWindow(col("ts").expr, 600_000_000, 300_000_000)
    with pytest.raises(NotImplementedError, match="sliding"):
        df.filter(Column(GetStructField(bare, "end")) > col("ts")) \
            .collect()


def test_window_start_time_offsets():
    s = _session()
    base = datetime.datetime(2024, 3, 1, 10, 0, 0,
                             tzinfo=datetime.timezone.utc)
    ts = [base + datetime.timedelta(minutes=m) for m in (0, 4, 6)]
    tb = pa.table({"ts": pa.array(ts, type=pa.timestamp("us", tz="UTC")),
                   "v": pa.array([1, 2, 4], type=pa.int64())})
    df = s.create_dataframe(tb)
    # zero and negative offsets are accepted (Spark parity)
    for st in ("0 minutes", "-5 minutes"):
        out = (df.group_by(F.window(col("ts"), "10 minutes",
                                    start_time=st).alias("w"))
               .agg(F.sum(col("v")).alias("s")).collect())
        assert sum(out.column("s").to_pylist()) == 7


def test_struct_key_grouping_on_cpu_engine():
    """The CPU oracle flattens struct keys for pyarrow grouping and
    rebuilds them (code-review round-3 finding)."""
    s = _session(False)
    base = datetime.datetime(2024, 3, 1, tzinfo=datetime.timezone.utc)
    ts = [base + datetime.timedelta(minutes=m) for m in (0, 3, 12)]
    tb = pa.table({"ts": pa.array(ts, type=pa.timestamp("us", tz="UTC")),
                   "v": pa.array([1, 2, 4], type=pa.int64())})
    out = (s.create_dataframe(tb)
           .group_by(F.window(col("ts"), "10 minutes").alias("w"))
           .agg(F.sum(col("v")).alias("s")).collect())
    assert sorted(out.column("s").to_pylist()) == [3, 4]


def test_approx_percentile_empty_input():
    for enabled in (True, False):
        s = _session(enabled)
        tb = pa.table({"v": pa.array([], type=pa.int64())})
        out = s.create_dataframe(tb).agg(
            F.approx_percentile(col("v"), 0.5).alias("m")).collect()
        assert out.num_rows == 1
        assert out.column("m").to_pylist() == [None], enabled


def test_normalize_nan_and_zero():
    from spark_rapids_tpu.api.column import Column
    from spark_rapids_tpu.expr.mathexpr import NormalizeNaNAndZero
    s = _session()
    tb = pa.table({"x": pa.array([0.0, -0.0, float("nan"), 1.5, None])})
    df = s.create_dataframe(tb)
    out = df.select(Column(NormalizeNaNAndZero(col("x").expr))
                    .alias("n")).collect()
    vals = out.column("n").to_pylist()
    assert str(vals[1]) == "0.0"          # -0.0 canonicalized
    assert np.isnan(vals[2])
    assert vals[3] == 1.5 and vals[4] is None
    # grouping floats already canonicalizes: -0.0 and 0.0 share a group
    g = (df.group_by(col("x")).agg(F.count("*").alias("c"))
         .collect())
    zero_rows = [c for x, c in zip(g.column("x").to_pylist(),
                                   g.column("c").to_pylist())
                 if x == 0.0]
    assert zero_rows == [2]


def test_sliding_window_expand_lowering():
    """Sliding windows lower through Expand + Filter (Spark's
    TimeWindowing rule): each row lands in every overlapping window and
    the aggregate matches a hand-computed oracle."""
    s = _session()
    base = datetime.datetime(2024, 3, 1, 10, 0, 0,
                             tzinfo=datetime.timezone.utc)
    minutes = (0, 3, 7, 12, 14, 21)
    ts = [base + datetime.timedelta(minutes=m) for m in minutes]
    vals = [1, 2, 3, 4, 5, 6]
    tb = pa.table({
        "ts": pa.array(ts, type=pa.timestamp("us", tz="UTC")),
        "v": pa.array(vals, type=pa.int64()),
    })
    df = s.create_dataframe(tb)
    out = (df.group_by(F.window(col("ts"), "10 minutes", "5 minutes")
                       .alias("w"))
           .agg(F.sum(col("v")).alias("s")).collect())
    got = {w["start"].replace(tzinfo=datetime.timezone.utc): sv
           for w, sv in zip(out.column("w").to_pylist(),
                            out.column("s").to_pylist())}
    # oracle: every window [start, start+10) stepping by 5 that contains
    # at least one row
    want = {}
    for m, v in zip(minutes, vals):
        for wstart in range(m - m % 5, m - 10, -5):
            if wstart <= m < wstart + 10:
                key = base + datetime.timedelta(minutes=wstart)
                want[key] = want.get(key, 0) + v
    assert got == want, (got, want)
    # each row appears in exactly 2 windows -> total doubles
    assert sum(out.column("s").to_pylist()) == 2 * sum(vals)


def test_sliding_window_in_select():
    s = _session()
    base = datetime.datetime(2024, 3, 1, tzinfo=datetime.timezone.utc)
    tb = pa.table({
        "ts": pa.array([base + datetime.timedelta(minutes=7)],
                       type=pa.timestamp("us", tz="UTC")),
        "v": pa.array([10], type=pa.int64()),
    })
    out = (s.create_dataframe(tb)
           .select(F.window(col("ts"), "10 minutes", "5 minutes")
                   .alias("w"), col("v")).collect())
    # minute 7 falls in windows starting at 0 and 5
    starts = sorted(w["start"].replace(tzinfo=datetime.timezone.utc)
                    for w in out.column("w").to_pylist())
    assert starts == [base, base + datetime.timedelta(minutes=5)]
    assert out.column("v").to_pylist() == [10, 10]


def test_multiple_sliding_windows_rejected():
    s = _session()
    base = datetime.datetime(2024, 3, 1, tzinfo=datetime.timezone.utc)
    tb = pa.table({"ts": pa.array([base], type=pa.timestamp("us",
                                                            tz="UTC"))})
    df = s.create_dataframe(tb)
    with pytest.raises(ValueError, match="one sliding time window"):
        df.select(F.window(col("ts"), "10 minutes", "5 minutes")
                  .alias("a"),
                  F.window(col("ts"), "30 minutes", "15 minutes")
                  .alias("b"))


def test_window_name_collision_handling():
    s = _session()
    base = datetime.datetime(2024, 3, 1, tzinfo=datetime.timezone.utc)
    tb = pa.table({
        "ts": pa.array([base + datetime.timedelta(minutes=3)],
                       type=pa.timestamp("us", tz="UTC")),
        "window": pa.array([42], type=pa.int64()),
    })
    df = s.create_dataframe(tb)
    # explicit alias colliding with a data column is an error
    with pytest.raises(ValueError, match="collides"):
        df.group_by(F.window(col("ts"), "10 minutes", "5 minutes")
                    .alias("window"))
    # the default internal name dodges the user's column
    out = (df.group_by(F.window(col("ts"), "10 minutes", "5 minutes")
                       .alias("w"))
           .agg(F.first(col("window")).alias("orig")).collect())
    assert out.column("orig").to_pylist() == [42, 42]


def test_sliding_window_mixed_with_window_function():
    """select() mixing a sliding window with a window FUNCTION routes
    both: the lowered select re-enters the normal routing (code-review
    round-3 finding: the early return skipped WindowExpression
    handling)."""
    from spark_rapids_tpu.expr.window import WindowBuilder
    s = _session()
    base = datetime.datetime(2024, 3, 1, tzinfo=datetime.timezone.utc)
    tb = pa.table({
        "ts": pa.array([base + datetime.timedelta(minutes=m)
                        for m in (1, 2, 8)],
                       type=pa.timestamp("us", tz="UTC")),
        "v": pa.array([10, 20, 30], type=pa.int64()),
    })
    df = s.create_dataframe(tb)
    w = WindowBuilder().order_by(col("v"))
    out = (df.select(F.window(col("ts"), "10 minutes", "5 minutes")
                     .alias("w"),
                     col("v"),
                     F.row_number().over(w).alias("rn"))
           .collect())
    # 3 rows x 2 overlapping windows each
    assert out.num_rows == 6
    assert sorted(set(out.column("rn").to_pylist())) == [1, 2, 3, 4, 5, 6]
