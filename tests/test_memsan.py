"""tmsan: the buffer-lifetime/peak-HBM analyzer differentially validated
against the runtime shadow ledger.

Three layers, mirroring the typechecker's oracle discipline
(test_interp_oracle.py):

  * differential — every golden good plan executes with the shadow
    ledger installed: measured peak device bytes <= the static TPU-L014
    bound, ledger clean (no leaks, no lifecycle violations) afterwards;
  * anti-vacuity — an injected leak, an injected use-after-close and an
    over-budget plan each produce their diagnostic (L015, L013, L014),
    statically AND at runtime, so a green gate is evidence;
  * repair — the TPU-L014 pre-flight forces the sort out-of-core
    (oc_budget) instead of downgrading, the repaired plan re-lints
    clean, still computes the right answer, and its measured peak
    respects the new bound.
"""

import importlib.util
import os

import pyarrow as pa
import pytest

from spark_rapids_tpu import types as t
from spark_rapids_tpu.analysis import lifetime
from spark_rapids_tpu.analysis.lifetime import (ALLOCATED, CLOSE, CLOSED,
                                                MATERIALIZE, REGISTER,
                                                REGISTERED, SPILL, UNBORN,
                                                analyze_memory,
                                                format_memory,
                                                lifecycle_next)
from spark_rapids_tpu.analysis.plan_lint import (downgrade_hazards,
                                                 lint_plan)
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec import base as eb
from spark_rapids_tpu.memory import memsan
from spark_rapids_tpu.memory.spill import SpillCatalog

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "goldens", "lint")


def _load(fname):
    spec = importlib.util.spec_from_file_location(
        fname.replace(".py", ""), os.path.join(GOLDEN_DIR, fname))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return {k: getattr(mod, k) for k in dir(mod) if k.startswith("plan_")}


GOOD = sorted(_load("good_plans.py"))


@pytest.fixture()
def fresh_catalog():
    with SpillCatalog._lock:
        old = SpillCatalog._instance
        SpillCatalog._instance = SpillCatalog()
    yield SpillCatalog._instance
    with SpillCatalog._lock:
        SpillCatalog._instance = old


def _release_plan(root):
    ids = []
    root.foreach(lambda e: ids.append(e._shuffle_id)
                 if getattr(e, "_shuffle_id", None) is not None else None)
    if ids:
        from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
        mgr = TpuShuffleManager.get()
        for sid in ids:
            mgr.unregister(sid)
    root.foreach(lambda e: e.release_shuffle()
                 if hasattr(e, "release_shuffle") else None)


# ---------------------------------------------------------------------------
# the lifecycle state machine itself
# ---------------------------------------------------------------------------

def test_lifecycle_machine_legal_paths():
    assert lifecycle_next(UNBORN, "alloc") == ALLOCATED
    assert lifecycle_next(ALLOCATED, REGISTER) == REGISTERED
    assert lifecycle_next(REGISTERED, SPILL) == "spilled"
    assert lifecycle_next("spilled", "unspill") == REGISTERED
    assert lifecycle_next(REGISTERED, CLOSE) == CLOSED


def test_lifecycle_machine_rejects_hazards():
    # use-after-close and register-after-close are not transitions
    assert lifecycle_next(CLOSED, MATERIALIZE) is None
    assert lifecycle_next(CLOSED, REGISTER) is None
    # an unregistered buffer cannot spill (nothing manages it)
    assert lifecycle_next(ALLOCATED, SPILL) is None


# ---------------------------------------------------------------------------
# differential: measured peak <= static bound, clean ledger, good corpus
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", GOOD)
def test_measured_peak_within_static_bound(name, fresh_catalog):
    root, conf_map = _load("good_plans.py")[name]()
    conf = RapidsConf(conf_map)
    res = analyze_memory(root, conf)
    bound = res.bound(root)
    assert bound is not None and not res.diags, format_memory(root, res)
    with memsan.installed() as ledger:
        ctx = eb.ExecContext(conf)
        ctx.task_context["no_speculation"] = True
        root.execute_collect(ctx)
        _release_plan(root)
        assert ledger.peak_device_bytes <= bound, (
            f"{name}: measured {ledger.peak_device_bytes} > bound "
            f"{int(bound)}\n" + format_memory(root, res))
        ledger.assert_clean()


# ---------------------------------------------------------------------------
# anti-vacuity: injections ARE caught (runtime + static)
# ---------------------------------------------------------------------------

def _one_batch(xp=None):
    import numpy as np
    from spark_rapids_tpu.columnar.device import batch_to_device
    rb = pa.RecordBatch.from_pydict(
        {"v": pa.array(range(64), type=pa.int64())})
    return batch_to_device(rb, xp=xp or np)


def test_injected_leak_is_caught(fresh_catalog):
    with memsan.installed() as ledger:
        sb = fresh_catalog.register(_one_batch())
        with pytest.raises(memsan.LifecycleViolation) as ei:
            ledger.assert_clean()
        assert "leaked buffer" in str(ei.value)
        assert "TPU-L015" in str(ei.value)
        sb.close()
        ledger.assert_clean()  # closing resolves the leak


def test_injected_use_after_close_is_caught(fresh_catalog):
    import numpy as np
    with memsan.installed():
        sb = fresh_catalog.register(_one_batch())
        sb.close()
        with pytest.raises(memsan.LifecycleViolation) as ei:
            sb.get_batch(np)
        assert "illegal materialize in state closed" in str(ei.value)


def test_use_after_close_guarded_even_without_ledger(fresh_catalog):
    """The engine itself now refuses (previously it returned None
    silently); the ledger adds provenance on top."""
    import numpy as np
    sb = fresh_catalog.register(_one_batch())
    sb.close()
    with pytest.raises(RuntimeError, match="use-after-close"):
        sb.get_batch(np)


def test_injected_double_spill_accounting(fresh_catalog):
    """Spill and unspill keep the ledger's device accounting exact."""
    with memsan.installed() as ledger:
        sb = fresh_catalog.register(_one_batch())
        live0 = ledger.device_live
        assert live0 >= sb.device_bytes
        sb.spill_to_host()
        assert ledger.device_live == live0 - sb.device_bytes
        sb.spill_to_disk()  # host->disk: no device delta
        assert ledger.device_live == live0 - sb.device_bytes
        sb.close()
        ledger.assert_clean()


def test_ledger_attributes_owner_exec(fresh_catalog):
    """Buffers registered inside an Exec's execute path carry the exec's
    name in the ledger and in leak_report()."""
    from spark_rapids_tpu.exec.outofcore import SpillBoundaryExec
    from spark_rapids_tpu.exec.basic import LocalScanExec
    scan = LocalScanExec(pa.table(
        {"v": pa.array(range(32), type=pa.int64())}))
    sb = SpillBoundaryExec(scan, consumers=2)  # never fully consumed
    with memsan.installed() as ledger:
        ctx = eb.ExecContext(RapidsConf({}))
        list(sb.execute_partition(0, ctx))
        leaks = ledger.live_entries()
        assert leaks and all(e.owner == "SpillBoundaryExec"
                             for e in leaks)
        assert any("owner=SpillBoundaryExec" in prov
                   for _i, _t, _b, prov in fresh_catalog.leak_report())
        with pytest.raises(memsan.LifecycleViolation,
                           match="SpillBoundaryExec"):
            ledger.assert_clean()


def test_runtime_use_after_close_on_shared_boundary(fresh_catalog):
    """Executing the L013 fixture really does materialize closed
    handles: the static prediction and the runtime agree."""
    root, conf_map = _load("bad_plans.py")[
        "plan_L013_shared_boundary_use_after_close"]()
    with memsan.installed():
        ctx = eb.ExecContext(RapidsConf(conf_map))
        with pytest.raises(memsan.LifecycleViolation):
            root.execute_collect(ctx)


def test_arena_alloc_after_close_is_caught():
    from spark_rapids_tpu.native.arena import HostArena
    arena = HostArena(1 << 16)
    with memsan.installed() as ledger:
        arena.alloc(128)
        assert ledger.arena_high_water >= 128
        arena.close()
        with pytest.raises(memsan.LifecycleViolation,
                           match="alloc after close"):
            arena.alloc(64)


# ---------------------------------------------------------------------------
# static rules over the bad fixtures (the plan-level injections)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,code", [
    ("plan_L013_shared_boundary_use_after_close", "TPU-L013"),
    ("plan_L014_peak_over_hbm_budget", "TPU-L014"),
    ("plan_L015_boundary_never_closes", "TPU-L015"),
])
def test_memory_fixture_flags_its_code(name, code):
    root, conf_map = _load("bad_plans.py")[name]()
    diags = lint_plan(root, RapidsConf(conf_map), infer=True)
    assert code in {d.code for d in diags}, [d.render() for d in diags]


def test_l014_vanishes_when_budget_fits():
    """The same plan under a roomy budget is admitted — the rule is
    driven by the bound, not the shape."""
    root, conf_map = _load("bad_plans.py")[
        "plan_L014_peak_over_hbm_budget"]()
    conf = RapidsConf(dict(
        conf_map, **{"spark.rapids.tpu.memsan.hbmBudgetBytes": "1g"}))
    assert not [d for d in lint_plan(root, conf, infer=True)
                if d.code == "TPU-L014"]


# ---------------------------------------------------------------------------
# the TPU-L014 repair: forced out-of-core, correct results, bounded peak
# ---------------------------------------------------------------------------

def test_l014_repair_forces_out_of_core_and_stays_correct(fresh_catalog):
    root, conf_map = _load("bad_plans.py")[
        "plan_L014_peak_over_hbm_budget"]()
    conf = RapidsConf(conf_map)
    diags = lint_plan(root, conf, infer=True)
    assert any(d.code == "TPU-L014" for d in diags)
    fixed = downgrade_hazards(root, diags, conf)
    # repaired in place: still on device, out-of-core forced
    assert fixed.placement == eb.TPU and fixed.oc_budget is not None
    assert not [d for d in lint_plan(fixed, conf, infer=True)
                if d.is_error]
    bound = analyze_memory(fixed, conf).bound(fixed)
    with memsan.installed() as ledger:
        ctx = eb.ExecContext(conf)
        ctx.task_context["no_speculation"] = True
        out = fixed.execute_collect(ctx)
        assert ledger.peak_device_bytes <= bound
        ledger.assert_clean()
    col = out.column("v").to_pylist()
    assert len(col) == 1 << 15  # nothing lost to the forced spilling
    # per-partition (non-global) sort: each partition is ordered
    assert sorted(col) == list(range(1 << 15))


def test_repair_sizes_budget_under_hbm_limit():
    root, conf_map = _load("bad_plans.py")[
        "plan_L014_peak_over_hbm_budget"]()
    conf = RapidsConf(conf_map)
    assert lifetime.try_outofcore_repair(root, root, conf)
    assert root.oc_budget is not None
    res = analyze_memory(root, conf)
    assert res.bound(root) <= res.budget and not res.diags


# ---------------------------------------------------------------------------
# session wiring: spark.rapids.tpu.memsan.enabled
# ---------------------------------------------------------------------------

def test_session_memsan_clean_query(fresh_catalog):
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api.column import col
    s = (TpuSession.builder()
         .config("spark.rapids.sql.explain", "NONE")
         .config("spark.rapids.tpu.memsan.enabled", True)
         .get_or_create())
    tb = pa.table({"k": pa.array([i % 3 for i in range(30)],
                                 type=pa.int64()),
                   "v": pa.array(range(30), type=pa.int64())})
    df = s.create_dataframe(tb, num_partitions=2)
    out = df.sort(col("v"), ascending=False).collect()
    assert out.column("v").to_pylist()[0] == 29
    assert memsan.active_ledger() is None  # uninstalled after the query


# ---------------------------------------------------------------------------
# TPU-R005 anti-vacuity: the AST rule sees an unrouted allocation
# ---------------------------------------------------------------------------

def test_r005_flags_unrouted_device_allocation(tmp_path):
    import ast
    from spark_rapids_tpu.analysis.repo_lint import _DeviceAllocVisitor
    src = (
        "def bad(batch, catalog):\n"
        "    sb = SpillableBatch(batch, catalog)\n"
        "    up = jax.device_put(batch)\n"
        "    arena = HostArena(1 << 20)\n"
        "    ok = catalog.register(batch)\n")
    v = _DeviceAllocVisitor("spark_rapids_tpu/exec/fake.py")
    v.visit(ast.parse(src))
    msgs = [d.message for d in v.diags]
    assert len(msgs) == 3, msgs
    assert any("SpillableBatch" in m for m in msgs)
    assert any("device_put" in m for m in msgs)
    assert any("HostArena" in m for m in msgs)
    assert all(d.code == "TPU-R005" for d in v.diags)


def test_allow_annotation_sanctions_single_site(tmp_path):
    """`# tpulint: allow[...]` suppresses exactly the annotated line."""
    from spark_rapids_tpu.analysis.repo_lint import _allowed_lines
    src = ("x = 1\n"
           "# tpulint: allow[TPU-R001] reason\n"
           "# continued reason\n"
           "np.asarray(y)\n"
           "np.asarray(z)\n")
    allowed = _allowed_lines(src)
    assert 4 in allowed["TPU-R001"]      # the annotated call
    assert 5 not in allowed["TPU-R001"]  # the next one still flags
