"""ICI mesh shuffle + distributed stage tests on the virtual 8-device
CPU mesh (the hermetic stand-in the driver complements with
__graft_entry__.dryrun_multichip)."""

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest
from spark_rapids_tpu.parallel.distributed import shard_map
from jax.sharding import PartitionSpec as P

from spark_rapids_tpu.parallel import (DistributedAggregate,
                                       DistributedExchange, build_mesh,
                                       exchange_by_pid, allgather_batch,
                                       stack_shards, unstack_shards)
from spark_rapids_tpu.columnar.device import batch_to_arrow
from spark_rapids_tpu.expr.core import AttributeReference as A
from spark_rapids_tpu.expr.aggregates import (AggregateExpression, Average,
                                              Count, Sum)

N_DEV = 8


def mesh8():
    assert len(jax.devices()) >= N_DEV
    return build_mesh(N_DEV)


def shard_tables(table, n=N_DEV):
    per = table.num_rows // n
    return [table.slice(i * per, per if i < n - 1 else
                        table.num_rows - per * (n - 1)) for i in range(n)]


def run_exchange(table, pid_of_row):
    """Drive exchange_by_pid under shard_map; return per-device tables."""
    mesh = mesh8()
    tables = shard_tables(table)
    stacked = stack_shards(tables)
    # pids derive from a designated int column via a pure function
    def step(shard):
        b = jax.tree_util.tree_map(lambda x: x[0], shard)
        pids = pid_of_row(b)
        out = exchange_by_pid(b, pids, N_DEV, "data")
        return jax.tree_util.tree_map(lambda x: x[None], out)

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"), check_vma=False))
    out = fn(stacked)
    return [batch_to_arrow(b) for b in unstack_shards(out)]


def test_exchange_routes_all_rows():
    n = 800
    rng = np.random.default_rng(0)
    table = pa.table({
        "k": pa.array(rng.integers(0, 64, n).astype(np.int64)),
        "v": pa.array(rng.random(n)),
    })
    outs = run_exchange(table, lambda b: b.columns[0].data % N_DEV)
    # every row lands exactly once, on the right device
    total = 0
    for d, rb in enumerate(outs):
        ks = rb.column("k").to_numpy()
        assert (ks % N_DEV == d).all()
        total += rb.num_rows
    assert total == n
    # multiset of (k, v) preserved
    got = pa.concat_tables(
        [pa.Table.from_batches([rb]) for rb in outs]).sort_by(
        [("k", "ascending"), ("v", "ascending")])
    want = table.sort_by([("k", "ascending"), ("v", "ascending")])
    assert got.equals(want)


def test_exchange_carries_nulls_and_strings():
    n = 160
    rng = np.random.default_rng(1)
    ks = rng.integers(0, 32, n)
    strs = [None if i % 7 == 0 else f"s{ks[i]}_" + "x" * (i % 5)
            for i in range(n)]
    vs = [None if i % 5 == 0 else int(i) for i in range(n)]
    table = pa.table({
        "k": pa.array(ks.astype(np.int64)),
        "s": pa.array(strs, type=pa.string()),
        "v": pa.array(vs, type=pa.int64()),
    })
    outs = run_exchange(table, lambda b: b.columns[0].data % N_DEV)
    got = pa.concat_tables(
        [pa.Table.from_batches([rb]) for rb in outs]).to_pydict()
    want = table.to_pydict()
    key = lambda r: (r[0], r[1] is None, r[1] or "", r[2] is None, r[2] or 0)  # noqa: E731
    got_rows = sorted(zip(got["k"], got["s"], got["v"]), key=key)
    want_rows = sorted(zip(want["k"], want["s"], want["v"]), key=key)
    assert got_rows == want_rows


def run_exchange_guarded(table, pid_of_row, slot):
    """exchange_by_pid with a sub-capacity slot under on_overflow='guard';
    returns (per-device tables, per-device ok bools)."""
    mesh = mesh8()
    stacked = stack_shards(shard_tables(table))

    def step(shard):
        b = jax.tree_util.tree_map(lambda x: x[0], shard)
        pids = pid_of_row(b)
        out, ok = exchange_by_pid(b, pids, N_DEV, "data", slot=slot,
                                  on_overflow="guard")
        return (jax.tree_util.tree_map(lambda x: x[None], out), ok[None])

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P("data"),
                           out_specs=(P("data"), P("data")),
                           check_vma=False))
    out, oks = fn(stacked)
    return ([batch_to_arrow(b) for b in unstack_shards(out)],
            [bool(x) for x in np.asarray(oks)])


def test_exchange_guard_mode_clean_when_budget_fits():
    """A sub-capacity slot that every destination fits under must route
    all rows AND report ok=True on every shard (the speculative-sizing
    fast path: ~slot/capacity of the full exchange footprint)."""
    n = 800  # 100 rows/shard; round-robin pids -> ~13 per destination
    table = pa.table({
        "k": pa.array((np.arange(n) % N_DEV).astype(np.int64)),
        "v": pa.array(np.arange(n, dtype=np.int64)),
    })
    outs, oks = run_exchange_guarded(
        table, lambda b: b.columns[0].data % N_DEV, slot=32)
    assert all(oks), oks
    total = 0
    for d, rb in enumerate(outs):
        assert (rb.column("k").to_numpy() % N_DEV == d).all()
        total += rb.num_rows
    assert total == n


def test_exchange_guard_mode_flags_overflow():
    """A skewed destination that exceeds the slot budget must flip the
    sending shards' guard to False — the caller's signal to re-run at
    slot=capacity — never silently drop rows without a flag."""
    n = 800  # every row targets device 0: 100 sends/shard > slot=32
    table = pa.table({
        "k": pa.array(np.zeros(n, dtype=np.int64)),
        "v": pa.array(np.arange(n, dtype=np.int64)),
    })
    outs, oks = run_exchange_guarded(
        table, lambda b: b.columns[0].data % N_DEV, slot=32)
    assert not any(oks), oks


def test_allgather_broadcast():
    table = pa.table({"b": pa.array(np.arange(64, dtype=np.int64))})
    mesh = mesh8()
    stacked = stack_shards(shard_tables(table))

    def step(shard):
        b = jax.tree_util.tree_map(lambda x: x[0], shard)
        out = allgather_batch(b, "data", N_DEV)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"), check_vma=False))
    outs = [batch_to_arrow(b) for b in unstack_shards(fn(stacked))]
    for rb in outs:
        assert sorted(rb.column("b").to_pylist()) == list(range(64))


def test_distributed_aggregate_matches_single_host():
    n = 4000
    rng = np.random.default_rng(2)
    table = pa.table({
        "k": pa.array(rng.integers(0, 97, n).astype(np.int64)),
        "v": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
        "f": pa.array(rng.random(n)),
    })
    dagg = DistributedAggregate(
        grouping=[A("k")],
        aggregates=[AggregateExpression(Sum(A("v")), "sv"),
                    AggregateExpression(Average(A("f")), "af"),
                    AggregateExpression(Count(None), "c")],
        in_names=["k", "v", "f"],
        in_types=_types(table),
        mesh=mesh8())
    got = dagg.run(shard_tables(table)).sort_by("k")

    import pyarrow.compute as pc
    gb = pa.TableGroupBy(table, ["k"], use_threads=False).aggregate(
        [("v", "sum"), ("f", "mean"), ("k", "count")])
    want = gb.sort_by("k")
    assert got.column("k").to_pylist() == want.column("k").to_pylist()
    assert got.column("sv").to_pylist() == want.column("v_sum").to_pylist()
    np.testing.assert_allclose(np.array(got.column("af")),
                               np.array(want.column("f_mean")), rtol=1e-9)
    assert got.column("c").to_pylist() == want.column("k_count").to_pylist()


def test_distributed_global_aggregate():
    n = 1000
    table = pa.table({"v": pa.array(np.arange(n, dtype=np.int64))})
    dagg = DistributedAggregate(
        grouping=[], aggregates=[AggregateExpression(Sum(A("v")), "sv"),
                                 AggregateExpression(Count(None), "c")],
        in_names=["v"], in_types=_types(table), mesh=mesh8())
    got = dagg.run(shard_tables(table))
    assert got.num_rows == 1
    assert got.column("sv").to_pylist() == [n * (n - 1) // 2]
    assert got.column("c").to_pylist() == [n]


def test_distributed_exchange_partitions_by_key():
    n = 512
    rng = np.random.default_rng(3)
    table = pa.table({
        "k": pa.array(rng.integers(0, 50, n).astype(np.int64)),
        "v": pa.array(rng.random(n)),
    })
    dx = DistributedExchange([A("k")], ["k", "v"], _types(table),
                             mesh=mesh8())
    outs = dx.run(shard_tables(table))
    # same key never appears on two devices
    seen = {}
    total = 0
    for d, tb in enumerate(outs):
        total += tb.num_rows
        for k in set(tb.column("k").to_pylist()):
            assert seen.setdefault(k, d) == d
    assert total == n


def _types(table):
    from spark_rapids_tpu.columnar.interop import from_arrow_type
    return [from_arrow_type(f.type) for f in table.schema]


def test_distributed_sort_balances_shards():
    """Routing uses the VALUE key word (nulls pinned to the boundary), so
    uniform data spreads across shards instead of all landing on one
    device (code-review round-3 finding: routing on the null-indicator
    word sent every non-null row to the last shard)."""
    import jax
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.columnar.interop import from_arrow_type
    from spark_rapids_tpu.expr.core import AttributeReference as A
    from spark_rapids_tpu.parallel.distributed import (DistributedSort,
                                                       stack_shards,
                                                       unstack_shards)
    from spark_rapids_tpu.parallel.mesh import build_mesh

    n_dev = 8
    rng = np.random.default_rng(9)
    n = 4096
    vals = rng.integers(-10**6, 10**6, n).astype(np.int64)
    tb = pa.table({"v": pa.array(vals)})
    per = n // n_dev
    shards = [tb.slice(i * per, per) for i in range(n_dev)]
    ds = DistributedSort([(A("v"), True, True)], ["v"],
                         [from_arrow_type(tb.schema[0].type)],
                         mesh=build_mesh(n_dev))
    out = ds._compiled(stack_shards(shards))
    per_shard = [int(np.asarray(b.num_rows)) for b in unstack_shards(out)]
    assert sum(per_shard) == n
    nonempty = sum(1 for c in per_shard if c > 0)
    assert nonempty >= n_dev // 2, per_shard     # spread, not one hot shard
    assert max(per_shard) < n // 2, per_shard    # no shard holds half
    # and the concatenation is still the total order
    allv = []
    for b in unstack_shards(out):
        m = int(np.asarray(b.num_rows))
        allv += list(np.asarray(b.columns[0].data)[:m])
    assert allv == sorted(vals.tolist())


def test_exchange_carries_structs():
    """Struct-of-flat columns ride the ICI exchange: row-aligned children
    move under the same permutation (round-5 widening; arrays/maps still
    stage via host)."""
    n = 240
    rng = np.random.default_rng(9)
    ks = rng.integers(0, 24, n)
    table = pa.table({
        "k": pa.array(ks.astype(np.int64)),
        "st": pa.array(
            [None if i % 11 == 0 else
             {"a": int(i), "b": None if i % 6 == 0 else float(i) / 3}
             for i in range(n)],
            type=pa.struct([("a", pa.int64()), ("b", pa.float64())])),
    })
    from spark_rapids_tpu.parallel.alltoall import exchange_supported
    from spark_rapids_tpu.columnar.interop import from_arrow_type
    assert exchange_supported(
        [from_arrow_type(f.type) for f in table.schema]) is None
    outs = run_exchange(table, lambda b: b.columns[0].data % N_DEV)
    for d, rb in enumerate(outs):
        assert (rb.column("k").to_numpy() % N_DEV == d).all()
    got = pa.concat_tables([pa.Table.from_batches([rb]) for rb in outs])
    key = lambda r: (r[0], repr(r[1]))  # noqa: E731
    got_rows = sorted(zip(got.column("k").to_pylist(),
                          got.column("st").to_pylist()), key=key)
    want_rows = sorted(zip(table.column("k").to_pylist(),
                           table.column("st").to_pylist()), key=key)
    assert got_rows == want_rows


def test_exchange_carries_arrays_and_maps():
    """Array/map columns of fixed-width elements ride the ICI exchange:
    child lanes move through the generalized span layout (round-5;
    string/struct elements still stage via host)."""
    n = 200
    rng = np.random.default_rng(15)
    ks = rng.integers(0, 16, n)
    arrs = [None if i % 13 == 0 else
            [int(x) if x % 4 else None
             for x in range(i % 5)]        # empty lists + null elements
            for i in range(n)]
    maps = [None if i % 9 == 0 else
            {int(j): float(i + j) / 7 for j in range(i % 3)}
            for i in range(n)]
    table = pa.table({
        "k": pa.array(ks.astype(np.int64)),
        "a": pa.array(arrs, type=pa.list_(pa.int64())),
        "m": pa.array(maps, type=pa.map_(pa.int64(), pa.float64())),
    })
    from spark_rapids_tpu.parallel.alltoall import exchange_supported
    from spark_rapids_tpu.columnar.interop import from_arrow_type
    assert exchange_supported(
        [from_arrow_type(f.type) for f in table.schema]) is None
    outs = run_exchange(table, lambda b: b.columns[0].data % N_DEV)
    for d, rb in enumerate(outs):
        assert (rb.column("k").to_numpy() % N_DEV == d).all()
    got = pa.concat_tables([pa.Table.from_batches([rb]) for rb in outs])
    key = lambda r: (r[0], repr(r[1]), repr(r[2]))  # noqa: E731
    got_rows = sorted(zip(got.column("k").to_pylist(),
                          got.column("a").to_pylist(),
                          got.column("m").to_pylist()), key=key)
    want_rows = sorted(zip(table.column("k").to_pylist(),
                           table.column("a").to_pylist(),
                           table.column("m").to_pylist()), key=key)
    assert got_rows == want_rows
