"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh BEFORE any
jax import, so TPU-path kernels and multi-chip sharding run hermetically
(the driver separately dry-runs multichip via __graft_entry__)."""

import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""   # disable the axon TPU tunnel
os.environ["JAX_PLATFORMS"] = "cpu"
# The persistent cache stays ON for the suite: a full fresh-compile run
# JITs ~600 programs in one process and XLA:CPU has segfaulted compiling
# late programs in such runs (LLVM JIT aging), while warm-cache solo
# runs have been stable across every round.  The cache is scoped to the
# machine instance (plugin._host_cpu_fingerprint), so stale-instance AOT
# loads — the other observed crash — cannot occur.  Set
# SPARK_RAPIDS_TPU_DISABLE_COMPILE_CACHE=1 only when running several
# engine processes concurrently against one cache dir.
# silence the cpu_aot_loader machine-feature ERROR spam: XLA bakes
# +prefer-no-scatter/-gather pseudo-features into its own AOT cache
# entries, so even same-host loads log a scary (but benign) mismatch
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    xla_flags += " --xla_force_host_platform_device_count=8"
if "xla_cpu_enable_fast_math" not in xla_flags:
    # fast-math breaks IEEE inf/nan semantics (floor(inf) -> nan)
    xla_flags += " --xla_cpu_enable_fast_math=false"
if "xla_cpu_parallel_codegen_split_count" not in xla_flags:
    # a full-suite process JITs hundreds of programs; XLA:CPU's parallel
    # LLVM codegen has crashed nondeterministically deep into such runs
    # (segfault inside backend_compile_and_load) — serialize it
    xla_flags += " --xla_cpu_parallel_codegen_split_count=1"
os.environ["XLA_FLAGS"] = xla_flags.strip()

# the axon sitecustomize imports jax at interpreter start, so env vars are
# too late — steer the (not-yet-initialized) backend via config directly
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tpu_session():
    from spark_rapids_tpu.api.session import TpuSession
    return TpuSession.builder().get_or_create()
