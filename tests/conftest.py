"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh BEFORE any
jax import, so TPU-path kernels and multi-chip sharding run hermetically
(the driver separately dry-runs multichip via __graft_entry__)."""

import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""   # disable the axon TPU tunnel
os.environ["JAX_PLATFORMS"] = "cpu"
# A full suite process drives the kernel's vm.max_map_count (65530)
# into the ground: glibc malloc serves every large XLA:CPU buffer with
# its own anonymous mmap, and ~600 jitted programs' worth of arrays put
# the process at ~36k maps by mid-suite and over the limit around the
# window tests — at which point ANY native allocation (a compile, a
# cache serialize, a cache read) segfaults.  mallopt(M_MMAP_MAX, 0)
# routes large allocations through the heap instead; map count stays
# flat and the crashes disappear.  (Root-caused from three distinct
# fatal stacks that all struck at the same process age.)
import ctypes

try:
    _libc = ctypes.CDLL("libc.so.6", use_errno=True)
    _libc.mallopt(-4, 0)        # M_MMAP_MAX = 0
except Exception:               # non-glibc platforms: keep defaults
    pass
# silence the cpu_aot_loader machine-feature ERROR spam: XLA bakes
# +prefer-no-scatter/-gather pseudo-features into its own AOT cache
# entries, so even same-host loads log a scary (but benign) mismatch
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    xla_flags += " --xla_force_host_platform_device_count=8"
if "xla_cpu_enable_fast_math" not in xla_flags:
    # fast-math breaks IEEE inf/nan semantics (floor(inf) -> nan)
    xla_flags += " --xla_cpu_enable_fast_math=false"
if "xla_cpu_parallel_codegen_split_count" not in xla_flags:
    # a full-suite process JITs hundreds of programs; XLA:CPU's parallel
    # LLVM codegen has crashed nondeterministically deep into such runs
    # (segfault inside backend_compile_and_load) — serialize it
    xla_flags += " --xla_cpu_parallel_codegen_split_count=1"
if "xla_cpu_use_thunk_runtime" not in xla_flags:
    # the thunk runtime JITs one LLVM module PER KERNEL (~16k modules x
    # 3 mappings for this suite), blowing through the kernel's
    # vm.max_map_count (65530) mid-run — at which point any native
    # allocation segfaults.  The legacy runtime emits one module per
    # executable: map count stays ~2k for the same suite.
    xla_flags += " --xla_cpu_use_thunk_runtime=false"
os.environ["XLA_FLAGS"] = xla_flags.strip()

# the axon sitecustomize imports jax at interpreter start, so env vars are
# too late — steer the (not-yet-initialized) backend via config directly
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tpu_session():
    from spark_rapids_tpu.api.session import TpuSession
    return TpuSession.builder().get_or_create()


@pytest.fixture(autouse=True, scope="module")
def _bound_jit_code_residency():
    """Flush compiled-code caches between test modules.

    Beyond the engine's own LRU (exec/base.py process_jit), jax keeps
    GLOBAL caches for eager ops and dropped jits; across ~40 modules the
    accumulated LLVM JIT segments walk the process into the kernel's
    vm.max_map_count, after which any native allocation segfaults.
    In-module kernel reuse (what the tests exercise) is unaffected."""
    yield
    import jax

    from spark_rapids_tpu.exec.base import clear_jit_cache
    clear_jit_cache()
    jax.clear_caches()
