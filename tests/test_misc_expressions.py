"""Round-2 expression catalog additions: monotonically_increasing_id,
spark_partition_id, rand, input_file_name, md5, concat_ws,
get_json_object (ref GpuMonotonicallyIncreasingID.scala,
GpuGetJsonObject.scala, stringFunctions.scala, InputFileBlockRule.scala)."""

import hashlib
import json

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _session(enabled=True):
    return TpuSession.builder().config("spark.rapids.sql.enabled",
                                       enabled).get_or_create()


def _placements(s):
    out = []
    s.last_plan.foreach(lambda e: out.append((type(e).__name__,
                                              e.placement)))
    return out


def test_monotonically_increasing_id_layout():
    s = _session()
    n = 1000
    tb = pa.table({"v": pa.array(np.arange(n, dtype=np.int64))})
    out = s.create_dataframe(tb, num_partitions=4).select(
        col("v"), F.monotonically_increasing_id().alias("mid"),
        F.spark_partition_id().alias("pid")).collect()
    # runs on TPU
    assert any(n_ == "ProjectExec" and p == "tpu"
               for n_, p in _placements(s))
    mids = out.column("mid").to_pylist()
    pids = out.column("pid").to_pylist()
    assert len(set(mids)) == n, "ids must be unique"
    for m, p in zip(mids, pids):
        assert (m >> 33) == p, "high bits carry the partition id"
    # within each partition ids increase by 1 from (pid << 33)
    by_pid = {}
    for m, p in zip(mids, pids):
        by_pid.setdefault(p, []).append(m)
    for p, ms in by_pid.items():
        base = p << 33
        assert sorted(ms) == list(range(base, base + len(ms)))


def test_rand_deterministic_and_engine_agreeing():
    tb = pa.table({"v": pa.array(np.arange(500, dtype=np.int64))})
    outs = {}
    for enabled in (True, False):
        s = _session(enabled)
        outs[enabled] = s.create_dataframe(tb, num_partitions=2).select(
            F.rand(42).alias("r")).collect().column("r").to_pylist()
    assert outs[True] == outs[False], "engines must agree"
    rs = outs[True]
    assert all(0.0 <= r < 1.0 for r in rs)
    assert len(set(rs)) > 450, "values must look uniform, not repeated"
    # different seed -> different stream
    s = _session(True)
    other = s.create_dataframe(tb, num_partitions=2).select(
        F.rand(7).alias("r")).collect().column("r").to_pylist()
    assert other != rs


def test_md5():
    s = _session()
    vals = ["hello", "", None, "spark-rapids-tpu"]
    tb = pa.table({"s": pa.array(vals)})
    out = s.create_dataframe(tb).select(F.md5(col("s")).alias("h")) \
        .collect()
    want = [hashlib.md5(v.encode()).hexdigest() if v is not None else None
            for v in vals]
    assert out.column("h").to_pylist() == want


def test_concat_ws_skips_nulls():
    s = _session()
    tb = pa.table({
        "a": pa.array(["x", None, "p", None]),
        "b": pa.array(["y", "q", None, None]),
    })
    out = s.create_dataframe(tb).select(
        F.concat_ws("-", col("a"), col("b")).alias("j")).collect()
    # Spark: null args skipped entirely; all-null -> empty string
    assert out.column("j").to_pylist() == ["x-y", "q", "p", ""]


def test_get_json_object():
    s = _session()
    docs = [
        json.dumps({"a": {"b": [1, 2, {"c": "deep"}]}, "s": "str",
                    "n": 2.5, "t": True, "z": None}),
        "not json",
        None,
        json.dumps([10, 20]),
    ]
    tb = pa.table({"j": pa.array(docs)})
    out = s.create_dataframe(tb).select(
        F.get_json_object(col("j"), "$.a.b[2].c").alias("deep"),
        F.get_json_object(col("j"), "$.s").alias("s"),
        F.get_json_object(col("j"), "$.n").alias("n"),
        F.get_json_object(col("j"), "$.t").alias("t"),
        F.get_json_object(col("j"), "$.z").alias("z"),
        F.get_json_object(col("j"), "$[1]").alias("idx"),
        F.get_json_object(col("j"), "$.a").alias("nested"),
        F.get_json_object(col("j"), "$.missing").alias("miss"),
    ).collect()
    assert out.column("deep").to_pylist() == ["deep", None, None, None]
    assert out.column("s").to_pylist() == ["str", None, None, None]
    assert out.column("n").to_pylist() == ["2.5", None, None, None]
    assert out.column("t").to_pylist() == ["true", None, None, None]
    assert out.column("z").to_pylist() == [None, None, None, None]
    assert out.column("idx").to_pylist() == [None, None, None, "20"]
    assert out.column("nested").to_pylist() == \
        ['{"b":[1,2,{"c":"deep"}]}', None, None, None]
    assert out.column("miss").to_pylist() == [None, None, None, None]


def test_input_file_name(tmp_path):
    import pyarrow.parquet as pq
    s = _session()
    paths = []
    for i in range(2):
        p = str(tmp_path / f"part-{i}.parquet")
        pq.write_table(pa.table({
            "v": pa.array(np.arange(5, dtype=np.int64) + 10 * i)}), p)
        paths.append(p)
    df = s.read.parquet(*paths)
    out = df.select(col("v"),
                    F.input_file_name().alias("f")).collect()
    got = dict(zip(out.column("v").to_pylist(),
                   out.column("f").to_pylist()))
    for i, p in enumerate(paths):
        for v in range(10 * i, 10 * i + 5):
            assert got[v] == p, (v, got[v])


def test_input_file_name_empty_after_exchange():
    s = _session()
    tb = pa.table({"k": pa.array([1, 2, 1, 2]),
                   "v": pa.array([1, 2, 3, 4])})
    # local (non-file) scan: no input file at all
    out = s.create_dataframe(tb, num_partitions=2) \
        .group_by(col("k")).agg(F.sum(col("v")).alias("sv")) \
        .select(F.input_file_name().alias("f")).collect()
    assert set(out.column("f").to_pylist()) == {""}


def test_split_and_registered_docs_refresh():
    s = _session()
    tb = pa.table({"s": pa.array(["a,b,c", "x", None])})
    out = s.create_dataframe(tb).select(
        F.split(col("s"), ",").alias("parts")).collect()
    assert out.column("parts").to_pylist() == \
        [["a", "b", "c"], ["x"], None]
