"""tpuxsan tests: the analytic cost model against synthetic ledger
records (linear vs log-linear pass families, absent cost keys staying
absent), StableHLO hazard parsing on checked-in snippets, the
TPU-L018/L019/L020/R017 rules with their clean twins, capacity
propagation through compacting operators, the speculative re-bucket
repair's arm/refuse cases, the shrink/bucket device helpers at their
edges, StableHLO persistence (dedupe + size cap), and the kernel-gap
report's two-ledger join, ranking and CLI render."""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as t
from spark_rapids_tpu.analysis import hloaudit, hlocost
from spark_rapids_tpu.analysis.interp import infer_plan
from spark_rapids_tpu.analysis.plan_lint import (downgrade_hazards,
                                                 lint_plan)
from spark_rapids_tpu.columnar.device import (DeviceBatch, DeviceColumn,
                                              bucket_floor, bucket_for,
                                              shrink_batch,
                                              shrink_column)
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec import base as eb
from spark_rapids_tpu.exec.basic import (FilterExec, LocalScanExec,
                                         ProjectExec)
from spark_rapids_tpu.expr.core import (Alias, AttributeReference,
                                        Literal)
from spark_rapids_tpu.expr.predicates import GreaterThan
from spark_rapids_tpu.obs.compileprof import (HLO_MAX_BYTES, HLO_SUFFIX,
                                              CompileObservatory,
                                              cost_summary, hlo_key)
from spark_rapids_tpu.tools.kernel_report import (aggregate_kernel_report,
                                                  format_kernel_report,
                                                  load_estimator_ledger,
                                                  run_kernel_report)


def _codes(diags):
    return sorted(d.code for d in diags)


def _scan(table, placement=eb.TPU, **kw):
    s = LocalScanExec(table, **kw)
    s.placement = placement
    return s


def _ints(n=8, name="v"):
    return pa.table({name: pa.array(range(n), type=pa.int64())})


# -- the analytic cost model ------------------------------------------------

def test_record_base_bytes_sums_dispatch_leaves():
    rec = {"caps": [[1024], [1024], []], "dtypes":
           ["int64", "bool", "int32"]}
    # the scalar leaf still books its width: 1024*8 + 1024*1 + 4
    assert hlocost.record_base_bytes(rec) == 1024 * 8 + 1024 + 4
    assert hlocost.record_base_bytes({}) == 0


def test_analytic_bytes_linear_family():
    rec = {"exec": "ProjectExec", "caps": [[1024]], "dtypes": ["int64"]}
    assert hlocost.analytic_bytes(rec) == int(1024 * 8 * 3.0)


def test_analytic_bytes_log_family_scales_with_bucket():
    small = {"exec": "TpuHashAggregateExec", "caps": [[1024]],
             "dtypes": ["int64"]}
    big = {"exec": "TpuHashAggregateExec", "caps": [[8192]],
           "dtypes": ["int64"]}
    # scan-composed programs pay log2(n) full-width stages
    assert hlocost.analytic_bytes(small) == int(1024 * 8 * 8.0 * 10)
    assert hlocost.analytic_bytes(big) == int(8192 * 8 * 8.0 * 13)
    assert hlocost.record_max_dim(big) == 8192


def test_cost_agreement_absent_is_absent_never_zero():
    rec = {"exec": "ProjectExec", "caps": [[64]], "dtypes": ["int64"]}
    assert hlocost.xla_bytes(rec) is None
    assert hlocost.cost_agreement(rec, 8.0) is None
    rec["cost"] = {"flops": 12.0}          # backend omitted bytes
    assert hlocost.xla_bytes(rec) is None
    rec["cost"] = {"bytes accessed": float(64 * 8 * 3)}
    ok, ratio = hlocost.cost_agreement(rec, 8.0)
    assert ok and ratio == pytest.approx(1.0)


def test_validate_model_agreement_and_vacuity():
    good = {"event": "build", "exec": "ProjectExec", "caps": [[64]],
            "dtypes": ["int64"],
            "cost": {"bytes accessed": float(64 * 8 * 3)}}
    drift = {"event": "build", "exec": "ProjectExec", "caps": [[64]],
             "dtypes": ["int64"],
             "cost": {"bytes accessed": float(64 * 8 * 3 * 100)}}
    out = hlocost.validate_model([good, drift], tolerance=8.0)
    assert (out["checked"], out["agreed"]) == (2, 1)
    assert out["agreement_pct"] == pytest.approx(50.0)
    assert out["worst"]["ratio"] == pytest.approx(0.01)
    # no cost data at all: the pct is None, never a fake 100
    vac = hlocost.validate_model(
        [{"event": "build", "exec": "ProjectExec"}], 8.0)
    assert vac["checked"] == 0 and vac["agreement_pct"] is None


def test_pad_waste_for_math():
    ratio, waste = hlocost.pad_waste_for(10, 1024, 8.0)
    assert ratio == pytest.approx((1024 - 10) / 1024)
    assert waste == int((1024 - 10) * 8.0)
    assert hlocost.pad_waste_for(1024, 1024, 8.0) == (0.0, 0)


# -- StableHLO hazard parsing ----------------------------------------------

_HOST_HLO = '''\
func.func @main(%arg0: tensor<4xi64>) -> tensor<4xi64> {
  %0 = "stablehlo.custom_call"(%arg0) {call_target_name = \
"xla_python_cpu_callback"} : (tensor<4xi64>) -> tensor<4xi64>
  return %0 : tensor<4xi64>
}
'''

_SEND_HLO = '''\
func.func @main(%arg0: tensor<4xi64>) -> tensor<4xi64> {
  %0 = "stablehlo.send"(%arg0) : (tensor<4xi64>) -> !stablehlo.token
  return %arg0 : tensor<4xi64>
}
'''

_BCAST_HLO = '''\
func.func @main(%arg0: tensor<1xf32>) -> tensor<8388608xf32> {
  %0 = "stablehlo.broadcast_in_dim"(%arg0) : (tensor<1xf32>) \
-> tensor<8388608xf32>
  return %0 : tensor<8388608xf32>
}
'''

_CLEAN_HLO = '''\
func.func @main(%arg0: tensor<4xi64>) -> tensor<4xi64> {
  %0 = stablehlo.add %arg0, %arg0 : tensor<4xi64>
  return %0 : tensor<4xi64>
}
'''


def test_parse_hlo_hazards_host_callback_and_send():
    hz = hloaudit.parse_hlo_hazards(_HOST_HLO, 16 << 20)
    assert len(hz["host_transfers"]) == 1
    assert "callback" in hz["host_transfers"][0][1]
    hz2 = hloaudit.parse_hlo_hazards(_SEND_HLO, 16 << 20)
    assert len(hz2["host_transfers"]) == 1


def test_parse_hlo_hazards_broadcast_bytes():
    hz = hloaudit.parse_hlo_hazards(_BCAST_HLO, 16 << 20)
    # the broadcast RESULT (last tensor on the line) is 32 MiB of f32
    assert hz["big_broadcasts"] == [(2, 8388608 * 4)]
    # raising the budget past the result size silences it
    assert not hloaudit.parse_hlo_hazards(
        _BCAST_HLO, 64 << 20)["big_broadcasts"]


def test_parse_hlo_hazards_clean_program():
    hz = hloaudit.parse_hlo_hazards(_CLEAN_HLO, 16 << 20)
    assert not hz["host_transfers"] and not hz["big_broadcasts"]


def test_audit_ledger_twins_and_dedupe(tmp_path):
    bad_h, ok_h = hlo_key(_HOST_HLO), hlo_key(_CLEAN_HLO)
    for h, text in ((bad_h, _HOST_HLO), (ok_h, _CLEAN_HLO)):
        (tmp_path / (h + HLO_SUFFIX)).write_text(text)
    recs = [
        {"event": "build", "exec": "ProbeExec", "hlo_hash": bad_h},
        # same program again: the audit reads it ONCE
        {"event": "build", "exec": "ProbeExec", "hlo_hash": bad_h},
        {"event": "build", "exec": "CleanExec", "hlo_hash": ok_h},
        # hash with no artifact (oversized or pruned): skipped, no crash
        {"event": "build", "exec": "GoneExec", "hlo_hash": "0" * 16},
    ]
    diags = hloaudit.audit_ledger(recs, str(tmp_path), 16 << 20)
    assert _codes(diags) == ["TPU-L019"]
    assert "ProbeExec" in diags[0].loc


def test_audit_ledger_no_dir_is_noop():
    recs = [{"event": "build", "exec": "X", "hlo_hash": "ab"}]
    assert hloaudit.audit_ledger(recs, None, 16 << 20) == []


# -- TPU-R017: raw jnp/lax bypassing the kernel table ----------------------

_R017_BAD = "import jax.numpy as jnp\n\n\ndef widen(c):\n" \
            "    return jnp.cumsum(c)\n"
_R017_XP = "def widen(c, xp):\n    return xp.cumsum(c)\n"
_R017_ALLOW = ("import jax.numpy as jnp\n\n\ndef widen(c):\n"
               "    return jnp.cumsum(c)  "
               "# tpulint: allow[TPU-R017] test fixture\n")
_R017_KERNEL = ("import jax.numpy as jnp\n\n\ndef count_matches(a):\n"
                "    return jnp.cumsum(a)\n")


def test_r017_raw_call_in_exec_trips():
    diags = hloaudit.module_diagnostics(_R017_BAD, "exec/fake.py")
    assert _codes(diags) == ["TPU-R017"]
    assert "jnp.cumsum" in diags[0].message


def test_r017_clean_twins():
    assert not hloaudit.module_diagnostics(_R017_XP, "exec/fake.py")
    assert not hloaudit.module_diagnostics(_R017_ALLOW, "exec/fake.py")
    # outside exec//ops/ the rule does not apply
    assert not hloaudit.module_diagnostics(_R017_BAD, "obs/fake.py")


def test_r017_registered_kernel_entry_point_passes():
    # count_matches is in the DEVICE_KERNELS capability table for
    # ops/join_kernels.py: the registered surface may call lax/jnp
    assert not hloaudit.module_diagnostics(_R017_KERNEL,
                                           "ops/join_kernels.py")
    # the same source elsewhere in ops/ is unregistered -> trips
    assert _codes(hloaudit.module_diagnostics(
        _R017_KERNEL, "ops/fake.py")) == ["TPU-R017"]


def test_r017_live_tree_owes_nothing():
    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "spark_rapids_tpu")
    live = [d for d in hloaudit.repo_diagnostics(pkg)
            if d.code == "TPU-R017"]
    assert live == [], [d.render() for d in live]


# -- capacity propagation + TPU-L018 ---------------------------------------

def _filter_plan(n, buckets):
    scan = _scan(_ints(n=n))
    flt = FilterExec(GreaterThan(AttributeReference("v"),
                                 Literal(n // 2, t.LONG)), scan)
    flt.placement = eb.TPU
    conf = RapidsConf({"spark.rapids.tpu.batchCapacityBuckets": buckets}
                      if buckets else {})
    return flt, conf


def test_plan_pad_waste_filter_inherits_child_capacity():
    flt, conf = _filter_plan(2000, "1024,1048576")
    waste = hlocost.plan_pad_waste(flt, conf, infer_plan(flt, conf))
    by_node = {id(w["node"]): w for w in waste}
    # the scan's 2000 rows land in the 1M bucket; the filter's ~1000
    # survivors COMPACT but keep the input capacity — re-bucketing is
    # the repair's job, not the model's assumption
    assert by_node[id(flt)]["capacity"] == 1048576
    assert by_node[id(flt.children[0])]["capacity"] == 1048576
    assert by_node[id(flt)]["waste_ratio"] > 0.99


def test_audit_plan_l018_trips_and_default_buckets_stay_clean():
    flt, conf = _filter_plan(10, "1048576")
    diags = hloaudit.audit_plan(flt, conf, infer_plan(flt, conf))
    assert "TPU-L018" in _codes(diags)
    # default buckets are <= 8x apart and the waste is under the MiB
    # floor: no finding
    flt2, conf2 = _filter_plan(10, None)
    assert not hloaudit.audit_plan(flt2, conf2,
                                   infer_plan(flt2, conf2))


def test_lint_plan_xsan_disabled_is_silent():
    flt, _ = _filter_plan(10, "1048576")
    conf = RapidsConf({"spark.rapids.tpu.batchCapacityBuckets":
                       "1048576",
                       "spark.rapids.tpu.xsan.enabled": False})
    codes = {d.code for d in lint_plan(flt, conf, infer=True)}
    assert not codes & {"TPU-L018", "TPU-L020"}


# -- TPU-L020: fusion breaks -----------------------------------------------

def _project_filter_plan(n):
    scan = _scan(_ints(n=n))
    flt = FilterExec(GreaterThan(AttributeReference("v"),
                                 Literal(0, t.LONG)), scan)
    flt.placement = eb.TPU
    proj = ProjectExec([Alias(AttributeReference("v"), "v2")], flt)
    proj.placement = eb.TPU
    return proj


def test_fusion_break_trips_on_large_intermediate():
    proj = _project_filter_plan(400000)
    conf = RapidsConf({})
    diags = hloaudit.audit_plan(proj, conf, infer_plan(proj, conf))
    l20 = [d for d in diags if d.code == "TPU-L020"]
    assert l20 and "FilterExec -> ProjectExec" in l20[0].message


def test_fusion_break_small_intermediate_clean():
    proj = _project_filter_plan(100)
    conf = RapidsConf({})
    assert not [d for d in hloaudit.audit_plan(
        proj, conf, infer_plan(proj, conf)) if d.code == "TPU-L020"]


# -- the speculative re-bucket repair --------------------------------------

def test_rebucket_repair_arms_when_smaller_bucket_exists():
    flt, conf = _filter_plan(1200, "1024,1048576")
    diags = lint_plan(flt, conf, infer=True)
    assert "TPU-L018" in {d.code for d in diags}
    downgrade_hazards(flt, diags, conf)
    # repaired speculatively: still on device, shrink target armed
    assert flt.rebucket_cap == 1024
    assert flt.placement == eb.TPU


def test_rebucket_repair_refuses_noop_shrink():
    flt, conf = _filter_plan(10, "1048576")
    assert hloaudit.try_rebucket_repair(flt, flt, conf) is False
    assert flt.rebucket_cap is None


# -- device shrink/bucket helpers at the edges -----------------------------

def test_bucket_for_and_floor_edges():
    bks = (1024, 8192)
    assert bucket_for(1024, bks) == 1024          # exact boundary
    assert bucket_for(1025, bks) == 8192
    assert bucket_for(10000, bks) == 16384        # over-max: pow2 up
    assert bucket_floor(8191, bks) == 1024
    assert bucket_floor(8192, bks) == 8192
    assert bucket_floor(10, bks) == 1024          # below the smallest


def test_default_bucket_tables_edges():
    from spark_rapids_tpu.columnar.device import (DEFAULT_CHAR_BUCKETS,
                                                  DEFAULT_ROW_BUCKETS)
    top = DEFAULT_ROW_BUCKETS[-1]
    assert bucket_for(top, DEFAULT_ROW_BUCKETS) == top
    assert bucket_for(top + 1, DEFAULT_ROW_BUCKETS) == top * 2
    assert bucket_for(0, DEFAULT_ROW_BUCKETS) == DEFAULT_ROW_BUCKETS[0]
    ctop = DEFAULT_CHAR_BUCKETS[-1]
    assert bucket_for(ctop, DEFAULT_CHAR_BUCKETS) == ctop
    assert bucket_floor(ctop - 1, DEFAULT_CHAR_BUCKETS) == \
        DEFAULT_CHAR_BUCKETS[-2]
    # each table stays sorted and <= 8x apart: the static L018 bound
    # (defaults never pad past padWasteMax) rests on this
    for bks in (DEFAULT_ROW_BUCKETS, DEFAULT_CHAR_BUCKETS):
        assert list(bks) == sorted(bks)
        assert all(b2 / b1 <= 8 for b1, b2 in zip(bks, bks[1:]))


def test_shrink_column_long_and_string():
    col = DeviceColumn(t.LONG, data=np.zeros(1024, np.int64),
                       validity=np.ones(1024, bool))
    out = shrink_column(col, 16)
    assert out.capacity == 16 and out.validity.shape == (16,)
    scol = DeviceColumn(t.STRING, data=np.zeros(64, np.uint8),
                        offsets=np.zeros(1025, np.int32))
    sout = shrink_column(scol, 16)
    # rows re-bucket; char data keeps its own byte bucket
    assert sout.capacity == 16 and sout.data.shape == (64,)


def test_shrink_batch_noop_and_rows_ride_along():
    col = DeviceColumn(t.LONG, data=np.zeros(1024, np.int64))
    b = DeviceBatch([col], 10, ["x"])
    assert shrink_batch(b, 2048) is b             # growing is a no-op
    small = shrink_batch(b, 16)
    assert small.capacity == 16 and small.num_rows == 10


# -- StableHLO persistence --------------------------------------------------

def test_save_hlo_dedupes_and_caps(tmp_path):
    obs = CompileObservatory.reset_for_tests()
    try:
        obs.configure(hlo_dir=str(tmp_path))
        k1, ok1 = obs.save_hlo(_CLEAN_HLO)
        k2, ok2 = obs.save_hlo(_CLEAN_HLO)
        assert ok1 and ok2 and k1 == k2 == hlo_key(_CLEAN_HLO)
        files = [f for f in os.listdir(tmp_path)
                 if f.endswith(HLO_SUFFIX)]
        assert files == [k1 + HLO_SUFFIX]
        big = "x" * (HLO_MAX_BYTES + 1)
        kb, okb = obs.save_hlo(big)
        assert not okb                     # key recorded, text not
        assert not os.path.exists(
            os.path.join(tmp_path, kb + HLO_SUFFIX))
    finally:
        CompileObservatory.reset_for_tests()


def test_cost_summary_records_only_reported_keys():
    class Fake:
        def __init__(self, ca):
            self._ca = ca

        def cost_analysis(self):
            if isinstance(self._ca, Exception):
                raise self._ca
            return self._ca

    got = cost_summary(Fake([{"bytes accessed": 5.0, "flops": 1.0,
                              "utilization": 9.0}]))
    assert got == {"bytes accessed": 5.0, "flops": 1.0}
    assert cost_summary(Fake([])) is None
    assert cost_summary(Fake(RuntimeError("no analysis"))) is None


# -- the kernel-gap report --------------------------------------------------

def _synthetic_ledgers():
    base = 8192 * 8
    compile_records = [
        {"event": "build", "exec": "HashJoinExec", "hlo_hash": "h1",
         "caps": [[8192]], "dtypes": ["int64"],
         "cost": {"bytes accessed": float(base * 8 * 13)}},
        # the same program rebuilt (eviction refault): ONE program
        {"event": "build", "exec": "HashJoinExec", "hlo_hash": "h1",
         "caps": [[8192]], "dtypes": ["int64"],
         "cost": {"bytes accessed": float(base * 8 * 13)}},
        {"event": "build", "exec": "ProjectExec", "hlo_hash": "h2",
         "caps": [[8192]], "dtypes": ["int64"],
         "cost": {"bytes accessed": float(base * 3)}},
    ]
    observe_records = [
        # the broadcast variant folds onto the HashJoinExec kernel kind
        {"event": "observe", "exec": "BroadcastHashJoinExec",
         "time_ns": 2_000_000_000, "act_bytes": base,
         "pad_waste_bytes": base // 2},
        {"event": "observe", "exec": "ProjectExec",
         "time_ns": 500_000_000, "act_bytes": base,
         "pad_waste_bytes": None},      # predates pad accounting
    ]
    return compile_records, observe_records


def test_aggregate_kernel_report_joins_and_ranks():
    compile_records, observe_records = _synthetic_ledgers()
    agg = aggregate_kernel_report(compile_records, observe_records)
    by_kind = {r["exec"]: r for r in agg["kinds"]}
    join = by_kind["HashJoinExec"]
    assert "BroadcastHashJoinExec" not in by_kind
    assert join["programs"] == 1 and join["builds"] == 2
    assert join["measured_s"] == pytest.approx(2.0)
    assert join["gap"] is not None and join["gap"] > 1.0
    assert join["projected_savings_s"] > 0.0
    # None pad bytes stayed absent: the Project row books zero
    assert by_kind["ProjectExec"]["pad_waste_bytes"] == 0
    targets = {t_["target"]: t_ for t_ in agg["targets"]}
    assert targets["fused hash build/probe"][
        "projected_savings_s"] > 0.0
    assert agg["cost_model"]["agreement_pct"] == pytest.approx(100.0)


def test_format_kernel_report_renders():
    agg = aggregate_kernel_report(*_synthetic_ledgers())
    text = format_kernel_report(agg)
    assert "kernel gap report" in text
    assert "HashJoinExec" in text
    assert "fused hash build/probe" in text


def test_run_kernel_report_cli(tmp_path, capsys):
    compile_records, observe_records = _synthetic_ledgers()
    cl = tmp_path / "compile_ledger.jsonl"
    el = tmp_path / "estimator_ledger.jsonl"
    cl.write_text("\n".join(json.dumps(r) for r in compile_records))
    el.write_text("\n".join(json.dumps(r) for r in observe_records)
                  + '\n{"torn": ')
    import io
    buf = io.StringIO()
    assert run_kernel_report(str(cl), str(tmp_path), out=buf) == 0
    assert "kernel gap report" in buf.getvalue()
    jbuf = io.StringIO()
    assert run_kernel_report(str(cl), str(el), as_json=True,
                             out=jbuf) == 0
    assert json.loads(jbuf.getvalue())["targets"]


def test_run_kernel_report_missing_ledger_errors(tmp_path):
    import io
    assert run_kernel_report(str(tmp_path / "nope.jsonl"),
                             str(tmp_path / "nope2.jsonl"),
                             out=io.StringIO()) == 2


def test_load_estimator_ledger_skips_torn_lines(tmp_path):
    el = tmp_path / "estimator_ledger.jsonl"
    el.write_text('{"event": "observe", "exec": "X"}\n{"torn": \n')
    recs = load_estimator_ledger(str(tmp_path))
    assert len(recs) == 1 and recs[0]["exec"] == "X"
