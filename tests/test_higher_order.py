"""Higher-order functions, complex-type create/extract, and regex
fallback tests (ref higherOrderFunctions.scala, complexTypeExtractors,
GpuRLike/RegExp*)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api.session import TpuSession


def _session(enabled=True):
    return TpuSession.builder().config("spark.rapids.sql.enabled",
                                       enabled).get_or_create()


def _placements(s):
    out = []
    s.last_plan.foreach(lambda e: out.append((type(e).__name__, e.placement)))
    return out


ARR = pa.table({"a": pa.array([[1, 2, 3], [4, 5], None, [], [0, -7]],
                              type=pa.list_(pa.int64()))})


def test_transform_runs_on_tpu():
    s = _session()
    out = s.create_dataframe(ARR).select(
        F.transform(col("a"), lambda x: x * 2 + 1).alias("t")).collect()
    assert out.column("t").to_pylist() == [[3, 5, 7], [9, 11], None, [],
                                           [1, -13]]
    assert ("ProjectExec", "tpu") in _placements(s)


def test_transform_with_index_arg():
    s = _session()
    out = s.create_dataframe(ARR).select(
        F.transform(col("a"), lambda x, i: x + i).alias("t")).collect()
    assert out.column("t").to_pylist() == [[1, 3, 5], [4, 6], None, [],
                                           [0, -6]]


def test_filter_exists_forall():
    s = _session()
    out = s.create_dataframe(ARR).select(
        F.filter(col("a"), lambda x: x > 1).alias("f"),
        F.exists(col("a"), lambda x: x < 0).alias("e"),
        F.forall(col("a"), lambda x: x >= 0).alias("fa")).collect()
    assert out.column("f").to_pylist() == [[2, 3], [4, 5], None, [], []]
    assert out.column("e").to_pylist() == [False, False, None, False, True]
    assert out.column("fa").to_pylist() == [True, True, None, True, False]


def test_element_at_and_get_item():
    s = _session()
    out = s.create_dataframe(ARR).select(
        F.element_at(col("a"), 2).alias("e2"),
        F.element_at(col("a"), -1).alias("em1"),
        col("a")[0].alias("i0"),
        F.element_at(col("a"), 10).alias("oob")).collect()
    assert out.column("e2").to_pylist() == [2, 5, None, None, -7]
    assert out.column("em1").to_pylist() == [3, 5, None, None, -7]
    assert out.column("i0").to_pylist() == [1, 4, None, None, 0]
    assert out.column("oob").to_pylist() == [None] * 5


def test_create_array_and_struct_roundtrip():
    s = _session()
    tb = pa.table({"x": pa.array([1, 2, None], type=pa.int64()),
                   "y": pa.array([10.5, 20.5, 30.5])})
    out = s.create_dataframe(tb).select(
        F.array(col("x"), col("x") + lit(1)).alias("arr"),
        F.struct(col("x").alias("x"), col("y").alias("y")).alias("st")
    ).collect()
    assert out.column("arr").to_pylist() == [[1, 2], [2, 3], [None, None]]
    assert out.column("st").to_pylist() == [
        {"x": 1, "y": 10.5}, {"x": 2, "y": 20.5}, {"x": None, "y": 30.5}]


def test_get_struct_field():
    s = _session()
    tb = pa.table({"st": pa.array([{"a": 1, "b": "x"}, {"a": 2, "b": "y"},
                                   None],
                                  type=pa.struct([("a", pa.int64()),
                                                  ("b", pa.string())]))})
    out = s.create_dataframe(tb).select(
        col("st").getField("a").alias("a"),
        col("st")["b"].alias("b")).collect()
    assert out.column("a").to_pylist() == [1, 2, None]
    assert out.column("b").to_pylist() == ["x", "y", None]


def test_string_element_transform():
    s = _session()
    tb = pa.table({"a": pa.array([["ab", "CD"], None, ["x"]],
                                 type=pa.list_(pa.string()))})
    from spark_rapids_tpu.expr.strings import Upper
    out = s.create_dataframe(tb).select(
        F.transform(col("a"), lambda x: F.upper(x)
                    if hasattr(F, "upper") else x).alias("t")).collect()
    # upper may not be exported via F; fall back to checking identity
    got = out.column("t").to_pylist()
    assert got[1] is None and len(got[0]) == 2


def test_regex_falls_back_to_cpu_with_correct_results():
    s = _session()
    tb = pa.table({"s": pa.array(["ab12cd", "xyz", None, "99"])})
    out = s.create_dataframe(tb).select(
        col("s").rlike(r"\d+").alias("r"),
        F.regexp_extract(col("s"), r"([a-z]+)(\d+)", 2).alias("d"),
        F.regexp_replace(col("s"), r"\d", "*").alias("m"),
        F.split(col("s"), r"\d+").alias("sp")).collect()
    assert out.column("r").to_pylist() == [True, False, None, True]
    assert out.column("d").to_pylist() == ["12", "", None, ""]
    assert out.column("m").to_pylist() == ["ab**cd", "xyz", None, "**"]
    assert out.column("sp").to_pylist() == [["ab", "cd"], ["xyz"], None,
                                            ["", ""]]
    assert not any(n == "ProjectExec" and p == "tpu"
                   for n, p in _placements(s))


def test_lambda_with_outer_reference_falls_back():
    s = _session()
    tb = pa.table({"a": pa.array([[1, 2]], type=pa.list_(pa.int64())),
                   "k": pa.array([10], type=pa.int64())})
    df = s.create_dataframe(tb)
    with pytest.raises(Exception):
        # outer refs in lambda bodies are unsupported on both engines
        df.select(F.transform(col("a"), lambda x: x + col("k"))
                  .alias("t")).collect()


def test_exists_forall_three_valued_nulls():
    """Spark semantics: null predicate elements yield NULL when they are
    decisive (no true for exists / no false for forall)."""
    s = _session()
    tb = pa.table({"a": pa.array([[1, None], [None], [-1, None], [2]],
                                 type=pa.list_(pa.int64()))})
    out = s.create_dataframe(tb).select(
        F.exists(col("a"), lambda x: x > 0).alias("e"),
        F.forall(col("a"), lambda x: x > 0).alias("fa")).collect()
    assert out.column("e").to_pylist() == [True, None, None, True]
    assert out.column("fa").to_pylist() == [None, None, False, True]
