"""tpucsan: the lock-order/shared-state static pass
(analysis/concurrency.py) and the runtime lock witness
(obs/lockwitness.py) that validates its edge relation.

Covers: lock extraction + canonical naming, direct and inter-procedural
lock-order edges, anti-vacuity for TPU-R008/R009/R010 (each rule's
fixture must trip and its corrected twin must not), allow-annotation
filtering, the repo artifact's known shape, witness edge recording /
unmodeled-edge / cycle detection / contention metrics, and a concurrent
golden-query round trip under `spark.rapids.tpu.csan.enabled`.
"""

import threading

import numpy as np
import pytest

from spark_rapids_tpu.analysis import concurrency as cc
from spark_rapids_tpu.obs import lockwitness


def _analyze(src, name="spark_rapids_tpu/fixmod.py", roots=None):
    return cc.analyze_sources({name: src}, roots=roots)


def _codes(res):
    return {d.code for d in res.diagnostics}


# ---------------------------------------------------------------------------
# lock extraction
# ---------------------------------------------------------------------------

def test_lock_extraction_kinds_and_names():
    res = _analyze(
        "import threading\n"
        "_mod_lock = threading.Lock()\n"
        "class C:\n"
        "    _cls_lock = threading.RLock()\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n")
    assert res.locks["fixmod._mod_lock"].kind == "lock"
    assert res.locks["fixmod.C._cls_lock"].kind == "rlock"
    assert res.locks["fixmod.C._cv"].kind == "condition"
    assert res.locks["fixmod.C._cls_lock"].class_level
    assert not res.locks["fixmod.C._cv"].class_level


def test_lock_extraction_skips_nonlocks_and_indirect():
    res = _analyze(
        "import threading\n"
        "_sem = threading.Semaphore(2)\n"
        "_ev = threading.Event()\n"
        "LOCK_TYPES = [type(threading.RLock())]\n"
        "_real = threading.Lock()\n")
    # Semaphore/Event are not locks; type(RLock()) is not a binding
    assert set(res.locks) == {"fixmod._real"}


def test_repo_extraction_finds_the_known_locks():
    art = cc.lock_order_artifact()
    for name, kind in (
            ("memory.admission.AdmissionController._cv", "condition"),
            ("memory.admission.AdmissionController._ilock", "lock"),
            ("api.pool.SessionPool._cv", "condition"),
            ("memory.spill.SpillCatalog._reg_lock", "rlock"),
            ("obs.metrics.MetricsRegistry._ilock", "lock"),
            ("shuffle.manager.TpuShuffleManager._lock", "lock"),
            ("obs.health._SERVER_LOCK", "lock")):
        assert art["locks"].get(name) == kind, name


# ---------------------------------------------------------------------------
# lock-order edges
# ---------------------------------------------------------------------------

def test_direct_nesting_edge():
    res = _analyze(
        "import threading\n"
        "_a = threading.Lock()\n"
        "_b = threading.Lock()\n"
        "def f():\n"
        "    with _a:\n"
        "        with _b:\n"
        "            pass\n")
    assert ("fixmod._a", "fixmod._b") in res.edges
    assert ("fixmod._b", "fixmod._a") not in res.edges


def test_interprocedural_edge_through_callee():
    res = _analyze(
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._outer = threading.Lock()\n"
        "        self._inner = threading.Lock()\n"
        "    def top(self):\n"
        "        with self._outer:\n"
        "            self.helper()\n"
        "    def helper(self):\n"
        "        with self._inner:\n"
        "            pass\n")
    assert ("fixmod.A._outer", "fixmod.A._inner") in res.edges


def test_repo_graph_models_the_metrics_edges():
    """The serving condvars publish gauges while held — those edges are
    exactly what the runtime witness replays against, so they must be
    in the static relation."""
    art = cc.lock_order_artifact()
    edges = {tuple(e) for e in art["edges"]}
    assert ("memory.admission.AdmissionController._cv",
            "obs.metrics.MetricsRegistry._ilock") in edges
    assert ("api.pool.SessionPool._cv",
            "obs.metrics.MetricsRegistry._ilock") in edges


def test_repo_graph_is_acyclic_and_roots_resolve():
    art = cc.lock_order_artifact()
    assert art["cycles"] == []
    assert len(art["roots"]) >= len(cc.THREAD_ROOTS)
    assert set(art["roots"].values()) == {r[0] for r in cc.THREAD_ROOTS}


# ---------------------------------------------------------------------------
# TPU-R008: ABBA cycles
# ---------------------------------------------------------------------------

_ABBA = (
    "import threading\n"
    "class Pair:\n"
    "    def __init__(self):\n"
    "        self._la = threading.Lock()\n"
    "        self._lb = threading.Lock()\n"
    "    def forward(self):\n"
    "        with self._la:\n"
    "            self.inner_b()\n"
    "    def backward(self):\n"
    "        with self._lb:\n"
    "            self.inner_a()\n"
    "    def inner_a(self):\n"
    "        with self._la:\n"
    "            pass\n"
    "    def inner_b(self):\n"
    "        with self._lb:\n"
    "            pass\n")


def test_abba_cycle_trips_r008():
    res = _analyze(_ABBA)
    assert "TPU-R008" in _codes(res)
    assert res.cycles, "cycle list must carry the ABBA pair"
    [d] = [d for d in res.diagnostics if d.code == "TPU-R008"]
    assert "fixmod.Pair._la" in d.message and \
        "fixmod.Pair._lb" in d.message


def test_consistent_order_is_clean():
    res = _analyze(
        "import threading\n"
        "class Pair:\n"
        "    def __init__(self):\n"
        "        self._la = threading.Lock()\n"
        "        self._lb = threading.Lock()\n"
        "    def forward(self):\n"
        "        with self._la:\n"
        "            self.inner_b()\n"
        "    def also_forward(self):\n"
        "        with self._la:\n"
        "            with self._lb:\n"
        "                pass\n"
        "    def inner_b(self):\n"
        "        with self._lb:\n"
        "            pass\n")
    assert "TPU-R008" not in _codes(res)


def test_reentrant_same_lock_is_not_a_cycle():
    """Per-instance locks collapse onto one static node: self-nesting
    (RLock reentry, sibling instances) must not report self-deadlock."""
    res = _analyze(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lk = threading.RLock()\n"
        "    def outer(self):\n"
        "        with self._lk:\n"
        "            self.inner()\n"
        "    def inner(self):\n"
        "        with self._lk:\n"
        "            pass\n")
    assert "TPU-R008" not in _codes(res)


# ---------------------------------------------------------------------------
# TPU-R009: shared state without a common guard
# ---------------------------------------------------------------------------

_R009_ROOTS = ["fixmod.root_a", "fixmod.root_b"]

_R009_BAD = (
    "import threading\n"
    "class Stats:\n"
    "    _instance = None\n"
    "    _ilock = threading.Lock()\n"
    "    def __init__(self):\n"
    "        self.tally = 0\n"
    "    @classmethod\n"
    "    def get(cls):\n"
    "        with cls._ilock:\n"
    "            if cls._instance is None:\n"
    "                cls._instance = Stats()\n"
    "            return cls._instance\n"
    "    def bump(self):\n"
    "        self.tally += 1\n"
    "def root_a():\n"
    "    Stats.get().bump()\n"
    "def root_b():\n"
    "    Stats.get().bump()\n")


def test_unguarded_multiroot_write_trips_r009():
    res = _analyze(_R009_BAD, roots=_R009_ROOTS)
    assert "TPU-R009" in _codes(res)
    [d] = [d for d in res.diagnostics if d.code == "TPU-R009"]
    assert "fixmod.Stats.tally" in d.message


def test_guarded_multiroot_write_is_clean():
    res = _analyze(_R009_BAD.replace(
        "    def bump(self):\n"
        "        self.tally += 1\n",
        "    def bump(self):\n"
        "        with self._ilock:\n"
        "            self.tally += 1\n"), roots=_R009_ROOTS)
    assert "TPU-R009" not in _codes(res)


def test_single_root_write_is_clean():
    res = _analyze(_R009_BAD, roots=["fixmod.root_a"])
    assert "TPU-R009" not in _codes(res)


def test_init_writes_do_not_count():
    """Construction is single-threaded by convention: __init__ writes
    must not feed R009 even when both roots construct instances."""
    res = _analyze(
        "import threading\n"
        "class Holder:\n"
        "    _lk = threading.Lock()\n"
        "    def __init__(self):\n"
        "        self.x = 0\n"
        "def root_a():\n"
        "    Holder()\n"
        "def root_b():\n"
        "    Holder()\n", roots=_R009_ROOTS)
    assert "TPU-R009" not in _codes(res)


def test_guard_through_caller_held_lock_is_clean():
    """The common guard may be held by the CALLER (always-held
    fixpoint), not lexically at the write."""
    res = _analyze(
        "import threading\n"
        "class Box:\n"
        "    _lk = threading.Lock()\n"
        "    _instance = None\n"
        "    def set_it(self, v):\n"
        "        self.val = v\n"
        "    def locked_set(self, v):\n"
        "        with self._lk:\n"
        "            self.set_it(v)\n"
        "def root_a():\n"
        "    Box().locked_set(1)\n"
        "def root_b():\n"
        "    Box().locked_set(2)\n", roots=_R009_ROOTS)
    assert "TPU-R009" not in _codes(res)


# ---------------------------------------------------------------------------
# TPU-R010: condvar / raw-lock misuse
# ---------------------------------------------------------------------------

def test_wait_outside_loop_trips_r010():
    res = _analyze(
        "import threading\n"
        "_cv = threading.Condition()\n"
        "_items = []\n"
        "def bad_wait():\n"
        "    with _cv:\n"
        "        if not _items:\n"
        "            _cv.wait()\n"
        "        return _items.pop()\n")
    assert "TPU-R010" in _codes(res)


def test_wait_in_predicate_loop_is_clean():
    res = _analyze(
        "import threading\n"
        "_cv = threading.Condition()\n"
        "_items = []\n"
        "def good_wait():\n"
        "    with _cv:\n"
        "        while not _items:\n"
        "            _cv.wait()\n"
        "        return _items.pop()\n")
    assert "TPU-R010" not in _codes(res)


def test_wait_for_is_exempt():
    res = _analyze(
        "import threading\n"
        "_cv = threading.Condition()\n"
        "_items = []\n"
        "def good_wait():\n"
        "    with _cv:\n"
        "        _cv.wait_for(lambda: bool(_items))\n"
        "        return _items.pop()\n")
    assert "TPU-R010" not in _codes(res)


def test_notify_without_lock_trips_r010():
    res = _analyze(
        "import threading\n"
        "_cv = threading.Condition()\n"
        "def bad_notify():\n"
        "    _cv.notify_all()\n")
    assert "TPU-R010" in _codes(res)


def test_notify_with_lock_held_is_clean():
    res = _analyze(
        "import threading\n"
        "_cv = threading.Condition()\n"
        "def good_notify():\n"
        "    with _cv:\n"
        "        _cv.notify_all()\n")
    assert "TPU-R010" not in _codes(res)


def test_acquire_without_finally_trips_r010():
    res = _analyze(
        "import threading\n"
        "_lk = threading.Lock()\n"
        "def bad_acquire():\n"
        "    _lk.acquire()\n"
        "    do_stuff()\n"
        "    _lk.release()\n"
        "def do_stuff():\n"
        "    pass\n")
    assert "TPU-R010" in _codes(res)


def test_acquire_with_finally_release_is_clean():
    res = _analyze(
        "import threading\n"
        "_lk = threading.Lock()\n"
        "def good_acquire():\n"
        "    _lk.acquire()\n"
        "    try:\n"
        "        do_stuff()\n"
        "    finally:\n"
        "        _lk.release()\n"
        "def do_stuff():\n"
        "    pass\n")
    assert "TPU-R010" not in _codes(res)


# ---------------------------------------------------------------------------
# allow annotations + rule registration
# ---------------------------------------------------------------------------

def test_allow_annotation_filters_the_finding():
    src = ("import threading\n"
           "_cv = threading.Condition()\n"
           "_items = []\n"
           "def bad_wait():\n"
           "    with _cv:\n"
           "        if not _items:\n"
           "            _cv.wait()  # tpulint: allow[TPU-R010]\n"
           "        return _items.pop()\n")
    sources = {"spark_rapids_tpu/fixmod.py": src}
    res = cc.analyze_sources(sources)
    assert "TPU-R010" in _codes(res)  # the raw pass still sees it
    assert not cc.filter_allowed(res, sources)  # ...the filter honors it


def test_rules_are_registered_in_the_catalog():
    from spark_rapids_tpu.analysis.diagnostics import RULE_CATALOG
    for code in ("TPU-R008", "TPU-R009", "TPU-R010"):
        assert code in RULE_CATALOG
        assert RULE_CATALOG[code].doc


def test_repo_lint_is_clean_of_csan_findings():
    assert cc.repo_diagnostics() == []


# ---------------------------------------------------------------------------
# runtime lock witness
# ---------------------------------------------------------------------------

class _Owner:
    pass


def _mk_witness(edges, locks=("t.A", "t.B")):
    art = {"locks": {n: "lock" for n in locks},
           "edges": [list(e) for e in edges], "cycles": []}
    w = lockwitness.LockWitness(art)
    o = _Owner()
    o.a = threading.Lock()
    o.b = threading.Lock()
    w.wrap("t.A", o, "a")
    w.wrap("t.B", o, "b")
    return w, o


def test_witness_records_modeled_edge():
    w, o = _mk_witness([("t.A", "t.B")])
    with o.a:
        with o.b:
            pass
    rep = w.report()
    assert ("t.A", "t.B") in {tuple(e) for e in rep["edges"]}
    assert rep["unmodeled"] == [] and rep["cycles"] == []
    assert rep["ok"]


def test_witness_flags_unmodeled_edge():
    w, o = _mk_witness([])  # static graph claims no nesting at all
    with o.a:
        with o.b:
            pass
    rep = w.report()
    assert ("t.A", "t.B") in {tuple(e) for e in rep["unmodeled"]}
    assert not rep["ok"]


def test_witness_accepts_transitive_static_edge():
    """Runtime sees A held while C is acquired; statically that path is
    A->B->C through a callee — the closure must explain it."""
    art = {"locks": {"t.A": "lock", "t.B": "lock", "t.C": "lock"},
           "edges": [["t.A", "t.B"], ["t.B", "t.C"]], "cycles": []}
    w = lockwitness.LockWitness(art)
    o = _Owner()
    o.a, o.c = threading.Lock(), threading.Lock()
    w.wrap("t.A", o, "a")
    w.wrap("t.C", o, "c")
    with o.a:
        with o.c:
            pass
    rep = w.report()
    assert rep["unmodeled"] == [] and rep["ok"]


def test_witness_detects_runtime_abba_cycle():
    w, o = _mk_witness([("t.A", "t.B"), ("t.B", "t.A")])
    with o.a:
        with o.b:
            pass
    with o.b:
        with o.a:
            pass
    rep = w.report()
    assert rep["cycles"] == [["t.A", "t.B"]]
    assert not rep["ok"]


def test_witness_per_thread_stacks_do_not_cross():
    """Held locks on one thread must not fabricate edges for another."""
    w, o = _mk_witness([])
    hold_a = threading.Event()
    done = threading.Event()

    def holder():
        with o.a:
            hold_a.set()
            done.wait(10)

    th = threading.Thread(target=holder, daemon=True)
    th.start()
    assert hold_a.wait(10)
    with o.b:   # thread-local stack: no (t.A, t.B) edge
        pass
    done.set()
    th.join(10)
    assert w.report()["edges"] == []


def test_witness_contention_metrics():
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    MetricsRegistry.reset_for_tests()
    try:
        w, o = _mk_witness([])
        entered = threading.Event()
        release = threading.Event()

        def holder():
            with o.a:
                entered.set()
                release.wait(10)

        th = threading.Thread(target=holder, daemon=True)
        th.start()
        assert entered.wait(10)
        blocker = threading.Thread(target=lambda: o.a.acquire(),
                                   daemon=True)
        blocker.start()
        # let the blocker actually contend before releasing
        import time
        time.sleep(0.1)
        release.set()
        blocker.join(10)
        o.a.release()  # the blocker's acquire
        reg = MetricsRegistry.get()
        cont = reg.counter("tpu_lock_contention_total",
                           labelnames=("lock",)).total()
        assert cont >= 1
        hist = reg.histogram("tpu_lock_wait_seconds",
                             labelnames=("lock",))
        wait_count, _ = hist.value(lock="t.A")
        assert wait_count >= 1
    finally:
        MetricsRegistry.reset_for_tests()


def test_witness_uninstall_restores_originals():
    w, o = _mk_witness([])
    assert isinstance(o.a, lockwitness._LockProxy)
    w.uninstall()
    assert isinstance(o.a, type(threading.Lock()))
    assert isinstance(o.b, type(threading.Lock()))


# ---------------------------------------------------------------------------
# witness round trip under a concurrent golden query
# ---------------------------------------------------------------------------

def test_witness_round_trip_under_concurrent_queries():
    """spark.rapids.tpu.csan.enabled wraps the engine locks; a small
    concurrent mix must produce observed nesting with ZERO unmodeled
    edges and ZERO runtime cycles — the static relation explains every
    acquisition chain execution actually performed."""
    import concurrent.futures as cf

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.memory.admission import AdmissionController
    from spark_rapids_tpu.obs.metrics import MetricsRegistry

    AdmissionController.reset_for_tests()
    lockwitness.reset_for_tests()
    try:
        witness = lockwitness.install()
        pool = SessionPool(2, {
            "spark.rapids.sql.enabled": True,
            "spark.rapids.tpu.csan.enabled": True,
            "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes":
                str(64 << 20),
            "spark.rapids.tpu.serve.admissionTimeoutMs": "30000",
        })
        witness.refresh()
        n = 600
        k = (np.arange(n) % 5).astype(np.int64)
        v = np.arange(n, dtype=np.int64)

        def work(s):
            out = (s.create_dataframe({"k": k, "v": v})
                   .group_by(col("k"))
                   .agg(F.sum(col("v")).alias("sv")).collect())
            assert out.num_rows == 5

        with cf.ThreadPoolExecutor(max_workers=4) as ex:
            futs = [ex.submit(pool.run, work) for _ in range(8)]
            for f in futs:
                f.result()
        pool.drain(timeout=30)
        pool.close()

        rep = witness.report()
        assert rep["n_wrapped"] >= 6
        assert rep["edges"], "vacuous: no nesting observed at all"
        assert rep["unmodeled"] == [], rep["unmodeled"]
        assert rep["cycles"] == [], rep["cycles"]
        assert rep["ok"]
        fams = {f.name for f in MetricsRegistry.get().families()}
        assert "tpu_lock_contention_total" in fams
        assert "tpu_lock_wait_seconds" in fams
    finally:
        lockwitness.reset_for_tests()
        AdmissionController.reset_for_tests()


def test_csan_disabled_leaves_locks_raw():
    """Without the conf, maybe_register is a no-op and pool condvars
    stay plain threading primitives — zero overhead on the default
    path."""
    from spark_rapids_tpu.api.pool import SessionPool

    lockwitness.reset_for_tests()
    pool = SessionPool(1, {"spark.rapids.sql.enabled": True})
    try:
        assert not isinstance(pool._cv, lockwitness._LockProxy)
        assert lockwitness.get_witness() is None
    finally:
        pool.close()
