"""Multi-tenant serving: byte-weighted admission control, the session
pool, and the rewritten TpuSemaphore.

Everything here runs in the shared tier-1 process, so each test restores
the process-global singletons it touches (AdmissionController,
TpuSemaphore._instance) — the fixtures below do that unconditionally.
"""

import threading
import time
import warnings

import numpy as np
import pytest

from spark_rapids_tpu.memory.admission import (AdmissionController,
                                               AdmissionTimeout)
from spark_rapids_tpu.memory.semaphore import TpuSemaphore


@pytest.fixture
def fresh_admission():
    prev_sem = TpuSemaphore._instance
    AdmissionController.reset_for_tests()
    yield
    AdmissionController.reset_for_tests()
    TpuSemaphore._instance = prev_sem


def run_with_watchdog(fn, timeout_s=120.0):
    """Deadlock canary for the concurrency stress tests: run ``fn`` on
    a daemon thread and, if it has not finished after ``timeout_s``,
    dump EVERY live thread's stack and fail — a wedged lock interleaving
    must produce a readable diagnosis, not hang CI until the job
    timeout."""
    import sys
    import traceback

    outcome = {}

    def body():
        try:
            fn()
            outcome["ok"] = True
        except BaseException as ex:  # re-raised on the test thread
            outcome["exc"] = ex

    th = threading.Thread(target=body, name="watchdog-body", daemon=True)
    th.start()
    th.join(timeout_s)
    if th.is_alive():
        frames = sys._current_frames()
        dump = []
        for t in threading.enumerate():
            fr = frames.get(t.ident)
            if fr is None:
                continue
            dump.append(f"--- thread {t.name!r} "
                        f"(daemon={t.daemon}, alive={t.is_alive()}) ---")
            dump.extend(line.rstrip()
                        for line in traceback.format_stack(fr))
        pytest.fail(
            f"watchdog expired after {timeout_s}s — probable deadlock; "
            f"all thread stacks:\n" + "\n".join(dump), pytrace=False)
    if "exc" in outcome:
        raise outcome["exc"]


# ---------------------------------------------------------------------------
# AdmissionController unit behavior
# ---------------------------------------------------------------------------

def test_admission_byte_bookkeeping(fresh_admission):
    ctrl = AdmissionController.configure(1000, 5.0)
    t1 = ctrl.admit(600)
    t2 = ctrl.admit(300)
    assert ctrl.bytes_in_flight == 900
    assert ctrl.max_in_flight_seen == 900
    ctrl.release(t1)
    ctrl.release(t1)  # idempotent: a double release must not underflow
    assert ctrl.bytes_in_flight == 300
    ctrl.release(t2)
    assert ctrl.bytes_in_flight == 0
    assert ctrl.queue_depth == 0


def test_admission_timeout_when_budget_full(fresh_admission):
    ctrl = AdmissionController.configure(1000, 5.0)
    t1 = ctrl.admit(900)
    t0 = time.monotonic()
    with pytest.raises(AdmissionTimeout):
        ctrl.admit(200, timeout_s=0.2)
    assert time.monotonic() - t0 >= 0.2
    assert ctrl.queue_depth == 0  # the timed-out waiter left the queue
    ctrl.release(t1)


def test_oversized_ticket_queues_then_completes(fresh_admission):
    """A ticket that does not fit RIGHT NOW (but fits the budget) must
    wait its turn and then run — never error, never deadlock."""
    ctrl = AdmissionController.configure(1000, 30.0)
    t1 = ctrl.admit(900)
    admitted_at = []

    def waiter():
        t2 = ctrl.admit(800, timeout_s=10)
        admitted_at.append(ctrl.bytes_in_flight)
        ctrl.release(t2)

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.15)
    assert ctrl.queue_depth == 1 and not admitted_at  # genuinely queued
    ctrl.release(t1)
    th.join(5)
    assert not th.is_alive()
    assert admitted_at == [800]
    assert ctrl.max_in_flight_seen <= 1000


def test_admission_is_fifo(fresh_admission):
    """Strict arrival order: a small ticket that WOULD fit must not
    overtake a queued larger one (head-of-line blocking is the
    starvation guarantee, not a bug)."""
    ctrl = AdmissionController.configure(1000, 30.0)
    t1 = ctrl.admit(600)
    order = []
    ready = threading.Event()

    def big():
        t = ctrl.admit(500, timeout_s=10)  # 600+500 > 1000: waits
        order.append("big")
        time.sleep(0.05)
        ctrl.release(t)

    def small():
        ready.wait(5)
        t = ctrl.admit(10, timeout_s=10)   # fits, but behind big
        order.append("small")
        ctrl.release(t)

    th_big = threading.Thread(target=big)
    th_small = threading.Thread(target=small)
    th_big.start()
    time.sleep(0.1)   # big is queued first
    th_small.start()
    ready.set()
    time.sleep(0.2)
    assert order == []  # small did NOT jump the queue
    ctrl.release(t1)
    th_big.join(5)
    th_small.join(5)
    assert order == ["big", "small"]


def test_configure_unset_budget_clears_controller(fresh_admission):
    AdmissionController.configure(1000, 5.0)
    assert AdmissionController.get() is not None
    AdmissionController.configure(None, 5.0)
    assert AdmissionController.get() is None


def test_configure_same_values_keeps_in_flight_state(fresh_admission):
    """Pooled sessions re-run plugin init with identical conf; the
    controller must keep its in-flight accounting across that."""
    ctrl = AdmissionController.configure(1000, 5.0)
    t = ctrl.admit(400)
    again = AdmissionController.configure(1000, 5.0)
    assert again is ctrl
    assert again.bytes_in_flight == 400
    ctrl.release(t)


# ---------------------------------------------------------------------------
# cooperative cancellation at the admission queue (obs/progress.py)
# ---------------------------------------------------------------------------

@pytest.fixture
def fresh_progress():
    from spark_rapids_tpu.obs import progress as prog
    prog.ProgressTracker.reset_for_tests()
    prog.bind_to_thread(None)
    yield prog.ProgressTracker.get()
    prog.bind_to_thread(None)
    prog.ProgressTracker.reset_for_tests()


def _wait_for(pred, timeout_s=5.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    pytest.fail(f"{what} not reached within {timeout_s}s")


def _admit_bound(ctrl, qid, nbytes, outcome, order):
    """Waiter thread body: register ``qid`` with the ProgressTracker and
    bind its cancel token to this thread — the queue-wait checkpoint in
    ``admit()`` finds the token via ``prog.current_token()``, exactly as
    the session path does."""
    from spark_rapids_tpu.obs import progress as prog
    tracker = prog.ProgressTracker.get()
    h = tracker.begin_query(qid, tenant="cancel-edge")
    prog.bind_to_thread(h)
    try:
        t = ctrl.admit(nbytes, label=qid, timeout_s=10)
        order.append(qid)
        outcome[qid] = ("admitted", t)
    except BaseException as ex:
        outcome[qid] = ("raised", ex)
    finally:
        prog.bind_to_thread(None)
        err = outcome[qid][1] if outcome[qid][0] == "raised" else None
        tracker.end_query(h, error=err)


def test_cancel_queue_head_wakes_next_waiter(fresh_admission, fresh_progress):
    """Cancelling the ticket at the HEAD of the admission queue must
    unwind it as a typed queue-wait cancel AND wake the waiter behind
    it — which then admits without anything else releasing."""
    from spark_rapids_tpu.obs.progress import (ProgressTracker,
                                               TpuQueryCancelled)

    def body():
        ctrl = AdmissionController.configure(1000, 30.0)
        t1 = ctrl.admit(900)
        outcome, order = {}, []
        th_a = threading.Thread(
            target=_admit_bound, args=(ctrl, "qa", 500, outcome, order))
        th_a.start()
        _wait_for(lambda: ctrl.queue_depth == 1, what="qa queued")
        th_b = threading.Thread(
            target=_admit_bound, args=(ctrl, "qb", 50, outcome, order))
        th_b.start()
        _wait_for(lambda: ctrl.queue_depth == 2, what="qb queued behind qa")
        assert not order  # qb (which fits) did NOT overtake the head

        assert ProgressTracker.get().cancel("qa", tenant="cancel-edge")
        th_a.join(5)
        assert not th_a.is_alive()
        kind, err = outcome["qa"]
        assert kind == "raised" and isinstance(err, TpuQueryCancelled)
        assert err.checkpoint == "queue-wait" and err.cause == "client"

        # the cancel itself promoted qb to head and woke it: 900+50 fits
        th_b.join(5)
        assert not th_b.is_alive()
        assert outcome["qb"][0] == "admitted" and order == ["qb"]

        ctrl.release(outcome["qb"][1])
        ctrl.release(t1)
        assert ctrl.bytes_in_flight == 0 and ctrl.queue_depth == 0

    run_with_watchdog(body, 60.0)


def test_cancel_mid_queue_preserves_survivor_fifo(fresh_admission,
                                                  fresh_progress):
    """Cancelling a MIDDLE ticket removes only that ticket; the
    survivors keep their original arrival order (head-of-line FIFO, no
    re-sort, no overtake by the now-smaller tail)."""
    from spark_rapids_tpu.obs.progress import (ProgressTracker,
                                               TpuQueryCancelled)

    def body():
        ctrl = AdmissionController.configure(1000, 30.0)
        t1 = ctrl.admit(900)
        outcome, order = {}, []
        threads = {}
        for i, (qid, nb) in enumerate(
                (("qa", 600), ("qb", 500), ("qc", 600))):
            th = threading.Thread(
                target=_admit_bound, args=(ctrl, qid, nb, outcome, order))
            th.start()
            threads[qid] = th
            _wait_for(lambda d=i + 1: ctrl.queue_depth == d,
                      what=f"{qid} queued")

        assert ProgressTracker.get().cancel("qb", tenant="cancel-edge")
        threads["qb"].join(5)
        assert not threads["qb"].is_alive()
        kind, err = outcome["qb"]
        assert kind == "raised" and isinstance(err, TpuQueryCancelled)
        assert err.checkpoint == "queue-wait"
        assert ctrl.queue_depth == 2  # the survivors are still queued
        assert not order              # ... and still blocked behind t1

        ctrl.release(t1)
        # qa (the original head) admits; qc (600 more) must keep waiting
        _wait_for(lambda: "qa" in outcome, what="qa admitted")
        assert outcome["qa"][0] == "admitted"
        assert ctrl.queue_depth == 1 and "qc" not in outcome

        ctrl.release(outcome["qa"][1])
        threads["qc"].join(5)
        assert not threads["qc"].is_alive()
        assert outcome["qc"][0] == "admitted"
        assert order == ["qa", "qc"]  # survivor FIFO preserved end-to-end

        ctrl.release(outcome["qc"][1])
        assert ctrl.bytes_in_flight == 0 and ctrl.queue_depth == 0

    run_with_watchdog(body, 60.0)


def test_cancel_after_admit_releases_ticket_exactly_once(fresh_admission,
                                                         fresh_progress):
    """A cancel that lands in the window between admission and the first
    partition raises at the next checkpoint; the unwind releases the
    ticket exactly once (and a second release is a no-op, not an
    underflow)."""
    from spark_rapids_tpu.obs import progress as prog
    from spark_rapids_tpu.obs.progress import (ProgressTracker,
                                               TpuQueryCancelled)
    ctrl = AdmissionController.configure(1000, 5.0)
    tracker = ProgressTracker.get()
    h = tracker.begin_query("qz", tenant="cancel-edge")
    prog.bind_to_thread(h)
    try:
        ticket = ctrl.admit(500, label="qz")
        assert ctrl.bytes_in_flight == 500
        assert tracker.cancel("qz", tenant="cancel-edge")
        with pytest.raises(TpuQueryCancelled) as ei:
            h.token.check(checkpoint="partition", operator="LocalScanExec")
        assert ei.value.checkpoint == "partition"
        assert ei.value.cause == "client"
        ctrl.release(ticket)              # the unwind path's release
        assert ctrl.bytes_in_flight == 0
        ctrl.release(ticket)              # double release must be a no-op
        assert ctrl.bytes_in_flight == 0
        assert ctrl.queue_depth == 0
        assert ctrl.max_in_flight_seen == 500
    finally:
        prog.bind_to_thread(None)
        tracker.end_query(h, error=None)


# ---------------------------------------------------------------------------
# session-path admission (the tmsan bound as the ticket)
# ---------------------------------------------------------------------------

def _agg_query(session, offset: int = 0, n: int = 400):
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    df = session.create_dataframe({
        "k": (np.arange(n) % 7).astype(np.int64),
        "v": np.arange(n, dtype=np.float64) + offset,
    })
    return (df.group_by(col("k"))
            .agg(F.sum(col("v")).alias("sv"))
            .collect())


def test_budget_one_byte_times_out(fresh_admission):
    """Anti-vacuity for the whole admission path: with a 1-byte budget
    every real plan's static bound is oversized and unrepairable below
    budget, so the query must surface a typed AdmissionTimeout — not an
    OOM, not a hang, not a silent pass."""
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes": "1",
        "spark.rapids.tpu.serve.admissionTimeoutMs": "300",
    })
    t0 = time.monotonic()
    with pytest.raises(AdmissionTimeout):
        _agg_query(s)
    assert time.monotonic() - t0 < 30  # timed out, did not hang
    ctrl = AdmissionController.get()
    assert ctrl is not None and ctrl.bytes_in_flight == 0


def test_admission_released_after_query(fresh_admission):
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes":
            str(1 << 30),
    })
    out = _agg_query(s)
    assert out.num_rows == 7
    ctrl = AdmissionController.get()
    assert ctrl is not None
    assert ctrl.bytes_in_flight == 0 and ctrl.queue_depth == 0
    assert ctrl.max_in_flight_seen > 0  # a real nonzero ticket flowed


# ---------------------------------------------------------------------------
# 8-thread mixed-query stress over the pool
# ---------------------------------------------------------------------------

def test_eight_thread_mixed_query_stress(fresh_admission):
    """Eight client threads over a 4-session pool: per-thread exact
    results, zero dirty ledgers, admitted bytes never past the budget,
    and balanced admission books."""
    import concurrent.futures as cf

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.obs.metrics import registry

    budget = 256 << 20
    pool = SessionPool(4, {
        "spark.rapids.sql.enabled": True,
        "spark.rapids.tpu.memsan.enabled": True,
        "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes": str(budget),
        "spark.rapids.tpu.serve.admissionTimeoutMs": "60000",
    })
    reg = registry()
    names = ("tpu_admission_admitted_total", "tpu_queries_completed_total",
             "tpu_queries_failed_total", "tpu_memsan_dirty_ledgers_total",
             "tpu_admission_timeouts_total")

    def cval(nm):
        # admission counters are tenant-labeled; total() is the
        # label-blind fleet-wide read
        if nm.startswith("tpu_admission_"):
            return reg.counter(nm, labelnames=("tenant",)).total()
        return reg.counter(nm).value()

    base = {nm: cval(nm) for nm in names}
    n = 1200
    k = (np.arange(n) % 7).astype(np.int64)

    def agg_worker(i):
        v = np.arange(n, dtype=np.float64) + i * 1000

        def work(s):
            from collections import defaultdict
            out = (s.create_dataframe({"k": k, "v": v})
                   .group_by(col("k"))
                   .agg(F.sum(col("v")).alias("sv")).collect())
            want = defaultdict(float)
            for kk, vv in zip(k, v):
                want[int(kk)] += vv
            got = dict(zip(out.column("k").to_pylist(),
                           out.column("sv").to_pylist()))
            assert got == pytest.approx(dict(want)), f"thread {i}"
        pool.run(work)

    def sort_worker(i):
        v = np.random.default_rng(i).permutation(n).astype(np.int64)

        def work(s):
            from spark_rapids_tpu.api.column import col as c
            out = (s.create_dataframe({"v": v})
                   .sort(c("v")).collect())
            assert out.column("v").to_pylist() == sorted(v.tolist()), \
                f"thread {i}"
        pool.run(work)

    def stress():
        jobs = [(agg_worker if i % 2 == 0 else sort_worker, i)
                for i in range(16)]
        with cf.ThreadPoolExecutor(max_workers=8) as ex:
            futs = [ex.submit(fn, i) for fn, i in jobs]
            for f in futs:
                f.result()  # re-raise any worker assertion
        pool.drain(timeout=30)
        pool.close()

    # watchdog: a wedged admission/pool/metrics interleaving dumps
    # all thread stacks instead of hanging the suite
    run_with_watchdog(stress, timeout_s=300.0)

    delta = {nm: cval(nm) - base[nm] for nm in names}
    assert delta["tpu_memsan_dirty_ledgers_total"] == 0
    assert delta["tpu_admission_timeouts_total"] == 0
    assert delta["tpu_admission_admitted_total"] == 16
    assert delta["tpu_admission_admitted_total"] == \
        delta["tpu_queries_completed_total"] + \
        delta["tpu_queries_failed_total"]
    ctrl = AdmissionController.get()
    assert ctrl is not None
    assert 0 < ctrl.max_in_flight_seen <= budget
    assert ctrl.bytes_in_flight == 0 and ctrl.queue_depth == 0
    # pooled sessions book admission under their pool-session tenant
    fam = reg.counter("tpu_admission_admitted_total",
                      labelnames=("tenant",))
    assert any(lbl["tenant"].startswith("pool-")
               for lbl, _ in fam.series())


def test_pool_drain_under_watchdog(fresh_admission):
    """drain() blocks until every borrowed session is returned, then
    returns promptly — run under the deadlock canary so a broken
    borrow/return/notify interleaving diagnoses itself."""
    from spark_rapids_tpu.api.pool import SessionPool

    pool = SessionPool(2, {"spark.rapids.sql.enabled": True})
    release = threading.Event()
    borrowed = threading.Event()

    def hold():
        with pool.session():
            borrowed.set()
            assert release.wait(30)

    def scenario():
        th = threading.Thread(target=hold, daemon=True)
        th.start()
        assert borrowed.wait(30)
        # a borrow is outstanding: drain must NOT complete yet
        with pytest.raises(TimeoutError):
            pool.drain(timeout=0.2)
        release.set()
        th.join(30)
        pool.drain(timeout=30)  # raises TimeoutError if it wedges
        pool.close()

    run_with_watchdog(scenario, timeout_s=120.0)


def test_pool_binds_active_session_per_thread(fresh_admission):
    from spark_rapids_tpu.api.pool import SessionPool
    from spark_rapids_tpu.api.session import TpuSession

    pool = SessionPool(2, {"spark.rapids.sql.enabled": True})
    seen = []

    def work(s):
        assert TpuSession.active() is s
        seen.append(s)
    pool.run(work)
    pool.run(work)
    pool.close()
    assert len(seen) == 2


# ---------------------------------------------------------------------------
# health: sustained admission backlog degrades
# ---------------------------------------------------------------------------

def test_health_degrades_on_sustained_deep_queue():
    from spark_rapids_tpu.obs.health import DEGRADED, OK, HealthMonitor
    from spark_rapids_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    mon = HealthMonitor(reg)
    depth = reg.gauge("tpu_admission_queue_depth", "d")
    depth.set(HealthMonitor._QUEUE_DEEP)
    # one deep snapshot is burst absorption, not an alert
    snap = mon.snapshot()
    assert snap["components"]["admission"]["status"] == OK
    # deep for a SECOND consecutive snapshot -> degraded
    snap = mon.snapshot()
    assert snap["components"]["admission"]["status"] == DEGRADED
    assert snap["status"] == DEGRADED
    depth.set(0)
    assert mon.snapshot()["components"]["admission"]["status"] == OK


def test_health_degrades_on_admission_timeouts():
    from spark_rapids_tpu.obs.health import DEGRADED, HealthMonitor
    from spark_rapids_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    mon = HealthMonitor(reg)
    assert mon.snapshot()["status"] == "ok"
    reg.counter("tpu_admission_timeouts_total", "d").inc()
    snap = mon.snapshot()
    assert snap["components"]["admission"]["status"] == DEGRADED


# ---------------------------------------------------------------------------
# history: serve fingerprint drift detection
# ---------------------------------------------------------------------------

def test_serve_fingerprint_drift_detection():
    from spark_rapids_tpu.obs.history import (deterministic_drift,
                                              diff_fingerprints)
    base = {
        "sql_id": 100_000, "description": "serve_mix",
        "serve_counters": {"admitted": 52, "repaired": 0,
                           "timeouts": 0, "completed": 52, "failed": 0},
        "serve_p50_ms": 2000.0, "serve_p99_ms": 3000.0,
    }
    same = dict(base)
    assert diff_fingerprints(base, same, wall_threshold_pct=50) == []
    moved = dict(base, serve_counters=dict(base["serve_counters"],
                                           timeouts=2, completed=50))
    drifts = diff_fingerprints(base, moved)
    kinds = [d.kind for d in drifts]
    assert "serve_counter_drift" in kinds
    assert deterministic_drift(drifts)
    slower = dict(base, serve_p99_ms=9000.0)
    drifts = diff_fingerprints(base, slower, wall_threshold_pct=50)
    assert [d.kind for d in drifts] == ["serve_latency_regression"]
    assert not deterministic_drift(drifts)  # timing, never gates CI
    # a run recorded before the serve fields existed never false-trips
    legacy = {"sql_id": 100_000, "description": "serve_mix"}
    assert diff_fingerprints(legacy, base, wall_threshold_pct=50) == []


# ---------------------------------------------------------------------------
# TpuSemaphore seed fixes
# ---------------------------------------------------------------------------

def test_semaphore_get_before_init_warns_and_reads_config(
        fresh_admission):
    """The seed fabricated max_concurrent=1 silently — every task on
    this path serialized.  get() must now warn and honor the configured
    default width (spark.rapids.sql.concurrentGpuTasks = 2)."""
    TpuSemaphore._instance = None
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        sem = TpuSemaphore.get()
    assert any(issubclass(w.category, RuntimeWarning) for w in caught)
    assert sem.max_concurrent == 2


def test_semaphore_double_release_does_not_inflate_permits(
        fresh_admission):
    sem = TpuSemaphore(1)
    assert sem.acquire_if_necessary(1)
    sem.release_if_necessary(1)
    sem.release_if_necessary(1)  # stray: must be a no-op
    sem.release_if_necessary(99)  # never-held task: also a no-op
    assert sem.acquire_if_necessary(2)
    # if the strays inflated the permit count past max_concurrent=1,
    # this third task would squeeze in alongside task 2
    assert not sem.acquire_if_necessary(3, timeout=0.05)
    sem.release_if_necessary(2)
    assert sem.acquire_if_necessary(3, timeout=1.0)
    sem.release_if_necessary(3)


# ---------------------------------------------------------------------------
# per-tenant admission accounting (PR 11)
# ---------------------------------------------------------------------------

def test_admission_counters_carry_tenant_label(fresh_admission):
    from spark_rapids_tpu.obs.metrics import registry

    ctrl = AdmissionController.configure(1000, 5.0)
    reg = registry()
    fam = reg.counter("tpu_admission_admitted_total",
                      labelnames=("tenant",))
    base_a = fam.value(tenant="tenant-a")
    base_b = fam.value(tenant="tenant-b")
    ta = ctrl.admit(300, tenant="tenant-a")
    tb = ctrl.admit(200, tenant="tenant-b")
    assert fam.value(tenant="tenant-a") - base_a == 1
    assert fam.value(tenant="tenant-b") - base_b == 1
    bif = reg.gauge("tpu_admission_bytes_in_flight",
                    labelnames=("tenant",))
    assert bif.value(tenant="tenant-a") == 300
    assert bif.value(tenant="tenant-b") == 200
    ctrl.release(ta)
    ctrl.release(tb)
    # drained tenants publish a final 0 (the series stays, at 0)
    assert bif.value(tenant="tenant-a") == 0
    assert bif.value(tenant="tenant-b") == 0
    assert ctrl.bytes_in_flight == 0


def test_admission_default_tenant_when_unset(fresh_admission):
    from spark_rapids_tpu.obs.metrics import registry

    ctrl = AdmissionController.configure(1000, 5.0)
    fam = registry().counter("tpu_admission_admitted_total",
                             labelnames=("tenant",))
    base = fam.value(tenant="default")
    t = ctrl.admit(10)          # no tenant given
    t2 = ctrl.admit(10, tenant="")  # empty string normalizes too
    assert fam.value(tenant="default") - base == 2
    ctrl.release(t)
    ctrl.release(t2)


def test_tenant_label_cardinality_cap(fresh_admission):
    """A runaway tenant id must collapse into the registry's single
    overflow series, never grow the family without bound."""
    from spark_rapids_tpu.obs.metrics import (DEFAULT_MAX_SERIES,
                                              OVERFLOW_LABEL,
                                              MetricsRegistry)

    MetricsRegistry.reset_for_tests()
    ctrl = AdmissionController.configure(10**9, 5.0)
    n_tenants = DEFAULT_MAX_SERIES + 16
    for i in range(n_tenants):
        t = ctrl.admit(1, tenant=f"hostile-{i}")
        ctrl.release(t)
    from spark_rapids_tpu.obs.metrics import registry
    fam = registry().counter("tpu_admission_admitted_total",
                             labelnames=("tenant",))
    assert fam.overflowed > 0
    series = fam.series()
    assert len(series) <= DEFAULT_MAX_SERIES + 1  # cap + overflow
    overflow = [c for lbl, c in series
                if lbl["tenant"] == OVERFLOW_LABEL]
    assert overflow and overflow[0].value >= 16
    assert fam.total() == n_tenants  # nothing dropped, only collapsed
    MetricsRegistry.reset_for_tests()


# ---------------------------------------------------------------------------
# ticket lifetime across an exchange-boundary re-plan (PR 11)
# ---------------------------------------------------------------------------

def test_reprice_mutates_ticket_and_releases_once(fresh_admission):
    """reprice() must keep the release-once invariant: the books
    balance to zero after exactly one release, no matter how many
    times the re-planner re-priced the live ticket."""
    ctrl = AdmissionController.configure(1000, 5.0)
    t = ctrl.admit(400, tenant="t0")
    assert ctrl.reprice(t, 700) == 300
    assert t.nbytes == 700 and ctrl.bytes_in_flight == 700
    assert ctrl.reprice(t, 700) == 0   # no-op at the same price
    assert ctrl.reprice(t, 250) == -450  # shrink is truthful too
    assert ctrl.bytes_in_flight == 250
    ctrl.release(t)
    ctrl.release(t)  # double release stays idempotent after reprice
    assert ctrl.bytes_in_flight == 0
    assert ctrl.reprice(t, 900) == 0  # released ticket: dead, no books
    assert ctrl.bytes_in_flight == 0


def test_reprice_above_budget_never_blocks(fresh_admission):
    """A mid-flight bound that overshoots the budget books honestly
    (future admits queue) instead of stalling the running query."""
    ctrl = AdmissionController.configure(1000, 5.0)
    t = ctrl.admit(600)
    assert ctrl.reprice(t, 1500) == 900
    assert ctrl.bytes_in_flight == 1500  # truthful, over budget
    with pytest.raises(AdmissionTimeout):
        ctrl.admit(10, timeout_s=0.2)  # correctly held back
    ctrl.release(t)
    assert ctrl.bytes_in_flight == 0


def test_reprice_shrink_unblocks_queued_waiter(fresh_admission):
    ctrl = AdmissionController.configure(1000, 30.0)
    t1 = ctrl.admit(900)
    admitted = []

    def waiter():
        t2 = ctrl.admit(500, timeout_s=10)
        admitted.append(ctrl.bytes_in_flight)
        ctrl.release(t2)

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.15)
    assert ctrl.queue_depth == 1 and not admitted
    ctrl.reprice(t1, 300)  # the re-planner sharpened the bound
    th.join(5)
    assert not th.is_alive()
    assert admitted == [800]  # 300 + 500
    ctrl.release(t1)
    assert ctrl.bytes_in_flight == 0


def test_replan_reprices_and_releases_once_end_to_end(
        fresh_admission, tmp_path, monkeypatch):
    """Satellite: an exchange-boundary strategy switch must re-price
    the live admission ticket and the books must balance to zero after
    the query — exactly like the SpeculativeSizingMiss retry path."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.obs.estimator import EstimatorLedger
    from spark_rapids_tpu.obs.metrics import registry
    from spark_rapids_tpu.plan import cost

    EstimatorLedger.reset_for_tests()
    orig = cost._static_rows

    def skewed(node, child_rows):
        r = orig(node, child_rows)
        if type(node).__name__ == "ShuffleExchangeExec":
            return r / 100.0  # injected 100x row misestimate
        return r

    monkeypatch.setattr(cost, "_static_rows", skewed)
    reg = registry()
    repriced = reg.counter("tpu_admission_repriced_total",
                           labelnames=("tenant",))
    replans = reg.counter("tpu_replan_total",
                          labelnames=("decision", "cause"))
    base_rp = repriced.total()
    base_sw = replans.value(decision="strategy_switch",
                            cause="row_misestimate")
    s = TpuSession({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.tpu.regress.historyDir": str(tmp_path),
        # predictions (the misestimate baseline) are flight-recorder
        # state, so the re-planner needs tracing on
        "spark.rapids.tpu.trace.enabled": True,
        "spark.rapids.tpu.feedback.enabled": True,
        "spark.rapids.tpu.singleChipFuse": "off",
        "spark.rapids.sql.autoBroadcastJoinThreshold": "0",
        "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes":
            str(1 << 30),
    })
    n = 2000
    left = s.create_dataframe(
        {"k": [i % 50 for i in range(n)], "v": list(range(n))},
        num_partitions=4)
    right = s.create_dataframe(
        {"k": list(range(50)), "w": [i * 10 for i in range(50)]},
        num_partitions=4)
    out = left.join(right, on="k").collect()
    assert out.num_rows == n
    # the misestimate provably re-planned and re-priced ...
    assert replans.value(decision="strategy_switch",
                         cause="row_misestimate") - base_sw >= 1
    assert repriced.total() - base_rp >= 1
    # ... and the repriced ticket still released exactly once
    ctrl = AdmissionController.get()
    assert ctrl is not None
    assert ctrl.bytes_in_flight == 0 and ctrl.queue_depth == 0
    EstimatorLedger.reset_for_tests()


def test_semaphore_reentrant_across_threads_same_task(fresh_admission):
    """Two threads sharing one task id must both hold without consuming
    two permits (the seed's check-then-acquire race double-acquired)."""
    sem = TpuSemaphore(1)
    results = []

    def worker():
        results.append(sem.acquire_if_necessary(7, timeout=2.0))

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5)
    assert results == [True, True]
    sem.release_if_necessary(7)  # depth 2 -> 1: still held
    assert sem.holders == 1
    sem.release_if_necessary(7)
    assert sem.holders == 0
    assert sem.acquire_if_necessary(8, timeout=1.0)
    sem.release_if_necessary(8)
