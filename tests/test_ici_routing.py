"""ICI transport routing: session queries run the fused mesh aggregate
when spark.rapids.shuffle.transport=ici and multiple chips exist."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _session(transport="ici"):
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", True)
            .config("spark.rapids.shuffle.transport", transport)
            .get_or_create())


def _names(s):
    out = []
    s.last_plan.foreach(lambda e: out.append(type(e).__name__))
    return out


def test_ici_aggregate_routed_and_correct():
    s = _session()
    rng = np.random.default_rng(0)
    n = 5000
    tb = pa.table({
        "k": pa.array(rng.integers(0, 64, n).astype(np.int64)),
        "v": pa.array(rng.integers(-500, 500, n).astype(np.int64)),
        "f": pa.array(rng.random(n)),
    })
    df = s.create_dataframe(tb, num_partitions=4)
    got = (df.filter(col("v") > -250).group_by(col("k"))
           .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("c"))
           .collect().sort_by("k"))
    assert "IciAggregateExec" in _names(s), _names(s)
    assert "ShuffleExchangeExec" not in _names(s)
    import pyarrow.compute as pc
    flt = tb.filter(pc.greater(tb.column("v"), -250))
    want = pa.TableGroupBy(flt, ["k"], use_threads=False).aggregate(
        [("v", "sum"), ("k", "count")]).sort_by("k")
    assert got.column("k").to_pylist() == want.column("k").to_pylist()
    assert got.column("sv").to_pylist() == want.column("v_sum").to_pylist()
    assert got.column("c").to_pylist() == want.column("k_count").to_pylist()


def test_ici_aggregate_with_string_keys():
    s = _session()
    rng = np.random.default_rng(1)
    n = 1200
    keys = [f"key_{int(i)}" for i in rng.integers(0, 40, n)]
    tb = pa.table({"k": pa.array(keys),
                   "v": pa.array(rng.integers(0, 100, n).astype(np.int64))})
    got = (s.create_dataframe(tb, num_partitions=3)
           .group_by(col("k")).agg(F.sum(col("v")).alias("sv"))
           .collect().sort_by("k"))
    assert "IciAggregateExec" in _names(s)
    want = pa.TableGroupBy(tb, ["k"], use_threads=False).aggregate(
        [("v", "sum")]).sort_by("k")
    assert got.column("k").to_pylist() == want.column("k").to_pylist()
    assert got.column("sv").to_pylist() == want.column("v_sum").to_pylist()


def test_tcp_transport_keeps_host_exchange():
    s = _session(transport="tcp")
    rng = np.random.default_rng(2)
    n = 1000
    tb = pa.table({"k": pa.array(rng.integers(0, 8, n).astype(np.int64)),
                   "v": pa.array(rng.random(n))})
    got = (s.create_dataframe(tb, num_partitions=3)
           .group_by(col("k")).agg(F.count("*").alias("c")).collect())
    assert "IciAggregateExec" not in _names(s)
    assert sum(got.column("c").to_pylist()) == n
