"""ICI transport routing: session queries run the fused mesh aggregate
when spark.rapids.shuffle.transport=ici and multiple chips exist."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession


def _session(transport="ici"):
    return (TpuSession.builder()
            .config("spark.rapids.sql.enabled", True)
            .config("spark.rapids.shuffle.transport", transport)
            .get_or_create())


def _names(s):
    out = []
    s.last_plan.foreach(lambda e: out.append(type(e).__name__))
    return out


def test_ici_aggregate_routed_and_correct():
    s = _session()
    rng = np.random.default_rng(0)
    n = 5000
    tb = pa.table({
        "k": pa.array(rng.integers(0, 64, n).astype(np.int64)),
        "v": pa.array(rng.integers(-500, 500, n).astype(np.int64)),
        "f": pa.array(rng.random(n)),
    })
    df = s.create_dataframe(tb, num_partitions=4)
    got = (df.filter(col("v") > -250).group_by(col("k"))
           .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("c"))
           .collect().sort_by("k"))
    assert "IciAggregateExec" in _names(s), _names(s)
    assert "ShuffleExchangeExec" not in _names(s)
    import pyarrow.compute as pc
    flt = tb.filter(pc.greater(tb.column("v"), -250))
    want = pa.TableGroupBy(flt, ["k"], use_threads=False).aggregate(
        [("v", "sum"), ("k", "count")]).sort_by("k")
    assert got.column("k").to_pylist() == want.column("k").to_pylist()
    assert got.column("sv").to_pylist() == want.column("v_sum").to_pylist()
    assert got.column("c").to_pylist() == want.column("k_count").to_pylist()


def test_ici_aggregate_with_string_keys():
    s = _session()
    rng = np.random.default_rng(1)
    n = 1200
    keys = [f"key_{int(i)}" for i in rng.integers(0, 40, n)]
    tb = pa.table({"k": pa.array(keys),
                   "v": pa.array(rng.integers(0, 100, n).astype(np.int64))})
    got = (s.create_dataframe(tb, num_partitions=3)
           .group_by(col("k")).agg(F.sum(col("v")).alias("sv"))
           .collect().sort_by("k"))
    assert "IciAggregateExec" in _names(s)
    want = pa.TableGroupBy(tb, ["k"], use_threads=False).aggregate(
        [("v", "sum")]).sort_by("k")
    assert got.column("k").to_pylist() == want.column("k").to_pylist()
    assert got.column("sv").to_pylist() == want.column("v_sum").to_pylist()


def test_tcp_transport_keeps_host_exchange():
    s = _session(transport="tcp")
    rng = np.random.default_rng(2)
    n = 1000
    tb = pa.table({"k": pa.array(rng.integers(0, 8, n).astype(np.int64)),
                   "v": pa.array(rng.random(n))})
    got = (s.create_dataframe(tb, num_partitions=3)
           .group_by(col("k")).agg(F.count("*").alias("c")).collect())
    assert "IciAggregateExec" not in _names(s)
    assert sum(got.column("c").to_pylist()) == n


def test_ici_join_routed_and_correct():
    """A shuffled hash join with transport=ici fuses into IciJoinExec:
    both sides exchanged over all_to_all inside one SPMD stage and the
    result equals the host path (ref GpuShuffledHashJoinBase)."""
    rng = np.random.default_rng(3)
    n = 4000
    fact = pa.table({
        "k": pa.array(rng.integers(0, 300, n).astype(np.int64)),
        "v": pa.array(rng.integers(-100, 100, n).astype(np.int64)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(250, dtype=np.int64)),
        "w": pa.array(rng.integers(0, 10, 250).astype(np.int64)),
    })
    # disable broadcast so the shuffled-hash path is chosen
    s2 = (TpuSession.builder()
          .config("spark.rapids.sql.enabled", True)
          .config("spark.rapids.shuffle.transport", "ici")
          .config("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
          .get_or_create())
    fdf = s2.create_dataframe(fact, num_partitions=4)
    ddf = s2.create_dataframe(dim, num_partitions=2)
    got = fdf.join(ddf, on="k", how="inner").collect()
    names = _names(s2)
    assert "IciJoinExec" in names, names
    assert "ShuffleExchangeExec" not in names

    # oracle: host path with ici off
    s3 = (TpuSession.builder()
          .config("spark.rapids.sql.enabled", False)
          .config("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
          .get_or_create())
    want = (s3.create_dataframe(fact, num_partitions=4)
            .join(s3.create_dataframe(dim, num_partitions=2),
                  on="k", how="inner").collect())
    key = lambda tb: sorted(zip(tb.column("k").to_pylist(),
                                tb.column("v").to_pylist(),
                                tb.column("w").to_pylist()))
    assert key(got) == key(want)


def test_ici_join_semi_anti():
    rng = np.random.default_rng(4)
    n = 2000
    left = pa.table({
        "k": pa.array(rng.integers(0, 100, n).astype(np.int64)),
        "v": pa.array(rng.integers(0, 50, n).astype(np.int64)),
    })
    right = pa.table({"k": pa.array(np.arange(0, 60, dtype=np.int64))})
    s2 = (TpuSession.builder()
          .config("spark.rapids.sql.enabled", True)
          .config("spark.rapids.shuffle.transport", "ici")
          .config("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
          .get_or_create())
    for how, pred in [("left_semi", lambda k: k < 60),
                      ("left_anti", lambda k: k >= 60)]:
        got = (s2.create_dataframe(left, num_partitions=3)
               .join(s2.create_dataframe(right, num_partitions=2),
                     on="k", how=how).collect())
        assert "IciJoinExec" in _names(s2), (how, _names(s2))
        want = sorted((k, v) for k, v in
                      zip(left.column("k").to_pylist(),
                          left.column("v").to_pylist()) if pred(k))
        assert sorted(zip(got.column("k").to_pylist(),
                          got.column("v").to_pylist())) == want, how


def test_ici_sort_routed_and_total_order():
    """A global sort with transport=ici fuses into IciSortExec (splitter
    sample + all_to_all + local sort in one SPMD program) and yields the
    exact total order of the host path (ref GpuRangePartitioner)."""
    s = _session()
    rng = np.random.default_rng(5)
    n = 3000
    tb = pa.table({
        "a": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
        "b": pa.array(rng.random(n)),
    })
    df = s.create_dataframe(tb, num_partitions=4)
    got = df.sort(col("a"), col("b")).collect()
    names = _names(s)
    assert "IciSortExec" in names, names
    assert "ShuffleExchangeExec" not in names
    want = tb.sort_by([("a", "ascending"), ("b", "ascending")])
    assert got.column("a").to_pylist() == want.column("a").to_pylist()
    assert got.column("b").to_pylist() == want.column("b").to_pylist()


def test_ici_sort_desc_with_strings():
    s = _session()
    rng = np.random.default_rng(6)
    n = 800
    words = [f"w{int(i):03d}" for i in rng.integers(0, 200, n)]
    tb = pa.table({"s": pa.array(words),
                   "v": pa.array(rng.integers(0, 99, n).astype(np.int64))})
    df = s.create_dataframe(tb, num_partitions=3)
    got = df.sort(col("s").desc(), col("v")).collect()
    assert "IciSortExec" in _names(s), _names(s)
    want = tb.sort_by([("s", "descending"), ("v", "ascending")])
    assert got.column("s").to_pylist() == want.column("s").to_pylist()
    assert got.column("v").to_pylist() == want.column("v").to_pylist()


def test_ici_flat_stage_is_device_resident(monkeypatch):
    """Flat-schema ICI stages must never stage rows through host Arrow:
    the scan->mesh edge is one jitted reshard over device batches (ref
    RapidsShuffleInternalManagerBase.scala:74 — shuffle input stays
    device-resident end-to-end)."""
    from spark_rapids_tpu.parallel import ici_exec

    def boom(*a, **k):  # host staging would be a regression
        raise AssertionError("host Arrow staging used for flat schema")

    monkeypatch.setattr(ici_exec, "_gather_source_table", boom)
    monkeypatch.setattr(ici_exec, "_emit_table", boom)

    s = _session()
    rng = np.random.default_rng(7)
    n = 4096
    tb = pa.table({
        "k": pa.array(rng.integers(0, 32, n).astype(np.int64)),
        "v": pa.array(rng.integers(-50, 50, n).astype(np.int64)),
    })
    got = (s.create_dataframe(tb, num_partitions=4)
           .group_by(col("k")).agg(F.sum(col("v")).alias("sv"))
           .collect().sort_by("k"))
    assert "IciAggregateExec" in _names(s)
    want = pa.TableGroupBy(tb, ["k"], use_threads=False).aggregate(
        [("v", "sum")]).sort_by("k")
    assert got.column("k").to_pylist() == want.column("k").to_pylist()
    assert got.column("sv").to_pylist() == want.column("v_sum").to_pylist()

    # sorts ride the same device-resident edge
    got2 = (s.create_dataframe(tb, num_partitions=4)
            .sort(col("v"), col("k")).collect())
    assert "IciSortExec" in _names(s)
    want2 = tb.sort_by([("v", "ascending"), ("k", "ascending")])
    assert got2.column("v").to_pylist() == want2.column("v").to_pylist()


def test_ici_full_outer_join():
    """Full-outer over ICI: co-located keys make per-shard unmatched
    emission globally exact (ref GpuHashJoin full outer)."""
    rng = np.random.default_rng(8)
    left = pa.table({
        "k": pa.array(rng.integers(0, 40, 500).astype(np.int64)),
        "v": pa.array(rng.integers(0, 9, 500).astype(np.int64)),
    })
    right = pa.table({
        "k": pa.array(np.arange(20, 60, dtype=np.int64)),
        "w": pa.array(np.arange(40, dtype=np.int64)),
    })

    def run(enabled_ici):
        s2 = (TpuSession.builder()
              .config("spark.rapids.sql.enabled", True)
              .config("spark.rapids.shuffle.transport",
                      "ici" if enabled_ici else "tcp")
              .config("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
              .get_or_create())
        out = (s2.create_dataframe(left, num_partitions=3)
               .join(s2.create_dataframe(right, num_partitions=2),
                     on="k", how="full").collect())
        return out, _names(s2)

    got, names = run(True)
    assert "IciJoinExec" in names, names
    want, _ = run(False)
    key = lambda tb: sorted(
        zip(tb.column("k").to_pylist(), tb.column("v").to_pylist(),
            tb.column("w").to_pylist()), key=str)
    assert key(got) == key(want)


def test_ici_bare_repartition_routed():
    """A hash repartition with no fused stage above it still rides ICI
    (IciExchangeExec; the transport is operator-agnostic like
    UCXShuffleTransport)."""
    s = _session()
    rng = np.random.default_rng(9)
    n = 3000
    tb = pa.table({
        "k": pa.array(rng.integers(0, 50, n).astype(np.int64)),
        "v": pa.array(rng.integers(-99, 99, n).astype(np.int64)),
    })
    got = (s.create_dataframe(tb, num_partitions=4)
           .repartition(8, col("k")).collect())
    names = _names(s)
    assert "IciExchangeExec" in names, names
    assert "ShuffleExchangeExec" not in names
    assert sorted(zip(got.column("k").to_pylist(),
                      got.column("v").to_pylist())) == \
        sorted(zip(tb.column("k").to_pylist(),
                   tb.column("v").to_pylist()))


def test_ici_join_and_string_stages_device_resident(monkeypatch):
    """The device-resident scan->mesh edge now covers joins and string
    schemas: staging through host Arrow is a regression (VERDICT r4
    missing #3; ref RapidsShuffleInternalManagerBase.scala:74)."""
    from spark_rapids_tpu.parallel import ici_exec

    def boom(*a, **k):
        raise AssertionError("host Arrow staging used")

    monkeypatch.setattr(ici_exec, "_gather_source_table", boom)

    rng = np.random.default_rng(21)
    n = 3000
    left = pa.table({
        "k": pa.array(rng.integers(0, 64, n).astype(np.int64)),
        "v": pa.array(rng.integers(-50, 50, n).astype(np.int64)),
    })
    right = pa.table({
        "k": pa.array(np.arange(64, dtype=np.int64)),
        "w": pa.array(np.arange(64, dtype=np.int64) * 3),
    })
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.shuffle.transport", "ici")
         .config("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
         .get_or_create())
    got = (s.create_dataframe(left, num_partitions=4)
           .join(s.create_dataframe(right, num_partitions=2), on="k")
           .group_by(col("k")).agg(F.sum(col("w")).alias("sw"))
           .collect().sort_by("k"))
    assert "IciJoinExec" in _names(s), _names(s)
    import pyarrow.compute as pc
    counts = pa.TableGroupBy(left, ["k"], use_threads=False).aggregate(
        [("k", "count")]).sort_by("k")
    want = {int(k): int(c) * int(k) * 3
            for k, c in zip(counts.column("k").to_pylist(),
                            counts.column("k_count").to_pylist())}
    assert {int(k): int(v) for k, v in
            zip(got.column("k").to_pylist(),
                got.column("sw").to_pylist())} == want

    # string-keyed aggregate rides the same device-resident edge
    keys = [f"key_{int(i):02d}" for i in rng.integers(0, 40, n)]
    tb = pa.table({"k": pa.array(keys),
                   "v": pa.array(rng.integers(0, 100, n).astype(np.int64))})
    got2 = (s.create_dataframe(tb, num_partitions=3)
            .group_by(col("k")).agg(F.sum(col("v")).alias("sv"))
            .collect().sort_by("k"))
    assert "IciAggregateExec" in _names(s)
    want2 = pa.TableGroupBy(tb, ["k"], use_threads=False).aggregate(
        [("v", "sum")]).sort_by("k")
    assert got2.column("k").to_pylist() == want2.column("k").to_pylist()
    assert got2.column("sv").to_pylist() == want2.column("v_sum").to_pylist()


def test_ici_left_join_with_condition():
    """Residual conditions on non-inner ICI joins: co-located shards make
    the expand+repair kernel locally exact (VERDICT r4 missing #5; ref
    GpuOverrides.scala:3352-3355).  Differential vs the host engine."""
    rng = np.random.default_rng(23)
    left = pa.table({
        "k": pa.array(rng.integers(0, 30, 600).astype(np.int64)),
        "va": pa.array(rng.integers(-40, 40, 600).astype(np.int64)),
    })
    right = pa.table({
        "k2": pa.array(rng.integers(0, 30, 200).astype(np.int64)),
        "vb": pa.array(rng.integers(-40, 40, 200).astype(np.int64)),
    })

    def q(session):
        a = session.create_dataframe(left, num_partitions=4)
        b = session.create_dataframe(right, num_partitions=2)
        return a.join(b, on=(col("k") == col("k2")) &
                      (col("va") > col("vb")), how="left")

    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.shuffle.transport", "ici")
         .config("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
         .get_or_create())
    got = q(s).collect()
    assert "IciJoinExec" in _names(s), _names(s)

    cpu = (TpuSession.builder()
           .config("spark.rapids.sql.enabled", False)
           .get_or_create())
    want = q(cpu).collect()
    order = [(n, "ascending") for n in got.schema.names]
    assert got.sort_by(order).equals(want.sort_by(order))


def test_ici_struct_keyed_time_window_aggregate():
    """Struct grouping keys (time-window buckets) ride the ICI path now
    that the exchange carries struct-of-flat columns (round-5 widening)."""
    import datetime
    rng = np.random.default_rng(29)
    n = 2000
    base = datetime.datetime(2024, 1, 1)
    ts = [base + datetime.timedelta(seconds=int(x))
          for x in rng.integers(0, 3600, n)]
    tb = pa.table({"t": pa.array(ts, type=pa.timestamp("us")),
                   "v": pa.array(rng.integers(0, 50, n).astype(np.int64))})

    def q(session):
        return (session.create_dataframe(tb, num_partitions=4)
                .group_by(F.window(col("t"), "10 minutes"))
                .agg(F.sum(col("v")).alias("sv")).collect())

    s = _session()
    got = q(s)
    assert "IciAggregateExec" in _names(s), _names(s)
    c = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", False).get_or_create())
    want = q(c)
    gs = sorted(zip(map(str, got.column(0).to_pylist()),
                    got.column("sv").to_pylist()))
    ws = sorted(zip(map(str, want.column(0).to_pylist()),
                    want.column("sv").to_pylist()))
    assert gs == ws


def test_ici_collect_list_rides_array_exchange():
    """collect_list's array-typed partial buffers now ride the ICI
    all_to_all (round-5 span widening) instead of the host fallback."""
    rng = np.random.default_rng(31)
    tb = pa.table({
        "k": pa.array(rng.integers(0, 20, 600).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, 600).astype(np.int64)),
    })

    def q(session):
        return (session.create_dataframe(tb, num_partitions=4)
                .group_by(col("k"))
                .agg(F.collect_list(col("v")).alias("vs")).collect())

    s = _session()
    got = q(s)
    assert "IciAggregateExec" in _names(s), _names(s)
    c = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", False).get_or_create())
    want = q(c)
    gs = {k: sorted(v) for k, v in zip(got.column("k").to_pylist(),
                                       got.column("vs").to_pylist())}
    ws = {k: sorted(v) for k, v in zip(want.column("k").to_pylist(),
                                       want.column("vs").to_pylist())}
    assert gs == ws


def test_ici_array_repartition_device_resident(monkeypatch):
    """A bare repartition of an array column rides the device-resident
    reshard + all_to_all (no host Arrow staging)."""
    from spark_rapids_tpu.parallel import ici_exec

    def boom(*a, **k):
        raise AssertionError("host Arrow staging used")

    monkeypatch.setattr(ici_exec, "_gather_source_table", boom)

    rng = np.random.default_rng(37)
    n = 1024
    arrs = [None if i % 17 == 0 else
            [int(x) for x in range(i % 4)] for i in range(n)]
    tb = pa.table({
        "k": pa.array(rng.integers(0, 64, n).astype(np.int64)),
        "a": pa.array(arrs, type=pa.list_(pa.int64())),
    })
    s = _session()
    got = (s.create_dataframe(tb, num_partitions=4)
           .repartition(8, col("k")).collect())
    assert "IciExchangeExec" in _names(s), _names(s)
    key = lambda r: (r[0], repr(r[1]))  # noqa: E731
    got_rows = sorted(zip(got.column("k").to_pylist(),
                          got.column("a").to_pylist()), key=key)
    want_rows = sorted(zip(tb.column("k").to_pylist(),
                           tb.column("a").to_pylist()), key=key)
    assert got_rows == want_rows
