"""Golden GOOD-plan corpus for the flow-sensitive plan typechecker.

Each ``plan_*()`` builder returns ``(exec_root, conf_map)`` — a clean,
runnable physical plan of the shapes the overrides engine actually
emits.  Consumed three ways:

  * tests/test_interp_oracle.py runs the differential oracle over every
    builder: the abstract interpreter's predicted schema / residency /
    partitioning / ordering must match real numpy-backend execution on
    EVERY subtree (the analyzer is statically checked against the
    engine, the verify_gates() discipline);
  * the same test asserts the flow-sensitive lint raises no errors here
    (zero false rejects), the complement of bad_plans.py's zero false
    admits;
  * ``devtools/run_lint.py --interp`` gates both in CI.

Keep the plans executable and hazard-free: a builder that trips a rule
belongs in bad_plans.py instead.
"""

import pyarrow as pa

from spark_rapids_tpu import types as t
from spark_rapids_tpu.exec import base as eb
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.basic import (CoalesceBatchesExec, FilterExec,
                                         LocalLimitExec, LocalScanExec,
                                         ProjectExec, SampleExec,
                                         UnionExec)
from spark_rapids_tpu.exec.broadcast import BroadcastExchangeExec
from spark_rapids_tpu.exec.gatherpart import GatherPartitionsExec
from spark_rapids_tpu.exec.join import HashJoinExec
from spark_rapids_tpu.exec.sort import SortExec
from spark_rapids_tpu.expr.aggregates import (AggregateExpression, FINAL,
                                              PARTIAL, Sum)
from spark_rapids_tpu.expr.arithmetic import Add
from spark_rapids_tpu.expr.core import (Alias, AttributeReference,
                                        Literal)
from spark_rapids_tpu.expr.predicates import GreaterThan
from spark_rapids_tpu.shuffle.exchange import ShuffleExchangeExec
from spark_rapids_tpu.shuffle.partitioning import HashPartitioning


def _scan(table, placement=eb.TPU, **kw):
    s = LocalScanExec(table, **kw)
    s.placement = placement
    return s


def _kv(n=32, k_mod=5, names=("k", "v")):
    return pa.table({
        names[0]: pa.array([i % k_mod for i in range(n)],
                           type=pa.int64()),
        names[1]: pa.array(range(n), type=pa.int64()),
    })


def plan_project_filter_device():
    """scan -> filter -> project, all device-resident."""
    scan = _scan(_kv())
    f = FilterExec(GreaterThan(AttributeReference("v"),
                               Literal(3, t.LONG)), scan)
    f.placement = eb.TPU
    p = ProjectExec([AttributeReference("k"),
                     Alias(Add(AttributeReference("v"),
                               Literal(1, t.LONG)), "v1")], f)
    p.placement = eb.TPU
    return p, {}


def plan_host_pipeline():
    """The same pipeline entirely on the host engine (numpy batches)."""
    scan = _scan(_kv(), placement=eb.CPU)
    f = FilterExec(GreaterThan(AttributeReference("v"),
                               Literal(3, t.LONG)), scan)
    f.placement = eb.CPU
    p = ProjectExec([AttributeReference("v")], f)
    p.placement = eb.CPU
    return p, {}


def plan_accelerated_island():
    """Host scan -> device compute -> host root: the NORMAL accelerated
    shape (one device region inside a host pipeline) that the residency
    rules must never flag."""
    scan = _scan(_kv(), placement=eb.CPU)
    up = eb.HostToDeviceExec(scan)
    p = ProjectExec([AttributeReference("k"),
                     AttributeReference("v")], up)
    p.placement = eb.TPU
    down = eb.DeviceToHostExec(p)
    return down, {}


def plan_partial_final_aggregate():
    """The canonical grouped-aggregate pipeline: partial below a hash
    exchange on the group key, FINAL above it (the contract the
    ClusteredContract declaration encodes)."""
    scan = _scan(_kv(n=64), num_partitions=2)
    grouping = [AttributeReference("k")]
    aggs = [AggregateExpression(Sum(AttributeReference("v")))]
    partial = TpuHashAggregateExec(grouping, aggs, PARTIAL, scan)
    ex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference(partial.output_names[0])], 4),
        partial)
    ex.placement = eb.TPU
    final = TpuHashAggregateExec(grouping, partial.aggregates, FINAL, ex)
    return final, {}


def plan_colocated_join_with_exchanges():
    """Shuffled hash join: both sides exchanged on the join keys with
    the same partition count — the contract the CoClusteredContract
    declaration encodes."""
    lt = _kv(n=32, names=("k", "v"))
    rt = _kv(n=24, names=("k2", "w"))
    lex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("k")], 4),
        _scan(lt, num_partitions=2))
    lex.placement = eb.TPU
    rex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("k2")], 4),
        _scan(rt, num_partitions=2))
    rex.placement = eb.TPU
    join = HashJoinExec([AttributeReference("k")],
                        [AttributeReference("k2")], "inner", None,
                        lex, rex, colocated=True)
    join.placement = eb.TPU
    return join, {}


def plan_broadcast_join():
    """Broadcast hash join: replicated build side satisfies the
    co-location requirement for any probe distribution."""
    probe = _scan(_kv(n=32), num_partitions=2)
    bex = BroadcastExchangeExec(_scan(_kv(n=8, names=("k2", "w"))))
    bex.placement = eb.TPU
    join = HashJoinExec([AttributeReference("k")],
                        [AttributeReference("k2")], "inner", None,
                        probe, bex)
    join.placement = eb.TPU
    return join, {}


def plan_global_sort():
    """Gather to one partition then sort: the single-chip global-sort
    shape; the predicted ordering contract is oracle-verified."""
    scan = _scan(_kv(n=48), num_partitions=3)
    g = GatherPartitionsExec(scan)
    g.placement = eb.TPU
    c = CoalesceBatchesExec(g)
    c.placement = eb.TPU
    s = SortExec([(AttributeReference("v"), False, True)], c,
                 is_global=True)
    s.placement = eb.TPU
    return s, {}


def plan_union_limit_sample():
    """Union of two scans, sampled and limited — forwarding operators
    whose states pass through."""
    u = UnionExec([_scan(_kv(n=16)), _scan(_kv(n=16))])
    u.placement = eb.TPU
    sm = SampleExec(0.5, 42, u)
    sm.placement = eb.TPU
    lim = LocalLimitExec(5, sm)
    lim.placement = eb.TPU
    return lim, {}


def plan_exchange_fully_read():
    """An exchange whose every column IS read above (no dead columns):
    the L010 non-example."""
    scan = _scan(_kv(n=32), num_partitions=2)
    ex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("k")], 4), scan)
    ex.placement = eb.TPU
    p = ProjectExec([Alias(Add(AttributeReference("k"),
                               AttributeReference("v")), "s")], ex)
    p.placement = eb.TPU
    return p, {}
