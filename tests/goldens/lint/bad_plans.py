"""Golden "bad plan" fixtures for the tpulint plan linter.

Each ``plan_<rule>()`` builder returns ``(exec_root, conf_map)`` — a
physical plan carrying exactly the hazard one TPU-Lxxx rule class
exists to catch, plus the session conf that arms it.  Consumed two
ways:

  * tests/test_lint_plan.py asserts each builder trips the codes listed
    in expected_codes.json (and nothing unexpected at error severity);
  * ``python -m spark_rapids_tpu.tools lint --plan
    tests/goldens/lint/bad_plans.py`` prints the diagnostics, which is
    the CLI's reference demo.

These plans are deliberately hazardous — they document plan shapes the
overrides engine must never emit, so do not "fix" them.
"""

import pyarrow as pa

from spark_rapids_tpu import types as t
from spark_rapids_tpu.exec import base as eb
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.basic import (FilterExec, LocalScanExec,
                                         ProjectExec)
from spark_rapids_tpu.exec.broadcast import BroadcastExchangeExec
from spark_rapids_tpu.exec.join import HashJoinExec
from spark_rapids_tpu.exec.python_udf import ArrowEvalPythonExec
from spark_rapids_tpu.expr.aggregates import (AggregateExpression,
                                              CollectList, PARTIAL, Sum)
from spark_rapids_tpu.expr.core import (Alias, AttributeReference,
                                        Literal)
from spark_rapids_tpu.expr.predicates import GreaterThan
from spark_rapids_tpu.expr.regex import RLike
from spark_rapids_tpu.shuffle.exchange import ShuffleExchangeExec
from spark_rapids_tpu.shuffle.partitioning import HashPartitioning
from spark_rapids_tpu.udf.python_udf import PythonUDF


def _scan(table, placement=eb.TPU, **kw):
    s = LocalScanExec(table, **kw)
    s.placement = placement
    return s


def _ints(n=8, name="v"):
    return pa.table({name: pa.array(range(n), type=pa.int64())})


def plan_L001_ici_ungrouped_array_agg():
    """Global collect_list under transport=ici: the array partial buffer
    passes the exchange admission gate but allgather_batch raises on it
    — the round-5 admit/crash mismatch (ADVICE alltoall.py:278)."""
    scan = _scan(_ints())
    agg = TpuHashAggregateExec(
        [], [AggregateExpression(CollectList(AttributeReference("v")))],
        PARTIAL, scan)
    agg.placement = eb.TPU
    return agg, {"spark.rapids.shuffle.transport": "ici"}


def plan_L002_ping_pong():
    """A host-placed filter sandwiched between device projections: two
    interconnect crossings per batch for one operator."""
    scan = _scan(_ints())
    p1 = ProjectExec([AttributeReference("v")], scan)
    p1.placement = eb.TPU
    host = FilterExec(GreaterThan(AttributeReference("v"),
                                  Literal(2, t.LONG)), p1)
    host.placement = eb.CPU
    p2 = ProjectExec([AttributeReference("v")], host)
    p2.placement = eb.TPU
    return p2, {}


def plan_L003_host_expr_on_device():
    """A device-placed projection carrying a regex (host-only, no TPU
    lowering): admitted here only because the plan skipped tagging."""
    tb = pa.table({"s": pa.array(["a", "b"], type=pa.string())})
    scan = _scan(tb)
    proj = ProjectExec(
        [Alias(RLike(AttributeReference("s"), Literal("a.*", t.STRING)),
               "m")], scan)
    proj.placement = eb.TPU
    return proj, {}


def plan_L004_driver_collect():
    """Broadcast of a build side far above the whole-build collect
    threshold (armed low so the fixture stays small)."""
    big = pa.table({"k": pa.array(range(4096), type=pa.int64())})
    bex = BroadcastExchangeExec(_scan(big))
    bex.placement = eb.TPU
    probe = _scan(_ints(name="k"))
    join = HashJoinExec([AttributeReference("k")],
                        [AttributeReference("k")], "inner", None,
                        probe, bex)
    join.placement = eb.TPU
    return join, {"spark.rapids.tpu.lint.maxDriverCollectBytes": "1k"}


def plan_L005_compile_churn():
    """Off-bucket scan capacity plus more distinct operator schemas than
    the compiled-program budget (armed low): every shape compiles its
    own XLA program family and churns the residency cache."""
    scan = _scan(_ints(), batch_rows=777)
    node = scan
    for i in range(4):
        node = ProjectExec([AttributeReference("v"),
                            Alias(AttributeReference("v"), f"c{i}")], node)
        node.placement = eb.TPU
    return node, {"spark.rapids.tpu.lint.maxCompiledPrograms": 3}


def plan_L006_partition_contract():
    """A join marked colocated with no establishing exchange under
    either side of its MULTI-partition inputs: matching keys are NOT
    co-located, so per-partition results are silently wrong (the bridge
    full-outer class).  The scans are 2-partition on purpose — the
    flow-sensitive checker correctly admits the single-partition
    variant (everything co-located trivially)."""
    left = _scan(_ints(name="k"), num_partitions=2)
    right = _scan(_ints(name="k"), num_partitions=2)
    join = HashJoinExec([AttributeReference("k")],
                        [AttributeReference("k")], "inner", None,
                        left, right, colocated=True)
    join.placement = eb.TPU
    return join, {}


def plan_L007_ici_host_staging():
    """transport=ici but the exchanged schema carries array<string>,
    which the all_to_all kernel cannot ride — the shuffle silently
    stages through host Arrow."""
    tb = pa.table({
        "k": pa.array(range(8), type=pa.int64()),
        "tags": pa.array([["x"]] * 8, type=pa.list_(pa.string())),
    })
    scan = _scan(tb)
    ex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("k")], 4), scan)
    ex.placement = eb.TPU
    return ex, {"spark.rapids.shuffle.transport": "ici"}


def plan_L008_udf_boundary():
    """An opaque Python UDF worker boundary consuming device-resident
    batches: serialize + re-upload per batch."""
    scan = _scan(_ints())
    udf = PythonUDF(lambda x: x + 1, t.LONG,
                    [AttributeReference("v")], name="plus1")
    node = ArrowEvalPythonExec([("u", udf)], scan)
    return node, {}


# ---------------------------------------------------------------------------
# flow-sensitive fixtures (TPU-L009..L012, analysis/interp.py)
# ---------------------------------------------------------------------------

def plan_L009_stale_bind_after_rewrite():
    """A projection bound against one schema whose child a rewrite then
    swapped for a different one (the with_new_children/AQE surgery
    class): the stale BoundReference reads ordinal 0 as long where the
    new child produces a double named differently.  Only the
    flow-sensitive checker sees it — node-local rules have no notion of
    'the schema the child actually produces'."""
    old_child = _scan(_ints(), placement=eb.CPU)
    proj = ProjectExec([AttributeReference("v")], old_child)
    proj.placement = eb.CPU
    new_child = _scan(pa.table({"w": pa.array([1.5, 2.5],
                                              type=pa.float64())}),
                      placement=eb.CPU)
    return proj.with_new_children([new_child]), {}


def plan_L010_dead_exchange_columns():
    """An exchange ships a wide payload column that nothing above the
    exchange ever reads — every byte still rides the wire.  Requires
    liveness THROUGH the plan: the column is dead because of a
    projection two levels up."""
    tb = pa.table({
        "k": pa.array(range(64), type=pa.int64()),
        "v": pa.array(range(64), type=pa.int64()),
        "payload": pa.array(["x" * 64] * 64, type=pa.string()),
    })
    scan = _scan(tb, num_partitions=2)
    ex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("k")], 4), scan)
    ex.placement = eb.TPU
    proj = ProjectExec([AttributeReference("k"),
                        AttributeReference("v")], ex)
    proj.placement = eb.TPU
    return proj, {}


def plan_L011_contract_broken_by_rewrite():
    """A colocated join whose establishing exchanges a rewrite re-keyed:
    both sides ARE exchanges (so the syntactic L006 shape check
    passes), but they hash-route on column `a`, not the join key `k` —
    matching keys land in different partitions.  Only the inferred
    distribution catches it."""
    lt = pa.table({"k": pa.array(range(8), type=pa.int64()),
                   "a": pa.array(range(8), type=pa.int64())})
    rt = pa.table({"k": pa.array(range(8), type=pa.int64()),
                   "a": pa.array(range(8), type=pa.int64())})
    lex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("a")], 4),
        _scan(lt, num_partitions=2))
    lex.placement = eb.TPU
    rex = ShuffleExchangeExec(
        HashPartitioning([AttributeReference("a")], 4),
        _scan(rt, num_partitions=2))
    rex.placement = eb.TPU
    join = HashJoinExec([AttributeReference("k")],
                        [AttributeReference("k")], "inner", None,
                        lex, rex, colocated=True)
    join.placement = eb.TPU
    return join, {}


def plan_L013_shared_boundary_use_after_close():
    """A SpillBoundary whose registered handles close after ONE
    consumption, shared by TWO union arms (the with_new_children/reuse
    surgery duplicated a consumer without re-deriving the producer's
    count): the second arm materializes closed buffers.  Executing this
    raises use-after-close at runtime — and under
    spark.rapids.tpu.memsan.enabled the shadow ledger pinpoints it with
    owning-exec provenance; the static lifetime pass predicts it from
    the parent count alone."""
    from spark_rapids_tpu.exec.outofcore import SpillBoundaryExec
    from spark_rapids_tpu.exec.basic import UnionExec
    scan = _scan(_ints(n=16))
    sb = SpillBoundaryExec(scan, consumers=1)
    p1 = ProjectExec([AttributeReference("v")], sb)
    p1.placement = eb.TPU
    p2 = ProjectExec([AttributeReference("v")], sb)
    p2.placement = eb.TPU
    u = UnionExec([p1, p2])
    u.placement = eb.TPU
    return u, {}


def plan_L014_peak_over_hbm_budget():
    """An in-core sort whose ~3x working set (registered input + concat
    + sorted copy) blows a deliberately small HBM budget: the OOM is
    predictable from the same size model the CBO uses.  The pre-flight
    repair forces the sort out-of-core (oc_budget) instead of
    downgrading — see test_memsan.py."""
    big = pa.table({"v": pa.array(range(1 << 15), type=pa.int64())})
    scan = _scan(big, num_partitions=4)
    s = __import__("spark_rapids_tpu.exec.sort",
                   fromlist=["SortExec"]).SortExec(
        [(AttributeReference("v"), True, True)], scan, is_global=False)
    s.placement = eb.TPU
    return s, {"spark.rapids.tpu.memsan.hbmBudgetBytes": "256k"}


def plan_L015_boundary_never_closes():
    """A SpillBoundary declaring TWO consumers in a plan with only one
    parent (the rewrite that UN-shared the subtree forgot the count):
    the close never fires and the registered device buffers survive the
    query — the plan-level leak class the SpillCatalog leak tracker
    would only report after the damage."""
    from spark_rapids_tpu.exec.outofcore import SpillBoundaryExec
    scan = _scan(_ints(n=16))
    sb = SpillBoundaryExec(scan, consumers=2)
    p = ProjectExec([AttributeReference("v")], sb)
    p.placement = eb.TPU
    return p, {}


def plan_L012_residency_ping_pong():
    """Two separate host islands inside one device pipeline: batches
    already resident on device cross down to host and back up TWICE
    along the same path.  The path-level rule totals the transfer
    bytes; the node-local L002 only ever sees one sandwich at a time."""
    scan = _scan(_ints(n=4096))
    d1 = ProjectExec([AttributeReference("v")], scan)
    d1.placement = eb.TPU
    h1 = FilterExec(GreaterThan(AttributeReference("v"),
                                Literal(1, t.LONG)), d1)
    h1.placement = eb.CPU
    d2 = ProjectExec([AttributeReference("v")], h1)
    d2.placement = eb.TPU
    h2 = FilterExec(GreaterThan(AttributeReference("v"),
                                Literal(2, t.LONG)), d2)
    h2.placement = eb.CPU
    d3 = ProjectExec([AttributeReference("v")], h2)
    d3.placement = eb.TPU
    return d3, {}


def plan_L018_pad_waste():
    """Ten live rows forced into a single 1M-row capacity bucket: the
    interp's row estimate is a sliver of the bucket every launch pads
    to, so ~100% of the memory traffic is padding (tpuxsan TPU-L018).
    The pre-flight repair re-buckets the filter speculatively when a
    smaller bucket exists; here there is none, so the finding stands."""
    scan = _scan(_ints(n=10))
    flt = FilterExec(GreaterThan(AttributeReference("v"),
                                 Literal(2, t.LONG)), scan)
    flt.placement = eb.TPU
    return flt, {"spark.rapids.tpu.batchCapacityBuckets": "1048576"}


def plan_L020_fusion_break():
    """A memory-bound projection feeding a memory-bound filter over a
    ~1.5 MiB intermediate: two separate compiled programs write and
    re-read the handoff a fused kernel would never materialize
    (tpuxsan TPU-L020, advisory — the kernel-gap report's target)."""
    scan = _scan(_ints(n=200000))
    proj = ProjectExec([AttributeReference("v")], scan)
    proj.placement = eb.TPU
    flt = FilterExec(GreaterThan(AttributeReference("v"),
                                 Literal(2, t.LONG)), proj)
    flt.placement = eb.TPU
    return flt, {}
