"""Codec round-trip and corruption-taxonomy tests: every backend
(native lz4, system libzstd, forced zlib fallback) must round-trip
arbitrary payloads, reject garbage with the typed CodecCorruptionError,
and the batch serializer above them must surface every decode failure
as TpuCorruptPayloadError — never a bare assert — while metering
raw/encoded bytes per codec."""

import struct

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.native import codec as ncodec
from spark_rapids_tpu.native.codec import CodecCorruptionError

PAYLOADS = [
    b"",
    b"x",
    b"hello shuffle " * 500,
    bytes(range(256)) * 32,
    np.random.default_rng(3).integers(0, 255, 50_000,
                                      dtype=np.uint8).tobytes(),
    b"\x00" * 32768,
]


@pytest.fixture
def zlib_fallback(monkeypatch):
    """Force BOTH native backends away so compress falls back to the
    stdlib zlib path (the no-native-toolchain deployment)."""
    monkeypatch.setattr(ncodec, "get_lib", lambda: None)
    monkeypatch.setattr(ncodec, "_zstd_lib", None)
    monkeypatch.setattr(ncodec, "_zstd_checked", True)


@pytest.mark.parametrize("payload", PAYLOADS)
@pytest.mark.parametrize("codec", ["lz4", "zstd"])
def test_native_roundtrip(codec, payload):
    assert ncodec.decompress(codec, ncodec.compress(codec, payload)) \
        == payload


@pytest.mark.parametrize("payload", PAYLOADS)
@pytest.mark.parametrize("codec", ["lz4", "zstd"])
def test_zlib_fallback_roundtrip(zlib_fallback, codec, payload):
    comp = ncodec.compress(codec, payload)
    # the frame must self-describe as the zlib backend so a reader WITH
    # the native libs still decodes it
    _, backend = ncodec._FRAME.unpack_from(comp, 0)
    assert backend == ncodec._B_ZLIB
    assert ncodec.decompress(codec, comp) == payload


@pytest.mark.parametrize("codec", ["lz4", "zstd"])
def test_fallback_frames_decode_with_native_present(codec, monkeypatch):
    """A payload written by a fallback-only process round-trips through
    a decoder that DOES have the native backends (mixed fleet)."""
    monkeypatch.setattr(ncodec, "get_lib", lambda: None)
    monkeypatch.setattr(ncodec, "_zstd_lib", None)
    monkeypatch.setattr(ncodec, "_zstd_checked", True)
    comp = ncodec.compress(codec, b"mixed-fleet " * 100)
    monkeypatch.undo()
    assert ncodec.decompress(codec, comp) == b"mixed-fleet " * 100


@pytest.mark.parametrize("codec", ["lz4", "zstd"])
def test_short_frame_is_typed_corruption(codec):
    with pytest.raises(CodecCorruptionError):
        ncodec.decompress(codec, b"\x01")


@pytest.mark.parametrize("codec", ["lz4", "zstd"])
def test_negative_size_is_typed_corruption(codec):
    frame = ncodec._FRAME.pack(-5, ncodec._B_ZLIB) + b"junk"
    with pytest.raises(CodecCorruptionError):
        ncodec.decompress(codec, frame)


@pytest.mark.parametrize("codec", ["lz4", "zstd"])
def test_unknown_backend_is_typed_corruption(codec):
    frame = ncodec._FRAME.pack(10, 99) + b"0123456789"
    with pytest.raises(CodecCorruptionError):
        ncodec.decompress(codec, frame)


def test_garbage_zlib_body_is_typed_corruption():
    frame = ncodec._FRAME.pack(100, ncodec._B_ZLIB) + b"\xff" * 40
    with pytest.raises(CodecCorruptionError):
        ncodec.lz4_decompress(frame)


def test_wrong_length_zlib_body_is_typed_corruption():
    import zlib
    frame = ncodec._FRAME.pack(999, ncodec._B_ZLIB) + zlib.compress(b"ab")
    with pytest.raises(CodecCorruptionError):
        ncodec.lz4_decompress(frame)


# -- the serializer above the codecs ---------------------------------------


def _batch(n=64):
    from spark_rapids_tpu.columnar.device import batch_to_device
    rb = pa.record_batch({"a": pa.array(np.arange(n, dtype=np.int64)),
                          "b": pa.array(np.arange(n, dtype=np.int64) * 2)})
    return batch_to_device(rb, xp=np)


@pytest.mark.parametrize("codec_name", ["none", "lz4", "zstd"])
def test_serialize_roundtrip_all_codecs(codec_name):
    from spark_rapids_tpu.columnar.device import batch_to_arrow
    from spark_rapids_tpu.memory import meta
    payload = meta.serialize_batch(_batch(),
                                   meta.CODEC_BY_NAME[codec_name])
    out = meta.deserialize_batch(payload, xp=np)
    assert batch_to_arrow(out).equals(batch_to_arrow(_batch()))


@pytest.mark.parametrize("codec_name", ["lz4", "zstd"])
def test_serialize_roundtrip_under_zlib_fallback(zlib_fallback,
                                                 codec_name):
    from spark_rapids_tpu.columnar.device import batch_to_arrow
    from spark_rapids_tpu.memory import meta
    payload = meta.serialize_batch(_batch(),
                                   meta.CODEC_BY_NAME[codec_name])
    out = meta.deserialize_batch(payload, xp=np)
    assert batch_to_arrow(out).equals(batch_to_arrow(_batch()))


def test_deserialize_truncated_payload_typed():
    from spark_rapids_tpu.memory import meta
    payload = meta.serialize_batch(_batch(), meta.CODEC_NONE)
    with pytest.raises(meta.TpuCorruptPayloadError,
                       match="truncated payload body"):
        meta.deserialize_batch(payload[:len(payload) - 16])


def test_deserialize_short_header_typed():
    from spark_rapids_tpu.memory import meta
    with pytest.raises(meta.TpuCorruptPayloadError,
                       match="too short for header"):
        meta.deserialize_batch(b"TPU")


def test_deserialize_bad_magic_typed():
    from spark_rapids_tpu.memory import meta
    payload = meta.serialize_batch(_batch(), meta.CODEC_NONE)
    with pytest.raises(meta.TpuCorruptPayloadError, match="bad batch"):
        meta.deserialize_batch(b"XXXX" + payload[4:])


def test_deserialize_unknown_codec_id_typed():
    from spark_rapids_tpu.memory import meta
    payload = bytearray(meta.serialize_batch(_batch(), meta.CODEC_NONE))
    # codec field lives at offset 6 (<4sHHqq: 4s magic, H version, H codec)
    struct.pack_into("<H", payload, 6, 77)
    with pytest.raises(meta.TpuCorruptPayloadError,
                       match="unknown codec id"):
        meta.deserialize_batch(bytes(payload))


def test_deserialize_corrupt_codec_frame_typed():
    from spark_rapids_tpu.memory import meta
    head = meta._HEADER.pack(meta.MAGIC, meta.VERSION, meta.CODEC_LZ4,
                             10, 20)
    with pytest.raises(meta.TpuCorruptPayloadError,
                       match="codec frame corrupt"):
        meta.deserialize_batch(head + b"\xff" * 20)


def test_deserialize_corrupt_arrow_body_typed():
    from spark_rapids_tpu.memory import meta
    body = b"\x01" * 64
    head = meta._HEADER.pack(meta.MAGIC, meta.VERSION, meta.CODEC_NONE,
                             10, len(body))
    with pytest.raises(meta.TpuCorruptPayloadError,
                       match="arrow body corrupt"):
        meta.deserialize_batch(head + body)


@pytest.mark.parametrize("codec_name", ["none", "lz4", "zstd"])
def test_serialize_meters_raw_and_encoded_bytes(codec_name):
    import spark_rapids_tpu.obs.metrics as m
    from spark_rapids_tpu.memory import meta
    m.MetricsRegistry.reset_for_tests()
    try:
        _, raw, enc = meta.serialize_batch_with_sizes(
            _batch(4096), meta.CODEC_BY_NAME[codec_name])
        raw_c = m.counter("tpu_shuffle_raw_bytes_total",
                          labelnames=("codec",))
        enc_c = m.counter("tpu_shuffle_compressed_bytes_total",
                          labelnames=("codec",))
        assert raw_c.value(codec=codec_name) == raw > 0
        assert enc_c.value(codec=codec_name) == enc > 0
        if codec_name == "none":
            assert raw == enc
        else:
            # sequential int64 lanes compress well below the 0.9 bar
            assert enc / raw < 0.9
    finally:
        m.MetricsRegistry.reset_for_tests()
