"""Adaptive query execution tests: partition coalescing + skew split
(model: the reference's AdaptiveQueryExecSuite)."""

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.shuffle.aqe import (AQEShuffleReadExec, coalesce_specs,
                                          skew_split_specs)


def _session(**extra):
    b = TpuSession.builder().config("spark.rapids.sql.enabled", True)
    for k, v in extra.items():
        b = b.config(k, v)
    return b.get_or_create()


def test_coalesce_specs_groups_adjacent():
    specs = coalesce_specs([10, 10, 10, 100, 5, 5], target=30)
    groups = [s.reduce_ids for s in specs]
    assert groups == [[0, 1, 2], [3], [4, 5]]


def test_coalesce_specs_huge_partition_alone():
    specs = coalesce_specs([500, 1, 1], target=100)
    assert [s.reduce_ids for s in specs] == [[0], [1, 2]]


def test_skew_split_detects_and_chunks():
    sizes = [1000_000, 10, 10, 10]
    n_blocks = [8, 2, 2, 2]
    specs = skew_split_specs(sizes, n_blocks, factor=5.0, threshold=100,
                             target=250_000)
    assert specs is not None
    skewed = [s for s in specs if s.block_slice is not None]
    assert len(skewed) >= 2  # partition 0 split into chunks
    covered = []
    for s in skewed:
        assert s.reduce_ids == [0]
        covered += list(range(*s.block_slice))
    assert covered == list(range(8))  # all blocks exactly once
    assert [s for s in specs if s.reduce_ids != [0]] and \
        all(s.block_slice is None for s in specs if s.reduce_ids != [0])


def test_skew_split_none_when_uniform():
    assert skew_split_specs([10, 11, 12], [2, 2, 2], 5.0, 100, 50) is None


def _placements(session):
    out = []
    session.last_plan.foreach(
        lambda e: out.append(type(e).__name__))
    return out


def test_aqe_coalesces_small_agg_partitions():
    s = _session(**{"spark.sql.adaptive.advisoryPartitionSizeInBytes":
                    "1g"})
    rng = np.random.default_rng(0)
    n = 5000
    tb = pa.table({"k": pa.array(rng.integers(0, 64, n).astype(np.int64)),
                   "v": pa.array(rng.random(n))})
    df = s.create_dataframe(tb, num_partitions=6)
    got = (df.group_by(col("k")).agg(F.sum(col("v")).alias("sv"))
           .collect().sort_by("k"))
    assert "AQEShuffleReadExec" in _placements(s)
    want = pa.TableGroupBy(tb, ["k"], use_threads=False).aggregate(
        [("v", "sum")]).sort_by("k")
    assert got.column("k").to_pylist() == want.column("k").to_pylist()
    np.testing.assert_allclose(np.array(got.column("sv")),
                               np.array(want.column("v_sum")), rtol=1e-9)
    # with a 1g target everything coalesces into one read partition
    reads = []
    s.last_plan.foreach(lambda e: reads.append(e)
                        if isinstance(e, AQEShuffleReadExec) else None)
    assert reads and all(r.num_partitions == 1 for r in reads)


def test_aqe_join_correct_with_skew():
    s = _session(**{
        "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes": "1k",
        "spark.sql.adaptive.advisoryPartitionSizeInBytes": "4k",
    })
    rng = np.random.default_rng(1)
    # heavily skewed: 80% of left rows share one key
    n = 8000
    keys = np.where(rng.random(n) < 0.8, 7,
                    rng.integers(0, 50, n)).astype(np.int64)
    left = pa.table({"k": pa.array(keys),
                     "v": pa.array(rng.integers(0, 100, n).astype(np.int64))})
    right = pa.table({"k": pa.array(np.arange(50, dtype=np.int64)),
                      "w": pa.array(np.arange(50, dtype=np.int64) * 10)})
    ldf = s.create_dataframe(left, num_partitions=8)
    rdf = s.create_dataframe(right, num_partitions=8)
    got = ldf.join(rdf, on="k", how="inner").collect()
    assert got.num_rows == n  # every left row matches exactly one right row
    sums = pa.TableGroupBy(got, ["k"], use_threads=False).aggregate(
        [("w", "count")]).sort_by("k")
    # key 7 kept all its rows through the split
    idx = sums.column("k").to_pylist().index(7)
    assert sums.column("w_count").to_pylist()[idx] == int((keys == 7).sum())


def test_aqe_disabled_still_correct():
    s = _session(**{"spark.sql.adaptive.enabled": False})
    rng = np.random.default_rng(2)
    n = 2000
    tb = pa.table({"k": pa.array(rng.integers(0, 16, n).astype(np.int64)),
                   "v": pa.array(rng.random(n))})
    df = s.create_dataframe(tb, num_partitions=4)
    got = (df.group_by(col("k")).agg(F.count("*").alias("c"))
           .collect())
    assert "AQEShuffleReadExec" not in _placements(s)
    assert sum(got.column("c").to_pylist()) == n
